// Native trace feeder: Alibaba cluster-trace-v2017 CSV -> dense event arrays.
//
// TPU-native equivalent of the reference's host-side trace ingestion
// (reference: src/trace/alibaba_cluster_trace_v2017/{workload,cluster}.rs).
// The hot host-side work — parsing millions of CSV rows, joining
// batch_instance to batch_task, filtering invalid rows and producing dense,
// time-sorted arrays ready to become device tensors — runs here in C++; the
// Python layer (kubernetriks_tpu/trace/feeder.py) binds via ctypes and keeps
// a pure-Python oracle with identical semantics for equality tests.
//
// Semantics mirrored exactly:
//  - workload join + validity filter: workload.rs:56-120 (missing
//    start/end/task_id, unknown task, missing cpu/mem, ts<=0, start>=end),
//    santicores x10 -> millicores, normalized mem x 128 GiB (truncating
//    double multiply), duration = end - start, stable sort by start ts.
//  - duplicate task ids are an input error: workload.rs:152-166.
//  - machine events: `add` -> create (cores x1000 -> millicores, mem x 128
//    GiB), `softerror`/`harderror` -> remove with dedup of re-removals and
//    ghost nodes, unknown types are an error: cluster.rs:16-38,55-105.
//
// C ABI: handle-based. Each parse returns an opaque handle; the caller
// queries the count, fills caller-allocated buffers, and frees the handle.
// Errors are reported as a handle whose error() string is non-empty.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr double kDenormalizationBase = 137438953472.0;  // 128 GiB
constexpr int64_t kCpuBase = 1000;                       // cores -> millicores

struct OptI64 {
  int64_t value = 0;
  bool present = false;
};

struct OptF64 {
  double value = 0.0;
  bool present = false;
};

// One CSV line split into fields. Real-format Alibaba dumps circulate with
// RFC4180 quirks the reference's csv crate also absorbs: quoted fields
// (commas inside quotes, "" escaping a literal quote) and CRLF endings
// (ReadLines strips the \r). Quoted fields with EMBEDDED newlines are not
// supported — none of the circulating traces use them and line framing
// happens before field splitting.
struct Row {
  std::vector<std::string> fields;
};

bool ReadLines(const std::string& path, std::vector<std::string>* lines,
               std::string* error) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) {
    *error = "cannot open file: " + path;
    return false;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    // ftell fails for directories and unseekable streams; surface a
    // ValueError-shaped error instead of letting std::string(size_t(-1))
    // throw across the C ABI.
    std::fclose(f);
    *error = "cannot determine file size (is it a regular file?): " + path;
    return false;
  }
  std::string content(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&content[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    *error = "short read: " + path;
    return false;
  }
  std::fclose(f);

  size_t start = 0;
  while (start <= content.size()) {
    size_t nl = content.find('\n', start);
    size_t end = (nl == std::string::npos) ? content.size() : nl;
    size_t len = end - start;
    if (len > 0 && content[start + len - 1] == '\r') --len;
    if (len > 0) lines->emplace_back(content, start, len);
    if (nl == std::string::npos) break;
    start = nl + 1;
  }
  return true;
}

void SplitCsv(const std::string& line, Row* row) {
  row->fields.clear();
  size_t i = 0;
  std::string field;
  while (true) {
    field.clear();
    if (i < line.size() && line[i] == '"') {
      // Quoted field: runs to the matching quote; "" is a literal quote.
      ++i;
      while (i < line.size()) {
        if (line[i] == '"') {
          if (i + 1 < line.size() && line[i + 1] == '"') {
            field.push_back('"');
            i += 2;
          } else {
            ++i;  // closing quote
            break;
          }
        } else {
          field.push_back(line[i++]);
        }
      }
      // Trailing unquoted residue after a closing quote (malformed input)
      // rides along verbatim, like Python's csv reader.
      while (i < line.size() && line[i] != ',') field.push_back(line[i++]);
    } else {
      while (i < line.size() && line[i] != ',') field.push_back(line[i++]);
    }
    row->fields.push_back(field);
    if (i >= line.size()) break;
    ++i;  // skip the comma
  }
}

// ASCII integer-literal syntax: optional sign, then digits with single
// underscores allowed BETWEEN digits — trace/alibaba.py's _ASCII_INT_RE,
// byte for byte (the Python side deliberately restricts itself to the
// ASCII subset so this scan can match it exactly; Unicode digits are a
// header on BOTH sides). A pure syntax test — Python ints are unbounded,
// so an out-of-int64-range digit string is still an integer (a DATA row);
// strtoll's ERANGE must not reclassify it.
bool LooksLikePythonInt(const std::string& s) {
  size_t i = 0;
  if (i < s.size() && (s[i] == '+' || s[i] == '-')) ++i;
  if (i >= s.size()) return false;
  bool prev_digit = false;
  for (; i < s.size(); ++i) {
    char c = s[i];
    if (c >= '0' && c <= '9') {
      prev_digit = true;
    } else if (c == '_') {
      if (!prev_digit) return false;
      prev_digit = false;
    } else {
      return false;
    }
  }
  return prev_digit;
}

// Header rule shared verbatim with the Python parsers (trace/alibaba.py
// _data_rows): the FIRST row of a file is a header iff its first field is
// non-empty and not an integer — data rows lead with an integer timestamp
// or an empty optional field, header names never do. Whitespace-trimmed
// like Python's str.strip before the test.
bool IsHeaderRow(const Row& row) {
  if (row.fields.empty()) return false;
  const std::string& raw = row.fields[0];
  size_t b = raw.find_first_not_of(" \t\f\v");
  if (b == std::string::npos) return false;  // empty/blank -> data row
  size_t e = raw.find_last_not_of(" \t\f\v");
  return !LooksLikePythonInt(raw.substr(b, e - b + 1));
}

bool ParseI64(const std::string& s, int64_t* out, std::string* error,
              const char* what) {
  if (s.empty()) {
    *error = std::string("empty required field: ") + what;
    return false;
  }
  char* endp = nullptr;
  errno = 0;
  long long v = std::strtoll(s.c_str(), &endp, 10);
  if (errno != 0 || endp == s.c_str() || *endp != '\0') {
    *error = std::string("bad integer '") + s + "' in " + what;
    return false;
  }
  *out = v;
  return true;
}

bool ParseOptI64(const std::string& s, OptI64* out, std::string* error,
                 const char* what) {
  if (s.empty()) {
    out->present = false;
    return true;
  }
  out->present = true;
  return ParseI64(s, &out->value, error, what);
}

bool ParseOptF64(const std::string& s, OptF64* out, std::string* error,
                 const char* what) {
  if (s.empty()) {
    out->present = false;
    return true;
  }
  char* endp = nullptr;
  errno = 0;
  double v = std::strtod(s.c_str(), &endp);
  if (errno != 0 || endp == s.c_str() || *endp != '\0') {
    *error = std::string("bad float '") + s + "' in " + what;
    return false;
  }
  out->value = v;
  out->present = true;
  return true;
}

struct TaskInfo {
  OptI64 cpus_santicores;
  OptF64 normalized_memory;
};

struct Handle {
  std::string error;

  // Workload result (parallel arrays, sorted stably by start_ts).
  std::vector<double> start_ts;
  std::vector<int64_t> cpu_millicores;
  std::vector<int64_t> ram_bytes;
  std::vector<double> duration;
  std::vector<int64_t> job_id;
  std::vector<int64_t> task_id;
  std::vector<int64_t> pod_no;

  // Machine-events result (kind: 0 = create, 1 = remove; cpu/ram only valid
  // for creates), in file order then stably sorted by ts.
  std::vector<double> m_ts;
  std::vector<int32_t> m_kind;
  std::vector<int64_t> m_cpu_millicores;
  std::vector<int64_t> m_ram_bytes;
  std::vector<int64_t> m_machine_id;
};

Handle* Fail(Handle* h, const std::string& error) {
  h->error = error;
  return h;
}

}  // namespace

extern "C" {

Handle* feeder_parse_workload(const char* instance_path,
                              const char* task_path) {
  Handle* h = new Handle();
  std::string err;

  std::vector<std::string> task_lines;
  if (!ReadLines(task_path, &task_lines, &err)) return Fail(h, err);

  // task_id-keyed join table; duplicate ids are an input error
  // (workload.rs:152-166).
  std::unordered_map<int64_t, TaskInfo> tasks;
  tasks.reserve(task_lines.size() * 2);
  Row row;
  bool first_task = true;
  for (const std::string& line : task_lines) {
    SplitCsv(line, &row);
    if (first_task) {
      first_task = false;
      if (IsHeaderRow(row)) continue;
    }
    if (row.fields.size() < 6) {
      return Fail(h, "batch_task row has fewer than 6 fields: " + line);
    }
    // Field-validation parity with the Python parser (trace/alibaba.py
    // BatchTask.from_row): the required integer columns must parse even
    // though the simulation never reads them, so malformed traces are
    // rejected identically whichever parser handled them.
    int64_t tid, ignored;
    if (!ParseI64(row.fields[0], &ignored, &err, "batch_task.task_create_time") ||
        !ParseI64(row.fields[1], &ignored, &err, "batch_task.task_end_time") ||
        !ParseI64(row.fields[2], &ignored, &err, "batch_task.job_id") ||
        !ParseI64(row.fields[3], &tid, &err, "batch_task.task_id") ||
        !ParseI64(row.fields[4], &ignored, &err, "batch_task.number_of_instances"))
      return Fail(h, err);
    TaskInfo info;
    if (row.fields.size() > 6 &&
        !ParseOptI64(row.fields[6], &info.cpus_santicores, &err,
                     "batch_task.cpus_requested"))
      return Fail(h, err);
    if (row.fields.size() > 7 &&
        !ParseOptF64(row.fields[7], &info.normalized_memory, &err,
                     "batch_task.normalized_memory"))
      return Fail(h, err);
    if (!tasks.emplace(tid, info).second) {
      return Fail(h, "duplicated task id: " + std::to_string(tid));
    }
  }

  std::vector<std::string> inst_lines;
  if (!ReadLines(instance_path, &inst_lines, &err)) return Fail(h, err);

  int64_t pod_counter = 0;
  h->start_ts.reserve(inst_lines.size());
  bool first_inst = true;
  for (const std::string& line : inst_lines) {
    SplitCsv(line, &row);
    if (first_inst) {
      first_inst = false;
      if (IsHeaderRow(row)) continue;
    }
    if (row.fields.size() < 8) {
      return Fail(h, "batch_instance row has fewer than 8 fields: " + line);
    }
    OptI64 start, end, jid, tid, mid_ignored;
    int64_t seq_ignored;
    if (!ParseOptI64(row.fields[0], &start, &err, "batch_instance.start_ts") ||
        !ParseOptI64(row.fields[1], &end, &err, "batch_instance.end_ts") ||
        !ParseOptI64(row.fields[2], &jid, &err, "batch_instance.job_id") ||
        !ParseOptI64(row.fields[3], &tid, &err, "batch_instance.task_id") ||
        // Columns the simulation never reads — validated for parity with the
        // Python parser (BatchInstance.from_row: machine_id is optional-int,
        // sequence numbers are required-int).
        !ParseOptI64(row.fields[4], &mid_ignored, &err,
                     "batch_instance.machine_id") ||
        !ParseI64(row.fields[6], &seq_ignored, &err,
                  "batch_instance.sequence_number") ||
        !ParseI64(row.fields[7], &seq_ignored, &err,
                  "batch_instance.total_sequence_number"))
      return Fail(h, err);

    // Validity filter, in the reference's order (workload.rs:56-120).
    if (!start.present || !end.present || !tid.present) continue;
    auto it = tasks.find(tid.value);
    if (it == tasks.end()) continue;
    const TaskInfo& task = it->second;
    if (!task.cpus_santicores.present || !task.normalized_memory.present)
      continue;
    if (start.value <= 0 || end.value <= 0 || start.value >= end.value)
      continue;

    h->start_ts.push_back(static_cast<double>(start.value));
    h->cpu_millicores.push_back(task.cpus_santicores.value * 10);
    h->ram_bytes.push_back(static_cast<int64_t>(
        task.normalized_memory.value * kDenormalizationBase));
    h->duration.push_back(static_cast<double>(end.value - start.value));
    h->job_id.push_back(jid.present ? jid.value : -1);
    h->task_id.push_back(tid.value);
    h->pod_no.push_back(pod_counter++);
  }

  // Stable sort by start timestamp (matches Python list.sort on ts over the
  // file-ordered events).
  std::vector<int64_t> order(h->start_ts.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = static_cast<int64_t>(i);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return h->start_ts[a] < h->start_ts[b];
  });
  auto permute_f64 = [&](std::vector<double>& v) {
    std::vector<double> out(v.size());
    for (size_t i = 0; i < order.size(); ++i) out[i] = v[order[i]];
    v.swap(out);
  };
  auto permute_i64 = [&](std::vector<int64_t>& v) {
    std::vector<int64_t> out(v.size());
    for (size_t i = 0; i < order.size(); ++i) out[i] = v[order[i]];
    v.swap(out);
  };
  permute_f64(h->start_ts);
  permute_i64(h->cpu_millicores);
  permute_i64(h->ram_bytes);
  permute_f64(h->duration);
  permute_i64(h->job_id);
  permute_i64(h->task_id);
  permute_i64(h->pod_no);
  return h;
}

Handle* feeder_parse_machines(const char* machine_events_path) {
  Handle* h = new Handle();
  std::string err;
  std::vector<std::string> lines;
  if (!ReadLines(machine_events_path, &lines, &err)) return Fail(h, err);

  std::unordered_set<int64_t> created, removed;
  Row row;
  bool first_machine = true;
  for (const std::string& line : lines) {
    SplitCsv(line, &row);
    if (first_machine) {
      first_machine = false;
      if (IsHeaderRow(row)) continue;
    }
    if (row.fields.size() < 3) {
      return Fail(h, "machine_events row has fewer than 3 fields: " + line);
    }
    int64_t ts, mid;
    if (!ParseI64(row.fields[0], &ts, &err, "machine_events.timestamp") ||
        !ParseI64(row.fields[1], &mid, &err, "machine_events.machine_id"))
      return Fail(h, err);
    const std::string& kind = row.fields[2];
    if (kind == "add") {
      OptI64 cpus;
      OptF64 mem;
      if (row.fields.size() > 4 &&
          !ParseOptI64(row.fields[4], &cpus, &err, "machine_events.cpus"))
        return Fail(h, err);
      if (row.fields.size() > 5 &&
          !ParseOptF64(row.fields[5], &mem, &err, "machine_events.memory"))
        return Fail(h, err);
      if (!cpus.present || !mem.present) {
        return Fail(h, "machine event 'add' for machine " +
                           std::to_string(mid) + " at t=" +
                           std::to_string(ts) + " lacks cpu/memory values");
      }
      created.insert(mid);
      h->m_ts.push_back(static_cast<double>(ts));
      h->m_kind.push_back(0);
      h->m_cpu_millicores.push_back(cpus.value * kCpuBase);
      h->m_ram_bytes.push_back(
          static_cast<int64_t>(mem.value * kDenormalizationBase));
      h->m_machine_id.push_back(mid);
    } else if (kind == "softerror" || kind == "harderror") {
      // Dedup of re-removals and ghost nodes (cluster.rs:82-86).
      if (removed.count(mid) || !created.count(mid)) continue;
      removed.insert(mid);
      h->m_ts.push_back(static_cast<double>(ts));
      h->m_kind.push_back(1);
      h->m_cpu_millicores.push_back(0);
      h->m_ram_bytes.push_back(0);
      h->m_machine_id.push_back(mid);
    } else {
      return Fail(h, "Unsupported operation for a node in alibaba cluster "
                     "trace: " + kind);
    }
  }

  std::vector<int64_t> order(h->m_ts.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = static_cast<int64_t>(i);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return h->m_ts[a] < h->m_ts[b];
  });
  Handle sorted;
  sorted.m_ts.resize(order.size());
  sorted.m_kind.resize(order.size());
  sorted.m_cpu_millicores.resize(order.size());
  sorted.m_ram_bytes.resize(order.size());
  sorted.m_machine_id.resize(order.size());
  for (size_t i = 0; i < order.size(); ++i) {
    sorted.m_ts[i] = h->m_ts[order[i]];
    sorted.m_kind[i] = h->m_kind[order[i]];
    sorted.m_cpu_millicores[i] = h->m_cpu_millicores[order[i]];
    sorted.m_ram_bytes[i] = h->m_ram_bytes[order[i]];
    sorted.m_machine_id[i] = h->m_machine_id[order[i]];
  }
  h->m_ts.swap(sorted.m_ts);
  h->m_kind.swap(sorted.m_kind);
  h->m_cpu_millicores.swap(sorted.m_cpu_millicores);
  h->m_ram_bytes.swap(sorted.m_ram_bytes);
  h->m_machine_id.swap(sorted.m_machine_id);
  return h;
}

const char* feeder_error(Handle* h) { return h->error.c_str(); }

int64_t feeder_workload_count(Handle* h) {
  return static_cast<int64_t>(h->start_ts.size());
}

void feeder_workload_fill(Handle* h, double* start_ts, int64_t* cpu,
                          int64_t* ram, double* duration, int64_t* job_id,
                          int64_t* task_id, int64_t* pod_no) {
  size_t n = h->start_ts.size();
  std::memcpy(start_ts, h->start_ts.data(), n * sizeof(double));
  std::memcpy(cpu, h->cpu_millicores.data(), n * sizeof(int64_t));
  std::memcpy(ram, h->ram_bytes.data(), n * sizeof(int64_t));
  std::memcpy(duration, h->duration.data(), n * sizeof(double));
  std::memcpy(job_id, h->job_id.data(), n * sizeof(int64_t));
  std::memcpy(task_id, h->task_id.data(), n * sizeof(int64_t));
  std::memcpy(pod_no, h->pod_no.data(), n * sizeof(int64_t));
}

void feeder_workload_fill_range(Handle* h, int64_t lo, int64_t n,
                                double* start_ts, int64_t* cpu, int64_t* ram,
                                double* duration, int64_t* job_id,
                                int64_t* task_id, int64_t* pod_no) {
  // Segment-at-a-time iteration for the streaming ingestion pipeline
  // (kubernetriks_tpu/batched/stream.py): callers pull rows [lo, lo + n)
  // of the sorted workload without materializing the whole columns on the
  // Python side — the compact parsed representation stays native-side and
  // each pull copies one bounded segment. Bounds are clamped defensively;
  // the Python binding validates them first.
  int64_t total = static_cast<int64_t>(h->start_ts.size());
  if (lo < 0) lo = 0;
  if (lo > total) lo = total;
  if (n > total - lo) n = total - lo;
  if (n <= 0) return;
  size_t c = static_cast<size_t>(n);
  size_t off = static_cast<size_t>(lo);
  std::memcpy(start_ts, h->start_ts.data() + off, c * sizeof(double));
  std::memcpy(cpu, h->cpu_millicores.data() + off, c * sizeof(int64_t));
  std::memcpy(ram, h->ram_bytes.data() + off, c * sizeof(int64_t));
  std::memcpy(duration, h->duration.data() + off, c * sizeof(double));
  std::memcpy(job_id, h->job_id.data() + off, c * sizeof(int64_t));
  std::memcpy(task_id, h->task_id.data() + off, c * sizeof(int64_t));
  std::memcpy(pod_no, h->pod_no.data() + off, c * sizeof(int64_t));
}

int64_t feeder_machine_count(Handle* h) {
  return static_cast<int64_t>(h->m_ts.size());
}

void feeder_machine_fill(Handle* h, double* ts, int32_t* kind, int64_t* cpu,
                         int64_t* ram, int64_t* machine_id) {
  size_t n = h->m_ts.size();
  std::memcpy(ts, h->m_ts.data(), n * sizeof(double));
  std::memcpy(kind, h->m_kind.data(), n * sizeof(int32_t));
  std::memcpy(cpu, h->m_cpu_millicores.data(), n * sizeof(int64_t));
  std::memcpy(ram, h->m_ram_bytes.data(), n * sizeof(int64_t));
  std::memcpy(machine_id, h->m_machine_id.data(), n * sizeof(int64_t));
}

void feeder_free(Handle* h) { delete h; }

}  // extern "C"
