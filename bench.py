"""Headline benchmark: pod-scheduling decisions/second on the batched backend.

Prints one JSON line per tracked shape; the LAST line is the headline:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Shapes:
- 1024 x 256-node clusters — the BASELINE.md tracked "1024x256-node vmap
  batch on single TPU" config, kept for round-over-round continuity
  (BENCH_r01/r02 recorded it).
- 1250 x 1000-node clusters — the NORTH-STAR per-chip share: >=10k
  concurrent 1000-node clusters on a v5e-8 is 1250 per chip
  (BASELINE.json). vs_baseline is computed on this line.

The reference publishes no benchmark numbers (BASELINE.md); vs_baseline is
measured against the driver-set north star of 1M decisions/s on a v5e-8,
i.e. 125k decisions/s per chip (BASELINE.json).

Scenario per shape: Poisson pod arrivals (2 pods/s for 1000 s, ~2k pods per
cluster), default kube-scheduler filter/score, stepped in 20-window device
chunks.
"""

import json
import sys
import time

import numpy as np

BASELINE_DECISIONS_PER_SEC_PER_CHIP = 1_000_000 / 8


def run_shape(n_clusters: int, n_nodes: int) -> float:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=1000.0,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
    )

    def decisions_now() -> int:
        # Device->host fetch of the (C,) decisions counter: a REAL sync
        # point. jax.block_until_ready alone intermittently returns early on
        # the tunneled TPU platform, which would leak device work past the
        # clock stop and inflate the result.
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up: 0..190 is 20 windows — the exact chunk shape the timed loop
    # dispatches, so no compilation happens inside the measured region.
    sim.step_until_time(190.0)
    decisions_before = decisions_now()

    t0 = time.perf_counter()
    end = 390.0
    while end <= 1200.0:
        sim.step_until_time(end)  # 20-window chunks
        end += 200.0
    decisions = decisions_now() - decisions_before
    elapsed = time.perf_counter() - t0
    return decisions / elapsed


def main() -> None:
    continuity = run_shape(1024, 256)
    print(
        json.dumps(
            {
                "metric": "pod-scheduling decisions/sec (single chip, 1024x256-node clusters)",
                "value": round(continuity),
                "unit": "decisions/s",
                "vs_baseline": round(
                    continuity / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3
                ),
            }
        ),
        flush=True,
    )
    north_star = run_shape(1250, 1000)
    print(
        json.dumps(
            {
                "metric": "pod-scheduling decisions/sec (single chip, 1250x1000-node clusters = north-star per-chip share)",
                "value": round(north_star),
                "unit": "decisions/s",
                "vs_baseline": round(
                    north_star / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
