"""Headline benchmark: pod-scheduling decisions/second on the batched backend.

Prints one JSON line per tracked shape; the LAST line is the headline:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Shapes:
- 1024 x 256-node clusters — the BASELINE.md tracked "1024x256-node vmap
  batch on single TPU" config, kept for round-over-round continuity
  (BENCH_r01/r02 recorded it).
- composed flagship: 256 clusters x (HPA pod group + cluster autoscaler +
  sliding pod window + Pallas kernels) — the composed-path tracker (r4);
  regressions in autoscaler passes / window slides / segmented slots show
  here even when the pure-scheduler shapes hold.
- 1250 x 1000-node clusters — the NORTH-STAR per-chip share: >=10k
  concurrent 1000-node clusters on a v5e-8 is 1250 per chip
  (BASELINE.json). vs_baseline is computed on this line (the LAST line).

The reference publishes no benchmark numbers (BASELINE.md); vs_baseline is
measured against the driver-set north star of 1M decisions/s on a v5e-8,
i.e. 125k decisions/s per chip (BASELINE.json).

Scenario per shape: Poisson pod arrivals (2 pods/s for 1000 s, ~2k pods per
cluster), default kube-scheduler filter/score, stepped in 20-window device
chunks.
"""

import json
import sys
import time

import numpy as np

BASELINE_DECISIONS_PER_SEC_PER_CHIP = 1_000_000 / 8


def run_shape(n_clusters: int, n_nodes: int) -> float:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=1000.0,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
    )

    def decisions_now() -> int:
        # Device->host fetch of the (C,) decisions counter: a REAL sync
        # point. jax.block_until_ready alone intermittently returns early on
        # the tunneled TPU platform, which would leak device work past the
        # clock stop and inflate the result.
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up: 0..190 is 20 windows — the exact chunk shape the timed loop
    # dispatches, so no compilation happens inside the measured region.
    sim.step_until_time(190.0)
    decisions_before = decisions_now()

    t0 = time.perf_counter()
    end = 390.0
    while end <= 1200.0:
        sim.step_until_time(end)  # 20-window chunks
        end += 200.0
    decisions = decisions_now() - decisions_before
    elapsed = time.perf_counter() - t0
    return decisions / elapsed


def run_composed(n_clusters: int = 256, n_nodes: int = 32) -> float:
    """The COMPOSED flagship configuration as a tracked line (VERDICT r3
    item 4): HPA pod groups + cluster autoscaler + sliding pod window +
    Pallas kernels on a dense cluster batch. Regressions in the composed
    path (autoscaler passes, window slides, segmented slot layout) show up
    here even when the pure-scheduler shapes above hold."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    config = SimulationConfig.from_yaml(
        """
sim_name: bench_composed
seed: 1
scheduling_cycle_interval: 10.0
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 32
  node_groups:
  - node_template:
      metadata: {name: ca_node}
      status: {capacity: {cpu: 64000, ram: 137438953472}}
"""
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    # Plain load ~88% of base capacity: the HPA burst pushes past it, so
    # pods park and the CA provisions (and later retires) template nodes.
    plain = PoissonWorkloadTrace(
        rate_per_second=1.5,
        horizon=1000.0,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 120.0),
        name_prefix="plain",
    )
    group = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 49.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 8
        max_pod_count: 64
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 8000, ram: 17179869184}
              limits: {cpu: 8000, ram: 17179869184}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 300.0
                total_load: 4.0
              - duration: 300.0
                total_load: 24.0
              - duration: 400.0
                total_load: 2.0
"""
    ).convert_to_simulator_events()
    workload = sorted(
        plain.convert_to_simulator_events() + group, key=lambda e: e[0]
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload,
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        pod_window=512,
        use_pallas=True,
    )

    def decisions_now() -> int:
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up through the HPA burst and several window slides, so both
    # quantized slide shapes and every dispatch-chunk shape compile before
    # the clock starts (a novel slide or chunk shape costs seconds of
    # compile through the tunnel and would otherwise land inside the timed
    # region); precompile_chunks covers ladder shapes the warm span's
    # binary decomposition happens not to use.
    sim.step_until_time(590.0)
    sim.precompile_chunks()
    decisions_before = decisions_now()
    t0 = time.perf_counter()
    end = 790.0
    while end <= 1200.0:
        sim.step_until_time(end)
        end += 200.0
    decisions = decisions_now() - decisions_before
    elapsed = time.perf_counter() - t0
    assert sim._pod_base > 0, "composed bench: pod window never slid"
    c = sim.metrics_summary()["counters"]
    assert c["total_scaled_up_pods"] > 0, "composed bench: HPA idle"
    assert c["total_scaled_up_nodes"] > 0, "composed bench: CA idle"
    return decisions / elapsed


def main() -> None:
    continuity = run_shape(1024, 256)
    print(
        json.dumps(
            {
                "metric": "pod-scheduling decisions/sec (single chip, 1024x256-node clusters)",
                "value": round(continuity),
                "unit": "decisions/s",
                "vs_baseline": round(
                    continuity / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3
                ),
            }
        ),
        flush=True,
    )
    composed = run_composed()
    print(
        json.dumps(
            {
                "metric": "pod-scheduling decisions/sec (single chip, composed flagship: 256 clusters x HPA+CA+sliding window+Pallas)",
                "value": round(composed),
                "unit": "decisions/s",
                "vs_baseline": round(
                    composed / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3
                ),
            }
        ),
        flush=True,
    )
    north_star = run_shape(1250, 1000)
    print(
        json.dumps(
            {
                "metric": "pod-scheduling decisions/sec (single chip, 1250x1000-node clusters = north-star per-chip share)",
                "value": round(north_star),
                "unit": "decisions/s",
                "vs_baseline": round(
                    north_star / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
