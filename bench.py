"""Headline benchmark: pod-scheduling decisions/second on the batched backend.

Prints one JSON line per tracked shape; the LAST line is the headline:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Shapes:
- 1024 x 256-node clusters — the BASELINE.md tracked "1024x256-node vmap
  batch on single TPU" config, kept for round-over-round continuity
  (BENCH_r01/r02 recorded it).
- composed flagship: 256 clusters x (HPA pod group + cluster autoscaler +
  sliding pod window + Pallas kernels) — the composed-path tracker (r4);
  regressions in autoscaler passes / window slides / segmented slots show
  here even when the pure-scheduler shapes hold. This line times >= 5
  repeated spans and reports the MEDIAN, with the min/max spread in a
  "spans" field on the same JSON line (cold-outlier robustness, r5
  VERDICT weakness #2).
- 1250 x 1000-node clusters — the NORTH-STAR per-chip share: >=10k
  concurrent 1000-node clusters on a v5e-8 is 1250 per chip
  (BASELINE.json). vs_baseline is computed on this line (the LAST line).

The reference publishes no benchmark numbers (BASELINE.md); vs_baseline is
measured against the driver-set north star of 1M decisions/s on a v5e-8,
i.e. 125k decisions/s per chip (BASELINE.json).

Scenario per shape: Poisson pod arrivals (2 pods/s for 1000 s, ~2k pods per
cluster), default kube-scheduler filter/score, stepped in 20-window device
chunks.

`--smoke` runs the tracked lines at CPU-safe toy shapes (tiny batches,
short horizons, no ladder precompile) purely to prove the bench plumbing
runs and parses end-to-end — the values are meaningless as performance
numbers — plus a superspan-MACHINERY line (scanned executor forced on,
in-bench asserts fail on silent fallback to the ladder), a
streaming-FEEDER line (superspan + the bounded-ring trace-ingestion
pipeline forced on, in-bench asserts fail on silent fallback to
whole-trace staging), and a compiled-PROFILE line (the best_fit scheduler
profile lowered into the decision kernels, in-bench asserts fail on
silent fallback to the default pipeline). tests/test_bench_smoke.py pins
it under JAX_PLATFORMS=cpu.

`--profile NAME` runs every tracked line under a named scheduler profile
(core/scheduler/kube_scheduler.NAMED_PROFILE_SPECS), compiled into the
scan and Pallas kernel paths at engine build (batched/pipeline.py).

`--sweep [N]` runs the scenario-vector fleet line standalone: N (default
64) heterogeneous what-if scenarios — per-lane HPA/CA control-law
parameters as traced (C,) data (batched/fleet.py) — through ONE resident
engine vs the one-process-per-scenario baseline, asserting zero
post-warm-up recompiles and zero lane cross-talk in-bench and writing
the full record to the KTPU_SWEEP_PATH JSON artifact. `--smoke` runs an
8-scenario/4-lane variant as its last line.

`--trace` arms the flight recorder (kubernetriks_tpu/telemetry) on the
composed lines: the JSON record gains a "telemetry" summary (per-phase
host wall time, observed syncs vs the documented steady-state budget,
dispatch stats, device-ring totals) and each traced line writes a
Perfetto-loadable Chrome trace next to the bench (KTPU_TRACE_PATH stem).
Telemetry-on is bit-identical to telemetry-off and gated <3% overhead
(tests/test_telemetry.py), so the traced number IS the tracked number.
"""

import json
import os
import sys
import time
import warnings

import numpy as np

BASELINE_DECISIONS_PER_SEC_PER_CHIP = 1_000_000 / 8


def _assert_profile_compiled(sim, profile, ctx: str) -> None:
    """Loud no-silent-fallback contract for --profile lines: the requested
    scheduler profile REALLY compiled into the pipeline (the bug class the
    compiled-profile subsystem kills), mirroring the superspan/streaming
    smoke asserts. No-op when no profile was requested."""
    if profile is None:
        return
    from kubernetriks_tpu.batched.pipeline import DEFAULT_PROFILE

    assert sim.profile.name == profile, (
        f"{ctx}: requested scheduler profile {profile!r} but the engine "
        f"compiled {sim.profile.name!r}"
    )
    assert profile == "default" or sim.profile != DEFAULT_PROFILE, (
        f"{ctx}: non-default profile silently fell back to the default "
        "pipeline"
    )


def run_shape(
    n_clusters: int,
    n_nodes: int,
    *,
    horizon: float = 1000.0,
    warm_until: float = 190.0,
    t_end: float = 1200.0,
    step: float = 200.0,
    profile: str = None,  # --profile: named scheduler profile (None = default)
) -> float:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=horizon,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        scheduler_profile=profile,
    )
    _assert_profile_compiled(sim, profile, "bench")

    def decisions_now() -> int:
        # Device->host fetch of the (C,) decisions counter: a REAL sync
        # point. jax.block_until_ready alone intermittently returns early on
        # the tunneled TPU platform, which would leak device work past the
        # clock stop and inflate the result.
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up: the default 0..190 is 20 windows — the exact chunk shape the
    # timed loop dispatches, so no compilation happens inside the measured
    # region.
    sim.step_until_time(warm_until)
    decisions_before = decisions_now()

    t0 = time.perf_counter()
    end = warm_until + step
    while end <= t_end:
        sim.step_until_time(end)  # fixed-size window chunks
        end += step
    decisions = decisions_now() - decisions_before
    elapsed = time.perf_counter() - t0
    return decisions / elapsed


# --faults: chaos-engine block appended to the composed config so the fault
# path (crash/recover slab events, per-attempt failure draws, CrashLoopBackOff
# requeues) gets its own measured dispatch/throughput line.
FAULTS_YAML = """
fault_injection:
  enabled: true
  node:
    mttf: 900.0
    mttr: 120.0
  pod:
    fail_prob: 0.05
    restart_limit: 3
"""


COMPOSED_GROUP_YAML = """
events:
- timestamp: 49.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 8
        max_pod_count: {max_pods}
        pod_template:
          metadata: {{name: grp}}
          spec:
            resources:
              requests: {{cpu: 8000, ram: 17179869184}}
              limits: {{cpu: 8000, ram: 17179869184}}
        target_resources_usage: {{cpu_utilization: 0.5}}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: {d1}
                total_load: 4.0
              - duration: {d2}
                total_load: 24.0
              - duration: {d3}
                total_load: 2.0
"""


def _composed_inputs(
    n_nodes: int,
    *,
    rate_per_second: float,
    horizon: float,
    max_group_pods: int,
    burst: tuple,
    faults: bool = False,
):
    """The composed flagship scenario's (config, cluster events, workload
    events) — shared by run_composed and the autotuner's measurement
    backend, so the tuner measures candidates on EXACTLY the tracked
    line's traces."""
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    faults_block = FAULTS_YAML if faults else ""
    config = SimulationConfig.from_yaml(
        f"""
sim_name: bench_composed
seed: 1
scheduling_cycle_interval: 10.0
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: {n_nodes}
  node_groups:
  - node_template:
      metadata: {{name: ca_node}}
      status: {{capacity: {{cpu: 64000, ram: 137438953472}}}}
{faults_block}
"""
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    # Plain load ~88% of base capacity: the HPA burst pushes past it, so
    # pods park and the CA provisions (and later retires) template nodes.
    plain = PoissonWorkloadTrace(
        rate_per_second=rate_per_second,
        horizon=horizon,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 120.0),
        name_prefix="plain",
    )
    group = GenericWorkloadTrace.from_yaml(
        COMPOSED_GROUP_YAML.format(
            max_pods=max_group_pods, d1=burst[0], d2=burst[1], d3=burst[2]
        )
    ).convert_to_simulator_events()
    workload = sorted(
        plain.convert_to_simulator_events() + group, key=lambda e: e[0]
    )
    return config, cluster.convert_to_simulator_events(), workload


def run_composed(
    n_clusters: int = 256,
    n_nodes: int = 32,
    *,
    rate_per_second: float = 1.5,
    horizon: float = 1000.0,
    pod_window: int = 512,
    warm_until: float = 590.0,
    t_end: float = 1200.0,
    step: float = 100.0,
    max_group_pods: int = 64,
    burst: tuple = (300.0, 300.0, 400.0),
    precompile: bool = True,
    use_pallas=True,  # True force-on (hardware bench), False off, None auto
    faults: bool = False,
    superspan=None,  # tri-state like use_pallas; True also asserts it engaged
    stream=None,  # tri-state; True also asserts the feeder really staged
    stream_segment=None,  # staging-slab width (columns); None = 4W default
    stream_depth=None,  # feeder ring capacity K; None = registry default
    mesh=None,  # jax.sharding.Mesh: shard the cluster batch (bench_mesh.py)
    fast_forward=None,
    trace: bool = False,  # --trace: flight recorder + telemetry in the JSON
    trace_path: str = None,  # Chrome trace output (Perfetto-loadable)
    metrics_path: str = None,  # capacity-observatory JSONL/prom export stem
    # PR 9 window-cost switches (None = engine/platform default) — exposed
    # so the A/B capture protocol can isolate each front against the same
    # bench scenario (see BENCH_r07.json).
    lane_major=None,
    window_razor=None,
    ca_descatter=None,
    profile=None,  # --profile: named scheduler profile (None = default)
    **engine_kwargs,  # tuned_profile=... and other build passthroughs
) -> dict:
    """The COMPOSED flagship configuration as a tracked line (VERDICT r3
    item 4): HPA pod groups + cluster autoscaler + sliding pod window +
    Pallas kernels on a dense cluster batch. Regressions in the composed
    path (autoscaler passes, window slides, segmented slot layout) show up
    here even when the pure-scheduler shapes above hold.

    Returns {"value": median, "spans": {...}}: the timed region is >= 5
    REPEATED spans, each clocked separately, and the line reports the
    median with min/max spread — one cold-compile or tunnel-hiccup outlier
    span no longer moves the headline the way it moved a single monolithic
    timed region (round-5 VERDICT weakness #2: driver-captured cold runs
    undershot claimed numbers by 23%)."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces

    config, cluster_events, workload = _composed_inputs(
        n_nodes,
        rate_per_second=rate_per_second,
        horizon=horizon,
        max_group_pods=max_group_pods,
        burst=burst,
        faults=faults,
    )
    sim = build_batched_from_traces(
        config,
        cluster_events,
        workload,
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        pod_window=pod_window,
        # Tri-states pass straight through: the engine treats None as the
        # platform default (the CPU smoke path passes False — it must not
        # force Pallas kernels onto a host backend; the superspan smoke
        # line passes superspan=True to engage the scanned path on CPU).
        use_pallas=use_pallas,
        superspan=superspan,
        stream=stream,
        stream_segment=stream_segment,
        stream_depth=stream_depth,
        mesh=mesh,
        fast_forward=fast_forward,
        lane_major=lane_major,
        window_razor=window_razor,
        ca_descatter=ca_descatter,
        scheduler_profile=profile,
        # --trace arms the flight recorder: host span tracer + device
        # metrics ring. Bit-identical to telemetry-off and inside the <3%
        # overhead gate (tests/test_telemetry.py), so the traced line IS
        # the tracked line — the BENCH JSON carries its own anatomy.
        # Without --trace, pass None so a user's KTPU_TRACE=1 still arms
        # the recorder (a concrete False would override the env flag).
        telemetry=True if trace else None,
        **engine_kwargs,
    )

    _assert_profile_compiled(sim, profile, "composed bench")

    if trace and metrics_path:
        # Capacity-observatory time-series export (telemetry/export.py):
        # every ring drain appends one JSONL record (occupancy gauges,
        # memory watermarks, watchdog verdicts) — the artifact CI uploads
        # next to the Chrome trace; the final report also lands as a
        # Prometheus textfile so standard scrape tooling can watch a run.
        from kubernetriks_tpu.telemetry.export import JsonlExporter

        sim.attach_metrics_exporter(JsonlExporter(metrics_path + ".jsonl"))

    def decisions_now() -> int:
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up through the HPA burst and several window slides, so both
    # quantized slide shapes and every dispatch-chunk shape compile before
    # the clock starts (a novel slide or chunk shape costs seconds of
    # compile through the tunnel and would otherwise land inside the timed
    # region); precompile_chunks covers the shapes the warm span happens
    # not to dispatch — the ladder (+ fused chunk+slide variants), or on a
    # superspan engine the ONE scanned program every steady-state span
    # uses, so a driver-captured cold run pays no compile inside the timed
    # region.
    sim.step_until_time(warm_until)
    if precompile:
        sim.precompile_chunks()
    # >= 5 repeated timed spans; each span's decision fetch is a real sync,
    # so no device work leaks across span clocks.
    #
    # Span VALIDITY (r7 protocol fix): a timed span that committed ZERO
    # decisions ran past trace exhaustion (or landed wholly inside an HPA
    # load-curve trough) — its rate is 0 by construction and poisons the
    # min/median (BENCH_r06.json recorded spans.min = 0 exactly this way).
    # Zero-decision spans are DROPPED from the protocol; if fewer than 5
    # valid spans remain by t_end, the bench re-arms extra spans (the HPA
    # churn cycles indefinitely, so decisions resume) up to a hard cap and
    # fails loudly rather than reporting a median over dead air.
    rates, span_decisions = [], []
    end = warm_until + step
    max_end = t_end + 5 * step  # re-arm bound

    def n_valid() -> int:
        return sum(1 for d in span_decisions if d > 0)

    while end <= t_end or (n_valid() < 5 and end <= max_end):
        decisions_before = decisions_now()
        t0 = time.perf_counter()
        sim.step_until_time(end)
        decisions = decisions_now() - decisions_before
        span_decisions.append(decisions)
        rates.append(decisions / (time.perf_counter() - t0))
        end += step
    valid = [r for r, d in zip(rates, span_decisions) if d > 0]
    dropped = len(rates) - len(valid)
    assert len(valid) >= 5, (
        f"composed bench: only {len(valid)} valid timed spans "
        f"({dropped} dropped as zero-decision/trace-exhausted, re-arm cap "
        f"{max_end}s reached) — extend horizon or shrink step"
    )
    assert sim._pod_base > 0, "composed bench: pod window never slid"
    c = sim.metrics_summary()["counters"]
    assert c["total_scaled_up_pods"] > 0, "composed bench: HPA idle"
    assert c["total_scaled_up_nodes"] > 0, "composed bench: CA idle"
    if superspan:
        # The scanned path actually engaged — a silent fallback to the
        # ladder would make this line vacuous (CI smoke pins this).
        assert sim.dispatch_stats["superspans"] > 0, (
            "composed bench: superspan requested but never dispatched"
        )
        assert sim.dispatch_stats["window_chunks"] == 0, (
            "composed bench: superspan engine dispatched ladder chunks"
        )
    if stream:
        # The streaming feeder actually staged the run — a silent fallback
        # to the resident whole-trace payload (the bug class this line
        # exists to catch, same pattern as the superspan fallback asserts)
        # would leave the device slide payload materialized and the feeder
        # idle.
        assert sim._device_slide is None, (
            "composed bench: streaming requested but the whole-trace "
            "device slide payload was materialized (silent fallback to "
            "resident staging)"
        )
        assert sim.dispatch_stats["feeder_slabs_produced"] > 0, (
            "composed bench: streaming requested but the feeder produced "
            "no slabs"
        )
        assert sim.dispatch_stats["stage_refills"] > 0, (
            "composed bench: streaming requested but no feeder slab was "
            "ever installed"
        )
        # Feeder work rides its own thread, not new host syncs: the
        # steady-state budget stays one progress readback per superspan.
        assert (
            sim.dispatch_stats["slide_syncs"]
            == sim.dispatch_stats["superspans"]
        ), "composed bench: streaming added host syncs beyond the budget"
    # Span-spread disclosure (PR 20): BENCH_r07 recorded a 6.3x max/min
    # span ratio — the median is still the honest headline, but a wide
    # spread means the per-span rate is load-phase-dependent and single
    # A/B deltas within the spread band are noise. WARN (never fail):
    # spread is a property of the scenario's load curve, not a bench bug.
    spread_frac = (
        round(max(valid) / min(valid), 3) if min(valid) > 0 else 0.0
    )
    if spread_frac > 2.0:
        warnings.warn(
            f"composed bench: timed-span spread max/min = {spread_frac}x "
            "(> 2x): per-span rates are load-phase-dependent; trust the "
            "median, not single-span deltas",
            RuntimeWarning,
            stacklevel=2,
        )
    out = {
        "value": float(np.median(valid)),
        "spans": {
            "n": len(valid),
            "min": round(min(valid)),
            "max": round(max(valid)),
            "dropped": dropped,
            "spread_frac": spread_frac,
        },
    }
    if trace:
        # Compact telemetry summary riding in the same JSON line: per-phase
        # host wall time, the observed sync count vs the documented
        # steady-state budget (1 progress readback per superspan + 1 shift
        # readback per fused slide), dispatch stats incl. ladder_fallbacks,
        # the device ring's per-window totals, and the per-window
        # window-program cost (the lane-major/razor/de-scatter observable).
        rep = sim.telemetry_report()
        out["telemetry"] = {
            "spans_ms": {
                name: round(s["total_ms"], 3)
                for name, s in rep["spans"].items()
            },
            "sync_budget": rep["sync_budget"],
            "dispatch_stats": rep["dispatch_stats"],
            "ring_totals": rep.get("ring", {}).get("totals", {}),
        }
        if "feeder" in rep:
            # Streaming-feeder anatomy: slab production vs installs, the
            # ring-depth gauge, and the stage-stall split (feeder-not-ready
            # vs upload-wait) — the starved-feeder observable.
            out["telemetry"]["feeder"] = rep["feeder"]
        # Per-window device-cost line: must exist and be positive on every
        # traced run — CPU CI runs --smoke --trace, so a change that stops
        # windows (or their cost accounting) from being recorded fails
        # loudly there, and layout regressions move a number CI can diff.
        pw = rep.get("per_window")
        assert pw and pw["ms_per_window"] > 0, (
            "composed bench --trace: telemetry report carries no "
            "per-window cost line (no windows recorded?)"
        )
        out["telemetry"]["per_window"] = {
            "windows": pw["windows"],
            "ms_per_window": round(pw["ms_per_window"], 4),
        }
        # Capacity-observatory section: occupancy high-water vs reserve
        # capacity plus RSS/slab watermarks — present and sane on every
        # traced run (CPU CI runs --smoke --trace, so a change that stops
        # the observatory sampling fails loudly there).
        res = rep.get("resources")
        assert res and res["memory"].get("rss_bytes", 0) > 0, (
            "composed bench --trace: telemetry report carries no "
            "resources section (observatory not sampling?)"
        )
        occ = res["occupancy"]
        assert {"hpa_reserve_used", "ca_reserve_used"} <= set(occ), occ
        out["telemetry"]["resources"] = {
            "occupancy": occ,
            "rss_mb": round(res["memory"]["rss_bytes"] / 1e6, 1),
            "rss_high_water_mb": round(
                res["memory"]["high_water"].get("rss_bytes", 0) / 1e6, 1
            ),
            "slabs": res["memory"].get("slabs", {}),
            "watchdog_fired": res["watchdog"]["fired"],
        }
        if trace_path:
            sim.write_chrome_trace(trace_path)
        if metrics_path:
            from kubernetriks_tpu.telemetry.export import (
                write_prometheus_textfile,
            )

            write_prometheus_textfile(metrics_path + ".prom", rep)
    # Release the streaming feeder's producer thread (and the engine it
    # keeps alive through its bound callbacks) — a driver looping bench
    # configurations must not accumulate parked feeders + staged slabs.
    sim.close()
    return out


# --endurance / the endurance SMOKE line: sustained churn through a
# deliberately tight CA reserve, so the run only finishes when slot
# reclaim (KTPU_RECLAIM, r14) actually recycles retired slots — the
# bounded-memory endurance machinery as a tracked line. Node-group pods
# only fit the CA template and fully retire between waves; the plain
# Poisson load keeps the scheduler busy so the line measures composed
# decisions/s, not idle windows.
ENDURANCE_CONFIG_YAML = """
sim_name: bench_endurance
seed: 1
scheduling_cycle_interval: 10.0
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 2
  node_groups:
  - node_template:
      metadata: {{name: ca_node}}
      status: {{capacity: {{cpu: 32000, ram: 68719476736}}}}
{faults_block}
"""


def _endurance_churn_events(n_waves: int, spacing: float, t0: float = 30.0):
    """Churn waves: each wave's pods only fit the CA template (24000 mcpu
    vs 16000 base nodes), run shorter than the wave spacing, and fully
    retire before the next wave — one reserve slot consumed per pod, so
    cumulative allocations overrun the 2-slot static reserve many times
    and the run RAISES without reclaim. Every third wave sends two pods
    (staggered finishes) so multi-slot retirement and the name-ordered
    scale-down walk both run."""
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    events, pod = [], 0
    for k in range(n_waves):
        t = t0 + k * spacing
        for j in range(2 if k % 3 == 2 else 1):
            events.append(
                f"""
- timestamp: {round(t + 7.0 * j, 1)}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: churn_{pod:04d}
        spec:
          resources:
            requests: {{cpu: 24000, ram: 25769803776}}
            limits: {{cpu: 24000, ram: 25769803776}}
          running_duration: {round(min(60.0, spacing / 2) + 14.0 * j, 1)}
"""
            )
            pod += 1
    return GenericWorkloadTrace.from_yaml(
        "events:" + "".join(events)
    ).convert_to_simulator_events()


def run_endurance(
    n_clusters: int = 4,
    n_nodes: int = 8,
    *,
    n_waves: int = 24,
    spacing: float = 160.0,
    rate_per_second: float = 0.25,
    pod_window: int = 128,
    warm_waves: int = 3,
    ca_slot_multiplier: int = 1,
    use_pallas=False,
    faults: bool = True,
    trace_path: str = None,
    metrics_path: str = None,
) -> dict:
    """The ENDURANCE line (ROADMAP #2, r14): composed churn many times
    the static CA reserve with slot reclaim + superspan + the streaming
    feeder on and the capacity observatory watching. In-bench asserts —
    the reasons this line exists, each failing loudly on CI:

    - reclaim actually FIRED (cumulative allocations >= 3x the static
      reserve, retired slots returned, the loud bound clean);
    - RSS/slab WATERMARKS flat (slab byte accounting identical at every
      quartile boundary, RSS high-water non-trending after warm-up);
    - zero RECOMPILES after warm-up (every dispatch-loop jit entry's
      cache size unchanged);
    - the saturation watchdog stayed QUIET (no reserve verdict: live
      occupancy never trends toward exhaustion when reclaim recycles).

    Returns the run_composed record shape plus an "endurance" block with
    the quartile decisions/s spread (first vs last quartile disclosed —
    reserve-pressure throughput decay would show there)."""
    import warnings as _warnings

    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.batched.fleet import jit_cache_sizes
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.telemetry.observatory import SaturationWarning
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        ENDURANCE_CONFIG_YAML.format(
            faults_block=FAULTS_YAML if faults else ""
        )
    )
    horizon = 30.0 + n_waves * spacing
    cluster = UniformClusterTrace(n_nodes, cpu=16000, ram=32 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=rate_per_second,
        horizon=horizon - 60.0,
        seed=3,
        cpu=2000,
        ram=4 * 1024**3,
        duration_range=(20.0, 60.0),
        name_prefix="plain",
    )
    workload = sorted(
        plain.convert_to_simulator_events()
        + _endurance_churn_events(n_waves, spacing),
        key=lambda e: e[0],
    )
    # Recompile sentinel (KTPU_EXPLAIN_RECOMPILES): names the jit entry
    # if anything compiles in the measured region; the cache-count
    # equality assert below stays as the count-level cross-check.
    from kubernetriks_tpu.recompile import RecompileSentinel, sentinel_mode

    sentinel = (
        RecompileSentinel("raise").install()
        if sentinel_mode() is not False
        else None
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload,
        n_clusters=n_clusters,
        max_pods_per_cycle=32,
        pod_window=pod_window,
        use_pallas=use_pallas,
        superspan=True,
        stream=True,
        fast_forward=False,
        reclaim=True,
        # Multiplier 1 over the 2-node quota = a TWO-slot reserve per
        # lane — the churn overruns it many times, so finishing at all
        # proves reclaim recycles (reclaim=False raises at readout
        # here). Long runs with pod faults pass multiplier 2: a failed
        # churn pod's CrashLoopBackOff retry can demand a slot while its
        # OWN node's removal is still inside the visibility horizon
        # (retirement is semantically gated on it, DESIGN §12.1), so at
        # scale the reserve needs quota + a drain-limbo margin — the
        # reference pre-sizes its component pools with the same headroom
        # (simulator.rs:212-230).
        ca_slot_multiplier=ca_slot_multiplier,
        telemetry=True,
        watchdog=True,
    )
    assert sim.reclaim, "endurance bench: reclaim requested but not armed"

    if metrics_path:
        from kubernetriks_tpu.telemetry.export import JsonlExporter

        sim.attach_metrics_exporter(JsonlExporter(metrics_path + ".jsonl"))

    def decisions_now() -> int:
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    warm_until = 30.0 + warm_waves * spacing
    with _warnings.catch_warnings(record=True):
        # Warm-up verdicts are discarded: the feeder thread's cold start
        # can stall one dispatch (a one-shot feeder_starved verdict), and
        # the first churn ramp has no reclaim history yet. The measured
        # region below asserts ZERO verdicts.
        _warnings.simplefilter("always")
        sim.step_until_time(warm_until)
        while sim._pod_base == 0 and warm_until < horizon / 2:
            # The staged-slide superspan program compiles at the FIRST
            # window slide; warm-up must cover it or the zero-recompile
            # gate would flag that legitimate cold compile.
            warm_until += spacing
            sim.step_until_time(warm_until)
        assert sim._pod_base > 0, (
            "endurance bench: pod window never slid inside the warm-up "
            "half — raise rate_per_second or shrink pod_window"
        )
    cache_after_warm = jit_cache_sizes()
    if sentinel is not None:
        sentinel.seal("endurance warm-up (build + first churn waves)")
    rss_after_warm = sim._sample_resources()["rss_bytes"]

    # One timed span per remaining wave (each span carries plain load
    # + one full churn cycle), every boundary sampling the slab
    # watermarks — flat is the claim, so every sample must agree.
    rates, span_decisions, slab_samples, end = [], [], [], warm_until
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        while end < horizon - 1.0:
            end = min(end + spacing, horizon - 1.0)
            before = decisions_now()
            t0 = time.perf_counter()
            sim.step_until_time(end)
            d = decisions_now() - before
            span_decisions.append(d)
            rates.append(d / (time.perf_counter() - t0))
            slab_samples.append((sim.pod_window, sim._slab_accounting()))
        # Flush the ring inside the capture scope so the final rows'
        # verdicts (if any) land in `caught`, not in a later readout.
        sim.drain_telemetry()
    saturation = [
        str(w.message)
        for w in caught
        if issubclass(w.category, SaturationWarning)
    ]
    # The hard gate is the RESERVE trajectory (the reclaim observable);
    # pipeline verdicts (feeder stalls / sync budget) depend on host
    # speed at these shapes and are disclosed, not asserted.
    reserve_verdicts = [m for m in saturation if "reserve" in m]
    pipeline_verdicts = [m for m in saturation if "reserve" not in m]

    # -- the in-bench endurance gates ------------------------------------
    reclaimed = int(sim.ca_slots_reclaimed().sum())
    total_alloc = int(np.asarray(sim.state.auto.ca_total).sum())
    reserve = int(sum(sim._reserve_capacities["ca_reserve"]))
    assert total_alloc >= 3 * reserve, (
        f"endurance bench: cumulative churn ({total_alloc} allocations) "
        f"never overran the static reserve ({reserve} slots) — the "
        "reclaim gate is vacuous; raise n_waves"
    )
    assert reclaimed >= total_alloc - reserve, (
        f"endurance bench: reclaim returned {reclaimed} slots for "
        f"{total_alloc} allocations over a {reserve}-slot reserve"
    )
    sim.check_autoscaler_bounds()  # loud bound must be CLEAN
    assert not reserve_verdicts, (
        "endurance bench: a reserve saturation verdict fired despite "
        f"reclaim: {reserve_verdicts}"
    )
    fired_final = sim.telemetry_report()["resources"]["watchdog"]["fired"]
    assert not any(k.endswith("_reserve_used") for k in fired_final), (
        f"endurance bench: a reserve verdict is live at the end: "
        f"{fired_final} — reclaim should keep occupancy off the "
        "exhaustion trajectory"
    )
    by_geometry = {}
    for pw, slabs in slab_samples:
        by_geometry.setdefault(pw, []).append(slabs)
    for pw, rows in by_geometry.items():
        for later in rows[1:]:
            assert later == rows[0], (
                "endurance bench: slab watermarks moved at fixed "
                f"geometry (pod_window {pw}): {rows[0]} -> {later}"
            )
    assert jit_cache_sizes() == cache_after_warm, (
        "endurance bench: dispatch-loop jit entries recompiled after "
        f"warm-up: {cache_after_warm} -> {jit_cache_sizes()}"
    )
    if sentinel is not None:
        # Names the entry where the count diff above can only count.
        sentinel.check("the endurance measured region")
        sentinel.uninstall()
    rss_end = sim._sample_resources()["rss_bytes"]
    assert rss_end < rss_after_warm * 1.5 + 256e6, (
        "endurance bench: host RSS trended after warm-up "
        f"({rss_after_warm / 1e6:.0f} MB -> {rss_end / 1e6:.0f} MB)"
    )

    valid = [r for r, d in zip(rates, span_decisions) if d > 0]
    dropped = len(rates) - len(valid)
    assert len(valid) >= 4, (
        f"endurance bench: only {len(valid)} valid timed spans"
    )
    q = max(1, len(valid) // 4)
    first_q, last_q = valid[:q], valid[-q:]
    out = {
        "value": float(np.median(valid)),
        "spans": {
            "n": len(valid),
            "min": round(min(valid)),
            "max": round(max(valid)),
            "dropped": dropped,
        },
        "endurance": {
            "waves": n_waves,
            "sim_horizon_s": horizon,
            "reserve_slots": reserve,
            "allocations": total_alloc,
            "reclaimed": reclaimed,
            "reclaim_over_reserve": round(total_alloc / max(reserve, 1), 1),
            "first_quartile_median": round(float(np.median(first_q))),
            "last_quartile_median": round(float(np.median(last_q))),
            "quartile_spread_pct": round(
                100.0
                * (np.median(last_q) - np.median(first_q))
                / max(float(np.median(first_q)), 1e-9),
                1,
            ),
            "rss_after_warm_mb": round(rss_after_warm / 1e6, 1),
            "rss_end_mb": round(rss_end / 1e6, 1),
            "watchdog_fired": sorted(fired_final),
            "pipeline_verdicts": pipeline_verdicts,
            "recompiles_after_warmup": 0,
        },
    }
    if trace_path:
        sim.write_chrome_trace(trace_path)
    if metrics_path:
        from kubernetriks_tpu.telemetry.export import (
            write_prometheus_textfile,
        )

        write_prometheus_textfile(
            metrics_path + ".prom", sim.telemetry_report()
        )
    sim.close()
    return out


SWEEP_GROUP_YAML = COMPOSED_GROUP_YAML  # same HPA burst group as composed


def _sweep_scenarios(n: int):
    """N deterministic heterogeneous scenarios over the vectorizable
    autoscaler parameters (batched/fleet.py SCENARIO_KEYS), plus two
    exact duplicates of scenario 0 planted at positions that land in a
    DIFFERENT lane and a DIFFERENT wave — the lane cross-talk probes the
    in-bench asserts compare bit-for-bit. Arithmetic in the index (no
    RNG): the sweep is reproducible by construction."""
    from kubernetriks_tpu.batched.fleet import Scenario

    out = []
    for i in range(n):
        out.append(
            Scenario(
                hpa_scan_interval=(30.0, 60.0, 90.0, 120.0)[i % 4],
                hpa_tolerance=0.05 + 0.05 * (i % 5),
                ca_scan_interval=10.0 + 5.0 * ((i // 2) % 4),
                ca_threshold=0.3 + 0.1 * ((i // 3) % 4),
            )
        )
    probes = []
    for pos in (min(n // 2 + 1, n - 1), n - 1):
        if pos > 0:
            out[pos] = out[0]
            probes.append(pos)
    return out, sorted(set(probes))


def _scenario_config(base_yaml: str, scen) -> "object":
    """A standalone SimulationConfig carrying one scenario's overrides as
    plain config scalars — the per-engine baseline's input (and the
    scalar-oracle shape tests/test_fleet.py compares lanes against)."""
    from kubernetriks_tpu.config import (
        KubeClusterAutoscalerConfig,
        KubeHorizontalPodAutoscalerConfig,
        SimulationConfig,
    )

    config = SimulationConfig.from_yaml(base_yaml)
    if scen.hpa_scan_interval is not None:
        config.horizontal_pod_autoscaler.scan_interval = scen.hpa_scan_interval
    if scen.hpa_tolerance is not None:
        config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
            KubeHorizontalPodAutoscalerConfig(
                target_threshold_tolerance=scen.hpa_tolerance
            )
        )
    if scen.ca_scan_interval is not None:
        config.cluster_autoscaler.scan_interval = scen.ca_scan_interval
    if scen.ca_threshold is not None:
        config.cluster_autoscaler.kube_cluster_autoscaler = (
            KubeClusterAutoscalerConfig(
                scale_down_utilization_threshold=scen.ca_threshold
            )
        )
    if scen.ca_max_node_count is not None:
        config.cluster_autoscaler.max_node_count = scen.ca_max_node_count
    if scen.as_to_ca_network_delay is not None:
        config.as_to_ca_network_delay = scen.as_to_ca_network_delay
    if scen.hpa_enabled is not None:
        config.horizontal_pod_autoscaler.enabled = scen.hpa_enabled
    return config


def _sweep_setup(
    n_nodes: int,
    rate_per_second: float,
    horizon: float,
    max_group_pods: int,
    burst: tuple,
):
    """Shared config + trace builder of the --sweep and open-loop lines:
    one composed (plain Poisson + HPA burst group) workload over a
    uniform cluster, autoscalers on. Returns (base_yaml, config,
    cluster_events, workload)."""
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    base_yaml = f"""
sim_name: bench_sweep
seed: 1
scheduling_cycle_interval: 10.0
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: {n_nodes}
  node_groups:
  - node_template:
      metadata: {{name: ca_node}}
      status: {{capacity: {{cpu: 64000, ram: 137438953472}}}}
"""
    config = SimulationConfig.from_yaml(base_yaml)
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=rate_per_second,
        horizon=horizon,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 120.0),
        name_prefix="plain",
    )
    group = GenericWorkloadTrace.from_yaml(
        SWEEP_GROUP_YAML.format(
            max_pods=max_group_pods, d1=burst[0], d2=burst[1], d3=burst[2]
        )
    ).convert_to_simulator_events()
    cluster_events = cluster.convert_to_simulator_events()
    workload = sorted(
        plain.convert_to_simulator_events() + group, key=lambda e: e[0]
    )
    return base_yaml, config, cluster_events, workload


def run_sweep(
    n_scenarios: int = 64,
    n_lanes: int = None,
    n_nodes: int = 8,
    *,
    rate_per_second: float = 0.375,
    horizon: float = 400.0,
    query_horizon: float = 450.0,
    max_group_pods: int = 16,
    burst: tuple = (100.0, 150.0, 250.0),
    baseline_engines: int = None,
    smoke: bool = False,
    sweep_path: str = None,
) -> dict:
    """The scenario-vector SWEEP line (ROADMAP #4 made measurable): N
    heterogeneous what-if scenarios — per-lane HPA scan interval /
    tolerance, CA scan interval / scale-down threshold — run through ONE
    resident `ScenarioFleet` (batched/fleet.py) over C cluster lanes, vs
    the old cost model of one engine (compile + warm-up + run) PER
    scenario.

    In-bench asserts (the bug classes this line exists to catch):
    - ZERO recompiles after warm-up: every jit entry's compiled-variant
      count (fleet.jit_cache_sizes) is captured after the first wave and
      must be unchanged after the full query stream — a scenario
      parameter that silently became a jit-static fails here loudly.
    - NO lane cross-talk: exact duplicates of scenario 0 planted in a
      different lane and a different wave must return bit-identical
      per-lane counters.
    - On the full sweep (N >= 64): fleet wall-clock beats the N-engine
      baseline by >= 5x. The baseline builds + runs `baseline_engines`
      real independent engines (the first pays the compile) and
      extrapolates to N from the warm per-engine mean — disclosed in the
      JSON as baseline.extrapolated.
    """
    import time as _time

    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.batched.fleet import (
        ScenarioFleet,
        jit_cache_sizes,
    )
    from kubernetriks_tpu.flags import flag_int

    if n_lanes is None:
        n_lanes = flag_int("KTPU_SWEEP_LANES") or (4 if smoke else 16)
    if baseline_engines is None:
        baseline_engines = flag_int("KTPU_SWEEP_BASELINE") or 3
    baseline_engines = max(1, min(baseline_engines, n_scenarios))

    base_yaml, config, cluster_events, workload = _sweep_setup(
        n_nodes, rate_per_second, horizon, max_group_pods, burst
    )
    scenarios, probe_positions = _sweep_scenarios(n_scenarios)

    # Recompile sentinel: the in-bench zero-recompile assert below
    # compares jit-cache COUNTS; the sentinel additionally NAMES the
    # entry on any post-warm-up compilation (KTPU_EXPLAIN_RECOMPILES=0
    # disarms it; unset arms it here, where the contract is the line's
    # whole point).
    from kubernetriks_tpu.recompile import RecompileSentinel, sentinel_mode

    sentinel = (
        RecompileSentinel("raise").install()
        if sentinel_mode() is not False
        else None
    )

    # --- the fleet: ONE engine, N scenarios as per-lane config data -----
    t0 = _time.perf_counter()
    fleet = ScenarioFleet(
        config,
        cluster_events,
        workload,
        n_lanes=n_lanes,
        horizon=query_horizon,
        max_pods_per_cycle=64,
        use_pallas=None if not smoke else False,
    )
    qids = [fleet.submit(s) for s in scenarios]
    # Warm-up = the first wave (compile + warm dispatch shapes), then the
    # zero-recompile capture, then the rest of the query stream.
    first_wave = [
        fleet._queue.popleft() for _ in range(min(n_lanes, len(fleet._queue)))
    ]
    fleet._run_wave(first_wave)
    sizes_after_warm = jit_cache_sizes()
    if sentinel is not None:
        sentinel.seal("sweep warm-up (build + first wave)")
    fleet.run()
    fleet_s = _time.perf_counter() - t0
    sizes_after_sweep = jit_cache_sizes()
    results = [fleet.results[q] for q in qids]
    fleet.close()
    sentinel_events = 0
    if sentinel is not None:
        # In-bench assert: raises RecompileError NAMING the jit entry if
        # anything compiled during the post-warm-up query stream.
        sentinel.check("the --sweep post-warm-up query stream")
        sentinel_events = len(sentinel.post_seal_events())
        sentinel.uninstall()

    recompiled = {
        name: (sizes_after_sweep[name], sizes_after_warm[name])
        for name in sizes_after_warm
        if sizes_after_sweep[name] != sizes_after_warm[name]
    }
    assert not recompiled, (
        "sweep: scenario updates RECOMPILED jit entries after warm-up "
        f"(compiled-variant counts moved: {recompiled}) — a scenario "
        "parameter regressed from traced data to a jit-static"
    )
    for pos in probe_positions:
        assert results[pos].counters == results[0].counters, (
            f"sweep: lane cross-talk — scenario {pos} is an exact "
            f"duplicate of scenario 0 but its per-lane counters differ "
            f"(lane {results[pos].lane}/wave {results[pos].wave} vs lane "
            f"{results[0].lane}/wave {results[0].wave}):\n"
            f"{results[pos].counters}\n{results[0].counters}"
        )
    decisions = sum(r.counters["scheduling_decisions"] for r in results)
    assert decisions > 0, "sweep: no scenario committed any decision"
    assert any(
        r.counters["scaled_up_nodes"] > 0 for r in results
    ), "sweep: CA idle across every scenario"

    # --- the per-engine baseline: one engine PER scenario ---------------
    # The pre-fleet cost model is one CLI run (one PROCESS) per what-if
    # scenario: every query pays engine build + XLA compile + warm-up
    # (ROADMAP #4's framing). Measured in-process, later engines would
    # silently hit the jit cache and understate that model, so each
    # baseline engine starts compile-COLD (jax.clear_caches) — the
    # honest stand-in for a fresh process — and the JSON discloses both
    # the per-engine measurements and the extrapolation.
    import jax

    base_times = []
    for i in range(baseline_engines):
        scen = scenarios[i]
        if not smoke:
            # Smoke is a plumbing check (recompile/cross-talk asserts,
            # no speedup gate): keep the jit caches warm so the CI smoke
            # job does not pay cold recompiles for a number nobody reads.
            jax.clear_caches()
        t1 = _time.perf_counter()
        sim = build_batched_from_traces(
            _scenario_config(base_yaml, scen),
            cluster_events,
            workload,
            n_clusters=1,
            max_pods_per_cycle=64,
            use_pallas=None if not smoke else False,
        )
        sim.step_until_time(query_horizon)
        int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
        sim.close()
        base_times.append(_time.perf_counter() - t1)
    baseline_s = float(np.mean(base_times)) * n_scenarios
    speedup = baseline_s / fleet_s if fleet_s > 0 else float("inf")
    if not smoke and n_scenarios >= 64:
        assert speedup >= 5.0, (
            f"sweep: fleet wall-clock {fleet_s:.2f}s vs extrapolated "
            f"{n_scenarios}-engine baseline {baseline_s:.2f}s = "
            f"{speedup:.2f}x < the 5x gate"
        )

    out = {
        "value": n_scenarios / fleet_s,
        "sweep": {
            "scenarios": n_scenarios,
            "lanes": n_lanes,
            "waves": -(-n_scenarios // n_lanes),
            "fleet_s": round(fleet_s, 3),
            "scenarios_per_s": round(n_scenarios / fleet_s, 3),
            "baseline": {
                "engines_measured": baseline_engines,
                "measured_s": [round(t, 3) for t in base_times],
                # One-process-per-scenario cost model: each measured
                # engine starts compile-cold (jax.clear_caches), like the
                # fresh CLI run every pre-fleet what-if query paid.
                # False on --smoke: the plumbing check keeps caches warm
                # (its baseline number is not a tracked comparison).
                "cold_process_model": not smoke,
                "extrapolated": baseline_engines < n_scenarios,
                "total_s": round(baseline_s, 3),
            },
            "speedup": round(speedup, 2),
            "recompiles_after_warmup": 0,
            "recompile_sentinel": {
                "armed": sentinel is not None,
                "post_warmup_events": sentinel_events,
            },
            "crosstalk_probes": probe_positions,
            "decisions_total": int(decisions),
        },
    }
    if sweep_path:
        with open(sweep_path, "w") as fh:
            json.dump(out["sweep"], fh, indent=2)
            fh.write("\n")
    return out


# Heterogeneous-horizon mix of the open-loop line: every 4-query block
# holds one full-horizon query and three shorter ones, so a WAVE-aligned
# fleet pays the block's max horizon on every lane while the lane-async
# fleet re-seeds each lane the round its own query finishes — the idle
# tail the per-lane window clock exists to delete.
OPEN_LOOP_HORIZON_MIX = (1.0, 0.0625, 0.125, 0.0625)


def run_open_loop(
    n_queries: int = 32,
    n_lanes: int = 4,
    n_nodes: int = 64,
    *,
    rate_per_second: float = 3.0,
    horizon: float = 400.0,
    query_horizon: float = 450.0,
    max_group_pods: int = 32,
    burst: tuple = (100.0, 150.0, 250.0),
    max_pods_per_cycle: int = 256,
    rounds: int = 5,
    span_windows: int = 4,
    horizon_mix: tuple = None,
    smoke: bool = False,
    json_path: str = None,
    trace_path: str = None,  # per-lane Chrome trace (query swimlanes)
    metrics_path: str = None,  # observatory JSONL/prom export stem
) -> dict:
    """The OPEN-LOOP client line (lane-async fleet, DESIGN §13): the same
    heterogeneous scenario stream submitted to a wave-aligned fleet and a
    lane-asynchronous fleet, with per-query horizons cycling
    OPEN_LOOP_HORIZON_MIX — the workload shape where wave alignment
    wastes the most device time (every wave runs to its longest lane).

    Protocol: both fleets run the full stream once as warm-up (compile +
    program warm), the jit caches and the recompile sentinel are sealed,
    then `rounds` timed repeats run on the RESIDENT fleets; the reported
    queries/s are medians (median-of->=5 in full mode).

    In-bench asserts:
    - A/B identity: every query's counters/replica readouts are
      bit-identical between the wave and lane-async fleets.
    - Zero post-warm-up recompiles (jit-cache counts + sentinel), as in
      --sweep.
    - Query observatory (PR 17): the bounded latency histogram's count
      equals the number of polled queries, and its bucket-derived p99
      lands within one bucket width of the exact sorted-array p99 over
      the bounded exact-sample window (while both exist).
    - Full mode only: mean lane occupancy > 90% on the mix, and the
      lane-async fleet sustains >= 1.5x the wave fleet's queries/s.
    """
    import time as _time

    from kubernetriks_tpu.batched.fleet import ScenarioFleet, jit_cache_sizes
    from kubernetriks_tpu.recompile import RecompileSentinel, sentinel_mode

    base_yaml, config, cluster_events, workload = _sweep_setup(
        n_nodes, rate_per_second, horizon, max_group_pods, burst
    )
    scenarios, _ = _sweep_scenarios(n_queries)
    mix = tuple(horizon_mix) if horizon_mix else OPEN_LOOP_HORIZON_MIX
    horizons = [
        query_horizon * mix[i % len(mix)] for i in range(n_queries)
    ]

    sentinel = (
        RecompileSentinel("raise").install()
        if sentinel_mode() is not False
        else None
    )

    def build(lane_async):
        return ScenarioFleet(
            config,
            cluster_events,
            workload,
            n_lanes=n_lanes,
            horizon=query_horizon,
            max_pods_per_cycle=max_pods_per_cycle,
            use_pallas=None if not smoke else False,
            lane_async=lane_async,
            span_windows=span_windows if lane_async else None,
            # Flight recorder on BOTH fleets so the A/B timing compares
            # identical window programs (the ring record is in-graph);
            # the async side's lane_active column cross-checks the host
            # occupancy ledger (ring_lane_occupancy in the record) and
            # the per-query latency stats flow into the observatory.
            telemetry=True,
        )

    def submit_stream(fleet):
        return [
            fleet.submit(s, h) for s, h in zip(scenarios, horizons)
        ]

    wave = build(False)
    asy = build(True)
    if metrics_path:
        # Observatory time-series export for the serving line, like the
        # composed line's: JSONL drain records now, the final report as
        # a Prometheus textfile (with the native query-latency histogram
        # series) after the timed rounds.
        from kubernetriks_tpu.telemetry.export import JsonlExporter

        asy.engine.attach_metrics_exporter(
            JsonlExporter(metrics_path + ".jsonl")
        )
    # Warm-up: the full stream once per fleet, plus the A/B identity
    # gate — every query's results bit-match across the two executions.
    warm_wave = submit_stream(wave)
    wave.run()
    warm_asy = submit_stream(asy)
    asy.run_async()
    for i, (qw, qa) in enumerate(zip(warm_wave, warm_asy)):
        rw, ra = wave.results[qw], asy.results[qa]
        assert (
            rw.counters == ra.counters
            and rw.hpa_replicas == ra.hpa_replicas
            and rw.ca_nodes == ra.ca_nodes
        ), (
            f"open-loop: query {i} diverges between the wave-aligned and "
            f"lane-async fleets (scenario {scenarios[i]}, horizon "
            f"{horizons[i]}):\n{rw.counters}\n{ra.counters}"
        )
    sizes_after_warm = jit_cache_sizes()
    if sentinel is not None:
        sentinel.seal("open-loop warm-up (both fleets, full stream)")
    # Drain the warm-up completions, then start the timed rounds from a
    # clean ledger: warm-up latencies are dominated by compile time and
    # would swamp the percentiles. reset_query_stats() resets the fleet
    # histograms AND the observatory's query stats atomically.
    asy.poll()
    asy.reset_query_stats()

    wave_times, asy_times = [], []
    polled_queries = 0
    for _ in range(max(1, rounds) if not smoke else 1):
        submit_stream(wave)
        t0 = _time.perf_counter()
        wave.run()
        wave_times.append(_time.perf_counter() - t0)
        submit_stream(asy)
        t0 = _time.perf_counter()
        asy.run_async()
        asy_times.append(_time.perf_counter() - t0)
        polled_queries += len(asy.poll())

    sizes_after = jit_cache_sizes()
    recompiled = {
        name: (sizes_after[name], sizes_after_warm[name])
        for name in sizes_after_warm
        if sizes_after[name] != sizes_after_warm[name]
    }
    assert not recompiled, (
        "open-loop: the post-warm-up query stream RECOMPILED jit entries "
        f"(compiled-variant counts moved: {recompiled})"
    )
    sentinel_events = 0
    if sentinel is not None:
        sentinel.check("the open-loop post-warm-up query stream")
        sentinel_events = len(sentinel.post_seal_events())
        sentinel.uninstall()

    wave_qps = n_queries / float(np.median(wave_times))
    asy_qps = n_queries / float(np.median(asy_times))
    speedup = asy_qps / wave_qps if wave_qps > 0 else float("inf")
    occupancy = asy.lane_occupancy()
    latency = asy.query_latency_percentiles()
    breakdown = asy.query_latency_breakdown()
    # Query-observatory asserts (PR 17): the bounded histogram must agree
    # with ground truth. (a) Exact count: one histogram sample per polled
    # query. (b) Percentile quantisation: while the exact-sample window
    # still holds the whole post-warm-up stream, the bucket-derived p99
    # (numpy's method="higher" rank convention) sits within one bucket
    # width (~5% relative) of the exact sorted-array p99.
    hist = asy.latency_hist
    assert hist.count == polled_queries, (
        f"open-loop: latency histogram holds {hist.count} samples but "
        f"{polled_queries} queries were polled — a drain path skipped "
        "the histogram (or double-counted)"
    )
    exact_window = list(asy.latency_exact_window)
    if exact_window and len(exact_window) == hist.count:
        exact_p99 = float(
            np.percentile(np.asarray(exact_window), 99, method="higher")
        )
        hist_p99 = hist.percentile(99.0)
        width = hist.bucket_width(exact_p99)
        assert abs(hist_p99 - exact_p99) <= width + 1e-12, (
            f"open-loop: histogram p99 {hist_p99 * 1e3:.3f}ms is more "
            f"than one bucket width ({width * 1e3:.3f}ms) from the exact "
            f"p99 {exact_p99 * 1e3:.3f}ms"
        )
    report = asy.engine.telemetry_report() if asy.engine._telemetry else {}
    ring_occ = (
        report.get("resources", {}).get("occupancy", {}).get("lane_occupancy")
    )
    if trace_path:
        # The per-lane Chrome trace: pid 2 carries one swimlane per
        # fleet lane, spans named by the occupying query id, flow arrows
        # linking each submit to its drain (CI uploads it; open it in
        # Perfetto — README "Query observatory").
        asy.engine.write_chrome_trace(trace_path)
    if metrics_path:
        from kubernetriks_tpu.telemetry.export import (
            write_prometheus_textfile,
        )

        write_prometheus_textfile(metrics_path + ".prom", report)
    wave.close()
    asy.close()

    if not smoke:
        assert occupancy["mean"] > 0.90, (
            f"open-loop: mean lane occupancy {occupancy['mean']:.3f} <= "
            "0.90 on the heterogeneous-horizon mix — dispatched lane-"
            "windows are being wasted (span too wide for the mix?)"
        )
        assert speedup >= 1.5, (
            f"open-loop: lane-async fleet at {asy_qps:.2f} queries/s vs "
            f"wave-aligned {wave_qps:.2f} = {speedup:.2f}x < the 1.5x gate"
        )

    out = {
        "value": asy_qps,
        "open_loop": {
            "queries": n_queries,
            "lanes": n_lanes,
            "span_windows": span_windows,
            "horizon_mix": list(mix),
            "rounds_timed": len(asy_times),
            "wave_queries_per_s": round(wave_qps, 3),
            "async_queries_per_s": round(asy_qps, 3),
            "speedup_vs_wave": round(speedup, 3),
            "lane_occupancy": {
                "mean": round(occupancy["mean"], 4),
                "min": round(occupancy["min"], 4),
            },
            "ring_lane_occupancy": ring_occ,
            "latency_ms": {
                k: round(v, 3)
                for k, v in latency.items()
                if k != "count"
            },
            # Queue-wait (submit->admit) vs service (admit->drain) split
            # + the raw bounded-histogram dump (log buckets, ~5%
            # relative resolution, exact count/sum) — PR 17's per-query
            # observability embedded in the SWEEP artifact.
            "latency_breakdown": {
                "queue_wait_ms": breakdown["queue_wait_ms"],
                "service_ms": breakdown["service_ms"],
            },
            "latency_histogram": breakdown["histogram"],
            "histogram_polled_queries": polled_queries,
            "ab_identity_checked": n_queries,
            "recompiles_after_warmup": 0,
            "recompile_sentinel": {
                "armed": sentinel is not None,
                "post_warmup_events": sentinel_events,
            },
        },
    }
    if json_path:
        with open(json_path, "w") as fh:
            json.dump(out["open_loop"], fh, indent=2)
            fh.write("\n")
    return out


def run_host_chaos(
    n_queries: int = 24,
    n_lanes: int = 4,
    n_nodes: int = 8,
    *,
    rate_per_second: float = 0.375,
    horizon: float = 300.0,
    query_horizon: float = 350.0,
    max_group_pods: int = 16,
    burst: tuple = (100.0, 150.0, 250.0),
    max_pods_per_cycle: int = 64,
    rounds: int = 4,
    chaos_seed: int = 7,
    dispatch_rate: float = 0.05,
    stall_rate: float = 0.05,
    stall_ms: float = 1.0,
    smoke: bool = False,
    json_path: str = None,
) -> dict:
    """The HOST-CHAOS line (fault-tolerant serving, DESIGN §15): the
    open-loop query stream through a lane-async fleet while a
    deterministic `HostChaos` injector (counter-seeded threefry, like the
    in-simulation chaos engine) fails dispatches and stalls lanes — the
    unit of failure must be a query or a lane, never the fleet.

    Protocol and in-bench gates:
    - QUIET A/B (the robustness layer is free when off): a plain fleet
      and the chaos-configured fleet (injector NOT yet armed, aggressive
      quarantine thresholds configured) run the same stream —
      bit-identical per-query results AND equal engine dispatch_stats,
      with the recompile sentinel armed and zero chaos events.
    - CHAOS phase (pinned seed => the exact same fault schedule every
      run): `rounds` repeats of the stream with the injector armed.
      The fleet must finish every round (no engine death), availability
      over the injected phase >= 90%, every failed qid streams exactly
      ONE typed error through poll() (stream-once audit), every lane
      faults at least once (the injector's least-faulted victim rule
      makes coverage deterministic), at least one lane quarantines AND
      is later re-admitted, and zero post-warm-up recompiles
      (quarantine/reset are data ops — jit-cache counts + sentinel).
    """
    import warnings as _warnings

    from kubernetriks_tpu.batched.faults import HostChaos
    from kubernetriks_tpu.batched.fleet import ScenarioFleet, jit_cache_sizes
    from kubernetriks_tpu.recompile import RecompileSentinel, sentinel_mode

    base_yaml, config, cluster_events, workload = _sweep_setup(
        n_nodes, rate_per_second, horizon, max_group_pods, burst
    )
    scenarios, _ = _sweep_scenarios(n_queries)
    mix = OPEN_LOOP_HORIZON_MIX
    horizons = [
        query_horizon * mix[i % len(mix)] for i in range(n_queries)
    ]

    sentinel = (
        RecompileSentinel("raise").install()
        if sentinel_mode() is not False
        else None
    )

    def build(**kw):
        return ScenarioFleet(
            config,
            cluster_events,
            workload,
            n_lanes=n_lanes,
            horizon=query_horizon,
            max_pods_per_cycle=max_pods_per_cycle,
            use_pallas=None if not smoke else False,
            lane_async=True,
            telemetry=True,
            **kw,
        )

    def submit_stream(fleet):
        return [fleet.submit(s, h) for s, h in zip(scenarios, horizons)]

    # QUIET layer A/B: plain fleet vs chaos-configured-but-disarmed
    # fleet. quarantine_faults=1 + a 2-round backoff makes the chaos
    # phase's fire -> probe -> re-admit cycle fast and deterministic;
    # when quiet it must cost NOTHING observable.
    plain = build()
    fl = build(
        quarantine_faults=1, quarantine_window=64, quarantine_backoff=2
    )
    q_plain = submit_stream(plain)
    plain.run_async()
    q_warm = submit_stream(fl)
    fl.run_async()
    for i, (qp, qw) in enumerate(zip(q_plain, q_warm)):
        rp, rw = plain.results[qp], fl.results[qw]
        assert (
            rp.counters == rw.counters
            and rp.hpa_replicas == rw.hpa_replicas
            and rp.ca_nodes == rw.ca_nodes
        ), (
            f"host-chaos: query {i} diverges between the plain fleet and "
            "the chaos-configured (disarmed) fleet — the robustness "
            f"layer is NOT free when quiet:\n{rp.counters}\n{rw.counters}"
        )
    stats_plain = dict(plain.engine.dispatch_stats)
    stats_quiet = dict(fl.engine.dispatch_stats)
    assert stats_plain == stats_quiet, (
        "host-chaos: dispatch_stats diverge between the plain fleet and "
        "the chaos-configured (disarmed) fleet on the same stream: "
        f"{stats_plain} vs {stats_quiet}"
    )
    assert fl.fault_report()["chaos"] is None
    plain.close()

    sizes_after_warm = jit_cache_sizes()
    if sentinel is not None:
        sentinel.seal("host-chaos warm-up (quiet A/B, full stream)")
    fl.poll()

    # CHAOS phase: pinned seed => deterministic fault schedule.
    chaos = HostChaos(
        seed=chaos_seed,
        dispatch_rate=dispatch_rate,
        stall_rate=stall_rate,
        stall_ms=stall_ms,
    )
    fl.arm_host_chaos(chaos)
    qids = []
    outcomes: dict = {}
    with _warnings.catch_warnings():
        # Quarantine verdicts warn by design (SaturationWarning); the
        # bench run expects them — the JSON record carries the counts.
        _warnings.simplefilter("ignore")
        for _ in range(max(1, rounds)):
            qids += submit_stream(fl)
            fl.run_async()
            for outcome in fl.poll():
                outcomes[outcome.query] = outcomes.get(outcome.query, 0) + 1
    res = [fl.results[q] for q in qids]
    fails = [r for r in res if not r.ok]
    availability = 1.0 - len(fails) / float(len(res))
    victim_lanes = sorted({r.lane for r in fails if r.lane >= 0})
    report = fl.fault_report()
    failed_by_kind = dict(report["failed"])

    # Stream-once audit: every chaos-phase qid produced exactly one
    # terminal outcome through poll(), result or typed error alike.
    missing = [q for q in qids if outcomes.get(q, 0) != 1]
    assert not missing, (
        f"host-chaos: {len(missing)} qids did not stream exactly one "
        f"terminal outcome via poll() (first: {missing[:5]})"
    )
    assert all(isinstance(r.kind, str) and not r.ok for r in fails)
    assert availability >= 0.90, (
        f"host-chaos: availability {availability:.4f} < 0.90 over the "
        f"injected phase ({len(fails)}/{len(res)} failed)"
    )
    assert victim_lanes == list(range(n_lanes)), (
        f"host-chaos: dispatch faults hit lanes {victim_lanes}, not all "
        f"{n_lanes} lanes — the least-faulted victim rule regressed"
    )
    assert report["quarantine_events"] >= 1, "no lane ever quarantined"
    assert report["readmissions"] >= 1, (
        "no quarantined lane was re-admitted (probe/backoff path dead)"
    )

    sizes_after = jit_cache_sizes()
    recompiled = {
        name: (sizes_after[name], sizes_after_warm[name])
        for name in sizes_after_warm
        if sizes_after[name] != sizes_after_warm[name]
    }
    assert not recompiled, (
        "host-chaos: the injected phase RECOMPILED jit entries — "
        "quarantine/lane-reset must stay data ops "
        f"(compiled-variant counts moved: {recompiled})"
    )
    sentinel_events = 0
    if sentinel is not None:
        sentinel.check("the host-chaos injected phase")
        sentinel_events = len(sentinel.post_seal_events())
        sentinel.uninstall()
    fl.close()

    out = {
        "value": availability,
        "host_chaos": {
            "queries_per_round": n_queries,
            "rounds": max(1, rounds),
            "lanes": n_lanes,
            "seed": chaos_seed,
            "rates": {
                "dispatch": dispatch_rate,
                "stall": stall_rate,
                "stall_ms": stall_ms,
            },
            "availability": round(availability, 4),
            "submitted": len(res),
            "failed": len(fails),
            "failed_by_kind": failed_by_kind,
            "victim_lanes": victim_lanes,
            "quarantine_events": report["quarantine_events"],
            "readmissions": report["readmissions"],
            "lane_states_final": report["lane_states"],
            "chaos_events": report["chaos"]["events"],
            "stream_once_audited": len(qids),
            "quiet_ab_identity_checked": n_queries,
            "quiet_dispatch_stats_equal": True,
            "recompiles_after_warmup": 0,
            "recompile_sentinel": {
                "armed": sentinel is not None,
                "post_warmup_events": sentinel_events,
            },
        },
    }
    if json_path:
        with open(json_path, "w") as fh:
            json.dump(out["host_chaos"], fh, indent=2)
            fh.write("\n")
    return out


def _tune_roundtrip_check(config, cluster_events, workload, *,
                          n_clusters, statics, build_kwargs):
    """Persisted-profile roundtrip gate: an engine built from the profile
    FILE must resolve bit-for-bit the statics table an engine built from
    hand-passed kwargs resolves (engine.tuning_statics) — 'the profile
    loads back build-identical'. Builds two engines WITHOUT stepping them
    (statics resolution is a build-time affair), returns (n_nodes,
    saved-profile -> check callable) so the caller can write the profile
    once the node axis is known."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces

    sim_hand = build_batched_from_traces(
        config, cluster_events, workload, n_clusters=n_clusters,
        tuned_profile=False, **statics, **build_kwargs,
    )
    hand = sim_hand.tuning_statics()
    n_nodes = sim_hand.n_nodes
    sim_hand.close()

    def check(profile_file: str) -> None:
        sim_prof = build_batched_from_traces(
            config, cluster_events, workload, n_clusters=n_clusters,
            tuned_profile=profile_file, **build_kwargs,
        )
        got = sim_prof.tuning_statics()
        sim_prof.close()
        assert got == hand, (
            f"tuned profile {profile_file} did not load back "
            f"build-identical: profile build resolved {got}, hand-passed "
            f"statics resolved {hand}"
        )

    return n_nodes, hand, check


# The composed flagship at the CPU-safe smoke shape — the tuner's
# measurement scenario (and the smoke tune line's roundtrip shape).
_TUNE_SMOKE_SHAPE = dict(
    rate_per_second=0.375, horizon=500.0, max_group_pods=16,
    burst=(100.0, 150.0, 250.0),
)
_TUNE_SMOKE_BUILD = dict(
    max_pods_per_cycle=64, pod_window=128, use_pallas=False,
)
# The hand-picked BENCH_r07 all-on reference: always seeded into the
# sweep, so the chosen config matches or beats it by construction
# (search.py takes the argmin over everything measured).
_TUNE_ALL_ON_SEED = {
    "superspan": True,
    "lane_major": True,
    "window_razor": True,
    "ca_descatter": True,
}


def run_tune(
    budget=None,
    *,
    n_clusters: int = 4,
    n_nodes: int = 8,
    warm_until: float = 290.0,
    t_end: float = 490.0,
    step: float = 40.0,
    json_path: str = None,
) -> dict:
    """--tune: the REAL measurement-driven sweep (tune/) over the
    registered performance statics, on the composed flagship scenario at
    the given shape. Staged coordinate descent, bench-protocol
    measurements (>= 5 valid spans each, recompile sentinel armed per
    candidate, whole-grid bit-identity), the observatory objective —
    then the winning profile persists to
    artifacts/tuned/<backend>_<C>x<N>.json (resumable: an existing
    profile there is the resume cache) and the record carries the
    tuned-vs-default A/B from the sweep's own measurements."""
    import jax

    from kubernetriks_tpu.flags import flag_int
    from kubernetriks_tpu.tune import (
        BenchMeasurementBackend,
        load_profile,
        profile_path,
        save_profile,
        staged_coordinate_descent,
    )
    from kubernetriks_tpu.tune.search import profile_doc

    if budget is None:
        budget = flag_int("KTPU_TUNE_BUDGET")
    backend_name = jax.default_backend()
    config, cluster_events, workload = _composed_inputs(
        n_nodes, **_TUNE_SMOKE_SHAPE
    )
    be = BenchMeasurementBackend(
        config, cluster_events, workload,
        n_clusters=n_clusters,
        warm_until=warm_until, t_end=t_end, step=step,
        build_kwargs=dict(_TUNE_SMOKE_BUILD),
    )
    # Resume: an existing profile for this backend + lane count (N is
    # unknown until the first build, hence the glob) is the cache — its
    # candidates replay for free, budget caps only NEW measurements. A
    # stale/unreadable profile is disclosed and the sweep starts fresh.
    import glob as _glob

    from kubernetriks_tpu.tune.profile import ARTIFACT_DIR

    resume = None
    pattern = json_path or os.path.join(
        ARTIFACT_DIR, f"{backend_name}_{n_clusters}x*.json"
    )
    for candidate_path in sorted(_glob.glob(pattern)):
        try:
            resume = load_profile(candidate_path).doc.get("candidates")
            print(
                f"tune: resuming from {candidate_path} "
                f"({len(resume or [])} cached candidates)",
                file=sys.stderr, flush=True,
            )
            break
        except (ValueError, OSError) as exc:
            print(
                f"tune: ignoring unreadable profile {candidate_path}: "
                f"{exc}",
                file=sys.stderr, flush=True,
            )
    result = staged_coordinate_descent(
        be,
        budget=budget,
        resume_candidates=resume,
        seed_configs=[dict(_TUNE_ALL_ON_SEED)],
        log=lambda msg: print(msg, file=sys.stderr, flush=True),
    )
    assert be.n_nodes is not None
    path = json_path or profile_path(backend_name, n_clusters, be.n_nodes)
    doc = profile_doc(
        result,
        backend=backend_name,
        n_clusters=n_clusters,
        n_nodes=be.n_nodes,
        budget=budget,
        protocol=(
            "bench.run_composed smoke-shape protocol: warm to "
            f"{warm_until}s, >=5 valid {step}s spans to {t_end}s, "
            "zero-decision spans dropped, recompile sentinel armed per "
            "candidate, whole-grid final-state bit-identity vs the first "
            "candidate; objective = observatory tuning_objective"
        ),
    )
    save_profile(doc, path)
    # Roundtrip gate: the file we just wrote builds an engine identical
    # to hand-passing the chosen statics.
    _, _, check = _tune_roundtrip_check(
        config, cluster_events, workload,
        n_clusters=n_clusters, statics=result.chosen,
        build_kwargs=dict(_TUNE_SMOKE_BUILD, fast_forward=False),
    )
    check(path)
    baseline_obj = result.baseline["objective"]
    all_on = result.candidates[1] if len(result.candidates) > 1 else None
    return {
        "value": result.objective,
        "tune": {
            "backend": backend_name,
            "profile": path,
            "chosen": result.chosen,
            "objective": result.objective,
            "baseline_objective": baseline_obj,
            "all_on_objective": (
                all_on["objective"] if all_on else None
            ),
            "ab_vs_default_frac": (
                round(result.objective / baseline_obj, 4)
                if baseline_obj else None
            ),
            "candidates": len(result.candidates),
            "measured": result.measured,
            "reused": result.reused,
            "complete": result.complete,
            "roundtrip_build_identical": True,
            "measurement": "bench",
        },
    }


def run_tune_fake(json_path: str = None) -> dict:
    """The fake-backend tune grid (the smoke tune line and --tune-fake /
    the CI tune-smoke job): the full staged coordinate descent driven by
    the PINNED FakeMeasurementBackend — a 2-knob bonus table
    (lane_major, window_razor), so the winner is known — then the real
    persistence + build seam end to end: the profile JSON is written
    (geometry taken from a real engine build at the smoke composed
    shape) and asserted to load back BUILD-IDENTICAL to hand-passed
    statics. No timings: this line gates the tune plumbing, not
    performance."""
    import jax

    from kubernetriks_tpu.tune import (
        FakeMeasurementBackend,
        save_profile,
        staged_coordinate_descent,
    )
    from kubernetriks_tpu.tune.search import profile_doc

    backend_name = jax.default_backend()
    be = FakeMeasurementBackend(
        {"lane_major": {True: 5.0}, "window_razor": {True: 3.0}}
    )
    result = staged_coordinate_descent(be)
    assert result.chosen["lane_major"] is True, (
        "fake tune grid: the pinned bonus table makes lane_major=True "
        f"the winner, got {result.chosen!r}"
    )
    assert result.chosen["window_razor"] is True, (
        "fake tune grid: the pinned bonus table makes window_razor=True "
        f"the winner, got {result.chosen!r}"
    )
    config, cluster_events, workload = _composed_inputs(
        8, **_TUNE_SMOKE_SHAPE
    )
    n_nodes, hand, check = _tune_roundtrip_check(
        config, cluster_events, workload,
        n_clusters=4, statics=result.chosen,
        build_kwargs=dict(_TUNE_SMOKE_BUILD, fast_forward=False),
    )
    doc = profile_doc(
        result,
        backend=backend_name,
        n_clusters=4,
        n_nodes=n_nodes,
        protocol="FakeMeasurementBackend pinned grid (plumbing gate)",
    )
    path = json_path or _tune_path()
    save_profile(doc, path)
    check(path)
    return {
        "value": result.objective,
        "tune": {
            "backend": backend_name,
            "profile": path,
            "chosen": result.chosen,
            "objective": result.objective,
            "baseline_objective": result.baseline["objective"],
            "candidates": len(result.candidates),
            "measured": result.measured,
            "reused": result.reused,
            "complete": result.complete,
            "roundtrip_build_identical": True,
            "measurement": "fake",
        },
    }


def _sweep_path() -> str:
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_SWEEP_PATH") or "ktpu_sweep"
    return f"{stem}.json"


def _tune_path() -> str:
    """The fake-grid tune line's profile artifact rides the sweep stem:
    <KTPU_SWEEP_PATH or ./ktpu_sweep>_tuned.json (CI uploads it as the
    `ktpu-tuned-profile` artifact). The REAL --tune sweep writes to
    artifacts/tuned/<backend>_<C>x<N>.json instead (tune/profile.py's
    canonical auto-resolution key)."""
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_SWEEP_PATH") or "ktpu_sweep"
    return f"{stem}_tuned.json"


def _open_loop_path() -> str:
    """The open-loop line's JSON artifact rides the sweep stem:
    <KTPU_SWEEP_PATH or ./ktpu_sweep>_openloop.json (CI uploads both)."""
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_SWEEP_PATH") or "ktpu_sweep"
    return f"{stem}_openloop.json"


def _host_chaos_path() -> str:
    """The host-chaos line's JSON artifact rides the sweep stem:
    <KTPU_SWEEP_PATH or ./ktpu_sweep>_hostchaos.json (CI uploads it as
    the `ktpu-host-chaos` artifact)."""
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_SWEEP_PATH") or "ktpu_sweep"
    return f"{stem}_hostchaos.json"


def _trace_path(label: str) -> str:
    """Per-line Chrome trace file: <KTPU_TRACE_PATH or ./ktpu_trace>_<label>.json
    (each traced composed line writes its own file; CI uploads the glob)."""
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_TRACE_PATH") or "ktpu_trace"
    return f"{stem}_{label}.json"


def _metrics_path(label: str) -> str:
    """Per-line capacity-observatory export stem:
    <KTPU_METRICS_PATH or ./ktpu_metrics>_<label> — the engine appends
    drain records to <stem>.jsonl (bounded rotation) and the bench writes
    the final report to <stem>.prom (Prometheus textfile); CI uploads the
    glob next to the Chrome traces."""
    from kubernetriks_tpu.flags import flag_str

    stem = flag_str("KTPU_METRICS_PATH") or "ktpu_metrics"
    return f"{stem}_{label}"


def _emit_sweep(metric: str, value: dict) -> None:
    """The sweep line's unit is scenarios/s (what-if queries drained per
    wall-clock second through the resident fleet), not decisions/s — it
    gets its own emitter so the headline decisions/s contract of the
    other lines stays untouched."""
    rec = {
        "metric": metric,
        "sweep": value["sweep"],
        "value": round(value["value"], 3),
        "unit": "scenarios/s",
    }
    print(json.dumps(rec), flush=True)


def _emit_open_loop(metric: str, value: dict) -> None:
    """The open-loop line's unit is queries/s (continuous submit/poll
    completions per wall-clock second through the lane-async fleet)."""
    rec = {
        "metric": metric,
        "open_loop": value["open_loop"],
        "value": round(value["value"], 3),
        "unit": "queries/s",
    }
    print(json.dumps(rec), flush=True)


def _emit_host_chaos(metric: str, value: dict) -> None:
    """The host-chaos line's unit is availability (completed/submitted
    over the injected phase) — a robustness gate, not a throughput
    number; the full fault-domain disclosure rides in the record."""
    rec = {
        "metric": metric,
        "host_chaos": value["host_chaos"],
        "value": round(value["value"], 4),
        "unit": "availability",
    }
    print(json.dumps(rec), flush=True)


def _emit_tune(metric: str, value: dict) -> None:
    """The tune line's unit is ms/window (the observatory objective the
    sweep minimizes), not decisions/s — the full sweep disclosure
    (chosen statics, profile path, baseline A/B, budget accounting)
    rides in the record."""
    rec = {
        "metric": metric,
        "tune": value["tune"],
        "value": round(value["value"], 4),
        "unit": "ms/window",
    }
    print(json.dumps(rec), flush=True)


def _emit(metric: str, value) -> None:
    # run_composed returns {"value": median, "spans": {n, min, max}} plus,
    # under --trace, a "telemetry" summary — both ride along in the same
    # JSON line; run_shape returns a bare float (single timed region, no
    # spread to report).
    rec = {"metric": metric}
    if isinstance(value, dict):
        rec["spans"] = value["spans"]
        if "telemetry" in value:
            rec["telemetry"] = value["telemetry"]
        if "endurance" in value:
            # run_endurance's gate disclosure (reclaim counts, quartile
            # throughput spread, watermark/recompile verdicts).
            rec["endurance"] = value["endurance"]
        value = value["value"]
    rec.update(
        value=round(value),
        unit="decisions/s",
        vs_baseline=round(value / BASELINE_DECISIONS_PER_SEC_PER_CHIP, 3),
    )
    print(json.dumps(rec), flush=True)


def main(argv=None) -> None:
    args = argv if argv is not None else sys.argv[1:]
    smoke = "--smoke" in args
    faults = "--faults" in args
    # --host-chaos: append the fault-tolerant-serving line (DESIGN §15)
    # after the open-loop line — a deterministic HostChaos injector
    # failing dispatches/stalling lanes with the availability,
    # quarantine and zero-recompile gates armed in-bench. Rides both
    # --smoke and --sweep; the sweep line stays LAST in smoke mode.
    host_chaos = "--host-chaos" in args
    # --trace: arm the flight recorder on the composed lines — the
    # telemetry summary lands in their JSON records and each traced line
    # writes a Perfetto-loadable Chrome trace (see _trace_path).
    trace = "--trace" in args
    # --profile NAME: run every tracked line under a named scheduler
    # profile (batched/pipeline.py compiles it into the scan and Pallas
    # decision kernels; the in-bench asserts fail loudly on a silent
    # fallback to the default pipeline). Default: the reference profile.
    profile = None
    if "--profile" in args:
        idx = args.index("--profile") + 1
        if idx >= len(args) or args[idx].startswith("--"):
            raise SystemExit(
                "bench: --profile needs a profile name "
                "(default | best_fit | balanced_packing)"
            )
        profile = args[idx]
    # --sweep [N]: the scenario-vector fleet line standalone — N (default
    # 64) heterogeneous what-if scenarios through ONE resident engine vs
    # the per-engine baseline, with the zero-recompile and lane-cross-talk
    # asserts armed. Writes the full sweep record to the KTPU_SWEEP_PATH
    # JSON artifact (CI uploads it).
    if "--sweep" in args:
        idx = args.index("--sweep") + 1
        n = 64
        if idx < len(args) and not args[idx].startswith("--"):
            n = int(args[idx])
        _emit_sweep(
            f"what-if scenarios/sec (scenario-vector fleet, {n} "
            "heterogeneous scenarios over resident lanes)",
            run_sweep(n_scenarios=n, sweep_path=_sweep_path()),
        )
        _emit_open_loop(
            # The OPEN-LOOP companion line: a continuous submit/poll
            # client streaming heterogeneous-horizon queries through the
            # lane-asynchronous fleet vs the wave-aligned fleet on the
            # same stream. In-bench gates: per-query A/B bit-identity,
            # zero post-warm-up recompiles, lane occupancy > 90%, and
            # >= 1.5x wave-aligned queries/s. Writes the open-loop
            # record next to the sweep artifact (SWEEP_rXX.json
            # material).
            "what-if queries/sec (open-loop lane-async fleet: 32 "
            "heterogeneous-horizon queries over 4 resident lanes)",
            run_open_loop(
                json_path=_open_loop_path(),
                trace_path=(
                    _trace_path("open_loop") if trace else None
                ),
                metrics_path=(
                    _metrics_path("open_loop") if trace else None
                ),
            ),
        )
        if host_chaos:
            _emit_host_chaos(
                "availability (host-chaos lane-async fleet: deterministic "
                "dispatch faults + stalls, quarantine/backoff armed)",
                run_host_chaos(json_path=_host_chaos_path()),
            )
        return
    # --endurance [N]: the bounded-memory endurance line standalone — N
    # (default 96) churn waves through the 4-slot-per-lane CA reserve with
    # reclaim + streaming + the watchdog armed; the in-bench gates
    # (reclaim fired, flat watermarks, zero recompiles, quiet watchdog)
    # run at full scale and the record disclosed the first/last-quartile
    # throughput spread (ENDUR_rXX.json material).
    if "--endurance" in args:
        idx = args.index("--endurance") + 1
        n = 96
        if idx < len(args) and not args[idx].startswith("--"):
            n = int(args[idx])
        _emit(
            f"pod-scheduling decisions/sec (endurance: {n} churn waves "
            "through a 4-slot CA reserve, reclaim + streaming + watchdog)",
            run_endurance(
                n_waves=n,
                # Quota (2) + drain-limbo margin: chaos pod-fault retries
                # race their own node's removal visibility at this scale
                # (see run_endurance).
                ca_slot_multiplier=2,
                trace_path=_trace_path("endurance") if trace else None,
                metrics_path=_metrics_path("endurance"),
            ),
        )
        return
    # --tune-fake: the pinned fake-backend grid + real persistence/build
    # seam standalone (the CI tune-smoke job: fast, deterministic, no
    # timings — uploads the written profile as the ktpu-tuned-profile
    # artifact).
    if "--tune-fake" in args:
        _emit_tune(
            "tuned statics objective (fake-backend grid + profile "
            "roundtrip, plumbing gate)",
            run_tune_fake(json_path=_tune_path()),
        )
        return
    # --tune [budget] (or KTPU_TUNE=1): the REAL measurement-driven
    # sweep — staged coordinate descent over the knob registry with the
    # bench protocol and the observatory objective, profile persisted to
    # artifacts/tuned/<backend>_<C>x<N>.json (resumable; KTPU_TUNE_BUDGET
    # caps new measurements). The record carries the tuned-vs-default
    # A/B from the sweep's own measurements.
    from kubernetriks_tpu.flags import flag_bool

    if "--tune" in args or flag_bool("KTPU_TUNE"):
        budget = None
        if "--tune" in args:
            idx = args.index("--tune") + 1
            if idx < len(args) and not args[idx].startswith("--"):
                budget = int(args[idx])
        _emit_tune(
            "tuned statics objective (measurement-driven sweep over the "
            "knob registry, composed flagship shape)",
            run_tune(budget=budget),
        )
        return
    if smoke:
        # CPU-safe plumbing check: every line must build, run its full
        # composed machinery (slides, HPA, CA asserts included) and print
        # parseable JSON. Values are NOT performance numbers. step=40 keeps
        # the composed lines' >= 5-timed-spans contract at toy shapes.
        smoke_composed = dict(
            rate_per_second=0.375, horizon=500.0, pod_window=128,
            warm_until=290.0, t_end=490.0, step=40.0, max_group_pods=16,
            burst=(100.0, 150.0, 250.0), precompile=False, use_pallas=False,
        )
        _emit(
            "pod-scheduling decisions/sec (SMOKE, 4x8-node clusters)",
            run_shape(4, 8, horizon=200.0, warm_until=90.0, t_end=290.0,
                      step=100.0),
        )
        _emit(
            "pod-scheduling decisions/sec (SMOKE, composed flagship: "
            "4 clusters x HPA+CA+sliding window)",
            run_composed(4, 8, trace=trace,
                         trace_path=_trace_path("smoke_composed") if trace else None,
                         metrics_path=_metrics_path("smoke_composed") if trace else None,
                         **smoke_composed),
        )
        _emit(
            # The superspan-MACHINERY line: same composed shape, scanned
            # multi-slide executor forced on (CPU default is off). The
            # in-bench asserts require the superspan path really dispatched
            # (and never fell back to the ladder), so the CPU CI job
            # catches a silent fallback — tests/test_bench_smoke.py pins
            # this line's presence.
            "pod-scheduling decisions/sec (SMOKE, composed flagship + "
            "superspan executor)",
            run_composed(4, 8, superspan=True, fast_forward=False,
                         trace=trace,
                         trace_path=_trace_path("smoke_superspan") if trace else None,
                         metrics_path=_metrics_path("smoke_superspan") if trace else None,
                         **smoke_composed),
        )
        _emit(
            # The streaming-FEEDER line: same composed shape, superspan +
            # the K-deep streaming ingestion ring forced on (CPU default
            # is off). The in-bench asserts require the feeder really
            # staged the run (device slide payload NOT materialized,
            # slabs produced AND installed, sync budget unchanged), so
            # the CPU CI job catches a silent fallback to whole-trace
            # staging — tests/test_bench_smoke.py pins this line. The
            # default segment width at this toy shape clamps to the whole
            # padded payload, so the superspan program is the
            # cache-warmed one from the previous line (zero extra
            # compile); the staging machinery still runs end to end
            # through the feeder ring.
            "pod-scheduling decisions/sec (SMOKE, composed flagship + "
            "superspan + streaming feeder)",
            run_composed(4, 8, superspan=True, stream=True,
                         fast_forward=False, trace=trace,
                         trace_path=_trace_path("smoke_stream") if trace else None,
                         metrics_path=_metrics_path("smoke_stream") if trace else None,
                         **smoke_composed),
        )
        _emit(
            # The ENDURANCE line (r14): churn waves through a 2-slot CA
            # reserve with slot reclaim + streaming + the saturation
            # watchdog armed — the run only finishes because reclaim
            # recycles retired slots (reclaim off raises at readout
            # here). The in-bench asserts (reclaim fired, flat RSS/slab
            # watermarks, zero recompiles after warm-up, quiet watchdog)
            # make a reclaim regression loud in CI —
            # tests/test_bench_smoke.py pins this line and its endurance
            # block.
            "pod-scheduling decisions/sec (SMOKE, endurance churn: CA "
            "reserve reclaim + streaming feeder)",
            run_endurance(
                n_clusters=2,
                n_waves=9,
                spacing=120.0,
                warm_waves=2,
                pod_window=64,
                trace_path=_trace_path("smoke_endurance") if trace else None,
                metrics_path=(
                    _metrics_path("smoke_endurance") if trace else None
                ),
            ),
        )
        _emit(
            # The compiled-PROFILE line: the same toy shape under the
            # second (best_fit packing) scheduler profile, exercising the
            # profile -> kernel-static lowering end to end. The in-bench
            # asserts require the engine really compiled the requested
            # profile (never a silent fallback to the default pipeline,
            # mirroring the streaming smoke line) —
            # tests/test_bench_smoke.py pins this line's presence.
            # Pinned to best_fit regardless of --profile: this line IS the
            # second-profile machinery gate, and its label must match what
            # ran (--profile still steers the non-smoke tracked lines).
            "pod-scheduling decisions/sec (SMOKE, 4x8-node clusters, "
            "best_fit profile)",
            run_shape(4, 8, horizon=200.0, warm_until=90.0, t_end=290.0,
                      step=100.0, profile="best_fit"),
        )
        _emit(
            "pod-scheduling decisions/sec (SMOKE, 4x8-node clusters = "
            "north-star stand-in)",
            # Same shape as the continuity line ON PURPOSE: the second run
            # is a jit-cache hit, so the plumbing check pays one
            # plain-shape compile, not two. Smoke values are meaningless as
            # performance numbers either way.
            run_shape(4, 8, horizon=200.0, warm_until=90.0, t_end=290.0,
                      step=100.0),
        )
        _emit_tune(
            # The TUNE line: the autotuner's plumbing gate — the full
            # staged coordinate descent driven by the pinned fake
            # measurement backend (2-knob bonus table, known winner),
            # then the REAL persistence + build seam: the profile JSON
            # is written next to the sweep artifact and asserted (in
            # run_tune_fake) to load back build-identical to
            # hand-passed statics via engine.tuning_statics. No
            # timings; tests/test_bench_smoke.py pins this line's
            # presence, position and record shape.
            "tuned statics objective (SMOKE, fake-backend grid + "
            "profile roundtrip)",
            run_tune_fake(json_path=_tune_path()),
        )
        if faults:
            _emit(
                "pod-scheduling decisions/sec (SMOKE, composed flagship + "
                "chaos faults)",
                run_composed(4, 8, faults=True, **smoke_composed),
            )
        _emit_open_loop(
            # The OPEN-LOOP line: 8 heterogeneous-horizon queries
            # streamed through a continuous submit/poll lane-async
            # fleet next to the wave-aligned fleet on the same stream —
            # the in-bench asserts require per-query A/B bit-identity
            # (lane-async completion order must not change any result)
            # and zero post-warm-up recompiles across pump rounds
            # (a per-lane clock or trace offset regressing to a
            # jit-static recompiles per reseed and fails loudly here).
            # tests/test_bench_smoke.py pins this line's presence and
            # position: BEFORE the sweep line, which must stay LAST
            # (its baseline's jax.clear_caches would cold-start this
            # line's fleets).
            "what-if queries/sec (SMOKE, open-loop lane-async fleet: 8 "
            "queries over 4 resident lanes)",
            run_open_loop(
                n_queries=8,
                n_lanes=4,
                n_nodes=8,
                rate_per_second=0.375,
                horizon=300.0,
                query_horizon=350.0,
                max_group_pods=16,
                max_pods_per_cycle=64,
                smoke=True,
                json_path=_open_loop_path(),
                trace_path=(
                    _trace_path("open_loop") if trace else None
                ),
                metrics_path=(
                    _metrics_path("open_loop") if trace else None
                ),
            ),
        )
        if host_chaos:
            _emit_host_chaos(
                # The HOST-CHAOS line (DESIGN §15): the open-loop stream
                # under a pinned-seed HostChaos injector — quiet-layer
                # A/B bit-identity, availability >= 90%, every-lane
                # fault coverage, quarantine fire -> probe -> re-admit,
                # stream-once error delivery and zero post-warm-up
                # recompiles are all asserted inside run_host_chaos.
                # AFTER the open-loop line (shares its warm jit caches),
                # BEFORE the sweep line (which must stay LAST: its
                # cold-process baseline clears the jit caches) —
                # tests/test_bench_smoke.py pins this order.
                "availability (SMOKE, host-chaos lane-async fleet: "
                "deterministic dispatch faults + stalls over 4 lanes)",
                run_host_chaos(
                    smoke=True,
                    json_path=_host_chaos_path(),
                ),
            )
        _emit_sweep(
            # The scenario-FLEET line: 8 heterogeneous what-if scenarios
            # through one resident 4-lane fleet (batched/fleet.py) — the
            # in-bench asserts fail loudly on a silent recompile after
            # warm-up (a scenario parameter regressing to a jit-static)
            # or on lane cross-talk (duplicate scenarios planted in a
            # different lane and wave must return bit-identical rows).
            # tests/test_bench_smoke.py pins this line's presence. LAST
            # among the smoke lines: its per-engine baseline models one
            # process per scenario via jax.clear_caches, which would
            # cold-start any line that ran after it.
            "what-if scenarios/sec (SMOKE, scenario-vector fleet: 8 "
            "scenarios over 4 resident lanes)",
            run_sweep(
                n_scenarios=8,
                n_lanes=4,
                horizon=300.0,
                query_horizon=350.0,
                smoke=True,
                # One cold baseline engine is enough for the smoke
                # plumbing check (the asserts this line exists for are
                # the recompile/cross-talk gates, not the speedup).
                baseline_engines=1,
                sweep_path=_sweep_path(),
            ),
        )
        return
    suffix = f", {profile} profile" if profile else ""
    if faults:
        _emit(
            "pod-scheduling decisions/sec (single chip, composed flagship + "
            f"chaos faults: crashes/recoveries + CrashLoopBackOff{suffix})",
            run_composed(faults=True, profile=profile),
        )
    _emit(
        f"pod-scheduling decisions/sec (single chip, 1024x256-node clusters{suffix})",
        run_shape(1024, 256, profile=profile),
    )
    _emit(
        "pod-scheduling decisions/sec (single chip, composed flagship: "
        f"256 clusters x HPA+CA+sliding window+Pallas{suffix})",
        run_composed(
            trace=trace,
            trace_path=_trace_path("composed") if trace else None,
            metrics_path=_metrics_path("composed") if trace else None,
            profile=profile,
        ),
    )
    _emit(
        "pod-scheduling decisions/sec (single chip, 1250x1000-node clusters "
        f"= north-star per-chip share{suffix})",
        run_shape(1250, 1000, profile=profile),
    )


if __name__ == "__main__":
    sys.exit(main())
