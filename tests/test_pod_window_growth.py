"""Automatic pod-window growth: when a dense stretch of the trace outgrows
the sliding window (no leading pod is terminal, so no slide is possible),
the engine doubles the window IN PLACE instead of failing — and the result
stays bit-identical to a full-resident run (same counters, same terminal
state). Covers plain pods, the HPA resident-ring re-positioning, and
checkpoint/resume across a growth."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import UniformClusterTrace
from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

N_CLUSTERS = 3


def _long_running_workload(n_pods=200, duration=600.0):
    """1 pod/s arrivals, each running long enough that the live span grows
    to ~n_pods before the first pod ever finishes: a window smaller than
    n_pods MUST grow (no slide is possible while the head pod runs)."""
    return GenericWorkloadTrace.from_yaml(
        "events:"
        + "".join(
            f"""
- timestamp: {1 + i}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:04d}
        spec:
          resources:
            requests: {{cpu: 10, ram: 10485760}}
            limits: {{cpu: 10, ram: 10485760}}
          running_duration: {duration}
"""
            for i in range(n_pods)
        )
    ).convert_to_simulator_events()


def _build(workload, n_clusters=N_CLUSTERS, hpa=False, **kwargs):
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = hpa
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload,
        n_clusters=n_clusters,
        max_pods_per_cycle=16,
        **kwargs,
    )


def test_window_grows_and_matches_resident():
    workload = _long_running_workload()
    ref = _build(workload)
    ref.step_until_time(1200.0)

    sim = _build(workload, pod_window=64)
    assert sim.pod_window == 64
    sim.step_until_time(1200.0)
    # 200 concurrent long-running pods forced growth past 64 (64 -> 128 ->
    # 200 == the whole plain segment, where it caps).
    assert sim.pod_window == 200
    assert sim.metrics_summary()["counters"] == ref.metrics_summary()["counters"]
    assert (
        sim.metrics_summary()["counters"]["pods_succeeded"] == 200 * N_CLUSTERS
    )
    # Fully grown (window == whole plain segment): same terminal phases on
    # the real slots (the resident build's device axis is 128-align padded
    # with EMPTY slots beyond them).
    P_real = np.asarray(sim.state.pods.phase).shape[1]
    assert np.array_equal(
        np.asarray(ref.state.pods.phase)[:, :P_real],
        np.asarray(sim.state.pods.phase),
    )


@pytest.mark.slow
def test_window_growth_repositions_hpa_ring():
    """Growth moves the resident pod-group ring right; HPA replica
    accounting must survive it (same counters as the resident run).
    Slow lane (tier-1 wall-clock budget): tier-1 keeps plain growth
    parity (test_window_grows_and_matches_resident) and growth x
    checkpoint (test_checkpoint_resume_across_growth); the HPA-ring
    reposition composition runs here in the slow lane."""
    group = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 5.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 6
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 100, ram: 104857600}
              limits: {cpu: 100, ram: 104857600}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 200.0
                total_load: 0.6
              - duration: 200.0
                total_load: 2.5
              - duration: 300.0
                total_load: 0.4
"""
    ).convert_to_simulator_events()
    workload = sorted(
        _long_running_workload(n_pods=150, duration=500.0) + group,
        key=lambda e: e[0],
    )

    ref = _build(workload, hpa=True)
    ref.step_until_time(1000.0)
    sim = _build(workload, hpa=True, pod_window=64)
    sim.step_until_time(1000.0)
    assert sim.pod_window > 64, "the window never grew"
    rc, sc = ref.metrics_summary()["counters"], sim.metrics_summary()["counters"]
    assert rc == sc
    assert sc["total_scaled_up_pods"] > 0


def test_checkpoint_resume_across_growth(tmp_path):
    """A checkpoint taken AFTER growth restores into a freshly built engine
    (which grows to match before loading) and finishes identically."""
    workload = _long_running_workload(n_pods=120, duration=400.0)
    ref = _build(workload)
    ref.step_until_time(900.0)

    sim = _build(workload, pod_window=32)
    sim.step_until_time(500.0)
    assert sim.pod_window > 32
    path = str(tmp_path / "ckpt")
    sim.save_checkpoint(path)

    fresh = _build(workload, pod_window=32)
    fresh.load_checkpoint(path)
    assert fresh.pod_window == sim.pod_window
    fresh.step_until_time(900.0)
    assert fresh.metrics_summary()["counters"] == ref.metrics_summary()["counters"]


def test_host_slide_fallback_matches_resident():
    """The host slide path (used when the device payload exceeds its memory
    budget) stays bit-identical: force it by dropping the device payload."""
    # Short durations: leading pods terminate well before the window fills,
    # so the engine SLIDES (growth never triggers and pod_base advances).
    workload = _long_running_workload(n_pods=120, duration=30.0)
    ref = _build(workload)
    ref.step_until_time(700.0)

    sim = _build(workload, pod_window=64)
    sim._device_slide = None  # force the host fallback
    sim.step_until_time(700.0)
    assert sim.pod_window == 64, "expected slides, not growth"
    assert sim._pod_base > 0, "window never slid"
    assert sim.metrics_summary()["counters"] == ref.metrics_summary()["counters"]


@pytest.mark.slow
def test_window_growth_under_mesh():
    """Growth on a C-sharded mesh: the inserted slots and the moved
    autoscale statics (HPA ring) stay shard-local on the 'clusters' axis,
    and the grown run equals the unsharded resident run. Slow lane
    (tier-1 wall-clock budget): tier-1 keeps growth coverage
    (test_window_grows_and_matches_resident, the HPA-ring reposition and
    checkpoint-resume growth cases) AND mesh parity
    (test_batched_sharding.test_sharded_run_matches_unsharded,
    test_flagship_compose.test_pallas_shard_map_matches_scan_on_mesh);
    this is the growthxmesh composition double-check."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < 4:
        import pytest

        pytest.skip("needs >= 4 virtual devices")
    mesh = Mesh(np.array(devices[:4]), ("clusters",))

    group = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 5.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 4
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 100, ram: 104857600}
              limits: {cpu: 100, ram: 104857600}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 300.0
                total_load: 1.8
              - duration: 300.0
                total_load: 0.4
"""
    ).convert_to_simulator_events()
    workload = sorted(
        _long_running_workload(n_pods=120, duration=400.0) + group,
        key=lambda e: e[0],
    )

    ref = _build(workload, n_clusters=4, hpa=True)
    ref.step_until_time(900.0)
    sim = _build(workload, n_clusters=4, hpa=True, pod_window=32, mesh=mesh)
    sim.step_until_time(900.0)
    assert sim.pod_window > 32, "the window never grew"
    # Still C-sharded (not merely present on 4 devices as replicas).
    for arr in (sim.state.pods.phase, sim.autoscale_statics.pod_group_id):
        assert arr.sharding.spec[0] == "clusters", arr.sharding
    rc, sc = ref.metrics_summary()["counters"], sim.metrics_summary()["counters"]
    assert rc == sc
    assert sc["total_scaled_up_pods"] > 0, "the HPA ring never activated"
