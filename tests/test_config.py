"""Config parsing incl. serde-style YAML tags (reference: src/config.yaml)."""

from kubernetriks_tpu.config import SimulationConfig, load_yaml_with_tags

FULL_CONFIG = """
sim_name: "kubernetriks"
seed: 123

metrics_printer:
  format: !PrettyTable
  output_file: /tmp/metrics.txt

horizontal_pod_autoscaler:
  enabled: false
  autoscaler_type: kube_horizontal_pod_autoscaler

cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  max_node_count: 200
  node_groups:
  - max_count: 50
    node_template:
      metadata:
        name: autoscaler_128cpu_256gb_node
      status:
        capacity:
          cpu: 128000
          ram: 274877906944
  - node_template:
      metadata:
        name: autoscaler_64cpu_128gb_node
      status:
        capacity:
          cpu: 64000
          ram: 137438953472

trace_config:
  generic_trace:
    workload_trace_path: workload.yaml
    cluster_trace_path: cluster.yaml

default_cluster:
- node_count: 10
  node_template:
    metadata:
      name: default_128cpu_256gb_node
    status:
      capacity:
        cpu: 128000
        ram: 274877906944

scheduling_cycle_interval: 10.0
enable_unscheduled_pods_conditional_move: false

as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.089
sched_to_as_network_delay: 0.023
as_to_node_network_delay: 0.152
as_to_ca_network_delay: 0.67
as_to_hpa_network_delay: 0.50
"""


def test_full_config_parse():
    config = SimulationConfig.from_yaml(FULL_CONFIG)
    assert config.sim_name == "kubernetriks"
    assert config.seed == 123
    assert config.metrics_printer.format == "PrettyTable"
    assert config.cluster_autoscaler.enabled
    assert config.cluster_autoscaler.max_node_count == 200
    assert len(config.cluster_autoscaler.node_groups) == 2
    assert config.cluster_autoscaler.node_groups[0].max_count == 50
    assert config.cluster_autoscaler.node_groups[1].max_count is None
    template = config.cluster_autoscaler.node_groups[0].node_template
    assert template.metadata.name == "autoscaler_128cpu_256gb_node"
    assert template.status.capacity.cpu == 128000
    assert template.status.capacity.ram == 274877906944
    assert config.trace_config.generic_trace.workload_trace_path == "workload.yaml"
    assert config.trace_config.alibaba_cluster_trace_v2017 is None
    assert config.default_cluster[0].node_count == 10
    assert config.scheduling_cycle_interval == 10.0
    assert config.as_to_ps_network_delay == 0.050
    assert config.as_to_hpa_network_delay == 0.50


def test_defaults():
    config = SimulationConfig.from_yaml("sim_name: x\nseed: 1\nscheduling_cycle_interval: 5.0")
    assert not config.cluster_autoscaler.enabled
    assert config.cluster_autoscaler.scan_interval == 10.0
    assert config.cluster_autoscaler.autoscaler_type == "kube_cluster_autoscaler"
    assert not config.horizontal_pod_autoscaler.enabled
    assert config.horizontal_pod_autoscaler.scan_interval == 60.0
    assert config.metrics_printer is None
    assert config.default_cluster is None
    assert config.as_to_ps_network_delay == 0.0


def test_tagged_yaml_loader():
    doc = load_yaml_with_tags(
        """
events:
- timestamp: 550
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_16
"""
    )
    event = doc["events"][0]["event_type"]
    assert event["__tag__"] == "CreatePod"
    assert event["pod"]["metadata"]["name"] == "pod_16"
