"""Batched path sharded over a device mesh: results must be identical to the
unsharded run, with the cluster axis split across all 8 virtual CPU devices."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubernetriks_tpu.batched.engine import BatchedSimulation, build_batched_from_traces
from kubernetriks_tpu.batched.trace_compile import compile_cluster_trace
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace
from tests.test_batched_equivalence import CLUSTER_YAML, make_workload


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return Mesh(np.array(devices), ("clusters",))


def test_sharded_run_matches_unsharded(mesh):
    config = default_test_simulation_config()
    workload_yaml, pod_names = make_workload()
    cluster_events = GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events()
    workload_events = GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events()

    compiled = compile_cluster_trace(cluster_events, workload_events, config)
    unsharded = BatchedSimulation(config, [compiled] * 16)
    sharded = BatchedSimulation(config, [compiled] * 16, mesh=mesh)

    # State actually lives distributed across the mesh.
    sharding = sharded.state.pods.phase.sharding
    assert isinstance(sharding, NamedSharding)
    assert sharding.spec[0] == "clusters"
    assert len(sharded.state.pods.phase.devices()) == 8

    unsharded.step_until_time(2000.0)
    sharded.step_until_time(2000.0)

    for field in ["pods_succeeded", "terminated_pods", "scheduling_decisions"]:
        np.testing.assert_array_equal(
            np.asarray(getattr(unsharded.state.metrics, field)),
            np.asarray(getattr(sharded.state.metrics, field)),
            err_msg=field,
        )
    np.testing.assert_array_equal(
        np.asarray(unsharded.state.pods.phase), np.asarray(sharded.state.pods.phase)
    )
    np.testing.assert_allclose(
        np.asarray(unsharded.state.pods.start_time),
        np.asarray(sharded.state.pods.start_time),
        rtol=1e-6,
    )
    assert sharded.metrics_summary()["counters"]["pods_succeeded"] == 16 * len(pod_names)


@pytest.mark.slow
def test_profiling_hooks(tmp_path, caplog):
    """profile_dir captures a jax.profiler trace; log_throughput emits the
    per-chunk decisions/s line (TPU analog of the scalar events/s log,
    reference: src/simulator.rs:363-368). Slow lane (tier-1 wall-clock
    budget): instrumentation plumbing, not a correctness gate — the
    flight recorder's tier-1 suite (test_telemetry) covers the tracing
    path the engine actually runs in steady state."""
    import logging
    import os

    from kubernetriks_tpu.test_util import default_test_simulation_config

    config = default_test_simulation_config()
    workload_yaml, _ = make_workload()
    sim = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=4,
    )
    sim.profile_dir = str(tmp_path / "trace")
    sim.log_throughput = True
    with caplog.at_level(logging.INFO, logger="kubernetriks_tpu.batched.engine"):
        sim.step_until_time(100.0)
    assert any("decisions/s" in rec.message for rec in caplog.records)
    dumped = []
    for root, _, files in os.walk(tmp_path / "trace"):
        dumped.extend(files)
    assert dumped, "profiler trace directory is empty"


def test_pod_axis_alignment_full_resident_only():
    """Full-resident builds pad the pod axis to a 128 multiple (Pallas
    wrapper pads become no-ops); padded slots are batch-padding slots that
    never leave PHASE_EMPTY, and the sliding path keeps exact widths."""
    import numpy as np

    from kubernetriks_tpu.batched.state import PHASE_EMPTY
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )
    from kubernetriks_tpu.batched.engine import build_batched_from_traces

    config = SimulationConfig.from_yaml(
        "sim_name: align\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5, horizon=100.0, seed=2, cpu=2000,
        ram=4 * 1024**3, duration_range=(10.0, 30.0),
    )

    full = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=2,
    )
    assert full.n_pods % 128 == 0
    assert full.n_real_pods <= full.n_pods
    full.step_until_time(200.0)
    phases = np.asarray(full.state.pods.phase)
    assert (phases[:, full.n_real_pods:] == PHASE_EMPTY).all(), (
        "alignment padding slots must never be touched"
    )

    windowed = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=2,
        pod_window=16,
    )
    assert windowed.n_pods == 16, "sliding path keeps exact widths"
    windowed.step_until_time(200.0)
    assert (
        windowed.metrics_summary()["counters"]
        == full.metrics_summary()["counters"]
    )
