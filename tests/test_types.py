"""Object-model semantics (reference: src/core/node.rs, src/core/pod.rs)."""

from kubernetriks_tpu.core.types import (
    Node,
    NodeConditionType,
    Pod,
    PodConditionType,
    RuntimeResources,
)


def test_node_new_sets_allocatable_to_capacity():
    node = Node.new("n1", 16000, 32 * 1024**3)
    assert node.status.allocatable == node.status.capacity
    assert node.status.allocatable is not node.status.capacity


def test_condition_upsert():
    node = Node.new("n1", 1000, 1000)
    node.update_condition("True", NodeConditionType.NODE_CREATED, 1.0)
    node.update_condition("True", NodeConditionType.NODE_READY, 2.0)
    node.update_condition("False", NodeConditionType.NODE_CREATED, 3.0)
    assert len(node.status.conditions) == 2
    created = node.get_condition(NodeConditionType.NODE_CREATED)
    assert created.status == "False" and created.last_transition_time == 3.0


def test_pod_conditions_and_duration():
    pod = Pod.new("p1", 4000, 8 * 1024**3, 21.0)
    assert pod.spec.running_duration == 21.0
    service = Pod.new("svc", 100, 100, None)
    assert service.spec.running_duration is None
    pod.update_condition("True", PodConditionType.POD_CREATED, 0.5)
    assert pod.get_condition(PodConditionType.POD_CREATED).status == "True"
    assert pod.get_condition(PodConditionType.POD_RUNNING) is None


def test_runtime_resources_arithmetic():
    a = RuntimeResources(4000, 100)
    b = RuntimeResources(1000, 40)
    assert (a - b) == RuntimeResources(3000, 60)
    assert (a + b) == RuntimeResources(5000, 140)
    assert a.fits(b)
    assert not b.fits(a)
    assert RuntimeResources(0, 0).is_zero()


def test_node_from_dict_defaults_allocatable_to_capacity():
    node = Node.from_dict(
        {"metadata": {"name": "n"}, "status": {"capacity": {"cpu": 64000, "ram": 1000}}}
    )
    assert node.status.allocatable == RuntimeResources(64000, 1000)
    assert node.status.allocatable is not node.status.capacity


def test_pod_from_dict_yaml_shape():
    pod = Pod.from_dict(
        {
            "metadata": {"name": "pod_16", "labels": {"scheduler_name": "custom"}},
            "spec": {
                "resources": {
                    "requests": {"cpu": 4000, "ram": 8589934592},
                    "limits": {"cpu": 8000, "ram": 17179869184},
                },
                "running_duration": 21.0,
            },
        }
    )
    assert pod.metadata.name == "pod_16"
    assert pod.metadata.labels["scheduler_name"] == "custom"
    assert pod.spec.resources.requests.cpu == 4000
    assert pod.spec.resources.limits.ram == 17179869184
    assert pod.spec.running_duration == 21.0


def test_copy_is_deep():
    node = Node.new("n", 100, 100)
    clone = node.copy()
    clone.status.allocatable.cpu = 1
    clone.metadata.labels["x"] = "y"
    assert node.status.allocatable.cpu == 100
    assert "x" not in node.metadata.labels
