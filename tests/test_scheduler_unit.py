"""Scheduler algorithm unit tests with hand-computed scores
(port of reference src/core/scheduler/scheduler.rs:479-603 and queue.rs tests)."""

import pytest

from kubernetriks_tpu.core.scheduler.interface import ScheduleError, SchedulingFailure
from kubernetriks_tpu.core.scheduler.kube_scheduler import KubeScheduler
from kubernetriks_tpu.core.scheduler.model import ConstantTimePerNodeModel
from kubernetriks_tpu.core.scheduler.queue import (
    ActiveQueue,
    QueuedPodInfo,
    UnschedulablePodKey,
    UnschedulableQueue,
)
from kubernetriks_tpu.core.node_component import NodeComponentPool
from kubernetriks_tpu.core.scheduler.scheduler import Scheduler
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.metrics.collector import MetricsCollector
from kubernetriks_tpu.sim.kernel import Simulation
from kubernetriks_tpu.test_util import default_test_simulation_config


def create_scheduler() -> Scheduler:
    fake_sim = Simulation(0)
    return Scheduler(
        0,
        KubeScheduler(),
        fake_sim.create_context("scheduler"),
        default_test_simulation_config(),
        MetricsCollector(),
    )


def test_no_nodes_no_schedule():
    scheduler = create_scheduler()
    pod = Pod.new("pod_1", 4000, 16000, 5.0)
    with pytest.raises(SchedulingFailure) as exc:
        scheduler.schedule_one(pod)
    assert exc.value.error == ScheduleError.NO_NODES_IN_CLUSTER


def test_pod_has_requested_zero_resources():
    scheduler = create_scheduler()
    scheduler.add_node(Node.new("node1", 3000, 8589934592))
    with pytest.raises(SchedulingFailure) as exc:
        scheduler.schedule_one(Pod.new("pod_1", 0, 0, 5.0))
    assert exc.value.error == ScheduleError.REQUESTED_RESOURCES_ARE_ZEROS


def test_no_sufficient_nodes_for_scheduling():
    scheduler = create_scheduler()
    scheduler.add_node(Node.new("node1", 3000, 8589934592))
    with pytest.raises(SchedulingFailure) as exc:
        scheduler.schedule_one(Pod.new("pod_1", 6000, 12884901888, 5.0))
    assert exc.value.error == ScheduleError.NO_SUFFICIENT_RESOURCES


def test_correct_pod_scheduling():
    """Hand-computed LeastAllocatedResources scores
    (reference: scheduler.rs:556-575):
      node1: ((8000-6000)*100/8000 + (14589934592-12884901888)*100/14589934592)/2 = 18.34
      node2: ((7000-6000)*100/7000 + (20589934592-12884901888)*100/20589934592)/2 = 25.85
      node3: ((6000-6000)*100/6000 + (100589934592-12884901888)*100/100589934592)/2 = 43.59
    """
    scheduler = create_scheduler()
    scheduler.add_node(Node.new("node1", 8000, 14589934592))
    scheduler.add_node(Node.new("node2", 7000, 20589934592))
    scheduler.add_node(Node.new("node3", 6000, 100589934592))
    pod = Pod.new("pod_1", 6000, 12884901888, 5.0)
    assert scheduler.schedule_one(pod) == "node3"


def test_several_pod_scheduling():
    """Capacity exhaustion on a single node (reference: scheduler.rs:577-603)."""
    scheduler = create_scheduler()
    scheduler.add_node(Node.new("node1", 16000, 100589934592))
    pods = [
        Pod.new("pod_1", 4000, 8589934592, 5.0),
        Pod.new("pod_2", 2000, 4294967296, 5.0),
        Pod.new("pod_3", 8000, 8589934592, 5.0),
        Pod.new("pod_4", 10000, 8589934592, 5.0),
    ]
    for pod in pods:
        scheduler.add_pod(pod)
    for pod in pods[:3]:
        assert scheduler.schedule_one(pod) == "node1"
        scheduler.reserve_node_resources(pod.metadata.name, "node1")
    with pytest.raises(SchedulingFailure) as exc:
        scheduler.schedule_one(pods[3])
    assert exc.value.error == ScheduleError.NO_SUFFICIENT_RESOURCES


def test_tie_break_prefers_last_sorted_name():
    """Equal scores: the reference's `>=` argmax keeps the last node in
    sorted-name order (kube_scheduler.rs:140-150)."""
    scheduler = create_scheduler()
    scheduler.add_node(Node.new("node_a", 8000, 8000))
    scheduler.add_node(Node.new("node_b", 8000, 8000))
    assert scheduler.schedule_one(Pod.new("p", 1000, 1000, 1.0)) == "node_b"


def test_active_queue_order():
    """Min-by-timestamp with FIFO tie-break (reference: queue.rs:88-114)."""
    queue = ActiveQueue()
    for ts in [1.0, 5.0, 4.0, 0.5, 4.0]:
        queue.push(QueuedPodInfo(ts, 1, ts, "some_pod"))
    assert [queue.pop().timestamp for _ in range(5)] == [0.5, 1.0, 4.0, 4.0, 5.0]
    assert queue.pop() is None


def test_unschedulable_queue_order():
    """(insert_timestamp, pod_name) ordering (reference: queue.rs:116-165)."""
    queue = UnschedulableQueue()
    entries = [
        (1.0, "some_pod"),
        (10.0, "some_pod_2"),
        (7.0, "some_pod_5"),
        (5.0, "some_pod_3"),
        (7.0, "some_pod_4"),
    ]
    for ts, name in entries:
        queue.insert(UnschedulablePodKey(name, ts), QueuedPodInfo(ts, 1, ts, name))
    ordered = [key.pod_name for key, _ in queue.sorted_items()]
    assert ordered == ["some_pod", "some_pod_3", "some_pod_4", "some_pod_5", "some_pod_2"]


def test_scheduling_time_model():
    model = ConstantTimePerNodeModel()
    nodes = {f"n{i}": Node.new(f"n{i}", 1, 1) for i in range(5)}
    assert model.simulate_time(Pod.new("p", 1, 1, 1.0), nodes) == pytest.approx(5e-6)


def test_node_pool_init_allocate_reclaim():
    """reference: node_component_pool.rs:79-143."""
    sim = Simulation(123)
    pool = NodeComponentPool(10, sim)
    assert len(pool) == 10
    for idx, component in enumerate(pool.pool):
        assert component.context_name() == f"pool_node_context_{idx}"

    config = default_test_simulation_config()
    node = Node.new("node_42", 0, 0)
    component = pool.allocate_component(node, 0, config)
    assert len(pool) == 9
    assert component.runtime.node == node

    pool.reclaim_component(component)
    assert len(pool) == 10
    assert pool.pool[-1].runtime is None


def test_node_pool_exhaustion_raises():
    sim = Simulation(123)
    pool = NodeComponentPool(2, sim)
    config = default_test_simulation_config()
    pool.allocate_component(Node.new("a", 0, 0), 0, config)
    pool.allocate_component(Node.new("b", 0, 0), 0, config)
    with pytest.raises(RuntimeError):
        pool.allocate_component(Node.new("c", 0, 0), 0, config)
