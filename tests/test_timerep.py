"""Direct property tests of the window-indexed 32-bit time representation
(batched/timerep.py) — the foundation the batched path's precision claims
rest on. The invariants, checked over randomized values up to Alibaba-scale
timestamps (~7e5 s) and beyond:

- from_f64 -> to_f64 roundtrips within interval * 2^-24 at ANY magnitude
  (float32 absolute seconds lose the sub-0.1 s delays above ~1e5 s).
- pair ordering (t_lt / t_le / t_min) agrees with float64 ordering whenever
  the float64 gap exceeds the offset ulp.
- t_norm returns off ∈ [0, interval) and preserves the represented time.
- t_add matches float64 addition to the same ulp bound.
- infinity (win >= INF_WIN) propagates through min/compare and never
  produces NaN.
"""

import numpy as np

from kubernetriks_tpu.batched.timerep import (
    INF_WIN,
    TPair,
    from_f64_np,
    is_inf,
    t_add,
    t_inf,
    t_le,
    t_lt,
    t_min,
    t_norm,
    to_f64,
)

INTERVAL = 10.0
# One float32 ulp at `interval`: the cast rounds within half an ulp and the
# boundary clamp within one — still three orders below the smallest modeled
# delay (0.023 s).
ULP = INTERVAL * 2**-23


def _pairs(rng, n, t_max=7e5):
    t = rng.uniform(0.0, t_max, n)
    # Mix in exact multiples and near-boundary values (the floor guard).
    t[: n // 8] = np.round(t[: n // 8] / INTERVAL) * INTERVAL
    t[n // 8 : n // 4] += -t[n // 8 : n // 4] % INTERVAL - 1e-9
    win, off = from_f64_np(t, INTERVAL)
    return t, TPair(win=win, off=off)


def test_roundtrip_precision_at_alibaba_scale():
    rng = np.random.default_rng(0)
    t, pair = _pairs(rng, 4096)
    back = to_f64(pair, INTERVAL)
    assert np.max(np.abs(back - t)) <= ULP
    assert np.all(pair.off >= 0.0) and np.all(pair.off < INTERVAL)
    # ...where float32 absolute seconds would already have lost the delays:
    f32_err = np.abs(t.astype(np.float32).astype(np.float64) - t)
    assert f32_err.max() > 0.01  # ~0.03-0.06 s at 7e5 s


def test_ordering_matches_f64():
    rng = np.random.default_rng(1)
    t_a, a = _pairs(rng, 4096)
    t_b, b = _pairs(rng, 4096)
    # Only compare where f64 separation exceeds the representable ulp.
    apart = np.abs(t_a - t_b) > 2 * ULP
    lt = np.asarray(t_lt(a, b))
    le = np.asarray(t_le(a, b))
    np.testing.assert_array_equal(lt[apart], (t_a < t_b)[apart])
    np.testing.assert_array_equal(le[apart], (t_a <= t_b)[apart])
    # t_le is t_lt-or-equal exactly (pairwise identical components).
    eq = (np.asarray(a.win) == np.asarray(b.win)) & (
        np.asarray(a.off) == np.asarray(b.off)
    )
    np.testing.assert_array_equal(le, lt | eq)
    m = t_min(a, b)
    np.testing.assert_allclose(
        np.asarray(to_f64(m, INTERVAL))[apart],
        np.minimum(t_a, t_b)[apart],
        atol=ULP,
    )


def test_add_and_norm():
    rng = np.random.default_rng(2)
    t_a, a = _pairs(rng, 4096)
    # Delay-like addends: sub-second to a few windows long.
    t_d = rng.uniform(0.0, 35.0, 4096)
    dwin, doff = from_f64_np(t_d, INTERVAL)
    s = t_add(a, TPair(win=dwin, off=doff), np.float32(INTERVAL))
    off = np.asarray(s.off)
    assert np.all(off >= 0.0) and np.all(off < INTERVAL)
    np.testing.assert_allclose(
        np.asarray(to_f64(s, INTERVAL)), t_a + t_d, atol=4 * ULP
    )
    # t_norm with an arbitrary multi-window offset lands in [0, interval)
    # and preserves the represented time (offsets at the window boundary may
    # legitimately round the carry up: 30 + 9.9999990 == 40.0 in float32).
    n = t_norm(a.win, np.float32(3.0) * np.float32(INTERVAL) + a.off, np.float32(INTERVAL))
    off_n = np.asarray(n.off)
    assert np.all(off_n >= 0.0) and np.all(off_n < INTERVAL)
    np.testing.assert_allclose(
        np.asarray(to_f64(n, INTERVAL)), t_a + 3 * INTERVAL, atol=4 * ULP
    )


def test_infinity_semantics():
    inf = t_inf((8,))
    assert np.all(np.asarray(is_inf(inf)))
    assert np.all(np.isinf(to_f64(inf, INTERVAL)))
    rng = np.random.default_rng(3)
    _, a = _pairs(rng, 8)
    # Finite always sorts before +inf; min picks the finite side.
    assert np.all(np.asarray(t_lt(a, inf)))
    assert not np.any(np.asarray(t_lt(inf, a)))
    m = t_min(inf, a)
    np.testing.assert_array_equal(np.asarray(m.win), np.asarray(a.win))
    # from_f64 of +inf maps to the canonical infinite pair, no NaN anywhere.
    win, off = from_f64_np(np.array([np.inf, 5.0]), INTERVAL)
    assert win[0] == INF_WIN and off[0] == 0.0
    assert not np.any(np.isnan(off))
