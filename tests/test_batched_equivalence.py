"""Batched-vs-scalar equivalence: the vectorized JAX path at batch=1 must
reproduce the scalar oracle's scheduling decisions, terminal counts and timing
stats on the same traces (SURVEY.md §7 'Scalar reference path').

Integer facts (assignments, phase counts, terminal counters) must match
exactly; float timing stats match to float32 tolerance (the scalar path runs
in Python f64, the batched state in f32).
"""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import (
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
)
from kubernetriks_tpu.core.types import PodConditionType
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

# Node/pod names sort in creation order so the scalar path's sorted-name
# iteration equals the batched path's slot order (tie-breaks align).
CLUSTER_YAML = """
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 4000, ram: 8589934592}}
- timestamp: 200
  event_type:
    !CreateNode
      node:
        metadata: {name: node_02}
        status: {capacity: {cpu: 16000, ram: 34359738368}}
"""


def pod_yaml(name, cpu, ram, duration, ts):
    duration_line = (
        f"running_duration: {duration}" if duration is not None else ""
    )
    return f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata: {{name: {name}}}
        spec:
          resources:
            requests: {{cpu: {cpu}, ram: {ram}}}
            limits: {{cpu: {cpu}, ram: {ram}}}
          {duration_line}
"""


GiB = 1024**3


def make_workload():
    events = ""
    # A mix that exercises: parallel fit, serialization, unschedulable-then-
    # freed, late big node. All ram values MiB-aligned so quantization is exact.
    specs = [
        ("pod_00", 2000, 4 * GiB, 50.0, 10),
        ("pod_01", 2000, 4 * GiB, 80.0, 11),
        ("pod_02", 4000, 8 * GiB, 40.0, 12),
        ("pod_03", 4000, 8 * GiB, 30.0, 13),
        ("pod_04", 12000, 24 * GiB, 60.0, 20),  # waits for node_02 at t=200
        ("pod_05", 1000, 2 * GiB, 25.0, 95),
        ("pod_06", 8000, 16 * GiB, 45.0, 210),
    ]
    for spec in specs:
        events += pod_yaml(*spec)
    return "events:" + events, [s[0] for s in specs]


def run_scalar(config, cluster_yaml, workload_yaml, until):
    sim = KubernetriksSimulation(config)
    sim.initialize(
        GenericClusterTrace.from_yaml(cluster_yaml),
        GenericWorkloadTrace.from_yaml(workload_yaml),
    )
    sim.step_until_time(until)
    return sim


def run_batched(config, cluster_yaml, workload_yaml, until, n_clusters=1):
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(cluster_yaml).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=n_clusters,
    )
    batched.step_until_time(until)
    return batched


@pytest.mark.parametrize("delays", ["zero", "reference"])
def test_batch_of_one_matches_scalar(delays):
    suffix = ""
    if delays == "zero":
        suffix = "\n".join(
            f"{k}: 0.0"
            for k in (
                "as_to_ps_network_delay",
                "ps_to_sched_network_delay",
                "sched_to_as_network_delay",
                "as_to_node_network_delay",
            )
        )
    config = default_test_simulation_config(suffix)
    workload_yaml, pod_names = make_workload()

    scalar = run_scalar(config, CLUSTER_YAML, workload_yaml, 2000.0)
    batched = run_batched(config, CLUSTER_YAML, workload_yaml, 2000.0)

    # Every pod: same terminal state, same assigned node, close start time.
    view = batched.pod_view(0)
    for name in pod_names:
        scalar_pod = scalar.persistent_storage.succeeded_pods.get(name)
        assert scalar_pod is not None, f"{name} did not succeed in scalar run"
        b = view[name]
        assert b["phase"] == PHASE_SUCCEEDED, f"{name}: batched phase {b['phase']}"
        assert b["node"] == scalar_pod.status.assigned_node, name
        scalar_start = scalar_pod.get_condition(
            PodConditionType.POD_RUNNING
        ).last_transition_time
        assert b["start_time"] == pytest.approx(scalar_start, abs=1e-2), name

    # Metrics: counts exact, timing stats to f32 tolerance.
    sm = scalar.metrics_collector.accumulated_metrics
    bm = batched.metrics_summary()
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded
    assert bm["counters"]["terminated_pods"] == sm.internal.terminated_pods
    for key, scalar_est in [
        ("pod_duration", sm.pod_duration_stats),
        ("pod_queue_time", sm.pod_queue_time_stats),
        ("pod_schedule_time", sm.pod_scheduling_algorithm_latency_stats),
    ]:
        best = bm["timings"][key]
        assert best["min"] == pytest.approx(scalar_est.min(), rel=1e-4, abs=1e-3), key
        assert best["max"] == pytest.approx(scalar_est.max(), rel=1e-4, abs=1e-3), key
        assert best["mean"] == pytest.approx(scalar_est.mean(), rel=1e-4, abs=1e-3), key


def test_node_removal_reschedules_like_scalar():
    config = default_test_simulation_config()
    cluster = (
        CLUSTER_YAML
        + """
- timestamp: 60
  event_type:
    !RemoveNode
      node_name: node_00
"""
    )
    workload = "events:" + pod_yaml("pod_00", 6000, 12 * GiB, 100.0, 10)
    scalar = run_scalar(config, cluster, workload, 3000.0)
    batched = run_batched(config, cluster, workload, 3000.0)

    scalar_pod = scalar.persistent_storage.succeeded_pods["pod_00"]
    b = batched.pod_view(0)["pod_00"]
    assert b["phase"] == PHASE_SUCCEEDED
    # Rescheduled onto node_02 (arrives t=200) in both paths.
    assert b["node"] == scalar_pod.status.assigned_node == "node_02"
    scalar_start = scalar_pod.get_condition(
        PodConditionType.POD_RUNNING
    ).last_transition_time
    assert b["start_time"] == pytest.approx(scalar_start, abs=1e-2)


def test_unschedulable_pod_stays_parked_in_both():
    config = default_test_simulation_config()
    workload = "events:" + pod_yaml("pod_00", 99000, 99 * GiB, 10.0, 10)
    scalar = run_scalar(config, CLUSTER_YAML, workload, 500.0)
    batched = run_batched(config, CLUSTER_YAML, workload, 500.0)

    assert "pod_00" in scalar.persistent_storage.unscheduled_pods_cache
    assert batched.pod_view(0)["pod_00"]["phase"] == PHASE_UNSCHEDULABLE
    assert batched.metrics_summary()["counters"]["pods_succeeded"] == 0


def test_pod_removal_while_running_matches():
    config = default_test_simulation_config()
    workload = (
        "events:"
        + pod_yaml("pod_00", 2000, 4 * GiB, 500.0, 10)
        + """
- timestamp: 100
  event_type:
    !RemovePod
      pod_name: pod_00
"""
    )
    scalar = run_scalar(config, CLUSTER_YAML, workload, 1000.0)
    batched = run_batched(config, CLUSTER_YAML, workload, 1000.0)

    assert scalar.metrics_collector.accumulated_metrics.pods_removed == 1
    bm = batched.metrics_summary()
    assert bm["counters"]["pods_removed"] == 1
    assert bm["counters"]["pods_succeeded"] == 0


def test_node_removed_same_tick_as_assignment_matches():
    """Same-tick race: node removal coincides with the scheduling cycle's
    assignment; the pending-removal guard drops the assignment in the scalar
    path (reference: tests/test_pods.rs:366-398, api_server.rs:163-193) and
    the batched removal-time resolution must agree — nothing ever runs."""
    config = default_test_simulation_config()
    cluster = (
        CLUSTER_YAML
        + """
- timestamp: 50
  event_type:
    !RemoveNode
      node_name: node_00
- timestamp: 50
  event_type:
    !RemoveNode
      node_name: node_01
- timestamp: 250
  event_type:
    !RemoveNode
      node_name: node_02
"""
    )
    # Queued at t=49.x, assigned in the t=50 cycle — the same tick the first
    # removals land; the late node_02 (created t=200) is removed at t=250,
    # racing the rescheduled assignment the same way.
    workload = "events:" + pod_yaml("pod_00", 2000, 4 * GiB, 100.0, 49)
    scalar = run_scalar(config, cluster, workload, 1000.0)
    batched = run_batched(config, cluster, workload, 1000.0)

    assert scalar.metrics_collector.accumulated_metrics.pods_succeeded == 0
    bm = batched.metrics_summary()["counters"]
    assert bm["pods_succeeded"] == 0
    # The pod survives, parked/queued with no nodes, in both paths.
    assert scalar.persistent_storage.get_pod("pod_00") is not None
    assert batched.pod_view(0)["pod_00"]["phase"] != PHASE_SUCCEEDED
    assert scalar.api_server.node_count() == 0


def test_pod_removed_before_scheduling_matches():
    """RemovePod while the pod is still parked: dropped from queues, never
    counted as a node-side removal, and the CA's unscheduled cache forgets
    it (reference: tests/test_pods.rs:401-449)."""
    config = default_test_simulation_config()
    # Too big for every node: parks unschedulable, then removed at t=50.
    workload = (
        "events:"
        + pod_yaml("pod_00", 99000, 99 * GiB, 500.0, 10)
        + """
- timestamp: 50
  event_type:
    !RemovePod
      pod_name: pod_00
"""
    )
    scalar = run_scalar(config, CLUSTER_YAML, workload, 1000.0)
    batched = run_batched(config, CLUSTER_YAML, workload, 1000.0)

    assert scalar.persistent_storage.get_pod("pod_00") is None
    assert "pod_00" not in scalar.persistent_storage.unscheduled_pods_cache
    assert scalar.metrics_collector.accumulated_metrics.pods_removed == 0
    bm = batched.metrics_summary()["counters"]
    assert bm["pods_removed"] == 0
    assert bm["pods_succeeded"] == 0
    from kubernetriks_tpu.batched.state import PHASE_REMOVED

    assert batched.pod_view(0)["pod_00"]["phase"] == PHASE_REMOVED


def test_pod_removed_after_finish_matches():
    """RemovePod landing after the pod already finished: tolerated, counted
    as succeeded not removed, in both paths (reference:
    tests/test_pods.rs:597-637, node_component.rs:298-332)."""
    config = default_test_simulation_config()
    workload = (
        "events:"
        + pod_yaml("pod_00", 2000, 4 * GiB, 50.0, 10)
        + """
- timestamp: 500
  event_type:
    !RemovePod
      pod_name: pod_00
"""
    )
    scalar = run_scalar(config, CLUSTER_YAML, workload, 1000.0)
    batched = run_batched(config, CLUSTER_YAML, workload, 1000.0)

    s = scalar.metrics_collector.accumulated_metrics
    assert (s.pods_removed, s.pods_succeeded) == (0, 1)
    bm = batched.metrics_summary()["counters"]
    assert (bm["pods_removed"], bm["pods_succeeded"]) == (0, 1)
    assert batched.pod_view(0)["pod_00"]["phase"] == PHASE_SUCCEEDED


def test_large_timestamp_equivalence_f64():
    """Fidelity at Alibaba-scale timestamps: the same scenario shifted to
    t ~ 1e6 s must still match the scalar f64 oracle with the reference's
    sub-0.1 s network delays (f32 sim time has ~0.06 s resolution there, which
    would swallow the delays; reference delay values: src/config.yaml:73-78)."""
    T0 = 1_000_000.0  # multiple of the 10 s cycle interval
    config = default_test_simulation_config(
        "\n".join(
            [
                "as_to_ps_network_delay: 0.050",
                "ps_to_sched_network_delay: 0.089",
                "sched_to_as_network_delay: 0.023",
                "as_to_node_network_delay: 0.152",
            ]
        )
    )

    cluster_yaml = CLUSTER_YAML.replace("timestamp: 5", f"timestamp: {5 + T0}").replace(
        "timestamp: 200", f"timestamp: {200 + T0}"
    )
    events = ""
    specs = [
        ("pod_00", 2000, 4 * GiB, 50.0, 10 + T0),
        ("pod_01", 2000, 4 * GiB, 80.0, 11 + T0),
        ("pod_02", 4000, 8 * GiB, 40.0, 12 + T0),
        ("pod_03", 4000, 8 * GiB, 30.0, 13 + T0),
        ("pod_04", 12000, 24 * GiB, 60.0, 20 + T0),  # waits for node_02
        ("pod_05", 1000, 2 * GiB, 25.0, 95 + T0),
    ]
    for spec in specs:
        events += pod_yaml(*spec)
    workload_yaml = "events:" + events

    scalar = run_scalar(config, cluster_yaml, workload_yaml, T0 + 2000.0)

    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(cluster_yaml).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=1,
    )
    # Windows before T0 are no-ops (no events, empty queues); skip them.
    batched.next_window = T0
    batched.step_until_time(T0 + 2000.0)

    view = batched.pod_view(0)
    for name, *_ in specs:
        scalar_pod = scalar.persistent_storage.succeeded_pods.get(name)
        assert scalar_pod is not None, f"{name} did not succeed in scalar run"
        b = view[name]
        assert b["phase"] == PHASE_SUCCEEDED, name
        assert b["node"] == scalar_pod.status.assigned_node, name
        scalar_start = scalar_pod.get_condition(
            PodConditionType.POD_RUNNING
        ).last_transition_time
        # f64 resolution at t=1e6 is ~1e-10 s; the delays must survive exactly.
        assert b["start_time"] == pytest.approx(scalar_start, abs=1e-6), name

    sm = scalar.metrics_collector.accumulated_metrics
    bm = batched.metrics_summary()
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded
    assert bm["counters"]["terminated_pods"] == sm.internal.terminated_pods


def test_conditional_move_matches_scalar():
    """enable_unscheduled_pods_conditional_move on the batched path: both
    resource-aware wake scans must mirror the scalar oracle
    (reference: src/core/scheduler/scheduler.rs:391-409 node-add scan with its
    inverted fits-stay sense, :366-380 freed-budget first-fit)."""
    config = default_test_simulation_config(
        "enable_unscheduled_pods_conditional_move: true"
    )
    assert config.enable_unscheduled_pods_conditional_move

    cluster = """
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 4000, ram: 8589934592}}
- timestamp: 60
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 2500, ram: 5368709120}}
"""
    # pod_00 fills node_00; pod_01 + pod_02 park unschedulable.
    # t=60 node_01 arrives: node scan walks (pod_01, pod_02) in park order —
    # pod_01 (3000 > 2500) does NOT fit => woken (and parks again);
    # pod_02 (2000 <= 2500) fits => STAYS parked (the reference's inverted
    # sense) even though node_01 could run it.
    # t~120 pod_00 finishes: freed scan order is (pod_02 ts~20, pod_01 ts~70);
    # pod_02 fits the freed (3000, 6 GiB) => woken and scheduled; pod_01 does
    # not fit the remaining (1000, 2 GiB) => stays until the 300 s stale flush.
    workload = (
        "events:"
        + pod_yaml("pod_00", 3000, 6 * GiB, 100.0, 10)
        + pod_yaml("pod_01", 3000, 6 * GiB, 40.0, 15)
        + pod_yaml("pod_02", 2000, 4 * GiB, 40.0, 16)
    )

    scalar = run_scalar(config, cluster, workload, 600.0)
    batched = run_batched(config, cluster, workload, 600.0)

    view = batched.pod_view(0)
    for name in ("pod_00", "pod_01", "pod_02"):
        scalar_pod = scalar.persistent_storage.succeeded_pods.get(name)
        assert scalar_pod is not None, f"{name} did not succeed in scalar run"
        b = view[name]
        assert b["phase"] == PHASE_SUCCEEDED, name
        assert b["node"] == scalar_pod.status.assigned_node, name
        scalar_start = scalar_pod.get_condition(
            PodConditionType.POD_RUNNING
        ).last_transition_time
        assert b["start_time"] == pytest.approx(scalar_start, abs=1e-6), name

    # The stale flush (not the wake scans) is what released pod_01: it parked
    # again after the node-add wake, then waited out the 300 s stay.
    scalar_p1_start = (
        scalar.persistent_storage.succeeded_pods["pod_01"]
        .get_condition(PodConditionType.POD_RUNNING)
        .last_transition_time
    )
    assert scalar_p1_start > 370.0

    sm = scalar.metrics_collector.accumulated_metrics
    bm = batched.metrics_summary()
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded == 3


def test_conditional_move_fitting_pod_stays_parked():
    """Pinned reference quirk: after a node-add wake, a pod that FITS the new
    node stays in the unschedulable queue (scheduler.rs:391-409 returns false
    => not moved) — on both paths."""
    config = default_test_simulation_config(
        "enable_unscheduled_pods_conditional_move: true"
    )
    cluster = """
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 1000, ram: 2147483648}}
- timestamp: 40
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""
    workload = "events:" + pod_yaml("pod_00", 4000, 8 * GiB, 50.0, 10)

    # Stop before the 300 s stale flush would release it.
    scalar = run_scalar(config, cluster, workload, 200.0)
    batched = run_batched(config, cluster, workload, 200.0)

    assert "pod_00" in scalar.persistent_storage.unscheduled_pods_cache
    assert len(scalar.scheduler.unschedulable_pods) == 1
    assert batched.pod_view(0)["pod_00"]["phase"] == PHASE_UNSCHEDULABLE
    # Flush-all would have scheduled it: rerun without conditional move.
    config2 = default_test_simulation_config()
    scalar2 = run_scalar(config2, cluster, workload, 200.0)
    batched2 = run_batched(config2, cluster, workload, 200.0)
    assert "pod_00" in scalar2.persistent_storage.succeeded_pods
    assert batched2.pod_view(0)["pod_00"]["phase"] == PHASE_SUCCEEDED


def test_multi_chunk_event_drain_matches_single_chunk():
    """Event application drains a window's due events in chunks of
    max_events_per_window inside a while_loop; a burst window (more events
    than the chunk size) must produce bit-identical state to a single big
    chunk — covers the cross-chunk cursor / n_creates / queue-seq carry."""
    import jax

    config = default_test_simulation_config()
    workload_yaml, pod_names = make_workload()

    big = run_batched(config, CLUSTER_YAML, workload_yaml, 2000.0)

    tiny = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=1,
        max_events_per_window=2,  # forces multi-iteration drains
    )
    tiny.step_until_time(2000.0)

    flat_a, _ = jax.tree_util.tree_flatten_with_path(big.state)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(tiny.state)
    for (path, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(path),
        )


def test_larger_batch_replicates_cluster_zero():
    """Every cluster in a homogeneous batch produces identical results."""
    config = default_test_simulation_config()
    workload_yaml, pod_names = make_workload()
    batched = run_batched(config, CLUSTER_YAML, workload_yaml, 2000.0, n_clusters=8)
    base = batched.cluster_metrics(0)
    for c in range(1, 8):
        assert batched.cluster_metrics(c) == base
    assert base["pods_succeeded"] == len(pod_names)


def test_checkpoint_resume_bit_identical(tmp_path):
    """save_checkpoint mid-run + load_checkpoint into a fresh build resumes
    bit-identically: the full state is one pytree (SURVEY §5.4)."""
    import jax

    config = default_test_simulation_config()
    workload_yaml, _ = make_workload()

    straight = run_batched(config, CLUSTER_YAML, workload_yaml, 2000.0)

    half = run_batched(config, CLUSTER_YAML, workload_yaml, 990.0)
    half.save_checkpoint(str(tmp_path / "ckpt"))

    resumed = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=1,
    )
    resumed.load_checkpoint(str(tmp_path / "ckpt"))
    assert resumed.next_window == 1000.0
    resumed.step_until_time(2000.0)

    flat_a, _ = jax.tree_util.tree_flatten_with_path(straight.state)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(resumed.state)
    for (path, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=jax.tree_util.keystr(path)
        )


def test_checkpoint_preserves_gauge_series(tmp_path):
    config = default_test_simulation_config()
    workload_yaml, _ = make_workload()
    sim = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=1,
    )
    sim.collect_gauges = True
    sim.step_until_time(490.0)
    sim.save_checkpoint(str(tmp_path / "g_ckpt"))

    resumed = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_YAML).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=1,
    )
    resumed.collect_gauges = True
    resumed.load_checkpoint(str(tmp_path / "g_ckpt"))
    resumed.step_until_time(700.0)
    times, samples = resumed.gauge_series()
    assert times[0] == 0.0 and times[-1] == 700.0  # no pre-checkpoint hole
    assert samples.shape[0] == len(times) == 71
