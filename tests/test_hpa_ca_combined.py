"""Combined HPA + CA cross-path golden: the horizontal autoscaler scales a
pod group beyond the base node's capacity, the parked replicas drive a
cluster-autoscaler scale-up, the load drop walks both back down — and the
batched path matches the scalar oracle EXACTLY (replica counts, node counts,
and all autoscaler counters at every 60 s boundary, through two full load
cycles). This is the full control-loop stack of the reference
(horizontal_pod_autoscaler.rs + cluster_autoscaler.rs + the unscheduled-pods
cache of persistent_storage.rs:137-168) interacting in one run."""

import numpy as np

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CONFIG_SUFFIX = """
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 10
  node_groups:
  - node_template:
      metadata:
        name: ca_node
      status:
        capacity:
          cpu: 8000
          ram: 17179869184
"""

CLUSTER_TRACE = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""

# Load curve: idle -> burst (desired 9 > the base node's 4-pod capacity,
# parking replicas until the CA adds nodes) -> idle (HPA scales to 1, CA
# drains its nodes), cycling.
WORKLOAD_TRACE = """
events:
- timestamp: 59.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 10
        pod_template:
          metadata:
            name: grp
          spec:
            resources:
              requests: {cpu: 2000, ram: 2147483648}
              limits: {cpu: 2000, ram: 2147483648}
        target_resources_usage:
          cpu_utilization: 0.5
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 300.0
                total_load: 1.0
              - duration: 300.0
                total_load: 4.5
              - duration: 600.0
                total_load: 0.5
"""


def test_hpa_drives_ca_and_both_paths_agree_exactly():
    config = default_test_simulation_config(CONFIG_SUFFIX)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(WORKLOAD_TRACE),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(WORKLOAD_TRACE).convert_to_simulator_events(),
        n_clusters=1,
    )

    expected = {  # (replicas, nodes) at sampled boundaries (probed golden)
        301.0: (2, 1),
        421.0: (8, 1),   # burst: HPA upscales before the CA reacts
        481.0: (9, 2),   # parked replicas pull in CA nodes
        541.0: (9, 3),   # peak: 9 x 2000 mcpu across 3 x 8000 nodes
        661.0: (1, 3),   # load drop: HPA scales in first
        721.0: (1, 1),   # CA drains its idle nodes
        1201.0: (1, 1),
        1681.0: (9, 2),  # second cycle reproduces the first
        1741.0: (9, 3),
    }
    for t in np.arange(61.0, 1800.0, 60.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        s_rep = len(scalar.horizontal_pod_autoscaler.pod_groups["grp"].created_pods)
        b_rep = batched.hpa_replicas(0)["grp"]
        s_nodes = scalar.api_server.node_count()
        b_nodes = int(np.asarray(batched.state.nodes.alive).sum())
        assert (b_rep, b_nodes) == (s_rep, s_nodes), (
            f"t={t}: batched (replicas, nodes) ({b_rep}, {b_nodes}) != "
            f"scalar ({s_rep}, {s_nodes})"
        )
        if float(t) in expected:
            assert (s_rep, s_nodes) == expected[float(t)], (
                f"t={t}: scalar {(s_rep, s_nodes)} != golden {expected[float(t)]}"
            )

    s = scalar.metrics_collector.accumulated_metrics
    b = batched.metrics_summary()["counters"]
    assert b["total_scaled_up_nodes"] == s.total_scaled_up_nodes == 4
    assert b["total_scaled_up_pods"] == s.total_scaled_up_pods == 15
    assert b["total_scaled_down_pods"] == s.total_scaled_down_pods == 8
