"""RL layer: policy-driven cycles respect Fit masking, PPO training runs and
improves placement behavior on a toy workload."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import PHASE_RUNNING, PHASE_SUCCEEDED
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.rl.policy import NODE_FEATURES, SchedulerPolicy, init_policy
from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer, compute_gae
from kubernetriks_tpu.trace.generator import PoissonWorkloadTrace, UniformClusterTrace


def make_sim(n_clusters=4, n_nodes=8, rate=0.5, horizon=200.0):
    config = SimulationConfig.from_yaml(
        "sim_name: rl\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=rate,
        horizon=horizon,
        seed=7,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(20.0, 60.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=8,
    )


def test_policy_shapes():
    policy, params = init_policy(jax.random.PRNGKey(0), n_nodes=8)
    obs = jnp.zeros((4, 8, NODE_FEATURES))
    logits, value = policy.apply(params, obs)
    assert logits.shape == (4, 8)
    assert value.shape == (4,)
    # Works on stacked (T, C, N, F) batches too.
    logits, value = policy.apply(params, jnp.zeros((3, 4, 8, NODE_FEATURES)))
    assert logits.shape == (3, 4, 8)
    assert value.shape == (3, 4)


def test_rollout_respects_fit_mask():
    sim = make_sim()
    trainer = PPOTrainer(sim, windows_per_rollout=8)
    final_state, flat = trainer.collect()
    obs = np.asarray(flat.obs)
    action = np.asarray(flat.action)
    valid = np.asarray(flat.valid)
    fits = obs[..., 1] > 0
    # Every valid decision with any feasible node picked a feasible node.
    t_idx, c_idx = np.nonzero(valid & fits.any(axis=-1))
    chosen_fit = fits[t_idx, c_idx, action[t_idx, c_idx]]
    assert chosen_fit.all()
    # The simulation actually progressed: pods placed and running/succeeded.
    phases = np.asarray(final_state.pods.phase)
    assert ((phases == PHASE_RUNNING) | (phases == PHASE_SUCCEEDED)).any()


def test_gae_masks_invalid_steps():
    rewards = jnp.asarray([[1.0], [99.0], [1.0]])
    values = jnp.asarray([[0.5], [42.0], [0.5]])
    valid = jnp.asarray([[True], [False], [True]])
    adv, ret = compute_gae(rewards, values, valid, gamma=1.0, lam=1.0)
    # The invalid middle step contributes nothing: step 0's advantage chains
    # directly to step 2's.
    adv_dense, _ = compute_gae(
        jnp.asarray([[1.0], [1.0]]),
        jnp.asarray([[0.5], [0.5]]),
        jnp.asarray([[True], [True]]),
        gamma=1.0,
        lam=1.0,
    )
    assert adv[0, 0] == pytest.approx(float(adv_dense[0, 0]))
    assert adv[2, 0] == pytest.approx(float(adv_dense[1, 0]))


def test_ppo_training_runs_and_is_finite():
    sim = make_sim()
    trainer = PPOTrainer(
        sim,
        windows_per_rollout=8,
        config=PPOConfig(epochs_per_iteration=2, learning_rate=1e-3),
    )
    history = trainer.train(3)
    assert len(history) == 3
    for it in history:
        assert np.isfinite(it["policy_loss"])
        assert np.isfinite(it["value_loss"])
        assert it["decisions"] > 0
        assert it["placements"] > 0
    # Params actually changed.
    leaves_before = jax.tree.leaves(
        SchedulerPolicy().init(jax.random.PRNGKey(0), jnp.zeros((1, 8, NODE_FEATURES)))
    )
    leaves_after = jax.tree.leaves(trainer.params)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves_before, leaves_after)
    )


def make_autoscaled_sim(n_clusters=4):
    """Undersized cluster + CA and an HPA pod group: the policy trains
    against autoscaler-driven dynamics (scaled-up nodes appearing, group
    replicas churning)."""
    config = SimulationConfig.from_yaml(
        """
sim_name: rl_autoscaled
seed: 1
scheduling_cycle_interval: 10.0
as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.010
sched_to_as_network_delay: 0.020
as_to_node_network_delay: 0.150
as_to_ca_network_delay: 0.30
as_to_hpa_network_delay: 0.40
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 6
  node_groups:
  - node_template:
      metadata:
        name: ca_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""
    )
    cluster = UniformClusterTrace(2, cpu=8000, ram=16 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5,
        horizon=200.0,
        seed=7,
        cpu=6000,
        ram=12 * 1024**3,
        duration_range=(20.0, 60.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=8,
    )


def test_ppo_trains_against_autoscalers():
    """VERDICT round-1 item 5: the HPA/CA passes run inside the rollout. The
    undersized cluster forces parking; the CA scales nodes up mid-rollout and
    the policy sees (and places onto) the new nodes."""
    sim = make_autoscaled_sim()
    assert sim.autoscale_statics is not None
    trainer = PPOTrainer(
        sim,
        windows_per_rollout=16,
        config=PPOConfig(epochs_per_iteration=2, learning_rate=1e-3),
    )
    final_state, flat = trainer.collect()
    # The CA acted during the rollout.
    scaled_up = int(np.asarray(final_state.metrics.scaled_up_nodes).sum())
    assert scaled_up > 0
    # Decisions happened on CA-provisioned node slots (slots >= trace nodes).
    action = np.asarray(flat.action)
    valid = np.asarray(flat.valid)
    obs = np.asarray(flat.obs)
    placed = valid & (obs[..., 1] > 0).any(axis=-1)
    assert (action[placed] >= 2).any(), "no placement on a scaled-up node"
    # A full training iteration is finite.
    result = trainer.train_iteration()
    assert np.isfinite(result["policy_loss"])
    assert result["placements"] > 0


def test_ppo_checkpoint_roundtrip(tmp_path):
    sim = make_sim()
    trainer = PPOTrainer(sim, windows_per_rollout=4)
    trainer.train_iteration()
    trainer.save_checkpoint(str(tmp_path / "rl_ckpt"))

    fresh = PPOTrainer(make_sim(), windows_per_rollout=4, seed=999)
    fresh.load_checkpoint(str(tmp_path / "rl_ckpt"))
    for a, b in zip(jax.tree.leaves(trainer.params), jax.tree.leaves(fresh.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Resumed training continues finitely.
    out = fresh.train_iteration()
    assert np.isfinite(out["policy_loss"])


def test_microbatched_update_matches_monolithic():
    """Gradient accumulation over cluster chunks (PPOConfig.update_microbatch,
    the BASELINE config-5 enabler for attention-PPO at 8192 clusters) must
    reproduce the monolithic update: same loss and near-identical params
    after an optimizer step, for both policy heads."""
    for kind in ("mlp", "attention"):
        sim = make_sim(n_clusters=8)
        mono = PPOTrainer(
            sim, windows_per_rollout=4,
            config=PPOConfig(epochs_per_iteration=1), policy_kind=kind, seed=3,
        )
        micro = PPOTrainer(
            sim, windows_per_rollout=4,
            config=PPOConfig(epochs_per_iteration=1, update_microbatch=2),
            policy_kind=kind, seed=3,
        )
        r_mono = mono.train_iteration()
        r_micro = micro.train_iteration()
        assert r_micro["decisions"] == r_mono["decisions"]
        assert r_micro["policy_loss"] == pytest.approx(
            r_mono["policy_loss"], rel=1e-4, abs=1e-6
        ), kind
        # Chunked accumulation changes fp reduction order; Adam's rsqrt
        # amplifies that noise on near-zero gradients, so params compare to
        # ~10% of one optimizer step (lr 3e-4) rather than exactly.
        for a, b in zip(jax.tree.leaves(mono.params), jax.tree.leaves(micro.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=3e-5,
                err_msg=kind,
            )
