"""CA slot reclaim + bounded-memory endurance gates (r14, ROADMAP #2).

The batched CA reserves node slots per group; without reclaim the cursor
is monotone, so sustained churn eventually RAISES
(engine.check_autoscaler_bounds) where the reference keeps running — its
node_component_pool reuses components on scale-down
(node_component_pool.rs:60-77). With reclaim (KTPU_RECLAIM) a periodic
in-trace compaction returns fully-retired slots, the cursor tracks LIVE
occupancy, and trajectories stay SCALAR-EXACT because every allocation
carries the scalar's total_allocated naming index
(autoscale.ca_name_order derives every name-ordered walk from it).

Gates here:
1. Churn engineered past the pre-reclaim reserve: the old path raises,
   the new path finishes with the EXACT scalar-oracle node trajectory
   (including double-digit allocation names, "ca_node_10" < "ca_node_2")
   and a quiet loud-bound.
2. A/B bit-identity within the reserve: reclaim on/off agree on
   trajectories, metrics and dispatch_stats when churn never exhausts
   the static reserve.
3. Checkpoint/restore roundtrip carries the reclaim counters (ckpt meta
   guards a mode mismatch loudly).
4. The slow-lane endurance gate: sustained churn many times the reserve
   with chaos + streaming feeder + a mid-run checkpoint/restore, exact
   oracle trajectory, zero saturation verdicts, flat slab watermarks.
"""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

# Reserve = min(per_group_cap, max_node_count) * ca_slot_multiplier:
# max_node_count 2 at multiplier 1 gives a TWO-slot reserve the wave
# churn overruns many times over.
RECLAIM_CA_SUFFIX = """
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 2
  node_groups:
  - node_template:
      metadata:
        name: ca_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""

CLUSTER_TRACE = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base_node}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""


def wave_workload(
    n_waves: int, spacing: float = 200.0, duration: float = 60.0
) -> str:
    """n_waves churn waves: each wave's 12000-mcpu pod only fits the CA
    template (base node is 8000), so the CA opens a node, the pod runs
    `duration` seconds, and the empty node scales back down before the
    next wave — one reserve slot consumed per pod, fully retired between
    waves. Every third wave sends TWO pods (staggered finishes), so two
    CA nodes coexist and the scale-down walks candidates in NAME order
    across reused slots."""
    events = []
    pod = 0
    for k in range(n_waves):
        t0 = 10.0 + k * spacing
        for j in range(2 if k % 3 == 2 else 1):
            events.append(
                f"""
- timestamp: {round(t0 + 7.0 * j, 1)}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: wave_pod_{pod:03d}
        spec:
          resources:
            requests:
              cpu: 12000
              ram: 12582912000
            limits:
              cpu: 12000
              ram: 12582912000
          running_duration: {round(duration + 11.0 * j, 1)}
"""
            )
            pod += 1
    return "events:" + "".join(events)


def _build_batched(workload: str, config_suffix: str = "", **kwargs):
    config = default_test_simulation_config(RECLAIM_CA_SUFFIX + config_suffix)
    kwargs.setdefault("n_clusters", 1)
    kwargs.setdefault("ca_slot_multiplier", 1)
    return config, build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        **kwargs,
    )


def _scalar(config, workload: str) -> KubernetriksSimulation:
    sim = KubernetriksSimulation(config)
    sim.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    return sim


def test_reclaim_churn_past_reserve_matches_scalar():
    """12 waves (16 allocations — double-digit names included) through a
    2-slot reserve: cumulative churn 8x the static capacity. The reclaim
    path finishes with the EXACT scalar node-count trajectory and a
    clean loud-bound; the cursor ends at live occupancy, not cumulative
    allocations."""
    n_waves = 12
    workload = wave_workload(n_waves)
    config, batched = _build_batched(workload, reclaim=True)
    assert batched.reclaim
    scalar = _scalar(config, workload)

    traj_scalar, traj_batched = [], []
    horizon = 10.0 + n_waves * 200.0
    # Mid-window samples OFF the simulator's 0.01 s event-time lattice:
    # the CA cadence drifts 0.7 s/cycle, so over enough cycles some
    # create/remove lands EXACTLY on any on-lattice sample grid and the
    # comparison degenerates to float-dust tie-breaking on both sides
    # (engine.node_count_at docstring) — +5.003 never collides.
    for t in np.arange(15.003, horizon, 10.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        traj_scalar.append(scalar.api_server.node_count())
        traj_batched.append(batched.node_count_at(float(t)))

    assert max(traj_scalar) >= 3, "scenario must exercise the CA"
    assert traj_batched == traj_scalar, (
        f"scalar  {traj_scalar}\nbatched {traj_batched}"
    )
    # Cumulative churn really overran the static reserve, and reclaim
    # returned the retired slots (>= allocations - reserve capacity).
    total = int(np.asarray(batched.state.auto.ca_total).sum())
    reserve = batched._reserve_capacities["ca_reserve"][0]
    assert total >= 3 * reserve, (total, reserve)
    assert int(batched.ca_slots_reclaimed().sum()) >= total - reserve
    # Double-digit allocation names were exercised ("ca_node_10" pops
    # before "ca_node_2" in the scale-down walk).
    assert total >= 10
    # The cursor is LIVE occupancy now: everything scaled back down.
    assert int(np.asarray(batched.state.auto.ca_cursor).sum()) <= reserve
    batched.check_autoscaler_bounds()  # must NOT raise


def test_reclaim_off_churn_past_reserve_raises_loudly():
    """The same churn without reclaim crosses the documented bound: the
    engine raises at readout instead of silently starving, and the
    message points at the reclaim switch."""
    workload = wave_workload(6)
    _, batched = _build_batched(workload, reclaim=False)
    assert not batched.reclaim
    with pytest.raises(RuntimeError, match="CA slot reserve exhausted"):
        batched.step_until_time(6 * 200.0)
        batched.metrics_summary()
    with pytest.raises(RuntimeError, match="KTPU_RECLAIM"):
        batched.check_autoscaler_bounds()


def test_reclaim_ab_bit_identity_within_reserve():
    """KTPU_RECLAIM=0 vs =1 on churn the static reserve can absorb:
    node trajectories, final metrics and dispatch_stats all agree — the
    off path compiles the pre-reclaim programs, the on path's compaction
    is invisible to the trajectory."""
    import jax

    workload = wave_workload(4)
    _, on = _build_batched(workload, reclaim=True, ca_slot_multiplier=3)
    _, off = _build_batched(workload, reclaim=False, ca_slot_multiplier=3)
    traj_on, traj_off = [], []
    for t in np.arange(15.003, 4 * 200.0 + 10.0, 10.0):
        on.step_until_time(float(t))
        off.step_until_time(float(t))
        traj_on.append(on.node_count_at(float(t)))
        traj_off.append(off.node_count_at(float(t)))
    assert traj_on == traj_off
    assert on.dispatch_stats == off.dispatch_stats
    flat_on = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(np.asarray, on.state.metrics)
    )[0]
    flat_off = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(np.asarray, off.state.metrics)
    )[0]
    for (path, a), (_, b) in zip(flat_on, flat_off):
        np.testing.assert_allclose(
            a, b, rtol=1e-6, err_msg=jax.tree_util.keystr(path)
        )
    # The on path really reclaimed (the A/B is not vacuous).
    assert int(on.ca_slots_reclaimed().sum()) > 0
    on.check_autoscaler_bounds()
    off.check_autoscaler_bounds()


def test_reclaim_checkpoint_roundtrip(tmp_path):
    """Mid-run save/restore under reclaim: the reclaim leaves (ca_alloc /
    ca_total / ca_reclaimed) ride the state pytree, the restored run
    continues bit-identically, and restoring into a reclaim-off engine
    raises the actionable meta guard instead of an opaque manifest diff."""
    from kubernetriks_tpu.batched.state import compare_states

    pytest.importorskip("orbax.checkpoint")
    workload = wave_workload(8)
    path = str(tmp_path / "ckpt")

    _, a = _build_batched(workload, reclaim=True)
    a.step_until_time(700.0)
    assert int(a.ca_slots_reclaimed().sum()) > 0, "save point must be post-reclaim"
    a.save_checkpoint(path)
    a.step_until_time(1500.0)

    _, b = _build_batched(workload, reclaim=True)
    b.load_checkpoint(path)
    b.step_until_time(1500.0)
    assert compare_states(a.state, b.state) == []
    np.testing.assert_array_equal(a.ca_slots_reclaimed(), b.ca_slots_reclaimed())

    _, c = _build_batched(workload, reclaim=False)
    with pytest.raises(ValueError, match="reclaim mismatch"):
        c.load_checkpoint(path)


def test_reclaim_tristate_default_follows_checkpoint(tmp_path):
    """A TRISTATE-defaulted engine (no reclaim arg, no KTPU_RECLAIM)
    follows the checkpoint's recorded mode instead of raising: the
    accelerator default is reclaim ON, so every pre-reclaim checkpoint
    would otherwise refuse to restore on TPU/GPU until the user dug up
    KTPU_RECLAIM=0. Explicit requests keep the loud guard (pinned by the
    roundtrip test above). Both directions, continuing bit-identically
    with the matching-mode engine."""
    from kubernetriks_tpu.batched.state import compare_states

    pytest.importorskip("orbax.checkpoint")
    workload = wave_workload(8)

    # Saved WITH reclaim -> defaulted engine (CPU tristate resolves off)
    # flips ON and continues exactly like a reclaim=True engine.
    path_on = str(tmp_path / "ckpt_on")
    _, a = _build_batched(workload, reclaim=True)
    a.step_until_time(700.0)
    a.save_checkpoint(path_on)
    a.step_until_time(1500.0)
    _, b = _build_batched(workload)  # reclaim unset: tristate default
    assert b._reclaim_requested is None and not b.reclaim
    with pytest.warns(RuntimeWarning, match="following the checkpoint"):
        b.load_checkpoint(path_on)
    assert b.reclaim
    b.step_until_time(1500.0)
    assert compare_states(a.state, b.state) == []

    # Saved WITHOUT reclaim -> an engine whose reclaim came from the
    # tristate default (simulated: accelerator backends default on)
    # flips OFF and continues exactly like a reclaim=False engine.
    path_off = str(tmp_path / "ckpt_off")
    _, c = _build_batched(workload, reclaim=False)
    c.step_until_time(700.0)
    c.save_checkpoint(path_off)
    c.step_until_time(1500.0)
    _, d = _build_batched(workload, reclaim=True)
    d._reclaim_requested = None  # as if reclaim=True came from the tristate
    with pytest.warns(RuntimeWarning, match="following the checkpoint"):
        d.load_checkpoint(path_off)
    assert not d.reclaim
    assert d.state.auto.ca_alloc is None
    d.step_until_time(1500.0)
    assert compare_states(c.state, d.state) == []


def test_reclaim_refused_on_interleaving_names():
    """A trace node named inside a CA group's decimal name family makes
    the static class order unsound: explicit reclaim=True raises at
    build, naming the collision."""
    bad_cluster = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: ca_node_15}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""
    config = default_test_simulation_config(RECLAIM_CA_SUFFIX)
    with pytest.raises(ValueError, match="name family"):
        build_batched_from_traces(
            config,
            GenericClusterTrace.from_yaml(bad_cluster).convert_to_simulator_events(),
            GenericWorkloadTrace.from_yaml(wave_workload(2)).convert_to_simulator_events(),
            n_clusters=1,
            reclaim=True,
        )


@pytest.mark.slow
def test_endurance_gate_chaos_streaming_ckpt():
    """The ROADMAP #2 endurance gate, slow lane: 48 churn waves (~13
    simulated hours, cumulative allocations ~30x the static reserve)
    with node chaos on, the streaming feeder staging slabs, reclaim
    compacting the reserve, and a mid-run checkpoint/restore roundtrip.
    Finishes with the EXACT scalar-oracle node trajectory, ZERO
    saturation verdicts (the reserve never trends toward exhaustion),
    flat slab watermarks, and a clean loud-bound."""
    import warnings

    from kubernetriks_tpu.telemetry.observatory import SaturationWarning

    n_waves = 48
    workload = wave_workload(n_waves)
    # Seed chosen so the crash chain actually fires at this shape (one
    # base node, ~9400 s horizon): seed 3 samples five crash/recover
    # cycles spread across the run; several nearby seeds sample none.
    fault_suffix = """
fault_injection:
  enabled: true
  seed: 3
  node:
    mttf: 2400.0
    mttr: 120.0
"""
    config_suffix = fault_suffix
    # Reserve 4 (multiplier 2 over the 2-quota): peak live occupancy is
    # 2, so the watchdog has nothing to say while cumulative churn
    # (~64 allocations) overruns the static reserve ~16x.
    kwargs = dict(
        reclaim=True,
        ca_slot_multiplier=2,
        pod_window=32,
        superspan=True,
        stream=True,
        telemetry=True,
        watchdog=True,
        telemetry_ring=64,
    )
    config, batched = _build_batched(workload, config_suffix, **kwargs)
    scalar = _scalar(config, workload)

    horizon = 10.0 + n_waves * 200.0
    ckpt_at = 10.0 + (n_waves // 2) * 200.0
    caught = []
    slabs_seen = []
    traj_scalar, traj_batched = [], []
    # Off-lattice samples — see test_reclaim_churn_past_reserve_matches_scalar.
    for t in np.arange(15.003, horizon, 10.0):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            scalar.step_until_time(float(t))
            batched.step_until_time(float(t))
        caught.extend(
            x for x in w if issubclass(x.category, SaturationWarning)
        )
        traj_scalar.append(scalar.api_server.node_count())
        traj_batched.append(batched.node_count_at(float(t)))
        if (int(t) - 15) % 500 == 0:
            slabs_seen.append(
                (batched.pod_window, batched._sample_resources()["slabs"])
            )

    assert traj_batched == traj_scalar, (
        "endurance trajectory diverged from the scalar oracle:\n"
        f"scalar  {traj_scalar}\nbatched {traj_batched}"
    )
    assert max(traj_scalar) >= 3
    assert int(np.asarray(batched.state.metrics.node_crashes).sum()) > 0, (
        "chaos never fired; the endurance gate is vacuous"
    )
    total = int(np.asarray(batched.state.auto.ca_total).sum())
    reserve = batched._reserve_capacities["ca_reserve"][0]
    assert total >= 3 * reserve, (total, reserve)
    assert int(batched.ca_slots_reclaimed().sum()) >= total - reserve
    # The hard gate is the RESERVE trajectory (the reclaim observable);
    # the end-of-trace headroom note and host-speed pipeline verdicts
    # (feeder stalls) are not reclaim regressions.
    reserve_verdicts = [
        str(x.message) for x in caught if "reserve" in str(x.message)
    ]
    assert reserve_verdicts == []
    # Flat slab watermarks per stage geometry (a pod-window growth is a
    # step, not a trend).
    by_geometry: dict = {}
    for pw, slabs in slabs_seen:
        by_geometry.setdefault(pw, []).append(slabs)
    for pw, rows in by_geometry.items():
        for later in rows[1:]:
            assert later == rows[0], (pw, later, rows[0])
    batched.check_autoscaler_bounds()

    # Checkpoint/restore roundtrip against the finished run: restore at
    # the midpoint and replay to the horizon — bit-identical end state.
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        return
    import tempfile

    from kubernetriks_tpu.batched.state import compare_states

    _, replay = _build_batched(workload, config_suffix, **kwargs)
    with tempfile.TemporaryDirectory() as td:
        path = td + "/ckpt"
        replay.step_until_time(ckpt_at)
        replay.save_checkpoint(path)
        _, resumed = _build_batched(workload, config_suffix, **kwargs)
        resumed.load_checkpoint(path)
        for sim in (replay, resumed):
            sim.step_until_time(horizon - 5.0)
        assert compare_states(replay.state, resumed.state) == []
        replay.close()
        resumed.close()
    batched.close()
