"""Multi-host placement/readout helpers: single-process degenerate semantics
on the suite's 8-device virtual mesh, plus a REAL two-process
jax.distributed harness (test_two_process_cross_process_branches) that
executes the cross-process branches of put_global/to_host — gloo CPU
collectives standing in for DCN — and steps a BatchedSimulation SPMD on the
cross-process mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubernetriks_tpu.parallel.multihost import (
    global_mesh,
    is_cross_process,
    put_global,
    to_host,
)


def test_initialize_from_env_is_noop_without_coordinator():
    """Unconditional initialize_from_env on a plain single-process run must
    return False instead of raising — checked in a fresh interpreter because
    jax.distributed.initialize only works before the backend starts."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        f"import sys; sys.path.insert(0, {root!r});\n"
        "from kubernetriks_tpu.parallel.multihost import initialize_from_env\n"
        "assert initialize_from_env() is False\n"
    )
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX_COORD")}
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run([sys.executable, "-c", code], env=env, check=True, timeout=120)


@pytest.mark.xfail(
    reason=(
        "installed jaxlib 0.4.x CPU backend cannot run cross-process "
        "computations (multihost_utils.process_allgather -> "
        "'Multiprocess computations aren't implemented on the CPU "
        "backend') — the worker's to_host allgather dies inside jax, not "
        "in framework code. Passes on real multi-host backends / newer "
        "jaxlib; see docs/DESIGN.md §'Known suite xfails'."
    ),
    strict=False,
)
def test_two_process_cross_process_branches():
    """Two jax.distributed CPU processes (4 virtual devices each, one
    8-device world): put_global assembles global arrays from per-process
    shards, to_host allgathers non-addressable arrays, and the engine steps
    on the cross-process mesh end to end (tests/multihost_worker.py)."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multihost_worker.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"ROUNDTRIP_OK {i}" in out
        assert f"ENGINE_OK {i}" in out
        assert f"SLIDING_OK {i}" in out
    # Both processes computed identical global metrics, and the sliding
    # window grew/slid identically on both.
    for tag in ("ENGINE_OK", "SLIDING_OK"):
        l0 = [l for l in outs[0].splitlines() if l.startswith(tag)][0].split()[2:]
        l1 = [l for l in outs[1].splitlines() if l.startswith(tag)][0].split()[2:]
        assert l0 == l1, (tag, l0, l1)


def test_put_global_matches_device_put():
    mesh = Mesh(np.array(jax.devices()[:8]), ("clusters",))
    tree = {
        "a": jnp.arange(32, dtype=jnp.int32).reshape(8, 4),
        "b": jnp.ones((16, 2, 3), jnp.float32),
    }
    shardings = {
        "a": NamedSharding(mesh, PartitionSpec("clusters", None)),
        "b": NamedSharding(mesh, PartitionSpec("clusters", None, None)),
    }
    got = put_global(tree, shardings)
    want = jax.device_put(tree, shardings)
    for k in tree:
        assert got[k].sharding == want[k].sharding
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(want[k]))


def test_to_host_and_cross_process_detection():
    mesh = global_mesh()
    assert not is_cross_process(mesh)  # single process in tests
    x = jax.device_put(
        jnp.arange(16, dtype=jnp.float32),
        NamedSharding(mesh, PartitionSpec("clusters")),
    )
    np.testing.assert_array_equal(to_host(x), np.arange(16, dtype=np.float32))


def test_engine_on_global_mesh_reads_metrics():
    """BatchedSimulation on the all-device mesh steps and reduces metrics
    through the multihost readout path."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: mh\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5, horizon=60.0, seed=2, cpu=2000,
        ram=4 * 1024**3, duration_range=(10.0, 30.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=16,
        max_pods_per_cycle=8,
        mesh=global_mesh(),
    )
    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["processed_nodes"] == 4 * 16
    assert counters["scheduling_decisions"] > 0
