"""RAM quantization semantics of the batched path (state.py: requests CEIL
to RAM_UNIT, capacities FLOOR), tested on deliberately UNALIGNED byte values.
The guarantee is one-sided: the batched path never overcommits a node
relative to the byte-exact scalar oracle; in exchange it may conservatively
park a pod whose byte-exact remainder would have just fit. Aligned values
(every other test) quantize exactly and the paths agree bit-for-bit."""

import numpy as np

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import PHASE_RUNNING, PHASE_UNSCHEDULABLE
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

MiB = 1024 * 1024
KiB = 1024


def _cluster(cap_ram: int) -> str:
    return f"""
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {{name: node_00}}
        status: {{capacity: {{cpu: 64000, ram: {cap_ram}}}}}
"""


def _pod(name: str, ram: int, ts: float) -> str:
    return f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata: {{name: {name}}}
        spec:
          resources:
            requests: {{cpu: 1000, ram: {ram}}}
            limits: {{cpu: 1000, ram: {ram}}}
          running_duration: 50.0
"""


def _run_both(cap_ram, pod_rams):
    config = default_test_simulation_config()
    cluster = _cluster(cap_ram)
    workload = "events:" + "".join(
        _pod(f"pod_{i:02d}", ram, 10.0 + i) for i, ram in enumerate(pod_rams)
    )
    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(cluster),
        GenericWorkloadTrace.from_yaml(workload),
    )
    scalar.step_until_time(40.0)
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(cluster).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    batched.step_until_time(40.0)
    return scalar, batched


def test_no_overcommit_on_unaligned_bytes():
    """Two pods whose byte sum exceeds capacity by one byte: NEITHER path runs
    both concurrently (the quantized path must not manufacture capacity)."""
    cap = 4096 * MiB
    scalar, batched = _run_both(cap, [2048 * MiB, 2048 * MiB + 1])
    # Scalar: second pod byte-exactly exceeds the remainder.
    assert "pod_01" in scalar.persistent_storage.unscheduled_pods_cache
    view = batched.pod_view(0)
    assert view["pod_00"]["phase"] == PHASE_RUNNING
    assert view["pod_01"]["phase"] == PHASE_UNSCHEDULABLE


def test_conservative_park_on_sub_unit_remainder():
    """The documented one-sided deviation: capacity 4096 MiB + 512 KiB with
    two requests of 2048 MiB + 256 KiB fits byte-exactly (scalar runs both)
    but not in MiB quanta (ceil 2049 + 2049 > floor 4096) — the batched path
    parks the second pod instead of overcommitting."""
    cap = 4096 * MiB + 512 * KiB
    req = 2048 * MiB + 256 * KiB
    scalar, batched = _run_both(cap, [req, req])
    assert "pod_01" not in scalar.persistent_storage.unscheduled_pods_cache
    assert scalar.persistent_storage.get_pod("pod_01").status.assigned_node
    view = batched.pod_view(0)
    assert view["pod_00"]["phase"] == PHASE_RUNNING
    assert view["pod_01"]["phase"] == PHASE_UNSCHEDULABLE

    # The batched node books exactly the quantized request, no more.
    used_units = int(
        np.asarray(batched.state.nodes.cap_ram[0, 0])
        - np.asarray(batched.state.nodes.alloc_ram[0, 0])
    )
    assert used_units == 2049  # ceil((2048 MiB + 256 KiB) / MiB)
