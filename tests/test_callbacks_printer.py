"""Run-loop callbacks (incl. the deadline variant for long-running services)
and the metrics printer's two output formats — the last user-visible surfaces
without direct tests (reference: src/simulation_callbacks.rs:8-129,
src/metrics/printer.rs:27-164)."""

import json

from kubernetriks_tpu.metrics.printer import (
    metrics_as_pretty_table,
    print_metrics,
)
from kubernetriks_tpu.sim.callbacks import (
    RunUntilAllPodsAreFinishedAndLongRunningPodsExceedDeadlineCallbacks,
    RunUntilAllPodsAreFinishedCallbacks,
)
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CLUSTER_YAML = """
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""


def _pod(name, duration, ts):
    duration_line = (
        f"running_duration: {duration}" if duration is not None else ""
    )
    return f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata: {{name: {name}}}
        spec:
          resources:
            requests: {{cpu: 1000, ram: 1073741824}}
            limits: {{cpu: 1000, ram: 1073741824}}
          {duration_line}
"""


def _sim(workload_yaml):
    sim = KubernetriksSimulation(default_test_simulation_config())
    sim.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_YAML),
        GenericWorkloadTrace.from_yaml(workload_yaml),
    )
    return sim


def test_run_until_finished_stops_after_all_pods(capsys):
    sim = _sim("events:" + _pod("pod_0", 50.0, 10) + _pod("pod_1", 80.0, 12))
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 2
    # It stopped at the first 1000-multiple check after the last finish.
    assert sim.sim.time() <= 2000.0


SERVICE_GROUP_YAML = """
- timestamp: 12
  event_type:
    !CreatePodGroup
      pod_group:
        name: svc
        initial_pod_count: 1
        max_pod_count: 3
        pod_template:
          metadata:
            name: svc
          spec:
            resources:
              requests: {cpu: 1000, ram: 1073741824}
              limits: {cpu: 1000, ram: 1073741824}
        target_resources_usage:
          cpu_utilization: 0.5
        resources_usage_model_config:
          cpu_config:
            model_name: constant
            config: "usage: 0.3"
"""


def test_deadline_callback_keeps_services_running_until_deadline(capsys):
    # One finite trace pod (counted in total_pods_in_trace) + a pod-group
    # service (group expansions are NOT counted — reference
    # simulator.rs:244-253 counts only CreatePodRequest trace events — which
    # is what lets the short-pods check pass while services keep running).
    sim = _sim("events:" + _pod("pod_0", 50.0, 10) + SERVICE_GROUP_YAML)
    sim.run_with_callbacks(
        RunUntilAllPodsAreFinishedAndLongRunningPodsExceedDeadlineCallbacks(
            deadline_time=5000.0
        )
    )
    # The finite pod finished; the service replica is still running at the
    # deadline (the reference's self-noted instant-termination bug must not
    # occur: the run must reach the deadline, not stop at the first check).
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 1
    assert sim.sim.time() >= 5000.0
    running = [
        name
        for name, pod in sim.persistent_storage.storage_data.pods.items()
        if name.startswith("svc") and pod.status.assigned_node
    ]
    assert running, "service replica should still be placed at the deadline"


def test_printer_json_and_table_formats(tmp_path):
    sim = _sim("events:" + _pod("pod_0", 50.0, 10))
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    table = metrics_as_pretty_table(sim.metrics_collector)
    assert "Metric" in table and "Pod queue time" in table and "|" in table

    from kubernetriks_tpu.config import MetricsPrinterConfig

    out_file = tmp_path / "metrics.json"
    print_metrics(
        sim.metrics_collector,
        MetricsPrinterConfig(format="JSON", output_file=str(out_file)),
    )
    data = json.loads(out_file.read_text())
    assert '"pods_succeeded": 1' in json.dumps(data)
