"""Smoke for scripts/bench_mesh.py: the one-command mesh benchmark runs
end to end on the suite's 8-device virtual CPU mesh and reports a sane
JSON record (the runnable form of the README's v5e-8 projection — see
bench_mesh.py docstring)."""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
)

import bench_mesh


def test_bench_mesh_smoke_runs_on_virtual_mesh():
    result = bench_mesh.run_mesh(
        8, clusters_per_device=2, n_nodes=8,
        horizon=200.0, warm_until=50.0, chunk=50.0,
    )
    assert result["devices"] == 8
    assert result["platform"] == "cpu"
    assert result["decisions"] > 0
    assert result["value"] > 0
    assert "8-device mesh, 16x8-node clusters" in result["metric"]
