"""Smoke for scripts/bench_mesh.py: the one-command mesh benchmark runs
end to end on the suite's 8-device virtual CPU mesh and reports a sane
JSON record (the runnable form of the README's v5e-8 projection — see
bench_mesh.py docstring)."""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
)

import pytest

import bench_mesh


@pytest.mark.slow
def test_bench_mesh_composed_smoke_streams_on_virtual_mesh():
    """--composed --smoke: the composed + chaos flagship shard_mapped
    over the 8-device virtual mesh with the STREAMING feeder staging
    every slab — the dry-run form of the MULTICHIP_r06 protocol (ISSUE
    10). Slow: the sharded composed superspan program is a heavy CPU
    compile; CI runs the same line as its own step and uploads the JSON
    artifact."""
    result = bench_mesh.run_mesh_composed(
        8, clusters_per_device=2, n_nodes=8, smoke=True
    )
    assert result["devices"] == 8
    assert result["platform"] == "cpu"
    assert result["measured"] is True
    assert result["value"] > 0
    assert result["spans"]["n"] >= 5
    budget = result["slide_budget"]
    assert budget["streaming_ring_bound_bytes"] > 0
    assert budget["budget_bytes"] == 2 << 30
    tel = result["telemetry"]
    assert tel["dispatch_stats"]["superspans"] > 0
    assert tel["dispatch_stats"]["feeder_slabs_produced"] > 0
    assert set(tel["feeder"]["stalls"]) == {
        "feeder_not_ready", "upload_wait",
    }


def test_bench_mesh_smoke_runs_on_virtual_mesh():
    result = bench_mesh.run_mesh(
        8, clusters_per_device=2, n_nodes=8,
        horizon=200.0, warm_until=50.0, chunk=50.0,
    )
    assert result["devices"] == 8
    assert result["platform"] == "cpu"
    assert result["decisions"] > 0
    assert result["value"] > 0
    assert "8-device mesh, 16x8-node clusters" in result["metric"]
