"""PR 20 autotuner gates (kubernetriks_tpu/tune/).

- Search: the staged coordinate descent is deterministic (two fresh runs
  produce identical candidate lists and the same winner), the pinned
  fake backend's winner is the bonus-table optimum, seeds are always
  measured, and budget + resume compose: a budget-stopped partial
  profile resumed with its own candidates reaches the unbudgeted run's
  chosen config with every prior measurement reused.
- Profile: save/load roundtrip preserves the document; unknown knobs and
  illegal values raise at LOAD, naming the field; explicit
  backend/geometry mismatches raise GeometryMismatch naming the field,
  auto-resolved ones warn (RuntimeWarning) and keep the statics.
- Build seam: an engine built from a profile FILE resolves bit-for-bit
  the statics a hand-kwargs build resolves (engine.tuning_statics), and
  STEPPING both produces bit-identical final state (compare_states) with
  EQUAL dispatch_stats — the profile changes how statics are sourced,
  never what runs. KTPU_TUNED_PROFILE: a path is strict (missing file
  raises), auto resolves artifacts/tuned/ by backend + lane count (no
  match = hand-picked statics), and a knob's own env flag outranks the
  profile entry.
- Slow lane: the REAL BenchMeasurementBackend sweep (bench.run_tune) on
  the composed smoke shape — chosen matches or beats the hand-picked
  all-on seed, zero post-warm-up recompiles on every candidate, and the
  whole grid held final-state bit-identity (asserted inside measure()).
"""

import json
import os
import sys

import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)
from kubernetriks_tpu.tune import (
    FakeMeasurementBackend,
    GeometryMismatch,
    KNOBS,
    TunedProfile,
    knob_by_name,
    load_profile,
    profile_path,
    resolve_build_profile,
    save_profile,
    staged_coordinate_descent,
    validate_statics,
)
from kubernetriks_tpu.tune.knobs import default_statics
from kubernetriks_tpu.tune.search import profile_doc

BONUSES = {"lane_major": {True: 5.0}, "window_razor": {True: 3.0}}


def _sweep(**kwargs):
    return staged_coordinate_descent(FakeMeasurementBackend(BONUSES), **kwargs)


# ---------------------------------------------------------------- search


def test_fake_sweep_pins_the_bonus_optimum():
    res = _sweep()
    assert res.chosen["lane_major"] is True
    assert res.chosen["window_razor"] is True
    assert res.objective == pytest.approx(92.0)
    assert res.baseline["statics"] == default_statics()
    assert res.baseline["objective"] == pytest.approx(100.0)
    assert res.complete is True
    assert res.measured == len(res.candidates)
    assert res.reused == 0


def test_fake_sweep_is_deterministic():
    a, b = _sweep(), _sweep()
    assert a.chosen == b.chosen
    assert a.candidates == b.candidates  # full records, visit order


def test_seed_configs_always_measured_and_can_win():
    # A seed strictly better than anything the bonus table rewards the
    # descent into: the argmin-over-everything rule must pick it.
    be = FakeMeasurementBackend(
        {"superspan_k": {32: 50.0}, "lane_major": {True: 5.0}}
    )
    seed = dict(default_statics(), superspan=True, superspan_k=32)
    res = staged_coordinate_descent(be, seed_configs=[seed])
    assert res.candidates[1]["statics"] == seed
    # The descent never flips superspan on by itself (no bonus on the
    # knob, ties keep the current value), so superspan_k stays inactive
    # on the descent path — ONLY the seed reaches the optimum. This is
    # exactly why run_tune seeds the hand-picked all-on config.
    assert res.chosen == seed


def test_budget_then_resume_reaches_the_unbudgeted_chosen():
    full = _sweep()
    partial = _sweep(budget=3)
    assert partial.complete is False
    assert partial.measured == 3
    assert len(partial.candidates) == 3
    resumed = _sweep(resume_candidates=partial.candidates)
    assert resumed.reused == 3
    assert resumed.complete is True
    assert resumed.chosen == full.chosen
    assert resumed.objective == full.objective


def test_zero_budget_raises_loudly():
    with pytest.raises(ValueError, match="did not cover even the baseline"):
        _sweep(budget=0)


# --------------------------------------------------------------- profile


def _doc(statics=None, backend="cpu", n_clusters=2, n_nodes=4):
    res = _sweep()
    doc = profile_doc(
        res, backend=backend, n_clusters=n_clusters, n_nodes=n_nodes
    )
    if statics is not None:
        doc["statics"] = statics
    return doc


def test_profile_roundtrips_and_names_are_the_key(tmp_path):
    doc = _doc()
    path = profile_path("cpu", 2, 4, root=str(tmp_path))
    assert path == os.path.join(str(tmp_path), "cpu_2x4.json")
    save_profile(doc, path)
    prof = load_profile(path)
    assert prof.backend == "cpu"
    assert (prof.n_clusters, prof.n_nodes) == (2, 4)
    assert prof.statics == doc["statics"]
    assert prof.doc["candidates"] == doc["candidates"]
    assert prof.explicit is True


def test_unknown_knob_raises_at_load_naming_the_field(tmp_path):
    doc = _doc(statics={"bogus_knob": 1})
    path = str(tmp_path / "p.json")
    with pytest.raises(ValueError, match="bogus_knob"):
        save_profile(doc, path)
    with open(path, "w") as fh:  # write it raw to test the LOAD side
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="bogus_knob"):
        load_profile(path)


def test_illegal_value_raises_naming_the_knob(tmp_path):
    doc = _doc(statics=dict(default_statics(), superspan_k=7))
    path = str(tmp_path / "p.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="superspan_k"):
        load_profile(path)
    with pytest.raises(ValueError, match="superspan_k"):
        validate_statics({"superspan_k": 7})
    with pytest.raises(ValueError, match="no_such_knob"):
        knob_by_name("no_such_knob")


def test_explicit_geometry_mismatch_raises_naming_the_field(tmp_path):
    path = str(tmp_path / "p.json")
    save_profile(_doc(), path)
    prof = load_profile(path)  # explicit
    with pytest.raises(GeometryMismatch, match="geometry.n_clusters"):
        prof.check_geometry(n_clusters=3)
    with pytest.raises(GeometryMismatch, match="backend"):
        prof.check_geometry(backend="tpu")
    with pytest.raises(GeometryMismatch, match="geometry.n_nodes"):
        prof.check_geometry(n_nodes=5)
    # Matching geometry is silent.
    prof.check_geometry(backend="cpu", n_clusters=2, n_nodes=4)


def test_auto_geometry_mismatch_warns_and_keeps_statics(tmp_path):
    path = str(tmp_path / "p.json")
    save_profile(_doc(), path)
    prof = load_profile(path, explicit=False)
    with pytest.warns(RuntimeWarning, match="geometry.n_nodes"):
        prof.check_geometry(n_nodes=5)
    assert prof.statics  # still usable after the warning


# ------------------------------------------------------------ build seam


TINY_YAML = "sim_name: tune\nseed: 1\nscheduling_cycle_interval: 10.0"


@pytest.fixture(scope="module")
def tiny_traces():
    config = SimulationConfig.from_yaml(TINY_YAML)
    cluster = UniformClusterTrace(4, cpu=64000, ram=128 * 1024**3)
    wl = PoissonWorkloadTrace(
        rate_per_second=0.2,
        horizon=200.0,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 90.0),
        name_prefix="p",
    )
    return (
        config,
        cluster.convert_to_simulator_events(),
        wl.convert_to_simulator_events(),
    )


def _build(tiny_traces, **kwargs):
    config, cev, wev = tiny_traces
    return build_batched_from_traces(
        config, cev, wev, n_clusters=2, use_pallas=False,
        fast_forward=False, **kwargs,
    )


@pytest.fixture(scope="module")
def tiny_profile_doc(tiny_traces):
    """A profile whose geometry matches the tiny build (cpu, C=2, N=4)
    and whose chosen statics flip lane_major + window_razor on."""
    sim = _build(tiny_traces, tuned_profile=False)
    n_nodes = sim.n_nodes
    sim.close()
    res = _sweep()
    import jax

    return profile_doc(
        res, backend=jax.default_backend(), n_clusters=2, n_nodes=n_nodes
    )


def test_profile_build_matches_hand_passed_statics(
    tiny_traces, tiny_profile_doc, tmp_path
):
    """The tentpole contract: a profile-sourced build IS the hand-kwargs
    build — resolved statics equal, and after stepping, final state
    bit-identical (compare_states) with dispatch_stats EQUAL (same
    statics -> same programs -> same host dispatch pattern)."""
    path = str(tmp_path / "tiny.json")
    save_profile(tiny_profile_doc, path)
    sim_prof = _build(tiny_traces, tuned_profile=path)
    sim_hand = _build(
        tiny_traces, tuned_profile=False, **tiny_profile_doc["statics"]
    )
    assert sim_prof.tuning_statics() == sim_hand.tuning_statics()
    assert sim_prof.lane_major is True and sim_prof.window_razor is True
    assert sim_prof.tuned_profile is not None
    assert sim_prof.tuned_profile.source == path
    assert sim_hand.tuned_profile is None
    sim_prof.step_until_time(150.0)
    sim_hand.step_until_time(150.0)
    bad = compare_states(sim_hand.state, sim_prof.state)
    assert not bad, f"profile-sourced build diverged: {bad}"
    assert sim_prof.dispatch_stats == sim_hand.dispatch_stats


def test_build_without_profile_is_untouched(tiny_traces):
    """No arg, no flag -> no profile consulted: the pre-tuner defaults
    resolve (CPU platform: everything off, descatter on)."""
    sim = _build(tiny_traces)
    assert sim.tuned_profile is None
    assert sim.tuning_statics() == default_statics()
    sim.close()


def test_env_flag_seam(tiny_traces, tiny_profile_doc, tmp_path, monkeypatch):
    path = str(tmp_path / "tiny.json")
    save_profile(tiny_profile_doc, path)
    # KTPU_TUNED_PROFILE=<path>: strict — applies the profile...
    monkeypatch.setenv("KTPU_TUNED_PROFILE", path)
    sim = _build(tiny_traces)
    assert sim.tuned_profile is not None and sim.lane_major is True
    sim.close()
    # ...and a knob's own env flag OUTRANKS the profile entry.
    monkeypatch.setenv("KTPU_LANE_MAJOR", "0")
    sim = _build(tiny_traces)
    assert sim.lane_major is False and sim.window_razor is True
    sim.close()
    monkeypatch.delenv("KTPU_LANE_MAJOR")
    # A flag naming a MISSING path raises (never silently untuned).
    monkeypatch.setenv("KTPU_TUNED_PROFILE", str(tmp_path / "gone.json"))
    with pytest.raises(FileNotFoundError):
        _build(tiny_traces)
    # An explicit build arg outranks the (broken) flag entirely.
    sim = _build(tiny_traces, tuned_profile=False)
    assert sim.tuned_profile is None
    sim.close()


def test_env_flag_auto_resolution(
    tiny_traces, tiny_profile_doc, tmp_path, monkeypatch
):
    import jax

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("KTPU_TUNED_PROFILE", "auto")
    # No artifacts/tuned/ anywhere: auto quietly resolves to no profile.
    sim = _build(tiny_traces)
    assert sim.tuned_profile is None
    sim.close()
    # A profile under artifacts/tuned/ keyed by backend + lane count is
    # picked up; its explicit flag is False (auto provenance).
    backend = jax.default_backend()
    path = profile_path(backend, 2, tiny_profile_doc["geometry"]["n_nodes"])
    save_profile(tiny_profile_doc, path)
    sim = _build(tiny_traces)
    assert sim.tuned_profile is not None
    assert sim.tuned_profile.explicit is False
    assert sim.lane_major is True
    sim.close()
    # An auto profile whose recorded N drifts from the build only WARNS
    # (post-build check) and the statics stay applied.
    os.remove(path)
    doc = dict(tiny_profile_doc, geometry={"n_clusters": 2, "n_nodes": 999})
    save_profile(doc, profile_path(backend, 2, 999))
    with pytest.warns(RuntimeWarning, match="geometry.n_nodes"):
        sim = _build(tiny_traces)
    assert sim.lane_major is True
    sim.close()


def test_resolve_build_profile_rejects_junk():
    with pytest.raises(TypeError, match="tuned_profile"):
        resolve_build_profile(42, backend="cpu", n_clusters=2)
    assert resolve_build_profile(False, backend="cpu", n_clusters=2) is None


def test_registry_covers_every_engine_static():
    """Every closed-domain knob the registry declares is an engine build
    kwarg AND appears in engine.tuning_statics — a renamed engine kwarg
    breaks here, not silently in a stale profile."""
    names = {k.name for k in KNOBS if k.values is not None}
    assert names == set(default_statics())
    import inspect

    from kubernetriks_tpu.batched.engine import BatchedSimulation

    params = set(inspect.signature(BatchedSimulation.__init__).parameters)
    assert names <= params, names - params


# ------------------------------------------------------------- slow lane


@pytest.mark.slow
def test_real_sweep_matches_or_beats_the_hand_picked_all_on(tmp_path):
    """The acceptance gate: bench.run_tune's REAL measurement sweep on
    the composed smoke shape. The hand-picked BENCH_r07 all-on config is
    seeded, so chosen <= all-on by construction — asserted anyway, along
    with zero post-warm-up recompiles on every candidate (the sentinel
    was armed per candidate inside measure(), which also enforced
    whole-grid final-state bit-identity + committed-decision equality)
    and the persisted profile's build roundtrip."""
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench

    rec = bench.run_tune(json_path=str(tmp_path / "real.json"))
    tune = rec["tune"]
    assert tune["measurement"] == "bench"
    assert tune["complete"] is True
    assert tune["roundtrip_build_identical"] is True
    assert tune["objective"] <= tune["all_on_objective"]
    assert tune["objective"] <= tune["baseline_objective"] or (
        tune["ab_vs_default_frac"] <= 1.0
    )
    doc = json.loads((tmp_path / "real.json").read_text())
    assert doc["statics"] == tune["chosen"]
    fingerprints = {c["fingerprint"] for c in doc["candidates"]}
    assert len(fingerprints) == 1, (
        "grid candidates disagree on the semantic fingerprint"
    )
    for cand in doc["candidates"]:
        assert cand["recompiles_after_warmup"] == 0
        assert cand["spans"]["n"] >= 5
        assert cand["spans"]["min"] > 0
