"""Chaos engine (kubernetriks_tpu/chaos.py): counter-PRNG parity, fault
compiler semantics, and the headline acceptance property — scalar-vs-batched
equivalence on fault-enabled random traces with identical fault metrics
(downtime, interruptions, restarts, permanently-failed), bit-identical
batched state across donation on/off and fast-forward on/off, and
seed-determinism (same seed -> bit-identical, different seed -> different).
"""

import numpy as np
import pytest

from kubernetriks_tpu import chaos
from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import (
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
    compare_states,
    tree_copy,
)
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.core.events import CreateNodeRequest, RemoveNodeRequest
from kubernetriks_tpu.core.types import Node, PodConditionType
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from test_random_equivalence import END_TIME, generate_traces

FAULT_YAML = """
fault_injection:
  enabled: true
  node:
    mttf: 2500.0
    mttr: 120.0
  pod:
    fail_prob: 0.12
    backoff_base: 10.0
    backoff_cap: 300.0
    restart_limit: 3
"""

GROUP_FAULT_YAML = """
fault_injection:
  enabled: true
  node:
    mttf: 4000.0
    mttr: 150.0
  pod:
    fail_prob: 0.08
    restart_limit: 2
  failure_groups:
  - members: [node_000, node_001, node_002, node_003]
    mttf: 3000.0
    mttr: 200.0
"""

# Backoff shorter than the failure-chain delay (0.21s with the default test
# delays): every retry is floored at the chain arrival — the regime where a
# naive fail+backoff requeue would beat the failure notification to the
# queue and desync the paths by a whole scheduling cycle.
SHORT_BACKOFF_YAML = """
fault_injection:
  enabled: true
  node:
    mttf: 2500.0
    mttr: 120.0
  pod:
    fail_prob: 0.12
    backoff_base: 0.05
    backoff_cap: 0.1
    restart_limit: 3
"""


# --- counter PRNG ------------------------------------------------------------


def test_threefry_numpy_jnp_parity():
    """The scalar oracle (numpy) and the device draw (jnp) must produce
    bit-identical uniforms for the same counters."""
    import jax.numpy as jnp

    cluster = np.arange(64, dtype=np.uint32) % 7
    slot = np.arange(64, dtype=np.uint32) * 13
    attempt = np.arange(64, dtype=np.uint32) % 5
    a0, a1 = chaos.pod_attempt_uniforms(42, cluster, slot, attempt, xp=np)
    b0, b1 = chaos.pod_attempt_uniforms(
        42,
        jnp.asarray(cluster),
        jnp.asarray(slot),
        jnp.asarray(attempt),
        xp=jnp,
    )
    np.testing.assert_array_equal(a0, np.asarray(b0))
    np.testing.assert_array_equal(a1, np.asarray(b1))
    # Uniforms live in [0, 1) and are not degenerate.
    assert a0.min() >= 0.0 and a0.max() < 1.0
    assert len(np.unique(a0)) > 32


def test_counter_prng_is_order_independent():
    """A draw depends only on its counter tuple — evaluating in any order or
    batch shape yields the same value (the property that lets both paths
    draw lazily without a synchronized stream)."""
    single = chaos.pod_attempt_uniforms(
        7, np.uint32(3), np.uint32(17), np.uint32(2)
    )
    batch = chaos.pod_attempt_uniforms(
        7,
        np.asarray([0, 3, 9], np.uint32),
        np.asarray([17, 17, 17], np.uint32),
        np.asarray([2, 2, 2], np.uint32),
    )
    assert float(single[0]) == float(batch[0][1])
    assert float(single[1]) == float(batch[1][1])


# --- fault compiler ----------------------------------------------------------


def _fault_cfg(yaml_suffix=FAULT_YAML):
    return SimulationConfig.from_yaml(
        "sim_name: t\nseed: 5\n" + yaml_suffix
    ).fault_injection


def test_inject_node_faults_chain_rules():
    GiB = 1024**3
    events = [
        (0.0, CreateNodeRequest(node=Node.new("n_a", 8000, 16 * GiB))),
        (5.0, CreateNodeRequest(node=Node.new("n_b", 8000, 16 * GiB))),
        (900.0, RemoveNodeRequest(node_name="n_b")),
    ]
    cfg = _fault_cfg()
    out = chaos.inject_node_faults(events, cfg, 5, 0, 20000.0, 10.0)
    injected = out[len(events):]
    assert injected, "mttf=2500 over a 20000s horizon must produce crashes"
    # Events come in (crash, recover) pairs, time-sorted, with ttr >= the
    # scheduling interval (window-separation clamp).
    crashes = [e for _, e in injected if isinstance(e, RemoveNodeRequest)]
    recovers = [e for _, e in injected if isinstance(e, CreateNodeRequest)]
    assert len(crashes) == len(recovers)
    assert all(e.crashed for e in crashes)
    assert all(e.recovered for e in recovers)
    assert all(e.downtime_s >= 10.0 for e in crashes)
    times = [ts for ts, _ in injected]
    assert times == sorted(times)
    # Every n_b fault pair fits strictly inside its lifetime [5, 900).
    by_node = [
        (ts, e.node_name) for ts, e in injected if isinstance(e, RemoveNodeRequest)
    ]
    for ts, name in by_node:
        if name == "n_b":
            assert 5.0 < ts < 900.0
    # Determinism: same inputs -> identical schedule; different cluster
    # index -> different schedule.
    again = chaos.inject_node_faults(events, cfg, 5, 0, 20000.0, 10.0)
    assert [(ts, type(e).__name__, getattr(e, "node_name", "")) for ts, e in out] == [
        (ts, type(e).__name__, getattr(e, "node_name", "")) for ts, e in again
    ]
    other = chaos.inject_node_faults(events, cfg, 5, 1, 20000.0, 10.0)
    assert [ts for ts, _ in out] != [ts for ts, _ in other]


def test_inject_correlated_group_faults():
    GiB = 1024**3
    events = [
        (0.0, CreateNodeRequest(node=Node.new(f"node_{i:03d}", 8000, 16 * GiB)))
        for i in range(6)
    ]
    cfg = _fault_cfg(GROUP_FAULT_YAML)
    cfg.node.mttf = 0.0  # isolate the group channel
    out = chaos.inject_node_faults(events, cfg, 5, 0, 30000.0, 10.0)
    injected = [(ts, e) for ts, e in out[len(events):]]
    crash_times = {}
    for ts, e in injected:
        if isinstance(e, RemoveNodeRequest):
            crash_times.setdefault(ts, set()).add(e.node_name)
    assert crash_times, "group mttf=3000 over 30000s must fire"
    # Blast radius: every group crash takes ALL four members down together.
    for ts, members in crash_times.items():
        assert members == {"node_000", "node_001", "node_002", "node_003"}, (
            ts,
            members,
        )


def test_overlapping_node_and_group_channels_never_double_crash():
    """The per-node and group chains are sampled independently; a group
    crash landing while a member is already down (or within one interval of
    its transitions) is dropped — never a second remove for a down node
    (which would KeyError at trace compile) or two same-slot transitions in
    one batched window."""
    GiB = 1024**3
    events = [
        (0.0, CreateNodeRequest(node=Node.new(f"n_{i}", 8000 + i * 1000, 16 * GiB)))
        for i in range(3)
    ]
    cfg = _fault_cfg(GROUP_FAULT_YAML)
    cfg.node.mttf, cfg.node.mttr = 500.0, 200.0
    cfg.failure_groups[0].members = ["n_0", "n_1"]
    cfg.failure_groups[0].mttf, cfg.failure_groups[0].mttr = 400.0, 300.0
    interval = 10.0
    for seed in range(6):  # dense chains: overlaps occur at several seeds
        out = chaos.inject_node_faults(events, cfg, seed, 0, 5000.0, interval)
        down = {}
        spans = {}
        for ts, e in out[len(events):]:
            if isinstance(e, RemoveNodeRequest):
                assert e.node_name not in down, (seed, ts, e.node_name)
                down[e.node_name] = ts
            else:
                name = e.node.metadata.name
                spans.setdefault(name, []).append((down.pop(name), ts))
        for name, ss in spans.items():
            ss.sort()
            for (_, end), (start, _) in zip(ss, ss[1:]):
                assert start >= end + interval, (seed, name, end, start)


# --- scalar vs batched equivalence under faults ------------------------------


def _run_scalar(config, seed):
    cluster_trace, workload_trace = generate_traces(seed)
    scalar = KubernetriksSimulation(config)
    scalar.initialize(cluster_trace, workload_trace)
    scalar.step_until_time(END_TIME)
    return scalar


def _build_batched(config, seed, **kwargs):
    cluster_trace, workload_trace = generate_traces(seed)
    return build_batched_from_traces(
        config,
        cluster_trace.convert_to_simulator_events(),
        workload_trace.convert_to_simulator_events(),
        n_clusters=1,
        **kwargs,
    )


@pytest.mark.parametrize(
    "seed,fault_yaml",
    [(101, FAULT_YAML), (202, GROUP_FAULT_YAML), (101, SHORT_BACKOFF_YAML)],
)
def test_fault_enabled_cross_path_equivalence(seed, fault_yaml):
    """The acceptance property: on a fault-enabled random trace the scalar
    and batched paths agree on every terminal counter INCLUDING the fault
    metrics, and pod-for-pod on terminal states."""
    config = default_test_simulation_config(fault_yaml)

    scalar = _run_scalar(config, seed)
    batched = _build_batched(config, seed)
    batched.step_until_time(END_TIME)

    sm = scalar.metrics_collector.accumulated_metrics
    bm = batched.metrics_summary()["counters"]
    assert bm["pods_succeeded"] == sm.pods_succeeded
    assert bm["pods_removed"] == sm.pods_removed
    assert bm["terminated_pods"] == sm.internal.terminated_pods
    # Fault metrics: counters exact, downtime to float tolerance (f32
    # accumulation on device vs f64 on host).
    assert bm["node_crashes"] == sm.node_crashes
    assert bm["node_recoveries"] == sm.node_recoveries
    assert bm["pod_interruptions"] == sm.pod_interruptions
    assert bm["pod_restarts"] == sm.pod_restarts
    assert bm["pods_failed"] == sm.pods_failed
    assert bm["node_downtime_s"] == pytest.approx(sm.node_downtime_s, rel=1e-5)
    # The scenario actually exercises the chaos engine.
    assert sm.node_crashes > 0
    assert sm.pod_restarts > 0
    assert sm.pods_succeeded > 50

    view = batched.pod_view(0)
    succeeded = scalar.persistent_storage.succeeded_pods
    failed = scalar.persistent_storage.failed_pods
    cache = scalar.persistent_storage.unscheduled_pods_cache
    for name, b in view.items():
        if b["phase"] == PHASE_SUCCEEDED:
            pod = succeeded.get(name)
            assert pod is not None, (name, seed)
            assert b["node"] == pod.status.assigned_node, (name, seed)
            scalar_start = pod.get_condition(
                PodConditionType.POD_RUNNING
            ).last_transition_time
            assert b["start_time"] == pytest.approx(scalar_start, abs=5e-6), (
                name,
                seed,
            )
        elif b["phase"] == PHASE_FAILED:
            assert name in failed, (name, seed)
        elif b["phase"] == PHASE_UNSCHEDULABLE:
            assert name in cache, (name, seed)


def test_fault_batched_bitwise_across_donation_and_fast_forward():
    """Donation on/off and fast-forward on/off must produce bit-identical
    final states and fault metrics under faults (the composed-path
    invariants extend to the chaos subsystem)."""
    config = default_test_simulation_config(FAULT_YAML)
    variants = [
        _build_batched(config, 101, donate=False, fast_forward=False),
        _build_batched(config, 101, donate=True, fast_forward=False),
        _build_batched(config, 101, donate=False, fast_forward=True),
    ]
    for sim in variants:
        sim.step_until_time(END_TIME)
    ref = variants[0]
    assert int(np.asarray(ref.state.metrics.node_crashes).sum()) > 0
    for other in variants[1:]:
        bad = compare_states(ref.state, other.state)
        assert bad == [], bad


def test_fault_seed_determinism():
    """Two identically-seeded fault runs are bit-identical; changing only
    the fault seed changes the trajectory."""
    config = default_test_simulation_config(FAULT_YAML)
    a = _build_batched(config, 101)
    b = _build_batched(config, 101)
    a.step_until_time(END_TIME)
    b.step_until_time(END_TIME)
    assert compare_states(a.state, b.state) == []

    config2 = default_test_simulation_config(
        FAULT_YAML.replace("enabled: true", "enabled: true\n  seed: 999")
    )
    c = _build_batched(config2, 101)
    c.step_until_time(END_TIME)
    assert compare_states(a.state, c.state) != []


def test_faults_off_state_is_pristine():
    """With fault_injection absent the fault fields stay inert zeros and
    the engine threads fault_params=None (identical compiled programs)."""
    config = default_test_simulation_config()
    sim = _build_batched(config, 101)
    assert sim.fault_params is None
    sim.step_until_time(END_TIME)
    m = sim.metrics_summary()["counters"]
    assert m["node_crashes"] == 0
    assert m["pod_restarts"] == 0
    assert m["pods_failed"] == 0
    assert m["node_downtime_s"] == 0.0
    assert not np.asarray(sim.state.pods.will_fail).any()
    assert not np.asarray(sim.state.pods.restarts).any()


def test_debug_finite_guard_names_offending_field():
    """KTPU_DEBUG_FINITE guard mode: a clean fault run passes the sweep; an
    injected NaN fails naming the field."""
    config = default_test_simulation_config(FAULT_YAML)
    sim = _build_batched(config, 101)
    sim._debug_finite = True
    sim.step_until_time(2000.0)  # sweeps after every dispatched chunk

    import jax.numpy as jnp

    est = sim.state.metrics.queue_time
    sim.state = sim.state._replace(
        metrics=sim.state.metrics._replace(
            queue_time=est._replace(total=est.total.at[0].set(jnp.nan))
        )
    )
    with pytest.raises(FloatingPointError, match="queue_time"):
        sim._check_finite()


@pytest.mark.parametrize("distribution", ["exponential", "fixed"])
def test_batched_chain_compilation_matches_loop(distribution):
    """inject_node_faults samples its crash/recover chains through the
    VECTORIZED _chains_batched (one threefry block per incarnation index for
    every lifetime at once); every chain must be bit-identical — same
    float64 values, same pair order — to the sequential per-lifetime _chain
    loop it replaced, across finite/infinite lifetimes, horizon cutoffs and
    the interval clamp."""
    rng = np.random.default_rng(42)
    produced = False
    for trial in range(8):
        U = int(rng.integers(1, 30))
        uids = list(range(U))
        t0s = [float(rng.uniform(0.0, 400.0)) for _ in range(U)]
        # Mix never-removed (inf) and trace-removed lifetimes, including
        # some too short to ever crash.
        ends = [
            float(np.inf)
            if rng.random() < 0.3
            else t0 + float(rng.uniform(5.0, 2500.0))
            for t0 in t0s
        ]
        horizon = float(rng.uniform(50.0, 3000.0))
        # Small mttf/mttr exercise the one-interval clamp lanes.
        mttf = float(rng.uniform(2.0, 800.0))
        mttr = float(rng.uniform(1.0, 200.0))
        seed = int(rng.integers(0, 10_000))
        cluster = int(rng.integers(0, 16))
        batched = chaos._chains_batched(
            seed, chaos.STREAM_NODE, cluster, uids, t0s, ends,
            horizon, mttf, mttr, distribution, 10.0,
        )
        loop = [
            chaos._chain(
                seed, chaos.STREAM_NODE, cluster, uid, t0s[i], ends[i],
                horizon, mttf, mttr, distribution, 10.0,
            )
            for i, uid in enumerate(uids)
        ]
        assert batched == loop, trial
        produced = produced or any(len(c) for c in batched)
    # The scenarios above must actually produce chains somewhere, or the
    # parity claim is vacuous.
    assert produced


def test_batched_chain_compilation_empty_inputs():
    assert chaos._chains_batched(
        1, chaos.STREAM_NODE, 0, [], [], [], 100.0, 10.0, 5.0,
        "exponential", 10.0,
    ) == []
