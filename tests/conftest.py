"""Force a deterministic 8-device virtual CPU platform for all tests.

Multi-chip sharding tests run against a virtual CPU mesh
(xla_force_host_platform_device_count) since only one real TPU chip is
available in dev; the driver validates real multi-chip paths separately via
__graft_entry__.dryrun_multichip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize registers the TPU backend at interpreter start and
# pins jax_platforms before conftest runs; override through the config API.
import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running training/benchmark tests"
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables at module boundaries: with the full suite in
    one process, the accumulated compile state eventually segfaults XLA's CPU
    compiler inside a later (unrelated) jit compile — reproducible only with
    ~the whole suite's compile history, gone when any half runs alone. Costs
    some cross-module recompiles; keeps the 170-test process bounded."""
    yield
    jax.clear_caches()
