"""Cluster autoscaler: scale-up on unschedulable pods, scale-down of
underutilized CA nodes (algorithm unit tests + end-to-end)."""

from kubernetriks_tpu.autoscalers.interface import (
    AutoscaleInfo,
    CaNodeGroup,
    ScaleDownInfo,
    ScaleUpInfo,
)
from kubernetriks_tpu.autoscalers.kube_cluster_autoscaler import (
    CLUSTER_AUTOSCALER_ORIGIN_LABEL,
    KubeClusterAutoscaler,
)
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace


def make_groups():
    small = Node.new("small_template", 4000, 8 * 1024**3)
    small.metadata.labels = {
        "origin": CLUSTER_AUTOSCALER_ORIGIN_LABEL,
        "node_group": "small_template",
    }
    big = Node.new("big_template", 64000, 128 * 1024**3)
    big.metadata.labels = {
        "origin": CLUSTER_AUTOSCALER_ORIGIN_LABEL,
        "node_group": "big_template",
    }
    return {
        "big_template": CaNodeGroup(node_template=big, max_count=2),
        "small_template": CaNodeGroup(node_template=small),
    }


def test_scale_up_bin_packs_pods_into_planned_nodes():
    ca = KubeClusterAutoscaler()
    groups = make_groups()
    pods = [Pod.new(f"p{i}", 2000, 1024**3, None) for i in range(4)]
    actions = ca.autoscale(
        AutoscaleInfo(scale_up=ScaleUpInfo(unscheduled_pods=pods)),
        groups,
        max_node_count=10,
    )
    # First pod allocates one big node (sorted group order: big_template first);
    # the triggering pod is NOT packed (reference quirk), so remaining pods
    # first-fit into that node. One node total.
    assert len(actions) == 1
    assert actions[0].node.metadata.name == "big_template_1"
    assert groups["big_template"].current_count == 1
    assert actions[0].node.status.allocatable == actions[0].node.status.capacity


def test_scale_up_respects_group_max_and_global_max():
    ca = KubeClusterAutoscaler()
    groups = make_groups()
    # Huge pods fit only the big template; its max_count is 2.
    pods = [Pod.new(f"p{i}", 64000, 100 * 1024**3, None) for i in range(5)]
    actions = ca.autoscale(
        AutoscaleInfo(scale_up=ScaleUpInfo(unscheduled_pods=pods)),
        groups,
        max_node_count=10,
    )
    assert len(actions) == 2
    assert groups["big_template"].current_count == 2

    # Global cap: reset and bound to 1 node overall.
    groups = make_groups()
    actions = ca.autoscale(
        AutoscaleInfo(scale_up=ScaleUpInfo(unscheduled_pods=pods)),
        groups,
        max_node_count=1,
    )
    assert len(actions) == 1


def test_scale_down_only_underutilized_ca_nodes_with_movable_pods():
    ca = KubeClusterAutoscaler()
    groups = make_groups()
    groups["small_template"].current_count = 2

    # Two CA nodes: one nearly empty (scale-down candidate), one busy.
    idle = groups["small_template"].node_template.copy()
    idle.metadata.name = "small_template_1"
    busy = groups["small_template"].node_template.copy()
    busy.metadata.name = "small_template_2"
    busy.status.allocatable.cpu -= 3500  # 87% cpu utilization

    # A non-CA node with room for the idle node's pod.
    manual = Node.new("manual_node", 64000, 128 * 1024**3)

    pod = Pod.new("pod_on_idle", 100, 1024**2, None)
    idle.status.allocatable.cpu -= 100
    idle.status.allocatable.ram -= 1024**2

    info = ScaleDownInfo(
        nodes=[idle, busy, manual],
        pods_on_autoscaled_nodes={"pod_on_idle": pod},
        assignments={
            "small_template_1": {"pod_on_idle"},
            "small_template_2": set(),
            "manual_node": set(),
        },
    )
    actions = ca.autoscale(AutoscaleInfo(scale_down=info), groups, max_node_count=10)
    assert [a.node_name for a in actions] == ["small_template_1"]
    assert groups["small_template"].current_count == 1


def test_scale_down_blocked_when_pods_cannot_move():
    ca = KubeClusterAutoscaler()
    groups = make_groups()
    groups["small_template"].current_count = 1

    idle = groups["small_template"].node_template.copy()
    idle.metadata.name = "small_template_1"
    pod = Pod.new("stuck_pod", 100, 1024**2, None)
    idle.status.allocatable.cpu -= 100
    # No other node has capacity.
    info = ScaleDownInfo(
        nodes=[idle],
        pods_on_autoscaled_nodes={"stuck_pod": pod},
        assignments={"small_template_1": {"stuck_pod"}},
    )
    actions = ca.autoscale(AutoscaleInfo(scale_down=info), groups, max_node_count=10)
    assert actions == []
    assert groups["small_template"].current_count == 1


CA_CONFIG_SUFFIX = """
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 10
  node_groups:
  - node_template:
      metadata:
        name: autoscaler_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""


def test_end_to_end_scale_up_then_down():
    """Pods arrive with no cluster; CA scales up; after pods finish, CA scales
    the idle nodes back down."""
    config = default_test_simulation_config(CA_CONFIG_SUFFIX)
    sim = KubernetriksSimulation(config)
    workload = "events:" + "".join(
        f"""
- timestamp: {5 + i}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i}
        spec:
          resources:
            requests:
              cpu: 4000
              ram: 8589934592
            limits:
              cpu: 4000
              ram: 8589934592
          running_duration: 50.0
"""
        for i in range(4)
    )
    sim.initialize(
        GenericClusterTrace.from_yaml(""), GenericWorkloadTrace.from_yaml(workload)
    )
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    metrics = sim.metrics_collector.accumulated_metrics
    assert metrics.pods_succeeded == 4
    assert metrics.total_scaled_up_nodes >= 1
    # After success, idle CA nodes get scaled down.
    assert metrics.total_scaled_down_nodes >= 1
    assert sim.api_server.node_count() < metrics.total_scaled_up_nodes + 1
