"""CLI entry point (cli.main): flag parsing, trace XOR validation, both
backends end-to-end from config files on disk, and the gauge-CSV sink — the
user-facing surface of reference main.rs:20-102."""

import csv
import os

import pytest

from kubernetriks_tpu.cli import main

CLUSTER_YAML = """
events:
- timestamp: 5
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""

WORKLOAD_YAML = """
events:
- timestamp: 10
  event_type:
    !CreatePod
      pod:
        metadata: {name: pod_00}
        spec:
          resources:
            requests: {cpu: 2000, ram: 4294967296}
            limits: {cpu: 2000, ram: 4294967296}
          running_duration: 40.0
"""


def _write_config(tmp_path, extra=""):
    (tmp_path / "cluster.yaml").write_text(CLUSTER_YAML)
    (tmp_path / "workload.yaml").write_text(WORKLOAD_YAML)
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"""
sim_name: cli_test
seed: 7
scheduling_cycle_interval: 10.0
as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.010
sched_to_as_network_delay: 0.020
as_to_node_network_delay: 0.150
trace_config:
  generic_trace:
    cluster_trace_path: {tmp_path}/cluster.yaml
    workload_trace_path: {tmp_path}/workload.yaml
{extra}
"""
    )
    return str(cfg)


def test_scalar_backend_runs_from_config(tmp_path, capsys):
    cfg = _write_config(tmp_path)
    assert main(["--config-file", cfg]) == 0
    out = capsys.readouterr().out
    assert '"pods_succeeded": 1' in out


def test_batched_backend_runs_with_gauge_csv(tmp_path, capsys):
    cfg = _write_config(tmp_path)
    gauges = tmp_path / "gauges.csv"
    assert (
        main(
            [
                "--config-file", cfg,
                "--backend", "batched",
                "--clusters", "2",
                "--gauge-csv", str(gauges),
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    assert '"pods_succeeded": 2' in out  # both lockstep clusters
    with open(gauges) as f:
        rows = list(csv.reader(f))
    assert rows[0][0] == "timestamp" and len(rows[0]) == 8
    assert len(rows) > 2


def test_report_table_covers_both_backends(tmp_path, capsys, monkeypatch):
    """--report table renders BOTH backends' metrics through the shared
    path (metrics/render.py) in the same table shape; on the batched
    backend with KTPU_TRACE=1 the telemetry report and the Chrome trace
    ride along."""
    cfg = _write_config(tmp_path)
    assert main(["--config-file", cfg, "--report", "table"]) == 0
    scalar_out = capsys.readouterr().out
    assert "| Metric" in scalar_out and "Pod queue time" in scalar_out

    monkeypatch.setenv("KTPU_TRACE", "1")
    monkeypatch.setenv("KTPU_TRACE_PATH", str(tmp_path / "cli_trace"))
    assert (
        main(
            ["--config-file", cfg, "--backend", "batched",
             "--report", "table"]
        )
        == 0
    )
    batched_out = capsys.readouterr().out
    assert "| Metric" in batched_out and "Pod queue time" in batched_out
    assert "| Phase" in batched_out  # telemetry span table
    assert (tmp_path / "cli_trace.json").exists()

    # --report supersedes a configured metrics_printer: ONE report in the
    # CLI-chosen format, not the config's PrettyTable plus the JSON.
    monkeypatch.delenv("KTPU_TRACE")
    cfg2 = _write_config(
        tmp_path, extra="metrics_printer:\n  format: PrettyTable\n"
    )
    assert main(["--config-file", cfg2, "--report", "json"]) == 0
    out2 = capsys.readouterr().out
    assert out2.count('"pods_succeeded"') == 1
    assert "| Metric" not in out2


def test_trace_config_rejects_both_sources(tmp_path):
    """The reference asserts exactly one of alibaba/generic (main.rs:62-65)."""
    cfg = tmp_path / "bad.yaml"
    cfg.write_text(
        f"""
sim_name: x
seed: 1
trace_config:
  generic_trace:
    cluster_trace_path: {tmp_path}/cluster.yaml
    workload_trace_path: {tmp_path}/workload.yaml
  alibaba_cluster_trace_v2017:
    machine_events_trace_path: m.csv
    batch_task_trace_path: t.csv
    batch_instance_trace_path: i.csv
"""
    )
    (tmp_path / "cluster.yaml").write_text(CLUSTER_YAML)
    (tmp_path / "workload.yaml").write_text(WORKLOAD_YAML)
    with pytest.raises(AssertionError):
        main(["--config-file", str(cfg)])
