"""Device-resident superspan executor (step.run_superspan).

The superspan path runs up to K consecutive slide-spans entirely on device —
window chunks, shift computation, quantization and slide application inside
ONE while_loop, refill columns drawn from a device-resident staging slab —
and must be BIT-IDENTICAL to the ladder path it replaces:

1. Composed flagship run (HPA + CA + sliding pod window), superspan ON vs
   the unfused two-dispatch-slide ladder: every state leaf exact, metrics
   exact, same slide trajectory — fault-free AND with fault_injection
   enabled (the commit-time threefry draws are slot-keyed and
   slide-invariant, so the on-device slides must not perturb them). The
   fault variant runs the non-default "best_fit" compiled scheduler
   profile on ladder+fused AND superspan executors — the chaos-on
   profile bit-identity gate (batched/pipeline.py).
2. The bounded RefillStage path (whole-trace payload over budget): staging
   installs, the double-buffered successor, and the SUPERSPAN_STAGE
   mid-flight exhaustion exit all preserve bit-identity.
3. The SUPERSPAN_GROW exit: a dense stretch with no terminal leading pod
   must grow the window in place, matching the full-resident run.
4. precompile_chunks warms the ONE superspan program instead of the ladder.
"""

import numpy as np
import pytest

import kubernetriks_tpu.batched.engine as engine_mod
from kubernetriks_tpu.batched.state import compare_states, strip_telemetry
from kubernetriks_tpu.test_util import default_test_simulation_config

from test_pod_window_growth import _build as _build_growth
from test_pod_window_growth import _long_running_workload
from test_window_donation_dispatch import _build_composed

FAULT_SUFFIX = """
fault_injection:
  enabled: true
  node:
    mttf: 2500.0
    mttr: 120.0
  pod:
    fail_prob: 0.12
    backoff_base: 10.0
    backoff_cap: 300.0
    restart_limit: 3
"""


def _run(sim, ends=(150.0, 300.0, 450.0)):
    for end in ends:
        sim.step_until_time(end)
    return sim


def _assert_superspan_matches_ladder(ss, ladder):
    # The superspan path really ran (and never silently fell back to the
    # ladder), and the run exercised slides — otherwise parity is vacuous.
    assert ss.dispatch_stats["superspans"] > 0
    assert ss.dispatch_stats["window_chunks"] == 0
    assert ss._pod_base > 0
    assert ladder.dispatch_stats["superspans"] == 0

    assert ss._pod_base == ladder._pod_base
    assert ss.next_window_idx == ladder.next_window_idx
    # strip_telemetry: a flight-recorder-armed ss engine (the fault test
    # below) carries the device ring, the ONE leaf allowed to differ.
    assert compare_states(strip_telemetry(ss.state), ladder.state) == []
    assert ss.metrics_summary() == ladder.metrics_summary()
    if ss.autoscale_statics is not None:
        # The carried windowed name ranks land back in the statics.
        np.testing.assert_array_equal(
            np.asarray(ss.autoscale_statics.pod_name_rank),
            np.asarray(ladder.autoscale_statics.pod_name_rank),
        )


@pytest.mark.slow
def test_superspan_composed_bit_identical():
    """Flagship composition: superspan ON (donated, whole-trace payload) ==
    the plain two-dispatch-slide ladder, bit for bit. Slow lane (tier-1
    wall-clock budget): the chaos-on variant below is the superset gate —
    same superspan-vs-ladder bit-identity assert over the same composed
    scenario with MORE channels live (fault slab events, commit-time
    draws, telemetry ring, non-default profile) — so tier-1 keeps that
    one; this fault-free isolate remains for diagnosis when the superset
    gate trips."""
    ss = _run(
        _build_composed(superspan=True, superspan_k=4, superspan_chunk=4)
    )
    assert ss._superspan_ok()
    ladder = _run(_build_composed(donate=False, fuse_slide=False))
    _assert_superspan_matches_ladder(ss, ladder)
    # Steady-state sync economy: one progress readback per superspan
    # dispatch, nothing else.
    assert ss.dispatch_stats["slide_syncs"] == ss.dispatch_stats["superspans"]


def test_superspan_composed_bit_identical_under_faults(tmp_path):
    """Same flagship parity with the chaos engine on: node crash chains ride
    the slab, pod-attempt threefry draws happen at commit inside the scanned
    windows — the on-device slides must leave every draw slot-keyed exactly
    as the ladder path sees it.

    BOTH engines run the non-default "best_fit" compiled scheduler profile
    (batched/pipeline.py): this is the chaos-on bit-identity gate for a
    non-default profile ACROSS EXECUTORS — the subject is the superspan
    executor, and the comparator dispatches plain ladder chunks PLUS the
    fused chunk+slide megastep (fuse_slide=True: the fused program is the
    last ladder chunk of every slide span), so ladder, fused and superspan
    all execute the same compiled profile and must agree bit for bit.
    Riding the existing fault engines keeps this at zero extra engines
    (the profile variant replaces the programs this test compiled anyway,
    the PR-8 telemetry pattern).

    The ss engine ALSO runs with the flight recorder armed (PR 8): the
    parity compare against the telemetry-OFF comparator is then the
    composed HPA+CA+superspan+chaos telemetry bit-identity gate. The
    composed-scale ring/report/budget gates ride here too;
    tests/test_telemetry.py covers the mechanics on cheap engines."""
    ss = _run(
        _build_composed(
            config_suffix=FAULT_SUFFIX,
            superspan=True,
            superspan_k=4,
            superspan_chunk=4,
            telemetry=True,
            telemetry_ring=32,  # < executed windows: drains + wrap exercised
            scheduler_profile="best_fit",
        )
    )
    assert ss.fault_params is not None
    assert ss.profile.name == "best_fit"
    ladder = _run(
        _build_composed(
            config_suffix=FAULT_SUFFIX,
            donate=False,
            fuse_slide=True,
            scheduler_profile="best_fit",
        )
    )
    # The comparator really exercised BOTH non-superspan executors: plain
    # ladder chunks and the fused chunk+slide megastep.
    assert ladder.dispatch_stats["window_chunks"] > 0
    assert ladder.dispatch_stats["fused_slides"] > 0
    counters = ss.metrics_summary()["counters"]
    assert counters["pod_interruptions"] + counters["pods_failed"] > 0, (
        "fault run produced no faults; parity under faults is vacuous"
    )
    _assert_superspan_matches_ladder(ss, ladder)
    # Threading the profile static added no host syncs: the superspan
    # engine's dispatch accounting still meets the steady-state budget
    # (asserted == below) and the comparator's chunk accounting is the
    # fused-ladder shape, exactly as under the default profile.
    assert ss.dispatch_stats["ladder_fallbacks"] == 0

    # --- composed-scale flight-recorder gates (PR 8) ---------------------
    from kubernetriks_tpu.telemetry.ring import RING_COLUMNS

    # No new syncs: the steady-state budget (1 progress readback per
    # superspan, zero ladder chunks) is untouched by telemetry.
    assert ss.dispatch_stats["slide_syncs"] == ss.dispatch_stats["superspans"]
    assert ss.dispatch_stats["ladder_fallbacks"] == 0
    # Ring lossless despite wrapping (capacity 32 < executed windows):
    # every executed window has exactly one record, and the per-window
    # decision deltas sum to the run's total decision counter.
    executed = ss.next_window_idx
    assert executed > 32
    wins, data = ss.telemetry_window_series()
    np.testing.assert_array_equal(wins, np.arange(executed, dtype=np.int32))
    assert (
        int(data[:, :, RING_COLUMNS.index("decisions")].sum())
        == counters["scheduling_decisions"]
    )
    # The composed scenario's activity is visible in the ring columns.
    for col in ("hpa_pod_actions", "ca_node_actions", "fault_events"):
        assert int(data[:, :, RING_COLUMNS.index(col)].sum()) > 0, col
    rep = ss.telemetry_report()
    assert rep["spans"]["superspan"]["count"] == ss.dispatch_stats["superspans"]
    assert rep["spans"]["progress_wait"]["count"] == ss.dispatch_stats["slide_syncs"]
    assert (
        rep["sync_budget"]["observed_slide_syncs"]
        == rep["sync_budget"]["steady_state_expected"]
    )
    assert rep["ring"]["windows_kept"] == executed
    # The emitted Chrome trace carries the async progress readbacks as
    # matched flow pairs (the overlap arrows a Perfetto view shows).
    from test_telemetry import validate_chrome_trace

    path = ss.write_chrome_trace(str(tmp_path / "trace.json"))
    validate_chrome_trace(path, expect_flows=True)


@pytest.mark.slow
def test_superspan_bounded_stage_and_exhaustion_exit(monkeypatch):
    """Over-budget traces stage refill columns through bounded RefillStage
    slabs. A minimal-width stage (W + W/2) exhausts after a single max
    slide, forcing SUPERSPAN_STAGE exits and restages mid-run — the end
    state must still match the ladder, and the engine must never spin on an
    exhausted buffer (the regression this test pins: _stage_covers accepts
    a stage with zero slide headroom left). Slow lane (tier-1 wall-clock
    budget): restage-under-exhaustion coverage stays tier-1 through
    test_superspan_capacity_edge_restages_instead_of_growing (the exact
    zero-headroom edge) and test_streaming's run-ahead-restage / K=1-ring
    / demand-mode gates over the same stage machinery; this ladder-parity
    variant remains for diagnosis when those trip."""
    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 0)
    ss = _build_composed(
        superspan=True,
        superspan_k=8,
        superspan_chunk=4,
        superspan_stage_cols=96,  # W=64: minimum width, 32 columns headroom
        fuse_slide=False,
    )
    assert ss._device_slide is None, "budget monkeypatch did not take"
    _run(ss)
    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 2 << 30)
    ladder = _run(_build_composed(donate=False, fuse_slide=False))
    _assert_superspan_matches_ladder(ss, ladder)
    # The initial install plus at least one mid-run restage happened.
    assert ss.dispatch_stats["stage_refills"] >= 2


def test_superspan_grow_exit_matches_resident():
    """SUPERSPAN_GROW: long-running pods leave no terminal leading slot, so
    the scanned loop reports shift == 0 and the engine grows the window in
    place — same counters and terminal phases as the full-resident run."""
    workload = _long_running_workload(n_pods=120, duration=600.0)
    ss = _build_growth(
        workload,
        pod_window=64,
        superspan=True,
        superspan_k=4,
        superspan_chunk=4,
        fast_forward=False,
    )
    ss.step_until_time(1200.0)
    assert ss.pod_window == 120, "window never grew"
    assert ss.dispatch_stats["superspans"] > 0
    ref = _build_growth(workload, fast_forward=False)
    ref.step_until_time(1200.0)
    assert (
        ss.metrics_summary()["counters"] == ref.metrics_summary()["counters"]
    )
    P_real = np.asarray(ss.state.pods.phase).shape[1]
    np.testing.assert_array_equal(
        np.asarray(ref.state.pods.phase)[:, :P_real],
        np.asarray(ss.state.pods.phase),
    )


@pytest.mark.slow
def test_precompile_warms_superspan_program():
    """A superspan engine warms exactly ONE program shape (the scanned loop
    serves every span/target); the warm dispatch must not perturb state or
    host mirrors. Slow lane (tier-1 wall-clock budget): warm-up plumbing,
    not simulation semantics — a precompile regression that let the
    superspan fall back to the ladder fails tier-1 loudly anyway via
    test_bench_smoke's superspan line (in-bench scanned-executor assert)
    and the dispatch-count gate in test_window_donation_dispatch."""
    ss = _build_composed(superspan=True, superspan_k=4, superspan_chunk=4)
    before = (ss.next_window_idx, ss._pod_base)
    snap = {
        k: np.asarray(v).copy()
        for k, v in (("phase", ss.state.pods.phase), ("time", ss.state.time))
    }
    assert ss.precompile_chunks() == 1
    assert (ss.next_window_idx, ss._pod_base) == before
    np.testing.assert_array_equal(np.asarray(ss.state.pods.phase), snap["phase"])
    np.testing.assert_array_equal(np.asarray(ss.state.time), snap["time"])
    # And the warmed program is the one the loop then uses: no ladder chunks.
    _run(ss)
    assert ss.dispatch_stats["window_chunks"] == 0
    assert ss.dispatch_stats["superspans"] > 0


def _exact_exhaustion_workload(W=64):
    """Engineered for the capacity-unreadable staging edge: pods 0..W/2-1
    terminate before the first slide, pods W/2..(3W/2)-1 run long enough to
    be live across it, and the final W/2 pods create after a long gap. The
    first slide is then EXACTLY the max quantum W/2 — landing a minimal
    (W + W/2)-wide stage's capacity column exactly at its edge with a live
    front pod and the true capacity far away. A blocked slide there must
    exit SUPERSPAN_STAGE (restage, re-read the real capacity), never
    SUPERSPAN_GROW: the ladder path never grows on this trace."""
    half = W // 2
    pods = [(1.0 + i, i, 20.0 if i < half else 100.0) for i in range(W + half)]
    pods += [(2001.0 + j, W + half + j, 20.0) for j in range(half)]
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    return GenericWorkloadTrace.from_yaml(
        "events:"
        + "".join(
            f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:04d}
        spec:
          resources:
            requests: {{cpu: 10, ram: 10485760}}
            limits: {{cpu: 10, ram: 10485760}}
          running_duration: {dur}
"""
            for ts, i, dur in pods
        )
    ).convert_to_simulator_events()


def test_superspan_capacity_edge_restages_instead_of_growing(monkeypatch):
    """Regression: a blocked slide whose capacity column lies beyond the
    stage (col == L after a max slide consumed all headroom) must exit
    SUPERSPAN_STAGE, not SUPERSPAN_GROW — growing there diverges from the
    ladder (which reads the TRUE capacity and just keeps running)."""
    W = 64
    workload = _exact_exhaustion_workload(W)
    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 0)
    ss = _build_growth(
        workload,
        pod_window=W,
        superspan=True,
        superspan_k=8,
        superspan_chunk=4,
        superspan_stage_cols=W + W // 2,  # minimum width: zero slack
        fast_forward=False,
    )
    assert ss._device_slide is None, "budget monkeypatch did not take"
    ss.step_until_time(2200.0)
    # The edge fired (initial install + at least one mid-run restage) and
    # was answered with a restage, not a spurious growth.
    assert ss.dispatch_stats["stage_refills"] >= 2
    assert ss.pod_window == W, "capacity-unreadable slide grew the window"
    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 2 << 30)
    ladder = _build_growth(
        workload, pod_window=W, fast_forward=False, fuse_slide=False
    )
    ladder.step_until_time(2200.0)
    assert ladder.pod_window == W
    _assert_superspan_matches_ladder(ss, ladder)
