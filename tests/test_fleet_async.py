"""Lane-asynchronous fleet gates (batched/fleet.py submit/pump/poll +
the per-lane window clocks of DESIGN §13).

1. A/B IDENTITY: the same heterogeneous-horizon query stream through a
   wave-aligned fleet and a lane-async fleet returns bit-identical
   per-query results — with chaos ON and more queries than lanes, so
   lanes finish early and re-seed mid-flight while neighbours keep
   stepping.
2. LANE PERMUTATION: submitting the same multiset in a different order
   lands queries on different lanes at different global windows — the
   per-query results still bit-match (a lane's trajectory is a pure
   function of its scenario + horizon, never its lane index or clock
   offset; per-lane fault seeds keep that true under chaos).
3. SCALAR ORACLES: each heterogeneous-horizon query's HPA replica count
   equals an independent scalar-oracle run of that scenario stepped to
   that query's OWN horizon (the test_fleet oracle protocol, made
   horizon-heterogeneous).
4. CONTINUOUS ENGINE MECHANICS: poll() streams completions exactly once;
   re-running a stream is recompile-free (cache counts + armed
   sentinel); the occupancy ledger and latency percentiles account every
   query; the trace mux masks per-lane row spans and never re-offers a
   flying lane.
5. QUERY OBSERVATORY (DESIGN §14): poll() of a never-submitted qid is a
   loud KeyError carrying the known-qid inventory; reset_query_stats()
   zeroes the bounded latency histograms without discarding results
   (poll-after-reset still streams each completion exactly once); every
   polled query's lifecycle stages are host-clock monotone.
"""

import os

import numpy as np
import pytest

from kubernetriks_tpu.batched.fleet import (
    Scenario,
    ScenarioFleet,
    jit_cache_sizes,
)
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import (
    GenericClusterTrace,
    GenericWorkloadTrace,
)

from test_fleet import FAULT_SUFFIX, _apply_scenario_to_config, _composed_traces
from test_random_hpa_equivalence import (
    CLUSTER_TRACE as HPA_CLUSTER_TRACE,
    make_workload as make_hpa_workload,
)
from test_window_donation_dispatch import COMPOSED_CONFIG_SUFFIX

# Scenario 0 == scenario 3 (in-stream duplicate at a different horizon
# slot); five queries over three lanes force a mid-flight reseed; the
# 150 s horizon finishes its lane ~3x earlier than its neighbours.
SCENS = [
    (Scenario(fault_seed=11, hpa_scan_interval=30.0), 450.0),
    (Scenario(fault_seed=22, ca_threshold=0.7), 250.0),
    (Scenario(fault_seed=33, hpa_tolerance=0.25), 350.0),
    (Scenario(fault_seed=11, hpa_scan_interval=30.0), 450.0),  # dup of 0
    (Scenario(fault_seed=44), 150.0),
]


def _build(lane_async, config, cluster_events, workload):
    return ScenarioFleet(
        config,
        cluster_events,
        workload,
        n_lanes=3,
        horizon=450.0,
        max_pods_per_cycle=16,
        use_pallas=False,
        ca_slot_multiplier=4,
        lane_async=lane_async,
    )


@pytest.fixture(scope="module")
def async_ab_runs():
    """One wave-aligned and two lane-async fleets (the second fed the
    permuted stream) over the composed+chaos scenario — the shared
    engines every gate below reads. KTPU_EXPLAIN_RECOMPILES=1 arms the
    recompile sentinel on the two lane-async fleets, so every
    post-warm-up pump round already runs under an expect_none guard.
    The WAVE reference stays unarmed: it compiles one program per
    distinct span length by design, and this stream's second wave
    introduces span lengths the first never ran."""
    config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    cluster_events, workload = _composed_traces()
    wave = _build(False, config, cluster_events, workload)
    for scen, hor in SCENS:
        wave.submit(scen, hor)
    wave_res = wave.run()

    os.environ["KTPU_EXPLAIN_RECOMPILES"] = "1"
    try:
        asy = _build(True, config, cluster_events, workload)
        qids = [asy.submit(s, h) for s, h in SCENS]
        asy.run_async()

        perm = [4, 2, 3, 0, 1]
        asy_p = _build(True, config, cluster_events, workload)
        qids_p = [asy_p.submit(*SCENS[i]) for i in perm]
        asy_p.run_async()

        yield wave, wave_res, asy, qids, asy_p, qids_p, perm
        wave.close()
        asy.close()
        asy_p.close()
    finally:
        os.environ.pop("KTPU_EXPLAIN_RECOMPILES", None)


def _same_result(a, b):
    return (
        a.counters == b.counters
        and a.hpa_replicas == b.hpa_replicas
        and a.ca_nodes == b.ca_nodes
    )


def test_async_bit_matches_wave(async_ab_runs):
    """The A/B gate: every query's counters / replica / node readouts are
    bit-identical between the wave-aligned and lane-async executions,
    with the chaos machinery demonstrably engaged."""
    wave, wave_res, asy, qids, _, _, _ = async_ab_runs
    total_faults = 0
    for i, qid in enumerate(qids):
        ra, rw = asy.results[qid], wave_res[i]
        assert _same_result(ra, rw), (
            f"query {i} ({SCENS[i]}) diverges between wave and async:\n"
            f"{rw.counters}\n{ra.counters}"
        )
        total_faults += (
            ra.counters["pod_restarts"] + ra.counters["node_crashes"]
        )
    assert total_faults > 0, "chaos fleet produced no faults (vacuous gate)"


def test_async_lane_permutation_bit_identical(async_ab_runs):
    """Permuted submission order = different lanes, different clock
    offsets, different reseed timing — identical per-query results. The
    in-stream duplicate (scenario 0 == 3) also bit-matches within one
    fleet across its two placements."""
    _, _, asy, qids, asy_p, qids_p, perm = async_ab_runs
    for j, i in enumerate(perm):
        ra, rp = asy.results[qids[i]], asy_p.results[qids_p[j]]
        assert _same_result(ra, rp), (
            f"scenario {i} differs between lane {ra.lane} (in-order) and "
            f"lane {rp.lane} (permuted)"
        )
    r0, r3 = asy.results[qids[0]], asy.results[qids[3]]
    assert _same_result(r0, r3)


def test_async_poll_streams_each_result_once(async_ab_runs):
    """poll() is the streaming read side: after run_async drained the
    whole stream, one poll returns every result exactly once (completion
    order) and the next poll returns nothing."""
    _, _, asy, qids, _, _, _ = async_ab_runs
    polled = asy.poll()
    assert sorted(r.query for r in polled) == sorted(qids)
    assert asy.poll() == []


def test_async_rerun_is_recompile_free(async_ab_runs):
    """The compile-once contract across reseeds: re-submitting the whole
    stream to the warm fleet moves no jit-cache count (and the armed
    sentinel would raise on any hidden compile), and reproduces the
    first run's results exactly."""
    _, _, asy, qids, _, _, _ = async_ab_runs
    assert asy._sentinel is not None, (
        "KTPU_EXPLAIN_RECOMPILES=1 did not arm the fleet sentinel"
    )
    first = {i: asy.results[qid] for i, qid in enumerate(qids)}
    sizes0 = jit_cache_sizes()
    rerun_qids = [asy.submit(s, h) for s, h in SCENS]
    asy.run_async()
    sizes1 = jit_cache_sizes()
    assert sizes0 == sizes1, {
        k: (sizes0[k], sizes1[k]) for k in sizes0 if sizes0[k] != sizes1[k]
    }
    for i, qid in enumerate(rerun_qids):
        assert _same_result(asy.results[qid], first[i]), f"rerun query {i}"
    asy.poll()  # drain the completion queue for later gates


def test_async_ledger_and_latency_account_every_query(async_ab_runs):
    """The occupancy ledger saw busy lane-windows, every completed query
    has a latency sample, and reset_query_stats() returns both to their
    pre-run state."""
    _, _, _, _, asy_p, qids_p, _ = async_ab_runs
    occ = asy_p.lane_occupancy()
    assert 0.0 < occ["min"] <= occ["mean"] <= 1.0
    assert occ["lane_windows_busy"] > 0
    lat = asy_p.query_latency_percentiles()
    assert lat["count"] == len(qids_p)
    assert 0.0 < lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"]
    asy_p.reset_query_stats()
    assert asy_p.query_latency_percentiles() == {"count": 0}
    assert asy_p.lane_occupancy()["mean"] == 1.0  # pristine ledger


def test_async_poll_unknown_qid_raises_with_inventory(async_ab_runs):
    """poll(qid) on a never-submitted query id is a LOUD KeyError that
    names the qid and inventories what the fleet has actually seen —
    never a silent empty list (a typo'd qid would otherwise read as
    'still pending' forever)."""
    _, _, asy, _, _, _, _ = async_ab_runs
    with pytest.raises(KeyError, match=r"poll\(9999\).*never submitted"):
        asy.poll(9999)
    with pytest.raises(KeyError, match="never submitted"):
        asy.poll(-1)
    try:
        asy.poll(9999)
    except KeyError as err:
        msg = str(err)
        assert "submitted (qids 0.." in msg  # the known-qid inventory
    # A known-but-pending qid is NOT an error: it returns [] (qid 0 was
    # submitted and already polled, so it's known and not completed).
    assert asy.poll(0) == []


def test_async_poll_after_reset_streams_results(async_ab_runs):
    """Poll-after-reset semantics: reset_query_stats() clears the latency
    HISTOGRAMS (count back to 0) but never discards RESULTS — a query
    completed before the reset is still polled exactly once after it."""
    _, _, _, _, asy_p, qids_p, _ = async_ab_runs
    # The ledger gate above already reset asy_p's stats; its results were
    # never polled.
    assert asy_p.query_latency_percentiles() == {"count": 0}
    first = asy_p.poll(qids_p[0])
    assert len(first) == 1 and first[0].query == qids_p[0]
    assert asy_p.poll(qids_p[0]) == []  # streamed once, even post-reset
    rest = asy_p.poll()
    assert sorted(r.query for r in rest) == sorted(qids_p[1:])
    # Polling completions from BEFORE the reset does not repopulate the
    # histograms: recording happens at drain time, not poll time.
    assert asy_p.query_latency_percentiles() == {"count": 0}


def test_async_query_lifecycle_stages_are_monotone(async_ab_runs):
    """Every polled query's lifecycle record carries the five host-clock
    stages of DESIGN §14 in order (submitted <= admitted <=
    first-dispatch <= drained <= polled) and a real lane assignment."""
    _, _, asy, qids, _, _, _ = async_ab_runs
    for qid in qids:
        rec = asy.query_lifecycle(qid)
        assert rec["lane"] >= 0
        assert "flow_id" in rec  # 0 here: the fixture runs untraced
        assert (
            rec["submitted_ns"]
            <= rec["admitted_ns"]
            <= rec["first_dispatch_ns"]
            <= rec["drained_ns"]
            <= rec["polled_ns"]
        ), rec
    with pytest.raises(KeyError, match="no lifecycle record"):
        asy.query_lifecycle(31337)


def test_async_matches_scalar_oracles_at_own_horizons():
    """Per-query scalar-oracle equivalence under heterogeneous horizons:
    each lane-async query's final HPA replica count equals an
    independent scalar run of that scenario stepped to that query's own
    horizon — four queries over three lanes, so one oracle checks a
    RE-SEEDED lane (the test_fleet HPA oracle protocol; tolerance-only
    scenarios, where scalar and batched sampling provably agree)."""
    queries = [
        (Scenario(), 950.0),
        (Scenario(hpa_tolerance=0.02), 470.0),
        (Scenario(hpa_tolerance=0.4), 710.0),
        (Scenario(hpa_tolerance=0.02), 230.0),
    ]
    workload = make_hpa_workload(29)
    base = default_test_simulation_config()
    base.horizontal_pod_autoscaler.enabled = True
    fleet = ScenarioFleet(
        base,
        GenericClusterTrace.from_yaml(
            HPA_CLUSTER_TRACE
        ).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_lanes=3,
        horizon=950.0,
        use_pallas=False,
        lane_async=True,
    )
    qids = [fleet.submit(s, h) for s, h in queries]
    fleet.run_async()
    diverged = set()
    for i, (scen, hor) in enumerate(queries):
        cfg = default_test_simulation_config()
        cfg.horizontal_pod_autoscaler.enabled = True
        sim = KubernetriksSimulation(_apply_scenario_to_config(cfg, scen))
        sim.initialize(
            GenericClusterTrace.from_yaml(HPA_CLUSTER_TRACE),
            GenericWorkloadTrace.from_yaml(workload),
        )
        sim.step_until_time(hor)
        groups = sim.horizontal_pod_autoscaler.pod_groups
        oracle = (
            len(groups["pod_group_1"].created_pods)
            if "pod_group_1" in groups
            else 0
        )
        got = fleet.results[qids[i]].hpa_replicas["pod_group_1"]
        assert got == oracle, (
            f"query {i} ({scen}, horizon {hor}): async fleet reports "
            f"{got} replicas, scalar oracle {oracle}"
        )
        diverged.add((oracle, hor))
    assert len(diverged) > 1  # the heterogeneity was non-vacuous
    fleet.close()


def test_trace_mux_masks_and_never_reoffers():
    """The lane trace multiplexer: a masked row span changes results
    (non-vacuous), equal masks bit-match across lane placements
    (including a 1-lane fleet — placement invariance), and offering a
    FLYING lane raises (never-re-offer invariant)."""
    config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    cluster_events, workload = _composed_traces()
    fleet = _build(True, config, cluster_events, workload)
    E = fleet.engine._lane_mux.n_rows
    q_full = fleet.submit(Scenario(fault_seed=11), 300.0)
    q_mask = fleet.submit(
        Scenario(fault_seed=11), 300.0, trace_rows=(0, E // 2)
    )
    q_full2 = fleet.submit(Scenario(fault_seed=11), 300.0)
    # Lands on a RE-USED lane: the mux must retire the old span first.
    q_mask2 = fleet.submit(
        Scenario(fault_seed=11), 300.0, trace_rows=(0, E // 2)
    )
    fleet.run_async()
    res = fleet.results
    assert res[q_full].counters == res[q_full2].counters
    assert res[q_mask].counters == res[q_mask2].counters
    assert res[q_full].counters != res[q_mask].counters, "mask did not bite"

    solo = ScenarioFleet(
        config,
        cluster_events,
        workload,
        n_lanes=1,
        horizon=450.0,
        max_pods_per_cycle=16,
        use_pallas=False,
        ca_slot_multiplier=4,
        lane_async=True,
    )
    s1 = solo.submit(Scenario(fault_seed=11), 300.0, trace_rows=(0, E // 2))
    solo.run_async()
    assert solo.results[s1].counters == res[q_mask].counters
    solo.close()

    flying = _build(True, config, cluster_events, workload)
    flying.submit(Scenario(), 300.0)
    flying.pump()  # lane 0 is now in flight
    with pytest.raises(RuntimeError, match="fly|flight|active"):
        flying.engine.set_lane_trace(0, 0, E // 2)
    flying.close()


def test_async_every_qid_streams_exactly_one_terminal_outcome(
    async_ab_runs,
):
    """The stream-once contract covers FAILURES too (the poll()
    hang-forever fix): a mixed stream — one query doomed by an
    already-expired deadline, one healthy — delivers exactly one
    terminal outcome per qid through poll(), discriminated by the shared
    `.ok`/`.kind` protocol, and a dead query never leaves its client
    polling forever."""
    from kubernetriks_tpu.batched.faults import (
        DeadlineExceededError,
        QueryError,
    )

    _, _, asy, qids, _, _, _ = async_ab_runs
    reference = asy.results[qids[0]]
    asy.poll()  # drain completions earlier gates may not have polled
    q_dead = asy.submit(*SCENS[0], deadline_s=1e-9)  # expired on arrival
    q_live = asy.submit(*SCENS[0])
    asy.run_async()
    outcomes = asy.poll()
    assert sorted(o.query for o in outcomes) == sorted([q_dead, q_live])
    by_qid = {o.query: o for o in outcomes}
    dead, live = by_qid[q_dead], by_qid[q_live]
    assert isinstance(dead, DeadlineExceededError)
    assert isinstance(dead, QueryError)  # a real Exception subclass
    assert (dead.ok, dead.kind) == (False, "deadline_exceeded")
    assert dead.lane == -1, "deadline failure must never occupy a lane"
    assert dead.late_s >= 0.0 and "deadline exceeded" in dead.message
    assert (live.ok, live.kind) == (True, "result")
    assert _same_result(live, reference)
    # Streamed exactly once: the broadcast poll and the per-qid poll are
    # both empty now, for the error exactly like for the result.
    assert asy.poll() == []
    assert asy.poll(q_dead) == [] and asy.poll(q_live) == []
    assert asy.failed_queries.get("deadline_exceeded", 0) >= 1
