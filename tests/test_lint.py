"""ktpu-lint self-tests: golden-clean over the real package, and every
seeded-violation fixture must be caught by its pass (>= 2 fixtures per
pass, acceptance-gated). The fixtures live in tests/lint_fixtures/ —
excluded from the default lint scope, linted here explicitly."""

import os

import pytest

from kubernetriks_tpu.lint import run_lint
from kubernetriks_tpu.lint.__main__ import DEFAULT_SCOPE, main as lint_main

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "lint_fixtures")


def _fixture(name: str):
    return os.path.join(FIXTURES, name)


def test_repo_is_golden_clean():
    """The whole default scope (package, bench.py, tests, scripts,
    experiments) lints clean — every legitimate sync/draw carries an
    explicit waiver with a reason. New violations fail CI here and in the
    dedicated lint job."""
    scope = [p for p in DEFAULT_SCOPE if os.path.exists(os.path.join(ROOT, p))]
    violations = run_lint(scope, ROOT)
    assert violations == [], "\n".join(v.render() for v in violations)


def test_cli_exit_codes():
    """`python -m kubernetriks_tpu.lint` exits 0 on clean input, 1 on a
    seeded violation."""
    assert lint_main(["--root", ROOT, "kubernetriks_tpu/flags.py"]) == 0
    assert (
        lint_main(["--root", ROOT, _fixture("envflags_direct_read.py")]) == 1
    )


# (fixture file, pass id, expected minimum violations, message fragment)
FIXTURE_CASES = [
    ("donation_read_after_donate.py", "donation", 1, "after it was donated"),
    ("donation_alias_and_attribute.py", "donation", 1, "self.state"),
    ("donation_loop_carried.py", "donation", 1, "step_donated"),
    ("hostsync_item_and_asarray.py", "hostsync", 3, ".item()"),
    ("hostsync_cast_and_branch.py", "hostsync", 2, "int()"),
    ("hostsync_export_hook.py", "hostsync", 3, "np.asarray"),
    ("jitstatic_unknown_param.py", "jitstatic", 1, "max_pods"),
    ("jitstatic_pair_drift.py", "jitstatic", 1, "collect_gauges"),
    ("jitstatic_coupled_drift.py", "jitstatic", 1, "travel together"),
    ("prng_jax_random.py", "prng", 3, "jax.random"),
    ("prng_np_random.py", "prng", 2, "random"),
    ("envflags_direct_read.py", "envflags", 1, "KTPU_SUPERSPAN"),
    ("envflags_unregistered.py", "envflags", 3, "not declared"),
]


@pytest.mark.parametrize(
    "fixture,pass_id,min_violations,fragment",
    FIXTURE_CASES,
    ids=[c[0] for c in FIXTURE_CASES],
)
def test_fixture_caught(fixture, pass_id, min_violations, fragment):
    violations = run_lint([_fixture(fixture)], ROOT, passes=[pass_id])
    rendered = "\n".join(v.render() for v in violations)
    assert len(violations) >= min_violations, rendered or "no violations"
    assert any(fragment in v.message for v in violations), rendered
    assert all(v.pass_id == pass_id for v in violations)
    # and the CLI gates on it (the CI job's contract)
    assert lint_main(["--root", ROOT, _fixture(fixture)]) == 1


@pytest.mark.parametrize(
    "fixture,pass_id",
    [(c[0], c[1]) for c in FIXTURE_CASES],
    ids=[c[0] for c in FIXTURE_CASES],
)
def test_fixture_all_passes_agree(fixture, pass_id):
    """Running ALL passes over a fixture still reports its seeded class
    (passes don't mask each other)."""
    violations = run_lint([_fixture(fixture)], ROOT)
    assert any(v.pass_id == pass_id for v in violations)


def test_no_false_positive_on_rebind_patterns():
    """The canonical safe patterns stay clean: `state = donated(state)`
    rebinds, alias rebinds through self.state, and a waived sync."""
    violations = run_lint(
        [_fixture("donation_read_after_donate.py")], ROOT, passes=["donation"]
    )
    lines = {v.line for v in violations}
    src_lines = open(
        os.path.join(ROOT, _fixture("donation_read_after_donate.py"))
    ).read().splitlines()
    good_start = next(
        i for i, line in enumerate(src_lines, 1) if "def good_driver" in line
    )
    assert all(line < good_start for line in lines), (
        "good_driver (rebind pattern) must not be flagged"
    )


def test_waiver_suppresses_with_reason_only():
    """A `# ktpu: sync-ok(reason)` waiver suppresses exactly its line; the
    same sync without a waiver in the same fixture is still reported."""
    violations = run_lint(
        [_fixture("hostsync_item_and_asarray.py")], ROOT, passes=["hostsync"]
    )
    src = open(
        os.path.join(ROOT, _fixture("hostsync_item_and_asarray.py"))
    ).read().splitlines()
    waived_lines = {
        i for i, line in enumerate(src, 1) if "ktpu: sync-ok" in line
    }
    assert waived_lines, "fixture must contain a waived sync"
    assert not (waived_lines & {v.line for v in violations})
    assert violations, "unwaived syncs must still be reported"


def test_observatory_and_export_are_hot_path_with_zero_waivers():
    """The capacity observatory's host half (telemetry/observatory.py)
    and its export seam (telemetry/export.py) carry the hot-path pragma
    — the host-sync pass patrols them like tracer.py — and stay
    golden-clean with ZERO sync-ok waivers: exports run strictly from
    drained host copies, never a device value."""
    from kubernetriks_tpu.lint import collect_files, is_hot

    paths = [
        "kubernetriks_tpu/telemetry/observatory.py",
        "kubernetriks_tpu/telemetry/export.py",
        "kubernetriks_tpu/telemetry/tracer.py",  # the PR 8 precedent
    ]
    files = collect_files(paths, ROOT)
    assert len(files) == len(paths)
    for sf in files:
        assert is_hot(sf), f"{sf.path} lost its hot-path pragma"
        src = open(os.path.join(ROOT, sf.path)).read()
        assert "ktpu: sync-ok" not in src, (
            f"{sf.path} grew a sync-ok waiver — the observatory/export "
            "half of telemetry must stay waiver-free (drained copies only)"
        )
    violations = run_lint(paths, ROOT, passes=["hostsync"])
    assert violations == [], "\n".join(v.render() for v in violations)


def test_jit_table_is_scanned_not_hardcoded():
    """The donated-entry table really comes from scanning jit sites: the
    package-wide context contains the engine's donated entries with their
    donated positions."""
    from kubernetriks_tpu.lint import build_context, collect_files

    files = collect_files(["kubernetriks_tpu"], ROOT)
    ctx = build_context(files)
    for entry in (
        "run_windows_donated",
        "run_windows_skip_donated",
        "run_superspan_donated",
        "hpa_pass_donated",
        "ca_pass_donated",
        "_fused_chunk_slide_donated",
    ):
        assert ctx.donated.get(entry) == (0,), (entry, ctx.donated.get(entry))
    # paired undonated entries resolved with identical statics (rule 2 ran
    # against real data)
    by_name = {e.name: e for e in ctx.jit_entries}
    assert frozenset(by_name["run_windows"].static_argnames) == frozenset(
        by_name["run_windows_donated"].static_argnames
    )


def test_flag_registry_truthiness(monkeypatch):
    """The ONE truthiness rule: '0'/''/'false'/'no'/'off' are false, unset
    takes the default, anything else is true — the KUBERNETRIKS_FAST_TESTS=0
    bug class (bool(os.environ.get(...)) made '0' truthy) can't recur."""
    from kubernetriks_tpu.flags import flag_bool, flag_str, flag_tristate

    for falsy in ("0", "", "false", "No", "OFF"):
        monkeypatch.setenv("KTPU_DEBUG_FINITE", falsy)
        assert flag_bool("KTPU_DEBUG_FINITE") is False
    for truthy in ("1", "2", "true", "on"):
        monkeypatch.setenv("KTPU_DEBUG_FINITE", truthy)
        assert flag_bool("KTPU_DEBUG_FINITE") is True
    monkeypatch.delenv("KTPU_DEBUG_FINITE", raising=False)
    assert flag_bool("KTPU_DEBUG_FINITE") is False  # registered default
    assert flag_bool("KTPU_MEGAKERNEL") is True  # registered default
    monkeypatch.delenv("KTPU_SUPERSPAN", raising=False)
    assert flag_tristate("KTPU_SUPERSPAN") is None
    monkeypatch.setenv("KTPU_SUPERSPAN", "0")
    assert flag_tristate("KTPU_SUPERSPAN") is False
    monkeypatch.setenv("KUBERNETRIKS_LOG", "debug")
    assert flag_str("KUBERNETRIKS_LOG") == "debug"
    monkeypatch.delenv("KUBERNETRIKS_LOG", raising=False)
    assert flag_str("KUBERNETRIKS_LOG") == "INFO"
    with pytest.raises(KeyError):
        flag_bool("KTPU_NOT_REGISTERED")
    with pytest.raises(TypeError):
        flag_bool("KUBERNETRIKS_LOG")  # registered as str, read as bool
    # int flags (streaming pipeline knobs): unset/empty -> default, decimal
    # parses, a typo raises AT the registry instead of selecting a default.
    from kubernetriks_tpu.flags import flag_int

    monkeypatch.delenv("KTPU_STREAM_DEPTH", raising=False)
    assert flag_int("KTPU_STREAM_DEPTH") == 3
    monkeypatch.setenv("KTPU_STREAM_DEPTH", " 5 ")
    assert flag_int("KTPU_STREAM_DEPTH") == 5
    monkeypatch.setenv("KTPU_STREAM_DEPTH", "")
    assert flag_int("KTPU_STREAM_DEPTH") == 3
    monkeypatch.setenv("KTPU_STREAM_DEPTH", "two")
    with pytest.raises(ValueError):
        flag_int("KTPU_STREAM_DEPTH")
    monkeypatch.delenv("KTPU_STREAM_SEGMENT", raising=False)
    assert flag_int("KTPU_STREAM_SEGMENT") is None
    with pytest.raises(TypeError):
        flag_int("KTPU_DEBUG_FINITE")  # registered as bool, read as int
