"""ktpu-lint self-tests: golden-clean over the real package, and every
seeded-violation fixture must be caught by its pass (>= 2 fixtures per
pass, acceptance-gated). The fixtures live in tests/lint_fixtures/ —
excluded from the default lint scope, linted here explicitly."""

import os

import pytest

from kubernetriks_tpu.lint import run_lint, run_lint_report
from kubernetriks_tpu.lint.__main__ import DEFAULT_SCOPE, main as lint_main

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "lint_fixtures")


def _fixture(name: str):
    return os.path.join(FIXTURES, name)


def test_repo_is_golden_clean():
    """The whole default scope (package, bench.py, tests, scripts,
    experiments) lints clean under all NINE passes — every legitimate
    sync/draw/mix carries an explicit waiver with a reason — AND carries
    zero stale waivers (a *-ok that suppresses nothing would silently
    re-license a future violation). New violations fail CI here and in
    the dedicated lint job (--strict-waivers)."""
    scope = [p for p in DEFAULT_SCOPE if os.path.exists(os.path.join(ROOT, p))]
    report = run_lint_report(scope, ROOT)
    assert report.violations == [], "\n".join(
        v.render() for v in report.violations
    )
    assert report.stale_waivers == [], "\n".join(
        w.render() for w in report.stale_waivers
    )


def test_cli_exit_codes():
    """`python -m kubernetriks_tpu.lint` exits 0 on clean input, 1 on a
    seeded violation."""
    assert lint_main(["--root", ROOT, "kubernetriks_tpu/flags.py"]) == 0
    assert (
        lint_main(["--root", ROOT, _fixture("envflags_direct_read.py")]) == 1
    )


# (fixture file, pass id, expected minimum violations, message fragment)
FIXTURE_CASES = [
    ("donation_read_after_donate.py", "donation", 1, "after it was donated"),
    ("donation_alias_and_attribute.py", "donation", 1, "self.state"),
    ("donation_loop_carried.py", "donation", 1, "step_donated"),
    ("hostsync_item_and_asarray.py", "hostsync", 3, ".item()"),
    ("hostsync_cast_and_branch.py", "hostsync", 2, "int()"),
    ("hostsync_export_hook.py", "hostsync", 3, "np.asarray"),
    ("jitstatic_unknown_param.py", "jitstatic", 1, "max_pods"),
    ("jitstatic_pair_drift.py", "jitstatic", 1, "collect_gauges"),
    ("jitstatic_coupled_drift.py", "jitstatic", 1, "travel together"),
    ("prng_jax_random.py", "prng", 3, "jax.random"),
    ("prng_np_random.py", "prng", 2, "random"),
    ("envflags_direct_read.py", "envflags", 1, "KTPU_SUPERSPAN"),
    ("envflags_unregistered.py", "envflags", 3, "not declared"),
    # contract-prover passes (v2)
    ("stateleaf_missing_consumer.py", "stateleaf", 1, "compare-states"),
    ("stateleaf_manifest_drift.py", "stateleaf", 2, "CLUSTER_STATE_LEAVES"),
    ("scenariotrace_control_flow.py", "scenariotrace", 3, "control flow"),
    (
        "scenariotrace_shape_and_static.py",
        "scenariotrace",
        2,
        "shape expression",
    ),
    ("shapecontract_tolerance_mix.py", "shapecontract", 3, "[:, None]"),
    ("shapecontract_lane_major_mix.py", "shapecontract", 2, "lane-major"),
    ("feederlock_unlocked_touch.py", "feederlock", 3, "unlocked"),
    ("feederlock_blocking_wait.py", "feederlock", 2, "HOLDING the ring lock"),
]


@pytest.mark.parametrize(
    "fixture,pass_id,min_violations,fragment",
    FIXTURE_CASES,
    ids=[c[0] for c in FIXTURE_CASES],
)
def test_fixture_caught(fixture, pass_id, min_violations, fragment):
    violations = run_lint([_fixture(fixture)], ROOT, passes=[pass_id])
    rendered = "\n".join(v.render() for v in violations)
    assert len(violations) >= min_violations, rendered or "no violations"
    assert any(fragment in v.message for v in violations), rendered
    assert all(v.pass_id == pass_id for v in violations)
    # and the CLI gates on it (the CI job's contract)
    assert lint_main(["--root", ROOT, _fixture(fixture)]) == 1


@pytest.mark.parametrize(
    "fixture,pass_id",
    [(c[0], c[1]) for c in FIXTURE_CASES],
    ids=[c[0] for c in FIXTURE_CASES],
)
def test_fixture_all_passes_agree(fixture, pass_id):
    """Running ALL passes over a fixture still reports its seeded class
    (passes don't mask each other)."""
    violations = run_lint([_fixture(fixture)], ROOT)
    assert any(v.pass_id == pass_id for v in violations)


def test_no_false_positive_on_rebind_patterns():
    """The canonical safe patterns stay clean: `state = donated(state)`
    rebinds, alias rebinds through self.state, and a waived sync."""
    violations = run_lint(
        [_fixture("donation_read_after_donate.py")], ROOT, passes=["donation"]
    )
    lines = {v.line for v in violations}
    src_lines = open(
        os.path.join(ROOT, _fixture("donation_read_after_donate.py"))
    ).read().splitlines()
    good_start = next(
        i for i, line in enumerate(src_lines, 1) if "def good_driver" in line
    )
    assert all(line < good_start for line in lines), (
        "good_driver (rebind pattern) must not be flagged"
    )


def test_waiver_suppresses_with_reason_only():
    """A `# ktpu: sync-ok(reason)` waiver suppresses exactly its line; the
    same sync without a waiver in the same fixture is still reported."""
    violations = run_lint(
        [_fixture("hostsync_item_and_asarray.py")], ROOT, passes=["hostsync"]
    )
    src = open(
        os.path.join(ROOT, _fixture("hostsync_item_and_asarray.py"))
    ).read().splitlines()
    waived_lines = {
        i for i, line in enumerate(src, 1) if "ktpu: sync-ok" in line
    }
    assert waived_lines, "fixture must contain a waived sync"
    assert not (waived_lines & {v.line for v in violations})
    assert violations, "unwaived syncs must still be reported"


def test_observatory_and_export_are_hot_path_with_zero_waivers():
    """The capacity observatory's host half (telemetry/observatory.py)
    and its export seam (telemetry/export.py) carry the hot-path pragma
    — the host-sync pass patrols them like tracer.py — and stay
    golden-clean with ZERO sync-ok waivers: exports run strictly from
    drained host copies, never a device value."""
    from kubernetriks_tpu.lint import collect_files, is_hot

    paths = [
        "kubernetriks_tpu/telemetry/observatory.py",
        "kubernetriks_tpu/telemetry/export.py",
        "kubernetriks_tpu/telemetry/tracer.py",  # the PR 8 precedent
        "kubernetriks_tpu/telemetry/histogram.py",  # PR 17 query half
    ]
    files = collect_files(paths, ROOT)
    assert len(files) == len(paths)
    for sf in files:
        assert is_hot(sf), f"{sf.path} lost its hot-path pragma"
        src = open(os.path.join(ROOT, sf.path)).read()
        assert "ktpu: sync-ok" not in src, (
            f"{sf.path} grew a sync-ok waiver — the observatory/export "
            "half of telemetry must stay waiver-free (drained copies only)"
        )
    violations = run_lint(paths, ROOT, passes=["hostsync"])
    assert violations == [], "\n".join(v.render() for v in violations)


def test_jit_table_is_scanned_not_hardcoded():
    """The donated-entry table really comes from scanning jit sites: the
    package-wide context contains the engine's donated entries with their
    donated positions."""
    from kubernetriks_tpu.lint import build_context, collect_files

    files = collect_files(["kubernetriks_tpu"], ROOT)
    ctx = build_context(files)
    for entry in (
        "run_windows_donated",
        "run_windows_skip_donated",
        "run_superspan_donated",
        "hpa_pass_donated",
        "ca_pass_donated",
        "_fused_chunk_slide_donated",
    ):
        assert ctx.donated.get(entry) == (0,), (entry, ctx.donated.get(entry))
    # paired undonated entries resolved with identical statics (rule 2 ran
    # against real data)
    by_name = {e.name: e for e in ctx.jit_entries}
    assert frozenset(by_name["run_windows"].static_argnames) == frozenset(
        by_name["run_windows_donated"].static_argnames
    )


def test_flag_registry_truthiness(monkeypatch):
    """The ONE truthiness rule: '0'/''/'false'/'no'/'off' are false, unset
    takes the default, anything else is true — the KUBERNETRIKS_FAST_TESTS=0
    bug class (bool(os.environ.get(...)) made '0' truthy) can't recur."""
    from kubernetriks_tpu.flags import flag_bool, flag_str, flag_tristate

    for falsy in ("0", "", "false", "No", "OFF"):
        monkeypatch.setenv("KTPU_DEBUG_FINITE", falsy)
        assert flag_bool("KTPU_DEBUG_FINITE") is False
    for truthy in ("1", "2", "true", "on"):
        monkeypatch.setenv("KTPU_DEBUG_FINITE", truthy)
        assert flag_bool("KTPU_DEBUG_FINITE") is True
    monkeypatch.delenv("KTPU_DEBUG_FINITE", raising=False)
    assert flag_bool("KTPU_DEBUG_FINITE") is False  # registered default
    assert flag_bool("KTPU_MEGAKERNEL") is True  # registered default
    monkeypatch.delenv("KTPU_SUPERSPAN", raising=False)
    assert flag_tristate("KTPU_SUPERSPAN") is None
    monkeypatch.setenv("KTPU_SUPERSPAN", "0")
    assert flag_tristate("KTPU_SUPERSPAN") is False
    monkeypatch.setenv("KUBERNETRIKS_LOG", "debug")
    assert flag_str("KUBERNETRIKS_LOG") == "debug"
    monkeypatch.delenv("KUBERNETRIKS_LOG", raising=False)
    assert flag_str("KUBERNETRIKS_LOG") == "INFO"
    with pytest.raises(KeyError):
        flag_bool("KTPU_NOT_REGISTERED")
    with pytest.raises(TypeError):
        flag_bool("KUBERNETRIKS_LOG")  # registered as str, read as bool
    # int flags (streaming pipeline knobs): unset/empty -> default, decimal
    # parses, a typo raises AT the registry instead of selecting a default.
    from kubernetriks_tpu.flags import flag_int

    monkeypatch.delenv("KTPU_STREAM_DEPTH", raising=False)
    assert flag_int("KTPU_STREAM_DEPTH") == 3
    monkeypatch.setenv("KTPU_STREAM_DEPTH", " 5 ")
    assert flag_int("KTPU_STREAM_DEPTH") == 5
    monkeypatch.setenv("KTPU_STREAM_DEPTH", "")
    assert flag_int("KTPU_STREAM_DEPTH") == 3
    monkeypatch.setenv("KTPU_STREAM_DEPTH", "two")
    with pytest.raises(ValueError):
        flag_int("KTPU_STREAM_DEPTH")
    monkeypatch.delenv("KTPU_STREAM_SEGMENT", raising=False)
    assert flag_int("KTPU_STREAM_SEGMENT") is None
    with pytest.raises(TypeError):
        flag_int("KTPU_DEBUG_FINITE")  # registered as bool, read as int


# --- contract-prover v2: state-leaf pass end-to-end --------------------------


def test_stateleaf_scratch_leaf_fails_against_real_tree(tmp_path):
    """THE acceptance gate for pass 6: a scratch leaf added to the REAL
    ClusterBatchState without touching any registry is caught. The test
    copies batched/state.py into a scratch repo layout, inserts a new
    field, and proves the stateleaf pass fails naming the leaf and the
    registries it missed (the untouched copy stays clean)."""
    src_path = os.path.join(ROOT, "kubernetriks_tpu", "batched", "state.py")
    src = open(src_path, encoding="utf-8").read()
    dest_dir = tmp_path / "kubernetriks_tpu" / "batched"
    dest_dir.mkdir(parents=True)
    dest = dest_dir / "state.py"

    # Untouched copy: clean (the classes, manifests and in-file
    # consumers — compare_states, strip_telemetry, init_state — agree).
    dest.write_text(src, encoding="utf-8")
    clean = run_lint(
        ["kubernetriks_tpu/batched/state.py"], str(tmp_path), passes=["stateleaf"]
    )
    assert clean == [], "\n".join(v.render() for v in clean)

    marker = "    nodes: NodeArrays\n"
    assert marker in src, "ClusterBatchState layout changed; update the test"
    dest.write_text(
        src.replace(marker, "    scratch_probe: jnp.ndarray\n" + marker, 1),
        encoding="utf-8",
    )
    violations = run_lint(
        ["kubernetriks_tpu/batched/state.py"], str(tmp_path), passes=["stateleaf"]
    )
    rendered = "\n".join(v.render() for v in violations)
    assert any(
        "scratch_probe" in v.message and "CLUSTER_STATE_LEAVES" in v.message
        for v in violations
    ), rendered or "scratch leaf escaped the manifest registry"
    # The required-field constructor registry catches it too.
    assert any(
        "scratch_probe" in v.message and "init-state" in v.message
        for v in violations
    ), rendered
    # And the CLI gates on it (the CI contract).
    assert (
        lint_main(["--root", str(tmp_path), "kubernetriks_tpu/batched/state.py"])
        == 1
    )


def test_stateleaf_scratch_clock_leaf_fails_against_real_tree(tmp_path):
    """The lane-async variant of the gate above: a scratch per-lane
    CLOCK leaf added to the REAL StepConstants without touching
    STEP_CONSTANTS_LEAVES is caught by the same tmp-tree e2e path (the
    untouched copy stays clean) — the 'how to add a consts leaf'
    checklist anchor for the DESIGN §13 clock protocol."""
    src_path = os.path.join(ROOT, "kubernetriks_tpu", "batched", "state.py")
    src = open(src_path, encoding="utf-8").read()
    dest_dir = tmp_path / "kubernetriks_tpu" / "batched"
    dest_dir.mkdir(parents=True)
    dest = dest_dir / "state.py"

    dest.write_text(src, encoding="utf-8")
    clean = run_lint(
        ["kubernetriks_tpu/batched/state.py"], str(tmp_path), passes=["stateleaf"]
    )
    assert clean == [], "\n".join(v.render() for v in clean)

    marker = "    lane_clock: Optional[jnp.ndarray] = None"
    assert marker in src, "StepConstants layout changed; update the test"
    dest.write_text(
        src.replace(
            marker,
            "    scratch_clock: Optional[jnp.ndarray] = None\n" + marker,
            1,
        ),
        encoding="utf-8",
    )
    violations = run_lint(
        ["kubernetriks_tpu/batched/state.py"], str(tmp_path), passes=["stateleaf"]
    )
    rendered = "\n".join(v.render() for v in violations)
    assert any(
        "scratch_clock" in v.message and "STEP_CONSTANTS_LEAVES" in v.message
        for v in violations
    ), rendered or "scratch clock leaf escaped the consts manifest"
    assert (
        lint_main(["--root", str(tmp_path), "kubernetriks_tpu/batched/state.py"])
        == 1
    )


def test_stateleaf_registries_match_runtime():
    """The AST-parsed manifests equal the live NamedTuple fields, the
    axis/scenario registries name real leaves, and the ckpt manifest
    covers exactly the structural leaves — the lint pass and the runtime
    can never drift apart silently."""
    from kubernetriks_tpu.batched import autoscale, state
    from kubernetriks_tpu.batched.engine import CKPT_COVERED_LEAVES

    assert state.CLUSTER_STATE_LEAVES == state.ClusterBatchState._fields
    assert state.TELEMETRY_RING_LEAVES == state.TelemetryRing._fields
    assert (
        autoscale.AUTOSCALE_STATE_LEAVES == autoscale.AutoscaleState._fields
    )
    assert state.STEP_CONSTANTS_LEAVES == state.StepConstants._fields
    # scenario-traced registries name real statics/consts leaves
    statics_fields = set(autoscale.AutoscaleStatics._fields)
    assert set(autoscale.SCENARIO_TRACED_LEAVES) <= statics_fields
    assert set(state.SCENARIO_TRACED_CONSTS) <= set(
        state.StepConstants._fields
    )
    # the pass's partial-scope fallback copy is pinned EQUAL to the
    # module manifests — the three spellings can never drift
    from kubernetriks_tpu.lint.scenariotrace import DEFAULT_TRACED

    assert DEFAULT_TRACED == set(autoscale.SCENARIO_TRACED_LEAVES) | set(
        state.SCENARIO_TRACED_CONSTS
    )
    # every fleet-composed leaf is registered as traced (compile-once)
    composed = {
        "hpa_interval",
        "hpa_tolerance",
        "ca_threshold",
        "ca_max_nodes",
        "pg_active_from",
        "d_hpa_up",
        "d_hpa_down",
        "d_ca_up",
        "d_ca_down",
        "ca_period",
        "ca_snap",
        "ca_finish_vis",
        "ca_commit_vis",
    }
    assert composed <= set(autoscale.SCENARIO_TRACED_LEAVES)
    # axis signatures name real leaves of the registered NamedTuples
    known = (
        statics_fields
        | set(autoscale.AutoscaleState._fields)
        | set(state.ClusterBatchState._fields)
        | set(state.NodeArrays._fields)
        | set(state.PodArrays._fields)
        | set(state.MetricArrays._fields)
        | set(state.StepConstants._fields)
    )
    for reg in (state.AXIS_SIGNATURES, autoscale.AXIS_SIGNATURES):
        unknown = set(reg) - known
        assert not unknown, f"AXIS_SIGNATURES names unknown leaves: {unknown}"
    # the lane-major-ambiguous set is exactly NODE_HOT_LEAVES
    node_sigs = {
        k for k, v in state.AXIS_SIGNATURES.items() if v == "@node"
    }
    assert node_sigs == set(state.NODE_HOT_LEAVES)
    # ckpt manifest == the structural (None-default) leaves
    structural = {
        f
        for cls in (state.ClusterBatchState, autoscale.AutoscaleState)
        for f in cls._fields
        if cls._field_defaults.get(f, "<nodefault>") is None
    }
    assert set(CKPT_COVERED_LEAVES) == structural


# --- contract-prover v2: stale-waiver detection ------------------------------


def test_stale_waiver_detection(tmp_path):
    """A waiver whose line no longer triggers its pass is reported stale;
    a load-bearing waiver is not; an unknown tag always is. The CLI exits
    0 by default (warning) and 1 under --strict-waivers."""
    fixture = tmp_path / "stale.py"
    fixture.write_text(
        "# ktpu: hot-path\n"
        "def readout(state):\n"
        "    # the USED waiver: .item() really syncs in a hot module\n"
        "    n = state.total.item()  # ktpu: sync-ok(readout at span boundary)\n"
        "    m = 1 + 1  # ktpu: sync-ok(nothing here syncs anymore)\n"
        "    k = 2  # ktpu: synk-ok(typo tag)\n"
        "    return n + m + k\n",
        encoding="utf-8",
    )
    report = run_lint_report([str(fixture)], str(tmp_path))
    assert report.violations == [], [v.render() for v in report.violations]
    lines = {w.line for w in report.stale_waivers}
    assert 5 in lines, "unused waiver not reported stale"
    assert 4 not in lines, "load-bearing waiver wrongly reported stale"
    assert any(
        w.line == 6 and "unknown waiver tag" in w.message
        for w in report.stale_waivers
    )
    assert lint_main(["--root", str(tmp_path), str(fixture)]) == 0
    assert (
        lint_main(
            ["--root", str(tmp_path), "--strict-waivers", str(fixture)]
        )
        == 1
    )


def test_stale_waivers_skipped_under_pass_filter(tmp_path):
    """--pass filters leave other passes' waivers unjudged (their usage
    was never recorded), so the CLI must not report them stale."""
    fixture = tmp_path / "filtered.py"
    fixture.write_text(
        "# ktpu: hot-path\n"
        "def f(state):\n"
        "    return state.total.item()  # ktpu: sync-ok(span boundary)\n",
        encoding="utf-8",
    )
    # envflags-only run: the sync-ok is out of judgment scope -> exit 0
    # even under --strict-waivers.
    assert (
        lint_main(
            [
                "--root",
                str(tmp_path),
                "--strict-waivers",
                "--pass",
                "envflags",
                str(fixture),
            ]
        )
        == 0
    )


# --- contract-prover v2: machine-readable output -----------------------------


def test_json_output(tmp_path, capsys):
    """--json emits file/line/pass/message records for violations and
    stale waivers — the CI annotation/artifact contract."""
    import json

    out_path = tmp_path / "lint.json"
    rc = lint_main(
        [
            "--root",
            ROOT,
            "--json",
            str(out_path),
            _fixture("scenariotrace_control_flow.py"),
        ]
    )
    assert rc == 1
    payload = json.loads(out_path.read_text())
    assert payload["counts"]["violations"] >= 3
    rec = payload["violations"][0]
    assert set(rec) >= {"file", "line", "pass", "message"}
    assert rec["pass"] == "scenariotrace"
    assert rec["file"].endswith("scenariotrace_control_flow.py")
    # --github annotations ride the same findings
    capsys.readouterr()
    lint_main(
        ["--root", ROOT, "--github", _fixture("scenariotrace_control_flow.py")]
    )
    out = capsys.readouterr().out
    assert "::error file=" in out and "ktpu-lint[scenariotrace]" in out


# --- contract-prover v2: doc sync --------------------------------------------

# Deliberate negatives in tests (never real flags).
_DOC_SYNC_ALLOW = {"KTPU_NOT_REGISTERED"}


def test_flag_doc_sync():
    """Every registered flag appears in README/DESIGN, and every KTPU_* /
    KUBERNETRIKS_* token in docs, bench and tests resolves to a
    registered flag (or a registered-prefix family like KTPU_SWEEP_*) —
    renamed tuners can no longer leave stale documentation behind."""
    import glob
    import re

    from kubernetriks_tpu import flags

    docs = ""
    for p in ("README.md", os.path.join("docs", "DESIGN.md")):
        docs += open(os.path.join(ROOT, p), encoding="utf-8").read()
    undocumented = [n for n in flags.REGISTRY if n not in docs]
    assert not undocumented, (
        f"flags missing from README/DESIGN: {undocumented} — document "
        "them (the README 'Environment flags' table is the catch-all)"
    )

    scan = [os.path.join(ROOT, "README.md"), os.path.join(ROOT, "bench.py")]
    scan += glob.glob(os.path.join(ROOT, "docs", "*.md"))
    scan += glob.glob(os.path.join(ROOT, "tests", "*.py"))
    scan += glob.glob(os.path.join(ROOT, "scripts", "*.py"))
    bad = {}
    for path in scan:
        text = open(path, encoding="utf-8").read()
        for tok in set(re.findall(r"\b(?:KTPU|KUBERNETRIKS)_[A-Z0-9_]*", text)):
            name = tok.rstrip("_")
            if name in flags.REGISTRY or tok in _DOC_SYNC_ALLOW:
                continue
            # KTPU_SWEEP_* style family references resolve to a prefix
            if tok.endswith("_") and any(
                k.startswith(tok) for k in flags.REGISTRY
            ):
                continue
            bad.setdefault(os.path.relpath(path, ROOT), []).append(tok)
    assert not bad, f"unregistered flag tokens in docs/tests: {bad}"
