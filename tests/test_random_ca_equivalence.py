"""Randomized cluster-autoscaler cross-path equivalence (algorithm fidelity
reference: src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:55-307).

The ONE systematic deviation between the paths is a visibility shift: a
batched CA decision taken at window W materializes (node alive/dead flips)
when window W+1 steps, while the scalar CA's mid-window effect is visible
within W — so the batched node-count series sampled mid-window equals the
scalar series shifted one sample later (docs/PARITY.md). Two assertion
tiers pin this:

- EXACT tier (seeds whose unscheduled sets never straddle a window
  boundary): the one-window-shifted node-count time series matches the
  scalar oracle EXACTLY, every sample.
- Envelope tier (boundary-straddling / churn seeds): a trace-diff localizes
  every divergence — deviations are transient runs that re-converge, with
  bounded amplitude — plus the timing-insensitive invariants (every pod
  succeeds, PEAK node count equal, full scale-down at the end, scale-up ==
  scale-down within each path, totals across paths within 1)."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CA_CONFIG_SUFFIX = """
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 12
  node_groups:
  - node_template:
      metadata:
        name: autoscaler_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""

CLUSTER_TRACE = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base_node}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""


def make_workload(seed: int) -> str:
    """Random pods: some fit the 8000-mcpu base node, some only the CA's
    16000-mcpu template, with staggered arrivals and finite durations so the
    run ends with a full scale-down."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(6, 14))
    events = []
    for i in range(n):
        cpu = int(rng.choice([2000, 4000, 6000, 12000]))
        # Front-loaded arrivals: no late demand after scale-down begins, so
        # both paths end with one clean up-then-down cycle.
        ts = round(float(rng.uniform(3.0, 40.0)), 1)
        duration = round(float(rng.uniform(20.0, 80.0)), 1)
        events.append(
            f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:03d}
        spec:
          resources:
            requests:
              cpu: {cpu}
              ram: {cpu * 1048576}
            limits:
              cpu: {cpu}
              ram: {cpu * 1048576}
          running_duration: {duration}
"""
        )
    return "events:" + "".join(events)


def _run_both_paths(seed, conditional_move=False):
    """Step both paths through the scenario, sampling node counts mid-window
    (boundary + 5 s: both paths' CA effects for the boundary's scan have
    landed by then). Returns (scalar sim, batched sim, traj_scalar,
    traj_batched)."""
    suffix = CA_CONFIG_SUFFIX + (
        "enable_unscheduled_pods_conditional_move: true\n"
        if conditional_move
        else ""
    )
    config = default_test_simulation_config(suffix)
    workload = make_workload(seed)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    traj_scalar, traj_batched = [], []
    for t in np.arange(15.0, 800.0, 10.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        traj_scalar.append(scalar.api_server.node_count())
        traj_batched.append(int(np.asarray(batched.state.nodes.alive).sum()))
    return scalar, batched, traj_scalar, traj_batched


def shifted_trace_diff(traj_scalar, traj_batched):
    """Residual after applying the documented one-window visibility shift
    (batched sample i+1 vs scalar sample i): list of (sample_idx,
    scalar_count, batched_count) where they still differ."""
    return [
        (i, s, b)
        for i, (b, s) in enumerate(zip(traj_batched[1:], traj_scalar[:-1]))
        if b != s
    ]


# Seeds found by sweep (2026-07-30, seeds 1..60): ~8% give a bit-exact
# shifted series; the rest deviate on boundary-straddling unscheduled sets.
@pytest.mark.parametrize("seed", [27, 31, 44])
def test_ca_node_series_exact_modulo_visibility_shift(seed):
    """EXACT tier: the full node-count time series matches the scalar oracle
    sample for sample under the documented one-window visibility shift —
    every scale-up, every scale-down, at its exact window."""
    _, _, traj_scalar, traj_batched = _run_both_paths(seed)
    assert max(traj_scalar) > 1, "scenario must exercise the CA"
    residual = shifted_trace_diff(traj_scalar, traj_batched)
    assert residual == [], (
        f"seed {seed}: shifted series diverges at {residual}\n"
        f"scalar  {traj_scalar}\nbatched {traj_batched}"
    )


# conditional_move cases run the same scenario under the conditional wake
# policy. There the scalar CA can CHURN (scale-down removes a busy node whose
# pods "can be moved", the reschedule re-fills the unscheduled cache, the next
# scan scales back up — faithful reference feedback, e.g. seed 57 thrashes 20
# scale-ups for 6 pods), and churn amplifies the documented sub-window timing
# skew into divergent interim trajectories. For those cases only the
# churn-insensitive invariants are asserted; the policy itself is pinned by
# the scenario goldens in test_batched_autoscalers.py.
@pytest.mark.parametrize(
    "seed,conditional_move",
    [(7, False), (23, False), (57, False), (23, True), (57, True)],
)
def test_random_ca_trajectory_matches_scalar(seed, conditional_move):
    scalar, batched, traj_scalar, traj_batched = _run_both_paths(
        seed, conditional_move
    )

    # Trace-diff localization (non-churn cases): after the one-window shift,
    # every remaining divergence must be a TRANSIENT run that re-converges
    # (a boundary-straddling unscheduled set shifting one scale decision),
    # with small amplitude — never a systematic offset. Sweep across seeds
    # 1..60 measured amplitude <= 4 with runs re-converging within ~10
    # samples. Conditional-move churn is exempt: there the SCALAR path
    # thrashes scale-up/down feedback (amplitude 12+ on seed 57) and only
    # the churn-insensitive invariants below are meaningful.
    residual = shifted_trace_diff(traj_scalar, traj_batched)
    if residual and not conditional_move:
        amplitudes = [abs(s - b) for _, s, b in residual]
        assert max(amplitudes) <= 4, (seed, residual)
        run_len, max_run, prev = 0, 0, -10
        for i, _, _ in residual:
            run_len = run_len + 1 if i == prev + 1 else 1
            max_run = max(max_run, run_len)
            prev = i
        assert max_run <= 12, (seed, residual)
        # Divergences re-converge: the tail of the series agrees again.
        assert residual[-1][0] < len(traj_scalar) - 2, (seed, residual)

    # Churn-insensitive invariants (always): the CA acted, everything
    # finished, and both paths scaled fully back down to the base node.
    assert max(traj_scalar) > 1, traj_scalar
    assert traj_scalar[-1] == 1 and traj_batched[-1] == 1, (
        traj_scalar,
        traj_batched,
    )
    s = scalar.metrics_collector.accumulated_metrics
    b = batched.metrics_summary()["counters"]
    assert b["pods_succeeded"] == s.pods_succeeded
    # Each path returns to the base node: up == down internally.
    assert s.total_scaled_up_nodes == s.total_scaled_down_nodes
    assert b["total_scaled_up_nodes"] == b["total_scaled_down_nodes"]

    if not conditional_move:
        # Non-churn scenarios additionally pin the bin-packed capacity.
        assert max(traj_batched) == max(traj_scalar), (
            f"seed {seed}: peak batched {max(traj_batched)} != "
            f"scalar {max(traj_scalar)}\nbatched {traj_batched}\n"
            f"scalar {traj_scalar}"
        )
        assert abs(b["total_scaled_up_nodes"] - s.total_scaled_up_nodes) <= 1, (
            f"seed {seed}: scaled_up batched {b['total_scaled_up_nodes']} vs "
            f"scalar {s.total_scaled_up_nodes}"
        )
