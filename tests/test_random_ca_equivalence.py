"""Randomized cluster-autoscaler cross-path EXACT equivalence (algorithm
fidelity reference: src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:55-307).

Round 4 retired the old "one-window visibility shift" framing: the batched
CA now reproduces the scalar trajectory sample-for-sample with no shift and
no tolerance envelope, because it models

- the TRUE drifting cadence (the scalar re-arms scan_interval after the
  info round-trip returns, so the period is round_trip + scan_interval and
  cycles drift across windows; autoscale.ca_pass docstring),
- the storage-snapshot time s_k = fire + as_to_ca + as_to_ps, including
  sub-window finish visibility on BOTH sides of the window boundary and
  pre-cycle shadows for snapshots that precede this window's
  commit-visibility time,
- node-NAME-ordered scale-down candidate walks and re-placement first-fits
  (info.nodes is name-sorted in the scalar),
- name-ordered unscheduled-cache bin-packing for scale-up,
- per-EVENT conditional-move wake scans (one greedy budget scan per
  node-add / freed event at its effect time, not a pooled window scan), and
- reschedule queue order for removed nodes (removal time, then removal
  emission order, then pod name).

Sampling uses BatchedSimulation.node_count_at, which resolves pending
create/remove effects at the sample time (the lazy window application is an
implementation detail, not an observable).

A 60-seed sweep of this scenario (plus the conditional-move variant on the
churn seeds) passes bit-exactly; the suite pins a representative subset.
"""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CA_CONFIG_SUFFIX = """
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 12
  node_groups:
  - node_template:
      metadata:
        name: autoscaler_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""

CLUSTER_TRACE = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base_node}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""


def make_workload(seed: int) -> str:
    """Random pods: some fit the 8000-mcpu base node, some only the CA's
    16000-mcpu template, with staggered arrivals and finite durations so the
    run ends with a full scale-down."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(6, 14))
    events = []
    for i in range(n):
        cpu = int(rng.choice([2000, 4000, 6000, 12000]))
        # Front-loaded arrivals: no late demand after scale-down begins, so
        # both paths end with one clean up-then-down cycle.
        ts = round(float(rng.uniform(3.0, 40.0)), 1)
        duration = round(float(rng.uniform(20.0, 80.0)), 1)
        events.append(
            f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:03d}
        spec:
          resources:
            requests:
              cpu: {cpu}
              ram: {cpu * 1048576}
            limits:
              cpu: {cpu}
              ram: {cpu * 1048576}
          running_duration: {duration}
"""
        )
    return "events:" + "".join(events)


def _run_both_paths(seed, conditional_move=False):
    """Step both paths through the scenario, sampling node counts mid-window
    (boundary + 5 s). Returns (scalar sim, batched sim, traj_scalar,
    traj_batched)."""
    suffix = CA_CONFIG_SUFFIX + (
        "enable_unscheduled_pods_conditional_move: true\n"
        if conditional_move
        else ""
    )
    config = default_test_simulation_config(suffix)
    workload = make_workload(seed)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    traj_scalar, traj_batched = [], []
    for t in np.arange(15.0, 800.0, 10.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        traj_scalar.append(scalar.api_server.node_count())
        traj_batched.append(batched.node_count_at(float(t)))
    return scalar, batched, traj_scalar, traj_batched


@pytest.mark.parametrize("seed", [1, 3, 6, 8, 27, 31, 44])
def test_ca_node_series_exact(seed):
    """The full node-count time series matches the scalar oracle EXACTLY,
    sample for sample — every scale-up, every scale-down, at its exact
    window, with NO shift and NO tolerance."""
    _, _, traj_scalar, traj_batched = _run_both_paths(seed)
    assert max(traj_scalar) > 1, "scenario must exercise the CA"
    assert traj_batched == traj_scalar, (
        f"seed {seed}\nscalar  {traj_scalar}\nbatched {traj_batched}"
    )


@pytest.mark.parametrize(
    "seed,conditional_move",
    [(7, False), (23, False), (57, False), (7, True), (23, True), (57, True)],
)
def test_random_ca_trajectory_matches_scalar(seed, conditional_move):
    """Exact trajectory equality including the conditional-move wake policy,
    on the seeds whose scalar path CHURNS (seed 57 thrashes up to the
    12-node quota and back through scale-down/reschedule feedback) — the
    cases the round-3 test could only bound with a tolerance envelope."""
    scalar, batched, traj_scalar, traj_batched = _run_both_paths(
        seed, conditional_move
    )
    assert traj_batched == traj_scalar, (
        f"seed {seed} cond={conditional_move}\n"
        f"scalar  {traj_scalar}\nbatched {traj_batched}"
    )

    # Churn-insensitive invariants, kept as a secondary net.
    c = batched.metrics_summary()["counters"]
    assert c["total_scaled_up_nodes"] == c["total_scaled_down_nodes"] + (
        traj_batched[-1] - 1
    )
    sm = scalar.metrics_collector.accumulated_metrics
    assert c["total_scaled_up_nodes"] == sm.total_scaled_up_nodes
    assert c["total_scaled_down_nodes"] == sm.total_scaled_down_nodes
