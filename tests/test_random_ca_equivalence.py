"""Randomized cluster-autoscaler cross-path equivalence: for generated
workloads that force scale-up (pods bigger than the base node) and scale-down
(everything finishes), the batched CA must match the scalar oracle on every
timing-insensitive invariant (algorithm fidelity reference:
src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:55-307).

Exact node-count trajectories are NOT asserted: batched CA decisions read
state at window boundaries while the scalar CA's scan interleaves mid-window
(docs/PARITY.md "documented behavioral deviations"), which legitimately
shifts individual scale events by a window and can split one scale-up
differently. What must agree regardless of that skew:
- every pod succeeds in both paths (scheduling outcome fidelity),
- the PEAK node count (the bin-packed capacity the demand requires),
- full scale-down back to the base node once the workload drains,
- scale-up == scale-down within each path, and the totals across paths
  within 1 (a boundary-straddling unscheduled set may provision one extra
  interim node)."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CA_CONFIG_SUFFIX = """
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 12
  node_groups:
  - node_template:
      metadata:
        name: autoscaler_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""

CLUSTER_TRACE = """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base_node}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""


def make_workload(seed: int) -> str:
    """Random pods: some fit the 8000-mcpu base node, some only the CA's
    16000-mcpu template, with staggered arrivals and finite durations so the
    run ends with a full scale-down."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(6, 14))
    events = []
    for i in range(n):
        cpu = int(rng.choice([2000, 4000, 6000, 12000]))
        # Front-loaded arrivals: no late demand after scale-down begins, so
        # both paths end with one clean up-then-down cycle.
        ts = round(float(rng.uniform(3.0, 40.0)), 1)
        duration = round(float(rng.uniform(20.0, 80.0)), 1)
        events.append(
            f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:03d}
        spec:
          resources:
            requests:
              cpu: {cpu}
              ram: {cpu * 1048576}
            limits:
              cpu: {cpu}
              ram: {cpu * 1048576}
          running_duration: {duration}
"""
        )
    return "events:" + "".join(events)


# conditional_move cases run the same scenario under the conditional wake
# policy. There the scalar CA can CHURN (scale-down removes a busy node whose
# pods "can be moved", the reschedule re-fills the unscheduled cache, the next
# scan scales back up — faithful reference feedback, e.g. seed 57 thrashes 20
# scale-ups for 6 pods), and churn amplifies the documented sub-window timing
# skew into divergent interim trajectories. For those cases only the
# churn-insensitive invariants are asserted; the policy itself is pinned by
# the scenario goldens in test_batched_autoscalers.py.
@pytest.mark.parametrize(
    "seed,conditional_move",
    [(7, False), (23, False), (57, False), (23, True), (57, True)],
)
def test_random_ca_trajectory_matches_scalar(seed, conditional_move):
    suffix = CA_CONFIG_SUFFIX + (
        "enable_unscheduled_pods_conditional_move: true\n"
        if conditional_move
        else ""
    )
    config = default_test_simulation_config(suffix)
    workload = make_workload(seed)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )

    traj_scalar, traj_batched = [], []
    # Sample mid-window (boundary + 5 s): both paths' CA effects for the
    # boundary's scan have landed by then (delays are sub-second). The
    # horizon leaves room for churny runs to settle back to the base node.
    for t in np.arange(15.0, 800.0, 10.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        traj_scalar.append(scalar.api_server.node_count())
        traj_batched.append(int(np.asarray(batched.state.nodes.alive).sum()))

    # Churn-insensitive invariants (always): the CA acted, everything
    # finished, and both paths scaled fully back down to the base node.
    assert max(traj_scalar) > 1, traj_scalar
    assert traj_scalar[-1] == 1 and traj_batched[-1] == 1, (
        traj_scalar,
        traj_batched,
    )
    s = scalar.metrics_collector.accumulated_metrics
    b = batched.metrics_summary()["counters"]
    assert b["pods_succeeded"] == s.pods_succeeded
    # Each path returns to the base node: up == down internally.
    assert s.total_scaled_up_nodes == s.total_scaled_down_nodes
    assert b["total_scaled_up_nodes"] == b["total_scaled_down_nodes"]

    if not conditional_move:
        # Non-churn scenarios additionally pin the bin-packed capacity.
        assert max(traj_batched) == max(traj_scalar), (
            f"seed {seed}: peak batched {max(traj_batched)} != "
            f"scalar {max(traj_scalar)}\nbatched {traj_batched}\n"
            f"scalar {traj_scalar}"
        )
        assert abs(b["total_scaled_up_nodes"] - s.total_scaled_up_nodes) <= 1, (
            f"seed {seed}: scaled_up batched {b['total_scaled_up_nodes']} vs "
            f"scalar {s.total_scaled_up_nodes}"
        )
