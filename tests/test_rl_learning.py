"""Learning proof (VERDICT r3 item 1): PPO actually learns a placement
strategy that beats both its own untrained init and the KubeScheduler
baseline on the bimodal fragmentation scenario.

The scenario (rl/evaluate.py make_proof_sim) is built so that placement
strategy — not capacity — decides outcomes: LeastAllocatedResources
(the kube default, reference src/scheduler/plugin.rs:33-63) spreads
long-lived small pods over every node, fragmenting the cluster below the
full-node large-pod request; best-fit packing leaves whole nodes free.
The full 120-iteration record with the learning curve is
docs/RL_LEARNING.json (scripts/train_rl_proof.py); this test runs a
shortened training (the policy locks onto the packing optimum within a
few iterations under potential-style shaping) and gates the claim.
"""

import jax
import numpy as np
import pytest

from kubernetriks_tpu.rl.evaluate import (
    PROOF_LARGE,
    PROOF_WINDOWS,
    eval_kube,
    eval_policy,
    make_proof_sim,
)
from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer

TRAIN_SEED_BASE = 11_000
HELDOUT_SEED_BASE = 91_000


@pytest.mark.slow
def test_ppo_learns_to_beat_kube_and_untrained():
    windows = np.arange(PROOF_WINDOWS, dtype=np.int32)
    train_sim = make_proof_sim(TRAIN_SEED_BASE, 32)
    trainer = PPOTrainer(
        train_sim,
        windows_per_rollout=PROOF_WINDOWS,
        config=PPOConfig(
            learning_rate=3e-4,
            gamma=0.995,
            gae_lambda=0.97,
            epochs_per_iteration=4,
            reward_size_weighted=True,
            shaping_coef=0.2,
        ),
        seed=0,
    )

    heldout = make_proof_sim(HELDOUT_SEED_BASE, 32)

    def greedy_eval():
        return eval_policy(
            heldout, trainer.policy_apply, trainer.params, windows,
            jax.random.PRNGKey(123), greedy=True, large_cpu=PROOF_LARGE["cpu"],
        )

    kube = eval_kube(
        make_proof_sim(HELDOUT_SEED_BASE, 32), windows,
        large_cpu=PROOF_LARGE["cpu"],
    )
    untrained = greedy_eval()
    for it in trainer.train(16):
        assert np.isfinite(it["policy_loss"])
    trained = greedy_eval()

    # vs the KubeScheduler baseline: the learned packing policy places the
    # large pods LeastAllocated strands (kube ~29% across the probe seeds).
    assert trained["large_placed_frac"] >= kube["large_placed_frac"] + 0.30, (
        trained, kube,
    )
    assert (
        trained["unschedulable_left_per_cluster"]
        < kube["unschedulable_left_per_cluster"]
    ), (trained, kube)
    assert trained["placements_per_cluster"] > kube["placements_per_cluster"]

    # vs its own untrained init (same architecture, same greedy readout):
    # materially fewer park decisions and shorter queues.
    assert trained["park_decisions_per_cluster"] <= (
        0.7 * untrained["park_decisions_per_cluster"]
    ), (trained, untrained)
    assert trained["mean_queue_time_s"] < untrained["mean_queue_time_s"], (
        trained, untrained,
    )
