"""Learning proof (VERDICT r3 item 1, tightened r5): PPO actually learns a
placement strategy that beats its own untrained init, the KubeScheduler
baseline, AND matches the best-fit packing heuristic — with both policy
heads (MLP and attention).

The scenario (rl/evaluate.py make_proof_sim) is built so that placement
strategy — not capacity — decides outcomes: LeastAllocatedResources
(the kube default, reference src/scheduler/plugin.rs:33-63) spreads
long-lived small pods over every node, fragmenting the cluster below the
full-node large-pod request; best-fit packing leaves whole nodes free.
The full 120-iteration records with learning curves are
docs/RL_LEARNING.json and docs/RL_LEARNING_ATTENTION.json
(scripts/train_rl_proof.py) — at full budget BOTH heads converge to the
best-fit heuristic's exact trajectory (large_placed 1.0, queue 5.79 s vs
kube's 6.20 s). This test runs a shortened training (the policy locks
onto the packing optimum within a few iterations under potential-style
shaping) and gates the claim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetriks_tpu.rl.evaluate import (
    PROOF_LARGE,
    PROOF_WINDOWS,
    bestfit_policy_apply,
    eval_kube,
    eval_policy,
    make_proof_sim,
)
from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer

TRAIN_SEED_BASE = 11_000
HELDOUT_SEED_BASE = 91_000


def _bestfit_apply(params, obs):
    """Best-fit packing baseline — the heuristic the policy should
    discover; upper-bound reference. Shared definition with the
    scheduler's "best_fit" device profile (rl/evaluate.py wraps the
    MostAllocatedResources scorer from the device-plugin registry)."""
    return bestfit_policy_apply(params, obs)


@pytest.mark.slow
@pytest.mark.parametrize("policy_kind,iterations", [("mlp", 16)])
def test_ppo_learns_to_beat_kube_and_match_bestfit(policy_kind, iterations):
    windows = np.arange(PROOF_WINDOWS, dtype=np.int32)
    train_sim = make_proof_sim(TRAIN_SEED_BASE, 32)
    trainer = PPOTrainer(
        train_sim,
        windows_per_rollout=PROOF_WINDOWS,
        config=PPOConfig(
            learning_rate=3e-4,
            gamma=0.995,
            gae_lambda=0.97,
            epochs_per_iteration=4,
            reward_size_weighted=True,
            shaping_coef=0.2,
        ),
        seed=0,
        policy_kind=policy_kind,
    )

    heldout = make_proof_sim(HELDOUT_SEED_BASE, 32)

    def greedy_eval(apply=None, params=None):
        return eval_policy(
            heldout,
            apply or trainer.policy_apply,
            trainer.params if apply is None else params,
            windows,
            jax.random.PRNGKey(123),
            greedy=True,
            large_cpu=PROOF_LARGE["cpu"],
        )

    kube = eval_kube(
        make_proof_sim(HELDOUT_SEED_BASE, 32), windows,
        large_cpu=PROOF_LARGE["cpu"],
    )
    bestfit = greedy_eval(_bestfit_apply, ())
    untrained = greedy_eval()
    for it in trainer.train(iterations):
        assert np.isfinite(it["policy_loss"])
    trained = greedy_eval()

    # vs the KubeScheduler baseline: the learned packing policy places the
    # large pods LeastAllocated strands (kube ~29% across the probe seeds)
    # AND beats kube's queue time — packing is not bought with latency.
    assert trained["large_placed_frac"] >= kube["large_placed_frac"] + 0.30, (
        trained, kube,
    )
    assert (
        trained["unschedulable_left_per_cluster"]
        < kube["unschedulable_left_per_cluster"]
    ), (trained, kube)
    assert trained["placements_per_cluster"] > kube["placements_per_cluster"]
    assert trained["mean_queue_time_s"] < kube["mean_queue_time_s"], (
        trained, kube,
    )

    # vs the best-fit heuristic (the r4 gap: trained attention reached only
    # 0.95 large-placed with WORSE queue time than best-fit; at adequate
    # budget both heads match the heuristic's trajectory): equal large-pod
    # placement within 5pt, queue time within 0.5 s.
    assert trained["large_placed_frac"] >= bestfit["large_placed_frac"] - 0.05, (
        trained, bestfit,
    )
    assert trained["mean_queue_time_s"] <= bestfit["mean_queue_time_s"] + 0.5, (
        trained, bestfit,
    )

    # vs its own untrained init (same architecture, same greedy readout):
    # materially fewer park decisions and shorter queues.
    assert trained["park_decisions_per_cluster"] <= (
        0.7 * untrained["park_decisions_per_cluster"]
    ), (trained, untrained)
    assert trained["mean_queue_time_s"] < untrained["mean_queue_time_s"], (
        trained, untrained,
    )


def test_attention_learning_record_matches_bestfit():
    """The attention head's full-budget record (docs/RL_LEARNING_ATTENTION.json,
    written by scripts/train_rl_proof.py --policy attention --iterations 120
    --clusters 128) shows convergence to the best-fit heuristic's trajectory —
    the r4 gap (95% large placed, worse queue than best-fit) was an
    under-training artifact. In-suite CPU training of the attention head to
    convergence costs ~20 min, so the suite gates the RECORD's claims; the
    MLP variant above trains live. Re-produce the record with the script to
    re-verify end to end."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "RL_LEARNING_ATTENTION.json",
    )
    with open(path) as fh:
        rec = json.load(fh)
    assert rec["scenario"]["policy"] == "attention"
    assert len(rec["curve"]) >= 100, "full-budget run expected"
    kube, bestfit, trained = (
        rec["kube_baseline"], rec["bestfit_heuristic"], rec["trained_greedy"]
    )
    assert trained["large_placed_frac"] >= bestfit["large_placed_frac"] - 0.05
    assert trained["large_placed_frac"] >= kube["large_placed_frac"] + 0.30
    assert trained["mean_queue_time_s"] <= bestfit["mean_queue_time_s"] + 0.5
    assert trained["mean_queue_time_s"] < kube["mean_queue_time_s"]
    assert (
        trained["unschedulable_left_per_cluster"]
        < kube["unschedulable_left_per_cluster"]
    )
    assert trained["placements_per_cluster"] >= bestfit["placements_per_cluster"]
