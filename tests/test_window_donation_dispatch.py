"""Composed-path dispatch & transfer overhaul guards.

1. DONATION EQUIVALENCE: the steady-state loop's donated, fused
   chunk+slide programs (step.run_windows_donated / run_windows_skip_donated,
   engine._fused_chunk_slide) update the full (C,N)/(C,P) state in place;
   a composed run (HPA + CA + sliding pod window) with donation + fusion ON
   must be BIT-IDENTICAL to the undonated, unfused two-dispatch-slide run —
   every simulation-state leaf exact, metric estimators exact (same
   programs' float op order), same slide trajectory (pod_base).

2. DISPATCH-COUNT REGRESSION: the steady-state sliding loop issues exactly
   popcount(span) device dispatches per slide span — each span's chunks are
   the greedy binary decomposition of its length, the span's LAST chunk
   carries the fused on-device slide (no separate shift/apply dispatches),
   and the only host sync per span is the asynchronous 4-byte shift
   readback at the span boundary (no per-chunk sync in the timed region).

3. The donated standalone autoscaler entry points
   (autoscale.hpa_pass_donated / ca_pass_donated) match the plain calls
   bit-for-bit.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states, tree_copy
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)
from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

COMPOSED_CONFIG_SUFFIX = """
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  autoscaler_type: kube_cluster_autoscaler
  scan_interval: 10.0
  max_node_count: 4
  node_groups:
  - node_template:
      metadata:
        name: ca_node
      status:
        capacity:
          cpu: 8000
          ram: 17179869184
"""

# HPA group whose load curve bursts past the base capacity: replicas park,
# the CA provisions template nodes, the load drop walks both back down.
GROUP_TRACE = """
events:
- timestamp: 49.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 8
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 4000, ram: 2147483648}
              limits: {cpu: 4000, ram: 2147483648}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 100.0
                total_load: 1.0
              - duration: 150.0
                total_load: 6.0
              - duration: 250.0
                total_load: 0.5
"""


def _build_composed(config_suffix="", **kwargs):
    config = default_test_simulation_config(COMPOSED_CONFIG_SUFFIX + config_suffix)
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=0.3,
        horizon=500.0,
        seed=7,
        cpu=2000,
        ram=2 * 1024**3,
        duration_range=(30.0, 90.0),
        name_prefix="plain",
    )
    workload = sorted(
        plain.convert_to_simulator_events()
        + GenericWorkloadTrace.from_yaml(GROUP_TRACE).convert_to_simulator_events(),
        key=lambda e: e[0],
    )
    # CPU defaults for both knobs are off (compile cost on a host backend);
    # this module is exactly the place that exercises them.
    kwargs.setdefault("fuse_slide", True)
    kwargs.setdefault("donate", True)
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload,
        n_clusters=2,
        max_pods_per_cycle=16,
        pod_window=64,
        fast_forward=False,
        **kwargs,
    )


@pytest.fixture(scope="module")
def composed_runs():
    donated = _build_composed()  # donate default + fused slide opt-in
    assert donated.donate and donated._fused_slide_ok()
    donated.precompile_chunks(max_chunk=16)  # also exercises scratch-copy warm-up
    for end in (150.0, 300.0, 450.0):
        donated.step_until_time(end)
    plain = _build_composed(donate=False, fuse_slide=False)
    assert not plain.donate and not plain._fused_slide_ok()
    for end in (150.0, 300.0, 450.0):
        plain.step_until_time(end)
    return donated, plain


def test_donated_composed_run_is_bit_identical(composed_runs):
    donated, plain = composed_runs
    # The run actually composes everything: slides happened, HPA scaled,
    # CA provisioned — otherwise this guard proves nothing.
    assert donated._pod_base > 0
    counters = donated.metrics_summary()["counters"]
    assert counters["total_scaled_up_pods"] > 0
    assert counters["total_scaled_up_nodes"] > 0
    # Donation really was in play on the steady loop.
    assert donated.dispatch_stats["fused_slides"] > 0
    assert plain.dispatch_stats["fused_slides"] == 0

    assert donated._pod_base == plain._pod_base
    assert compare_states(donated.state, plain.state) == []
    assert donated.metrics_summary() == plain.metrics_summary()


def test_autoscaler_entry_points_donated_match_plain(composed_runs):
    from kubernetriks_tpu.batched.autoscale import (
        ca_pass,
        ca_pass_donated,
        hpa_pass,
        hpa_pass_donated,
    )

    donated, _ = composed_runs
    state = donated.state
    st = donated.autoscale_statics
    W = jnp.full((donated.n_clusters,), donated.next_window_idx, jnp.int32)

    ref, ref_auto = hpa_pass(
        tree_copy(state), state.auto, st, W, donated.consts,
        seg=donated._hpa_seg,
    )
    ref = ref._replace(auto=ref_auto)
    got = hpa_pass_donated(
        tree_copy(state), st, W, donated.consts, seg=donated._hpa_seg
    )
    assert compare_states(ref, got) == []

    ref, ref_auto = ca_pass(
        tree_copy(state), state.auto, st, W, donated.consts,
        donated.max_ca_pods_per_cycle, donated.max_pods_per_scale_down,
    )
    ref = ref._replace(auto=ref_auto)
    got = ca_pass_donated(
        tree_copy(state), st, W, donated.consts,
        donated.max_ca_pods_per_cycle, donated.max_pods_per_scale_down,
    )
    assert compare_states(ref, got) == []


def _greedy_decomposition(span, ladder):
    out = []
    while span > 0:
        chunk = next(c for c in ladder if c <= span)
        out.append(chunk)
        span -= chunk
    return out


def test_steady_state_dispatch_counts():
    """popcount(span) dispatches per slide span, slide fused into the last
    chunk, no separate slide dispatches, one async shift sync per span."""
    from kubernetriks_tpu.batched.engine import _CHUNK_LADDER

    config = default_test_simulation_config()
    cluster = UniformClusterTrace(8, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=1.0,
        horizon=500.0,
        seed=5,
        cpu=1000,
        ram=1024**3,
        duration_range=(20.0, 40.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=2,
        max_pods_per_cycle=16,
        pod_window=128,
        fast_forward=False,
        fuse_slide=True,
        donate=True,
    )
    assert sim._fused_slide_ok()

    log = []
    orig = sim._dispatch_windows

    def recording(idxs, fuse_slide=False, freeze_lanes=False):
        log.append((len(idxs), fuse_slide))
        return orig(idxs, fuse_slide=fuse_slide, freeze_lanes=freeze_lanes)

    sim._dispatch_windows = recording
    sim.step_until_time(400.0)

    stats = sim.dispatch_stats
    assert stats["fused_slides"] > 0, "no slide span exercised"
    # Every slide ran fused into its span's last chunk: zero separate
    # shift/apply dispatches, and dispatch count == chunk count.
    assert stats["slide_dispatches"] == 0
    assert stats["window_chunks"] == len(log)
    # One host sync per span boundary (the async shift readback), none per
    # chunk.
    assert stats["slide_syncs"] == stats["fused_slides"]

    # Reconstruct slide spans: a fused dispatch closes a span. Each interior
    # span's chunks must be exactly the greedy binary decomposition of its
    # length — popcount(span) dispatches, no more.
    span_sizes = []
    for size, fused in log:
        span_sizes.append(size)
        if fused:
            span = sum(span_sizes)
            assert span_sizes == _greedy_decomposition(span, _CHUNK_LADDER)
            assert len(span_sizes) == bin(span).count("1")
            span_sizes = []
    # Trailing (target-reaching) span also follows the ladder decomposition.
    if span_sizes:
        assert span_sizes == _greedy_decomposition(sum(span_sizes), _CHUNK_LADDER)


def _build_dense_sliding(**kwargs):
    """Dense sliding-window trace for the superspan gate: 2 arrivals/s
    against a 64-slot window with short pod lifetimes — every span is a few
    windows long, so the ladder path pays a host sync every handful of
    windows and the superspan's K-for-1 sync economy is measurable."""
    config = default_test_simulation_config()
    cluster = UniformClusterTrace(8, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=500.0,
        seed=5,
        cpu=1000,
        ram=1024**3,
        duration_range=(20.0, 40.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=2,
        max_pods_per_cycle=16,
        pod_window=64,
        fast_forward=False,
        **kwargs,
    )


def test_superspan_dispatch_count_gate():
    """Superspan host-sync regression gate: the steady-state loop's ONLY
    host syncs are the one (4,)-int32 progress readback per run_superspan
    dispatch, so a run whose ladder twin slides n_slides times costs

        host_syncs <= ceil(n_slides / K) + O(1)

    (the O(1): step_until_time boundaries redispatch with a partial span
    budget). The acceptance bar: >= 4x fewer host syncs than the ladder
    path on the same dense sliding-window trace."""
    import math

    K = 8
    ss = _build_dense_sliding(superspan=True, superspan_k=K, superspan_chunk=8)
    assert ss._superspan_ok()
    ss.step_until_time(400.0)

    ladder = _build_dense_sliding(fuse_slide=True, donate=True)
    assert ladder._fused_slide_ok()
    ladder.step_until_time(400.0)

    # Same work completed — otherwise the sync comparison is meaningless.
    assert ss._pod_base == ladder._pod_base > 0
    assert ss.next_window_idx == ladder.next_window_idx
    n_slides = ladder.dispatch_stats["slide_syncs"]
    assert n_slides >= 8, "trace not dense enough for the gate to mean anything"
    # The device loop really completed multi-span dispatches (spans split at
    # a K-budget or target boundary count once, so this undercounts the
    # ladder's per-slide syncs — > K/2 per dispatch on average still proves
    # the scan is doing span work, not one-span-per-dispatch).
    assert ss.dispatch_stats["superspan_spans"] > 0

    syncs = ss.dispatch_stats["slide_syncs"]
    # Every superspan dispatch costs exactly one sync, and nothing else
    # syncs: no ladder chunks, no separate slide dispatches.
    assert syncs == ss.dispatch_stats["superspans"]
    assert ss.dispatch_stats["window_chunks"] == 0
    assert ss.dispatch_stats["slide_dispatches"] == 0
    # The gate: ceil(n_slides/K) + O(1), with the O(1) pinned small.
    assert syncs <= math.ceil(n_slides / K) + 2, (syncs, n_slides)
    # Acceptance bar: >= 4x fewer host syncs than the ladder path.
    assert 4 * syncs <= n_slides, (syncs, n_slides)
