"""Fault-domain gates for the serving fleet (batched/faults.py + the
fleet.py fault isolation of DESIGN §15).

1. TYPED OUTCOMES: the QueryError taxonomy carries the FleetResult
   readout protocol (`.ok` / `.kind` / `.query` / `.lane`), every class
   is a real Exception, and poll() streams errors under the same
   stream-once contract as results.
2. HOST CHAOS: the counter-seeded injector replays the exact same fault
   schedule per seed, the least-faulted victim rule covers every lane by
   construction, and `KTPU_HOST_CHAOS` parsing is loud on bad specs.
3. ISOLATION + QUARANTINE (module fixture, one scripted end-to-end run):
   a dispatch fault kills ONLY the victim lane's query — neighbors and
   every later query on the crash-reset lane bit-match a fault-free
   reference fleet; the faulted lane quarantines, backs off, probes and
   re-admits; the whole fault path moves no jit-cache count.
4. HOST BOUNDARIES: loud submit() validation naming the field, bounded
   admission (reject streams RejectedError with a retry-after hint;
   block pumps inline), queued-past-deadline failure without occupying a
   lane, graceful close() (drain in-flight, fail queued, refuse new).
5. STREAM-ONCE AUDIT: across the fixture's whole life — quiet, chaos,
   deadline, backpressure, shutdown — every submitted qid streamed
   exactly one terminal outcome through poll().
"""

import pytest

from kubernetriks_tpu.batched.faults import (
    DeadlineExceededError,
    FeederError,
    HostChaos,
    InjectedFault,
    LaneFaultError,
    QueryError,
    RejectedError,
    ShutdownError,
)
from kubernetriks_tpu.batched.fleet import (
    FleetResult,
    Scenario,
    ScenarioFleet,
    jit_cache_sizes,
)
from kubernetriks_tpu.test_util import default_test_simulation_config

from test_fleet import FAULT_SUFFIX, _composed_traces
from test_fleet_async import SCENS
from test_window_donation_dispatch import COMPOSED_CONFIG_SUFFIX


# --- the QueryError taxonomy (pure protocol, no engine) ----------------------


def test_query_outcome_protocol():
    """Results and errors share one discrimination protocol: `.ok` and a
    stable string `.kind` — a poll loop never needs isinstance ladders,
    and every error is a real Exception (raisable where no qid exists)."""
    assert FleetResult.ok is True and FleetResult.kind == "result"
    taxonomy = {
        RejectedError: "rejected",
        DeadlineExceededError: "deadline_exceeded",
        LaneFaultError: "lane_fault",
        FeederError: "feeder",
        ShutdownError: "shutdown",
    }
    for cls, kind in taxonomy.items():
        err = cls(7, "boom", lane=2)
        assert isinstance(err, QueryError) and isinstance(err, Exception)
        assert err.ok is False and err.kind == kind
        assert (err.query, err.lane, err.message) == (7, 2, "boom")
    # Kind-specific payloads.
    rej = RejectedError(1, "full", retry_after_s=0.25)
    assert rej.retry_after_s == 0.25
    lane = LaneFaultError(2, "died", cause=ValueError("xla"))
    assert isinstance(lane.cause, str) and "xla" in lane.cause  # repr'd
    feed = FeederError(3, "producer died", slab_lo=128, restarts=2)
    assert (feed.slab_lo, feed.restarts) == (128, 2)
    with pytest.raises(ShutdownError):
        raise ShutdownError(-1, "no qid to stream under")


# --- HostChaos: determinism, victim rule, flag parsing -----------------------


def test_host_chaos_flag_parsing_is_loud():
    for off in (None, "", "0", "false", "no", "off", "OFF"):
        assert HostChaos.from_flag(off) is None
    on = HostChaos.from_flag("1")
    assert (on.seed, on.dispatch_rate) == (7, 0.04)
    assert (on.feeder_rate, on.stall_rate, on.stall_ms) == (0.05, 0.03, 2.0)
    spec = HostChaos.from_flag("seed=3, dispatch=0.5, stall_ms=1.5")
    assert (spec.seed, spec.dispatch_rate, spec.stall_ms) == (3, 0.5, 1.5)
    assert spec.feeder_rate == 0.05  # unspecified keys keep the defaults
    with pytest.raises(ValueError, match="unknown key 'bogus'"):
        HostChaos.from_flag("bogus=1")
    with pytest.raises(ValueError, match="key=value"):
        HostChaos.from_flag("just-noise")


def test_host_chaos_schedule_is_a_pure_function_of_the_seed():
    def schedule(seed):
        chaos = HostChaos(seed=seed, dispatch_rate=0.3, stall_rate=0.3)
        return [
            (chaos.dispatch_fault([0, 1, 2]), chaos.stall_s())
            for _ in range(40)
        ]

    assert schedule(7) == schedule(7)  # replayable
    assert schedule(7) != schedule(8)  # and actually seeded
    hits = [v for v, _ in schedule(7) if v is not None]
    assert hits, "rate 0.3 over 40 draws produced no faults (vacuous)"


def test_host_chaos_victim_rule_covers_every_lane():
    """The least-faulted rule (ties to the lowest index): coverage is by
    construction, even when the active set shrinks mid-run — the shrunk
    set's survivor still gets faulted, and a re-grown set resumes at its
    least-faulted member."""
    chaos = HostChaos(seed=1, dispatch_rate=1.0)
    assert [chaos.dispatch_fault([0, 1, 2]) for _ in range(3)] == [0, 1, 2]
    assert chaos.dispatch_fault([0, 1, 2]) == 0  # wraps to least-faulted
    shrunk = HostChaos(seed=1, dispatch_rate=1.0)
    assert shrunk.dispatch_fault([0, 1]) == 0
    assert shrunk.dispatch_fault([1]) == 1
    assert shrunk.dispatch_fault([1]) == 1
    assert shrunk.dispatch_fault([0, 1, 2]) == 2  # never-faulted lane
    assert shrunk.dispatch_fault([]) is None  # nothing active, no fault
    assert shrunk.events["dispatch_faults"] == 4


def test_host_chaos_stall_and_feeder_channels():
    chaos = HostChaos(seed=2, stall_rate=1.0, stall_ms=5.0)
    assert chaos.stall_s() == pytest.approx(0.005)
    assert HostChaos(seed=2).stall_s() == 0.0  # rate 0: no draw, no stall
    killer = HostChaos(seed=2, feeder_rate=1.0)
    assert killer.feeder_kill() is True
    assert HostChaos(seed=2).feeder_kill() is False
    rep = killer.report()
    assert rep["seed"] == 2 and rep["events"]["feeder_kills"] == 1
    assert set(rep["rates"]) == {"dispatch", "feeder", "stall"}


# --- the scripted end-to-end fault run (module fixture) ----------------------


class ScriptedInjector:
    """Duck-typed HostChaos stand-in that faults EXACTLY the scripted
    lanes, in order, whenever the head of the script is active — the
    surgical control the isolation gates need (the probabilistic
    injector is covered above and by bench.py --host-chaos)."""

    def __init__(self, script):
        self.script = list(script)
        self.seed = -1  # InjectedFault's message interpolates it
        self.faults = 0

    def stall_s(self):
        return 0.0

    def feeder_kill(self):
        return False

    def dispatch_fault(self, active_lanes):
        if self.script and self.script[0] in {int(v) for v in active_lanes}:
            self.faults += 1
            return self.script.pop(0)
        return None

    def report(self):
        return {
            "seed": self.seed,
            "rates": {},
            "events": {"dispatch_faults": self.faults},
        }


@pytest.fixture(scope="module")
def fault_run():
    """One reference fleet (fault-free) + one chaos fleet driven through
    every fault domain in sequence: quiet A/B, scripted lane faults with
    quarantine/probe/re-admission, an expired deadline, bounded
    admission (reject + block), and a graceful close with work queued.
    Every poll() outcome is tallied for the stream-once audit."""
    config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    cluster_events, workload = _composed_traces()

    def build(**kw):
        return ScenarioFleet(
            config,
            cluster_events,
            workload,
            n_lanes=3,
            horizon=450.0,
            max_pods_per_cycle=16,
            use_pallas=False,
            ca_slot_multiplier=4,
            lane_async=True,
            **kw,
        )

    art = {}
    ref = build()
    ref_qids = [ref.submit(s, h) for s, h in SCENS]
    ref.run_async()
    ref.poll()
    art["ref_results"] = [ref.results[q] for q in ref_qids]

    fl = build(quarantine_faults=1, quarantine_window=64, quarantine_backoff=2)
    outcome_counts = {}

    def drain_poll():
        polled = fl.poll()
        for o in polled:
            outcome_counts[o.query] = outcome_counts.get(o.query, 0) + 1
        return polled

    # Phase 1 — QUIET: aggressive quarantine thresholds configured, no
    # injector armed. Must bit-match the plain reference fleet.
    quiet_qids = [fl.submit(s, h) for s, h in SCENS]
    fl.run_async()
    drain_poll()
    art["quiet_results"] = [fl.results[q] for q in quiet_qids]
    art["quiet_stats"] = dict(fl.engine.dispatch_stats)
    art["ref_stats"] = dict(ref.engine.dispatch_stats)
    art["quiet_report"] = fl.fault_report()

    # Phase 2 — CHAOS: script one fault on lane 0, then one on lane 1.
    # quarantine_faults=1 means each fault fires a quarantine; the
    # 2-round backoff expires mid-stream, so both lanes probe and
    # re-admit before the queue dries.
    sizes_before = jit_cache_sizes()
    injector = ScriptedInjector([0, 1])
    fl.arm_host_chaos(injector)
    chaos_qids = [fl.submit(s, h) for s, h in SCENS + SCENS]
    states_seen = set()
    while fl.pending or fl._active:
        fl.pump()
        states_seen.update(fl.lane_states())
    art["chaos_states_seen"] = states_seen
    art["chaos_outcomes"] = drain_poll()
    art["chaos_qids"] = chaos_qids
    art["chaos_results"] = [fl.results[q] for q in chaos_qids]
    art["chaos_report"] = fl.fault_report()
    art["jit_cache_moved"] = {
        k: (sizes_before[k], v)
        for k, v in jit_cache_sizes().items()
        if sizes_before.get(k) != v
    }
    fl.arm_host_chaos(None)

    # Phase 3 — DEADLINE: expired-on-arrival query fails at the next
    # pump boundary without ever occupying a lane.
    art["deadline_qid"] = fl.submit(SCENS[0][0], 150.0, deadline_s=1e-9)
    fl.run_async()
    art["deadline_outcomes"] = drain_poll()

    # Phase 4 — BOUNDED ADMISSION: reject streams a typed refusal with a
    # retry-after hint; block pumps inline until a slot frees.
    fl.max_queue, fl.queue_policy = 1, "reject"
    art["accepted_qid"] = fl.submit(*SCENS[0])
    art["rejected_qid"] = fl.submit(*SCENS[1])
    art["rejected_outcomes"] = drain_poll()  # streamed before any pump
    fl.queue_policy = "block"
    art["blocked_qids"] = [fl.submit(*SCENS[i]) for i in range(3)]
    art["queue_depth_after_block"] = fl.pending
    fl.run_async()
    drain_poll()
    fl.max_queue, fl.queue_policy = None, "reject"

    # Phase 5 — GRACEFUL CLOSE: 5 queries over 3 lanes, one pump (all
    # lanes in flight, 2 queued), then close(drain=True).
    shut_qids = [fl.submit(s, h) for s, h in SCENS]
    fl.pump()
    art["in_flight_at_close"] = sorted(
        q for q, _, _ in fl._active.values()
    )
    fl.close()
    art["shut_qids"] = shut_qids
    art["shutdown_outcomes"] = drain_poll()
    art["outcome_counts"] = outcome_counts
    art["n_submitted"] = fl._next_query
    art["final_report"] = fl.fault_report()

    yield ref, fl, art
    ref.close()


def test_quiet_robustness_layer_is_free(fault_run):
    """Quarantine thresholds configured + injector unarmed = the exact
    pre-fault-domain fleet: bit-identical per-query results and equal
    engine dispatch_stats against the plain reference."""
    _, _, art = fault_run
    for i, (rq, rr) in enumerate(
        zip(art["quiet_results"], art["ref_results"])
    ):
        assert rq.ok and rr.ok
        assert (
            rq.counters == rr.counters
            and rq.hpa_replicas == rr.hpa_replicas
            and rq.ca_nodes == rr.ca_nodes
        ), f"quiet query {i} diverges from the plain reference fleet"
    assert art["quiet_stats"] == art["ref_stats"]
    rep = art["quiet_report"]
    assert rep["chaos"] is None and rep["failed"] == {}
    assert rep["availability"] == 1.0


def test_lane_fault_is_isolated_to_the_victim_query(fault_run):
    """Poison isolation: exactly the two scripted queries die (typed
    LaneFaultError naming the lane and cause), every OTHER chaos-phase
    query — including later queries re-seeded onto the crash-reset
    lanes — bit-matches the fault-free reference."""
    _, _, art = fault_run
    fails = [r for r in art["chaos_results"] if not r.ok]
    assert len(fails) == 2
    assert sorted(f.lane for f in fails) == [0, 1]
    for f in fails:
        assert isinstance(f, LaneFaultError) and f.kind == "lane_fault"
        assert "InjectedFault" in f.cause and "crash-reset" in f.message
        assert f.scenario is not None and f.horizon is not None
    for i, r in enumerate(art["chaos_results"]):
        if not r.ok:
            continue
        ref_r = art["ref_results"][i % len(SCENS)]
        assert (
            r.counters == ref_r.counters
            and r.hpa_replicas == ref_r.hpa_replicas
            and r.ca_nodes == ref_r.ca_nodes
        ), f"chaos-phase query {i} diverged after a NEIGHBOR lane fault"
    rep = art["chaos_report"]
    assert rep["failed"] == {"lane_fault": 2}
    assert rep["chaos"]["events"]["dispatch_faults"] == 2


def test_quarantine_fires_probes_and_readmits(fault_run):
    """The quarantine lifecycle: both faulted lanes leave the admission
    rotation (the states were observable mid-run), probe after the
    backoff, complete their probe query and re-admit — ending idle with
    no quarantine residue."""
    _, fl, art = fault_run
    assert {"quarantined", "probe", "active"} <= art["chaos_states_seen"]
    rep = art["chaos_report"]
    assert rep["quarantine_events"] == 2
    assert rep["readmissions"] == 2
    assert rep["lane_states"] == ["idle"] * 3
    assert fl._quarantine == {}  # no residue after re-admission


def test_fault_path_moves_no_jit_cache_count(fault_run):
    """Crash recovery is pure data ops: lane_reset + a zeroed plan reuse
    the admission path's compiled programs — the whole chaos phase moves
    no jit-cache count."""
    _, _, art = fault_run
    assert art["jit_cache_moved"] == {}, (
        "the fault/quarantine path RECOMPILED jit entries: "
        f"{art['jit_cache_moved']}"
    )


def test_deadline_fails_queued_query_without_a_lane(fault_run):
    _, _, art = fault_run
    (out,) = art["deadline_outcomes"]
    assert out.query == art["deadline_qid"]
    assert isinstance(out, DeadlineExceededError)
    assert out.lane == -1 and out.late_s >= 0.0
    assert "without" in out.message and "lane" in out.message


def test_bounded_admission_reject_and_block(fault_run):
    """policy='reject': the refused qid streams a RejectedError (with a
    retry-after hint once service times exist) BEFORE any pump —
    admission refusal is immediate. policy='block': submit() pumps
    inline until a slot frees, so the queue never exceeds the bound and
    everything completes."""
    _, fl, art = fault_run
    outs = {o.query: o for o in art["rejected_outcomes"]}
    rej = outs[art["rejected_qid"]]
    assert isinstance(rej, RejectedError) and rej.kind == "rejected"
    assert "queue full" in rej.message and "'reject'" in rej.message
    assert rej.retry_after_s is not None and rej.retry_after_s > 0.0
    assert art["accepted_qid"] not in outs  # accepted, not yet complete
    assert art["queue_depth_after_block"] <= 1
    for qid in [art["accepted_qid"]] + art["blocked_qids"]:
        assert fl.results[qid].ok, f"backpressured query {qid} failed"


def test_graceful_close_drains_in_flight_and_fails_queued(fault_run):
    """close(drain=True): the three in-flight queries finish with real
    results; the two still-queued fail with typed ShutdownErrors; new
    submits raise ShutdownError; poll() keeps working on host state."""
    _, fl, art = fault_run
    outs = {o.query: o for o in art["shutdown_outcomes"]}
    shut = art["shut_qids"]
    for qid in art["in_flight_at_close"]:
        assert outs[qid].ok, f"in-flight query {qid} was not drained"
    queued = [q for q in shut if q not in art["in_flight_at_close"]]
    assert len(queued) == 2
    for qid in queued:
        assert isinstance(outs[qid], ShutdownError)
        assert "queued at close()" in outs[qid].message
    with pytest.raises(ShutdownError, match="after close"):
        fl.submit(*SCENS[0])
    assert fl.poll() == []  # the stream stays functional after close


def test_every_submitted_qid_streamed_exactly_one_outcome(fault_run):
    """The stream-once audit across the fixture's WHOLE life — quiet,
    chaos, deadline, backpressure, shutdown: every qid ever submitted
    delivered exactly one terminal outcome through poll(), result and
    typed error alike (no hangs, no duplicates)."""
    _, _, art = fault_run
    counts = art["outcome_counts"]
    bad = {
        q: counts.get(q, 0)
        for q in range(art["n_submitted"])
        if counts.get(q, 0) != 1
    }
    assert not bad, f"qids without exactly one streamed outcome: {bad}"
    rep = art["final_report"]
    assert rep["submitted"] == art["n_submitted"]
    assert rep["completed"] + sum(rep["failed"].values()) == rep["submitted"]


# --- loud submit() validation (uses the open reference fleet) ----------------


def test_submit_validation_names_the_field(fault_run):
    """Malformed queries are caller bugs, rejected BEFORE admission with
    a ValueError naming the field and the legal range — never in-flight
    poison at a reseed boundary."""
    ref, _, _ = fault_run
    with pytest.raises(ValueError, match=r"unknown scenario key.*'warp'"):
        ref.submit({"warp": 9.0}, 100.0)
    with pytest.raises(ValueError, match=r"scenario\['ca_threshold'\].*SCALAR"):
        ref.submit({"ca_threshold": [0.5, 0.6]}, 100.0)
    with pytest.raises(ValueError, match=r"scenario\['hpa_tolerance'\].*>= 0"):
        ref.submit({"hpa_tolerance": -0.25}, 100.0)
    with pytest.raises(ValueError, match="Scenario or a mapping"):
        ref.submit(42, 100.0)
    for bad_h in (0, -5.0, float("nan"), "soon"):
        with pytest.raises(ValueError, match="horizon must be a finite"):
            ref.submit(Scenario(), bad_h)
    with pytest.raises(ValueError, match="deadline_s must be a finite"):
        ref.submit(Scenario(), 100.0, deadline_s=0.0)
    with pytest.raises(ValueError, match="trace_rows"):
        ref.submit(Scenario(), 100.0, trace_rows=(4, 2))
    # Nothing above was admitted: the queue is still empty.
    assert ref.pending == 0
