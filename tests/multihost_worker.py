"""Worker for the two-process DCN harness (tests/test_multihost.py).

Each of the two processes owns 4 virtual CPU devices; jax.distributed glues
them into one 8-device world (gloo CPU collectives stand in for DCN), so the
CROSS-process branches of parallel/multihost.py — put_global assembling a
global array from per-process shards, to_host allgathering non-addressable
shards — execute for real, followed by a BatchedSimulation stepping SPMD on
the cross-process mesh.

Run: python multihost_worker.py <process_id> <coordinator_port>
Prints ROUNDTRIP_OK / ENGINE_OK lines consumed by the launcher test.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(pid: int, port: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from kubernetriks_tpu.parallel.multihost import initialize_from_env

    assert initialize_from_env(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from kubernetriks_tpu.parallel.multihost import (
        global_mesh,
        is_cross_process,
        put_global,
        to_host,
    )

    mesh = global_mesh()
    assert is_cross_process(mesh)

    # put_global -> to_host roundtrip through the non-addressable branches.
    host = np.arange(64, dtype=np.int32).reshape(8, 8)
    sharding = NamedSharding(mesh, PartitionSpec("clusters", None))
    g = put_global({"x": host}, {"x": sharding})["x"]
    assert not g.is_fully_addressable
    np.testing.assert_array_equal(to_host(g), host)
    print(f"ROUNDTRIP_OK {pid}", flush=True)

    # Engine end-to-end on the cross-process mesh: trace upload via
    # put_global, SPMD window stepping, metric readout via allgather.
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: mh2\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5,
        horizon=60.0,
        seed=2,
        cpu=2000,
        ram=4 * 1024**3,
        duration_range=(10.0, 30.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=16,
        max_pods_per_cycle=8,
        mesh=mesh,
    )
    assert not sim.state.pods.phase.is_fully_addressable
    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["processed_nodes"] == 4 * 16, counters
    assert counters["scheduling_decisions"] > 0
    print(f"ENGINE_OK {pid} {counters['scheduling_decisions']}", flush=True)

    # Sliding pod window ACROSS processes: device-resident slides (the
    # shift amount is a replicated scalar every process reads identically)
    # plus an in-place window growth, vs an unsharded local reference.
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    # 30 long-running head pods force growth (16 -> 128 < the 160-slot
    # plain segment); once they finish, the short tail slides the grown
    # window (so BOTH cross-process growth and cross-process slides run).
    slide_workload = GenericWorkloadTrace.from_yaml(
        "events:"
        + "".join(
            f"""
- timestamp: {1 + i}
  event_type:
    !CreatePod
      pod:
        metadata: {{name: p_{i:03d}}}
        spec:
          resources:
            requests: {{cpu: 100, ram: 104857600}}
            limits: {{cpu: 100, ram: 104857600}}
          running_duration: {100.0 if i < 30 else 15.0}
"""
            for i in range(160)
        )
    ).convert_to_simulator_events()

    def build_sliding(**kw):
        return build_batched_from_traces(
            config,
            cluster.convert_to_simulator_events(),
            slide_workload,
            n_clusters=16,
            max_pods_per_cycle=8,
            **kw,
        )

    ref = build_sliding()  # local, unsharded, full-resident
    ref.step_until_time(400.0)
    ssim = build_sliding(mesh=mesh, pod_window=16)
    assert ssim._device_slide is not None
    assert not ssim.state.pods.phase.is_fully_addressable
    ssim.step_until_time(400.0)
    # The 30 long-running head pods forced growth past 16; the short tail
    # then slid the grown window.
    assert ssim.pod_window > 16, "window never grew"
    assert ssim._pod_base > 0, "window never slid"
    sc = ssim.metrics_summary()["counters"]
    assert sc == ref.metrics_summary()["counters"], (
        sc, ref.metrics_summary()["counters"],
    )
    print(
        f"SLIDING_OK {pid} {ssim.pod_window} {ssim._pod_base}", flush=True
    )


if __name__ == "__main__":
    main(int(sys.argv[1]), sys.argv[2])
