"""Worker for the two-process DCN harness (tests/test_multihost.py).

Each of the two processes owns 4 virtual CPU devices; jax.distributed glues
them into one 8-device world (gloo CPU collectives stand in for DCN), so the
CROSS-process branches of parallel/multihost.py — put_global assembling a
global array from per-process shards, to_host allgathering non-addressable
shards — execute for real, followed by a BatchedSimulation stepping SPMD on
the cross-process mesh.

Run: python multihost_worker.py <process_id> <coordinator_port>
Prints ROUNDTRIP_OK / ENGINE_OK lines consumed by the launcher test.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(pid: int, port: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from kubernetriks_tpu.parallel.multihost import initialize_from_env

    assert initialize_from_env(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from kubernetriks_tpu.parallel.multihost import (
        global_mesh,
        is_cross_process,
        put_global,
        to_host,
    )

    mesh = global_mesh()
    assert is_cross_process(mesh)

    # put_global -> to_host roundtrip through the non-addressable branches.
    host = np.arange(64, dtype=np.int32).reshape(8, 8)
    sharding = NamedSharding(mesh, PartitionSpec("clusters", None))
    g = put_global({"x": host}, {"x": sharding})["x"]
    assert not g.is_fully_addressable
    np.testing.assert_array_equal(to_host(g), host)
    print(f"ROUNDTRIP_OK {pid}", flush=True)

    # Engine end-to-end on the cross-process mesh: trace upload via
    # put_global, SPMD window stepping, metric readout via allgather.
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: mh2\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5,
        horizon=60.0,
        seed=2,
        cpu=2000,
        ram=4 * 1024**3,
        duration_range=(10.0, 30.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=16,
        max_pods_per_cycle=8,
        mesh=mesh,
    )
    assert not sim.state.pods.phase.is_fully_addressable
    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["processed_nodes"] == 4 * 16, counters
    assert counters["scheduling_decisions"] > 0
    print(f"ENGINE_OK {pid} {counters['scheduling_decisions']}", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), sys.argv[2])
