"""Scenario-vector fleet gates (batched/fleet.py + the per-lane statics).

1. HOMOGENEOUS IDENTITY: a scenario build whose vectors all carry the base
   config's values is bit-identical to the scalar-config build (state
   compare + dispatch_stats equality) — the vectorization changed the
   SHAPE of the parameter leaves, never their meaning.
2. HETEROGENEOUS ORACLE EQUIVALENCE: a mixed-parameter fleet matches N
   independent scalar-oracle runs lane by lane — the HPA replica
   trajectory under per-lane (scan_interval, tolerance) and the CA node
   trajectory under per-lane (scan_interval, threshold, as_to_ca delay),
   sampled exactly like test_random_hpa_equivalence /
   test_random_ca_equivalence.
3. LANE PERMUTATION: the same scenario placed in different lanes (and the
   same fleet with its lanes shuffled) produces bit-identical per-lane
   state rows and metrics — with chaos ON (per-lane fault seeds make a
   lane's fault stream a function of its scenario, not its lane index).
4. WAVE RESET: queries beyond the lane count pack into waves over the
   SAME resident engine — wave-2 results bit-match wave-1's for equal
   scenarios, and no jit entry recompiles after the first wave.
"""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.fleet import (
    Scenario,
    ScenarioFleet,
    jit_cache_sizes,
    scenario_vectors,
)
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

from test_random_ca_equivalence import (
    CA_CONFIG_SUFFIX,
    CLUSTER_TRACE as CA_CLUSTER_TRACE,
    make_workload as make_ca_workload,
)
from test_random_hpa_equivalence import (
    CLUSTER_TRACE as HPA_CLUSTER_TRACE,
    make_workload as make_hpa_workload,
)
from test_window_donation_dispatch import (
    COMPOSED_CONFIG_SUFFIX,
    GROUP_TRACE,
)

FAULT_SUFFIX = """
fault_injection:
  enabled: true
  node:
    mttf: 400.0
    mttr: 60.0
  pod:
    fail_prob: 0.2
    restart_limit: 2
"""


def _composed_traces():
    cluster = UniformClusterTrace(4, cpu=16000, ram=32 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=0.3,
        horizon=400.0,
        seed=7,
        cpu=2000,
        ram=2 * 1024**3,
        duration_range=(30.0, 90.0),
        name_prefix="plain",
    )
    workload = sorted(
        plain.convert_to_simulator_events()
        + GenericWorkloadTrace.from_yaml(GROUP_TRACE).convert_to_simulator_events(),
        key=lambda e: e[0],
    )
    return cluster.convert_to_simulator_events(), workload


def _apply_scenario_to_config(config, scen: Scenario):
    """Scalar-oracle view of one scenario: its overrides as plain config
    scalars (the shape bench.py's per-engine baseline builds too)."""
    from kubernetriks_tpu.config import (
        KubeClusterAutoscalerConfig,
        KubeHorizontalPodAutoscalerConfig,
    )

    if scen.hpa_scan_interval is not None:
        config.horizontal_pod_autoscaler.scan_interval = scen.hpa_scan_interval
    if scen.hpa_tolerance is not None:
        config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
            KubeHorizontalPodAutoscalerConfig(
                target_threshold_tolerance=scen.hpa_tolerance
            )
        )
    if scen.hpa_enabled is not None:
        config.horizontal_pod_autoscaler.enabled = scen.hpa_enabled
    if scen.ca_scan_interval is not None:
        config.cluster_autoscaler.scan_interval = scen.ca_scan_interval
    if scen.ca_threshold is not None:
        config.cluster_autoscaler.kube_cluster_autoscaler = (
            KubeClusterAutoscalerConfig(
                scale_down_utilization_threshold=scen.ca_threshold
            )
        )
    if scen.ca_max_node_count is not None:
        config.cluster_autoscaler.max_node_count = scen.ca_max_node_count
    if scen.as_to_ca_network_delay is not None:
        config.as_to_ca_network_delay = scen.as_to_ca_network_delay
    return config


def _lane_rows(sim, lane):
    """Every state leaf's row for one lane, as host arrays keyed by path —
    the per-lane bit-identity comparator for lane-permutation gates."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(sim.state)
    return {
        jax.tree_util.keystr(path): np.asarray(leaf)[lane]
        for path, leaf in flat
    }


def _assert_lane_rows_equal(rows_a, rows_b, ctx):
    assert rows_a.keys() == rows_b.keys()
    for key in rows_a:
        np.testing.assert_array_equal(
            rows_a[key], rows_b[key], err_msg=f"{ctx}: lane rows differ at {key}"
        )


# --- 1. homogeneous identity ------------------------------------------------


def test_homogeneous_vectors_bit_identical_to_scalar_config_build():
    """scenario=None and an explicit all-base-values scenario build the
    same statics and run bit-identically with equal dispatch_stats: the
    (C,)-vectorization is a pure re-shaping of the parameter leaves."""
    config = default_test_simulation_config(COMPOSED_CONFIG_SUFFIX)
    cluster_events, workload = _composed_traces()

    def build(scenario):
        return build_batched_from_traces(
            config,
            cluster_events,
            workload,
            n_clusters=2,
            max_pods_per_cycle=16,
            scenario=scenario,
        )

    plain = build(None)
    neutral = build(dict(scenario_vectors(config, 2)))
    for end in (150.0, 300.0, 450.0):
        plain.step_until_time(end)
        neutral.step_until_time(end)
    mismatches = compare_states(plain.state, neutral.state)
    assert not mismatches, mismatches
    assert plain.dispatch_stats == neutral.dispatch_stats
    # The statics leaves really are per-lane vectors on BOTH builds.
    assert plain.autoscale_statics.hpa_interval.win.shape == (2,)
    assert plain.autoscale_statics.ca_threshold.shape == (2,)


# --- 2. heterogeneous oracle equivalence ------------------------------------


def test_heterogeneous_hpa_fleet_matches_scalar_oracles():
    """Per-lane (hpa_tolerance, hpa_enabled): each lane's replica
    trajectory equals an independent scalar-oracle run with those
    scalars, sampled at every 60 s boundary (the
    test_random_hpa_equivalence protocol, heterogenized). Scan-interval
    heterogeneity is pinned against independent BATCHED builds in the
    next test: at non-default scan intervals the scalar HPA reads the
    60 s metrics-collection cycle's latest (possibly stale) sample while
    the batched path samples at the HPA tick itself — a pre-existing
    modeling deviation documented in docs/PARITY.md, not a fleet
    property."""
    scens = [
        Scenario(),
        Scenario(hpa_tolerance=0.02),
        Scenario(hpa_tolerance=0.4),
        Scenario(hpa_enabled=False),
    ]
    workload = make_hpa_workload(29)
    base = default_test_simulation_config()
    base.horizontal_pod_autoscaler.enabled = True

    batched = build_batched_from_traces(
        base,
        GenericClusterTrace.from_yaml(HPA_CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=len(scens),
        scenario=dict(scenario_vectors(base, len(scens), scens)),
    )
    scalars = []
    for scen in scens:
        cfg = default_test_simulation_config()
        cfg.horizontal_pod_autoscaler.enabled = True
        sim = KubernetriksSimulation(_apply_scenario_to_config(cfg, scen))
        sim.initialize(
            GenericClusterTrace.from_yaml(HPA_CLUSTER_TRACE),
            GenericWorkloadTrace.from_yaml(workload),
        )
        scalars.append(sim)

    trajs_scalar = [[] for _ in scens]
    trajs_batched = [[] for _ in scens]
    for t in np.arange(61.0, 960.0, 60.0):
        batched.step_until_time(float(t))
        for lane, sim in enumerate(scalars):
            sim.step_until_time(float(t))
            hpa = sim.horizontal_pod_autoscaler
            if hpa is None:
                # Scalar with HPA off has no autoscaler component; the
                # group's replica count stays at the trace's initial
                # creation burst — the batched lane must report exactly
                # that (its pg_active_from parks at +inf).
                trajs_scalar[lane].append(
                    int(np.asarray(batched.autoscale_statics.pg_initial)[lane, 0])
                )
            else:
                groups = hpa.pod_groups
                trajs_scalar[lane].append(
                    len(groups["pod_group_1"].created_pods)
                    if "pod_group_1" in groups
                    else 0
                )
            trajs_batched[lane].append(
                batched.hpa_replicas(lane)["pod_group_1"]
            )
    for lane in range(len(scens)):
        assert trajs_batched[lane] == trajs_scalar[lane], (
            f"lane {lane} ({scens[lane]}):\n"
            f"scalar  {trajs_scalar[lane]}\nbatched {trajs_batched[lane]}"
        )
    # The scenarios really diverged from each other (non-vacuous fleet).
    assert len({tuple(t) for t in trajs_scalar}) > 1
    # The disabled lane stayed parked at the initial replica count.
    assert set(trajs_batched[3]) == {trajs_batched[3][0]}


def test_heterogeneous_hpa_scan_fleet_matches_independent_builds():
    """Per-lane hpa_scan_interval: every fleet lane is bit-identical to
    an INDEPENDENT scalar-config batched build with that scan interval —
    the vectorized cadence is exactly the scalar-config cadence, lane by
    lane (the scalar-ORACLE comparison lives in
    test_heterogeneous_hpa_scan_fleet_matches_scalar below, unblocked by
    the r14 collection latch)."""
    scans = [60.0, 30.0, 120.0]
    workload = make_hpa_workload(17)
    base = default_test_simulation_config()
    base.horizontal_pod_autoscaler.enabled = True
    cluster_ev = GenericClusterTrace.from_yaml(
        HPA_CLUSTER_TRACE
    ).convert_to_simulator_events()
    workload_ev = GenericWorkloadTrace.from_yaml(
        workload
    ).convert_to_simulator_events()

    fleet = build_batched_from_traces(
        base,
        cluster_ev,
        workload_ev,
        n_clusters=len(scans),
        scenario=dict(
            scenario_vectors(
                base,
                len(scans),
                [Scenario(hpa_scan_interval=s) for s in scans],
            )
        ),
    )
    solos = []
    for s in scans:
        cfg = default_test_simulation_config()
        cfg.horizontal_pod_autoscaler.enabled = True
        cfg.horizontal_pod_autoscaler.scan_interval = s
        solos.append(
            build_batched_from_traces(cfg, cluster_ev, workload_ev, n_clusters=1)
        )

    trajs_fleet = [[] for _ in scans]
    trajs_solo = [[] for _ in scans]
    for t in np.arange(61.0, 660.0, 30.0):
        fleet.step_until_time(float(t))
        for lane, solo in enumerate(solos):
            solo.step_until_time(float(t))
            trajs_fleet[lane].append(fleet.hpa_replicas(lane)["pod_group_1"])
            trajs_solo[lane].append(solo.hpa_replicas(0)["pod_group_1"])
    for lane, s in enumerate(scans):
        assert trajs_fleet[lane] == trajs_solo[lane], (
            f"lane {lane} (scan {s}):\n"
            f"solo  {trajs_solo[lane]}\nfleet {trajs_fleet[lane]}"
        )
    assert len({tuple(t) for t in trajs_fleet}) > 1, (
        "scan intervals did not diverge the trajectories (vacuous)"
    )


def test_heterogeneous_hpa_scan_fleet_matches_scalar():
    """Lane-by-lane SCALAR-oracle equivalence at non-default HPA scan
    intervals — the case the per-lane scan vectors surfaced and the
    documented metrics-staleness deviation used to block (PARITY.md): the
    scalar HPA reads the collector's 60 s sample, not a fresh evaluation
    at its own tick. With the r14 collection latch (AutoscaleState
    col_*), every fleet lane's replica trajectory must now equal an
    independent scalar run at that lane's scan interval — including the
    same-instant FIFO rule (a scan-120 cycle at a shared collection
    instant fires BEFORE the collection, its event id is older)."""
    from kubernetriks_tpu.sim.simulator import KubernetriksSimulation

    scans = [30.0, 90.0, 120.0]
    workload = make_hpa_workload(17)
    base = default_test_simulation_config()
    base.horizontal_pod_autoscaler.enabled = True
    cluster_ev = GenericClusterTrace.from_yaml(
        HPA_CLUSTER_TRACE
    ).convert_to_simulator_events()
    workload_ev = GenericWorkloadTrace.from_yaml(
        workload
    ).convert_to_simulator_events()
    fleet = build_batched_from_traces(
        base,
        cluster_ev,
        workload_ev,
        n_clusters=len(scans),
        scenario=dict(
            scenario_vectors(
                base,
                len(scans),
                [Scenario(hpa_scan_interval=s) for s in scans],
            )
        ),
    )
    scalars = []
    for s in scans:
        cfg = default_test_simulation_config()
        cfg.horizontal_pod_autoscaler.enabled = True
        cfg.horizontal_pod_autoscaler.scan_interval = s
        sim = KubernetriksSimulation(cfg)
        sim.initialize(
            GenericClusterTrace.from_yaml(HPA_CLUSTER_TRACE),
            GenericWorkloadTrace.from_yaml(workload),
        )
        scalars.append(sim)

    trajs_fleet = [[] for _ in scans]
    trajs_scalar = [[] for _ in scans]
    for t in np.arange(61.0, 660.0, 30.0):
        fleet.step_until_time(float(t))
        for lane, sim in enumerate(scalars):
            sim.step_until_time(float(t))
            trajs_fleet[lane].append(fleet.hpa_replicas(lane)["pod_group_1"])
            trajs_scalar[lane].append(
                len(
                    sim.horizontal_pod_autoscaler.pod_groups[
                        "pod_group_1"
                    ].created_pods
                )
            )
    for lane, s in enumerate(scans):
        assert trajs_fleet[lane] == trajs_scalar[lane], (
            f"lane {lane} (scan {s}):\n"
            f"scalar {trajs_scalar[lane]}\nfleet  {trajs_fleet[lane]}"
        )
        assert len(set(trajs_scalar[lane])) > 1, "trajectory never moved"


def test_heterogeneous_ca_fleet_matches_scalar_oracles():
    """Per-lane (ca_scan_interval, ca_threshold, as_to_ca delay): each
    lane's node-count trajectory equals an independent scalar-oracle run
    (the test_random_ca_equivalence protocol, heterogenized — including
    the drifting cadence, which now drifts per lane)."""
    scens = [
        Scenario(),
        Scenario(ca_threshold=0.8),
        Scenario(ca_scan_interval=25.0),
        Scenario(as_to_ca_network_delay=0.35),
    ]
    workload = make_ca_workload(8)
    base = default_test_simulation_config(CA_CONFIG_SUFFIX)

    batched = build_batched_from_traces(
        base,
        GenericClusterTrace.from_yaml(CA_CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=len(scens),
        scenario=dict(scenario_vectors(base, len(scens), scens)),
    )
    scalars = []
    for scen in scens:
        cfg = default_test_simulation_config(CA_CONFIG_SUFFIX)
        sim = KubernetriksSimulation(_apply_scenario_to_config(cfg, scen))
        sim.initialize(
            GenericClusterTrace.from_yaml(CA_CLUSTER_TRACE),
            GenericWorkloadTrace.from_yaml(workload),
        )
        scalars.append(sim)

    trajs_scalar = [[] for _ in scens]
    trajs_batched = [[] for _ in scens]
    for t in np.arange(15.0, 600.0, 10.0):
        batched.step_until_time(float(t))
        for lane, sim in enumerate(scalars):
            sim.step_until_time(float(t))
            trajs_scalar[lane].append(sim.api_server.node_count())
            trajs_batched[lane].append(batched.node_count_at(float(t), lane))
    for lane in range(len(scens)):
        assert trajs_batched[lane] == trajs_scalar[lane], (
            f"lane {lane} ({scens[lane]}):\n"
            f"scalar  {trajs_scalar[lane]}\nbatched {trajs_batched[lane]}"
        )
    assert max(trajs_scalar[0]) > 1, "scenario must exercise the CA"
    assert len({tuple(t) for t in trajs_scalar}) > 1


# --- 3 + 4. lane permutation, chaos on, waves + zero recompiles -------------


@pytest.fixture(scope="module")
def chaos_fleet_runs():
    """Two fleets over the composed+chaos scenario whose query lists are
    lane-PERMUTED (and carry a duplicate scenario), each run for two
    waves — the shared engine-pair every permutation/wave gate reads.

    KTPU_EXPLAIN_RECOMPILES=1 is set for the whole fixture: both fleets
    arm the recompile sentinel, so every post-warm-up wave the gates
    below exercise runs under an expect_none guard — a compile during a
    wave would raise RecompileError naming the jit entry (the runtime
    cross-check of the compile-once contract the zero-recompile gate
    pins by cache counts)."""
    import os

    os.environ["KTPU_EXPLAIN_RECOMPILES"] = "1"
    config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    cluster_events, workload = _composed_traces()

    def build_and_run(order):
        fleet = ScenarioFleet(
            config,
            cluster_events,
            workload,
            n_lanes=3,
            horizon=450.0,
            max_pods_per_cycle=16,
            use_pallas=False,
            # Chaos churn consumes the never-reclaimed CA slot reserve
            # across waves faster than a single run; widen it so the
            # strict divergence bound stays quiet.
            ca_slot_multiplier=4,
        )
        results = fleet.sweep([SCENS[i] for i in order])
        return fleet, results

    # Scenario 0 appears twice (lanes 0 and 2 of wave 1); scenario 3 rides
    # wave 2 — fleet B runs the same multiset in a different lane order
    # and wave split.
    SCENS = [
        Scenario(fault_seed=11, hpa_scan_interval=30.0),
        Scenario(fault_seed=22, ca_threshold=0.7),
        Scenario(fault_seed=11, hpa_scan_interval=30.0),  # dup of 0
        Scenario(fault_seed=33, hpa_tolerance=0.25),
    ]
    try:
        fleet_a, res_a = build_and_run([0, 1, 2, 3])
        fleet_b, res_b = build_and_run([3, 2, 1, 0])
        yield SCENS, fleet_a, res_a, fleet_b, res_b
        fleet_a.close()
        fleet_b.close()
    finally:
        os.environ.pop("KTPU_EXPLAIN_RECOMPILES", None)


def test_lane_permutation_bit_identical(chaos_fleet_runs):
    """Same scenario, different lane / different fleet order -> identical
    per-lane counters (chaos on: the fault stream follows the scenario's
    seed, not the lane index)."""
    scens, fleet_a, res_a, fleet_b, res_b = chaos_fleet_runs
    # Fault machinery really engaged (non-vacuous chaos gate).
    total_faults = sum(
        r.counters["pod_restarts"] + r.counters["node_crashes"]
        for r in res_a
    )
    assert total_faults > 0, "chaos fleet produced no faults"
    # In-fleet duplicate: scenario 0 == scenario 2, different lanes.
    assert res_a[0].lane != res_a[2].lane
    assert res_a[0].counters == res_a[2].counters
    assert res_a[0].hpa_replicas == res_a[2].hpa_replicas
    # Cross-fleet permutation: query i of A ran scens[i]; query j of B ran
    # scens[perm[j]] — match by scenario identity.
    order_b = [3, 2, 1, 0]
    for i, scen in enumerate(scens):
        j = order_b.index(i)
        assert res_a[i].counters == res_b[j].counters, (
            f"scenario {i} differs between lane {res_a[i].lane} (A) and "
            f"lane {res_b[j].lane} (B)"
        )
        assert res_a[i].ca_nodes == res_b[j].ca_nodes


def test_lane_permutation_state_rows_bit_identical(chaos_fleet_runs):
    """Beyond counters: the duplicate scenario's full per-lane STATE rows
    (every pod/node/metric leaf) are bit-identical across lanes at the
    final wave boundary. Both fleets' last waves run scenarios {3} (A)
    and {0} (B) — compare the full state rows of the wave-1 lanes via
    the recorded results instead, which carry identical counters; the
    state-row comparison runs within fleet A's final state for its own
    last wave's idle lanes (base scenario) vs fleet B's."""
    scens, fleet_a, res_a, fleet_b, res_b = chaos_fleet_runs
    # Final wave of A ran [scens[3]] in lane 0 (+ 2 idle base lanes);
    # final wave of B ran [scens[0]] in lane 0. The idle lanes (1, 2) of
    # both fleets ran the BASE scenario for the same span -> their full
    # state rows must match bit-for-bit across the two fleets.
    rows_a1 = _lane_rows(fleet_a.engine, 1)
    rows_a2 = _lane_rows(fleet_a.engine, 2)
    rows_b1 = _lane_rows(fleet_b.engine, 1)
    _assert_lane_rows_equal(rows_a1, rows_a2, "idle lanes within fleet A")
    _assert_lane_rows_equal(rows_a1, rows_b1, "idle lanes across fleets")


def test_wave_reset_and_zero_recompiles(chaos_fleet_runs):
    """Wave packing: 4 queries over 3 lanes = 2 waves on ONE resident
    engine; a repeat of wave-1's scenario in a later wave bit-matches,
    and re-running a scenario stream triggers no recompile."""
    scens, fleet_a, res_a, _, _ = chaos_fleet_runs
    assert fleet_a.waves_run == 2
    assert {r.wave for r in res_a} == {0, 1}
    sizes0 = jit_cache_sizes()
    res_rerun = fleet_a.sweep([scens[0], scens[3]])
    sizes1 = jit_cache_sizes()
    assert sizes0 == sizes1, {
        k: (sizes0[k], sizes1[k]) for k in sizes0 if sizes0[k] != sizes1[k]
    }
    # The re-run wave reproduces the original waves' results exactly.
    assert res_rerun[0].counters == res_a[0].counters
    assert res_rerun[1].counters == res_a[3].counters


def test_wave_sentinel_armed_and_quiet(chaos_fleet_runs):
    """KTPU_EXPLAIN_RECOMPILES=1 (fixture-scoped) really armed the
    sentinel: the fleets carry one, and another post-warm-up wave runs
    quiet under its expect_none guard (a compile would raise
    RecompileError naming the jit entry — pinned the other way by
    tests/test_recompile.py's shape-drift gate)."""
    scens, fleet_a, res_a, _, _ = chaos_fleet_runs
    assert fleet_a._sentinel is not None, (
        "ScenarioFleet did not arm the recompile sentinel under "
        "KTPU_EXPLAIN_RECOMPILES=1"
    )
    rerun = fleet_a.sweep([scens[1]])
    assert rerun[0].counters == res_a[1].counters


def test_per_lane_fault_seed_matches_standalone_run(chaos_fleet_runs):
    """A lane's chaos stream is a pure function of its scenario: lane
    (seed 22) inside the 3-lane fleet == a standalone 1-lane fleet run
    with the same seed (the scalar-keying generalization: draws key on
    (seed, cluster 0), not the lane index)."""
    scens, fleet_a, res_a, _, _ = chaos_fleet_runs
    config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    cluster_events, workload = _composed_traces()
    solo = ScenarioFleet(
        config,
        cluster_events,
        workload,
        n_lanes=1,
        horizon=450.0,
        max_pods_per_cycle=16,
        use_pallas=False,
        ca_slot_multiplier=4,
    )
    try:
        r = solo.sweep([scens[1]])[0]
        assert r.counters == res_a[1].counters
        assert r.hpa_replicas == res_a[1].hpa_replicas
    finally:
        solo.close()


def test_update_scenario_requires_fleet_build():
    """A scenario-less engine refuses late scenario updates (its consts
    pytree may lack the fault_seed leaf — a late update would
    shadow-compile next to the existing programs)."""
    config = default_test_simulation_config(COMPOSED_CONFIG_SUFFIX)
    cluster_events, workload = _composed_traces()
    sim = build_batched_from_traces(
        config, cluster_events, workload, n_clusters=1, max_pods_per_cycle=16
    )
    with pytest.raises(ValueError, match="scenario"):
        sim.update_scenario({"hpa_scan_interval": 30.0})
    with pytest.raises(ValueError, match="fleet"):
        sim.fleet_reset()


def test_scenario_validation():
    from kubernetriks_tpu.batched.fleet import normalize_scenario

    with pytest.raises(KeyError, match="unknown scenario key"):
        normalize_scenario({"bogus": 1.0}, 2)
    with pytest.raises(ValueError, match="shape"):
        normalize_scenario({"hpa_scan_interval": np.zeros(3)}, 2)
    out = normalize_scenario({"hpa_scan_interval": 30.0}, 2)
    np.testing.assert_array_equal(out["hpa_scan_interval"], [30.0, 30.0])
