"""Alibaba v2017 trace parsing and conversion
(port of reference src/trace/alibaba_cluster_trace_v2017 tests)."""

import pytest

from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest, RemoveNodeRequest
from kubernetriks_tpu.trace.alibaba import (
    AlibabaClusterTraceV2017,
    AlibabaWorkloadTraceV2017,
    CPU_BASE,
    DENORMALIZATION_BASE,
    read_batch_instances,
    read_batch_tasks,
    read_machine_events,
)


def test_batch_instance_parsing():
    """reference: workload.rs:220-243."""
    rows = read_batch_instances(
        "41562,41618,120,686,299,Terminated,1,1,1.5,0.29,1.0,1.2\n"
    )
    inst = rows[0]
    assert inst.start_timestamp == 41562
    assert inst.end_timestamp == 41618
    assert inst.job_id == 120
    assert inst.task_id == 686
    assert inst.machine_id == 299
    assert inst.status == "Terminated"


def test_batch_task_parsing():
    """reference: workload.rs:245-262."""
    tasks = read_batch_tasks("10718,12897,15,64,2003,Terminated,50,0.01600704061294748\n")
    task = tasks[64]
    assert task.task_create_time == 10718
    assert task.number_of_instances == 2003
    assert task.cpus_requested_per_instance == 50
    assert task.normalized_memory_per_instance == pytest.approx(0.01600704061294748)


def test_optional_fields_parse_as_none():
    """reference: workload.rs:264-311."""
    rows = read_batch_instances("0,,120,686,,Interrupted,1,1,,,,\n")
    inst = rows[0]
    assert inst.start_timestamp == 0
    assert inst.end_timestamp is None
    assert inst.machine_id is None

    tasks = read_batch_tasks("6036,6046,4,6,452,Waiting,,\n")
    assert tasks[6].cpus_requested_per_instance is None
    assert tasks[6].normalized_memory_per_instance is None


def test_duplicate_task_id_raises():
    with pytest.raises(ValueError):
        read_batch_tasks(
            "1,2,3,64,1,Terminated,50,0.5\n1,2,3,64,1,Terminated,50,0.5\n"
        )


def test_workload_conversion_filters_and_converts():
    """Invalid rows (missing/<=0/start>=end timestamps, missing task or
    resources) are dropped; units convert santicores x10 and normalized mem
    x128 GiB (reference: workload.rs:56-120)."""
    instances = read_batch_instances(
        "\n".join(
            [
                "41562,41618,120,686,299,Terminated,1,1",  # valid
                ",41618,120,686,299,Interrupted,1,1",  # missing start
                "41562,,120,686,299,Interrupted,1,1",  # missing end
                "41700,41600,120,686,299,Terminated,1,1",  # start >= end
                "0,41618,120,686,299,Terminated,1,1",  # start <= 0
                "41562,41618,120,999,299,Terminated,1,1",  # unknown task
                "41562,41618,121,700,299,Terminated,1,1",  # task lacks resources
            ]
        )
    )
    tasks = read_batch_tasks(
        "10718,12897,15,686,1,Terminated,50,0.25\n10718,12897,15,700,1,Terminated,,\n"
    )
    trace = AlibabaWorkloadTraceV2017(instances, tasks)
    events = trace.convert_to_simulator_events()
    assert len(events) == 1
    ts, event = events[0]
    assert ts == 41562.0
    assert isinstance(event, CreatePodRequest)
    pod = event.pod
    assert pod.metadata.name == "120_686_0"
    assert pod.spec.resources.requests.cpu == 500  # 50 santicores -> 500 millicores
    assert pod.spec.resources.requests.ram == int(0.25 * DENORMALIZATION_BASE)
    assert pod.spec.running_duration == 56.0


def test_cluster_conversion_add_and_errors():
    """`add` creates; soft/hard errors remove once; ghost removals skipped
    (reference: cluster.rs:128-201)."""
    events = read_machine_events(
        "\n".join(
            [
                "10,1,add,,64,0.69",
                "20,2,add,,32,0.5",
                "30,1,softerror,disk,,",
                "40,1,harderror,disk,,",  # already removed - dedup
                "50,99,softerror,agent,,",  # ghost node - skip
            ]
        )
    )
    trace = AlibabaClusterTraceV2017(events)
    converted = trace.convert_to_simulator_events()
    assert len(converted) == 3
    assert isinstance(converted[0][1], CreateNodeRequest)
    node = converted[0][1].node
    assert node.metadata.name == "alibaba_node_1"
    assert node.status.capacity.cpu == 64 * CPU_BASE
    assert node.status.capacity.ram == int(0.69 * DENORMALIZATION_BASE)
    assert isinstance(converted[2][1], RemoveNodeRequest)
    assert converted[2][1].node_name == "alibaba_node_1"


def test_unknown_machine_event_type_raises():
    trace = AlibabaClusterTraceV2017(read_machine_events("10,1,explode,,64,0.69\n"))
    with pytest.raises(ValueError):
        trace.convert_to_simulator_events()


def test_generators():
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        SyntheticWorkloadTrace,
        UniformClusterTrace,
    )

    workload = SyntheticWorkloadTrace(pod_count=100, seed=1)
    events = workload.convert_to_simulator_events()
    assert len(events) == 100
    assert all(events[i][0] <= events[i + 1][0] for i in range(99))

    # Same seed -> identical trace.
    again = SyntheticWorkloadTrace(pod_count=100, seed=1).convert_to_simulator_events()
    assert [(ts, e.pod.metadata.name, e.pod.spec.resources.requests.cpu) for ts, e in events] == [
        (ts, e.pod.metadata.name, e.pod.spec.resources.requests.cpu) for ts, e in again
    ]

    poisson = PoissonWorkloadTrace(rate_per_second=1.0, horizon=100.0, seed=2)
    pevents = poisson.convert_to_simulator_events()
    assert 50 < len(pevents) < 200
    cluster = UniformClusterTrace(10)
    assert len(cluster.convert_to_simulator_events()) == 10


# ---------------------------------------------------------------------------
# Real-format CSV quirks: the circulating Alibaba dumps carry CRLF line
# endings, RFC4180-quoted fields and (sometimes) a header line. The parser
# must absorb all three — and the header rule (first row only, first field
# non-empty and non-integer) must never eat a data row.
# ---------------------------------------------------------------------------

INSTANCE_BASE = (
    "41562,41618,120,686,299,Terminated,1,1\n"
    ",41618,120,686,,Interrupted,1,1\n"  # optional start/machine empty
    "41563,41620,120,686,300,Terminated,2,2\n"
)
TASK_BASE = (
    "10718,12897,15,64,2003,Terminated,50,0.016007\n"
    "10720,12899,15,65,1,Waiting,,\n"
)
MACHINE_BASE = "10,1,add,,64,0.69\n50,1,softerror,links_broken,,\n"

from kubernetriks_tpu.test_util import (
    ALIBABA_INSTANCE_HEADER as INSTANCE_HEADER,
    ALIBABA_TASK_HEADER as TASK_HEADER,
    ALIBABA_MACHINE_HEADER as MACHINE_HEADER,
    quirkify_csv as _quirkify,
)


QUIRKS = [
    dict(crlf=True),
    dict(quote=True),
    dict(crlf=True, quote=True),
    "header",
    "header+crlf+quote",
]


@pytest.mark.parametrize("quirk", QUIRKS, ids=str)
def test_csv_quirks_parse_identically(quirk):
    for base, header, read in (
        (INSTANCE_BASE, INSTANCE_HEADER, read_batch_instances),
        (TASK_BASE, TASK_HEADER, read_batch_tasks),
        (MACHINE_BASE, MACHINE_HEADER, read_machine_events),
    ):
        if quirk == "header":
            kw = dict(header=header)
        elif quirk == "header+crlf+quote":
            kw = dict(header=header, crlf=True, quote=True)
        else:
            kw = quirk
        assert read(_quirkify(base, **kw)) == read(base), (quirk, header)


def test_first_row_with_empty_leading_field_is_data_not_header():
    """batch_instance's start_ts is OPTIONAL: a file whose first row has an
    empty first field must parse as data (the header rule requires a
    non-empty, non-integer first field)."""
    rows = read_batch_instances(",41618,120,686,,Interrupted,1,1\n")
    assert len(rows) == 1 and rows[0].start_timestamp is None


def test_header_rule_applies_to_first_row_only():
    """A malformed non-integer first field PAST row one is a parse error,
    not a silently skipped header."""
    with pytest.raises(ValueError):
        read_batch_tasks(
            "10718,12897,15,64,2003,Terminated,50,0.016\n"
            "oops,12899,15,65,1,Waiting,,\n"
        )


def test_quoted_field_with_embedded_comma():
    """RFC4180 quoting protects commas inside fields (machine event_detail
    free text is where real dumps use it)."""
    events = read_machine_events('50,1,softerror,"links, broken",,\n')
    assert events[0].event_detail == "links, broken"
