"""Parity tests for the fused Pallas scheduling kernel (interpret mode on the
CPU test platform): the kernel must reproduce the lax.scan formulation of the
scheduling cycle bit for bit — same decisions, same allocatables, same parks —
at both the kernel-call level and the full-simulation level.

Scalar semantics under test: the compiled scheduler profile's filter mask +
weighted score (batched/pipeline.py; default = Fit + LeastAllocatedResources,
reference: src/core/scheduler/kube_scheduler.rs:63-152, plugin.rs:33-63) +
last-max-wins argmax. Kernel-level parity is gated PER PROFILE: every
supported profile has an independent NumPy restatement of its scoring below,
so a lowering bug in one profile's expressions cannot hide behind another's.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.pipeline import compile_profile
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.ops.scheduler_kernel import fused_schedule_cycle
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)

NEG_INF = np.float32(-np.inf)


def _np_least_allocated(cpu, ram, rc, rr):
    cpu_f = cpu.astype(np.float32)
    ram_f = ram.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        cpu_s = np.where(
            cpu > 0, (cpu_f - np.float32(rc)) * np.float32(100.0) / cpu_f, NEG_INF
        )
        ram_s = np.where(
            ram > 0, (ram_f - np.float32(rr)) * np.float32(100.0) / ram_f, NEG_INF
        )
    return (cpu_s + ram_s) * np.float32(0.5)


def _np_most_allocated(cpu, ram, rc, rr):
    cpu_f = cpu.astype(np.float32)
    ram_f = ram.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        cpu_s = np.where(
            cpu > 0, (np.float32(rc) - cpu_f) * np.float32(100.0) / cpu_f, NEG_INF
        )
        ram_s = np.where(
            ram > 0, (np.float32(rr) - ram_f) * np.float32(100.0) / ram_f, NEG_INF
        )
    return (cpu_s + ram_s) * np.float32(0.5)


def _np_balanced(cpu, ram, rc, rr):
    cpu_f = cpu.astype(np.float32)
    ram_f = ram.astype(np.float32)
    ok = (cpu > 0) & (ram > 0)
    with np.errstate(divide="ignore", invalid="ignore"):
        cpu_frac = np.float32(rc) / np.where(ok, cpu_f, np.float32(1.0))
        ram_frac = np.float32(rr) / np.where(ok, ram_f, np.float32(1.0))
    return np.where(
        ok,
        np.float32(100.0) - np.abs(cpu_frac - ram_frac) * np.float32(100.0),
        NEG_INF,
    )


# Independent score restatements per profile: name -> [(scorer fn, weight)].
NP_PROFILE_SCORERS = {
    "default": [(_np_least_allocated, 1.0)],
    "best_fit": [(_np_most_allocated, 1.0)],
    "balanced_packing": [(_np_most_allocated, 1.0), (_np_balanced, 0.25)],
}


def scan_reference(
    alive, alloc_cpu, alloc_ram, valid, req_cpu, req_ram, profile="default"
):
    """NumPy restatement of the lax.scan scheduling core under the given
    profile (float32 scores, last-max-wins argmax), the oracle for the
    kernel."""
    C, N = alloc_cpu.shape
    K = valid.shape[1]
    alloc_cpu = alloc_cpu.copy()
    alloc_ram = alloc_ram.copy()
    assign = np.zeros((C, K), bool)
    fit_any = np.zeros((C, K), bool)
    best = np.zeros((C, K), np.int32)
    scorers = NP_PROFILE_SCORERS[profile]
    for c in range(C):
        for k in range(K):
            fit = alive[c] & (req_cpu[c, k] <= alloc_cpu[c]) & (req_ram[c, k] <= alloc_ram[c])
            total = np.zeros(N, np.float32)
            for fn, w in scorers:
                s = fn(alloc_cpu[c], alloc_ram[c], req_cpu[c, k], req_ram[c, k])
                total = total + (s if w == 1.0 else s * np.float32(w))
            score = np.where(fit, total, NEG_INF)
            fit_any[c, k] = fit.any()
            if fit.any():
                m = score.max()
                b = np.max(np.where(score == m, np.arange(N), -1))
                best[c, k] = b
                if valid[c, k]:
                    assign[c, k] = True
                    alloc_cpu[c, b] -= req_cpu[c, k]
                    alloc_ram[c, b] -= req_ram[c, k]
    return assign, fit_any, best, alloc_cpu, alloc_ram


@pytest.mark.parametrize(
    "profile", ["default", "best_fit", "balanced_packing"]
)
@pytest.mark.parametrize("shape", [(3, 7, 5), (5, 130, 9), (2, 256, 33)])
def test_kernel_matches_scan_reference(shape, profile):
    C, N, K = shape
    rng = np.random.default_rng(shape[1])
    alive = rng.random((C, N)) < 0.8
    cap = rng.integers(1_000, 64_000, size=(C, N)).astype(np.int32)
    alloc_cpu = (cap * rng.random((C, N))).astype(np.int32)
    alloc_ram = (cap * rng.random((C, N))).astype(np.int32)
    valid = rng.random((C, K)) < 0.9
    req_cpu = rng.integers(0, 8_000, size=(C, K)).astype(np.int32)
    req_ram = rng.integers(0, 8_000, size=(C, K)).astype(np.int32)

    out = fused_schedule_cycle(
        jnp.asarray(alive),
        jnp.asarray(alloc_cpu),
        jnp.asarray(alloc_ram),
        jnp.asarray(valid),
        jnp.asarray(req_cpu),
        jnp.asarray(req_ram),
        interpret=True,
        profile=compile_profile(profile),
    )
    a_ref, f_ref, b_ref, cpu_ref, ram_ref = scan_reference(
        alive, alloc_cpu, alloc_ram, valid, req_cpu, req_ram, profile=profile
    )
    np.testing.assert_array_equal(np.asarray(out[0]), a_ref)
    # fit_any/best are only defined for valid candidates: the kernel's
    # early-exit loop skips iterations past the tile's last valid candidate
    # (leaving zeros), and best additionally holds garbage sentinels where
    # fit_any is false on both paths. Every consumer gates on `valid`.
    np.testing.assert_array_equal(
        np.where(valid, np.asarray(out[1]), False), np.where(valid, f_ref, False)
    )
    defined = valid & f_ref
    np.testing.assert_array_equal(
        np.where(defined, np.asarray(out[2]), -1), np.where(defined, b_ref, -1)
    )
    np.testing.assert_array_equal(np.asarray(out[3]), cpu_ref)
    np.testing.assert_array_equal(np.asarray(out[4]), ram_ref)


def _build(use_pallas, profile=None):
    config = SimulationConfig.from_yaml(
        "sim_name: pallas_parity\nseed: 9\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(12, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=1.0,
        horizon=300.0,
        seed=11,
        cpu=3000,
        ram=6 * 1024**3,
        duration_range=(15.0, 90.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=3,
        max_pods_per_cycle=16,
        use_pallas=use_pallas,
        pallas_interpret=use_pallas,
        scheduler_profile=profile,
    )


@pytest.mark.parametrize("profile", [None, "best_fit"])
def test_full_sim_pallas_matches_scan(profile):
    """Whole-run parity: identical final state pytrees (phases, assignments,
    allocatables, timings, metrics) between the scan and Pallas paths —
    under the default AND a non-default compiled profile (the profile is a
    kernel static; both formulations must lower it identically)."""
    sim_scan = _build(use_pallas=False, profile=profile)
    sim_pallas = _build(use_pallas=True, profile=profile)
    assert sim_pallas.use_pallas and not sim_scan.use_pallas
    assert sim_pallas.profile.name == (profile or "default")
    sim_scan.step_until_time(500.0)
    sim_pallas.step_until_time(500.0)

    assert compare_states(sim_scan.state, sim_pallas.state) == []

    summary = sim_pallas.metrics_summary()
    assert summary["counters"]["scheduling_decisions"] > 50


# --- fused selection + cycle kernel ------------------------------------------


def selection_oracle(alive, alloc_cpu, alloc_ram, eligible, qwin, qoff, qseq,
                     req_cpu, req_ram, K):
    """NumPy restatement of prepare_cycle's sorted top-K compaction followed
    by the scan core: candidates in (win, off, seq) order."""
    C, P = eligible.shape
    cand = np.zeros((C, K), np.int32)
    valid = np.zeros((C, K), bool)
    creq_cpu = np.zeros((C, K), np.int32)
    creq_ram = np.zeros((C, K), np.int32)
    for c in range(C):
        keys_w = np.where(eligible[c], qwin[c], np.iinfo(np.int32).max)
        keys_o = np.where(eligible[c], qoff[c], np.inf)
        keys_s = np.where(eligible[c], qseq[c], np.iinfo(np.int32).max)
        order = np.lexsort((np.arange(P), keys_s, keys_o, keys_w))[:K]
        n = min(K, len(order))
        cand[c, :n] = order
        valid[c, :n] = eligible[c][order]
        creq_cpu[c, :n] = req_cpu[c][order]
        creq_ram[c, :n] = req_ram[c][order]
    assign, fit_any, best, cpu, ram = scan_reference(
        alive, alloc_cpu, alloc_ram, valid, creq_cpu, creq_ram
    )
    return cand, valid, assign, fit_any, best, cpu, ram


@pytest.mark.parametrize("shape", [(3, 7, 20, 5), (5, 130, 40, 9), (2, 64, 300, 33)])
def test_select_kernel_matches_sort_plus_scan(shape):
    from kubernetriks_tpu.ops.scheduler_kernel import fused_select_schedule_cycle

    C, N, P, K = shape
    rng = np.random.default_rng(P)
    alive = rng.random((C, N)) < 0.8
    cap = rng.integers(1_000, 64_000, size=(C, N)).astype(np.int32)
    alloc_cpu = (cap * rng.random((C, N))).astype(np.int32)
    alloc_ram = (cap * rng.random((C, N))).astype(np.int32)
    eligible = rng.random((C, P)) < 0.5
    qwin = rng.integers(0, 5, size=(C, P)).astype(np.int32)
    # Quantized offsets: with only 4 distinct values, exact (win, off)
    # collisions among eligible pods are common, so the kernel's FINAL
    # seq-level tie-break stage is genuinely exercised (a continuous random
    # off would never collide and a broken seq stage would pass).
    qoff = (
        rng.integers(0, 4, size=(C, P)).astype(np.float32) * np.float32(2.5)
    )
    # seq unique per cluster, like the queue counter guarantees.
    qseq = np.stack([rng.permutation(P) for _ in range(C)]).astype(np.int32)
    req_cpu = rng.integers(0, 8_000, size=(C, P)).astype(np.int32)
    req_ram = rng.integers(0, 8_000, size=(C, P)).astype(np.int32)

    out = fused_select_schedule_cycle(
        jnp.asarray(alive),
        jnp.asarray(alloc_cpu),
        jnp.asarray(alloc_ram),
        jnp.asarray(eligible),
        jnp.asarray(qwin),
        jnp.asarray(qoff),
        jnp.asarray(qseq),
        jnp.asarray(req_cpu),
        jnp.asarray(req_ram),
        k_pods=K,
        interpret=True,
    )
    cand_r, valid_r, assign_r, fit_r, best_r, cpu_r, ram_r = selection_oracle(
        alive, alloc_cpu, alloc_ram, eligible, qwin, qoff, qseq,
        req_cpu, req_ram, K,
    )
    cand, valid, assign, fit_any, best, cpu, ram = (np.asarray(o) for o in out)
    np.testing.assert_array_equal(valid, valid_r)
    np.testing.assert_array_equal(
        np.where(valid, cand, -1), np.where(valid_r, cand_r, -1)
    )
    np.testing.assert_array_equal(assign, assign_r)
    np.testing.assert_array_equal(
        np.where(valid, fit_any, False), np.where(valid_r, fit_r, False)
    )
    defined = valid & fit_r
    np.testing.assert_array_equal(
        np.where(defined, best, -1), np.where(defined, best_r, -1)
    )
    np.testing.assert_array_equal(cpu, cpu_r)
    np.testing.assert_array_equal(ram, ram_r)


def test_full_sim_selection_kernel_matches_scan():
    """Full-simulation equivalence with the selection kernel FORCED on
    (interpret mode; the auto gate needs C >= 128, which suite shapes
    don't reach)."""
    scan_sim = _build(False)
    sel_sim = _build(True)
    sel_sim.use_pallas_select = True
    scan_sim.step_until_time(400.0)
    sel_sim.step_until_time(400.0)
    bad = compare_states(scan_sim.state, sel_sim.state)
    assert not bad, bad


# --- free / event / commit scatter kernels -----------------------------------


def test_free_kernel_matches_scatter_add():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_free_resources

    rng = np.random.default_rng(7)
    C, P, N = 5, 40, 9
    freed = rng.random((C, P)) < 0.3
    node = rng.integers(0, N, size=(C, P)).astype(np.int32)
    req_cpu = rng.integers(1, 500, size=(C, P)).astype(np.int32)
    req_ram = rng.integers(1, 500, size=(C, P)).astype(np.int32)
    alloc_cpu = rng.integers(0, 10_000, size=(C, N)).astype(np.int32)
    alloc_ram = rng.integers(0, 10_000, size=(C, N)).astype(np.int32)

    finishes = freed & (rng.random((C, P)) < 0.7)
    value = rng.uniform(0.0, 100.0, size=(C, P)).astype(np.float32)
    got_cpu, got_ram, stats = fused_free_resources(
        jnp.asarray(freed), jnp.asarray(node), jnp.asarray(req_cpu),
        jnp.asarray(req_ram), jnp.asarray(finishes), jnp.asarray(value),
        jnp.asarray(alloc_cpu), jnp.asarray(alloc_ram),
        interpret=True,
    )
    want_cpu, want_ram = alloc_cpu.copy(), alloc_ram.copy()
    for c in range(C):
        for p in range(P):
            if freed[c, p]:
                want_cpu[c, node[c, p]] += req_cpu[c, p]
                want_ram[c, node[c, p]] += req_ram[c, p]
    np.testing.assert_array_equal(np.asarray(got_cpu), want_cpu)
    np.testing.assert_array_equal(np.asarray(got_ram), want_ram)
    # Estimator fold over the finished subset.
    stats = np.asarray(stats)
    for c in range(C):
        vals = value[c][finishes[c]]
        assert stats[c, 0] == len(vals)
        np.testing.assert_allclose(stats[c, 1], vals.sum(), rtol=1e-6)
        np.testing.assert_allclose(stats[c, 2], (vals * vals).sum(), rtol=1e-6)
        assert stats[c, 3] == (vals.min() if len(vals) else np.inf)
        assert stats[c, 4] == (vals.max() if len(vals) else -np.inf)


def test_event_kernel_matches_scatters():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_event_scatter

    rng = np.random.default_rng(11)
    C, E, N, P = 4, 12, 7, 20
    kind = rng.integers(1, 5, size=(C, E)).astype(np.int32)
    # Node events index N-space, pod events P-space; sprinkle out-of-range
    # slots (sliding-window drops).
    slot = np.where(
        (kind == 1) | (kind == 2),
        rng.integers(0, N + 2, size=(C, E)),
        rng.integers(0, P + 3, size=(C, E)),
    ).astype(np.int32)
    rel = rng.uniform(-5.0, 15.0, size=(C, E)).astype(np.float32)
    seq = rng.integers(0, 1000, size=(C, E)).astype(np.int32)
    # valid must be a per-lane prefix (due events are a sorted slab prefix).
    counts = rng.integers(0, E + 1, size=(C,))
    valid = np.arange(E)[None, :] < counts[:, None]

    created0 = rng.random((C, N)) < 0.2
    nrm0 = np.where(rng.random((C, N)) < 0.3, rng.uniform(0, 20, (C, N)), np.inf).astype(np.float32)
    pcr0 = np.full((C, P), np.inf, np.float32)
    pseq0 = np.zeros((C, P), np.int32)
    prm0 = np.full((C, P), np.inf, np.float32)

    got = fused_event_scatter(
        jnp.asarray(kind), jnp.asarray(slot), jnp.asarray(rel),
        jnp.asarray(seq), jnp.asarray(valid),
        jnp.asarray(created0), jnp.asarray(nrm0), jnp.asarray(pcr0),
        jnp.asarray(pseq0), jnp.asarray(prm0),
        interpret=True,
    )
    created, nrm, pcr, pseq, prm = (
        created0.copy(), nrm0.copy(), pcr0.copy(), pseq0.copy(), prm0.copy()
    )
    for c in range(C):
        for e in range(E):
            if not valid[c, e]:
                continue
            s = slot[c, e]
            if kind[c, e] == 1 and s < N:
                created[c, s] = True
            elif kind[c, e] == 2 and s < N:
                nrm[c, s] = min(nrm[c, s], rel[c, e])
            elif kind[c, e] == 3 and s < P:
                pcr[c, s] = min(pcr[c, s], rel[c, e])
                pseq[c, s] = max(pseq[c, s], seq[c, e])
            elif kind[c, e] == 4 and s < P:
                prm[c, s] = min(prm[c, s], rel[c, e])
    np.testing.assert_array_equal(np.asarray(got[0]), created)
    np.testing.assert_array_equal(np.asarray(got[1]), nrm)
    np.testing.assert_array_equal(np.asarray(got[2]), pcr)
    np.testing.assert_array_equal(np.asarray(got[3]), pseq)
    np.testing.assert_array_equal(np.asarray(got[4]), prm)


def test_commit_kernel_matches_scatters():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_commit_scatter

    rng = np.random.default_rng(13)
    C, K, P, N = 4, 10, 30, 6
    # Unique candidate slots per cluster (a pod is selected at most once).
    cand = np.stack([rng.permutation(P)[:K] for _ in range(C)]).astype(np.int32)
    counts = rng.integers(0, K + 1, size=(C,))
    valid = np.arange(K)[None, :] < counts[:, None]
    assign = valid & (rng.random((C, K)) < 0.6)
    park = valid & ~assign
    best = rng.integers(0, N, size=(C, K)).astype(np.int32)
    start_s = rng.uniform(0, 5, size=(C, K)).astype(np.float32)
    park_s = rng.uniform(0, 5, size=(C, K)).astype(np.float32)
    phase0 = rng.integers(0, 4, size=(C, P)).astype(np.int32)
    node0 = rng.integers(-1, N, size=(C, P)).astype(np.int32)

    got = fused_commit_scatter(
        jnp.asarray(cand), jnp.asarray(assign), jnp.asarray(park),
        jnp.asarray(best), jnp.asarray(start_s), jnp.asarray(park_s),
        jnp.asarray(phase0), jnp.asarray(node0),
        interpret=True,
    )
    phase, node = phase0.copy(), node0.copy()
    start_tmp = np.full((C, P), np.inf, np.float32)
    park_tmp = np.full((C, P), np.inf, np.float32)
    for c in range(C):
        for k in range(K):
            s = cand[c, k]
            if assign[c, k]:
                phase[c, s] = 3
                node[c, s] = best[c, k]
                start_tmp[c, s] = start_s[c, k]
            elif park[c, k]:
                phase[c, s] = 2
                park_tmp[c, s] = park_s[c, k]
    np.testing.assert_array_equal(np.asarray(got[0]), phase)
    np.testing.assert_array_equal(np.asarray(got[1]), node)
    np.testing.assert_array_equal(np.asarray(got[2]), start_tmp)
    np.testing.assert_array_equal(np.asarray(got[3]), park_tmp)


@pytest.mark.parametrize(
    "seed,megakernel,profile",
    [
        (3, "1", None),
        (17, "1", "balanced_packing"),
        (17, "0", "best_fit"),
    ],
)
def test_random_trace_all_kernels_match_scan(seed, megakernel, profile, monkeypatch):
    # Pin the megakernel choice regardless of ambient env (the engine reads
    # KTPU_MEGAKERNEL at build time); the "0" case keeps the two-kernel
    # fallback path covered. The non-default profiles ride the same
    # engines (zero extra compiles vs parametrizing profiles separately):
    # the megakernel case lowers balanced_packing into
    # _select_cycle_commit_kernel, the two-kernel case lowers best_fit
    # into _select_cycle_kernel — so every in-kernel decision core is
    # profile-exercised against the scan path.
    monkeypatch.setenv("KTPU_MEGAKERNEL", megakernel)
    """Randomized full-sim equivalence with EVERY Pallas kernel forced on
    (the r4 MEGAKERNEL — selection + cycle + commit + queue-time estimator
    fold in one launch — plus the free and event kernels, interpret mode)
    against the pure-XLA scan path, over a trace with node churn and
    autoscalers — the strongest single parity statement the suite makes
    about the kernel set."""
    from kubernetriks_tpu.test_util import default_test_simulation_config
    from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

    rng = np.random.default_rng(seed)
    config = default_test_simulation_config(
        """
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 6
  node_groups:
  - node_template:
      metadata: {name: kca}
      status: {capacity: {cpu: 16000, ram: 34359738368}}
"""
    )
    cluster_events = ["events:"]
    for i in range(4):
        ts = round(float(rng.uniform(1.0, 20.0)), 1)
        cluster_events.append(
            f"""
- timestamp: {ts}
  event_type:
    !CreateNode
      node:
        metadata: {{name: n{i}}}
        status: {{capacity: {{cpu: 8000, ram: 17179869184}}}}"""
        )
    # One mid-run node failure to exercise reschedules through the kernels.
    cluster_events.append(
        """
- timestamp: 120.0
  event_type:
    !RemoveNode
      node_name: n0"""
    )
    workload_events = ["events:"]
    for i in range(int(rng.integers(25, 40))):
        ts = round(float(rng.uniform(2.0, 300.0)), 1)
        cpu = int(rng.choice([1000, 2000, 4000, 12000]))
        dur = round(float(rng.uniform(15.0, 90.0)), 1)
        workload_events.append(
            f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata: {{name: p{i:03d}}}
        spec:
          resources:
            requests: {{cpu: {cpu}, ram: {cpu * 1048576}}}
            limits: {{cpu: {cpu}, ram: {cpu * 1048576}}}
          running_duration: {dur}"""
        )
    cluster = GenericClusterTrace.from_yaml("".join(cluster_events)).convert_to_simulator_events()
    workload = GenericWorkloadTrace.from_yaml("".join(workload_events)).convert_to_simulator_events()

    def build(pallas):
        sim = build_batched_from_traces(
            config,
            list(cluster),
            list(workload),
            n_clusters=4,
            max_pods_per_cycle=8,
            use_pallas=pallas,
            pallas_interpret=pallas,
            scheduler_profile=profile,
        )
        if pallas:
            sim.use_pallas_select = True  # force the dense kernel set at C=4
        return sim

    scan_sim, kern_sim = build(False), build(True)
    scan_sim.step_until_time(600.0)
    kern_sim.step_until_time(600.0)
    bad = compare_states(scan_sim.state, kern_sim.state)
    assert not bad, (seed, bad)
    counters = scan_sim.metrics_summary()["counters"]
    assert counters["scheduling_decisions"] > 0
