"""Native C++ trace feeder == pure-Python oracle, row for row.

The feeder (native/trace_feeder.cc via kubernetriks_tpu.trace.feeder) must
reproduce the Python pipeline's join/filter/convert semantics exactly
(reference: src/trace/alibaba_cluster_trace_v2017/{workload,cluster}.rs), so
every test here runs both implementations on the same CSVs and diffs events.
"""

import numpy as np
import pytest

from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest, RemoveNodeRequest
from kubernetriks_tpu.trace import feeder
from kubernetriks_tpu.trace.alibaba import (
    AlibabaClusterTraceV2017,
    AlibabaWorkloadTraceV2017,
    read_batch_instances,
    read_batch_tasks,
    read_machine_events,
)

pytestmark = pytest.mark.skipif(
    not feeder.native_available(),
    reason=f"native feeder unavailable: {feeder.native_build_error()}",
)


WORKLOAD_TASKS = (
    # create, end, job, task, n_inst, status, cpus(santicores), norm_mem
    "100,200,1,10,2,Terminated,50,0.015625\n"     # 500 mcpu, 2 GiB
    "100,300,1,11,1,Terminated,100,0.25\n"        # 1000 mcpu, 32 GiB
    "100,300,1,12,1,Terminated,,\n"               # missing resources -> filtered
    "100,300,1,13,1,Terminated,64,0.5\n"
)
WORKLOAD_INSTANCES = (
    "41562,41618,1,10,299,Terminated,1,2\n"   # valid
    "41563,41619,1,10,300,Terminated,2,2\n"   # valid (same task, 2nd instance)
    ",41618,1,10,299,Interrupted,1,2\n"       # no start -> filtered
    "41562,,1,10,299,Interrupted,1,2\n"       # no end -> filtered
    "41562,41618,1,,299,Failed,1,2\n"         # no task id -> filtered
    "41562,41618,1,99,299,Terminated,1,2\n"   # unknown task -> filtered
    "41562,41618,1,12,299,Terminated,1,2\n"   # task lacks resources -> filtered
    "0,41618,1,11,299,Terminated,1,2\n"       # start <= 0 -> filtered
    "41618,41618,1,11,299,Terminated,1,2\n"   # start >= end -> filtered
    "41000,41001,1,11,299,Terminated,1,2\n"   # valid
    "41000,41100,,13,1,Terminated,1,1\n"      # valid, missing job id
)
MACHINE_EVENTS = (
    "10,1,add,,64,0.69\n"
    "10,2,add,,32,0.5\n"
    "50,1,softerror,links_broken,,\n"
    "60,1,harderror,,,\n"        # re-removal -> deduped
    "70,3,softerror,,,\n"        # ghost node -> deduped
    "80,2,harderror,,,\n"
    "90,4,add,,8,0.125\n"
)


def _python_workload_events(instances_text, tasks_text):
    trace = AlibabaWorkloadTraceV2017(
        read_batch_instances(instances_text), read_batch_tasks(tasks_text)
    )
    return trace.convert_to_simulator_events()


def _python_cluster_events(machines_text):
    return AlibabaClusterTraceV2017(
        read_machine_events(machines_text)
    ).convert_to_simulator_events()


def _write(tmp_path, name, text):
    path = tmp_path / name
    path.write_text(text)
    return str(path)


def test_workload_native_matches_python(tmp_path):
    inst = _write(tmp_path, "batch_instance.csv", WORKLOAD_INSTANCES)
    task = _write(tmp_path, "batch_task.csv", WORKLOAD_TASKS)

    arrays = feeder.load_workload_arrays(inst, task)
    native = feeder.workload_events_from_arrays(arrays)
    python = _python_workload_events(WORKLOAD_INSTANCES, WORKLOAD_TASKS)

    assert len(native) == len(python) == 4
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert isinstance(nev, CreatePodRequest)
        assert nev.pod.metadata.name == pev.pod.metadata.name
        assert nev.pod.spec.resources.requests.cpu == pev.pod.spec.resources.requests.cpu
        assert nev.pod.spec.resources.requests.ram == pev.pod.spec.resources.requests.ram
        assert nev.pod.spec.running_duration == pev.pod.spec.running_duration
    # The missing-job-id row renders like the Python f-string.
    assert any(ev.pod.metadata.name.startswith("None_13_") for _, ev in native)


def test_cluster_native_matches_python(tmp_path):
    path = _write(tmp_path, "machine_events.csv", MACHINE_EVENTS)

    arrays = feeder.load_cluster_arrays(path)
    native = feeder.cluster_events_from_arrays(arrays)
    python = _python_cluster_events(MACHINE_EVENTS)

    assert len(native) == len(python) == 5
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert type(nev) is type(pev)
        if isinstance(nev, CreateNodeRequest):
            assert nev.node.metadata.name == pev.node.metadata.name
            assert nev.node.status.capacity.cpu == pev.node.status.capacity.cpu
            assert nev.node.status.capacity.ram == pev.node.status.capacity.ram
        else:
            assert isinstance(nev, RemoveNodeRequest)
            assert nev.node_name == pev.node_name


def test_duplicate_task_id_raises(tmp_path):
    inst = _write(tmp_path, "i.csv", WORKLOAD_INSTANCES)
    task = _write(tmp_path, "t.csv", "1,2,3,64,1,T,50,0.5\n1,2,3,64,1,T,50,0.5\n")
    with pytest.raises(ValueError, match="duplicated task id: 64"):
        feeder.load_workload_arrays(inst, task)


def test_add_without_resources_raises(tmp_path):
    path = _write(tmp_path, "m.csv", "10,1,add,,,\n")
    with pytest.raises(ValueError, match="lacks cpu/memory"):
        feeder.load_cluster_arrays(path)


def test_unknown_machine_event_raises(tmp_path):
    path = _write(tmp_path, "m.csv", "10,1,add,,64,0.5\n20,1,frobnicate,,,\n")
    with pytest.raises(ValueError, match="Unsupported operation"):
        feeder.load_cluster_arrays(path)


def test_native_matches_python_on_random_trace(tmp_path):
    """Fuzz: a few thousand random rows with every failure mode mixed in."""
    rng = np.random.default_rng(7)
    n_tasks, n_inst = 200, 4000
    task_lines = []
    for tid in range(n_tasks):
        if rng.random() < 0.1:
            cpu, mem = "", ""
        else:
            cpu, mem = str(rng.integers(10, 640)), f"{rng.random():.6f}"
        task_lines.append(f"1,2,{rng.integers(1, 50)},{tid},1,Terminated,{cpu},{mem}")
    inst_lines = []
    for _ in range(n_inst):
        start = rng.integers(-10, 5000)
        end = start + rng.integers(-5, 500)
        tid = rng.integers(0, int(n_tasks * 1.1))  # some unknown tasks
        s = "" if rng.random() < 0.05 else str(start)
        e = "" if rng.random() < 0.05 else str(end)
        t = "" if rng.random() < 0.05 else str(tid)
        j = "" if rng.random() < 0.05 else str(rng.integers(1, 50))
        inst_lines.append(f"{s},{e},{j},{t},1,Terminated,1,1")
    inst_text = "\n".join(inst_lines) + "\n"
    task_text = "\n".join(task_lines) + "\n"

    inst = _write(tmp_path, "bi.csv", inst_text)
    task = _write(tmp_path, "bt.csv", task_text)

    arrays = feeder.load_workload_arrays(inst, task)
    native = feeder.workload_events_from_arrays(arrays)
    python = _python_workload_events(inst_text, task_text)

    assert len(native) == len(python)
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert nev.pod.metadata.name == pev.pod.metadata.name
        assert nev.pod.spec.resources.requests.cpu == pev.pod.spec.resources.requests.cpu
        assert nev.pod.spec.resources.requests.ram == pev.pod.spec.resources.requests.ram
        assert nev.pod.spec.running_duration == pev.pod.spec.running_duration


def test_time_slab_iteration(tmp_path):
    inst = _write(tmp_path, "bi.csv", WORKLOAD_INSTANCES)
    task = _write(tmp_path, "bt.csv", WORKLOAD_TASKS)
    arrays = feeder.load_workload_arrays(inst, task)

    slabs = feeder.iter_time_slabs(arrays, slab_seconds=100.0)
    # Slabs cover every event exactly once, in order.
    covered = []
    for t0, t1, idx in slabs:
        chunk = arrays.start_ts[idx]
        assert ((chunk >= t0) & (chunk < t1)).all()
        covered.extend(chunk.tolist())
    assert covered == arrays.start_ts.tolist()


def test_workload_segment_reader_matches_whole_fill(tmp_path):
    """Segment-at-a-time iteration (the streaming pipeline's trace-side
    seam): WorkloadSegmentReader pulls bounded row ranges of the natively
    parsed + sorted workload, and concatenating every segment must
    reproduce the whole-trace fill bit for bit — same sort, same filters,
    only the Python-side working set changes. The pure-Python oracle
    iterator (iter_workload_segments) must yield the identical stream."""
    import numpy as np

    inst = _write(tmp_path, "bi.csv", WORKLOAD_INSTANCES)
    task = _write(tmp_path, "bt.csv", WORKLOAD_TASKS)
    whole = feeder.load_workload_arrays(inst, task)

    with feeder.WorkloadSegmentReader(inst, task) as reader:
        assert len(reader) == len(whole.start_ts) == 4
        # Odd segment size: the final segment is a ragged remainder.
        native_segs = list(reader.iter_segments(rows_per_segment=3))
        # Out-of-range reads clamp (never over-read the native buffers).
        tail = reader.read(3, 100)
        assert len(tail.start_ts) == 1
        assert reader.read(4, 5).start_ts.size == 0
    oracle_segs = list(feeder.iter_workload_segments(whole, 3))

    assert [lo for lo, _ in native_segs] == [lo for lo, _ in oracle_segs]
    for (_, n_seg), (_, o_seg) in zip(native_segs, oracle_segs):
        for field in (
            "start_ts", "cpu_millicores", "ram_bytes", "duration",
            "job_id", "task_id", "pod_no",
        ):
            np.testing.assert_array_equal(
                getattr(n_seg, field), getattr(o_seg, field), err_msg=field
            )
    cat = np.concatenate([s.start_ts for _, s in native_segs])
    np.testing.assert_array_equal(cat, whole.start_ts)


def test_compile_from_arrays_matches_event_compile(tmp_path):
    """Dense-array fast path == compile_cluster_trace over the event objects."""
    from kubernetriks_tpu.batched.trace_compile import (
        compile_cluster_trace,
        compile_from_arrays,
    )
    from kubernetriks_tpu.test_util import default_test_simulation_config

    inst = _write(tmp_path, "bi.csv", WORKLOAD_INSTANCES)
    task = _write(tmp_path, "bt.csv", WORKLOAD_TASKS)
    machines = _write(tmp_path, "me.csv", MACHINE_EVENTS)

    w_arrays = feeder.load_workload_arrays(inst, task)
    c_arrays = feeder.load_cluster_arrays(machines)
    config = default_test_simulation_config()

    fast = compile_from_arrays(c_arrays, w_arrays, config)
    slow = compile_cluster_trace(
        feeder.cluster_events_from_arrays(c_arrays),
        feeder.workload_events_from_arrays(w_arrays),
        config,
    )

    np.testing.assert_array_equal(fast.ev_time, slow.ev_time)
    np.testing.assert_array_equal(fast.ev_kind, slow.ev_kind)
    np.testing.assert_array_equal(fast.ev_slot, slow.ev_slot)
    np.testing.assert_array_equal(fast.node_cap_cpu, slow.node_cap_cpu)
    np.testing.assert_array_equal(fast.node_cap_ram, slow.node_cap_ram)
    np.testing.assert_array_equal(fast.pod_req_cpu, slow.pod_req_cpu)
    np.testing.assert_array_equal(fast.pod_req_ram, slow.pod_req_ram)
    np.testing.assert_array_equal(fast.pod_duration, slow.pod_duration)
    assert fast.node_names == slow.node_names
    assert fast.pod_names == slow.pod_names


def test_batched_sim_runs_from_native_arrays(tmp_path):
    """End to end: native feeder -> compile_from_arrays -> BatchedSimulation."""
    from kubernetriks_tpu.batched.engine import BatchedSimulation
    from kubernetriks_tpu.batched.trace_compile import compile_from_arrays
    from kubernetriks_tpu.test_util import default_test_simulation_config

    # One 64-core node, two pods that fit.
    machines = _write(tmp_path, "me.csv", "1,1,add,,64,0.5\n")
    task = _write(tmp_path, "bt.csv", "100,200,1,10,2,Terminated,50,0.015625\n")
    inst = _write(
        tmp_path, "bi.csv",
        "100,150,1,10,1,Terminated,1,2\n200,260,1,10,2,Terminated,2,2\n",
    )
    config = default_test_simulation_config()
    compiled = compile_from_arrays(
        feeder.load_cluster_arrays(machines),
        feeder.load_workload_arrays(inst, task),
        config,
    )
    sim = BatchedSimulation(config, [compiled] * 2)
    sim.run_to_completion()
    counters = sim.metrics_summary()["counters"]
    assert counters["pods_succeeded"] == 2 * 2
    assert counters["processed_nodes"] == 1 * 2


def test_same_tick_create_remove_with_asymmetric_shifts(tmp_path):
    """A same-timestamp add+softerror pair must keep create-before-remove
    ordering even when shift_create_node > shift_remove_node (regression:
    the remove used to sort first, crashing one compiler and silently
    diverging in the other)."""
    from kubernetriks_tpu.batched.state import EV_CREATE_NODE, EV_REMOVE_NODE
    from kubernetriks_tpu.batched.trace_compile import (
        compile_cluster_trace,
        compile_from_arrays,
    )
    from kubernetriks_tpu.test_util import default_test_simulation_config

    machines = _write(
        tmp_path, "me.csv", "100,1,add,,64,0.5\n100,1,softerror,,,\n"
    )
    inst = _write(tmp_path, "bi.csv", "100,150,1,10,1,Terminated,1,1\n")
    task = _write(tmp_path, "bt.csv", "1,2,1,10,1,Terminated,50,0.015625\n")

    config = default_test_simulation_config()
    # Make the create shift strictly larger than the remove shift.
    config.ps_to_sched_network_delay = 1.0
    config.as_to_node_network_delay = 0.0

    c_arrays = feeder.load_cluster_arrays(machines)
    w_arrays = feeder.load_workload_arrays(inst, task)
    fast = compile_from_arrays(c_arrays, w_arrays, config)
    slow = compile_cluster_trace(
        feeder.cluster_events_from_arrays(c_arrays),
        feeder.workload_events_from_arrays(w_arrays),
        config,
    )
    for compiled in (fast, slow):
        kinds = list(compiled.ev_kind)
        assert kinds.index(EV_CREATE_NODE) < kinds.index(EV_REMOVE_NODE)
    np.testing.assert_array_equal(fast.ev_time, slow.ev_time)
    np.testing.assert_array_equal(fast.ev_kind, slow.ev_kind)
    np.testing.assert_array_equal(fast.ev_slot, slow.ev_slot)


def test_native_rejects_malformed_required_fields(tmp_path):
    """Field-validation parity (ADVICE r1): the native parser must reject the
    same malformed rows the Python parser raises on, even for columns the
    simulation never reads."""
    import pytest

    from kubernetriks_tpu.trace import feeder

    if not feeder.native_available():
        pytest.skip("no native toolchain")

    tasks = tmp_path / "batch_task.csv"
    instances = tmp_path / "batch_instance.csv"

    # Garbage in batch_task.number_of_instances (field 4).
    tasks.write_text("10,100,1,7,garbage,Terminated,100,0.5\n")
    instances.write_text("10,100,1,7,0,Terminated,0,1\n")
    with pytest.raises(ValueError, match="number_of_instances"):
        feeder.load_workload_arrays(str(instances), str(tasks))

    # Garbage in batch_instance.sequence_number (field 6).
    tasks.write_text("10,100,1,7,1,Terminated,100,0.5\n")
    instances.write_text("10,100,1,7,0,Terminated,oops,1\n")
    with pytest.raises(ValueError, match="sequence_number"):
        feeder.load_workload_arrays(str(instances), str(tasks))


def test_native_rejects_malformed_machine_id(tmp_path):
    import pytest

    from kubernetriks_tpu.trace import feeder

    if not feeder.native_available():
        pytest.skip("no native toolchain")
    tasks = tmp_path / "batch_task.csv"
    instances = tmp_path / "batch_instance.csv"
    tasks.write_text("10,100,1,7,1,Terminated,100,0.5\n")
    instances.write_text("10,100,1,7,garbage,Terminated,0,1\n")
    with pytest.raises(ValueError, match="machine_id"):
        feeder.load_workload_arrays(str(instances), str(tasks))


# --- opt-in real-trace tier -------------------------------------------------
# Mirrors the reference's #[ignore]d real-CSV tests
# (/root/reference/src/trace/alibaba_cluster_trace_v2017/workload.rs:206-219):
# with KUBERNETRIKS_ALIBABA_DIR pointing at a directory holding the real
# Alibaba v2017 machine_events.csv / batch_task.csv / batch_instance.csv,
# the C++ feeder and the Python oracle must agree row for row at full scale.

import os

from kubernetriks_tpu.flags import flag_str

_REAL_DIR = flag_str("KUBERNETRIKS_ALIBABA_DIR")


def _real_path(name):
    path = os.path.join(_REAL_DIR, name)
    assert os.path.exists(path), f"KUBERNETRIKS_ALIBABA_DIR lacks {name}"
    return path


@pytest.mark.skipif(
    not _REAL_DIR, reason="set KUBERNETRIKS_ALIBABA_DIR to the real v2017 CSVs"
)
def test_real_alibaba_workload_native_matches_python():
    inst = _real_path("batch_instance.csv")
    task = _real_path("batch_task.csv")

    arrays = feeder.load_workload_arrays(inst, task)
    python = AlibabaWorkloadTraceV2017.from_files(inst, task).convert_to_simulator_events()

    n = len(arrays.start_ts)
    assert len(python) == n > 0
    p_ts = np.fromiter((ts for ts, _ in python), np.float64, count=n)
    p_cpu = np.fromiter(
        (ev.pod.spec.resources.requests.cpu for _, ev in python), np.int64, count=n
    )
    p_ram = np.fromiter(
        (ev.pod.spec.resources.requests.ram for _, ev in python), np.int64, count=n
    )
    p_dur = np.fromiter(
        (ev.pod.spec.running_duration for _, ev in python), np.float64, count=n
    )
    np.testing.assert_array_equal(arrays.start_ts, p_ts)
    np.testing.assert_array_equal(arrays.cpu_millicores.astype(np.int64), p_cpu)
    np.testing.assert_array_equal(arrays.ram_bytes.astype(np.int64), p_ram)
    np.testing.assert_array_equal(arrays.duration, p_dur)
    # Names spot-check across the span (full string compare of 4M rows is
    # pointless once the numeric join keys match).
    for i in np.linspace(0, n - 1, 997).astype(int):
        assert arrays.pod_name(int(i)) == python[int(i)][1].pod.metadata.name


@pytest.mark.skipif(
    not _REAL_DIR, reason="set KUBERNETRIKS_ALIBABA_DIR to the real v2017 CSVs"
)
def test_real_alibaba_cluster_native_matches_python():
    machines = _real_path("machine_events.csv")

    arrays = feeder.load_cluster_arrays(machines)
    native = feeder.cluster_events_from_arrays(arrays)
    python = _python_cluster_events(open(machines).read())

    assert len(native) == len(python) > 0
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert type(nev) is type(pev)
        if isinstance(nev, CreateNodeRequest):
            assert nev.node.metadata.name == pev.node.metadata.name
            assert nev.node.status.capacity.cpu == pev.node.status.capacity.cpu
            assert nev.node.status.capacity.ram == pev.node.status.capacity.ram
        else:
            assert nev.node_name == pev.node_name


# ---------------------------------------------------------------------------
# Real-format CSV quirks (CRLF endings, RFC4180-quoted fields, optional
# header): the native feeder's SplitCsv/IsHeaderRow must match the Python
# oracle's csv-module + _data_rows behavior on the same quirked files.
# ---------------------------------------------------------------------------

from kubernetriks_tpu.test_util import (
    ALIBABA_INSTANCE_HEADER as INSTANCE_HEADER,
    ALIBABA_TASK_HEADER as TASK_HEADER,
    ALIBABA_MACHINE_HEADER as MACHINE_HEADER,
    quirkify_csv as _quirkify,
)


def _assert_workload_matches(native, python):
    assert len(native) == len(python)
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert nev.pod.metadata.name == pev.pod.metadata.name
        assert nev.pod.spec.resources.requests.cpu == pev.pod.spec.resources.requests.cpu
        assert nev.pod.spec.resources.requests.ram == pev.pod.spec.resources.requests.ram
        assert nev.pod.spec.running_duration == pev.pod.spec.running_duration


QUIRK_CASES = [
    dict(crlf=True),
    dict(quote=True),
    dict(crlf=True, quote=True),
    dict(header=True),
    dict(header=True, crlf=True, quote=True),
]


@pytest.mark.parametrize("quirk", QUIRK_CASES, ids=str)
def test_workload_csv_quirks_native_matches_python(tmp_path, quirk):
    kw = dict(quirk)
    use_header = kw.pop("header", False)
    inst_text = _quirkify(
        WORKLOAD_INSTANCES, header=INSTANCE_HEADER if use_header else None, **kw
    )
    task_text = _quirkify(
        WORKLOAD_TASKS, header=TASK_HEADER if use_header else None, **kw
    )
    inst = _write(tmp_path, "bi.csv", inst_text)
    task = _write(tmp_path, "bt.csv", task_text)

    native = feeder.workload_events_from_arrays(
        feeder.load_workload_arrays(inst, task)
    )
    python = _python_workload_events(inst_text, task_text)
    assert len(native) == 4  # quirks change NOTHING about the join/filter
    _assert_workload_matches(native, python)


@pytest.mark.parametrize("quirk", QUIRK_CASES, ids=str)
def test_cluster_csv_quirks_native_matches_python(tmp_path, quirk):
    kw = dict(quirk)
    use_header = kw.pop("header", False)
    text = _quirkify(
        MACHINE_EVENTS, header=MACHINE_HEADER if use_header else None, **kw
    )
    path = _write(tmp_path, "me.csv", text)

    native = feeder.cluster_events_from_arrays(feeder.load_cluster_arrays(path))
    python = _python_cluster_events(text)
    assert len(native) == len(python) == 5
    for (nts, nev), (pts, pev) in zip(native, python):
        assert nts == pts
        assert type(nev) is type(pev)


def test_native_quoted_field_with_embedded_comma(tmp_path):
    """RFC4180: commas inside quotes are field content ("" is a literal
    quote) — the machine event_detail free-text column is where real dumps
    use both."""
    text = '10,1,add,,64,0.69\n50,1,softerror,"links, ""b"" broken",,\n'
    path = _write(tmp_path, "me.csv", text)
    native = feeder.cluster_events_from_arrays(feeder.load_cluster_arrays(path))
    python = _python_cluster_events(text)
    assert len(native) == len(python) == 2
    assert isinstance(native[1][1], RemoveNodeRequest)


def test_native_first_row_empty_leading_field_is_data(tmp_path):
    """An empty first field on row one is DATA (batch_instance's optional
    start_ts), not a header — the row must flow through the join/filter
    exactly as the Python oracle drops it (no start -> filtered), without
    desyncing the rows behind it."""
    inst_text = (
        ",41618,1,10,299,Interrupted,1,2\n"       # empty start: data, filtered
        "41562,41618,1,10,299,Terminated,1,2\n"   # survives
    )
    task_text = "100,200,1,10,2,Terminated,50,0.015625\n"
    inst = _write(tmp_path, "bi.csv", inst_text)
    task = _write(tmp_path, "bt.csv", task_text)
    native = feeder.workload_events_from_arrays(
        feeder.load_workload_arrays(inst, task)
    )
    python = _python_workload_events(inst_text, task_text)
    assert len(native) == 1
    _assert_workload_matches(native, python)


def test_native_non_ascii_digit_first_row_is_header_on_both_sides(tmp_path):
    """The header rule's integer test is the ASCII subset on BOTH sides: a
    first row leading with full-width digits (which Python's bare int()
    would happily parse, but a byte-level C scan cannot) is a header for
    the Python oracle AND the native feeder, so the two parses never desync
    by a row. Pins the _ASCII_INT_RE / LooksLikePythonInt equivalence at
    its one divergence-prone edge."""
    inst_text = (
        "４１５６２,41618,1,10,299,Terminated,1,2\n"
        "41562,41618,1,10,299,Terminated,1,2\n"   # survives on both sides
    )
    task_text = "100,200,1,10,2,Terminated,50,0.015625\n"
    inst = _write(tmp_path, "bi.csv", inst_text)
    task = _write(tmp_path, "bt.csv", task_text)
    native = feeder.workload_events_from_arrays(
        feeder.load_workload_arrays(inst, task)
    )
    python = _python_workload_events(inst_text, task_text)
    assert len(native) == len(python) == 1
    _assert_workload_matches(native, python)
