"""Runtime sanitizer (KTPU_SANITIZE) — the dynamic half of ktpu-lint.

The flagship composed scenario (HPA + CA + sliding window + superspan +
chaos faults) must run to completion under the sanitizer — proving ZERO
unwaived device-to-host transfers in the steady-state dispatch region (an
unwaived transfer raises through jax's transfer guard) — and produce
bit-identical results to the unsanitized run. Plus unit teeth: the guard
really raises on an unwaived sync, and donation enforcement really makes
read-after-donate crash on CPU (where XLA donation is a no-op — the bug
class that silently passes CPU CI without the sanitizer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetriks_tpu import sanitize
from kubernetriks_tpu.batched.state import compare_states

from test_superspan import FAULT_SUFFIX
from test_window_donation_dispatch import _build_composed


def _run(sim, ends=(150.0, 300.0, 450.0)):
    for end in ends:
        sim.step_until_time(end)
    return sim


@pytest.mark.slow
def test_sanitized_composed_bit_identical():
    """Sanitizer smoke: one composed span (HPA + CA + superspan + chaos)
    under the sanitizer on CPU — zero unwaived transfers (the guard would
    raise), donated inputs consumed after every donated call, finite sweep
    at each superspan boundary — with results bit-identical to the
    unsanitized path. Slow lane (tier-1 wall-clock budget): KTPU_SANITIZE
    is an opt-in debug mode, not a simulation path — the guard-raise /
    consume-donated / NaN-sweep unit gates below stay tier-1, and the
    composed machinery itself is covered bit-exactly by test_superspan's
    chaos-on tier-1 gate; this composed-under-sanitizer variant runs in
    the slow lane."""
    kwargs = dict(
        config_suffix=FAULT_SUFFIX,
        superspan=True,
        superspan_k=4,
        superspan_chunk=4,
    )
    sane = _run(_build_composed(sanitize_mode=True, **kwargs))
    plain = _run(_build_composed(sanitize_mode=False, **kwargs))
    # The sanitized run exercised the real machinery: superspan dispatches,
    # slides, donation, faults.
    assert sane._sanitize and not plain._sanitize
    assert sane.donate
    assert sane.dispatch_stats["superspans"] > 0
    assert sane._pod_base > 0
    assert sane.fault_params is not None
    summary = sane.metrics_summary()
    assert summary == plain.metrics_summary()
    assert (
        summary["counters"]["pod_interruptions"]
        + summary["counters"]["pods_failed"]
        > 0
    ), "fault run produced no faults; sanitized parity is vacuous"
    assert compare_states(sane.state, plain.state) == []
    assert sane._pod_base == plain._pod_base
    assert sane.next_window_idx == plain.next_window_idx


def test_guard_raises_on_unwaived_transfer():
    """An unwaived device-to-host sync inside the guard raises; the same
    sync inside an allow_transfer scope passes. This backs the 'zero
    unwaived transfers' claim of the smoke test above on EVERY backend:
    jax's own transfer guard never fires on CPU (host-resident buffers),
    so the sanitizer's choke point at to_host is the CPU net."""
    from kubernetriks_tpu.parallel.multihost import to_host

    x = jnp.arange(8)
    with pytest.raises(RuntimeError, match="unwaived device-to-host"):
        with sanitize.guard(True):
            to_host(x + 1)
    with sanitize.guard(True):
        with sanitize.allow_transfer(True, "test readback"):
            got = to_host(x + 1)
    np.testing.assert_array_equal(got, np.arange(1, 9))
    # inactive guard is a no-op nullcontext
    with sanitize.guard(False):
        to_host(x + 2)
    # guard depth unwinds cleanly after the raise above
    to_host(x)


def test_consume_donated_makes_read_after_donate_crash():
    """On CPU, XLA donation is a no-op: a donated input SURVIVES the call,
    so reading it afterwards silently returns stale data — the exact bug
    class the donation lint pass + sanitizer target. consume_donated
    force-deletes the survivors, so the read raises on every backend."""
    donated_step = jax.jit(lambda s: jax.tree.map(lambda a: a + 1, s),
                           donate_argnums=(0,))
    state = {"a": jnp.arange(4), "b": jnp.ones((2, 2))}
    out = donated_step(state)
    # jax 0.4.37's CPU runtime happens to implement donation (inputs come
    # back is_deleted) — consume_donated then force-deletes nothing and the
    # read already raises; on runtimes where donation is a no-op it deletes
    # the survivors. Either way the invariant below holds on every backend.
    sanitize.consume_donated(state)  # ktpu: donation-ok(the test enforces donation on the donated input — that's its job)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(state["a"])  # ktpu: donation-ok(deliberate read-after-donate: the test asserts it RAISES)
    # the call's result is untouched
    np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(1, 5))
    # idempotent: consuming again touches nothing
    assert sanitize.consume_donated(state) == 0  # ktpu: donation-ok(idempotence check on the already-consumed input)


def test_sanitize_folds_in_finite_sweep():
    """KTPU_SANITIZE runs the KTPU_DEBUG_FINITE state sweep without the
    flag being set: a NaN planted in a non-sentinel float field raises at
    the next dispatch boundary."""
    sim = _build_composed(sanitize_mode=True, superspan=True)
    assert not sim._debug_finite  # sweep is active via sanitize alone
    sim.step_until_time(50.0)
    # plant NaN into the first all-finite float leaf instead of guessing
    # field names: flatten, poison, rebuild (the sweep flags NaN in ANY
    # float field, sentinel-exempt or not)
    leaves, treedef = jax.tree_util.tree_flatten(sim.state)
    poisoned = False
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            arr = np.array(leaf)
            if arr.size and np.isfinite(arr).all():
                arr.flat[0] = np.nan
                leaves[i] = jnp.asarray(arr)
                poisoned = True
                break
    assert poisoned, "no finite float leaf found to poison"
    sim.state = jax.tree_util.tree_unflatten(treedef, leaves)
    with pytest.raises(FloatingPointError, match="NaN"):
        sim._check_finite()
