"""Flagship composition: every scale feature at once.

Round-2 verdict gap: the sliding pod window, HPA pod groups, the cluster
autoscaler, the device mesh, and the Pallas cycle kernel each worked but were
mutually exclusive. These tests pin the composed behavior:

- the window slides over PLAIN trace pods while HPA ring slots stay
  device-resident (trace_compile.segment_pod_slots segmented layout),
- the composition runs under a C-sharded mesh (the window shift is a
  shard-preserving concatenation),
- the Pallas kernel runs per-shard through shard_map,

and every variant reproduces the full-resident unsharded run exactly
(scalar-oracle anchored by the goldens the components already pass:
reference src/main.rs:57-102 one-config end-to-end run).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import PoissonWorkloadTrace
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

from tests.test_hpa_ca_combined import (
    CLUSTER_TRACE as HPA_CA_CLUSTER,
    CONFIG_SUFFIX as HPA_CA_SUFFIX,
    WORKLOAD_TRACE as HPA_CA_WORKLOAD,
)

N_CLUSTERS = 8
HORIZON = 1500.0


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8
    return Mesh(np.array(devices), ("clusters",))


@pytest.fixture(scope="module")
def mixed_traces():
    """Plain finite Poisson pods (the window slides over these) interleaved
    with the HPA+CA pod group burst workload (resident ring slots)."""
    plain = PoissonWorkloadTrace(
        rate_per_second=0.25,
        horizon=1200.0,
        seed=13,
        cpu=1200,
        ram=2 * 1024**3,
        duration_range=(15.0, 70.0),
    ).convert_to_simulator_events()
    group = GenericWorkloadTrace.from_yaml(
        HPA_CA_WORKLOAD
    ).convert_to_simulator_events()
    workload = sorted(plain + group, key=lambda e: e[0])
    cluster = GenericClusterTrace.from_yaml(HPA_CA_CLUSTER).convert_to_simulator_events()
    return cluster, workload


def _build(mixed_traces, **kwargs):
    cluster, workload = mixed_traces
    config = default_test_simulation_config(HPA_CA_SUFFIX)
    return build_batched_from_traces(
        config,
        list(cluster),
        list(workload),
        n_clusters=N_CLUSTERS,
        max_pods_per_cycle=16,
        # This scenario churns 22 CA node opens (measured) past the default
        # 2 x 10 reserve; the wider reserve keeps the composed run
        # reference-faithful under the strict reserve check.
        ca_slot_multiplier=4,
        **kwargs,
    )


@pytest.fixture(scope="module")
def full_run(mixed_traces):
    sim = _build(mixed_traces)
    sim.step_until_time(HORIZON)
    return sim


def _assert_matches_full(sim, full):
    sm, fm = sim.metrics_summary(), full.metrics_summary()
    assert sm == fm
    assert sim.hpa_replicas(0) == full.hpa_replicas(0)
    np.testing.assert_array_equal(
        np.asarray(sim.ca_node_counts(0)), np.asarray(full.ca_node_counts(0))
    )
    pv, fv = sim.pod_view(0), full.pod_view(0)
    for name in pv:
        assert pv[name] == fv[name], name


def test_window_slides_over_plain_pods_with_hpa_and_ca(mixed_traces, full_run):
    """Sliding pod window + HPA pod groups + CA, unsharded: identical
    terminal metrics, replica trajectory, CA node counts and pod states."""
    sim = _build(mixed_traces, pod_window=64)
    T = int(sim.consts.trace_pod_bound)
    assert sim.pod_window == 64 < T, "window must be smaller than plain pods"
    assert sim.n_pods > 64, "resident HPA ring slots must extend the window"
    sim.step_until_time(HORIZON)
    assert sim._pod_base > 0, "the window never slid"
    # Autoscalers actually did something in this scenario.
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_pods"] > 0
    assert counters["total_scaled_up_nodes"] > 0
    assert counters["total_scaled_down_nodes"] > 0
    _assert_matches_full(sim, full_run)


@pytest.mark.slow
def test_flagship_composition_on_mesh(mixed_traces, full_run, mesh):
    """The full composition — sliding window + HPA + CA + 8-device mesh +
    per-shard Pallas kernel (interpret mode on the CPU platform) — matches
    the full-resident unsharded scan run.

    `slow`: this test FAILED from the seed onward (jax.shard_map API drift
    — see docs/DESIGN.md §"Known suite xfails") so tier-1 never carried
    its ~20 s; now that r9's multihost.shard_map shim fixed it, the heavy
    sliding+mesh+interpret combination runs in the slow suite while
    test_pallas_shard_map_matches_scan_on_mesh (also newly fixed, ~3x
    cheaper) keeps per-shard kernel mesh coverage in tier-1."""
    sim = _build(
        mixed_traces,
        pod_window=64,
        mesh=mesh,
        use_pallas=True,
        pallas_interpret=True,
    )
    assert len(sim.state.pods.phase.devices()) == 8
    sim.step_until_time(HORIZON)
    assert sim._pod_base > 0, "the window never slid under the mesh"
    assert len(sim.state.pods.phase.devices()) == 8, (
        "the window shift dropped the mesh sharding"
    )
    _assert_matches_full(sim, full_run)


def test_pallas_shard_map_matches_scan_on_mesh(mixed_traces, full_run, mesh):
    """Pallas kernel under shard_map on the full-resident mesh run: the whole
    final state pytree matches the unsharded scan path bit for bit (metric
    accumulators to the documented f32 tolerance)."""
    sim = _build(mixed_traces, mesh=mesh, use_pallas=True, pallas_interpret=True)
    sim.step_until_time(HORIZON)
    bad = compare_states(full_run.state, sim.state)
    assert not bad, bad


def test_checkpoint_resume_through_flagship_composition(tmp_path, mixed_traces, full_run):
    """save/load_checkpoint mid-run through the COMPOSED configuration
    (sliding pod window + segmented HPA rings + CA): the restored sim must
    resume with the correct window base and finish identical to the
    uninterrupted run."""
    half = _build(mixed_traces, pod_window=64)
    half.step_until_time(800.0)
    assert half._pod_base > 0, "checkpoint should capture a shifted window"
    half.save_checkpoint(str(tmp_path / "flagship_ckpt"))

    resumed = _build(mixed_traces, pod_window=64)
    resumed.load_checkpoint(str(tmp_path / "flagship_ckpt"))
    assert resumed._pod_base == half._pod_base
    assert resumed.next_window == half.next_window
    resumed.step_until_time(HORIZON)
    _assert_matches_full(resumed, full_run)


def test_heterogeneous_batch_segmented_layout():
    """A batch mixing DIFFERENT traces — one with an HPA pod group, one with
    plain pods only, one with nodes only (zero pods) — through the segmented
    layout and the sliding window: each cluster must behave exactly like its
    own single-cluster full-resident run."""
    config = default_test_simulation_config(HPA_CA_SUFFIX)
    from kubernetriks_tpu.batched.engine import BatchedSimulation
    from kubernetriks_tpu.batched.trace_compile import compile_cluster_trace

    cluster = GenericClusterTrace.from_yaml(HPA_CA_CLUSTER).convert_to_simulator_events()
    plain = PoissonWorkloadTrace(
        rate_per_second=0.2,
        horizon=900.0,
        seed=29,
        cpu=1000,
        ram=2 * 1024**3,
        duration_range=(15.0, 60.0),
    ).convert_to_simulator_events()
    group = GenericWorkloadTrace.from_yaml(HPA_CA_WORKLOAD).convert_to_simulator_events()

    mixed = compile_cluster_trace(
        cluster, sorted(plain + group, key=lambda e: e[0]), config
    )
    plain_only = compile_cluster_trace(cluster, list(plain), config)
    nodes_only = compile_cluster_trace(cluster, [], config)
    batch = [mixed, plain_only, nodes_only]

    hetero = BatchedSimulation(
        config, batch, max_pods_per_cycle=16, pod_window=48
    )
    assert hetero._resident_shift > 0, "segmented layout must be active"
    hetero.step_until_time(1200.0)
    assert hetero._pod_base > 0

    for i, compiled in enumerate(batch):
        solo = BatchedSimulation(config, [compiled], max_pods_per_cycle=16)
        solo.step_until_time(1200.0)
        assert hetero.cluster_metrics(i) == solo.cluster_metrics(0), i
        pv_h, pv_s = hetero.pod_view(i), solo.pod_view(0)
        for name in pv_h:
            assert pv_h[name] == pv_s[name], (i, name)
        if i == 0:
            # The group cluster's replica trajectory is its own.
            assert hetero.hpa_replicas(0) == solo.hpa_replicas(0)
