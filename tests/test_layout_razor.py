"""PR 9 exactness gates: lane-major hot state (KTPU_LANE_MAJOR), the
empty-window resolution razor (KTPU_WINDOW_RAZOR) and the CA scale-down
de-scatter (KTPU_CA_DESCATTER) are all bit-identical to the paths they
replace.

- Layout-equivalence sweep: lane-major vs row-major final state across the
  ladder, fused chunk+slide and superspan executors on one composed
  HPA+CA+sliding-window engine WITH chaos faults on — the full flagship
  feature set — with razor+de-scatter also flipped on against an all-off
  reference, and dispatch_stats EQUAL (the modes are device-side layout /
  program changes; zero new host syncs).
- Empty-window razor gate: a gappy dense-stepped trace (bursts separated by
  provably-empty windows, fast-forward OFF so the razor — not the span
  skipper — is what fires) produces identical state with the razor on/off.
- Kernel-wrapper lane-major unit gates: each wrapper that accepts
  nodes_lane_major returns bit-identical results for transposed node
  operands (interpret mode, so this holds on CPU CI).

State comparison uses state.compare_states — the documented parity policy
(exact everywhere; float32 metric accumulators to 1e-6, which covers the
axis-flipped node_downtime_s reduction order).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states, swap_node_layout
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)
from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

COMPOSED_YAML = """
sim_name: layout_razor
seed: 1
scheduling_cycle_interval: 10.0
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 8
  node_groups:
  - node_template:
      metadata: {name: ca_node}
      status: {capacity: {cpu: 64000, ram: 137438953472}}
fault_injection:
  enabled: true
  node:
    mttf: 300.0
    mttr: 60.0
  pod:
    fail_prob: 0.1
    restart_limit: 2
"""

GROUP_YAML = """
events:
- timestamp: 49.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 4
        max_pod_count: 8
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 8000, ram: 17179869184}
              limits: {cpu: 8000, ram: 17179869184}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 60.0
                total_load: 2.0
              - duration: 90.0
                total_load: 12.0
              - duration: 150.0
                total_load: 1.0
"""


@pytest.fixture(scope="module")
def composed_traces():
    config = SimulationConfig.from_yaml(COMPOSED_YAML)
    cluster = UniformClusterTrace(8, cpu=64000, ram=128 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=0.375,
        horizon=300.0,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 120.0),
        name_prefix="plain",
    )
    group = GenericWorkloadTrace.from_yaml(GROUP_YAML)
    workload = sorted(
        plain.convert_to_simulator_events()
        + group.convert_to_simulator_events(),
        key=lambda e: e[0],
    )
    return config, cluster.convert_to_simulator_events(), workload


def _run_composed(composed_traces, **kwargs):
    config, cev, wev = composed_traces
    sim = build_batched_from_traces(
        config,
        cev,
        wev,
        n_clusters=4,
        max_pods_per_cycle=16,
        pod_window=64,
        use_pallas=False,
        fast_forward=False,
        **kwargs,
    )
    sim.step_until_time(350.0)
    return sim


@pytest.fixture(scope="module")
def composed_reference(composed_traces):
    """Row-major, razor off, de-scatter off, ladder executor — the r8
    path every new mode must reproduce bit for bit."""
    return _run_composed(
        composed_traces,
        superspan=False,
        lane_major=False,
        window_razor=False,
        ca_descatter=False,
    )


@pytest.mark.parametrize(
    "executor",
    ["ladder", "fused", "superspan"],
)
def test_lane_major_bit_identity_across_executors(
    composed_traces, composed_reference, executor
):
    """Lane-major + razor + de-scatter ON vs the all-off row-major
    reference: final composed chaos state identical under the parity
    policy, on every steady-state executor."""
    kwargs = dict(superspan=False)
    if executor == "fused":
        # Undonated on purpose: the plain chunk programs are then jit-cache
        # hits from the ladder case, so this case compiles only the fused
        # chunk+slide program (tier-1 wall-clock budget).
        kwargs = dict(superspan=False, fuse_slide=True)
    elif executor == "superspan":
        kwargs = dict(superspan=True)
    sim = _run_composed(
        composed_traces,
        lane_major=True,
        window_razor=True,
        ca_descatter=True,
        **kwargs,
    )
    bad = compare_states(composed_reference.state, sim.state)
    assert not bad, f"{executor}: lane-major state diverged: {bad}"
    if executor == "fused":
        assert sim.dispatch_stats["fused_slides"] > 0
    if executor == "superspan":
        assert sim.dispatch_stats["superspans"] > 0
        assert sim.dispatch_stats["window_chunks"] == 0
    else:
        # The new modes are device-side program changes: the host dispatch
        # loop — chunk counts, slides, syncs — is IDENTICAL with them on
        # (the no-new-host-syncs half of the acceptance criteria). The
        # ladder/fused executors share the reference's dispatch pattern
        # modulo the fused-slide split, which fused engines disclose in
        # their own counters checked above.
        if executor == "ladder":
            assert sim.dispatch_stats == composed_reference.dispatch_stats
    # State AT REST is row-major regardless of the program layout: readout,
    # checkpointing and sharding never see transposed leaves (conversion
    # lives at the jit entries), and the swap helper is self-inverse on a
    # real post-run state. Asserted on the sweep engines (zero extra
    # builds — tier-1 wall-clock budget).
    C, N = sim.n_clusters, sim.n_nodes
    assert sim.state.nodes.alive.shape == (C, N)
    assert sim.state.nodes.alloc_cpu.shape == (C, N)
    twice = swap_node_layout(swap_node_layout(sim.state))
    assert not compare_states(sim.state, twice)


def _gappy_plain_traces():
    """A plain engine shape with real empty windows: two pod bursts
    separated by a long quiet stretch, durations short enough that the
    stretch has no finishes due either."""
    config = SimulationConfig.from_yaml(
        "sim_name: razor\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(8, cpu=64000, ram=128 * 1024**3)
    bursts = []
    for burst_t0 in (0.0, 600.0):
        w = PoissonWorkloadTrace(
            rate_per_second=1.0,
            horizon=60.0,
            seed=int(burst_t0) + 5,
            cpu=4000,
            ram=8 * 1024**3,
            duration_range=(20.0, 40.0),
            name_prefix=f"b{int(burst_t0)}",
        )
        bursts += [(t + burst_t0, ev) for t, ev in w.convert_to_simulator_events()]
    return config, cluster.convert_to_simulator_events(), sorted(
        bursts, key=lambda e: e[0]
    )


def test_window_razor_empty_window_identity():
    """Razor on vs off over a gappy trace stepped WITHOUT fast-forward:
    the gated resolution path must produce identical state even though
    most windows take the skip branch (the correctness half of the
    empty-window-cost claim)."""
    config, cev, wev = _gappy_plain_traces()

    def run(razor):
        sim = build_batched_from_traces(
            config,
            cev,
            wev,
            n_clusters=2,
            max_pods_per_cycle=16,
            fast_forward=False,
            window_razor=razor,
        )
        sim.step_until_time(800.0)
        return sim

    on, off = run(True), run(False)
    bad = compare_states(off.state, on.state)
    assert not bad, f"razor diverged: {bad}"
    assert on.dispatch_stats == off.dispatch_stats
    assert (
        on.metrics_summary()["counters"]["scheduling_decisions"]
        == off.metrics_summary()["counters"]["scheduling_decisions"]
        > 0
    )


# --- kernel-wrapper lane-major unit gates (interpret mode) -------------------


def _node_ops(rng, C, N):
    alive = rng.random((C, N)) < 0.8
    cap = rng.integers(1000, 64000, (C, N)).astype(np.int32)
    alloc = (cap * rng.random((C, N))).astype(np.int32)
    return alive, alloc, alloc // 2


def test_free_kernel_lane_major_identity():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_free_resources

    rng = np.random.default_rng(0)
    C, N, P = 3, 5, 9
    alive, acpu, aram = _node_ops(rng, C, N)
    freed = rng.random((C, P)) < 0.4
    node = rng.integers(-1, N, (C, P)).astype(np.int32)
    node = np.where(freed, np.clip(node, 0, N - 1), node)
    reqc = rng.integers(0, 500, (C, P)).astype(np.int32)
    reqr = rng.integers(0, 500, (C, P)).astype(np.int32)
    fin = freed & (rng.random((C, P)) < 0.5)
    val = rng.random((C, P)).astype(np.float32)
    row = fused_free_resources(
        freed, node, reqc, reqr, fin, val, acpu, aram, interpret=True
    )
    lane = fused_free_resources(
        freed, node, reqc, reqr, fin, val, acpu.T, aram.T,
        interpret=True, nodes_lane_major=True,
    )
    np.testing.assert_array_equal(np.asarray(row[0]), np.asarray(lane[0]).T)
    np.testing.assert_array_equal(np.asarray(row[1]), np.asarray(lane[1]).T)
    np.testing.assert_array_equal(np.asarray(row[2]), np.asarray(lane[2]))


def test_cycle_kernel_lane_major_identity():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_schedule_cycle

    rng = np.random.default_rng(1)
    C, N, K = 3, 6, 4
    alive, acpu, aram = _node_ops(rng, C, N)
    valid = rng.random((C, K)) < 0.7
    reqc = rng.integers(0, 4000, (C, K)).astype(np.int32)
    reqr = rng.integers(0, 4000, (C, K)).astype(np.int32)
    row = fused_schedule_cycle(
        alive, acpu, aram, valid, reqc, reqr, interpret=True
    )
    lane = fused_schedule_cycle(
        alive.T, acpu.T, aram.T, valid, reqc, reqr,
        interpret=True, nodes_lane_major=True,
    )
    for i in range(3):  # candidate-shaped outputs
        np.testing.assert_array_equal(np.asarray(row[i]), np.asarray(lane[i]))
    for i in (3, 4):  # node-shaped outputs come back lane-major
        np.testing.assert_array_equal(
            np.asarray(row[i]), np.asarray(lane[i]).T
        )


def test_event_kernel_lane_major_identity():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_event_scatter

    rng = np.random.default_rng(2)
    C, N, P, E = 3, 5, 7, 6
    kind = rng.integers(1, 5, (C, E)).astype(np.int32)
    slot = rng.integers(0, max(N, P), (C, E)).astype(np.int32)
    rel = rng.random((C, E)).astype(np.float32)
    seq = rng.integers(0, 100, (C, E)).astype(np.int32)
    valid = (np.cumsum(rng.random((C, E)) < 0.8, axis=1) == np.arange(1, E + 1))
    created = rng.random((C, N)) < 0.2
    nrm = np.where(rng.random((C, N)) < 0.2, rng.random((C, N)), np.inf).astype(
        np.float32
    )
    pcr = np.full((C, P), np.inf, np.float32)
    pseq = np.zeros((C, P), np.int32)
    prm = np.full((C, P), np.inf, np.float32)
    row = fused_event_scatter(
        kind, slot, rel, seq, valid, created, nrm, pcr, pseq, prm,
        interpret=True,
    )
    lane = fused_event_scatter(
        kind, slot, rel, seq, valid, created.T, nrm.T, pcr, pseq, prm,
        interpret=True, nodes_lane_major=True,
    )
    np.testing.assert_array_equal(np.asarray(row[0]), np.asarray(lane[0]).T)
    np.testing.assert_array_equal(np.asarray(row[1]), np.asarray(lane[1]).T)
    for i in (2, 3, 4):
        np.testing.assert_array_equal(np.asarray(row[i]), np.asarray(lane[i]))


def test_megakernel_lane_major_identity():
    from kubernetriks_tpu.ops.scheduler_kernel import fused_select_cycle_commit

    rng = np.random.default_rng(3)
    C, N, P, K = 3, 5, 9, 4
    alive, acpu, aram = _node_ops(rng, C, N)
    elig = rng.random((C, P)) < 0.5
    qwin = rng.integers(0, 10, (C, P)).astype(np.int32)
    qoff = rng.random((C, P)).astype(np.float32)
    qseq = rng.permutation(C * P).reshape(C, P).astype(np.int32)
    reqc = rng.integers(0, 4000, (C, P)).astype(np.int32)
    reqr = rng.integers(0, 4000, (C, P)).astype(np.int32)
    waited = rng.random((C, P)).astype(np.float32)
    phase = rng.integers(0, 4, (C, P)).astype(np.int32)
    node = rng.integers(-1, N, (C, P)).astype(np.int32)
    qpre = np.cumsum(rng.random((C, K)), axis=1).astype(np.float32)
    start = (qpre + 0.5).astype(np.float32)
    park = qpre.copy()
    args = (elig, qwin, qoff, qseq, reqc, reqr, waited, phase, node,
            qpre, start, park)
    row = fused_select_cycle_commit(
        alive, acpu, aram, *args, k_pods=K, interpret=True
    )
    lane = fused_select_cycle_commit(
        alive.T, acpu.T, aram.T, *args, k_pods=K, interpret=True,
        nodes_lane_major=True,
    )
    for i in (0, 1):  # allocatables come back lane-major
        np.testing.assert_array_equal(
            np.asarray(row[i]), np.asarray(lane[i]).T
        )
    for i in range(2, 7):
        np.testing.assert_array_equal(np.asarray(row[i]), np.asarray(lane[i]))
