"""Randomized HPA cross-path equivalence: for generated load curves and
targets, the batched HPA's replica trajectory must match the scalar oracle at
every scan-cycle boundary (formula fidelity reference:
src/autoscalers/horizontal_pod_autoscaler/kube_horizontal_pod_autoscaler.rs:54-155)."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.config import KubeHorizontalPodAutoscalerConfig
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CLUSTER_TRACE = """
events:
- timestamp: 5.0
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 64000, ram: 68719476736}}
"""


def make_workload(seed: int) -> str:
    """Random pod group: initial/max counts, cpu target, and a 2-4 segment
    cyclic load curve."""
    rng = np.random.default_rng(seed)
    initial = int(rng.integers(2, 9))
    max_pods = int(rng.integers(20, 60))
    target = round(float(rng.uniform(0.3, 0.9)), 2)
    segments = "".join(
        f"""
              - duration: {int(rng.integers(2, 9)) * 60}.0
                total_load: {round(float(rng.uniform(0.5, 12.0)), 2)}"""
        for _ in range(int(rng.integers(2, 5)))
    )
    return f"""
events:
- timestamp: 59.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: pod_group_1
        initial_pod_count: {initial}
        max_pod_count: {max_pods}
        pod_template:
          metadata:
            name: pod_group_1
          spec:
            resources:
              requests:
                cpu: 100
                ram: 104857600
              limits:
                cpu: 100
                ram: 104857600
        target_resources_usage:
          cpu_utilization: {target}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |{segments}
"""


@pytest.mark.parametrize("seed", [17, 29, 41])
def test_random_hpa_trajectory_matches_scalar(seed):
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
        KubeHorizontalPodAutoscalerConfig()
    )
    workload = make_workload(seed)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )

    trajectory_scalar, trajectory_batched = [], []
    # Sample just after every 60 s HPA boundary across two+ curve cycles.
    for t in np.arange(61.0, 1500.0, 60.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        trajectory_scalar.append(
            len(scalar.horizontal_pod_autoscaler.pod_groups["pod_group_1"].created_pods)
        )
        trajectory_batched.append(batched.hpa_replicas(0)["pod_group_1"])

    assert trajectory_batched == trajectory_scalar, (
        f"seed {seed}: batched {trajectory_batched} != scalar {trajectory_scalar}"
    )
    # The trajectory actually moved (non-trivial scenario).
    assert len(set(trajectory_scalar)) > 1, trajectory_scalar


@pytest.mark.parametrize("scan", [30.0, 90.0, 120.0])
def test_hpa_nondefault_scan_matches_scalar(scan):
    """Metrics-staleness fix (r14): at NON-default scan intervals the
    scalar HPA reads whatever the collector's fixed 60 s cycle last
    pulled — a scan-30 cycle at t=30 sees the t=0 sample, and a scan-120
    cycle at a shared collection instant fires BEFORE the collection
    (its event id is older). The batched collection latch (AutoscaleState
    col_*) replays exactly that, so the replica trajectories now match
    the scalar at every window-aligned scan interval (non-window-aligned
    scans keep the window-granularity tick approximation — PARITY.md)."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    config.horizontal_pod_autoscaler.scan_interval = scan
    config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
        KubeHorizontalPodAutoscalerConfig()
    )
    workload = make_workload(29)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    trajectory_scalar, trajectory_batched = [], []
    for t in np.arange(61.0, 1500.0, 30.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        trajectory_scalar.append(
            len(scalar.horizontal_pod_autoscaler.pod_groups["pod_group_1"].created_pods)
        )
        trajectory_batched.append(batched.hpa_replicas(0)["pod_group_1"])
    assert trajectory_batched == trajectory_scalar, (
        f"scan {scan}: batched {trajectory_batched} != scalar "
        f"{trajectory_scalar}"
    )
    assert len(set(trajectory_scalar)) > 1, trajectory_scalar


@pytest.mark.parametrize("seed", [17, 29, 41])
def test_random_hpa_scale_down_identities_match_scalar(seed):
    """Scale-down victim IDENTITY parity (VERDICT r3 item 5): the batched
    path must remove the lexicographically-smallest created NAME, exactly
    like the scalar's BTreeSet pop (kube_horizontal_pod_autoscaler.rs:
    197-205) — which is NOT FIFO once replica indices cross a decimal digit
    boundary ("pod_group_1_10" < "pod_group_1_2"). These scenarios scale
    into double-digit indices, so the digit-boundary pops are exercised."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
        KubeHorizontalPodAutoscalerConfig()
    )
    workload = make_workload(seed)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    st = batched.autoscale_statics
    pod_group_id = np.asarray(st.pod_group_id)[0]
    slot_start = np.asarray(st.pg_slot_start)[0]
    slot_count = np.asarray(st.pg_slot_count)[0]
    from kubernetriks_tpu.batched.timerep import INF_WIN
    BIG = np.int32(INF_WIN)

    removed_scalar: list = []
    removed_batched: list = []
    prev_created = set(
        scalar.horizontal_pod_autoscaler.pod_groups["pod_group_1"].created_pods
    ) if "pod_group_1" in scalar.horizontal_pod_autoscaler.pod_groups else set()

    for t in np.arange(61.0, 1500.0, 60.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))

        groups = scalar.horizontal_pod_autoscaler.pod_groups
        cur = set(groups["pod_group_1"].created_pods) if "pod_group_1" in groups else set()
        removed_scalar.extend(sorted(prev_created - cur))
        prev_created = cur

        # This tick's batched victims: slots whose removal_time is pending
        # (wiped at the effect application, so each sample sees exactly one
        # tick's decisions). Occupant name comes from the stored replica
        # index (pods.hpa_idx, written at activation).
        rw = np.asarray(batched.state.pods.removal_time.win)[0]
        hidx = np.asarray(batched.state.pods.hpa_idx)[0]
        names = []
        for p in np.nonzero(rw < BIG)[0]:
            assert pod_group_id[p] >= 0 and hidx[p] >= 0
            names.append(f"pod_group_1_{int(hidx[p])}")
        removed_batched.extend(sorted(names))

    assert removed_scalar, "scenario must scale down at least once"
    assert any(
        int(n.rsplit("_", 1)[1]) >= 10 for n in removed_scalar
    ), "scenario must exercise double-digit indices"
    assert removed_batched == removed_scalar, (
        f"seed {seed}\nscalar  {removed_scalar}\nbatched {removed_batched}"
    )


def test_hpa_only_multi_node_cluster_runs():
    """r4 regression: HPA-only configs (CA off) with MORE THAN ONE node
    crashed at trace time — node_name_rank carried the CA slot-reserve
    padding (+S) even when the engine appended no CA slots, and every
    existing HPA-only batched test used a single node, where the size-1
    node axis silently broadcast against the oversized rank array."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
        KubeHorizontalPodAutoscalerConfig()
    )
    multi_node_cluster = """
events:
- timestamp: 5.0
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 64000, ram: 68719476736}}
- timestamp: 5.0
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 64000, ram: 68719476736}}
- timestamp: 5.0
  event_type:
    !CreateNode
      node:
        metadata: {name: node_02}
        status: {capacity: {cpu: 64000, ram: 68719476736}}
"""
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(multi_node_cluster).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(make_workload(17)).convert_to_simulator_events(),
        n_clusters=2,
    )
    batched.step_until_time(700.0)
    c = batched.metrics_summary()["counters"]
    assert c["total_scaled_up_pods"] > 0, "HPA never acted"
