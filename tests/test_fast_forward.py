"""Fast-forward window skipping (step.run_windows_skip) is EXACT: the final
state pytree of a fast-forwarded run must be bit-identical to stepping every
window index — across sparse traces (where whole spans skip), autoscalers
(tick bookkeeping catch-up), conditional-move wakes, flush cadences, node
failures, and the sliding pod window."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import PoissonWorkloadTrace, UniformClusterTrace
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace


def _sparse_traces(rate=0.02, horizon=3000.0, seed=5):
    """~1 pod per 5 windows: plenty of provably-empty spans to skip."""
    cluster = UniformClusterTrace(6, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=rate,
        horizon=horizon,
        seed=seed,
        cpu=3000,
        ram=6 * 1024**3,
        duration_range=(15.0, 120.0),
    )
    return (
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
    )


def _run_both(config, cluster, workload, until, n_clusters=3, **kwargs):
    plain = build_batched_from_traces(
        config, list(cluster), list(workload), n_clusters=n_clusters,
        max_pods_per_cycle=8, fast_forward=False, **kwargs,
    )
    fast = build_batched_from_traces(
        config, list(cluster), list(workload), n_clusters=n_clusters,
        max_pods_per_cycle=8, fast_forward=True, **kwargs,
    )
    assert fast.fast_forward and not plain.fast_forward
    plain.step_until_time(until)
    fast.step_until_time(until)
    assert fast.next_window_idx == plain.next_window_idx
    bad = compare_states(plain.state, fast.state)
    assert not bad, bad
    return plain, fast


def test_sparse_trace_exact():
    config = SimulationConfig.from_yaml(
        "sim_name: ff\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster, workload = _sparse_traces()
    plain, fast = _run_both(config, cluster, workload, 4000.0)
    assert plain.metrics_summary()["counters"]["pods_succeeded"] > 0


def test_sparse_trace_with_autoscalers_exact():
    """HPA + CA enabled on a sparse mixed trace: the tick catch-up must
    reproduce hpa_next/ca_next and the CA/HPA trajectories exactly."""
    from tests.test_hpa_ca_combined import (
        CLUSTER_TRACE,
        CONFIG_SUFFIX,
        WORKLOAD_TRACE,
    )

    config = default_test_simulation_config(CONFIG_SUFFIX)
    plain_events = PoissonWorkloadTrace(
        rate_per_second=0.03,
        horizon=1500.0,
        seed=11,
        cpu=1000,
        ram=2 * 1024**3,
        duration_range=(20.0, 60.0),
    ).convert_to_simulator_events()
    group = GenericWorkloadTrace.from_yaml(WORKLOAD_TRACE).convert_to_simulator_events()
    workload = sorted(plain_events + group, key=lambda e: e[0])
    cluster = GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events()
    plain, fast = _run_both(config, cluster, workload, 2000.0)
    counters = fast.metrics_summary()["counters"]
    assert counters["total_scaled_up_pods"] > 0
    assert counters["total_scaled_up_nodes"] > 0


def test_parked_pods_and_flush_cadence_exact():
    """Pods that can never fit park forever; the 30 s flush and 300 s stale
    windows must fire at identical indices in both modes."""
    config = default_test_simulation_config()
    cluster = GenericClusterTrace.from_yaml(
        """
events:
- timestamp: 2.0
  event_type:
    !CreateNode
      node:
        metadata: {name: tiny}
        status: {capacity: {cpu: 2000, ram: 4294967296}}
"""
    ).convert_to_simulator_events()
    workload = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 13.0
  event_type:
    !CreatePod
      pod:
        metadata: {name: too_big}
        spec:
          resources:
            requests: {cpu: 64000, ram: 4294967296}
            limits: {cpu: 64000, ram: 4294967296}
          running_duration: 50.0
- timestamp: 700.0
  event_type:
    !CreatePod
      pod:
        metadata: {name: fits}
        spec:
          resources:
            requests: {cpu: 1000, ram: 1073741824}
            limits: {cpu: 1000, ram: 1073741824}
          running_duration: 40.0
"""
    ).convert_to_simulator_events()
    _run_both(config, cluster, workload, 1500.0)


def test_conditional_move_exact():
    config = default_test_simulation_config(
        "enable_unscheduled_pods_conditional_move: true\n"
    )
    cluster, workload = _sparse_traces(rate=0.05, horizon=1500.0, seed=23)
    _run_both(config, cluster, workload, 2500.0)


def test_sliding_pod_window_fast_forward_exact():
    config = SimulationConfig.from_yaml(
        "sim_name: ffw\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster, workload = _sparse_traces(rate=0.05, horizon=4000.0, seed=31)
    _run_both(config, cluster, workload, 5000.0, pod_window=24)


def test_dense_trace_exact():
    """Dense spans (every window interesting): the skip must degenerate to
    plain stepping with an identical result."""
    config = SimulationConfig.from_yaml(
        "sim_name: ffd\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster, workload = _sparse_traces(rate=1.5, horizon=400.0, seed=41)
    _run_both(config, cluster, workload, 700.0)


def test_fast_forward_under_mesh_exact():
    """Fast-forward on an 8-device mesh: the skip's global reductions and
    bookkeeping catch-up must behave identically sharded."""
    import jax
    from jax.sharding import Mesh

    config = SimulationConfig.from_yaml(
        "sim_name: ffm\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster, workload = _sparse_traces(rate=0.04, horizon=2000.0, seed=47)
    mesh = Mesh(np.array(jax.devices()), ("clusters",))
    plain = build_batched_from_traces(
        config, list(cluster), list(workload), n_clusters=8,
        max_pods_per_cycle=8, fast_forward=False,
    )
    fast = build_batched_from_traces(
        config, list(cluster), list(workload), n_clusters=8,
        max_pods_per_cycle=8, fast_forward=True, mesh=mesh,
    )
    plain.step_until_time(3000.0)
    fast.step_until_time(3000.0)
    assert len(fast.state.pods.phase.devices()) == 8
    bad = compare_states(plain.state, fast.state)
    assert not bad, bad


def test_gauge_collection_forces_per_window_stepping():
    """collect_gauges needs one sample per window, so the fast-forward
    dispatch must fall back to the scan — the gauge series stays dense."""
    config = SimulationConfig.from_yaml(
        "sim_name: ffg\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster, workload = _sparse_traces(rate=0.03, horizon=800.0, seed=53)
    sim = build_batched_from_traces(
        config, list(cluster), list(workload), n_clusters=2,
        max_pods_per_cycle=8, fast_forward=True,
    )
    assert sim.fast_forward
    sim.collect_gauges = True
    sim.step_until_time(1000.0)
    times, samples = sim.gauge_series()
    # One gauge row per window (0..100 inclusive), no gaps despite
    # fast_forward being on.
    assert len(times) == 101
    np.testing.assert_allclose(np.diff(times), 10.0)
    assert samples.shape[0] == 101
