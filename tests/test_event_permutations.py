"""Same-timestamp event-permutation property test (SURVEY §5.2's suggested
TPU-equivalent of race detection; VERDICT r3 item 8).

The reference's DSLab queue is FIFO among same-timestamp events, so the
EMISSION order of a trace's same-timestamp events is part of its semantics:
permuting them may legitimately change outcomes. The property that must
hold is that both paths change IDENTICALLY — for every permutation of the
same-timestamp groups, the batched path reproduces the scalar oracle's
terminal state (and when a permutation does shift an outcome, it shifts on
both paths together).

The scenario forces heavy timestamp collisions: all arrivals land on a
coarse grid, including node-create/pod-create collisions and multi-pod
bursts at one instant on an undersized cluster (so processing order decides
who parks)."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import PHASE_SUCCEEDED, PHASE_UNSCHEDULABLE
from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config

GiB = 1024**3
END_TIME = 800.0


def base_events(seed: int):
    """(cluster_events, workload_events) with same-timestamp bursts on a
    5-second grid over an undersized 3-node cluster."""
    rng = np.random.default_rng(seed)
    cluster = [
        (0.0, CreateNodeRequest(node=Node.new(f"node_{i}", 16000, 32 * GiB)))
        for i in range(2)
    ]
    # A third node arrives ON the grid, colliding with pod creates.
    cluster.append(
        (20.0, CreateNodeRequest(node=Node.new("node_late", 16000, 32 * GiB)))
    )
    workload = []
    for i in range(36):
        ts = float(rng.integers(0, 12)) * 5.0  # heavy collisions
        cpu = int(rng.choice([2000, 6000, 12000]))
        duration = float(rng.integers(4, 16)) * 5.0
        workload.append(
            (
                ts,
                CreatePodRequest(
                    pod=Pod.new(f"pod_{i:03d}", cpu, cpu * 1024 * 1024, duration)
                ),
            )
        )
    return cluster, workload


def permute_same_ts(events, perm_seed: int):
    """Shuffle events WITHIN each same-timestamp group (stable time order
    across groups preserved) — emission order among equal timestamps is the
    degree of freedom under test."""
    rng = np.random.default_rng(perm_seed)
    by_ts: dict = {}
    for ev in events:
        by_ts.setdefault(ev[0], []).append(ev)
    out = []
    for ts in sorted(by_ts):
        group = by_ts[ts]
        rng.shuffle(group)
        out.extend(group)
    return out


def run_scalar(cluster, workload, config=None):
    from kubernetriks_tpu.trace.interface import Trace

    class _ListTrace(Trace):
        def __init__(self, events):
            self._events = events

        def convert_to_simulator_events(self):
            return list(self._events)

        def event_count(self):
            return len(self._events)

    sim = KubernetriksSimulation(config or default_test_simulation_config())
    sim.initialize(_ListTrace(cluster), _ListTrace(workload))
    sim.step_until_time(END_TIME)
    return sim


def run_batched(cluster, workload, config=None):
    sim = build_batched_from_traces(
        config or default_test_simulation_config(),
        cluster,
        workload,
        n_clusters=1,
    )
    sim.step_until_time(END_TIME)
    return sim


def terminal_signature(batched):
    """Comparable terminal summary of a batched run."""
    c = batched.metrics_summary()["counters"]
    view = batched.pod_view(0)
    return (
        c["pods_succeeded"],
        c["scheduling_decisions"],
        tuple(sorted((n, v["phase"], v["node"]) for n, v in view.items())),
    )


@pytest.mark.parametrize("perm_seed", [0, 1, 2])
def test_batched_matches_scalar_under_same_ts_permutations(perm_seed):
    """For every permutation of same-timestamp event groups, the batched
    terminal state equals the scalar oracle's — pod for pod."""
    cluster, workload = base_events(seed=7)
    cluster_p = permute_same_ts(cluster, perm_seed)
    workload_p = permute_same_ts(workload, perm_seed)

    scalar = run_scalar(list(cluster_p), list(workload_p))
    batched = run_batched(list(cluster_p), list(workload_p))

    sm = scalar.metrics_collector.accumulated_metrics
    c = batched.metrics_summary()["counters"]
    assert c["pods_succeeded"] == sm.pods_succeeded, perm_seed
    assert sm.pods_succeeded > 20, "scenario must be non-trivial"

    succeeded = scalar.persistent_storage.succeeded_pods
    cache = scalar.persistent_storage.unscheduled_pods_cache
    for name, b in batched.pod_view(0).items():
        if b["phase"] == PHASE_SUCCEEDED:
            pod = succeeded.get(name)
            assert pod is not None, (name, perm_seed)
            assert b["node"] == pod.status.assigned_node, (name, perm_seed)
        elif b["phase"] == PHASE_UNSCHEDULABLE:
            assert name in cache, (name, perm_seed)


FAULT_SUFFIX = """
fault_injection:
  enabled: true
  node:
    mttf: 350.0
    mttr: 60.0
  pod:
    fail_prob: 0.15
    restart_limit: 2
"""


def fault_base_events(seed: int):
    """base_events variant with DISTINCT node capacities: a chaos recovery
    re-creates its node on a fresh (later) slot, so the batched score
    argmax's last-in-slot-order tie-break can diverge from the scalar's
    last-in-name-order walk when two nodes score EXACTLY equal — which
    equal-capacity nodes do whenever both are empty (docs/PARITY.md).
    Distinct capacities make exact score ties impossible, keeping the
    permutation property about event ORDER, not float tie-breaks."""
    rng = np.random.default_rng(seed)
    caps = {"node_0": 16000, "node_1": 14000, "node_late": 18000}
    cluster = [
        (0.0, CreateNodeRequest(node=Node.new(n, caps[n], 32 * GiB)))
        for n in ("node_0", "node_1")
    ]
    cluster.append(
        (20.0, CreateNodeRequest(node=Node.new("node_late", caps["node_late"], 32 * GiB)))
    )
    workload = []
    for i in range(36):
        ts = float(rng.integers(0, 12)) * 5.0
        cpu = int(rng.choice([2000, 6000, 12000]))
        duration = float(rng.integers(4, 16)) * 5.0
        workload.append(
            (
                ts,
                CreatePodRequest(
                    pod=Pod.new(f"pod_{i:03d}", cpu, cpu * 1024 * 1024, duration)
                ),
            )
        )
    return cluster, workload


@pytest.mark.parametrize("perm_seed", [0, 1, 2])
def test_fault_interleavings_match_scalar_under_permutations(perm_seed):
    """Chaos extension of the permutation property: traces mixing pod
    arrivals, a planned RemoveNode, AND injected crashes/recoveries still
    reproduce the scalar oracle pod-for-pod under every same-timestamp
    permutation — including identical fault metrics. (Permuting same-ts
    CreateNode events permutes the fault compiler's node uids, so the crash
    schedules themselves vary across permutations; both paths derive them
    from the same permuted trace.)"""
    from kubernetriks_tpu.batched.state import PHASE_FAILED
    from kubernetriks_tpu.core.events import RemoveNodeRequest

    config = default_test_simulation_config(FAULT_SUFFIX)
    cluster, workload = fault_base_events(seed=7)
    # A planned removal rides alongside the injected crashes.
    cluster.append((400.0, RemoveNodeRequest(node_name="node_1")))
    cluster_p = permute_same_ts(cluster, perm_seed)
    workload_p = permute_same_ts(workload, perm_seed)

    scalar = run_scalar(list(cluster_p), list(workload_p), config)
    batched = run_batched(list(cluster_p), list(workload_p), config)

    sm = scalar.metrics_collector.accumulated_metrics
    c = batched.metrics_summary()["counters"]
    assert c["pods_succeeded"] == sm.pods_succeeded, perm_seed
    assert c["node_crashes"] == sm.node_crashes, perm_seed
    assert c["node_recoveries"] == sm.node_recoveries, perm_seed
    assert c["pod_interruptions"] == sm.pod_interruptions, perm_seed
    assert c["pod_restarts"] == sm.pod_restarts, perm_seed
    assert c["pods_failed"] == sm.pods_failed, perm_seed
    assert sm.node_crashes > 0, "scenario must inject at least one crash"
    assert sm.pod_restarts > 0, "scenario must exercise CrashLoopBackOff"

    succeeded = scalar.persistent_storage.succeeded_pods
    failed = scalar.persistent_storage.failed_pods
    cache = scalar.persistent_storage.unscheduled_pods_cache
    for name, b in batched.pod_view(0).items():
        if b["phase"] == PHASE_SUCCEEDED:
            pod = succeeded.get(name)
            assert pod is not None, (name, perm_seed)
            assert b["node"] == pod.status.assigned_node, (name, perm_seed)
        elif b["phase"] == PHASE_FAILED:
            assert name in failed, (name, perm_seed)
        elif b["phase"] == PHASE_UNSCHEDULABLE:
            assert name in cache, (name, perm_seed)


def test_permutation_shifts_are_shared():
    """When a permutation DOES change an outcome (FIFO-per-timestamp is real
    semantics, not an artifact), both paths shift together: the batched
    terminal signature varies across permutations only in ways the per-
    permutation scalar equality above already certifies. This pins that the
    property test actually exercises order-sensitive collisions."""
    signatures = set()
    for perm_seed in (0, 1, 2):
        cluster, workload = base_events(seed=7)
        batched = run_batched(
            permute_same_ts(cluster, perm_seed),
            permute_same_ts(workload, perm_seed),
        )
        signatures.add(terminal_signature(batched))
    # At least one permutation pair must differ somewhere (otherwise the
    # scenario is too easy to witness order sensitivity).
    assert len(signatures) >= 2, "permutations never changed any outcome"
