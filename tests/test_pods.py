"""End-to-end pod lifecycle scenarios (port of reference tests/test_pods.rs)."""

import pytest

from kubernetriks_tpu.core.types import PodConditionType
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CLUSTER_TRACE = """
events:
- timestamp: 30
  event_type:
    !CreateNode
      node:
        metadata:
          name: trace_node_42
        status:
          capacity:
            cpu: 2000
            ram: 4294967296
"""


def make_pod_event(name: str, cpu: int, ram: int, duration, ts: float) -> str:
    duration_line = f"running_duration: {duration}" if duration is not None else ""
    return f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: {name}
        spec:
          resources:
            requests:
              cpu: {cpu}
              ram: {ram}
            limits:
              cpu: {cpu}
              ram: {ram}
          {duration_line}
"""


def run_sim(cluster_yaml: str, workload_yaml: str, config_suffix: str = ""):
    sim = KubernetriksSimulation(default_test_simulation_config(config_suffix))
    sim.initialize(
        GenericClusterTrace.from_yaml(cluster_yaml),
        GenericWorkloadTrace.from_yaml(workload_yaml),
    )
    return sim


def test_pod_arrived_before_a_node():
    """reference: tests/test_pods.rs:75-116."""
    workload = "events:" + make_pod_event("pod_16", 2000, 4294967296, 100.0, 5)
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    pod = sim.persistent_storage.succeeded_pods["pod_16"]
    running = pod.get_condition(PodConditionType.POD_RUNNING)
    assert running.last_transition_time > 30.0
    assert pod.get_condition(PodConditionType.POD_SUCCEEDED) is not None


def test_many_pods_running_one_at_a_time_at_slow_node():
    """Node fits one pod at a time; pods serialize
    (reference: tests/test_pods.rs:119-215)."""
    workload = "events:" + "".join(
        make_pod_event(f"pod_{i}", 2000, 4294967296, 50.0, 10 + i) for i in range(3)
    )
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    finish_times = []
    for i in range(3):
        pod = sim.persistent_storage.succeeded_pods[f"pod_{i}"]
        succeeded = pod.get_condition(PodConditionType.POD_SUCCEEDED)
        assert succeeded is not None
        finish_times.append(succeeded.last_transition_time)
    finish_times.sort()
    # Each run takes 50s on a node that fits exactly one pod: finishes are
    # spaced at least ~50s apart.
    assert finish_times[1] - finish_times[0] >= 50.0
    assert finish_times[2] - finish_times[1] >= 50.0
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 3


def test_pods_run_in_parallel_when_fitting():
    """Three pods all fit the node simultaneously
    (reference: tests/test_pods.rs:218-313)."""
    workload = "events:" + "".join(
        make_pod_event(f"pod_{i}", 600, 1000000, 50.0, 10) for i in range(3)
    )
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    finish_times = [
        sim.persistent_storage.succeeded_pods[f"pod_{i}"]
        .get_condition(PodConditionType.POD_SUCCEEDED)
        .last_transition_time
        for i in range(3)
    ]
    assert max(finish_times) - min(finish_times) < 50.0
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 3


def test_node_remove_while_pods_were_running():
    """Node removed mid-run, returns at t=1100; pods reschedule and succeed
    (reference: tests/test_pods.rs:316-364)."""
    cluster = (
        CLUSTER_TRACE
        + """
- timestamp: 60
  event_type:
    !RemoveNode
      node_name: trace_node_42
- timestamp: 1100
  event_type:
    !CreateNode
      node:
        metadata:
          name: trace_node_42
        status:
          capacity:
            cpu: 2000
            ram: 4294967296
"""
    )
    workload = "events:" + make_pod_event(
        "pod_0", 333, 4967296, 100.0, 41
    ) + make_pod_event("pod_1", 333, 4967296, 100.0, 42)
    sim = run_sim(cluster, workload)
    sim.step_for_duration(1000.0)

    metrics = sim.metrics_collector.accumulated_metrics
    assert metrics.total_pods_in_trace == 2
    assert metrics.pods_succeeded == 0

    sim.step_for_duration(2000.0)
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 2


def test_node_removed_at_the_same_time_as_assignment():
    """Same-tick race: removal at t=50 coincides with the scheduling cycle;
    the api server's pending-removal guard drops the assignment
    (reference: tests/test_pods.rs:366-398)."""
    cluster = (
        CLUSTER_TRACE
        + """
- timestamp: 50
  event_type:
    !RemoveNode
      node_name: trace_node_42
"""
    )
    workload = "events:" + make_pod_event(
        "pod_0", 333, 4967296, 100.0, 41
    ) + make_pod_event("pod_1", 333, 4967296, 100.0, 42)
    sim = run_sim(cluster, workload)
    sim.step_for_duration(1000.0)

    metrics = sim.metrics_collector.accumulated_metrics
    assert metrics.total_pods_in_trace == 2
    assert metrics.pods_succeeded == 0


def test_pod_removal_before_scheduling():
    """Remove while still queued (no node yet)
    (reference: tests/test_pods.rs:401-449)."""
    workload = (
        "events:"
        + make_pod_event("pod_1", 8000, 4294967296, 500.0, 10)
        + """
- timestamp: 50
  event_type:
    !RemovePod
      pod_name: pod_1
"""
    )
    # Node too small: pod never schedules, sits in unschedulable queue.
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.step_for_duration(1000.0)
    assert sim.persistent_storage.get_pod("pod_1") is None
    assert sim.metrics_collector.accumulated_metrics.pods_removed == 0
    # Not marked removed from a node since it never ran; it was dropped from
    # queues. Unscheduled cache must not retain it.
    assert "pod_1" not in sim.persistent_storage.unscheduled_pods_cache


def test_pod_removal_while_running():
    """Remove a running pod: node frees resources, metrics count removal
    (reference: tests/test_pods.rs:401-510)."""
    workload = (
        "events:"
        + make_pod_event("pod_1", 2000, 4294967296, 500.0, 10)
        + """
- timestamp: 100
  event_type:
    !RemovePod
      pod_name: pod_1
"""
    )
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.step_for_duration(2000.0)

    assert sim.metrics_collector.accumulated_metrics.pods_removed == 1
    assert "pod_1" not in sim.persistent_storage.succeeded_pods
    node_component = sim.api_server.get_node_component("trace_node_42")
    assert node_component.runtime.node.status.allocatable.cpu == 2000
    assert not node_component.running_pods


def test_pod_removal_after_finish():
    """Remove request lands after the pod finished: removed=False path
    (reference: tests/test_pods.rs:597-637)."""
    workload = (
        "events:" + make_pod_event("pod_1", 2000, 4294967296, 50.0, 10) + """
- timestamp: 500
  event_type:
    !RemovePod
      pod_name: pod_1
"""
    )
    sim = run_sim(CLUSTER_TRACE, workload)
    sim.step_for_duration(2000.0)

    assert sim.metrics_collector.accumulated_metrics.pods_removed == 0
    assert sim.metrics_collector.accumulated_metrics.pods_succeeded == 1
    assert "pod_1" in sim.persistent_storage.succeeded_pods


def test_remove_unschedulable_pod_then_add_node_conditional_move():
    """Regression: removing a pod parked in the unschedulable queue must purge
    its queue entry; a later node arrival with conditional move scans the
    queue and would otherwise dereference the removed pod."""
    workload = (
        "events:"
        + make_pod_event("doomed", 8000, 4294967296, 500.0, 10)
        + """
- timestamp: 50
  event_type:
    !RemovePod
      pod_name: doomed
"""
    )
    cluster = (
        CLUSTER_TRACE
        + """
- timestamp: 100
  event_type:
    !CreateNode
      node:
        metadata:
          name: big_late_node
        status:
          capacity:
            cpu: 16000
            ram: 34359738368
"""
    )
    sim = run_sim(
        cluster, workload, "enable_unscheduled_pods_conditional_move: true\n"
    )
    sim.step_for_duration(1000.0)
    assert len(sim.scheduler.unschedulable_pods) == 0
    assert sim.scheduler.pod_count() == 0


def test_node_removal_frees_space_for_unschedulable_pod():
    """Big pod unschedulable while a small node is full; removing the blocker
    node is irrelevant — port covers removal freeing space scenario
    (reference: tests/test_pods.rs:513-594): a second bigger node joins later."""
    cluster = (
        CLUSTER_TRACE
        + """
- timestamp: 300
  event_type:
    !CreateNode
      node:
        metadata:
          name: big_node
        status:
          capacity:
            cpu: 16000
            ram: 34359738368
"""
    )
    workload = "events:" + make_pod_event("pod_big", 8000, 8589934592, 50.0, 10)
    sim = run_sim(cluster, workload)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())

    pod = sim.persistent_storage.succeeded_pods["pod_big"]
    scheduled = pod.get_condition(PodConditionType.POD_SCHEDULED)
    assert scheduled.status == "True"
    assert pod.status.assigned_node == "big_node"
    assert scheduled.last_transition_time > 300.0
