"""THE north-star determinism test (port of reference tests/test_determinism.rs):
random cluster + workload traces generated from the sim's own seeded RNG, run
repeatedly; pods_succeeded and all three timing estimators must be
bit-identical across runs.

Tier-1 runs the FAST scales by default (150/1500 x 3 — the former
KUBERNETRIKS_FAST_TESTS opt-in semantics, now the default: the
reference-scale run alone dominated the old ~36-min default suite). The
reference's own scale (~<=1000 node / ~<=10000 pod events, 1 + 10 repeat
runs, reference: tests/test_determinism.rs:70-126) lives in
test_simulation_determinism_reference_scale behind `-m slow`.
"""

from kubernetriks_tpu.metrics.collector import MetricsCollector
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

MAX_NODE_EVENTS = 150
MAX_POD_EVENTS = 1500
REPEAT_RUNS = 3


def generate_cluster_trace(sim: KubernetriksSimulation) -> GenericClusterTrace:
    """reference: tests/test_determinism.rs:14-47 (event mix: ~1/3 removals)."""
    import math

    kernel = sim.sim
    events = math.ceil(kernel.rand() * MAX_NODE_EVENTS)
    created_nodes = {}
    trace_events = []
    for _ in range(events):
        if math.ceil(kernel.rand() * 10.0) % 3.0 == 0.0 and created_nodes:
            next_node_name = sorted(created_nodes)[0]
            creation_ts = created_nodes.pop(next_node_name)
            trace_events.append(
                {
                    "timestamp": creation_ts + kernel.rand() * 10000.0,
                    "event_type": {"__tag__": "RemoveNode", "node_name": next_node_name},
                }
            )
        else:
            name = kernel.random_string(5)
            creation_ts = kernel.rand() * 1000.0
            cpu = math.ceil(kernel.rand() * 10000.0)
            ram = int(kernel.rand() * 100000000000.0)
            created_nodes[name] = creation_ts
            trace_events.append(
                {
                    "timestamp": creation_ts,
                    "event_type": {
                        "__tag__": "CreateNode",
                        "node": {
                            "metadata": {"name": name, "creation_timestamp": creation_ts},
                            "status": {"capacity": {"cpu": cpu, "ram": ram}},
                        },
                    },
                }
            )
    # Guarantee termination: one large always-alive node so every pod
    # eventually schedules (the reference relies on its seed for this).
    trace_events.append(
        {
            "timestamp": 0.0,
            "event_type": {
                "__tag__": "CreateNode",
                "node": {
                    "metadata": {"name": "anchor_node"},
                    "status": {
                        "capacity": {"cpu": 100000, "ram": 1000000000000}
                    },
                },
            },
        }
    )
    return GenericClusterTrace(events=trace_events)


def generate_workload_trace(sim: KubernetriksSimulation) -> GenericWorkloadTrace:
    """reference: tests/test_determinism.rs:49-68."""
    import math

    kernel = sim.sim
    events = math.ceil(kernel.rand() * MAX_POD_EVENTS)
    trace_events = []
    for _ in range(events):
        trace_events.append(
            {
                "timestamp": kernel.rand() * 100000.0,
                "event_type": {
                    "__tag__": "CreatePod",
                    "pod": {
                        "metadata": {"name": kernel.random_string(8)},
                        "spec": {
                            "resources": {
                                "requests": {
                                    "cpu": math.ceil(kernel.rand() * 1000.0),
                                    "ram": int(kernel.rand() * 10000000000.0),
                                },
                                "limits": {"cpu": 1000, "ram": 10000000000},
                            },
                            "running_duration": kernel.rand() * 1000.0,
                        },
                    },
                },
            }
        )
    return GenericWorkloadTrace(events=trace_events)


def run_simulation() -> MetricsCollector:
    config = default_test_simulation_config()
    config.seed = 46
    sim = KubernetriksSimulation(config)
    cluster_trace = generate_cluster_trace(sim)
    workload_trace = generate_workload_trace(sim)
    sim.initialize(cluster_trace, workload_trace)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())
    return sim.metrics_collector


import pytest


@pytest.mark.slow
def test_simulation_determinism_reference_scale():
    """The reference-scale run (tests/test_determinism.rs:70-126): the
    north-star determinism fact at full size. Minutes-long scalar-python
    repeats — behind -m slow so tier-1 iteration isn't gated on it."""
    global MAX_NODE_EVENTS, MAX_POD_EVENTS, REPEAT_RUNS
    saved = (MAX_NODE_EVENTS, MAX_POD_EVENTS, REPEAT_RUNS)
    MAX_NODE_EVENTS, MAX_POD_EVENTS, REPEAT_RUNS = 1000, 10000, 10
    try:
        test_simulation_determinism()
    finally:
        MAX_NODE_EVENTS, MAX_POD_EVENTS, REPEAT_RUNS = saved


def test_simulation_determinism():
    first = run_simulation()
    assert first.accumulated_metrics.pods_succeeded > 0
    for _ in range(REPEAT_RUNS):
        current = run_simulation()
        assert (
            first.accumulated_metrics.pods_succeeded
            == current.accumulated_metrics.pods_succeeded
        )
        assert (
            first.accumulated_metrics.pod_queue_time_stats
            == current.accumulated_metrics.pod_queue_time_stats
        )
        assert (
            first.accumulated_metrics.pod_scheduling_algorithm_latency_stats
            == current.accumulated_metrics.pod_scheduling_algorithm_latency_stats
        )
        assert (
            first.accumulated_metrics.pod_duration_stats
            == current.accumulated_metrics.pod_duration_stats
        )


def test_oracle_golden_values():
    """Pin the scalar oracle's EXACT metric values for seed 46 at the fast
    scale (VERDICT r1: determinism was asserted run-to-run but nothing
    guarded the oracle itself against silent regressions). Any change to
    event ordering, delay composition, tie-breaks, or the RNG shifts these
    numbers and must be a conscious decision."""
    global MAX_NODE_EVENTS, MAX_POD_EVENTS
    saved = (MAX_NODE_EVENTS, MAX_POD_EVENTS)
    MAX_NODE_EVENTS, MAX_POD_EVENTS = 150, 1500
    try:
        mc = run_simulation()
    finally:
        MAX_NODE_EVENTS, MAX_POD_EVENTS = saved
    m = mc.accumulated_metrics
    assert m.pods_succeeded == 274
    assert m.pod_queue_time_stats.min() == 0.004830714602652006
    assert m.pod_queue_time_stats.max() == 9.917483625002205
    assert m.pod_queue_time_stats.mean() == 4.985349303244703
    assert m.pod_duration_stats.min() == 1.8261357929489908
    assert m.pod_duration_stats.max() == 997.4819772974708
    assert m.pod_duration_stats.mean() == 505.97398806872496
