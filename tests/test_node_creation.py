"""Trace node + default node creation (port of reference tests/test_node_creation.rs)."""

from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import (
    check_count_of_nodes_in_components_equals_to,
    check_expected_node_appeared_in_components,
    default_test_simulation_config,
)
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CLUSTER_TRACE = """
events:
- timestamp: 100
  event_type:
    !CreateNode
      node:
        metadata:
          name: trace_node
        status:
          capacity:
            cpu: 2000
            ram: 4294967296
"""


def test_node_creation_from_trace_and_default_cluster():
    config = default_test_simulation_config(
        """
default_cluster:
- node_template:
      metadata:
        name: default_super_node
      status:
        capacity:
          cpu: 64000
          ram: 137438953472
"""
    )
    sim = KubernetriksSimulation(config)
    sim.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(""),
    )
    # Default node exists immediately; the trace node appears only after its
    # timestamp + control-plane round trips.
    check_count_of_nodes_in_components_equals_to(1, sim)
    check_expected_node_appeared_in_components("default_super_node", sim)

    sim.step_for_duration(1000.0)
    check_count_of_nodes_in_components_equals_to(2, sim)
    check_expected_node_appeared_in_components("trace_node", sim)
    assert sim.metrics_collector.accumulated_metrics.total_nodes_in_trace == 1
    assert sim.metrics_collector.accumulated_metrics.internal.processed_nodes == 1
