"""Streaming trace-ingestion pipeline (batched/stream.py + engine wiring).

The feeder generalizes PR 3's double-buffered staging into a K-deep ring
of device-resident RefillStage slabs produced by a background thread, and
must change NOTHING about what the simulation computes:

1. Bit-identity: a composed flagship run (HPA + CA + sliding window) with
   the streaming feeder staging every slab — chaos faults ON — matches
   the resident whole-trace ladder path on every state leaf and metric.
   (The resident ladder == fused == resident superspan equalities are
   pinned by test_window_donation_dispatch.py and test_superspan.py; the
   streaming-vs-ladder compare closes the square.)
2. No new host syncs: the steady-state budget stays ONE progress readback
   per superspan (feeder work rides its own thread), and at identical
   stage geometry the streaming run's dispatch/sync counts EQUAL the
   non-streaming bounded double-buffer baseline's.
3. Segment boundaries: minimal-width slabs force mid-run SUPERSPAN_STAGE
   exhaustion exits and restages through the ring; run-ahead geometry
   (stride > 0) restages through slabs produced AHEAD of demand.
4. K = 1 degenerate ring and checkpoint save/restore mid-stream (the
   restore re-seeks the feeder; slab content is position-keyed, so no
   replay divergence is possible).
5. Bounded memory: a long plain trace runs with a segment budget far
   below the whole compiled payload and matches the scalar oracle.
6. The ring never re-offers a spent slab (unit-level, fake slabs).
"""

import numpy as np
import pytest

import kubernetriks_tpu.batched.engine as engine_mod
from kubernetriks_tpu.batched.state import compare_states, strip_telemetry
from kubernetriks_tpu.batched.stream import StreamFeeder

from test_superspan import FAULT_SUFFIX, _run
from test_window_donation_dispatch import _build_composed


def _stream_build(**kwargs):
    kwargs.setdefault("superspan", True)
    kwargs.setdefault("superspan_k", 4)
    kwargs.setdefault("superspan_chunk", 4)
    kwargs.setdefault("stream", True)
    kwargs.setdefault("stream_segment", 96)
    kwargs.setdefault("stream_depth", 2)
    return _build_composed(**kwargs)


def _assert_streamed(sim):
    """The feeder really staged the run — no silent fallback to resident
    whole-trace staging, no ladder dispatches, sync budget intact."""
    assert sim._device_slide is None
    assert sim.dispatch_stats["superspans"] > 0
    assert sim.dispatch_stats["window_chunks"] == 0
    assert sim.dispatch_stats["stage_refills"] > 0
    assert (
        sim.dispatch_stats["feeder_slabs_produced"]
        >= sim.dispatch_stats["stage_refills"]
    )
    # Feeder work rides its own thread, not new host syncs.
    assert (
        sim.dispatch_stats["slide_syncs"] == sim.dispatch_stats["superspans"]
    )


@pytest.fixture(scope="module")
def ladder_fault():
    return _run(
        _build_composed(
            config_suffix=FAULT_SUFFIX, donate=False, fuse_slide=False
        )
    )


@pytest.fixture(scope="module")
def ladder_ff():
    return _run(_build_composed(donate=False, fuse_slide=False))


@pytest.fixture(scope="module")
def stream_ff(ladder_ff):
    """Fault-free streaming run at minimal stage width (96 = W + W/2):
    demand-mode staging, several segment-boundary restages — anchored
    against the resident ladder here, reused by the sync-equality and
    checkpoint tests below."""
    sim = _run(_stream_build())
    _assert_streamed(sim)
    assert sim.dispatch_stats["stage_refills"] >= 2, (
        "minimal-width slabs produced no mid-run restage; boundary "
        "coverage is vacuous"
    )
    assert compare_states(strip_telemetry(sim.state), ladder_ff.state) == []
    assert sim.metrics_summary() == ladder_ff.metrics_summary()
    return sim


def test_streaming_composed_bit_identical_under_faults(ladder_fault):
    """Flagship composition + chaos: every node-crash chain and
    commit-time pod-failure draw must land identically when every refill
    column the on-device slides consume came through the feeder ring."""
    ss = _run(_stream_build(config_suffix=FAULT_SUFFIX))
    assert ss.fault_params is not None
    counters = ss.metrics_summary()["counters"]
    assert counters["pod_interruptions"] + counters["pods_failed"] > 0, (
        "fault run produced no faults; parity under faults is vacuous"
    )
    _assert_streamed(ss)
    assert ss.dispatch_stats["stage_refills"] >= 2
    assert ss._pod_base == ladder_fault._pod_base
    assert ss.next_window_idx == ladder_fault.next_window_idx
    assert (
        compare_states(strip_telemetry(ss.state), ladder_fault.state) == []
    )
    assert ss.metrics_summary() == ladder_fault.metrics_summary()
    np.testing.assert_array_equal(
        np.asarray(ss.autoscale_statics.pod_name_rank),
        np.asarray(ladder_fault.autoscale_statics.pod_name_rank),
    )
    ss.close()


def test_streaming_syncs_equal_bounded_double_buffer(
    stream_ff, monkeypatch
):
    """The no-new-syncs gate against the PR 3 baseline: at identical
    stage geometry (same slab width, hence the same compiled superspan
    program), the streaming run's slab schedule reproduces the
    double-buffered engine's — equal superspan dispatches, equal
    progress-readback syncs, equal installs — with the assembly moved off
    the engine thread."""
    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 0)
    baseline = _run(
        _build_composed(
            superspan=True,
            superspan_k=4,
            superspan_chunk=4,
            superspan_stage_cols=96,
            stream=False,
            fuse_slide=False,
        )
    )
    assert baseline._device_slide is None and baseline._feeder is None
    assert compare_states(
        strip_telemetry(stream_ff.state), baseline.state
    ) == []
    for key in ("superspans", "slide_syncs", "stage_refills"):
        assert (
            stream_ff.dispatch_stats[key] == baseline.dispatch_stats[key]
        ), key


def test_streaming_run_ahead_restages_through_ring(ladder_ff):
    """Run-ahead geometry (L = 2W, stride = W/2 > 0): the producer
    schedules slabs AHEAD of consumption, exhaustion exits install the
    already-uploaded successor, and the result still matches the resident
    ladder bit for bit."""
    sim = _run(_stream_build(stream_segment=128, stream_depth=3))
    _assert_streamed(sim)
    rep = sim._feeder.report()
    assert rep["stride_cols"] > 0, "geometry did not produce run-ahead"
    assert sim.dispatch_stats["stage_refills"] >= 2
    assert rep["ring_depth_high_water"] <= 3
    assert compare_states(strip_telemetry(sim.state), ladder_ff.state) == []
    assert sim.metrics_summary() == ladder_ff.metrics_summary()
    sim.close()


def test_streaming_k1_degenerate_ring(ladder_ff):
    """stream_depth=1: the ring holds at most ONE slab (the producer
    blocks until the consumer frees it) — synchronous-but-off-thread
    staging, still exact."""
    sim = _run(_stream_build(stream_segment=128, stream_depth=1))
    _assert_streamed(sim)
    rep = sim._feeder.report()
    assert rep["ring_capacity"] == 1
    assert rep["ring_depth_high_water"] == 1
    assert compare_states(strip_telemetry(sim.state), ladder_ff.state) == []
    sim.close()


def test_streaming_checkpoint_restore_reseeks_feeder(stream_ff, tmp_path):
    """Mid-stream checkpoint: save while the feeder holds live slabs,
    restore into a FRESH streaming engine, continue — the restore
    re-seeks the feeder (closed + rebuilt at the restored base, no slab
    replay) and the continued run matches the uninterrupted one exactly."""
    first = _stream_build()
    first.step_until_time(150.0)
    assert first._feeder is not None, "no slab staged before the save"
    path = str(tmp_path / "ckpt")
    first.save_checkpoint(path)
    first.close()

    resumed = _stream_build()
    resumed.load_checkpoint(path)
    assert resumed._feeder is None, "restore must re-seek (drop) the feeder"
    assert resumed._pod_base == first._pod_base
    for end in (300.0, 450.0):
        resumed.step_until_time(end)
    _assert_streamed(resumed)
    assert resumed._pod_base == stream_ff._pod_base
    assert (
        compare_states(
            strip_telemetry(resumed.state), strip_telemetry(stream_ff.state)
        )
        == []
    )
    assert resumed.metrics_summary() == stream_ff.metrics_summary()
    resumed.close()


def test_streaming_long_trace_bounded_memory_vs_scalar_oracle():
    """The memory-bound acceptance gate: a long plain trace (no
    autoscalers) streams through slabs whose width is far below the whole
    compiled payload — the whole-trace device payload is never built, the
    ring never exceeds its depth, restages happen throughout — and the
    readout matches the float64 scalar oracle."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
    from kubernetriks_tpu.test_util import default_test_simulation_config
    from kubernetriks_tpu.trace.generator import UniformClusterTrace
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    N_PODS, END = 400, 900.0

    def workload_yaml():
        return GenericWorkloadTrace.from_yaml(
            "events:"
            + "".join(
                f"""
- timestamp: {1.0 + i}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:04d}
        spec:
          resources:
            requests: {{cpu: 100, ram: 104857600}}
            limits: {{cpu: 100, ram: 104857600}}
          running_duration: {20.0 + (i % 5) * 5.0}
"""
                for i in range(N_PODS)
            )
        )

    config = default_test_simulation_config()
    cluster = UniformClusterTrace(6, cpu=16000, ram=32 * 1024**3)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(cluster, workload_yaml())
    scalar.step_until_time(END)
    sm = scalar.metrics_collector.accumulated_metrics

    sim = build_batched_from_traces(
        config,
        UniformClusterTrace(6, cpu=16000, ram=32 * 1024**3)
        .convert_to_simulator_events(),
        workload_yaml().convert_to_simulator_events(),
        n_clusters=1,
        max_pods_per_cycle=16,
        pod_window=64,
        fast_forward=False,
        superspan=True,
        superspan_k=8,
        superspan_chunk=4,
        stream=True,
        stream_segment=96,
        stream_depth=2,
    )
    sim.step_until_time(END)
    _assert_streamed(sim)
    # Segment budget far below the whole payload, ring bounded, many
    # segment boundaries crossed.
    rep = sim._feeder.report() if sim._feeder else None
    assert rep is not None
    assert rep["segment_cols"] * 3 < rep["trace_cols"], (
        "segment budget is not far below the whole payload; the memory "
        "bound is vacuous"
    )
    assert rep["ring_depth_high_water"] <= 2
    assert sim.dispatch_stats["stage_refills"] >= 3
    assert sim._pod_base > 0

    bm = sim.metrics_summary()
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded == N_PODS
    assert bm["counters"]["pods_removed"] == sm.pods_removed
    assert (
        bm["counters"]["terminated_pods"] == sm.internal.terminated_pods
    )
    for key, est in [
        ("pod_duration", sm.pod_duration_stats),
        ("pod_queue_time", sm.pod_queue_time_stats),
        ("pod_schedule_time", sm.pod_scheduling_algorithm_latency_stats),
    ]:
        got = bm["timings"][key]
        assert got["min"] == pytest.approx(est.min(), rel=1e-4, abs=1e-3), key
        assert got["max"] == pytest.approx(est.max(), rel=1e-4, abs=1e-3), key
        assert got["mean"] == pytest.approx(est.mean(), rel=1e-4, abs=1e-3), key
    sim.close()


def test_payload_source_seam_releases_host_arrays_bit_identical():
    """Host O(T) bound (r14, ROADMAP #2): attach_payload_source swaps the
    resident whole-trace request/duration arrays for a bounded
    segment-at-a-time source (trace.feeder reader contract) and RELEASES
    them. The feeder-sourced run must be BIT-identical to the resident
    run — FeederPayloadSource mirrors compile_from_arrays' conversions
    (int32 millicores, ceil-div RAM quantization, float64 seconds), so a
    staged slab cannot differ — and host_payload_bytes must drop by the
    released arrays' size while the small int32 tables stay disclosed."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.batched.trace_compile import FeederPayloadSource
    from kubernetriks_tpu.test_util import default_test_simulation_config
    from kubernetriks_tpu.trace.feeder import WorkloadArrays, WorkloadArraysReader
    from kubernetriks_tpu.trace.generator import UniformClusterTrace
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    N_PODS, END = 220, 520.0
    specs = [
        (1.0 + i, 100 + (i % 4) * 50, (100 + (i % 3) * 37) * 1024**2,
         20.0 + (i % 5) * 5.0)
        for i in range(N_PODS)
    ]

    def workload_yaml():
        return GenericWorkloadTrace.from_yaml(
            "events:"
            + "".join(
                f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i:04d}
        spec:
          resources:
            requests: {{cpu: {cpu}, ram: {ram}}}
            limits: {{cpu: {cpu}, ram: {ram}}}
          running_duration: {dur}
"""
                for i, (ts, cpu, ram, dur) in enumerate(specs)
            )
        )

    def build():
        return build_batched_from_traces(
            default_test_simulation_config(),
            UniformClusterTrace(6, cpu=16000, ram=32 * 1024**3)
            .convert_to_simulator_events(),
            workload_yaml().convert_to_simulator_events(),
            n_clusters=1,
            max_pods_per_cycle=16,
            pod_window=64,
            fast_forward=False,
            superspan=True,
            superspan_k=8,
            superspan_chunk=4,
            stream=True,
            stream_segment=96,
            stream_depth=2,
        )

    resident = build()
    fed = build()
    # The reader rows ARE the payload columns of a pure-workload trace
    # (pod slots assigned in row order); WorkloadArraysReader is the
    # python-oracle stand-in for the native WorkloadSegmentReader.
    rows = sorted(specs, key=lambda s: s[0])
    arrays = WorkloadArrays(
        start_ts=np.asarray([r[0] for r in rows], np.float64),
        cpu_millicores=np.asarray([r[1] for r in rows], np.int64),
        ram_bytes=np.asarray([r[2] for r in rows], np.int64),
        duration=np.asarray([r[3] for r in rows], np.float64),
        job_id=np.full(N_PODS, -1, np.int64),
        task_id=np.zeros(N_PODS, np.int64),
        pod_no=np.arange(N_PODS, dtype=np.int64),
    )
    before = fed._slab_accounting()["host_payload_bytes"]
    fed.attach_payload_source(
        FeederPayloadSource(
            WorkloadArraysReader(arrays), n_clusters=1, ram_unit=fed.ram_unit
        )
    )
    after = fed._slab_accounting()["host_payload_bytes"]
    assert fed._full_pods is None, "resident payload arrays must be released"
    assert after < before, (before, after)

    # The attach-time fidelity gate: a source whose rows disagree with the
    # compiled payload (wrong trace, broken conversions, or a single
    # workload broadcast onto a heterogeneous fleet) must raise LOUDLY
    # before anything is released — silent wrong trajectories are the
    # failure mode the gate exists for. The failed attach must leave the
    # engine on its previous (verified) source.
    bad = WorkloadArrays(
        start_ts=arrays.start_ts,
        cpu_millicores=arrays.cpu_millicores,
        ram_bytes=arrays.ram_bytes,
        duration=arrays.duration + np.float64(1.0),
        job_id=arrays.job_id,
        task_id=arrays.task_id,
        pod_no=arrays.pod_no,
    )
    with pytest.raises(ValueError, match="disagrees with the compiled"):
        fed.attach_payload_source(
            FeederPayloadSource(
                WorkloadArraysReader(bad), n_clusters=1, ram_unit=fed.ram_unit
            )
        )

    resident.step_until_time(END)
    fed.step_until_time(END)
    _assert_streamed(resident)
    _assert_streamed(fed)
    assert fed.dispatch_stats == resident.dispatch_stats
    mismatches = compare_states(
        strip_telemetry(resident.state), strip_telemetry(fed.state)
    )
    assert mismatches == [], mismatches
    # The seam really fed segments (not a vacuous pass-through).
    assert fed.dispatch_stats["stage_refills"] >= 3
    resident.close()
    fed.close()


# --- unit-level ring semantics (fake slabs, no jax) -----------------------


def _fake_feeder(**kwargs):
    def assemble(lo, width):
        return {"lo": lo, "width": width}

    def upload(seg):
        return ("slab", seg["lo"], seg["width"])

    kwargs.setdefault("base", 0)
    kwargs.setdefault("window", 64)
    kwargs.setdefault("trace_cols", 10_000)
    return StreamFeeder(assemble, upload, settle=None, **kwargs)


def test_feeder_never_reoffers_spent_or_retired_slab():
    f = _fake_feeder(width=256, depth=2)  # stride 160: run-ahead mode
    stage, lo, fresh = f.get_stage(0)
    assert (lo, fresh) == (0, True)
    assert stage == ("slab", 0, 256)
    # Serving again without moving is NOT fresh (no double refill count).
    _, _, fresh = f.get_stage(64)
    assert not fresh
    f.retire(0)
    # The retired slab still COVERS base 100 (0 + 256 - 64 >= 100), but it
    # must never be served again: the ring's head is now the slab at 160,
    # and a base below it is a seek error, not a re-offer.
    with pytest.raises(AssertionError, match="never .e-offered|re-offer"):
        f.get_stage(100)
    f.close()


def test_feeder_ring_is_bounded_and_runs_ahead():
    f = _fake_feeder(width=256, depth=2)
    f.get_stage(0)  # wait until the first slab exists
    deadline = 200
    while f.ring_high_water < 2 and deadline:  # producer runs ahead to K
        deadline -= 1
        import time as _t

        _t.sleep(0.01)
    assert f.ring_high_water == 2, "producer never filled the ring to K"
    # Advance the base across several strides: spent slabs are dropped,
    # fresh slabs install, the ring NEVER exceeds its depth.
    served = [f.get_stage(base)[1] for base in (200, 400, 600, 800)]
    assert served == sorted(served)
    assert f.ring_high_water <= 2
    rep = f.report()
    assert rep["slabs_produced"] >= len(set(served))
    f.close()


def test_feeder_demand_mode_builds_exactly_on_demand():
    f = _fake_feeder(width=96, depth=2)  # stride 0: demand mode
    assert not f.ahead
    _, lo0, _ = f.get_stage(0)
    assert lo0 == 0
    f.retire(0)
    _, lo1, fresh = f.get_stage(40)
    assert (lo1, fresh) == (40, True)
    rep = f.report()
    assert rep["ring_depth_high_water"] == 1  # never runs ahead
    assert rep["slabs_produced"] == 2
    f.close()


def test_feeder_producer_error_propagates():
    def assemble(lo, width):
        raise RuntimeError("boom at lo=%d" % lo)

    f = StreamFeeder(
        assemble,
        lambda seg: seg,
        base=0,
        width=96,
        window=64,
        trace_cols=1000,
        depth=2,
        settle=None,
    )
    with pytest.raises(RuntimeError, match="stream feeder producer failed"):
        f.get_stage(0)
    f.close()


# --- fault domain: death context, chaos kills, supervised restart ---------


def test_feeder_producer_error_carries_slab_context():
    """Producer death crosses the thread boundary WITH its slab context:
    the consumer-facing FeederProducerError names the slab index and
    payload span the producer was building when it died, and chains the
    original exception (DESIGN §15)."""
    from kubernetriks_tpu.batched.faults import FeederProducerError

    def assemble(lo, width):
        if lo >= 96:
            raise RuntimeError("disk on fire at lo=%d" % lo)
        return {"lo": lo, "width": width}

    f = StreamFeeder(
        assemble,
        lambda seg: ("slab", seg["lo"]),
        base=0,
        width=96,
        window=64,
        trace_cols=10_000,
        depth=2,
        settle=None,
    )
    _, lo0, _ = f.get_stage(0)
    assert lo0 == 0
    f.retire(0)
    with pytest.raises(FeederProducerError) as exc_info:
        f.get_stage(96)
    err = exc_info.value
    assert isinstance(err, RuntimeError)  # the pre-existing contract class
    assert (err.slab_lo, err.width) == (96, 96)
    assert "stream feeder producer failed" in str(err)
    assert "slab lo=96 span=[96, 192)" in str(err)
    assert "disk on fire" in str(err)
    assert isinstance(err.__cause__, RuntimeError)
    f.close()


def test_feeder_chaos_kill_surfaces_with_slab_context():
    """The KTPU_HOST_CHAOS feeder channel draws INSIDE the producer
    thread: an injected kill surfaces to the consumer exactly like a real
    producer death — typed, with the slab being built named."""
    from kubernetriks_tpu.batched.faults import (
        FeederProducerError,
        HostChaos,
        InjectedFeederKill,
    )

    f = _fake_feeder(
        width=96, depth=2, chaos=HostChaos(seed=3, feeder_rate=1.0)
    )
    with pytest.raises(FeederProducerError) as exc_info:
        f.get_stage(0)
    err = exc_info.value
    assert err.slab_lo == 0
    assert "injected stream-feeder kill" in str(err)
    assert isinstance(err.__cause__, InjectedFeederKill)
    f.close()


def test_feeder_retired_watermark_survives_restart():
    """The supervisor's carry-over: a replacement feeder built with the
    dead ring's retired-slab high-water mark keeps the never-re-offer
    invariant across the restart — at/below the watermark asserts,
    strictly past it serves."""
    f = _fake_feeder(width=256, depth=2)
    _, lo0, _ = f.get_stage(0)
    f.retire(lo0)
    assert f.retired_watermark() == lo0
    f.close()
    reoffer = _fake_feeder(width=256, depth=2, base=0, retired_lo=lo0)
    with pytest.raises(AssertionError, match="retired"):
        reoffer.get_stage(0)
    reoffer.close()
    onward = _fake_feeder(width=256, depth=2, base=160, retired_lo=lo0)
    _, lo, fresh = onward.get_stage(160)
    assert lo > lo0 and fresh
    onward.close()


class _KillNth:
    """Duck-typed chaos for the supervisor test: kill exactly the Nth
    slab-build attempts (deterministic, schedule-independent)."""

    def __init__(self, kills):
        self.kills = set(kills)
        self.calls = 0

    def feeder_kill(self):
        self.calls += 1
        return self.calls in self.kills


def test_feeder_supervisor_restarts_and_preserves_bit_identity(ladder_ff):
    """The engine's feeder supervisor: two injected producer deaths
    mid-run each restart the feeder (backoff + retired-watermark carry) —
    the run completes, bit-matches the resident ladder on every state
    leaf and metric, and the restart count lands in
    telemetry_report()['feeder']."""
    sim = _stream_build()
    kills = _KillNth({1, 3})
    sim._feeder_chaos = kills  # before the first staged dispatch
    _run(sim)
    _assert_streamed(sim)
    assert kills.calls >= 4, "the killed builds were never retried"
    rep = sim.telemetry_report()["feeder"]
    assert rep["restarts"] == 2
    assert compare_states(strip_telemetry(sim.state), ladder_ff.state) == []
    assert sim.metrics_summary() == ladder_ff.metrics_summary()
    sim.close()
