"""Kernel semantics: event ordering, FIFO tie-break, cancellation, determinism.

Mirrors the behaviors the reference inherits from dslab-core (reference:
src/simulator.rs:74-186 usage; tests/test_cast_box.rs event shape).
"""

from dataclasses import dataclass

from kubernetriks_tpu.sim.kernel import EventHandler, Simulation


@dataclass
class Ping:
    tag: str


class Recorder(EventHandler):
    def __init__(self):
        self.seen = []

    def on_ping(self, data: Ping, time: float) -> None:
        self.seen.append((time, data.tag))


def test_time_ordering_and_fifo_tiebreak():
    sim = Simulation(seed=1)
    rec = Recorder()
    dst = sim.add_handler("rec", rec)
    ctx = sim.create_context("src")

    ctx.emit(Ping("late"), dst, 5.0)
    ctx.emit(Ping("first_at_2"), dst, 2.0)
    ctx.emit(Ping("second_at_2"), dst, 2.0)
    ctx.emit(Ping("early"), dst, 1.0)

    sim.step_until_no_events()
    assert rec.seen == [
        (1.0, "early"),
        (2.0, "first_at_2"),
        (2.0, "second_at_2"),
        (5.0, "late"),
    ]
    assert sim.time() == 5.0
    assert sim.event_count() == 4


def test_cancellation():
    sim = Simulation(seed=1)
    rec = Recorder()
    dst = sim.add_handler("rec", rec)
    ctx = sim.create_context("src")

    keep = ctx.emit(Ping("keep"), dst, 1.0)
    drop = ctx.emit(Ping("drop"), dst, 2.0)
    ctx.cancel_event(drop)
    sim.step_until_no_events()
    assert [tag for _, tag in rec.seen] == ["keep"]
    assert keep != drop


def test_step_until_time_advances_clock_without_events():
    sim = Simulation(seed=1)
    rec = Recorder()
    dst = sim.add_handler("rec", rec)
    ctx = sim.create_context("src")
    ctx.emit(Ping("a"), dst, 3.0)
    sim.step_until_time(2.0)
    assert sim.time() == 2.0
    assert rec.seen == []
    sim.step_until_time(10.0)
    assert rec.seen == [(3.0, "a")]
    assert sim.time() == 10.0


def test_rng_determinism():
    draws = []
    for _ in range(2):
        sim = Simulation(seed=46)
        ctx = sim.create_context("c")
        draws.append(
            [ctx.gen_range_float(0.0, 1.0) for _ in range(100)]
            + [float(ctx.gen_range_int(0, 1000)) for _ in range(100)]
        )
    assert draws[0] == draws[1]


def test_handler_self_events():
    class SelfTicker(EventHandler):
        def __init__(self, sim):
            self.ctx = sim.create_context("ticker")
            sim.add_handler("ticker", self)
            self.ticks = 0

        def start(self):
            self.ctx.emit_self(Ping("tick"), 1.0)

        def on_ping(self, data: Ping, time: float) -> None:
            self.ticks += 1
            if self.ticks < 5:
                self.ctx.emit_self(Ping("tick"), 1.0)

    sim = Simulation(seed=0)
    ticker = SelfTicker(sim)
    ticker.start()
    sim.step_until_no_events()
    assert ticker.ticks == 5
    assert sim.time() == 5.0
