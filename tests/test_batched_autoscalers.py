"""Batched HPA / cluster-autoscaler passes reproduce the scalar golden
scenarios (tests/test_hpa.py, tests/test_cluster_autoscaler.py) on a whole
cluster batch at once."""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

from tests.test_hpa import CLUSTER_TRACE, WORKLOAD_TRACE

N_CLUSTERS = 3


def _build(config, cluster_yaml, workload_yaml, **kwargs):
    return build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(cluster_yaml).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload_yaml).convert_to_simulator_events(),
        n_clusters=N_CLUSTERS,
        **kwargs,
    )


def test_batched_hpa_golden_trajectory():
    """Replica counts 5->9->14->(hold)->4->(hold)->7->12->14 at the 60 s
    cycle boundaries, identically in every cluster of the batch (scalar
    golden: tests/test_hpa.py; reference: tests/test_hpa.rs:90-135)."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True

    sim = _build(config, CLUSTER_TRACE, WORKLOAD_TRACE)
    expected = [
        (61.0, 5),
        (121.0, 9),
        (181.0, 14),
        (450.0, 14),
        (600.5, 4),
        (759.5, 4),
        (781.0, 7),
        (841.0, 12),
        (901.0, 14),
        (1200.0, 14),
    ]
    for until, replicas in expected:
        sim.step_until_time(until)
        for c in range(N_CLUSTERS):
            assert sim.hpa_replicas(c) == {"pod_group_1": replicas}, (
                f"at t={until}"
            )

    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_pods"] == (4 + 5 + 3 + 5 + 2) * N_CLUSTERS
    assert counters["total_scaled_down_pods"] == 10 * N_CLUSTERS


def test_batched_hpa_scaled_down_pods_terminate():
    """Scale-down marks the oldest pods for removal; they terminate as removed
    and free node resources."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True

    sim = _build(config, CLUSTER_TRACE, WORKLOAD_TRACE)
    sim.step_until_time(700.0)  # after the 14 -> 4 scale-down
    counters = sim.metrics_summary()["counters"]
    assert counters["pods_removed"] == 10 * N_CLUSTERS
    # The 4 survivors are still running.
    view = sim.pod_view(0)
    from kubernetriks_tpu.batched.state import PHASE_REMOVED, PHASE_RUNNING

    phases = [v["phase"] for v in view.values()]
    assert phases.count(PHASE_RUNNING) == 4
    assert phases.count(PHASE_REMOVED) == 10


CA_CONFIG_SUFFIX = """
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 10
  node_groups:
  - node_template:
      metadata:
        name: autoscaler_node
      status:
        capacity:
          cpu: 16000
          ram: 34359738368
"""


def _ca_workload(n_pods=4, cpu=4000, ram=8589934592, duration=50.0):
    return "events:" + "".join(
        f"""
- timestamp: {5 + i}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i}
        spec:
          resources:
            requests:
              cpu: {cpu}
              ram: {ram}
            limits:
              cpu: {cpu}
              ram: {ram}
          running_duration: {duration}
"""
        for i in range(n_pods)
    )


def test_batched_ca_scale_up_then_down():
    """Pods arrive with no cluster; CA bin-packs them onto one scaled-up node;
    after they finish, the idle node is scaled back down (scalar golden:
    tests/test_cluster_autoscaler.py::test_end_to_end_scale_up_then_down)."""
    config = default_test_simulation_config(CA_CONFIG_SUFFIX)
    sim = _build(config, "", _ca_workload())

    sim.step_until_time(300.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["pods_succeeded"] == 4 * N_CLUSTERS
    # All four pods fit one 16000-millicore template node.
    assert counters["total_scaled_up_nodes"] == 1 * N_CLUSTERS
    assert counters["total_scaled_down_nodes"] == 1 * N_CLUSTERS
    for c in range(N_CLUSTERS):
        assert sim.ca_node_counts(c).sum() == 0
    # The scaled-up node slot is dead again.
    assert not np.asarray(sim.state.nodes.alive).any()


def test_batched_ca_respects_global_max():
    """max_node_count=1 caps scale-up regardless of demand."""
    suffix = CA_CONFIG_SUFFIX.replace("max_node_count: 10", "max_node_count: 1")
    config = default_test_simulation_config(suffix)
    # 8 pods x 4000 mcpu need 2 nodes; quota allows 1.
    sim = _build(config, "", _ca_workload(n_pods=8))

    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 1 * N_CLUSTERS


def test_batched_ca_scale_down_waits_for_movable_pods():
    """A CA node keeps long-running pods that fit nowhere else: never scaled
    down while they run."""
    config = default_test_simulation_config(CA_CONFIG_SUFFIX)
    sim = _build(config, "", _ca_workload(n_pods=1, cpu=2000, duration=-1.0)
        .replace("running_duration: -1.0", "running_duration: null"))

    sim.step_until_time(200.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 1 * N_CLUSTERS
    # 2000/16000 cpu = 12.5% < 50% threshold, but the pod has nowhere to go.
    assert counters["total_scaled_down_nodes"] == 0
    for c in range(N_CLUSTERS):
        assert sim.ca_node_counts(c).sum() == 1


HIGH_INITIAL_WORKLOAD_TRACE = """
events:
- timestamp: 59.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: pod_group_1
        initial_pod_count: 6
        max_pod_count: 3
        pod_template:
          metadata:
            name: pod_group_1
          spec:
            resources:
              requests:
                cpu: 100
                ram: 104857600
              limits:
                cpu: 100
                ram: 104857600
        target_resources_usage:
          cpu_utilization: 0.6
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 300.0
                total_load: 0.6
              - duration: 300.0
                total_load: 6
"""


def test_batched_hpa_scale_up_after_deep_scale_down():
    """A group whose initial_pod_count exceeds the slot multiplier x
    max_pod_count must still be able to scale back up after a scale-down
    (regression: slot reserve used to be max(initial, mult*max), leaving zero
    creation headroom and permanently pinning the group at its low point)."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True

    sim = _build(config, CLUSTER_TRACE, HIGH_INITIAL_WORKLOAD_TRACE)
    expected = [
        (61.0, 6),   # initial expansion, first cycle sees no running pods yet
        (121.0, 1),  # util 0.6/6 = 0.1, desired ceil(6*0.1/0.6) = 1
        (181.0, 1),  # util 0.6/1 = 0.6, ratio 1.0: hold
        (361.0, 2),  # load switched to 6 at t=359.5: util 1.0, ceil(1/0.6)=2
        (421.0, 3),  # ceil(2/0.6) = 4, clamped to max_pod_count 3
        (481.0, 3),  # hold at the clamp
    ]
    for until, replicas in expected:
        sim.step_until_time(until)
        for c in range(N_CLUSTERS):
            assert sim.hpa_replicas(c) == {"pod_group_1": replicas}, (
                f"at t={until}: {sim.hpa_replicas(c)}"
            )


def test_batched_hpa_ring_survives_many_load_cycles():
    """Slots are ring-reused: an HPA group cycling down/up for many load
    periods never exhausts its reserve (regression: tail used to be a
    monotonic allocator, silently pinning the group once cumulative
    scale-ups passed the reserve)."""
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True

    # 200 s load period: 2 pods' worth of load for 100 s, then 12 pods' worth.
    workload = HIGH_INITIAL_WORKLOAD_TRACE.replace(
        "initial_pod_count: 6", "initial_pod_count: 2"
    ).replace(
        "max_pod_count: 3", "max_pod_count: 6"
    ).replace(
        "- duration: 300.0\n                total_load: 0.6",
        "- duration: 100.0\n                total_load: 1.2",
    ).replace(
        "- duration: 300.0\n                total_load: 6",
        "- duration: 100.0\n                total_load: 12",
    )
    sim = _build(config, CLUSTER_TRACE, workload)

    # Reserve = 2 + 2*6 = 14 slots; each period churns ~4 creations, so by
    # t=3000 (~15 periods) a monotonic allocator would long be exhausted.
    samples = []
    for cycle_end in range(61, 3001, 60):
        sim.step_until_time(float(cycle_end))
        samples.append(sim.hpa_replicas(0)["pod_group_1"])
    late = samples[len(samples) // 2 :]
    # Steady-state oscillation 2 -> 4 -> 6 -> 2 keeps hitting both the clamp
    # and the trough long after the reserve would have been exhausted.
    assert max(late) == 6, samples
    assert min(late) == 2, samples
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_pods"] > 14 * N_CLUSTERS  # > reserve


def test_batched_gauge_time_series(tmp_path):
    """Per-window gauge collection (batched analog of the scalar 5 s gauge
    CSV cycle, reference: src/metrics/collector.rs:216-228): node/pod counts
    and utilizations track the known HPA scenario, and the CSV dump follows
    the scalar 8-column schema."""
    import csv

    from kubernetriks_tpu.metrics.collector import GAUGE_CSV_COLUMNS
    from kubernetriks_tpu.test_util import default_test_simulation_config

    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    sim = _build(config, CLUSTER_TRACE, WORKLOAD_TRACE)
    sim.collect_gauges = True
    sim.step_until_time(700.0)

    times, samples = sim.gauge_series()
    assert times.shape[0] == samples.shape[0] == 71  # windows 0..700
    assert samples.shape[1:] == (N_CLUSTERS, 7)
    # Nodes appear at t=5 -> every window from 1 on sees them alive.
    assert (samples[1:, :, 0] == samples[1, 0, 0]).all()
    assert samples[0, 0, 0] == 0
    # While replicas run, cluster cpu utilization is positive and <= 1.
    mid = samples[20, 0]
    assert 0.0 < mid[5] <= 1.0
    assert 0.0 <= mid[3] <= 1.0
    # Pod counts track the HPA trajectory (group created t=59.5, initial 5
    # replicas running shortly after).
    assert samples[10, 0, 1] >= 5

    out = tmp_path / "gauges.csv"
    sim.write_gauge_csv(str(out))
    with open(out) as f:
        rows = list(csv.reader(f))
    assert rows[0] == GAUGE_CSV_COLUMNS
    assert len(rows) == 72
    assert float(rows[2][0]) == 10.0  # timestamp column in seconds


# --- CA unscheduled-cache fidelity (VERDICT r1 item 9) -----------------------
# The batched cache is UNSCHEDULABLE | (QUEUED & attempts >= 2): a pod enters
# the scalar storage cache when it parks (PodNotScheduled,
# persistent_storage.py:228) and leaves ONLY on assignment (:200) or removal
# (:307); attempts increments solely on wake-from-park, so the disjunction is
# exact, not a heuristic. These tests pin the adversarial cases.

CACHE_CA_SUFFIX = """
cluster_autoscaler:
  enabled: true
  scan_interval: 30.0
  max_node_count: 10
  node_groups:
  - node_template:
      metadata:
        name: cache_ca_node
      status:
        capacity:
          cpu: 32000
          ram: 68719476736
"""


def test_ca_cache_cleared_by_same_window_wake_and_schedule():
    """A parked pod woken AND scheduled in the same window must be out of the
    cache when the CA snapshot runs after the cycle — no ghost scale-up
    (scalar: assignment discards the cache entry before the CA request)."""
    config = default_test_simulation_config(CACHE_CA_SUFFIX)
    cluster = """
events:
- timestamp: 2
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 2000, ram: 4294967296}}
- timestamp: 25
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 8000, ram: 17179869184}}
"""
    workload = """
events:
- timestamp: 5
  event_type:
    !CreatePod
      pod:
        metadata: {name: pod_00}
        spec:
          resources:
            requests: {cpu: 4000, ram: 8589934592}
          running_duration: 50.0
"""
    sim = _build(config, cluster, workload)
    # CA ticks at t=0 (nothing exists) and t=30 — the same window where
    # node_01's arrival wakes pod_00 and the cycle schedules it.
    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["pods_succeeded"] == 1 * N_CLUSTERS
    assert counters["total_scaled_up_nodes"] == 0


def test_ca_cache_keeps_woken_but_uncycled_pod():
    """A woken pod beyond the cycle's K budget is QUEUED with attempts >= 2
    at CA time and must STILL count as unscheduled (scalar: the cache entry
    persists until assignment)."""
    config = default_test_simulation_config(CACHE_CA_SUFFIX)
    cluster = """
events:
- timestamp: 2
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 1000, ram: 2147483648}}
- timestamp: 25
  event_type:
    !CreateNode
      node:
        metadata: {name: node_01}
        status: {capacity: {cpu: 1000, ram: 2147483648}}
"""
    # Two pods that fit neither tiny node; node_01's arrival wakes both, but
    # max_pods_per_cycle=1 re-parks only pod_00 — pod_01 sits QUEUED with
    # attempts=2 when the t=30 CA snapshot runs.
    workload = """
events:
- timestamp: 5
  event_type:
    !CreatePod
      pod:
        metadata: {name: pod_00}
        spec:
          resources:
            requests: {cpu: 8000, ram: 17179869184}
          running_duration: 20.0
- timestamp: 6
  event_type:
    !CreatePod
      pod:
        metadata: {name: pod_01}
        spec:
          resources:
            requests: {cpu: 8000, ram: 17179869184}
          running_duration: 20.0
"""
    sim = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(cluster).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=N_CLUSTERS,
        max_pods_per_cycle=1,
    )
    sim.step_until_time(35.0)
    from kubernetriks_tpu.batched.state import PHASE_QUEUED

    view = sim.pod_view(0)
    phases = np.asarray(sim.state.pods.phase[0])
    attempts = np.asarray(sim.state.pods.attempts[0])
    # The adversarial setup held: one pod is QUEUED (not UNSCHEDULABLE) with
    # attempts >= 2 at the CA tick...
    assert ((phases == PHASE_QUEUED) & (attempts >= 2)).sum() >= 1, (phases, attempts)
    # ...and the CA counted BOTH pods: scale-up covers two 8-core pods.
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 1 * N_CLUSTERS  # both fit one 32-core node
    sim.step_until_time(200.0)
    assert sim.metrics_summary()["counters"]["pods_succeeded"] == 2 * N_CLUSTERS


def test_ca_cache_cleared_by_pod_removal():
    """A pod removed while parked leaves the cache: the next CA snapshot sees
    nothing unscheduled and must not scale up (scalar: clean_up discards the
    entry on removal)."""
    config = default_test_simulation_config(CACHE_CA_SUFFIX)
    cluster = """
events:
- timestamp: 2
  event_type:
    !CreateNode
      node:
        metadata: {name: node_00}
        status: {capacity: {cpu: 1000, ram: 2147483648}}
"""
    workload = """
events:
- timestamp: 5
  event_type:
    !CreatePod
      pod:
        metadata: {name: pod_00}
        spec:
          resources:
            requests: {cpu: 8000, ram: 17179869184}
          running_duration: 20.0
- timestamp: 12
  event_type:
    !RemovePod
      pod_name: pod_00
"""
    sim = _build(config, cluster, workload)
    sim.step_until_time(100.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 0
    assert counters["pods_succeeded"] == 0

@pytest.mark.parametrize(
    "seeds",
    [
        (0,),
        # Second churn seed behind `-m slow` (tier-1 wall-clock budget):
        # the kernel-vs-walk equivalence gate itself stays tier-1 on
        # seed 0; the extra seed only widens the churn sampling.
        pytest.param((7,), marks=pytest.mark.slow),
    ],
)
def test_ca_scale_down_kernel_matches_xla_walk(seeds):
    """The Mosaic scale-down kernel (ops/autoscale_kernel.py) is bit-exact
    vs the XLA while_loop walk: the same composed HPA+CA churn scenario
    stepped with use_pallas on (interpret mode off-TPU) and off produces
    identical node lifecycles, CA counts, and counters at every probe."""
    from kubernetriks_tpu.trace.generator import PoissonWorkloadTrace

    suffix = CA_CONFIG_SUFFIX + """
horizontal_pod_autoscaler:
  enabled: true
"""
    group = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 19.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 12
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 3000, ram: 6442450944}
              limits: {cpu: 3000, ram: 6442450944}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 120.0
                total_load: 1.0
              - duration: 120.0
                total_load: 5.0
              - duration: 160.0
                total_load: 0.5
"""
    ).convert_to_simulator_events()

    for seed in seeds:
        plain = PoissonWorkloadTrace(
            rate_per_second=0.4,
            horizon=400.0,
            seed=seed,
            cpu=4000,
            ram=8 * 1024**3,
            duration_range=(20.0, 90.0),
            name_prefix="plain",
        ).convert_to_simulator_events()
        workload = sorted(plain + group, key=lambda e: e[0])

        def build(**kw):
            config = default_test_simulation_config(suffix)
            return build_batched_from_traces(
                config,
                GenericClusterTrace.from_yaml(
                    """
events:
- timestamp: 1.0
  event_type:
    !CreateNode
      node:
        metadata: {name: base}
        status: {capacity: {cpu: 16000, ram: 34359738368}}
"""
                ).convert_to_simulator_events(),
                workload,
                n_clusters=N_CLUSTERS,
                max_pods_per_cycle=16,
                **kw,
            )

        ref = build()
        ker = build(use_pallas=True, pallas_interpret=True)
        # Pin the test to the kernel path: if the fits-heuristic ever says
        # no at these shapes, this test degrades to ref-vs-ref and proves
        # nothing — fail loudly instead.
        from kubernetriks_tpu.ops.autoscale_kernel import (
            ca_down_kernel_fits,
            ca_up_kernel_fits,
        )

        assert ca_down_kernel_fits(
            ker.state.nodes.alive.shape[1],
            ker.autoscale_statics.ca_slots.shape[1],
            ker.max_pods_per_scale_down,
        )
        assert ca_up_kernel_fits(
            ker.autoscale_statics.ca_slots.shape[1],
            ker.autoscale_statics.ng_ca_start.shape[1],
            ker.max_ca_pods_per_cycle,
        )
        for until in (100.0, 250.0, 500.0):
            ref.step_until_time(until)
            ker.step_until_time(until)
            assert (
                ref.metrics_summary()["counters"]
                == ker.metrics_summary()["counters"]
            ), f"seed={seed} t={until}"
            assert np.array_equal(
                np.asarray(ref.state.nodes.alive), np.asarray(ker.state.nodes.alive)
            )
            assert np.array_equal(
                np.asarray(ref.state.nodes.remove_time.win),
                np.asarray(ker.state.nodes.remove_time.win),
            )
            assert np.array_equal(
                np.asarray(ref.state.pods.phase), np.asarray(ker.state.pods.phase)
            )
            for c in range(N_CLUSTERS):
                assert np.array_equal(
                    ref.ca_node_counts(c), ker.ca_node_counts(c)
                ), f"seed={seed} t={until}"
        assert ref.metrics_summary()["counters"]["total_scaled_down_nodes"] > 0


# --- Adversarial tests PAST the documented autoscaler work bounds ----------
# (autoscale.py "Remaining bounded deviations"). Each test drives one bound
# and pins the documented behavior: conservative skip + eventual convergence
# for K_sd, a LOUD readout error (engine.check_autoscaler_bounds) for
# reserve exhaustion, and window-cadence degradation for sub-window
# scan intervals.


def test_ca_scale_down_conservative_skip_past_k_sd_and_convergence():
    """Bound: scale-down considers at most K_sd (max_pods_per_scale_down)
    pods per candidate node; a node carrying MORE is conservatively skipped
    (autoscale.py:804 `cnt <= K_sd`) even when under the utilization
    threshold with every pod movable — the reference
    (kube_cluster_autoscaler.rs:148-181) has no such cap and would remove
    it. Convergence: once pods finish and the count drops to <= K_sd, the
    very next cycle removes the node."""
    # Big trace node arrives at t=60 — AFTER the CA scaled a node up for the
    # three pods — so the pods land on the CA node but are movable later.
    cluster = """
events:
- timestamp: 60.0
  event_type:
    !CreateNode
      node:
        metadata:
          name: big_node
        status:
          capacity:
            cpu: 64000
            ram: 137438953472
"""
    # 3 x 1000 mcpu on the 16000 template = 19% util, well under the 0.5
    # threshold: the ONLY thing blocking scale-down is cnt=3 > K_sd=2.
    # pod_0 finishes at ~t=115; pods 1-2 run long.
    workload = "events:" + "".join(
        f"""
- timestamp: {5 + i}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i}
        spec:
          resources:
            requests:
              cpu: 1000
              ram: 1073741824
            limits:
              cpu: 1000
              ram: 1073741824
          running_duration: {100.0 if i == 0 else 900.0}
"""
        for i in range(3)
    )
    config = default_test_simulation_config(CA_CONFIG_SUFFIX)
    sim = _build(config, cluster, workload, max_pods_per_scale_down=2)

    # Phase 1: the skip. From t=60 the big node is up, the CA node is under
    # threshold and all 3 pods fit big_node — five scan cycles pass and the
    # node is still conservatively skipped because 3 > K_sd.
    sim.step_until_time(110.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 1 * N_CLUSTERS
    assert counters["total_scaled_down_nodes"] == 0, (
        "a node with > K_sd pods must be conservatively skipped"
    )

    # Phase 2: convergence. pod_0 finishes (~t=115) -> 2 pods <= K_sd; the
    # next cycles walk the node, re-place both pods onto big_node and scale
    # it down.
    sim.step_until_time(250.0)
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_down_nodes"] == 1 * N_CLUSTERS, (
        "once the pod count drops to K_sd the skip must lift"
    )
    for c in range(N_CLUSTERS):
        assert sim.ca_node_counts(c).sum() == 0
    # The two long-running pods were rescheduled and run on the big node.
    from kubernetriks_tpu.batched.state import PHASE_RUNNING

    view = sim.pod_view(0)
    running = [k for k, v in view.items() if v["phase"] == PHASE_RUNNING]
    assert sorted(running) == ["pod_1", "pod_2"]
    assert all(view[k]["node"] == "big_node" for k in running)


def test_ca_slot_reserve_exhaustion_raises_loudly():
    """Bound: scaled-up node slots are never reclaimed (autoscale.py:43-45;
    the reference's pool RECLAIMS on scale-down, node_component_pool.rs:60-77,
    so churn never exhausts it there). With max_count=1 the group reserves
    ca_slot_multiplier x 1 = 2 slots; the third scale-up of an up/down/up
    churn finds the cursor exhausted and silently starves — the readout
    must raise instead of reporting the starved trajectory."""
    import pytest

    suffix = CA_CONFIG_SUFFIX + "    max_count: 1\n"
    config = default_test_simulation_config(suffix)
    # Three well-separated one-pod bursts; each scales one node up, runs
    # 20 s, and the idle node is scaled down before the next burst.
    workload = "events:" + "".join(
        f"""
- timestamp: {ts}
  event_type:
    !CreatePod
      pod:
        metadata:
          name: pod_{i}
        spec:
          resources:
            requests:
              cpu: 4000
              ram: 8589934592
            limits:
              cpu: 4000
              ram: 8589934592
          running_duration: 20.0
"""
        for i, ts in enumerate((5.0, 150.0, 300.0))
    )
    sim = _build(config, "", workload)
    sim.step_until_time(450.0)
    with pytest.raises(RuntimeError, match="CA slot reserve exhausted"):
        sim.metrics_summary()
    # Opting out reads the starved trajectory: only the first two bursts
    # ever got a node; pod_2's demand starved silently.
    sim.strict_autoscaler_bounds = False
    counters = sim.metrics_summary()["counters"]
    assert counters["total_scaled_up_nodes"] == 2 * N_CLUSTERS
    assert counters["total_scaled_down_nodes"] == 2 * N_CLUSTERS
    assert counters["pods_succeeded"] == 2 * N_CLUSTERS


def test_hpa_reserve_clamp_raises_loudly():
    """Bound: an HPA cycle can only activate reusable slots from the
    group's reserve (hpa_pass `up = min(up0, n_reusable)`); when the
    reserve is too small the surplus replicas are silently dropped — a
    divergence from the scalar, which creates every desired replica
    (kube_horizontal_pod_autoscaler.rs:157-181). pod_group_slot_multiplier=0
    shrinks the golden trace's reserve to its 5 initial slots, so the
    t=120 scale-up 5 -> 9 clamps 4 replicas; the readout must raise."""
    import pytest

    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    sim = _build(
        config, CLUSTER_TRACE, WORKLOAD_TRACE, pod_group_slot_multiplier=0
    )
    sim.step_until_time(130.0)
    with pytest.raises(RuntimeError, match="HPA slot reserve exhausted"):
        sim.metrics_summary()
    # The diverged count is visible (and capped at the reserve) once the
    # strict check is off.
    sim.strict_autoscaler_bounds = False
    assert sim.hpa_replicas(0) == {"pod_group_1": 5}
    assert sim.metrics_summary()["counters"]["total_scaled_up_pods"] == 0


def test_sub_window_ca_scan_interval_one_cycle_per_window():
    """Bound: CA cadences faster than the scheduling window degrade to ONE
    cycle per window (autoscale.py:50-51 — ca_pass advances ca_next by one
    period per due window). scan_interval=3 s under a 10 s window with
    K_up=4 and 8 cache pods: the scalar would fire cycles ~3-4 s apart and
    have both nodes planned within one window; the batched path plans the
    second node one WINDOW later. Both converge to the same final state."""
    suffix = CA_CONFIG_SUFFIX.replace("scan_interval: 10.0", "scan_interval: 3.0")
    config = default_test_simulation_config(suffix)
    sim = _build(
        config,
        "",
        _ca_workload(n_pods=8, duration=400.0),
        max_ca_pods_per_cycle=4,
    )
    sim.step_until_time(400.0)
    counters = sim.metrics_summary()["counters"]
    # 8 pods open 3 template nodes, not 2: each cycle's FIRST unplanned pod
    # triggers a node it is NOT packed into (reference quirk,
    # kube_cluster_autoscaler.rs:210-218), so cycle 1 opens a node for pods
    # 0-3's overflow, cycle 2 (one window later — the degraded cadence)
    # opens one holding pods 5-7, and the still-parked trigger pod forces a
    # third. The point under test is the CADENCE: with scan_interval=3 the
    # scalar would fire all these cycles within one 10 s window; the
    # batched path needs one window per cycle, converging to the same
    # placement a few windows later.
    assert counters["total_scaled_up_nodes"] == 3 * N_CLUSTERS
    assert counters["scheduling_decisions"] >= 8 * N_CLUSTERS
    from kubernetriks_tpu.batched.state import PHASE_RUNNING

    phases = [v["phase"] for v in sim.pod_view(0).values()]
    assert phases.count(PHASE_RUNNING) == 8
