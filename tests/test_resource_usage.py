"""Resource usage models (port of reference src/core/resource_usage tests)."""

import pytest

from kubernetriks_tpu.core.resource_usage import (
    ConstantResourceUsageModel,
    PodGroupResourceUsageModel,
    default_resource_usage_config,
    resource_usage_model_from_config,
)
from kubernetriks_tpu.core.types import ResourceUsageModelConfig


def test_constant_usage_any_time():
    model = ConstantResourceUsageModel.from_str("usage: 27.0")
    for t in [0.0, 500.0, 500.0, 1000.0, 1001.0]:
        assert model.current_usage(t) == 27.0


def test_pod_group_one_unit():
    model = PodGroupResourceUsageModel.from_str(
        "- duration: 1000.0\n  total_load: 10.0\n", 0.0
    )
    for t in [0.0, 500.0, 500.0, 1000.0, 1001.0, 7431.0, 63431.0]:
        assert model.current_usage(t, 50) == 0.2


def test_pod_group_time_going_backwards_raises():
    model = PodGroupResourceUsageModel.from_str(
        "- duration: 1000.0\n  total_load: 10.0\n", 0.0
    )
    assert model.current_usage(0.0, 50) == 0.2
    assert model.current_usage(500.0, 50) == 0.2
    with pytest.raises(RuntimeError):
        model.current_usage(250.0, 50)


COMPLEX_CONFIG = """
- duration: 1000.0
  total_load: 10.0
- duration: 10.0
  total_load: 400.0
- duration: 200.0
  total_load: 20.0
- duration: 500.0
  total_load: 1.0
"""


@pytest.mark.parametrize("shift", [0.0, 1.0, 500.0, 1000.0, 1010.0, 1499.0])
def test_pod_group_complex_curve_with_creation_shift(shift):
    """Load curve anchored at pod-group creation time; cyclic wrap
    (reference: src/core/resource_usage/pod_group.rs:140-176)."""
    model = PodGroupResourceUsageModel.from_str(COMPLEX_CONFIG, shift)
    assert model.current_usage(0.0 + shift, 10) == 1.0
    assert model.current_usage(1000.0 + shift, 10) == 1.0
    assert model.current_usage(1000.0 + shift, 1600) == 0.25
    assert model.current_usage(1000.1 + shift, 500) == 0.8
    assert model.current_usage(1010.0 + shift, 40) == 0.5
    assert model.current_usage(1010.0 + shift, 20) == 1.0
    assert model.current_usage(8550.0 + shift, 20) == 0.5
    assert model.current_usage(9560.0 + shift, 80) == 0.25
    assert model.current_usage(9759.0 + shift, 200) == 0.1
    assert model.current_usage(54376.0 + shift, 20) == 0.05


def test_factory_dispatch():
    constant = resource_usage_model_from_config(
        default_resource_usage_config(32.0)
    )
    assert constant.current_usage(10.0) == 32.0
    pod_group = resource_usage_model_from_config(
        ResourceUsageModelConfig(
            model_name="pod_group",
            config="- duration: 100.0\n  total_load: 5.0\n",
        ),
        pod_group_creation_time="50.0",
    )
    assert pod_group.current_usage(60.0, 10) == 0.5
    with pytest.raises(ValueError):
        resource_usage_model_from_config(
            ResourceUsageModelConfig(model_name="bogus", config="")
        )
