"""Recompile-sentinel tests (KTPU_EXPLAIN_RECOMPILES): post-warm-up XLA
compilations raise/warn NAMING the jit entry — the runtime cross-check of
the scenariotrace lint pass's static compile-once guarantee."""

import logging

import jax
import jax.numpy as jnp
import pytest

from kubernetriks_tpu.recompile import (
    RecompileError,
    RecompileSentinel,
    RecompileWarning,
    maybe_sentinel,
    sentinel_mode,
)


def test_shape_drift_raises_naming_the_entry():
    """The acceptance gate: warm a jit entry, seal, drift its shape —
    check() raises RecompileError carrying the entry's name; a cache-hit
    call between seal and drift stays quiet."""
    sent = RecompileSentinel("raise").install()
    try:

        @jax.jit
        def drifty_probe(x):
            return x * 2 + 1

        drifty_probe(jnp.zeros((4,)))
        assert any("drifty_probe" in e for e in sent.events), (
            "warm-up compile not observed — the jax_log_compiles hook "
            "is not wired"
        )
        sent.seal("unit warm-up")
        drifty_probe(jnp.zeros((4,)))  # cache hit: no event
        sent.check("steady state")  # must pass
        drifty_probe(jnp.zeros((5,)))  # deliberate shape drift
        with pytest.raises(RecompileError, match="drifty_probe"):
            sent.check("drift probe")
    finally:
        sent.uninstall()


def test_warn_mode_and_expect_none_windows():
    """expect_none guards a block independent of seal(); warn mode emits
    RecompileWarning instead of raising."""
    sent = RecompileSentinel("warn").install()
    try:

        @jax.jit
        def warm_probe(x):
            return x - 1

        warm_probe(jnp.zeros((3,)))
        with sent.expect_none("cache-hit window"):
            warm_probe(jnp.zeros((3,)))
        with pytest.warns(RecompileWarning, match="warm_probe"):
            with sent.expect_none("drift window"):
                warm_probe(jnp.zeros((6,)))
    finally:
        sent.uninstall()


def test_uninstall_restores_logging_state():
    """Install/uninstall round-trips jax_log_compiles and the compile
    loggers' propagation — the sentinel leaves no global residue."""
    before = bool(jax.config.jax_log_compiles)
    prop_before = logging.getLogger("jax._src.dispatch").propagate
    sent = RecompileSentinel().install()
    sent.uninstall()
    assert bool(jax.config.jax_log_compiles) == before
    assert logging.getLogger("jax._src.dispatch").propagate == prop_before


def test_flag_wiring(monkeypatch):
    """Tristate semantics: unset -> benches arm, fleet does not (None);
    1 -> fleet arms a raising sentinel; 0 -> forced off."""
    monkeypatch.delenv("KTPU_EXPLAIN_RECOMPILES", raising=False)
    assert sentinel_mode() is None
    assert maybe_sentinel() is None
    monkeypatch.setenv("KTPU_EXPLAIN_RECOMPILES", "0")
    assert sentinel_mode() is False
    assert maybe_sentinel() is None
    monkeypatch.setenv("KTPU_EXPLAIN_RECOMPILES", "1")
    assert sentinel_mode() is True
    sent = maybe_sentinel()
    assert sent is not None and sent.mode == "raise"
    sent.uninstall()
