"""Capacity observatory soak gates (tests/test_soak.py).

The measurable precursor to ROADMAP #2's endurance deliverable: a short
composed run (HPA + CA + sliding window + superspan + streaming feeder +
chaos) with the flight recorder AND the saturation watchdog armed,
asserting the three observatory claims that make multi-week runs
watchable:

1. EXACT occupancy: the ring's reserve-occupancy gauge columns
   (hpa_reserve_used / ca_reserve_used / pod_headroom) match an
   INDEPENDENT host-side recomputation from drained state — integer
   equality at every sampled window, not a tolerance.
2. The watchdog fires BEFORE the loud bound: on an engineered
   near-exhaustion CA reserve (ca_slot_multiplier=1), a SaturationWarning
   with a time-to-exhaustion estimate lands while the
   ca_reserve_starved divergence counter is still ZERO.
3. FLAT watermarks: across steady-state superspans the slab/ring byte
   accounting is exactly constant and host RSS does not trend — the
   bounded-memory claim of the streaming pipeline, observed rather than
   argued.

A longer variant of the same gates runs behind `-m slow`. Pure
observatory mechanics (trajectory fit, exporters, synthetic watchdog
verdicts) are unit-tested here too — no engine needed.
"""

import json
import math
import os
import warnings

import numpy as np
import pytest

from kubernetriks_tpu.telemetry.export import (
    JsonlExporter,
    prometheus_lines,
    write_prometheus_textfile,
)
from kubernetriks_tpu.telemetry.observatory import (
    Observatory,
    SaturationWarning,
    UNBOUNDED_SENTINEL,
    fit_slope,
    sample_host_memory,
    time_to_exhaustion,
)
from kubernetriks_tpu.telemetry.ring import RING_COLUMNS

from test_superspan import FAULT_SUFFIX
from test_window_donation_dispatch import _build_composed

COL = {name: idx for idx, name in enumerate(RING_COLUMNS)}


def _build_soak(**kwargs):
    """The soak engine: the composed fault scenario with streaming +
    superspan forced on (CPU defaults are off), the flight recorder and
    watchdog armed, and a deliberately TIGHT CA slot reserve
    (ca_slot_multiplier=1) so sustained HPA/CA churn walks the
    never-reclaimed cursor toward exhaustion inside the test budget."""
    kwargs.setdefault("superspan", True)
    kwargs.setdefault("superspan_k", 4)
    kwargs.setdefault("superspan_chunk", 4)
    kwargs.setdefault("stream", True)
    kwargs.setdefault("telemetry", True)
    kwargs.setdefault("watchdog", True)
    kwargs.setdefault("telemetry_ring", 16)
    kwargs.setdefault("ca_slot_multiplier", 1)
    return _build_composed(config_suffix=FAULT_SUFFIX, **kwargs)


def _oracle_occupancy(sim):
    """INDEPENDENT host-side recomputation of the ring's occupancy gauge
    columns from drained state: live HPA replicas (tail - head over
    groups), consumed CA cursor, and the plain-trace headroom formula —
    the acceptance-criteria oracle."""
    auto = sim.state.auto
    if auto is not None:
        hpa = (
            np.asarray(auto.hpa_tail).astype(np.int64)
            - np.asarray(auto.hpa_head)
        ).sum(axis=1)
        ca = np.asarray(auto.ca_cursor).astype(np.int64).sum(axis=1)
    else:
        hpa = np.zeros(sim.n_clusters, np.int64)
        ca = np.zeros(sim.n_clusters, np.int64)
    T = int(sim.consts.trace_pod_bound)
    plain_w = min(sim.n_pods, T - int(sim.consts.resident_shift))
    headroom = np.maximum(T - np.asarray(sim.state.pod_base) - plain_w, 0)
    return hpa, ca, headroom


def _run_soak_and_check(sim, ends):
    """Step through `ends`, oracle-checking the latest ring row against
    state at every boundary and collecting watchdog warnings + resource
    samples. Returns (warnings, samples)."""
    caught = []
    samples = []
    first_fire_starved = None
    for end in ends:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            # Drains (and hence watchdog passes) fire both inside the
            # step at its sync points AND at the forced series drain.
            sim.step_until_time(end)
            wins, data = sim.telemetry_window_series()
        fired = [x for x in w if issubclass(x.category, SaturationWarning)]
        if (
            any("ca_reserve_used" in str(x.message) for x in fired)
            and first_fire_starved is None
        ):
            # The moment the CA verdict FIRST fired, the loud bound had
            # not: the divergence counter the engine raises on at readout
            # is still zero (warning-before-failure, the acceptance gate).
            first_fire_starved = int(
                np.asarray(sim.state.metrics.ca_reserve_starved).sum()
            )
        caught.extend(fired)
        last = sim.next_window_idx - 1
        if len(wins) and last >= 0:
            # Integer-exact gauge oracle at the latest executed window.
            assert wins[-1] == last, (wins[-1], last)
            hpa, ca, headroom = _oracle_occupancy(sim)
            np.testing.assert_array_equal(
                data[-1, :, COL["hpa_reserve_used"]], hpa
            )
            np.testing.assert_array_equal(
                data[-1, :, COL["ca_reserve_used"]], ca
            )
            np.testing.assert_array_equal(
                data[-1, :, COL["pod_headroom"]], headroom
            )
        # Tag each sample with the stage geometry: a pod-window GROWTH
        # legitimately re-seeks the feeder at a wider slab, so flatness
        # is asserted per geometry — a trend WITHIN one would be a leak.
        samples.append((sim.pod_window, sim._sample_resources()))
    return caught, samples, first_fire_starved


def _assert_soak_gates(sim, caught, samples, first_fire_starved):
    # The run really composed everything: superspans dispatched, feeder
    # staged, faults happened, autoscalers acted.
    assert sim.dispatch_stats["superspans"] > 0
    assert sim.dispatch_stats["window_chunks"] == 0
    assert sim.dispatch_stats["feeder_slabs_produced"] > 0
    counters = np.asarray(sim.state.metrics.pod_interruptions).sum() + (
        np.asarray(sim.state.metrics.pods_failed).sum()
    )
    assert counters > 0, "fault run produced no faults; soak is vacuous"

    # Gate 2: the watchdog fired with a CA-reserve verdict carrying a
    # time-to-exhaustion estimate, BEFORE the loud bound (starved == 0 at
    # first fire — engine.check_autoscaler_bounds had nothing to raise).
    ca_warnings = [
        w for w in caught if "ca_reserve_used" in str(w.message)
    ]
    assert ca_warnings, [str(w.message) for w in caught]
    assert any(
        "to exhaustion" in str(w.message) for w in ca_warnings
    ), [str(w.message) for w in ca_warnings]
    assert first_fire_starved == 0, (
        "watchdog first fired only AFTER the loud reserve bound tripped"
    )
    fired = sim.observatory.report()["watchdog"]["fired"]
    assert "ca_reserve_used" in fired

    # Gate 3: flat watermarks across steady-state superspans. Slab/ring
    # accounting is EXACTLY constant per stage geometry (a pod-window
    # growth re-seeks the feeder at a wider slab — a step, not a trend);
    # host RSS may wiggle with allocator noise but must not trend
    # (generous container bound).
    steady = samples[1:]
    by_geometry: dict = {}
    for pod_window, sample in steady:
        by_geometry.setdefault(pod_window, []).append(sample["slabs"])
        assert sample["slabs"]["device_slide_bytes"] == 0, (
            "streaming engine materialized the whole-trace slide payload"
        )
    for pod_window, slabs in by_geometry.items():
        for later in slabs[1:]:
            assert later == slabs[0], (pod_window, later, slabs[0])
    last_slabs = steady[-1][1]["slabs"]
    assert last_slabs.get("feeder_ring_capacity_bytes", 0) > 0
    assert last_slabs["feeder_ring_capacity_bytes"] == (
        last_slabs["feeder_slab_bytes"] * sim._stream_depth
    )
    rss = [s["rss_bytes"] for _, s in steady if s["rss_bytes"] > 0]
    if len(rss) >= 2:
        assert rss[-1] - rss[0] < 256 * 1024 * 1024, (
            f"RSS trended across steady superspans: {rss}"
        )


def test_soak_composed_chaos_streaming_watchdog_two_lane_heterogeneous():
    """The tier-1 soak: ~45 windows of the composed fault scenario with
    an engineered near-exhaustion CA reserve. Occupancy exact, watchdog
    before the bound, watermarks flat.

    Scenario-vector fleet follow-through (batched/fleet.py) rides the
    SAME engine: the two lanes are HETEROGENEOUS — lane 0 runs the
    near-exhaustion chaos scenario, lane 1 runs with the HPA parked and
    CA quota zero (the plain Poisson load alone could otherwise open CA
    nodes), so the capacity observatory must judge each lane against ITS
    OWN occupancy/capacity row: every reserve verdict names cluster 0,
    never the idle lane, while the per-lane gauges stay integer-exact
    (the oracle check inside _run_soak_and_check is element-wise per
    lane) and the idle lane's CA cursor stays zero. The homogeneous
    two-saturating-lane shape keeps running in the slow-lane variant."""
    from kubernetriks_tpu.batched.fleet import Scenario, scenario_vectors
    from kubernetriks_tpu.test_util import default_test_simulation_config
    from test_window_donation_dispatch import COMPOSED_CONFIG_SUFFIX

    # The scenario vectors' base values must come from the SAME config
    # the engine builds with (the composed + fault scenario).
    soak_config = default_test_simulation_config(
        COMPOSED_CONFIG_SUFFIX + FAULT_SUFFIX
    )
    sim = _build_soak(
        scenario=dict(
            scenario_vectors(
                soak_config,
                2,
                [Scenario(), Scenario(hpa_enabled=False, ca_max_node_count=0)],
            )
        )
    )
    try:
        caught, samples, first_fire_starved = _run_soak_and_check(
            sim, ends=np.arange(50.0, 451.0, 50.0)
        )
        _assert_soak_gates(sim, caught, samples, first_fire_starved)
        # Heterogeneous-lane gates: verdicts target the saturating lane.
        events = [
            e
            for e in sim.observatory.events
            if e["kind"] in ("ca_reserve_used", "hpa_reserve_used")
        ]
        assert events and all(e["cluster"] == 0 for e in events), events
        # The idle lane really was idle: no CA slot ever consumed there.
        ca_cursor = np.asarray(sim.state.auto.ca_cursor)
        assert ca_cursor[1].sum() == 0, ca_cursor
        assert ca_cursor[0].sum() > 0, ca_cursor
    finally:
        sim.close()


@pytest.mark.slow
def test_soak_composed_long():
    """The slow-lane variant: the same gates over 3x the simulated span
    (the HPA load curve cycles indefinitely, so churn keeps walking the
    CA cursor) — closer to the endurance shape ROADMAP #2 asks for."""
    sim = _build_soak(ca_slot_multiplier=2, telemetry_ring=64)
    try:
        caught, samples, first_fire_starved = _run_soak_and_check(
            sim, ends=np.arange(50.0, 1351.0, 50.0)
        )
        _assert_soak_gates(sim, caught, samples, first_fire_starved)
    finally:
        sim.close()


# --- observatory mechanics (no engine) -----------------------------------


def test_fit_and_eta_math():
    xs = [0.0, 10.0, 20.0, 30.0]
    ys = np.array([[0.0, 0.0], [1.0, 0.0], [2.0, 0.0], [3.0, 0.0]])
    slopes = fit_slope(xs, ys)
    assert slopes.shape == (2,)
    assert abs(slopes[0] - 0.1) < 1e-12 and slopes[1] == 0.0
    assert time_to_exhaustion(3.0, 0.1, 10.0) == pytest.approx(70.0)
    assert time_to_exhaustion(3.0, 0.0, 10.0) == math.inf
    assert time_to_exhaustion(12.0, 0.1, 10.0) == 0.0
    # falling gauges (pod headroom): eta to zero
    assert time_to_exhaustion(50.0, -5.0, None, falling=True) == pytest.approx(10.0)
    assert time_to_exhaustion(50.0, 5.0, None, falling=True) == math.inf


def _ring_buf(rows):
    """Synthetic drained ring buffer: rows = [(window, hpa, ca, head)]
    for ONE cluster, padded into the (C=1, R, K) int32 layout."""
    R = len(rows)
    buf = np.full((1, R, len(RING_COLUMNS)), -1, np.int32)
    for slot, (w, hpa, ca, head) in enumerate(rows):
        buf[0, slot, COL["window"]] = w
        buf[0, slot, COL["hpa_reserve_used"]] = hpa
        buf[0, slot, COL["ca_reserve_used"]] = ca
        buf[0, slot, COL["pod_headroom"]] = head
    return buf


def test_watchdog_fires_on_rising_reserve_trajectory():
    obs = Observatory(
        interval=10.0,
        capacities={"hpa_reserve": [100], "ca_reserve": [20]},
        horizon_s=1e6,
    )
    obs.ingest(
        _ring_buf([(w, 0, 8 + w, UNBOUNDED_SENTINEL) for w in range(6)])
    )
    with pytest.warns(SaturationWarning, match="ca_reserve_used"):
        rec = obs.observe()
    assert rec["watchdog"], rec
    ev = [e for e in rec["watchdog"] if e["kind"] == "ca_reserve_used"][0]
    # occupancy 13/20 rising 1 slot / 10 sim-s -> 70 s to exhaustion.
    assert ev["eta_s"] == pytest.approx(70.0, abs=1.0)
    assert obs.report()["watchdog"]["fired"]["ca_reserve_used"] == 5


def test_watchdog_recovers_and_rewarns_after_reclaim():
    """Non-monotone-gauge semantics (r14): under slot reclaim the reserve
    occupancy FALLS when retired slots return, so (1) the trajectory fit
    sees the NET slope — a post-reclaim trough must not keep an old
    verdict alive — and (2) a previously-fired verdict CLEARS below the
    hysteresis fraction (a recovery event on the trail, good news, no
    warning) and a later saturation RE-fires instead of being shadowed by
    the first verdict."""
    obs = Observatory(
        interval=10.0,
        capacities={"hpa_reserve": [100], "ca_reserve": [20]},
        horizon_s=1e6,
    )
    obs.ingest(_ring_buf([(w, 0, 17, UNBOUNDED_SENTINEL) for w in range(6)]))
    with pytest.warns(SaturationWarning, match="ca_reserve_used"):
        obs.observe()
    assert "ca_reserve_used" in obs.fired
    # Reclaim returns the retired slots: occupancy drops to 3/20, below
    # the recover fraction (warn_frac / 2 by default) — the verdict
    # clears WITHOUT warning and the recovery lands on the event trail.
    obs.ingest(
        _ring_buf([(6 + w, 0, 3, UNBOUNDED_SENTINEL) for w in range(6)])
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    assert "ca_reserve_used" not in obs.fired
    recovered = [
        e for e in rec["watchdog"] if e["kind"] == "ca_reserve_used_recovered"
    ]
    assert recovered and recovered[-1]["frac"] == pytest.approx(3 / 20)
    # The next churn peak saturates the reserve again: the re-armed
    # verdict RE-fires (recover -> re-warn cycle).
    obs.ingest(
        _ring_buf([(12 + w, 0, 18, UNBOUNDED_SENTINEL) for w in range(6)])
    )
    with pytest.warns(SaturationWarning, match="ca_reserve_used"):
        obs.observe()
    assert "ca_reserve_used" in obs.fired


def test_watchdog_flat_tie_names_most_saturated_cluster():
    """Two lanes both past warn_frac with FLAT trajectories (eta = inf for
    both): the verdict must name the more saturated lane, not the lower
    lane index — per-lane judgment for heterogeneous fleets."""
    obs = Observatory(
        interval=10.0, capacities={"ca_reserve": [20, 20]}, horizon_s=1e6
    )
    R = 6
    buf = np.full((2, R, len(RING_COLUMNS)), -1, np.int32)
    for slot in range(R):
        buf[:, slot, COL["window"]] = slot
        buf[0, slot, COL["ca_reserve_used"]] = 17  # flat, 85%
        buf[1, slot, COL["ca_reserve_used"]] = 19  # flat, 95%
        buf[:, slot, COL["hpa_reserve_used"]] = 0
        buf[:, slot, COL["pod_headroom"]] = UNBOUNDED_SENTINEL
    obs.ingest(buf)
    with pytest.warns(SaturationWarning, match="cluster 1"):
        rec = obs.observe()
    ev = [e for e in rec["watchdog"] if e["kind"] == "ca_reserve_used"][0]
    assert ev["cluster"] == 1 and ev["used"] == 19


def test_watchdog_fires_idle_lane_verdict():
    """Lane-async idle-waste verdict (DESIGN §13): a lane whose
    lane_active ring bit was 0 for most of the recent windows draws
    exactly ONE lane_idle verdict naming the worst lane — and never
    re-fires (the idle fraction is only cured by feeding the submit
    queue; repeating the verdict every drain would be noise)."""
    obs = Observatory(interval=10.0, capacities={})

    def lane_buf(w0, R, lane1_active):
        buf = np.full((2, R, len(RING_COLUMNS)), -1, np.int32)
        for slot in range(R):
            buf[:, slot, COL["window"]] = w0 + slot
            buf[:, slot, COL["hpa_reserve_used"]] = 0
            buf[:, slot, COL["ca_reserve_used"]] = 0
            buf[:, slot, COL["pod_headroom"]] = UNBOUNDED_SENTINEL
            buf[0, slot, COL["lane_active"]] = 1
            buf[1, slot, COL["lane_active"]] = lane1_active(slot)
        return buf

    # Lane 1 active for 2 of 8 windows (25% < the 50% floor).
    obs.ingest(lane_buf(0, 8, lambda slot: 1 if slot < 2 else 0))
    with pytest.warns(SaturationWarning, match="lane 1"):
        rec = obs.observe()
    ev = [e for e in rec["watchdog"] if e["kind"] == "lane_idle"][0]
    assert ev["lane"] == 1
    assert ev["active_frac"] == pytest.approx(0.25)
    assert "lane_idle" in obs.report()["watchdog"]["fired"]
    # One-shot: more idle windows do NOT re-warn.
    obs.ingest(lane_buf(8, 6, lambda slot: 0))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec2 = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    assert [e for e in rec2["watchdog"] if e["kind"] == "lane_idle"] == []


def test_watchdog_lane_verdict_vacuous_without_lane_async():
    """Outside lane-async builds the lane_active column is never 0 (the
    synthetic buffers carry the -1 pad), so the verdict cannot fire."""
    obs = Observatory(interval=10.0, capacities={})
    obs.ingest(
        _ring_buf([(w, 0, 0, UNBOUNDED_SENTINEL) for w in range(8)])
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    assert rec["watchdog"] == []


def test_watchdog_quiet_on_flat_and_low_occupancy():
    obs = Observatory(
        interval=10.0,
        capacities={"hpa_reserve": [100], "ca_reserve": [100]},
    )
    obs.ingest(
        _ring_buf([(w, 5, 10, UNBOUNDED_SENTINEL) for w in range(6)])
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    assert rec["watchdog"] == []


def test_watchdog_flags_feeder_and_sync_budget():
    obs = Observatory(interval=10.0, capacities={})
    obs.ingest(_ring_buf([(0, 0, 0, UNBOUNDED_SENTINEL)]))
    with pytest.warns(SaturationWarning) as caught:
        obs.observe(
            dispatch_stats={
                "feeder_slabs_produced": 40,
                "stage_refills": 3,
                "superspans": 10,
                "fused_slides": 0,
                "slide_syncs": 13,
            },
            sync_budget={
                "steady_state_expected": 10,
                "observed_slide_syncs": 13,
            },
            feeder={
                "ring_capacity": 3,
                "stalls": {
                    "feeder_not_ready": {"count": 2, "ms": 5.0},
                    "upload_wait": {"count": 0, "ms": 0.0},
                },
            },
        )
    kinds = {e["kind"] for e in obs.events}
    assert {"sync_budget", "feeder_waste", "feeder_starved"} <= kinds
    messages = " ".join(str(w.message) for w in caught)
    assert "budget" in messages and "producer is not keeping ahead" in messages


def test_host_memory_sample_is_live():
    mem = sample_host_memory()
    assert mem["rss_bytes"] > 0
    assert mem["peak_rss_bytes"] >= mem["rss_bytes"] // 2


def test_jsonl_exporter_is_bounded(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    exp = JsonlExporter(path, max_bytes=2048)
    record = {"occupancy": {"ca_reserve_used": {"used_max": 3}}, "pad": "x" * 64}
    for i in range(200):
        exp.emit({**record, "window": i})
    assert exp.lines_written == 200
    # Bounded: live file + one rotation, both under the cap (plus one line).
    assert os.path.getsize(path) <= 2048 + 256
    assert os.path.getsize(path + ".1") <= 2048 + 256
    # Tail-friendly: every kept line parses and the newest window is last.
    lines = open(path).read().splitlines()
    assert json.loads(lines[-1])["window"] == 199


def test_prometheus_textfile(tmp_path):
    report = {
        "dispatch_stats": {"superspans": 7},
        "sync_budget": {"steady_state_expected": 7, "observed_slide_syncs": 7},
        "ring": {"windows_recorded": 12, "windows_kept": 12,
                 "totals": {"decisions": 99}},
        "resources": {
            "occupancy": {
                "ca_reserve_used": {"used_max": 3, "capacity_min": 8,
                                    "frac_max": 0.375, "high_water": 3},
            },
            "memory": {
                "rss_bytes": 123456,
                "slabs": {"feeder_ring_capacity_bytes": 4096},
                "high_water": {"rss_bytes": 234567},
            },
            "watchdog": {"enabled": True, "fired": {"ca_reserve_used": 9}},
            "samples": 4,
        },
    }
    lines = prometheus_lines(report)
    text = "\n".join(lines)
    assert 'ktpu_dispatch_total{kind="superspans"} 7' in text
    assert 'ktpu_ring_total{column="decisions"} 99' in text
    assert 'ktpu_occupancy{field="used_max",gauge="ca_reserve_used"} 3' in text
    assert 'ktpu_memory_bytes{kind="rss_bytes"} 123456' in text
    assert 'ktpu_memory_bytes{kind="slabs.feeder_ring_capacity_bytes"} 4096' in text
    assert 'ktpu_memory_high_water_bytes{kind="rss_bytes"} 234567' in text
    assert 'ktpu_watchdog_fired_window{kind="ca_reserve_used"} 9' in text
    path = str(tmp_path / "metrics.prom")
    assert write_prometheus_textfile(path, report) == path
    assert open(path).read().strip() == text.strip()
    assert not os.path.exists(path + ".tmp")


# --- query observatory (PR 17, DESIGN §14) --------------------------------


def test_latency_histogram_percentiles_within_one_bucket_width():
    """Property gate for the bounded log-bucket histogram: over random
    log-uniform latency streams, count and sum are EXACT and every
    bucket-derived percentile lands within one bucket width of
    numpy.percentile(..., method="higher") over the raw samples."""
    from kubernetriks_tpu.telemetry import LatencyHistogram

    rng = np.random.default_rng(1234)
    for trial in range(6):
        n = int(rng.integers(3, 4000))
        lat = np.exp(rng.uniform(np.log(1e-5), np.log(120.0), n))
        h = LatencyHistogram()
        for v in lat.tolist():
            h.record(v)
        assert h.count == n
        assert h.sum_s == pytest.approx(math.fsum(lat.tolist()), rel=1e-9)
        assert h.min_s == lat.min() and h.max_s == lat.max()
        for q in (0.0, 50.0, 90.0, 95.0, 99.0, 100.0):
            exact = float(np.percentile(lat, q, method="higher"))
            got = h.percentile(q)
            assert abs(got - exact) <= h.bucket_width(exact) + 1e-15, (
                trial,
                q,
                got,
                exact,
                h.bucket_width(exact),
            )


def test_latency_histogram_memory_is_o_buckets_under_100k_soak():
    """The bounded-memory claim, observed: 100k samples spanning the
    underflow and overflow buckets leave the footprint EXACTLY where it
    started — O(buckets), never O(queries) — while count stays exact and
    the sparse cumulative dump stays monotone and complete."""
    from kubernetriks_tpu.telemetry import LatencyHistogram

    h = LatencyHistogram()
    base = h.footprint_bytes()
    assert 0 < base < 8192  # ~522 int64 buckets, nothing per-sample
    rng = np.random.default_rng(7)
    vals = np.exp(rng.uniform(np.log(1e-7), np.log(1e6), 100_000))
    for v in vals.tolist():
        h.record(v)
    assert h.count == 100_000
    assert h.footprint_bytes() == base
    bks = h.buckets()
    cums = [c for _, c in bks]
    assert cums[-1] == 100_000 and bks[-1][0] == math.inf
    assert all(cums[i] < cums[i + 1] for i in range(len(cums) - 1))
    # The overflow percentile reports the exact observed maximum (the
    # bucket boundary is +Inf — useless as a number).
    assert h.percentile(100.0) == float(vals.max())
    h.reset()
    assert h.count == 0 and h.sum_s == 0.0
    assert h.percentiles_ms() == {} and h.buckets() == []
    assert h.to_dict() == {"count": 0, "sum_s": 0.0, "buckets": []}
    assert h.footprint_bytes() == base


def test_slo_burn_rate_fires_before_occupancy_and_recovers():
    """The SLO verdict (KTPU_SLO_MS): a burst of over-SLO queries burns
    the 1% error budget past BOTH burn thresholds while every occupancy
    gauge stays healthy — so the latency regression pages strictly
    before any reserve/idle-lane verdict could notice. Fast queries then
    dilute the fast window below half its threshold: the fast verdict
    RECOVERS (event on the trail, no warning) while the slow verdict
    stays fired without re-warning, and reset_query_stats() re-arms
    everything atomically."""
    obs = Observatory(
        interval=10.0, capacities={}, slo_ms=10.0, slo_burn_window_s=60.0
    )
    obs.ingest(_ring_buf([(w, 0, 0, UNBOUNDED_SENTINEL) for w in range(6)]))
    for _ in range(32):
        obs.note_query(0.05, queue_wait_s=0.001, service_s=0.049)
    with pytest.warns(SaturationWarning, match="slo fast burn"):
        rec = obs.observe()
    kinds = {e["kind"] for e in rec["watchdog"]}
    assert kinds == {"slo_fast_burn", "slo_slow_burn"}, kinds  # ONLY slo
    ev = [e for e in rec["watchdog"] if e["kind"] == "slo_fast_burn"][0]
    assert ev["burn_rate"] == pytest.approx(100.0)  # (32/32)/0.01
    assert ev["violations"] == 32 and ev["samples"] == 32
    stats = obs.query_stats()
    assert stats["count"] == 32
    assert stats["queue_wait"]["p50_ms"] == pytest.approx(1.0, rel=0.06)
    assert stats["service"]["p99_ms"] == pytest.approx(49.0, rel=0.06)
    assert stats["histogram"]["count"] == 32
    report = obs.report()
    assert report["watchdog"]["slo_ms"] == 10.0
    assert report["watchdog"]["slo_burn_window_s"] == 60.0

    for _ in range(600):
        obs.note_query(0.001)  # healthy: 1ms << 10ms SLO
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec2 = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    kinds2 = {e["kind"] for e in rec2["watchdog"]}
    # fast burn: (32/632)/1% = 5.1x <= 14.4/2 -> recovered; slow burn:
    # 5.1x is under 6x (no re-fire) but above 3x (no recovery) -> held.
    assert kinds2 == {"slo_fast_burn_recovered"}, kinds2
    assert "slo_fast_burn" not in obs.fired
    assert "slo_slow_burn" in obs.fired

    obs.reset_query_stats()
    assert obs.query_stats() == {"count": 0}
    assert "slo_slow_burn" not in obs.fired  # re-armed with the stats


def test_slo_verdict_disarmed_without_flag():
    """No KTPU_SLO_MS, no slo kwarg: note_query records latencies but the
    SLO verdict never fires, no matter how slow the queries are."""
    obs = Observatory(interval=10.0, capacities={})
    assert obs.slo_ms is None
    obs.ingest(_ring_buf([(0, 0, 0, UNBOUNDED_SENTINEL)]))
    for _ in range(64):
        obs.note_query(10.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec = obs.observe()
    assert not [x for x in w if issubclass(x.category, SaturationWarning)]
    assert rec["watchdog"] == []
    assert obs.query_stats()["count"] == 64


def test_prometheus_native_histogram_rendering():
    """The exporter renders the observatory's query section as a native
    Prometheus histogram — cumulative _bucket{le=...} samples with the
    precision-preserving value rule, "+Inf" passed through as the
    literal label, exact _sum/_count — and never leaks the histogram
    dict as a flattened gauge."""
    report = {
        "resources": {
            "queries": {
                "count": 3,
                "p50_ms": 1.5,
                "p95_ms": 2.0,
                "p99_ms": 2.0,
                "queue_wait": {"p50_ms": 0.25, "p95_ms": 0.5, "p99_ms": 0.5},
                "service": {"p50_ms": 1.25, "p95_ms": 1.5, "p99_ms": 1.5},
                "histogram": {
                    "count": 3,
                    "sum_s": 0.00525,
                    "buckets": [[0.001, 1], [0.002, 2], ["+Inf", 3]],
                },
            },
        },
    }
    text = "\n".join(prometheus_lines(report))
    assert 'ktpu_query_latency{stat="count"} 3' in text
    assert 'ktpu_query_latency{stat="p50_ms"} 1.5' in text
    assert 'ktpu_query_latency{stat="queue_wait_p50_ms"} 0.25' in text
    assert 'ktpu_query_latency{stat="service_p99_ms"} 1.5' in text
    assert 'ktpu_query_latency_seconds_bucket{le="0.001"} 1' in text
    assert 'ktpu_query_latency_seconds_bucket{le="0.002"} 2' in text
    assert 'ktpu_query_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "ktpu_query_latency_seconds_sum 0.00525" in text
    assert "ktpu_query_latency_seconds_count 3" in text
    assert 'stat="histogram' not in text


def test_watchdog_without_telemetry_raises():
    with pytest.raises(ValueError, match="watchdog"):
        _build_composed(telemetry=False, watchdog=True)
