# ktpu: state-module
"""Seeded stateleaf violations: the leaf manifest drifted from the class
— a new field (`scratch`) missing from CLUSTER_STATE_LEAVES, and a stale
manifest entry (`legacy_ring`) naming a field that no longer exists."""

from typing import NamedTuple

import numpy as np


class ClusterBatchState(NamedTuple):
    time: np.ndarray
    pods: np.ndarray
    scratch: np.ndarray  # added without touching the manifest


CLUSTER_STATE_LEAVES = ("time", "pods", "legacy_ring")


def compare_states(a, b):
    import jax

    flat_a, _ = jax.tree_util.tree_flatten_with_path(a)
    return flat_a
