"""Seeded violation: donated/undonated pair declaring DIFFERENT static
sets — the drift makes `collect_gauges` traced in one variant only, so the
"bit-identical pair" compiles different programs (it happened once in
step.py)."""

import jax

_STATICS = ("max_events", "use_kernel")


def _impl(state, slab, max_events, use_kernel, collect_gauges=False):
    return state


run_entry = jax.jit(_impl, static_argnames=_STATICS + ("collect_gauges",))
run_entry_donated = jax.jit(
    _impl,
    static_argnames=_STATICS,  # BAD: missing "collect_gauges"
    donate_argnums=(0,),
)
