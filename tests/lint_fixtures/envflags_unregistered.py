"""Seeded violations: reads of KTPU_* names missing from the registry."""

import os


def mystery_knobs():
    a = os.environ["KTPU_NOT_A_FLAG"]  # BAD: unregistered (and direct)
    b = "KUBERNETRIKS_SECRET_MODE" in os.environ  # BAD: unregistered read
    c = os.getenv("KTPU_TURBO", "1")  # BAD: unregistered (and direct)
    return a, b, c
