"""Seeded violation: static_argnames naming a parameter that doesn't exist
(the renamed-kwarg regression class: the static set silently stops
matching and the kwarg traces)."""

from functools import partial

import jax

_STATICS = ("n_windows", "use_kernel")


@partial(jax.jit, static_argnames=_STATICS + ("max_pods",))
def run(state, n_windows, use_kernel, max_pods_per_cycle):
    # BAD: "max_pods" is not a parameter (it is max_pods_per_cycle)
    return state
