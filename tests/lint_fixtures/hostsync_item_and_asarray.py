# ktpu: hot-path
"""Seeded violations: blocking host syncs in a hot-path module."""

import jax
import numpy as np


def read_counter(state):
    return state.metrics.decisions.sum().item()  # BAD: .item() sync


def snapshot(state):
    phases = np.asarray(state.pods.phase)  # BAD: np.asarray materialization
    jax.block_until_ready(state)  # BAD: blocking fence
    return phases


def waived_counter(state):
    return state.metrics.decisions.sum().item()  # ktpu: sync-ok(test waiver: readout at span boundary)
