# ktpu: sim-path
"""Seeded scenariotrace violations: a scenario leaf reaching a SHAPE
expression and a jit-STATIC kwarg — both compile the current wave's
config into the program."""

from functools import partial

import jax
import jax.numpy as jnp

_STATICS = ("n_slots",)


@partial(jax.jit, static_argnames=_STATICS)
def grow_reserve(state, n_slots):
    return state


def resize(st, state):
    # Per-lane quota flowing into a shape: the array's SIZE would track
    # the scenario, recompiling every wave.
    pad = jnp.zeros(st.ca_max_nodes.max())
    # ...and into a declared jit static of a known entry.
    out = grow_reserve(state, n_slots=st.ca_max_nodes[0])
    return pad, out
