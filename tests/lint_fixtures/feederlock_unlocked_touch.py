# ktpu: threaded
"""Seeded feederlock violations: shared mutable attributes (written from
the producer thread) touched outside the ring lock — a torn counter and
an unlocked ring append."""

import threading


class LeakyFeeder:
    def __init__(self):
        self._cond = threading.Condition()
        self._ring = []
        self.produced = 0
        self.width = 128  # written only here: thread-safe config, exempt

    def _produce(self, slab):
        with self._cond:
            self._ring.append(slab)
        # Unlocked read-modify-write of a shared counter: torn updates.
        self.produced += 1

    def drain(self):
        # Unlocked container mutation from the consumer side.
        self._ring.pop()
        with self._cond:
            n = self.produced
        return n, self.width  # width is init-only config: must NOT flag
