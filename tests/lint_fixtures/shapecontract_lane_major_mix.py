# ktpu: sim-path
"""Seeded shapecontract violations: lane-major hazards — a (C,) lane
vector meeting a layout-ambiguous hot node leaf directly (wrong in one
of the two layouts whichever expansion you pick), plus a (C,)/(C,P) pod
mix through a jnp.where combiner."""

import jax.numpy as jnp

# Fixtures lint in isolation, so they carry their own signature registry
# (mirroring the real batched/state.py + autoscale.py entries).
AXIS_SIGNATURES = {
    "alive": "@node",
    "phase": "C,P",
    "time": "C",
    "ca_max_nodes": "C",
}


def razor_mask(state, st):
    nodes = state.nodes
    # alive is (C, N) row-major at rest but (N, C) inside lane-major
    # programs: the bare mask-mix must go through the axis-parameterized
    # helpers, never a direct broadcast.
    droppable = nodes.alive & (st.ca_max_nodes > 0)
    # (C, P) pod phase against the (C,) lane clock through a combiner.
    stale = jnp.where(state.pods.phase > 0, state.time, 0)
    # Explicit expansion stays clean.
    stale_ok = jnp.where(state.pods.phase > 0, state.time[:, None], 0)
    return droppable, stale, stale_ok
