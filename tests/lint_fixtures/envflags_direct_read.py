"""Seeded violation: direct environment read of a registered KTPU_* flag
outside flags.py (ad-hoc truthiness — the `env != "0"` class)."""

import os


def superspan_enabled():
    env = os.environ.get("KTPU_SUPERSPAN")  # BAD: bypasses the registry
    return env != "0" if env is not None else False
