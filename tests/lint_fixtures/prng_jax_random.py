# ktpu: sim-path
"""Seeded violation: ad-hoc jax.random keying on the simulation path
(order-dependent draws break scalar/batched bit-identity)."""

import jax


def crash_draws(seed, n):
    key = jax.random.PRNGKey(seed)  # BAD
    keys = jax.random.split(key, n)  # BAD
    return jax.random.uniform(keys[0], (n,))  # BAD
