"""Seeded violation: a window-program entry that threads `fault_params` as
a static but leaves `profile` traced (or undeclared) — the forked static
set would compile the DEFAULT scheduler pipeline no matter what profile the
engine configured (the silent-wrong-profile failure mode)."""

import jax

_STATICS = ("max_events", "fault_params")


def _impl(state, slab, max_events, fault_params=None, profile=None):
    return state


# BAD: "profile" missing from the static set while "fault_params" is
# declared and the wrapped function takes both.
run_entry = jax.jit(_impl, static_argnames=_STATICS)
