# ktpu: sim-path
"""Seeded violations: np.random and stdlib random on the simulation path."""

import random  # BAD: stdlib random import

import numpy as np


def jitter(n):
    rng = np.random.default_rng(0)  # BAD
    return rng.uniform(size=n) + random.random()  # BAD (stdlib draw)
