# ktpu: hot-path
"""Seeded violations: a metrics-export hook that smuggles device syncs
into the telemetry drain path. The REAL export seam
(kubernetriks_tpu/telemetry/export.py, observatory.py) runs strictly on
drained host copies and carries ZERO sync-ok waivers — this fixture is
the bug class the golden-clean gate keeps out of it."""

import json

import jax.numpy as jnp
import numpy as np


class LeakyJsonlExporter:
    """An exporter that reaches back into live engine state instead of
    consuming the drained record it was handed."""

    def __init__(self, path, engine):
        self.path = path
        self.engine = engine

    def emit(self, record):
        # BAD: host materialization of a live device array inside the
        # export hook (np.asarray on the engine's resident state).
        queued = np.asarray(self.engine.state.pods.phase)
        # BAD: .item() readback — a blocking device-to-host sync the
        # drain path never budgeted for.
        decisions = self.engine.state.metrics.scheduling_decisions.sum().item()
        record = dict(record, queued=int(queued.sum()), decisions=decisions)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(record) + "\n")


def occupancy_now(engine):
    used = jnp.sum(engine.state.auto.ca_cursor, axis=1)
    # BAD: int() on an array-valued expression (implicit __int__ sync)
    # while building an export record.
    return int(used.max())
