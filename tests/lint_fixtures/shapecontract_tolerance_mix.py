# ktpu: sim-path
"""Seeded shapecontract violations: the exact PR 13 bug class — a (C,)
per-lane leaf meeting a (C, G) per-group expression without an explicit
[:, None], in compare and arithmetic positions."""

import jax.numpy as jnp

# Fixtures lint in isolation, so they carry their own signature registry
# (mirroring the real batched/autoscale.py entries for these leaves).
AXIS_SIGNATURES = {
    "hpa_tolerance": "C",
    "ca_max_nodes": "C",
    "col_util_cpu": "C,G",
    "col_util_ram": "C,G",
    "ca_count": "C,G",
    "hpa_tail": "C,G",
}


def hpa_desired(st, auto):
    # (C, G) utilization ratio against the (C,) tolerance: the bare
    # compare broadcasts the lane axis on the WRONG side.
    util = auto.col_util_cpu / jnp.maximum(auto.col_util_ram, 1.0)
    over = util > st.hpa_tolerance
    under = util < (1.0 - st.hpa_tolerance)
    # The correct spelling stays clean:
    over_ok = util > st.hpa_tolerance[:, None]
    # Arithmetic mix: (C, G) head count plus the (C,) CA quota.
    budget = auto.ca_count + st.ca_max_nodes
    budget_ok = auto.ca_count + st.ca_max_nodes[:, None]
    # A deliberate mix under a waiver stays clean.
    planned = auto.hpa_tail - st.ca_max_nodes  # ktpu: shape-ok(fixture: deliberate lane fold)
    return over, under, over_ok, budget, budget_ok, planned
