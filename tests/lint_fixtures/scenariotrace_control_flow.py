# ktpu: sim-path
"""Seeded scenariotrace violations: per-lane scenario leaves flowing into
Python control flow and host casts — each would turn a what-if config
into a recompile (or bake the previous wave's config into the program)."""

import jax.numpy as jnp


def plan_cycle(st, auto):
    # Branching on a traced per-lane leaf: implicit host sync AND a
    # program whose structure depends on the scenario.
    if st.hpa_tolerance.max() > 0.5:
        tol = st.hpa_tolerance * 2.0
    else:
        tol = st.hpa_tolerance
    # Host cast of the per-lane CA quota.
    quota = int(st.ca_max_nodes.sum())
    # .item() read of the fault seed.
    seed0 = st.fault_seed.item()
    # Presence checks stay LEGAL (structural static) — must not flag.
    if st.fault_seed is None:
        return tol, 0, 0
    return tol, quota, seed0


def waived_probe(st):
    # A deliberate, documented host read keeps working under a waiver.
    return float(st.ca_threshold[0])  # ktpu: scenario-ok(debug probe off the hot path)
