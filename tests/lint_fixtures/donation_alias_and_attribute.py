"""Seeded violations: donation through a local alias and through `self.X`.

The engine's real call sites go through aliases
(`fn = run_donated if self.donate else run_plain`), so the pass must
poison arguments of alias calls too; and the donated argument is usually
an attribute path (`self.state`), which must poison deeper reads
(`self.state.pods`) until the attribute is rebound.
"""

import jax


def _impl(state, w):
    return state


run_plain = jax.jit(_impl)
run_donated = jax.jit(_impl, donate_argnums=(0,))


class Driver:
    def step_bad(self, w):
        fn = run_donated if self.donate else run_plain
        out = fn(self.state, w)
        phases = self.state.pods  # BAD: read before rebinding self.state
        self.state = out
        return phases

    def step_good(self, w):
        fn = run_donated if self.donate else run_plain
        self.state = fn(self.state, w)
        return self.state.pods  # fine: rebound
