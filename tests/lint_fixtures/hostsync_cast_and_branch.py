# ktpu: hot-path
"""Seeded violations: implicit syncs through int() casts and Python
branches on traced values (the `if shift > 0:` class that undoes the
async-readback work)."""

import jax.numpy as jnp


def decide_slide(phase, create_win, base):
    shift = jnp.argmax(phase, axis=1).min()
    s = int(shift)  # BAD: blocking device-to-host readback via __int__
    return s


def branch_on_traced(state):
    pending = jnp.sum(state.pods.phase == 1)
    if pending > 0:  # BAD: Python branch forces bool() on a traced value
        return True
    return False
