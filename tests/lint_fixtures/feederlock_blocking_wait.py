# ktpu: threaded
"""Seeded feederlock violations: blocking while HOLDING the ring lock —
an Event.wait and a time.sleep inside the with-lock block (the condvar's
own .wait() is the one legal wait and must NOT flag)."""

import threading
import time


class StallingFeeder:
    def __init__(self):
        self._cond = threading.Condition()
        self._ready = threading.Event()
        self.backlog = 0

    def get(self):
        with self._cond:
            while self.backlog == 0:
                self._cond.wait()  # legal: the condvar releases the lock
            # Blocking on a NON-lock event while holding the lock: the
            # producer can never publish, both threads stall.
            self._ready.wait()
            time.sleep(0.01)
            self.backlog -= 1
