# ktpu: state-module
"""Seeded stateleaf violation: a state class whose BY-NAME consumer
misses a leaf. `compare_states` here has no pytree-generic traversal and
never names `auto` — the exact "new leaf added to ClusterBatchState but
not to compare_states" hazard, self-contained (state-module pragma)."""

from typing import NamedTuple, Optional

import numpy as np


class ClusterBatchState(NamedTuple):
    time: np.ndarray
    pods: np.ndarray
    auto: Optional[np.ndarray] = None


# The manifest itself is complete — only the consumer lags.
CLUSTER_STATE_LEAVES = ("time", "pods", "auto")


def compare_states(a, b):
    bad = []
    if not (a.time == b.time).all():
        bad.append("time")
    if not (a.pods == b.pods).all():
        bad.append("pods")
    # `auto` silently escapes the comparison: the seeded violation.
    return bad
