"""Seeded violation: read of a donated variable after the donated call.

On TPU, `state`'s buffers are consumed by the call; the `.time` read on the
last line observes garbage. On CPU (donation no-op) it silently passes —
exactly the bug class the donation pass exists for.
"""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def consume_state(state, idxs):
    return state


def bad_driver(state, idxs):
    new_state = consume_state(state, idxs)
    return new_state, state.time  # BAD: read after donate


def good_driver(state, idxs):
    state = consume_state(state, idxs)
    return state, state.time  # fine: rebound from the call's result
