"""Seeded violation: loop-carried read-after-donate.

The first iteration donates `state` and stores the result somewhere else;
the second iteration's `state.time` read hits the donated buffer. A single
linear walk misses it — the pass walks loop bodies twice.
"""

import jax


@jax.jit
def _impl(state):
    return state


step_donated = jax.jit(_impl, donate_argnums=(0,))


def bad_loop(state, n):
    outs = []
    for _ in range(n):
        outs.append(step_donated(state))  # BAD on iteration 2: state was
        # donated on iteration 1 and never rebound
    return outs
