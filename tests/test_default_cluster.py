"""Default-cluster node-group naming rules, checked across all three components
(port of reference tests/test_default_cluster.rs)."""

from kubernetriks_tpu.core.types import Node, NodeConditionType
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import (
    check_count_of_nodes_in_components_equals_to,
    check_expected_node_is_equal_to_nodes_in_components,
    default_test_simulation_config,
)


def make_default_node(name: str, cpu: int, ram: int) -> Node:
    node = Node.new(name, cpu, ram)
    node.update_condition("True", NodeConditionType.NODE_CREATED, 0.0)
    return node


def test_config_default_cluster_is_none():
    sim = KubernetriksSimulation(default_test_simulation_config())
    sim.initialize_default_cluster()
    check_count_of_nodes_in_components_equals_to(0, sim)


def test_config_default_cluster_with_no_name_prefix():
    config = default_test_simulation_config(
        """
default_cluster:
- node_count: 10
  node_template:
      metadata:
        labels:
          storage_type: ssd
          proc_type: intel
      status:
        capacity:
          cpu: 18000
          ram: 18589934592
- node_count: 20
  node_template:
      status:
        capacity:
          cpu: 24000
          ram: 18589934592
"""
    )
    sim = KubernetriksSimulation(config)
    sim.initialize_default_cluster()
    check_count_of_nodes_in_components_equals_to(30, sim)

    for idx in range(10):
        expected = make_default_node(f"default_node_{idx}", 18000, 18589934592)
        expected.metadata.labels = {"storage_type": "ssd", "proc_type": "intel"}
        check_expected_node_is_equal_to_nodes_in_components(expected, sim)
    for idx in range(10, 30):
        expected = make_default_node(f"default_node_{idx}", 24000, 18589934592)
        check_expected_node_is_equal_to_nodes_in_components(expected, sim)


def test_config_default_cluster_with_name_prefix():
    config = default_test_simulation_config(
        """
default_cluster:
- node_count: 5
  node_template:
      metadata:
        name: group_a
      status:
        capacity:
          cpu: 18000
          ram: 18589934592
"""
    )
    sim = KubernetriksSimulation(config)
    sim.initialize_default_cluster()
    check_count_of_nodes_in_components_equals_to(5, sim)
    for idx in range(5):
        expected = make_default_node(f"group_a_{idx}", 18000, 18589934592)
        check_expected_node_is_equal_to_nodes_in_components(expected, sim)


def test_config_default_cluster_single_named_node():
    config = default_test_simulation_config(
        """
default_cluster:
- node_template:
      metadata:
        name: super_node
      status:
        capacity:
          cpu: 1024000
          ram: 549755813888
- node_count: 1
  node_template:
      metadata:
        name: another_single
      status:
        capacity:
          cpu: 2000
          ram: 4294967296
"""
    )
    sim = KubernetriksSimulation(config)
    sim.initialize_default_cluster()
    check_count_of_nodes_in_components_equals_to(2, sim)
    check_expected_node_is_equal_to_nodes_in_components(
        make_default_node("super_node", 1024000, 549755813888), sim
    )
    check_expected_node_is_equal_to_nodes_in_components(
        make_default_node("another_single", 2000, 4294967296), sim
    )


def test_mixed_groups_share_global_index():
    """Unnamed/named multi-node groups share one running node index
    (reference: simulator.rs:322-343 `total_nodes` spans groups)."""
    config = default_test_simulation_config(
        """
default_cluster:
- node_count: 2
  node_template:
      metadata:
        name: prefix_a
      status:
        capacity:
          cpu: 1000
          ram: 1000
- node_count: 2
  node_template:
      status:
        capacity:
          cpu: 2000
          ram: 2000
"""
    )
    sim = KubernetriksSimulation(config)
    sim.initialize_default_cluster()
    check_count_of_nodes_in_components_equals_to(4, sim)
    for name in ["prefix_a_0", "prefix_a_1", "default_node_2", "default_node_3"]:
        assert sim.persistent_storage.get_node(name) is not None
