"""TP/SP parallelism of the policy head: ring attention (sequence-parallel
over the node axis) and the tensor-parallel FFN, checked for parity against
the single-device forward on the suite's 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kubernetriks_tpu.parallel.ring import full_attention, ring_attention
from kubernetriks_tpu.rl.attention_policy import (
    attention_policy_apply,
    init_attention_policy,
    make_sharded_apply,
)
from kubernetriks_tpu.rl.policy import NODE_FEATURES
from kubernetriks_tpu.parallel.multihost import shard_map


def _seq_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _rand_qkv(rng, B, H, N, D):
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, H, N, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, N, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, N, D), jnp.float32)
    mask = jax.random.bernoulli(ks[3], 0.7, (B, 1, N))
    return q, k, v, mask


def test_ring_attention_matches_full_attention():
    q, k, v, mask = _rand_qkv(jax.random.PRNGKey(0), B=3, H=2, N=16, D=8)
    want = full_attention(q, k, v, mask)

    mesh = _seq_mesh(8)
    ring = jax.jit(
        shard_map(
            lambda q, k, v, m: ring_attention(q, k, v, m, "seq"),
            mesh=mesh,
            in_specs=(
                P(None, None, "seq", None),
                P(None, None, "seq", None),
                P(None, None, "seq", None),
                P(None, None, "seq"),
            ),
            out_specs=P(None, None, "seq", None),
        )
    )
    got = ring(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


def test_ring_attention_fully_masked_rows_are_zero():
    q, k, v, mask = _rand_qkv(jax.random.PRNGKey(1), B=2, H=1, N=8, D=4)
    mask = jnp.zeros_like(mask, bool)  # no valid keys anywhere
    want = full_attention(q, k, v, mask)
    assert np.all(np.asarray(want) == 0.0)

    mesh = _seq_mesh(8)
    got = jax.jit(
        shard_map(
            lambda q, k, v, m: ring_attention(q, k, v, m, "seq"),
            mesh=mesh,
            in_specs=(
                P(None, None, "seq", None),
                P(None, None, "seq", None),
                P(None, None, "seq", None),
                P(None, None, "seq"),
            ),
            out_specs=P(None, None, "seq", None),
        )
    )(q, k, v, mask)
    assert np.all(np.isfinite(np.asarray(got)))
    assert np.all(np.asarray(got) == 0.0)


def _rand_feats(rng, C, N):
    ks = jax.random.split(rng, 2)
    feats = jax.random.uniform(ks[0], (C, N, NODE_FEATURES), jnp.float32)
    alive = jax.random.bernoulli(ks[1], 0.8, (C, N)).astype(jnp.float32)
    return feats.at[..., 0].set(alive)


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (1, 4, 2), (2, 4, 1)])
def test_sharded_attention_policy_matches_unsharded(mesh_shape):
    """DP x SP x TP forward == plain forward: clusters sharded on `data`,
    node axis on `seq` (ring attention), FFN hidden dim on `model`."""
    d, s, m = mesh_shape
    devices = np.array(jax.devices()[: d * s * m]).reshape(mesh_shape)
    mesh = Mesh(devices, ("data", "seq", "model"))

    params = init_attention_policy(jax.random.PRNGKey(7), hidden=32, heads=4)
    feats = _rand_feats(jax.random.PRNGKey(8), C=4, N=8)

    want_logits, want_value = attention_policy_apply(params, feats)
    apply = make_sharded_apply(mesh)
    got_logits, got_value = apply(params, feats)

    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(got_value), np.asarray(want_value), rtol=1e-5, atol=1e-6
    )


def test_sharded_attention_policy_gradients_match():
    """Training through the sharded forward: d(loss)/d(params) computed
    through shard_map (ring attention + TP psums) matches the unsharded
    gradient — the guarantee that TP/SP training is the same optimization
    problem, not just the same inference."""
    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devices, ("data", "seq", "model"))
    params = init_attention_policy(jax.random.PRNGKey(3), hidden=32, heads=4)
    feats = _rand_feats(jax.random.PRNGKey(4), C=4, N=8)
    sharded_apply = make_sharded_apply(mesh)

    def loss(apply):
        def f(p):
            logits, value = apply(p, feats)
            return (jnp.tanh(logits).sum() + (value**2).sum()).astype(jnp.float32)
        return f

    g_ref = jax.grad(loss(attention_policy_apply))(params)
    g_sh = jax.grad(loss(sharded_apply))(params)
    # Tolerances: in float64 the two gradients agree to ~1e-10 relative
    # (mathematically the same function); in float32 the online-softmax
    # backward reassociates, leaving ~1e-6-absolute noise that is large
    # RELATIVE only on near-zero elements — hence the atol floor.
    for k in g_ref:
        np.testing.assert_allclose(
            np.asarray(g_sh[k]), np.asarray(g_ref[k]),
            rtol=5e-3, atol=5e-6, err_msg=k,
        )


def test_ppo_trains_attention_policy():
    """The attention policy drops into the PPO trainer at the same seam as
    the MLP head and one iteration produces finite losses + decisions."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: attn_rl\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(8, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=0.5, horizon=100.0, seed=5, cpu=2000,
        ram=4 * 1024**3, duration_range=(20.0, 60.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=4,
        max_pods_per_cycle=8,
    )
    trainer = PPOTrainer(
        sim,
        windows_per_rollout=4,
        config=PPOConfig(epochs_per_iteration=1),
        hidden=32,
        policy_kind="attention",
    )
    result = trainer.train_iteration()
    assert np.isfinite(result["policy_loss"])
    assert result["decisions"] > 0
    assert result["placements"] > 0
