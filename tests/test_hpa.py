"""Golden HPA trajectory: replicas 5->9->14->(hold)->4->(hold)->7->12->14
(port of reference tests/test_hpa.rs)."""

from kubernetriks_tpu.config import KubeHorizontalPodAutoscalerConfig
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

CLUSTER_TRACE = """
events:
- timestamp: 5.0
  event_type:
    !CreateNode
      node:
        metadata:
          name: trace_node_42
        status:
          capacity:
            cpu: 64000
            ram: 68719476736
"""

WORKLOAD_TRACE = """
events:
- timestamp: 59.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: pod_group_1
        initial_pod_count: 5
        max_pod_count: 100
        pod_template:
          metadata:
            name: pod_group_1
          spec:
            resources:
              requests:
                cpu: 100
                ram: 104857600
              limits:
                cpu: 100
                ram: 104857600
        target_resources_usage:
          cpu_utilization: 0.6
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 500.0
                total_load: 8
              - duration: 200.0
                total_load: 2
"""


def pod_group_len(sim: KubernetriksSimulation) -> int:
    return len(sim.horizontal_pod_autoscaler.pod_groups["pod_group_1"].created_pods)


def test_pod_group_created_and_scaled_by_cpu_utilization():
    config = default_test_simulation_config()
    config.horizontal_pod_autoscaler.enabled = True
    config.horizontal_pod_autoscaler.kube_horizontal_pod_autoscaler_config = (
        KubeHorizontalPodAutoscalerConfig()
    )

    sim = KubernetriksSimulation(config)
    sim.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(WORKLOAD_TRACE),
    )

    # HPA cycles at 60, 120, 180, ... The expected replica counts below follow
    # the k8s formula desired = ceil(current * util/target) with util =
    # min(1, total_load / pod_count), target 0.6, tolerance 0.1
    # (worked out in the reference test's comments, tests/test_hpa.rs:90-135).
    sim.step_until_time(61.0)
    assert pod_group_len(sim) == 5
    sim.step_until_time(121.0)
    assert pod_group_len(sim) == 9
    sim.step_until_time(181.0)
    assert pod_group_len(sim) == 14
    sim.step_until_time(450.0)
    assert pod_group_len(sim) == 14
    sim.step_until_time(600.5)
    assert pod_group_len(sim) == 4
    sim.step_until_time(759.5)
    assert pod_group_len(sim) == 4
    sim.step_until_time(781.0)
    assert pod_group_len(sim) == 7
    sim.step_until_time(841.0)
    assert pod_group_len(sim) == 12
    sim.step_until_time(901.0)
    assert pod_group_len(sim) == 14
    sim.step_until_time(1200.0)
    assert pod_group_len(sim) == 14
    # Scale metrics reflect the up/down churn.
    metrics = sim.metrics_collector.accumulated_metrics
    assert metrics.total_scaled_up_pods == (4 + 5 + 3 + 5 + 2)
    assert metrics.total_scaled_down_pods == 10
