"""bench.py --smoke: the CPU-safe plumbing check for the three tracked
bench lines (continuity shape, composed flagship, north-star stand-in).
Asserts all three lines build, RUN their full machinery — the composed
line includes real window slides, HPA scale-ups and CA provisioning, the
same in-bench asserts the flagship line enforces on hardware — and emit
parseable JSON with the headline fields. Values are not performance
numbers; tier-1 runs this under JAX_PLATFORMS=cpu (conftest pins it)."""

import json
import os
import sys


def test_bench_smoke_emits_three_parseable_lines(capsys):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench.main(["--smoke"])
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines() if ln.strip()
    ]
    assert len(lines) == 3, lines
    records = [json.loads(ln) for ln in lines]
    for rec in records:
        assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
        assert rec["unit"] == "decisions/s"
        assert rec["value"] > 0
        # Smoke values are toy-shape numbers; the rounded-to-3-decimals
        # ratio can legitimately print as 0.0.
        assert rec["vs_baseline"] >= 0
    # Line order is part of the contract: continuity, composed, north-star
    # (the LAST line is the headline the driver reads).
    assert "composed" in records[1]["metric"]
    assert "north-star" in records[2]["metric"]


def test_bench_smoke_faults_adds_chaos_line(capsys):
    """--faults appends a fault-enabled composed smoke line (the chaos
    engine's dispatch/throughput tracker) after the standard three."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench.main(["--smoke", "--faults"])
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines() if ln.strip()
    ]
    assert len(lines) == 4, lines
    records = [json.loads(ln) for ln in lines]
    assert "chaos" in records[3]["metric"]
    assert records[3]["value"] > 0
