"""bench.py --smoke: the CPU-safe plumbing check for the tracked bench
lines (continuity shape, composed flagship, superspan machinery,
north-star stand-in). Asserts every line builds, RUNS its full machinery —
the composed lines include real window slides, HPA scale-ups and CA
provisioning, the same in-bench asserts the flagship line enforces on
hardware; the superspan line additionally asserts the SCANNED executor
dispatched (so CI catches a silent fallback to the ladder path) — and
emits parseable JSON with the headline fields. Composed lines time >= 5
repeated spans and carry the median + min/max spread. Values are not
performance numbers; tier-1 runs this under JAX_PLATFORMS=cpu (conftest
pins it)."""

import json
import os
import sys


def _smoke_records(capsys, args):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench.main(args)
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines() if ln.strip()
    ]
    records = [json.loads(ln) for ln in lines]
    for rec in records:
        assert set(rec) - {"spans"} == {"metric", "value", "unit", "vs_baseline"}
        assert rec["unit"] == "decisions/s"
        assert rec["value"] > 0
        # Smoke values are toy-shape numbers; the rounded-to-3-decimals
        # ratio can legitimately print as 0.0.
        assert rec["vs_baseline"] >= 0
    return records


def test_bench_smoke_emits_four_parseable_lines(capsys):
    records = _smoke_records(capsys, ["--smoke"])
    assert len(records) == 4, records
    # Line order is part of the contract: continuity, composed, superspan
    # machinery, north-star (the LAST line is the headline the driver
    # reads).
    assert "composed" in records[1]["metric"]
    assert "superspan" in records[2]["metric"]
    assert "north-star" in records[3]["metric"]
    # Composed lines report the >= 5-span median with min/max spread; the
    # plain-shape lines keep the bare single-region value.
    for rec in records[1:3]:
        spans = rec["spans"]
        assert spans["n"] >= 5
        assert spans["min"] <= rec["value"] <= spans["max"]
    assert "spans" not in records[0] and "spans" not in records[3]


def test_bench_smoke_faults_adds_chaos_line(capsys):
    """--faults appends a fault-enabled composed smoke line (the chaos
    engine's dispatch/throughput tracker) after the standard four."""
    records = _smoke_records(capsys, ["--smoke", "--faults"])
    assert len(records) == 5, records
    assert "chaos" in records[4]["metric"]
    assert records[4]["value"] > 0
    assert records[4]["spans"]["n"] >= 5
