"""bench.py --smoke: the CPU-safe plumbing check for the tracked bench
lines (continuity shape, composed flagship, superspan machinery,
streaming feeder, endurance churn, north-star stand-in, tune plumbing).
Asserts every
line builds, RUNS its full machinery — the composed lines include real
window slides, HPA scale-ups and CA provisioning, the same in-bench
asserts the flagship line enforces on hardware; the superspan line
additionally asserts the SCANNED executor dispatched (so CI catches a
silent fallback to the ladder path), the streaming line asserts the
FEEDER ring staged the run (so CI catches a silent fallback to
whole-trace staging), and the endurance line asserts CA slot RECLAIM
fired with flat RSS/slab watermarks and zero recompiles (so CI catches
a reclaim regression before the slow endurance gate does) — and emits
parseable JSON with the headline fields. Composed lines time >= 5
repeated spans and carry the median + min/max spread. Values are not
performance numbers; tier-1 runs this under JAX_PLATFORMS=cpu (conftest
pins it)."""

import json
import os
import sys

import pytest


def _smoke_records(capsys, args):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench.main(args)
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines() if ln.strip()
    ]
    records = [json.loads(ln) for ln in lines]
    for rec in records:
        if rec.get("unit") == "scenarios/s":
            # The scenario-fleet sweep line: its own unit and record
            # shape (what-if queries per second + the full sweep block).
            assert set(rec) == {"metric", "value", "unit", "sweep"}
            assert rec["value"] > 0
            continue
        if rec.get("unit") == "queries/s":
            # The open-loop lane-async line (DESIGN §13): queries per
            # second + the full open_loop block.
            assert set(rec) == {"metric", "value", "unit", "open_loop"}
            assert rec["value"] > 0
            continue
        if rec.get("unit") == "availability":
            # The host-chaos line (DESIGN §15): availability over the
            # injected phase + the full host_chaos block.
            assert set(rec) == {"metric", "value", "unit", "host_chaos"}
            assert 0.0 <= rec["value"] <= 1.0
            continue
        if rec.get("unit") == "ms/window":
            # The tune line (PR 20): the autotuner objective (fake
            # units on smoke) + the full tune block (chosen statics,
            # profile path, budget accounting).
            assert set(rec) == {"metric", "value", "unit", "tune"}
            assert rec["value"] > 0
            continue
        assert set(rec) - {"spans", "telemetry", "endurance"} == {
            "metric", "value", "unit", "vs_baseline",
        }
        assert rec["unit"] == "decisions/s"
        assert rec["value"] > 0
        # Smoke values are toy-shape numbers; the rounded-to-3-decimals
        # ratio can legitimately print as 0.0.
        assert rec["vs_baseline"] >= 0
    return records


def test_bench_smoke_emits_ten_parseable_lines(capsys, tmp_path, monkeypatch):
    # --trace rides along (the CI smoke job runs it this way): the
    # composed lines must carry the flight-recorder summary AND write a
    # Perfetto-loadable Chrome trace per traced line.
    monkeypatch.setenv("KTPU_TRACE_PATH", str(tmp_path / "ktpu_trace"))
    monkeypatch.setenv("KTPU_METRICS_PATH", str(tmp_path / "ktpu_metrics"))
    monkeypatch.setenv("KTPU_SWEEP_PATH", str(tmp_path / "ktpu_sweep"))
    records = _smoke_records(capsys, ["--smoke", "--trace"])
    assert len(records) == 10, records
    # Line order is part of the contract: continuity, composed, superspan
    # machinery, streaming feeder, endurance churn, compiled profile,
    # north-star, tune plumbing, open-loop lane-async fleet, scenario
    # fleet (the sweep runs LAST: its cold-process baseline clears the
    # jit caches, which would cold-start anything after it).
    assert "composed" in records[1]["metric"]
    assert "superspan" in records[2]["metric"]
    assert "streaming" in records[3]["metric"]
    assert "endurance churn" in records[4]["metric"]
    # The compiled-profile line ran under the second (best_fit) scheduler
    # profile — its in-bench asserts fail loudly when the engine silently
    # falls back to the default pipeline, so its presence IS the gate.
    assert "best_fit profile" in records[5]["metric"]
    assert "north-star" in records[6]["metric"]
    assert "tuned statics" in records[7]["metric"]
    assert "open-loop lane-async fleet" in records[8]["metric"]
    assert "scenario-vector fleet" in records[9]["metric"]
    # The TUNE line (PR 20): run_tune_fake's in-bench assert already
    # proved the written profile loads back BUILD-IDENTICAL to
    # hand-passed statics (engine.tuning_statics equality); pin the
    # disclosure, the pinned fake winner, and the JSON artifact CI
    # uploads (a valid ktpu-tuned-profile document with every measured
    # candidate disclosed).
    tune = records[7]["tune"]
    assert tune["measurement"] == "fake"
    assert tune["chosen"]["lane_major"] is True
    assert tune["chosen"]["window_razor"] is True
    assert tune["objective"] < tune["baseline_objective"]
    assert tune["roundtrip_build_identical"] is True
    assert tune["complete"] is True
    tuned_doc = json.loads(
        (tmp_path / "ktpu_sweep_tuned.json").read_text()
    )
    assert tuned_doc["kind"] == "ktpu-tuned-profile"
    assert tuned_doc["statics"] == tune["chosen"]
    assert len(tuned_doc["candidates"]) == tune["candidates"]
    assert tuned_doc["knob_registry"]
    # The ENDURANCE line (r14): run_endurance's in-bench gates (reclaim
    # actually fired, flat RSS/slab watermarks, zero recompiles after
    # warm-up, no reserve saturation verdict) already ran — the record's
    # endurance block discloses what was checked; pin the disclosure so a
    # gate that silently stops running fails here.
    endur = records[4]["endurance"]
    assert endur["allocations"] >= 3 * endur["reserve_slots"]
    assert endur["reclaimed"] >= endur["allocations"] - endur["reserve_slots"]
    assert endur["recompiles_after_warmup"] == 0
    # Reserve verdicts are the hard gate inside run_endurance; pipeline
    # verdicts (feeder stalls at toy shapes) are disclosed, not asserted.
    assert not any(
        k.endswith("_reserve_used") for k in endur["watchdog_fired"]
    )
    assert endur["rss_end_mb"] <= endur["rss_after_warm_mb"] * 1.5 + 256
    assert records[4]["spans"]["n"] >= 4
    assert records[4]["spans"]["min"] > 0
    # The scenario-fleet line: its in-bench asserts (zero recompiles
    # after warm-up, no lane cross-talk on the duplicate-scenario probes)
    # already ran inside run_sweep — the record's sweep block discloses
    # what was checked, and the JSON artifact landed for CI upload.
    sweep = records[9]["sweep"]
    assert sweep["scenarios"] == 8 and sweep["lanes"] == 4
    assert sweep["waves"] == 2
    assert sweep["recompiles_after_warmup"] == 0
    assert sweep["crosstalk_probes"]
    assert sweep["decisions_total"] > 0
    # Smoke keeps the jit caches warm (no cold-process baseline; the
    # speedup gate only arms on the full --sweep) and discloses it.
    assert sweep["baseline"]["cold_process_model"] is False
    sweep_doc = json.loads((tmp_path / "ktpu_sweep.json").read_text())
    assert sweep_doc == sweep
    # The OPEN-LOOP line (DESIGN §13): run_open_loop's in-bench asserts
    # (A/B bit-identity on every query between the wave-aligned and
    # lane-async fleets, zero recompiles across post-warm-up pump
    # rounds) already ran; pin the disclosure + the JSON artifact CI
    # uploads. The occupancy/speedup hard gates arm on the full --sweep
    # only — smoke pins the machinery, not toy-shape performance.
    ol = records[8]["open_loop"]
    assert ol["queries"] == 8 and ol["lanes"] == 4
    assert ol["ab_identity_checked"] == 8
    assert ol["recompiles_after_warmup"] == 0
    assert ol["recompile_sentinel"]["post_warmup_events"] == 0
    assert ol["async_queries_per_s"] > 0 and ol["wave_queries_per_s"] > 0
    assert 0 < ol["lane_occupancy"]["min"] <= ol["lane_occupancy"]["mean"] <= 1
    assert ol["latency_ms"]["p50_ms"] > 0
    ol_doc = json.loads((tmp_path / "ktpu_sweep_openloop.json").read_text())
    assert ol_doc == ol
    # Composed lines report the >= 5-span median with min/max spread; the
    # plain-shape lines keep the bare single-region value.
    for rec in records[1:4]:
        spans = rec["spans"]
        assert spans["n"] >= 5
        assert spans["min"] <= rec["value"] <= spans["max"]
        # r7 span-validity protocol: zero-decision (trace-exhausted) spans
        # are dropped and DISCLOSED, and every span that made the median
        # committed decisions — spans.min == 0 can no longer happen.
        assert spans["dropped"] >= 0
        assert spans["min"] > 0
    for rec in (records[0], records[5], records[6]):
        assert "spans" not in rec
    # Telemetry summary embedded in (exactly) the traced composed lines:
    # per-phase wall time, the observed-vs-expected sync budget, dispatch
    # stats with the ladder_fallbacks observable, device-ring totals.
    # The endurance line (records[4]) writes its trace/metrics artifacts
    # but keeps the flight-recorder summary out of the record — its
    # disclosure is the endurance block.
    for rec in (records[0], records[4], records[5], records[6]):
        assert "telemetry" not in rec
    for rec in records[1:4]:
        tel = rec["telemetry"]
        assert tel["spans_ms"]
        assert tel["sync_budget"]["observed_slide_syncs"] >= 0
        assert "ladder_fallbacks" in tel["dispatch_stats"]
        assert tel["ring_totals"]["decisions"] > 0
        # Per-window window-program cost (the lane-major / window-razor /
        # CA-de-scatter observable): present and positive on every traced
        # composed line, so layout regressions surface on CPU CI.
        pw = tel["per_window"]
        assert pw["windows"] > 0
        assert pw["ms_per_window"] > 0
    # The superspan line's trace shows the scanned executor: superspan
    # dispatches present, zero ladder chunks, sync budget exactly met.
    tel = records[2]["telemetry"]
    assert tel["dispatch_stats"]["superspans"] > 0
    assert tel["dispatch_stats"]["window_chunks"] == 0
    assert (
        tel["sync_budget"]["observed_slide_syncs"]
        == tel["sync_budget"]["steady_state_expected"]
    )
    # The streaming line's trace shows the feeder pipeline: slabs
    # produced AND installed, the whole-trace payload never materialized
    # (dispatch stats make a starved feeder observable: production vs
    # installs plus the stall split in the feeder section), sync budget
    # still exactly one progress readback per superspan.
    tel = records[3]["telemetry"]
    assert tel["dispatch_stats"]["superspans"] > 0
    assert tel["dispatch_stats"]["feeder_slabs_produced"] > 0
    assert tel["dispatch_stats"]["stage_refills"] > 0
    assert (
        tel["sync_budget"]["observed_slide_syncs"]
        == tel["sync_budget"]["steady_state_expected"]
    )
    feeder = tel["feeder"]
    # dispatch_stats is cumulative across feeder re-seeks (window growth);
    # the feeder section describes the LAST feeder generation.
    assert feeder["slabs_produced"] <= tel["dispatch_stats"]["feeder_slabs_produced"]
    assert feeder["ring_depth_high_water"] <= feeder["ring_capacity"]
    assert set(feeder["stalls"]) == {"feeder_not_ready", "upload_wait"}
    # Capacity-observatory resources section on every traced composed
    # line (the capacity half of the flight recorder): occupancy gauges
    # with reserve-capacity fractions plus RSS/slab watermarks — present
    # and sane, so a change that stops the observatory sampling fails on
    # CPU CI.
    for rec in records[1:4]:
        res = rec["telemetry"]["resources"]
        assert res["rss_mb"] > 0
        assert res["rss_high_water_mb"] >= res["rss_mb"] * 0.5
        occ = res["occupancy"]
        assert {"hpa_reserve_used", "ca_reserve_used", "pod_headroom"} <= set(occ)
        ca = occ["ca_reserve_used"]
        assert ca["capacity_min"] > 0
        assert 0 <= ca["used_max"] <= ca["high_water"] <= ca["capacity_min"]
        assert res["slabs"]["telemetry_ring_bytes"] > 0
        assert "watchdog_fired" in res
    # The streaming line's slab accounting shows the bounded feeder ring
    # and NO whole-trace device payload (the memory bound, in bytes).
    res = records[3]["telemetry"]["resources"]
    assert res["slabs"]["device_slide_bytes"] == 0
    assert res["slabs"].get("feeder_ring_capacity_bytes", 0) > 0
    for label in (
        "smoke_composed", "smoke_superspan", "smoke_stream", "smoke_endurance",
    ):
        path = tmp_path / f"ktpu_trace_{label}.json"
        assert path.exists(), f"missing Chrome trace {path}"
        doc = json.loads(path.read_text())
        assert doc["traceEvents"], "empty Chrome trace"
        # The observatory's time-series export landed next to the trace:
        # parseable JSONL drain records + the Prometheus textfile.
        jsonl = tmp_path / f"ktpu_metrics_{label}.jsonl"
        assert jsonl.exists(), f"missing metrics JSONL {jsonl}"
        lines = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
        assert lines and all("occupancy" in ln for ln in lines)
        assert lines[-1]["resources"]["rss_bytes"] > 0
        prom = tmp_path / f"ktpu_metrics_{label}.prom"
        assert prom.exists(), f"missing Prometheus textfile {prom}"
        prom_text = prom.read_text()
        assert "ktpu_occupancy{" in prom_text
        assert "ktpu_memory_bytes{" in prom_text


@pytest.mark.slow
def test_bench_smoke_faults_adds_chaos_line(capsys, tmp_path, monkeypatch):
    """--faults inserts a fault-enabled composed smoke line (the chaos
    engine's dispatch/throughput tracker) before the final sweep line.
    --trace rides along so the traced composed lines are jit-cache hits
    from the previous test (same programs); the chaos line itself is
    untraced either way. Slow lane (tier-1 wall-clock budget): the
    ten-line test covers every line contract including the sweep; this
    variant only adds the chaos line's presence on top of chaos-path
    coverage tier-1 already carries (test_superspan / test_streaming /
    test_soak fault engines, test_chaos)."""
    monkeypatch.setenv("KTPU_TRACE_PATH", str(tmp_path / "ktpu_trace"))
    monkeypatch.setenv("KTPU_METRICS_PATH", str(tmp_path / "ktpu_metrics"))
    monkeypatch.setenv("KTPU_SWEEP_PATH", str(tmp_path / "ktpu_sweep"))
    records = _smoke_records(capsys, ["--smoke", "--faults", "--trace"])
    assert len(records) == 11, records
    assert "tuned statics" in records[7]["metric"]
    assert "chaos" in records[8]["metric"]
    assert records[8]["value"] > 0
    assert records[8]["spans"]["n"] >= 5
    assert "telemetry" not in records[8]
    assert "open-loop lane-async fleet" in records[9]["metric"]
    assert "scenario-vector fleet" in records[10]["metric"]


@pytest.mark.slow
def test_bench_smoke_host_chaos_adds_availability_line(
    capsys, tmp_path, monkeypatch
):
    """--host-chaos inserts the fault-tolerant-serving line (DESIGN §15)
    AFTER the open-loop line (shared warm jit caches) and BEFORE the
    sweep (which must stay LAST: its baseline clears the jit caches).
    run_host_chaos's in-bench gates already ran — quiet-layer A/B
    bit-identity + dispatch_stats equality, stream-once typed-error
    delivery, availability >= 90% under the pinned-seed injector, every
    lane faulted, quarantine fired AND re-admitted, zero post-warm-up
    recompiles; pin the disclosure + the JSON artifact CI uploads. Slow
    lane: the ten-line test covers the default contract (no flag = no
    line); fault-path unit coverage lives in test_fleet_faults.py."""
    monkeypatch.setenv("KTPU_SWEEP_PATH", str(tmp_path / "ktpu_sweep"))
    records = _smoke_records(capsys, ["--smoke", "--host-chaos"])
    assert len(records) == 11, records
    assert "tuned statics" in records[7]["metric"]
    assert "open-loop lane-async fleet" in records[8]["metric"]
    assert "host-chaos" in records[9]["metric"]
    assert "scenario-vector fleet" in records[10]["metric"]
    hc = records[9]["host_chaos"]
    assert hc["availability"] >= 0.90
    assert hc["lanes"] == 4 and hc["victim_lanes"] == [0, 1, 2, 3]
    assert hc["quarantine_events"] >= 1 and hc["readmissions"] >= 1
    assert sum(hc["failed_by_kind"].values()) == hc["failed"]
    assert hc["stream_once_audited"] == hc["submitted"]
    assert hc["quiet_ab_identity_checked"] > 0
    assert hc["quiet_dispatch_stats_equal"] is True
    assert hc["recompiles_after_warmup"] == 0
    assert hc["recompile_sentinel"]["post_warmup_events"] == 0
    hc_doc = json.loads(
        (tmp_path / "ktpu_sweep_hostchaos.json").read_text()
    )
    assert hc_doc == hc
