"""Hardened checkpointing (kubernetriks_tpu/checkpoint.py): atomic saves
(temp dir + rename — no torn checkpoints), clear ValueError on
structure/shape/dtype mismatch instead of an orbax stack trace, and a
mid-run save -> restore -> continue roundtrip on the composed batched path
(HPA pod group + cluster autoscaler + fault injection) that lands
bit-identical to the uninterrupted run."""

import os

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states
from kubernetriks_tpu.checkpoint import ckpt_restore, ckpt_save
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest
from kubernetriks_tpu.core.types import Node, Pod

GiB = 1024**3

COMPOSED_CONFIG_YAML = """
sim_name: ckpt_roundtrip
seed: 3
scheduling_cycle_interval: 10.0
as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.010
sched_to_as_network_delay: 0.020
as_to_node_network_delay: 0.150
as_to_ca_network_delay: 0.30
as_to_hpa_network_delay: 0.40
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 4
  node_groups:
  - node_template:
      metadata: {name: ca_node}
      status: {capacity: {cpu: 16000, ram: 34359738368}}
fault_injection:
  enabled: true
  node:
    mttf: 700.0
    mttr: 80.0
  pod:
    fail_prob: 0.15
    restart_limit: 2
"""

GROUP_TRACE_YAML = """
events:
- timestamp: 40.0
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 6
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 2000, ram: 4294967296}
              limits: {cpu: 2000, ram: 4294967296}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 200.0
                total_load: 3.0
              - duration: 300.0
                total_load: 12.0
              - duration: 400.0
                total_load: 2.0
"""


def _traces(seed=11, n_pods=60):
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    rng = np.random.default_rng(seed)
    cluster = [
        (0.0, CreateNodeRequest(node=Node.new(f"node_{i}", 16000, 32 * GiB)))
        for i in range(4)
    ]
    workload = []
    for i in range(n_pods):
        ts = float(np.round(rng.uniform(1.0, 600.0), 3))
        cpu = int(rng.integers(1, 9)) * 1000
        duration = float(np.round(rng.uniform(20.0, 200.0), 3))
        workload.append(
            (
                ts,
                CreatePodRequest(
                    pod=Pod.new(f"pod_{i:03d}", cpu, cpu * 1024 * 1024, duration)
                ),
            )
        )
    group = GenericWorkloadTrace.from_yaml(
        GROUP_TRACE_YAML
    ).convert_to_simulator_events()
    workload = sorted(workload + group, key=lambda e: e[0])
    return cluster, workload


def _build(**kwargs):
    config = SimulationConfig.from_yaml(COMPOSED_CONFIG_YAML)
    cluster, workload = _traces()
    return build_batched_from_traces(
        config,
        cluster,
        workload,
        n_clusters=2,
        # Crash churn keeps re-provisioning CA nodes and scaled-up slots are
        # never reclaimed — widen the reserve so the chaos scenario stays
        # inside the documented CA slot bound.
        ca_slot_multiplier=8,
        **kwargs,
    )


END = 1600.0
MID = 600.0


def test_midrun_save_restore_continue_roundtrip(tmp_path):
    """Composed batched path: run to MID, checkpoint, restore into a fresh
    engine, continue both to END — bit-identical final states."""
    path = str(tmp_path / "ckpt")

    straight = _build()
    straight.step_until_time(END)

    interrupted = _build()
    interrupted.step_until_time(MID)
    interrupted.save_checkpoint(path)
    # Saves are atomic: no temp/aside dir left behind, manifest present.
    assert set(os.listdir(tmp_path)) == {"ckpt", "ckpt.structure.json"}

    resumed = _build()
    resumed.load_checkpoint(path)
    resumed.step_until_time(END)

    bad = compare_states(straight.state, resumed.state)
    assert bad == [], bad
    c = resumed.metrics_summary()["counters"]
    assert c["pods_succeeded"] > 0
    assert c["node_crashes"] > 0  # the composed run exercises the chaos path
    assert c["total_scaled_up_pods"] > 0  # ...and the HPA


def test_save_overwrites_previous_checkpoint(tmp_path):
    path = str(tmp_path / "ckpt")
    sim = _build()
    sim.step_until_time(200.0)
    sim.save_checkpoint(path)
    sim.step_until_time(400.0)
    sim.save_checkpoint(path)  # overwrite must be atomic too
    fresh = _build()
    fresh.load_checkpoint(path)
    assert int(np.asarray(fresh.state.time).max()) == int(
        np.asarray(sim.state.time).max()
    )


def test_restore_structure_mismatch_raises_value_error(tmp_path):
    """A checkpoint restored against a different state layout fails with a
    ValueError naming the mismatch, not an orbax stack trace."""
    path = str(tmp_path / "ckpt")
    sim = _build()
    sim.step_until_time(200.0)
    sim.save_checkpoint(path)

    import jax.numpy as jnp

    payload = sim._ckpt_payload()
    # Shape mismatch: a template whose pod axis is wider than the save's.
    bad_pods = sim.state.pods._replace(
        phase=jnp.zeros(
            (sim.n_clusters, sim.n_pods + 8), jnp.int32
        )
    )
    bad_payload = {
        "state": sim.state._replace(pods=bad_pods),
        "next_window_idx": payload["next_window_idx"],
    }
    with pytest.raises(ValueError, match="phase"):
        ckpt_restore(path, bad_payload)

    with pytest.raises(ValueError, match="structure"):
        ckpt_restore(path, {"something": jnp.zeros((3,), jnp.int32)})


def test_restore_profile_mismatch_raises_value_error(tmp_path):
    """The compiled scheduler profile is an engine-build static: restoring
    a checkpoint into an engine compiled with a DIFFERENT profile must
    raise the actionable guard, not silently continue the run under
    different scheduling semantics (both directions: profiled save into a
    default engine, and a default save into a profiled engine — the
    latter exercises the no-meta-means-default rule)."""
    path = str(tmp_path / "ckpt")
    profiled = _build(scheduler_profile="best_fit")
    profiled.step_until_time(200.0)
    profiled.save_checkpoint(path)
    with pytest.raises(ValueError, match="scheduler-profile mismatch"):
        _build().load_checkpoint(path)

    path2 = str(tmp_path / "ckpt2")
    plain = _build()
    plain.step_until_time(200.0)
    plain.save_checkpoint(path2)
    with pytest.raises(ValueError, match="scheduler-profile mismatch"):
        _build(scheduler_profile="best_fit").load_checkpoint(path2)
    # Matching profile restores cleanly.
    ok = _build(scheduler_profile="best_fit")
    ok.load_checkpoint(path)
    assert ok.profile.name == "best_fit"


def test_restore_missing_path_raises_value_error(tmp_path):
    sim = _build()
    with pytest.raises(ValueError, match="no checkpoint"):
        ckpt_restore(str(tmp_path / "nope"), sim._ckpt_payload())


def test_restore_recovers_aside_after_crashed_swap(tmp_path):
    """A save that crashed between moving the old checkpoint aside and
    swinging the new one into place leaves only the .old aside; restore
    finds it (the aside's manifest is the one at the main manifest path)."""
    import jax.numpy as jnp

    payload = {"a": jnp.arange(6, dtype=jnp.int32).reshape(2, 3)}
    path = str(tmp_path / "ckpt")
    ckpt_save(path, payload)
    os.rename(path, path + ".old")  # crash point: aside moved, swap pending
    out = ckpt_restore(path, payload)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(payload["a"]))


def test_ckpt_save_restore_plain_pytree(tmp_path):
    """The helpers stay usable on arbitrary pytrees (RL training uses them
    directly)."""
    import jax.numpy as jnp

    payload = {
        "a": jnp.arange(6, dtype=jnp.int32).reshape(2, 3),
        "b": {"c": jnp.ones((4,), jnp.float32)},
    }
    path = str(tmp_path / "plain")
    ckpt_save(path, payload)
    out = ckpt_restore(path, payload)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(payload["a"]))
    np.testing.assert_array_equal(
        np.asarray(out["b"]["c"]), np.asarray(payload["b"]["c"])
    )
