"""Randomized cross-path equivalence: scalar oracle vs batched JAX path on
generated traces (VERDICT round-1 item 3; scalar-path fidelity reference:
src/core/scheduler/scheduler.rs, kube_scheduler.rs; batched formulation:
kubernetriks_tpu/batched/).

Each seed generates a random cluster trace (creates + removals) and workload
trace (creates + removals) with names zero-padded so the scalar path's
sorted-name tie-breaks coincide with the batched path's slot order. Both
paths run to quiescence; per-pod terminal state, assigned node, start times,
terminal counters, and timing estimators must agree (integers exactly,
floats to pair-time tolerance).
"""

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import (
    PHASE_REMOVED,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
)
from kubernetriks_tpu.core.types import PodConditionType
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace

MiB = 1024 * 1024
GiB = 1024**3


def generate_traces(seed: int, n_nodes: int = 24, n_pods: int = 220):
    """Random traces exercising node removal (-> reschedule), pod removal
    (before/while/after running), contention, and unschedulable parking.
    An anchor node guarantees every surviving pod eventually schedules."""
    rng = np.random.default_rng(seed)
    cluster_events = [
        {
            "timestamp": 0.0,
            "event_type": {
                "__tag__": "CreateNode",
                "node": {
                    "metadata": {"name": "node_anchor"},  # sorts after node_0xx? no: 'a' > digits
                    "status": {"capacity": {"cpu": 100000, "ram": 1024 * GiB}},
                },
            },
        }
    ]
    for i in range(n_nodes):
        ts = float(np.round(rng.uniform(0.0, 500.0), 3))
        cpu = int(rng.integers(2, 17)) * 1000
        ram = int(rng.integers(4, 65)) * GiB
        cluster_events.append(
            {
                "timestamp": ts,
                "event_type": {
                    "__tag__": "CreateNode",
                    "node": {
                        "metadata": {"name": f"node_{i:03d}"},
                        "status": {"capacity": {"cpu": cpu, "ram": ram}},
                    },
                },
            }
        )
        if rng.random() < 0.3:
            cluster_events.append(
                {
                    "timestamp": float(np.round(ts + rng.uniform(50.0, 3000.0), 3)),
                    "event_type": {
                        "__tag__": "RemoveNode",
                        "node_name": f"node_{i:03d}",
                    },
                }
            )

    workload_events = []
    for i in range(n_pods):
        ts = float(np.round(rng.uniform(1.0, 1500.0), 3))
        cpu = int(rng.integers(1, 41)) * 100
        ram = int(rng.integers(64, 8193)) * MiB  # MiB-aligned: quantization exact
        duration = float(np.round(rng.uniform(10.0, 400.0), 3))
        workload_events.append(
            {
                "timestamp": ts,
                "event_type": {
                    "__tag__": "CreatePod",
                    "pod": {
                        "metadata": {"name": f"pod_{i:04d}"},
                        "spec": {
                            "resources": {
                                "requests": {"cpu": cpu, "ram": ram},
                                "limits": {"cpu": cpu, "ram": ram},
                            },
                            "running_duration": duration,
                        },
                    },
                },
            }
        )
        if rng.random() < 0.2:
            # Removal may land before scheduling, while running, or after
            # finish — all three scalar outcomes (node_component.rs:298-332).
            workload_events.append(
                {
                    "timestamp": float(np.round(ts + rng.uniform(0.0, 500.0), 3)),
                    "event_type": {"__tag__": "RemovePod", "pod_name": f"pod_{i:04d}"},
                }
            )
    return (
        GenericClusterTrace(events=cluster_events),
        GenericWorkloadTrace(events=workload_events),
    )


END_TIME = 12000.0  # past last event + max duration + stale flush + slack


# Per-profile sweeps (compiled scheduler-profile pipeline,
# batched/pipeline.py): the SAME generated traces run under non-default
# profiles on both paths — the scalar KubeScheduler interprets the profile
# through the plugin registry, the batched engine compiles it into the
# scan path — and must still agree pod-for-pod. Seeds are pinned to runs
# whose pod finishes keep clear of the freed-resource visibility gap
# (docs/PARITY.md "Freed-resource visibility at cycle boundaries"):
# packing profiles actively chase just-freed nodes, so a finish landing
# within the notification chain (0.21 s) of a cycle boundary makes the
# batched cycle see space the scalar scheduler's cache doesn't yet —
# a documented model residue, not a profile-lowering defect.
@pytest.mark.parametrize(
    "seed,conditional_move,profile",
    [
        (101, False, None),
        (202, False, None),
        (303, False, None),
        (404, True, None),
        (505, True, None),
        (101, False, "best_fit"),
        (505, False, "best_fit"),
        (101, False, "balanced_packing"),
    ],
)
def test_random_trace_cross_path_equivalence(seed, conditional_move, profile):
    import dataclasses

    suffix = (
        "enable_unscheduled_pods_conditional_move: true" if conditional_move else ""
    )
    config = default_test_simulation_config(suffix)
    if profile is not None:
        config = dataclasses.replace(config, scheduler_profile=profile)

    # convert_to_simulator_events has move-out semantics (it consumes the
    # trace, like the reference's Vec move-out) — build each path from a
    # fresh generation.
    cluster_trace, workload_trace = generate_traces(seed)
    scalar = KubernetriksSimulation(config)
    scalar.initialize(cluster_trace, workload_trace)
    scalar.step_until_time(END_TIME)

    cluster_trace, workload_trace = generate_traces(seed)
    batched = build_batched_from_traces(
        config,
        cluster_trace.convert_to_simulator_events(),
        workload_trace.convert_to_simulator_events(),
        n_clusters=1,
    )
    assert batched.profile.name == (profile or "default")
    batched.step_until_time(END_TIME)

    # --- terminal counters: exact --------------------------------------------
    sm = scalar.metrics_collector.accumulated_metrics
    bm = batched.metrics_summary()
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded, seed
    assert bm["counters"]["pods_removed"] == sm.pods_removed, seed
    assert bm["counters"]["terminated_pods"] == sm.internal.terminated_pods, seed
    assert sm.pods_succeeded > 50  # the scenario is non-trivial

    # --- per-pod terminal state ---------------------------------------------
    view = batched.pod_view(0)
    succeeded = scalar.persistent_storage.succeeded_pods
    cache = scalar.persistent_storage.unscheduled_pods_cache
    for name, b in view.items():
        if b["phase"] == PHASE_SUCCEEDED:
            pod = succeeded.get(name)
            assert pod is not None, f"{name} (seed {seed}): batched succeeded, scalar did not"
            assert b["node"] == pod.status.assigned_node, (name, seed)
            scalar_start = pod.get_condition(
                PodConditionType.POD_RUNNING
            ).last_transition_time
            # Pair-time resolution: interval * 2^-24 ~ 1e-6 s at interval=10.
            assert b["start_time"] == pytest.approx(scalar_start, abs=5e-6), (
                name,
                seed,
            )
        elif b["phase"] == PHASE_UNSCHEDULABLE:
            assert name in cache, (name, seed)
        elif b["phase"] == PHASE_REMOVED:
            assert name not in succeeded, (name, seed)

    # --- timing estimators ---------------------------------------------------
    for key, scalar_est in [
        ("pod_duration", sm.pod_duration_stats),
        ("pod_queue_time", sm.pod_queue_time_stats),
        ("pod_schedule_time", sm.pod_scheduling_algorithm_latency_stats),
    ]:
        best = bm["timings"][key]
        assert best["min"] == pytest.approx(scalar_est.min(), rel=1e-4, abs=1e-3), (key, seed)
        assert best["max"] == pytest.approx(scalar_est.max(), rel=1e-4, abs=1e-3), (key, seed)
        assert best["mean"] == pytest.approx(scalar_est.mean(), rel=1e-4, abs=1e-3), (key, seed)


def test_batched_path_determinism():
    """The determinism north star applied to the batched path: two
    identically-built runs over the same generated traces produce
    bit-identical final state pytrees (reference analog:
    tests/test_determinism.rs applied per backend)."""
    import jax

    config = default_test_simulation_config()

    def run():
        cluster_trace, workload_trace = generate_traces(909)
        sim = build_batched_from_traces(
            config,
            cluster_trace.convert_to_simulator_events(),
            workload_trace.convert_to_simulator_events(),
            n_clusters=4,
        )
        sim.step_until_time(END_TIME)
        return sim

    a, b = run(), run()
    assert a.metrics_summary()["counters"]["pods_succeeded"] > 0
    flat_a, _ = jax.tree_util.tree_flatten_with_path(a.state)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(b.state)
    for (path, x), (_, y) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jax.tree_util.keystr(path)
        )
