"""Flight recorder (kubernetriks_tpu/telemetry) — PR 8 mechanics gates.

Two-tier coverage, split to keep tier-1 inside its wall-clock budget:

- The COMPOSED-SCALE gates (HPA + CA + superspan + chaos: telemetry-on
  bit-identical across executors, composed ring columns live, steady-state
  sync budget) ride the existing engines of
  test_superspan.py::test_superspan_composed_bit_identical_under_faults —
  arming the flight recorder there costs zero extra compiles.
- THIS module pins the recorder's mechanics on cheap engines (small
  programs, fast compiles — full-resident for the pair, one sliding
  superspan for the staging pipeline): strict dispatch-stats equality
  telemetry-on vs -off (the no-new-syncs gate),
  ring wrap + pressure-drain losslessness, Chrome trace-event schema
  (spans, flow pairs, counter tracks), checkpoint roundtrip of the ring,
  the <3% overhead gate, the ladder-fallback observable, the tracer
  per-span microbenchmark, and the shared JSON/table render path.
"""

import json
import time

import numpy as np
import pytest

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.batched.state import compare_states, strip_telemetry
from kubernetriks_tpu.telemetry.ring import RING_COLUMNS
from kubernetriks_tpu.telemetry.tracer import PH_WINDOW_CHUNK, SpanTracer
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generator import (
    PoissonWorkloadTrace,
    UniformClusterTrace,
)

from test_window_donation_dispatch import _build_dense_sliding

ENDS = (150.0, 300.0, 450.0)


def _build_plain(**kwargs):
    """Cheapest real engine: full-resident, no autoscalers, one small
    run_windows program — the module's workhorse (tier-1 wall-clock:
    the composed/superspan-scale telemetry gates ride test_superspan's
    existing engines instead of recompiling composed programs here)."""
    config = default_test_simulation_config()
    cluster = UniformClusterTrace(8, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=1.0,
        horizon=400.0,
        seed=5,
        cpu=4000,
        ram=4 * 1024**3,
        duration_range=(20.0, 40.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=2,
        max_pods_per_cycle=16,
        fast_forward=False,
        **kwargs,
    )


@pytest.fixture(scope="module")
def cheap_pair():
    """Telemetry-ON vs telemetry-OFF plain runs. telemetry_ring=16 is
    deliberately SMALLER than the executed window count, so the
    pressure-based drain at step_until_time exits must fire mid-run for
    the series to stay lossless."""
    on = _build_plain(telemetry=True, telemetry_ring=16)
    off = _build_plain()
    for end in ENDS:
        on.step_until_time(end)
        off.step_until_time(end)
    return on, off


def test_telemetry_on_is_bit_identical(cheap_pair):
    on, off = cheap_pair
    assert on.dispatch_stats["window_chunks"] > 0
    assert compare_states(strip_telemetry(on.state), off.state) == []
    assert on.metrics_summary() == off.metrics_summary()
    assert on.next_window_idx == off.next_window_idx


def test_telemetry_adds_no_new_syncs(cheap_pair):
    """The dispatch-count regression gate: telemetry must not add a
    single dispatch or blocking readback to the steady-state loop —
    slide_syncs is the budget the lint sync-ok waivers document."""
    on, off = cheap_pair
    assert on.dispatch_stats == off.dispatch_stats


def test_ring_series_is_lossless_and_matches_metrics(cheap_pair):
    """Every executed window has exactly one ring record (the ring
    wrapped several times — capacity 16 < executed windows — so this also
    proves the pressure drain fired at existing boundaries), and the
    per-window decision deltas sum to the run's total decision counter."""
    on, _ = cheap_pair
    executed = on.next_window_idx
    assert executed > on._telemetry_ring_size  # the ring really wrapped
    wins, data = on.telemetry_window_series()
    np.testing.assert_array_equal(wins, np.arange(executed, dtype=np.int32))
    assert on._ring_windows_recorded == executed
    total = on.metrics_summary()["counters"]["scheduling_decisions"]
    assert total > 0
    assert int(data[:, :, RING_COLUMNS.index("decisions")].sum()) == total
    assert int(data[:, :, RING_COLUMNS.index("alive_nodes")].max()) > 0


def test_telemetry_report_shape(cheap_pair):
    on, _ = cheap_pair
    rep = on.telemetry_report()
    assert rep["enabled"]
    assert (
        rep["spans"]["window_chunk"]["count"]
        == on.dispatch_stats["window_chunks"]
    )
    # Full-resident run: zero slides, zero syncs — budget trivially met
    # (the composed-scale budget gate lives in test_superspan.py).
    assert rep["sync_budget"]["observed_slide_syncs"] == (
        rep["sync_budget"]["steady_state_expected"]
    ) == 0
    assert rep["dispatch_stats"]["ladder_fallbacks"] == 0
    assert rep["ring"]["windows_kept"] == on.next_window_idx


def validate_chrome_trace(path, expect_flows):
    """Chrome trace-event JSON schema check, shared with the superspan
    fault test (which validates a trace WITH async-readback flow pairs):
    X spans with nonnegative durations, process metadata, the device
    ring's sim-time counter track, s/f flows in matched id pairs, and
    every span name drawn from the known phase taxonomy."""
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phases = {"M": 0, "X": 0, "s": 0, "f": 0, "C": 0}
    flow_ids = {"s": set(), "f": set()}
    for ev in events:
        assert {"ph", "name", "pid"} <= set(ev)
        phases[ev["ph"]] = phases.get(ev["ph"], 0) + 1
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
        if ev["ph"] in ("s", "f"):
            flow_ids[ev["ph"]].add(ev["id"])
        if ev["ph"] == "C":
            assert ev["args"], "counter event without a value"
    assert phases["X"] > 0, "no host spans"
    assert phases["C"] > 0, "no device-ring counter track"
    assert flow_ids["s"] == flow_ids["f"], (
        "async readback flows must come in matched start/finish pairs"
    )
    if expect_flows:
        assert phases["s"] > 0, "no async-readback flow events"
    # Every span name is a known phase (schema, not free text) — except
    # the lane-swimlane process (pid LANE_PID, PR 17), whose spans are
    # named by the occupying query id ("q<qid>").
    import re

    from kubernetriks_tpu.telemetry import PHASE_NAMES
    from kubernetriks_tpu.telemetry.tracer import LANE_PID

    for ev in events:
        if ev["ph"] == "X":
            if ev["pid"] == LANE_PID:
                assert re.fullmatch(r"q\d+", ev["name"]), (
                    f"lane swimlane span named {ev['name']!r}, expected "
                    "q<qid>"
                )
            else:
                assert ev["name"] in PHASE_NAMES


def test_chrome_trace_schema(cheap_pair, tmp_path):
    """The emitted trace validates (a full-resident run has no async
    readbacks, hence no flow pairs — the superspan fault test validates
    the flow-carrying trace)."""
    on, _ = cheap_pair
    path = on.write_chrome_trace(str(tmp_path / "trace.json"))
    validate_chrome_trace(path, expect_flows=False)


def test_checkpoint_roundtrip_with_telemetry(cheap_pair, tmp_path):
    """The ring is ordinary state: a save→restore roundtrip on a
    telemetry-on engine reproduces it (and the drained series)."""
    pytest.importorskip("orbax.checkpoint")
    on, off = cheap_pair
    path = str(tmp_path / "ckpt")
    on.save_checkpoint(path)
    fresh = _build_plain(telemetry=True, telemetry_ring=16)
    fresh.load_checkpoint(path)
    assert compare_states(fresh.state, on.state) == []
    wins_a, data_a = on.telemetry_window_series()
    wins_b, data_b = fresh.telemetry_window_series()
    # The restored engine re-drains only what the restored ring still
    # holds (capacity 16): the tail of the original series, bit-equal.
    assert len(wins_b) > 0 and set(wins_b) <= set(wins_a)
    np.testing.assert_array_equal(data_b, data_a[-len(wins_b):])
    # Mismatch guard: restoring onto a telemetry-off engine (different
    # state pytree) raises the actionable message, not an opaque orbax
    # structure error — and before touching the engine's state.
    plain = _build_plain()
    with pytest.raises(ValueError, match="telemetry ring mismatch"):
        plain.load_checkpoint(path)
    # The reverse mismatch too: a plain save writes NO meta file at all
    # (full-resident, no ring), and restoring it into an armed engine
    # must raise the same actionable message, not an orbax structure
    # error — the guard runs even with the meta absent.
    plain_path = str(tmp_path / "ckpt_plain")
    off.save_checkpoint(plain_path)
    import os

    assert not os.path.exists(plain_path + ".meta.json")
    with pytest.raises(ValueError, match="telemetry ring mismatch"):
        fresh.load_checkpoint(plain_path)


def test_ring_drain_handles_uneven_spans():
    """Wrap-loss regression: a short call that leaves undrained rows
    under the exit-drain threshold, followed by a call long enough to
    wrap past them, must still produce a lossless series (the entry-side
    guard drains before dispatching the wrapping span)."""
    sim = _build_plain(telemetry=True, telemetry_ring=16)
    sim.step_until_time(60.0)  # 7 windows: below the exit-drain threshold
    sim.step_until_time(180.0)  # 12 more: would overwrite rows 0-2 unguarded
    wins, _ = sim.telemetry_window_series()
    np.testing.assert_array_equal(
        wins, np.arange(sim.next_window_idx, dtype=np.int32)
    )
    assert sim._ring_windows_recorded == sim.next_window_idx


def test_drain_telemetry_rows_survive_donated_dispatches():
    """Explicit mid-run drain (engine.drain_telemetry) vs the
    donated-dispatch aliasing hazard: ring.snapshot forces OWNED numpy
    copies, so rows drained now must stay bit-identical after later
    DONATED dispatches consume (and mutate in place) the device ring
    buffer the fetch may have aliased on CPU."""
    sim = _build_dense_sliding(
        telemetry=True, telemetry_ring=16, donate=True, fuse_slide=True
    )
    sim.step_until_time(120.0)
    rec = sim.drain_telemetry()
    assert rec and rec["window"] == sim.next_window_idx - 1
    assert "occupancy" in rec and "resources" in rec
    wins0, data0 = sim.telemetry_window_series()
    snap = data0.copy()
    sim.step_until_time(400.0)  # donated dispatches consume old buffers
    wins1, data1 = sim.telemetry_window_series()
    np.testing.assert_array_equal(wins1[: len(wins0)], wins0)
    np.testing.assert_array_equal(data1[: len(wins0)], snap)
    # And with telemetry off it degrades to a cheap no-op, not an error.
    off = _build_plain()
    assert off.drain_telemetry() == {}


def test_single_long_call_stays_lossless_on_sliding_engine():
    """The PR 8 known edge, fixed: ONE step_until_time call spanning far
    more windows than the ring stays lossless on engines whose
    steady-state loop has sync points (slides / superspan readbacks) —
    the pressure drain now rides those existing blocks mid-call, so the
    windows_recorded > windows_kept disclosure is reserved for a single
    DISPATCH outrunning the ring, not a single call."""
    sim = _build_dense_sliding(telemetry=True, telemetry_ring=16)
    sim.step_until_time(450.0)  # ~45 windows >> ring capacity, ONE call
    assert sim.next_window_idx > sim._telemetry_ring_size
    assert sim.dispatch_stats["slide_syncs"] > 0  # drains had blocks to ride
    wins, _ = sim.telemetry_window_series()
    np.testing.assert_array_equal(
        wins, np.arange(sim.next_window_idx, dtype=np.int32)
    )
    assert sim._ring_windows_recorded == sim.next_window_idx


def test_series_cap_bounds_host_memory_and_discloses():
    """The host-side series accumulator is BOUNDED (the endurance-run
    guard): past telemetry_series_windows distinct windows the oldest
    rows are pruned, newest kept, and the loss is disclosed in the
    report — the O(T) growth the capacity observatory would otherwise
    reintroduce through its own lossless drains."""
    sim = _build_plain(telemetry=True, telemetry_ring=16)
    sim.telemetry_series_windows = 10
    for end in ENDS:
        sim.step_until_time(end)
    wins, _ = sim.telemetry_window_series()
    assert len(wins) <= 10
    assert wins[-1] == sim.next_window_idx - 1  # newest windows survive
    rep = sim.telemetry_report()
    assert rep["ring"]["series_dropped_windows"] > 0
    assert rep["ring"]["windows_kept"] <= 10


def test_readout_does_not_emit_phantom_export_records():
    """telemetry_report()/telemetry_window_series() force a drain, but a
    drain that re-observes only known rows (fresh_windows == 0) must not
    reach the exporters or re-judge the watchdog — readout stays
    side-effect-free on the JSONL stream."""
    sim = _build_plain(telemetry=True, telemetry_ring=16)
    records = []

    class _Recorder:
        def emit(self, record):
            records.append(record)

    sim.attach_metrics_exporter(_Recorder())
    sim.step_until_time(150.0)
    sim.telemetry_window_series()  # forced drain picking up any residue
    n = len(records)
    assert n > 0
    assert all(r["fresh_windows"] > 0 for r in records)
    for _ in range(3):
        sim.telemetry_report()
    assert len(records) == n, "readout emitted phantom export records"


def test_staged_superspan_records_prefetch_spans(monkeypatch):
    """Over-budget (bounded RefillStage) superspan runs surface the
    staging pipeline in the trace: stage_assemble/stage_put spans for
    every install, stage_prefetch spans for the double-buffered
    successor, and the hit/miss counters feeding
    stage_prefetch_hit_rate — the overlap the flight recorder exists to
    make visible."""
    import kubernetriks_tpu.batched.engine as engine_mod

    monkeypatch.setattr(engine_mod, "_DEVICE_SLIDE_BUDGET_BYTES", 0)
    sim = _build_dense_sliding(
        telemetry=True, telemetry_ring=16,
        superspan=True, superspan_k=4, superspan_chunk=4,
    )
    assert sim._device_slide is None, "budget monkeypatch did not take"
    for end in ENDS:
        sim.step_until_time(end)
    rep = sim.telemetry_report()
    assert rep["spans"]["stage_assemble"]["count"] >= 1
    assert rep["spans"]["stage_put"]["count"] >= 1
    assert rep["spans"]["stage_prefetch"]["count"] >= 1
    hits = rep["counters"].get("stage_prefetch_hit", 0)
    misses = rep["counters"].get("stage_prefetch_miss", 0)
    assert hits + misses >= 1  # at least the initial install counted
    assert rep.get("stage_prefetch_hit_rate", 0) == hits / (hits + misses)


def test_ladder_fallback_counter():
    """A superspan-selected engine forced onto the ladder (log_throughput
    wants per-chunk timings) counts the fallback — observable outside
    bench.py --smoke. One short span keeps the compile bill at two small
    ladder shapes."""
    sim = _build_dense_sliding(superspan=True)
    sim.log_throughput = True
    sim.step_until_time(80.0)
    assert sim.dispatch_stats["superspans"] == 0
    assert sim.dispatch_stats["ladder_fallbacks"] > 0
    assert sim.dispatch_stats["window_chunks"] > 0


def test_tracer_span_cost_microbench():
    """Design bound: begin/end is well under a microsecond each on real
    hardware; the CI gate allows generous container noise but still
    catches an accidental allocation or string format on the record
    path."""
    tr = SpanTracer(capacity=1 << 12)
    n = 20_000
    t_start = time.perf_counter_ns()
    for _ in range(n):
        t0 = tr.begin()
        tr.end(PH_WINDOW_CHUNK, t0)
    per_span_us = (time.perf_counter_ns() - t_start) / n / 1e3
    assert per_span_us < 10.0, f"{per_span_us:.2f} µs per span"
    rep = tr.report()
    assert rep["spans"]["window_chunk"]["count"] == n
    assert rep["span_events"]["kept"] == 1 << 12  # ring wrapped, report exact


def test_tracer_lane_swimlanes_and_query_phases(tmp_path):
    """Query-observatory tracer surface (PR 17): the queue-wait/service
    phases exist in the taxonomy, lane_event renders one pid-LANE_PID
    swimlane per lane with the occupying query id as the span name (plus
    process/thread metadata), the submit->drain flow pairs match, and
    report() discloses the lane-span ring's recorded/kept counts."""
    from kubernetriks_tpu.telemetry import PHASE_NAMES
    from kubernetriks_tpu.telemetry.tracer import (
        LANE_PID,
        PH_QUERY_QUEUE,
        PH_QUERY_SERVICE,
        NullTracer,
    )

    assert PHASE_NAMES[PH_QUERY_QUEUE] == "query_queue"
    assert PHASE_NAMES[PH_QUERY_SERVICE] == "query_service"
    tr = SpanTracer()
    t0 = tr.begin()
    fid = tr.flow_start(PH_QUERY_QUEUE)
    tr.end(PH_QUERY_QUEUE, t0, dur=1_000)
    tr.end(PH_QUERY_SERVICE, t0 + 1_000, dur=5_000)
    tr.lane_event(2, 7, t0 + 1_000, 5_000)
    tr.lane_event(0, 8, t0 + 1_000, 4_000)
    tr.flow_end(PH_QUERY_QUEUE, fid)
    doc = tr.chrome_trace()
    evs = doc["traceEvents"]
    lanes = [e for e in evs if e.get("pid") == LANE_PID and e["ph"] == "X"]
    assert {e["name"] for e in lanes} == {"q7", "q8"}
    assert {e["tid"] for e in lanes} == {0, 2}
    assert all(e["dur"] > 0 for e in lanes)
    meta = [
        e
        for e in evs
        if e.get("pid") == LANE_PID and e["ph"] == "M"
    ]
    names = {e["name"]: e["args"]["name"] for e in meta}
    assert names["process_name"] == "ktpu-lanes"
    thread_names = {
        e["args"]["name"] for e in meta if e["name"] == "thread_name"
    }
    assert thread_names == {"lane 0", "lane 2"}
    flows = [e for e in evs if e["ph"] in ("s", "f")]
    assert {e["id"] for e in flows if e["ph"] == "s"} == {
        e["id"] for e in flows if e["ph"] == "f"
    }
    rep = tr.report()
    assert rep["lane_spans"] == {"recorded": 2, "kept": 2}
    assert rep["spans"]["query_queue"]["count"] == 1
    assert rep["spans"]["query_service"]["count"] == 1
    # The written file passes the shared schema validator's span-name
    # rules (no C counter track here — a unit tracer has no device ring
    # extra_events — so only the span/flow/name assertions apply).
    path = tr.write_chrome_trace(str(tmp_path / "lanes.json"))
    with open(path) as fh:
        for ev in json.load(fh)["traceEvents"]:
            if ev["ph"] == "X" and ev["pid"] == LANE_PID:
                assert ev["name"].startswith("q")
    # NullTracer mirrors the whole surface as no-ops.
    nt = NullTracer()
    nt.lane_event(0, 0, 0, 0)
    assert nt.report()["lane_spans"] == {"recorded": 0, "kept": 0}


def test_overhead_gate_smoke_scenario():
    """<3% wall-clock overhead, telemetry-on vs -off, on the smoke-scale
    scenario: both engines advance through the SAME sim regions in
    alternating timed spans (each pair hits identical windows), and the
    medians must stay inside the gate (small absolute slack absorbs
    container scheduling noise on sub-second spans). Engine configs match
    the module fixture's exactly, so the programs are jit-cache hits —
    the test times execution, not compilation."""
    on = _build_plain(telemetry=True, telemetry_ring=16)
    off = _build_plain()
    # Warm both: any residual compile + first slides out of the timed
    # region.
    on.step_until_time(120.0)
    off.step_until_time(120.0)
    pairs = []
    end = 120.0
    for _ in range(3):
        end += 100.0
        t0 = time.perf_counter()
        off.step_until_time(end)
        t_off = time.perf_counter() - t0
        t0 = time.perf_counter()
        on.step_until_time(end)
        t_on = time.perf_counter() - t0
        pairs.append((t_on, t_off))
    t_on_med = float(np.median([a for a, _ in pairs]))
    t_off_med = float(np.median([b for _, b in pairs]))
    assert t_on_med <= t_off_med * 1.03 + 0.10, (
        f"telemetry overhead gate: on={t_on_med:.3f}s off={t_off_med:.3f}s "
        f"(pairs={pairs})"
    )


def test_shared_render_path_covers_scalar_batched_and_telemetry(cheap_pair):
    """metrics/render.py is the ONE JSON/table path: the scalar printer's
    table, the batched summary and the telemetry report all render
    through it, and scalar/batched reports share the {"counters",
    "timings"} schema with identical timing keys."""
    from kubernetriks_tpu.metrics.collector import MetricsCollector
    from kubernetriks_tpu.metrics.printer import metrics_as_dict
    from kubernetriks_tpu.metrics.render import (
        render_metrics,
        render_telemetry,
    )

    on, _ = cheap_pair
    batched = on.metrics_summary()
    scalar = metrics_as_dict(MetricsCollector())

    assert set(scalar) == set(batched) == {"counters", "timings"}
    assert set(scalar["timings"]) == set(batched["timings"])
    for d in (scalar, batched):
        table = render_metrics(d, "table")
        assert "Metric" in table and "Pod queue time" in table and "|" in table
        parsed = json.loads(render_metrics(d, "json"))
        assert parsed["counters"] == json.loads(
            json.dumps(d["counters"], default=float)
        )
    rep_table = render_telemetry(on.telemetry_report(), "table")
    assert "window_chunk" in rep_table and "Ring windows kept" in rep_table
    json.loads(render_telemetry(on.telemetry_report(), "json"))
