"""End-to-end Alibaba replay on the batched path (VERDICT round-1 item 4):
synthesized reference-format CSVs drive the native C++ feeder ->
compile_from_arrays -> BatchedSimulation, with and without the cluster
autoscaler, and the replay's terminal counters match the scalar oracle
(flagship workload reference:
src/trace/alibaba_cluster_trace_v2017/workload.rs:48-147,
experiments/alibaba_demo.ipynb).
"""

import numpy as np
import pytest

from kubernetriks_tpu.cli import build_batched_simulation
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import DEFAULT_TEST_CONFIG_YAML
from kubernetriks_tpu.trace.alibaba import (
    AlibabaClusterTraceV2017,
    AlibabaWorkloadTraceV2017,
)
from kubernetriks_tpu.trace.synthetic_alibaba import write_synthetic_trace_dir


def _alibaba_config(machines, tasks, instances, extra="") -> SimulationConfig:
    return SimulationConfig.from_yaml(
        DEFAULT_TEST_CONFIG_YAML
        + f"""
trace_config:
  alibaba_cluster_trace_v2017:
    machine_events_trace_path: {machines}
    batch_task_trace_path: {tasks}
    batch_instance_trace_path: {instances}
"""
        + extra
    )


def test_alibaba_replay_batched_matches_scalar(tmp_path):
    """Pure replay (no autoscalers): the batched path — built through the
    CLI's native-feeder + compile_from_arrays fast path — must reproduce the
    scalar oracle's terminal counters and duration stats."""
    machines, tasks, instances = write_synthetic_trace_dir(
        str(tmp_path), n_machines=100, n_tasks=700, horizon=4000.0, seed=7
    )
    config = _alibaba_config(machines, tasks, instances)

    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        AlibabaClusterTraceV2017.from_file(machines),
        AlibabaWorkloadTraceV2017.from_files(instances, tasks),
    )
    scalar.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())
    sm = scalar.metrics_collector.accumulated_metrics

    batched = build_batched_simulation(config, n_clusters=1)
    batched.run_to_completion()
    bm = batched.metrics_summary()

    assert sm.pods_succeeded > 500
    assert bm["counters"]["pods_succeeded"] == sm.pods_succeeded
    assert bm["counters"]["terminated_pods"] == sm.internal.terminated_pods
    assert bm["counters"]["processed_nodes"] == 100
    best = bm["timings"]["pod_duration"]
    assert best["min"] == pytest.approx(sm.pod_duration_stats.min(), rel=1e-5)
    assert best["max"] == pytest.approx(sm.pod_duration_stats.max(), rel=1e-5)
    assert best["mean"] == pytest.approx(sm.pod_duration_stats.mean(), rel=1e-4)



CA_EXTRA_YAML = """
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: {max_nodes}
  node_groups:
  - node_template:
      metadata:
        name: {node_name}
      status:
        capacity:
          cpu: 64000
          ram: 94489280512
"""


def _contended_ca_setup(
    tmp_path, n_machines, n_tasks, error_fraction, seed, max_nodes, node_name
):
    """Synthesize an undersized cluster (heavy 16-64 core tasks vs few
    machines, so the CA has unscheduled pods to act on) and its CA config."""
    from kubernetriks_tpu.trace.synthetic_alibaba import (
        write_batch_workload,
        write_machine_events,
    )

    machines = str(tmp_path / "machine_events.csv")
    tasks = str(tmp_path / "batch_task.csv")
    instances = str(tmp_path / "batch_instance.csv")
    write_machine_events(
        machines, n_machines=n_machines, error_fraction=error_fraction,
        horizon=3000.0, seed=seed,
    )
    write_batch_workload(
        tasks, instances, n_tasks=n_tasks, horizon=3000.0,
        cpu_santicores_range=(1600, 6400), heavy_fraction=0.0, seed=seed + 1,
    )
    config = _alibaba_config(
        machines, tasks, instances,
        extra=CA_EXTRA_YAML.format(max_nodes=max_nodes, node_name=node_name),
    )
    return config, machines, tasks, instances


def test_alibaba_replay_batched_with_cluster_autoscaler(tmp_path):
    """Replay on an undersized cluster with machine failures and the CA
    enabled: unscheduled pods trigger scale-ups, failed machines trigger
    reschedules, and every pod still terminates."""
    config, *_ = _contended_ca_setup(
        tmp_path, n_machines=6, n_tasks=150, error_fraction=0.3, seed=11,
        max_nodes=64, node_name="alibaba_ca_node",
    )

    # ca_slot_multiplier=4: this contended trace churns 156 node opens per
    # cluster (measured), past the default 2 x 64 reserve — the strict
    # reserve check (engine.check_autoscaler_bounds) would raise. The wider
    # reserve keeps the batched trajectory reference-faithful (the scalar
    # pool reclaims components and never starves).
    batched = build_batched_simulation(config, n_clusters=2, ca_slot_multiplier=4)
    batched.run_to_completion(max_time=1e6)
    bm = batched.metrics_summary()

    n_pods = batched.n_real_pods  # device n_pods is 128-align padded
    assert bm["counters"]["total_scaled_up_nodes"] > 0
    # Every instance terminates (succeeded; none are removed in this trace).
    assert bm["counters"]["pods_succeeded"] == 2 * n_pods
    assert bm["counters"]["terminated_pods"] == 2 * n_pods
    # Homogeneous batch: both clusters behaved identically.
    assert batched.cluster_metrics(0) == batched.cluster_metrics(1)
    # Machine failures actually happened (removals + CA churn).
    assert np.asarray(batched.state.nodes.alive).sum() < 2 * batched.n_nodes


def _assert_windowed_matches_full(config, machines, tasks, instances,
                                  pod_window, n_clusters=1, **build_kwargs):
    """Run the same compiled trace full-resident and through a sliding pod
    window; the window must actually slide and every terminal counter and
    timing stat must match."""
    from kubernetriks_tpu.batched.engine import BatchedSimulation
    from kubernetriks_tpu.batched.trace_compile import compile_from_arrays
    from kubernetriks_tpu.trace import feeder

    wa = feeder.load_workload_arrays(instances, tasks)
    ca = feeder.load_cluster_arrays(machines)
    compiled = compile_from_arrays(ca, wa, config)

    full = BatchedSimulation(
        config, [compiled] * n_clusters, max_pods_per_cycle=64, **build_kwargs
    )
    full.run_to_completion(max_time=1e6)
    fm = full.metrics_summary()

    windowed = BatchedSimulation(
        config, [compiled] * n_clusters, max_pods_per_cycle=64,
        pod_window=pod_window, **build_kwargs,
    )
    assert windowed.n_pods == pod_window < full.n_pods
    windowed.run_to_completion(max_time=1e6)
    wm = windowed.metrics_summary()
    assert windowed._pod_base > 0  # the window actually slid

    assert wm["counters"] == fm["counters"]
    for key in ("pod_duration", "pod_queue_time", "pod_schedule_time"):
        assert wm["timings"][key] == pytest.approx(fm["timings"][key], rel=1e-6)
    return fm


def test_sliding_pod_window_matches_full(tmp_path):
    """pod_window streams the trace through a small device window: terminal
    counters and duration stats must match the full-resident run exactly."""
    machines, tasks, instances = write_synthetic_trace_dir(
        str(tmp_path), n_machines=60, n_tasks=500, horizon=4000.0, seed=21
    )
    config = _alibaba_config(machines, tasks, instances)
    _assert_windowed_matches_full(
        config, machines, tasks, instances, pod_window=384, n_clusters=2
    )


@pytest.mark.slow
def test_sliding_pod_window_with_autoscaler_and_failures(tmp_path):
    """Sliding window composed with the CA and machine failures: parked pods
    (which block the shift until terminal), scale-ups into reserved slots,
    and reschedules off failed nodes must all match the full-resident run.
    Slow lane (tier-1 wall-clock budget): tier-1 keeps the sliding-window
    alibaba parity (test_sliding_pod_window_matches_full) and the
    window x CA x faults composition through test_superspan /
    test_streaming / test_soak's fault engines; this is the alibaba-trace
    variant of that composition."""
    config, machines, tasks, instances = _contended_ca_setup(
        tmp_path, n_machines=8, n_tasks=160, error_fraction=0.25, seed=31,
        max_nodes=32, node_name="win_ca_node",
    )
    # ca_slot_multiplier=4: churn past the default reserve (see the replay
    # test above) — widened so the strict reserve check stays quiet.
    fm = _assert_windowed_matches_full(
        config, machines, tasks, instances, pod_window=192,
        ca_slot_multiplier=4,
    )
    assert fm["counters"]["total_scaled_up_nodes"] > 0
