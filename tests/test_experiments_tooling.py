"""Offline tooling (experiments/): trace preprocessing filters and gauge
plotting — script ports of the reference notebooks
(experiments/{modify_traces,alibaba_demo}.ipynb)."""

import csv
import importlib.util
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "experiments", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_add_only_and_fit_only_filters(tmp_path):
    mt = _load("modify_traces")
    machines = tmp_path / "machine_events.csv"
    machines.write_text(
        "0,0,add,,64,0.7\n"
        "0,1,add,,16,0.1\n"
        "500,1,softerror,,,\n"
    )
    add_only = tmp_path / "add_only.csv"
    assert mt.filter_add_only(str(machines), str(add_only)) == 2

    tasks = tmp_path / "batch_task.csv"
    tasks.write_text(
        # fits the 64-core machine (32 cores, mem 0.5)
        "10,100,1,1,1,Terminated,3200,0.5\n"
        # too many cores (80 > 64)
        "10,100,1,2,1,Terminated,8000,0.1\n"
        # cpu fits the small machine but memory only fits the big one -> keep
        "10,100,1,3,1,Terminated,1000,0.6\n"
        # memory fits nothing
        "10,100,1,4,1,Terminated,1000,0.9\n"
        # missing resources -> dropped
        "10,100,1,5,1,Terminated,,\n"
    )
    fit_only = tmp_path / "fit_only.csv"
    assert mt.filter_fit_only(str(add_only), str(tasks), str(fit_only)) == 2
    kept = [row for row in csv.reader(open(fit_only))]
    assert [r[3] for r in kept] == ["1", "3"]

    stats = mt.analyze(str(fit_only))
    assert stats["tasks"] == 2 and stats["instances"] == 2

    # Instance-side analysis (trace_analysis.ipynb cells 3/5): row count vs
    # task instance sum, and the validity-filter count.
    instances_csv = tmp_path / "batch_instance.csv"
    instances_csv.write_text(
        "10,100,1,1,m1,Terminated,1,1,,,,\n"   # valid under both predicates
        "20,15,1,3,m1,Terminated,1,3,,,,\n"    # end < start -> invalid
        ",100,1,3,m1,Terminated,1,1,,,,\n"     # missing start -> invalid
        "0,50,1,1,m1,Terminated,2,2,,,,\n"     # start==0: notebook-valid, simulator drops
        "30,30,1,3,m1,Terminated,2,3,,,,\n"    # zero duration: notebook-valid, simulator drops
        "40,90,1,9,m1,Terminated,1,1,,,,\n"    # task 9 not in the fit-only task file -> no join
    )
    stats = mt.analyze(str(fit_only), str(instances_csv))
    assert stats["instance_rows"] == 6
    assert stats["instance_rows_valid"] == 4
    assert stats["instance_rows_loadable"] == 1
    assert stats["instances_match_tasks"] is False


def test_plot_gauges_renders_png(tmp_path):
    pg = _load("plot_gauges")
    gauge_csv = tmp_path / "gauges.csv"
    with open(gauge_csv, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(
            ["timestamp", "current_nodes", "current_pods",
             "pods_in_scheduling_queues", "node_average_cpu_utilization",
             "node_average_ram_utilization", "cluster_total_cpu_utilization",
             "cluster_total_ram_utilization"]
        )
        for t in range(0, 200, 5):
            writer.writerow([t, 4, t % 7, t % 3, 0.5, 0.25, 0.4, 0.2])
    out = tmp_path / "out.png"
    pg.plot(str(gauge_csv), str(out))
    assert out.exists() and out.stat().st_size > 10000

    # Load-curve overlay (alibaba_demo.ipynb cell 5): piecewise-cyclic
    # expected utilization, clamped at 1, anchored at group creation.
    import numpy as np

    expected = pg.expected_utilization(
        np.array([0.0, 50.0, 110.0, 170.0]),
        np.array([2.0, 2.0, 4.0, 0.0]),
        [{"duration": 60.0, "total_load": 1.0},
         {"duration": 60.0, "total_load": 6.0}],
    )
    np.testing.assert_allclose(expected, [0.5, 0.5, 1.0, 1.0])
    out2 = tmp_path / "out_overlay.png"
    pg.plot(str(gauge_csv), str(out2),
            load_curve="[{duration: 60.0, total_load: 3.0}]")
    assert out2.exists() and out2.stat().st_size > 10000
