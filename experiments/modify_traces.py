"""Offline Alibaba-v2017 trace preprocessing (script port of the reference's
experiments/modify_traces.ipynb + trace_analysis.ipynb).

Subcommands:
  add-only   machine_events.csv -> add-events-only cluster trace
             (modify_traces.ipynb cell 2: drops softerror/harderror rows)
  fit-only   batch_task.csv filtered to tasks with cpus <= --max-cores that
             fit on at least one machine of the add-only cluster trace
             (modify_traces.ipynb cell 5); columns pass through unchanged
  analyze    row/instance counts and basic stats for a workload
             (trace_analysis.ipynb)

All CSVs are headerless in the trace's column order (reference:
src/trace/alibaba_cluster_trace_v2017/{cluster,workload}.rs row structs).

Usage:
  python experiments/modify_traces.py add-only machine_events.csv server_event_add_only.csv
  python experiments/modify_traces.py fit-only server_event_add_only.csv batch_task.csv batch_task_fit_only.csv
  python experiments/modify_traces.py analyze batch_task_fit_only.csv [batch_instance.csv]
"""

from __future__ import annotations

import argparse
import csv
import sys

import numpy as np


def filter_add_only(machine_events_in: str, out: str) -> int:
    """Keep only `add` machine events (the reference's modified cluster trace
    ignores failures for the demo run). Returns rows written."""
    kept = 0
    with open(machine_events_in) as fin, open(out, "w", newline="") as fout:
        writer = csv.writer(fout)
        for row in csv.reader(fin):
            if row and row[2] == "add":
                writer.writerow(row)
                kept += 1
    return kept


def _load_machines(machine_events_add_only: str):
    cpus, mems = [], []
    with open(machine_events_add_only) as f:
        for row in csv.reader(f):
            if not row:
                continue
            cpus.append(float(row[4]))
            mems.append(float(row[5]))
    return np.asarray(cpus), np.asarray(mems)


def filter_fit_only(
    machine_events_add_only: str,
    batch_task_in: str,
    out: str,
    max_cores: float = 64.0,
    cpu_unit_divisor: float = 100.0,
) -> int:
    """Keep tasks with per-instance cpus <= max_cores that fit (cpu AND
    memory) on at least one machine (modify_traces.ipynb cell 5).

    Unit note: the simulator parses the batch_task cpu column as SANTIcores
    (1 core = 100, reference workload.rs:83) while machine_events carries
    cores; the reference notebook compares the two raw columns directly (a
    unit quirk of its dataset copy). This script compares in cores —
    task santicores / cpu_unit_divisor vs machine cores — pass
    --cpu-unit-divisor 1 to reproduce the notebook's literal behavior.
    Returns rows written."""
    node_cpu, node_mem = _load_machines(machine_events_add_only)
    if node_cpu.size == 0:
        raise SystemExit("no machines in the add-only trace")
    kept = 0
    with open(batch_task_in) as fin, open(out, "w", newline="") as fout:
        writer = csv.writer(fout)
        for row in csv.reader(fin):
            if not row:
                continue
            if len(row) < 8 or row[6] == "" or row[7] == "":
                continue  # missing resources: the simulator would skip these
            cores = float(row[6]) / cpu_unit_divisor
            mem = float(row[7])
            if cores > max_cores:
                continue
            if not bool(np.any((node_cpu >= cores) & (node_mem >= mem))):
                continue
            writer.writerow(row)
            kept += 1
    return kept


def analyze(batch_task_path: str, batch_instance_path: str | None = None) -> dict:
    """Task/instance counts and cpu/mem stats (trace_analysis.ipynb).

    With a batch_instance CSV, also reproduces the notebook's instance-side
    checks: total instance rows (cell 3 compares this against the sum of the
    tasks' number_of_instances column) plus two validity counts — the
    notebook's non-strict predicate (cell 5: non-empty start/end/task_id,
    end >= start >= 0) and the predicate the simulator actually loads with
    (start > 0, end > 0, start < end, AND task_id joins a batch_task row
    with non-empty cpu/mem — kubernetriks_tpu/trace/alibaba.py, mirroring
    workload.rs:56-120). The join matters when analyzing a filtered task
    file (fit-only) against the full instance trace: unjoined instances are
    dropped at load."""
    tasks = 0
    instances = 0
    cpus, mems = [], []
    joinable_task_ids = set()
    with open(batch_task_path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            tasks += 1
            if len(row) > 4 and row[4] != "":
                instances += int(row[4])
            if len(row) > 7 and row[6] != "" and row[7] != "":
                cpus.append(float(row[6]))
                mems.append(float(row[7]))
                # The simulator joins task_id as an integer, so "007" and
                # "7" are the same task; mirror that here.
                try:
                    joinable_task_ids.add(int(row[3]))
                except ValueError:
                    pass
    stats = {
        "tasks": tasks,
        "instances": instances,
        "cpu_mean": float(np.mean(cpus)) if cpus else None,
        "cpu_max": float(np.max(cpus)) if cpus else None,
        "mem_mean": float(np.mean(mems)) if mems else None,
        "mem_p75": float(np.quantile(mems, 0.75)) if mems else None,
    }
    if batch_instance_path is not None:
        rows = 0
        valid_notebook = 0
        valid_simulator = 0
        with open(batch_instance_path) as f:
            for row in csv.reader(f):
                if not row:
                    continue
                rows += 1
                if len(row) < 4 or row[0] == "" or row[1] == "" or row[3] == "":
                    continue
                start, end = float(row[0]), float(row[1])
                if end >= start >= 0:
                    valid_notebook += 1
                try:
                    task_id = int(row[3])
                except ValueError:
                    task_id = None
                if 0 < start < end and task_id in joinable_task_ids:
                    valid_simulator += 1
        stats["instance_rows"] = rows
        stats["instance_rows_valid"] = valid_notebook
        stats["instance_rows_loadable"] = valid_simulator
        stats["instances_match_tasks"] = rows == instances
    return stats


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    p1 = sub.add_parser("add-only")
    p1.add_argument("machine_events")
    p1.add_argument("out")
    p2 = sub.add_parser("fit-only")
    p2.add_argument("machine_events_add_only")
    p2.add_argument("batch_task")
    p2.add_argument("out")
    p2.add_argument("--max-cores", type=float, default=64.0)
    p2.add_argument("--cpu-unit-divisor", type=float, default=100.0)
    p3 = sub.add_parser("analyze")
    p3.add_argument("batch_task")
    p3.add_argument("batch_instance", nargs="?", default=None)
    args = parser.parse_args(argv)

    if args.cmd == "add-only":
        kept = filter_add_only(args.machine_events, args.out)
        print(f"wrote {kept} add events -> {args.out}")
    elif args.cmd == "fit-only":
        kept = filter_fit_only(
            args.machine_events_add_only,
            args.batch_task,
            args.out,
            args.max_cores,
            args.cpu_unit_divisor,
        )
        print(f"wrote {kept} fitting tasks -> {args.out}")
    else:
        print(analyze(args.batch_task, args.batch_instance))
    return 0


if __name__ == "__main__":
    sys.exit(main())
