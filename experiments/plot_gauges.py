"""Plot a gauge-metrics CSV (script port of the reference's
experiments/alibaba_demo.ipynb cells 4-5).

Consumes the 8-column gauge schema written by either backend (scalar:
MetricsCollector's 5 s cycle; batched: BatchedSimulation.write_gauge_csv —
both via the CLI's --gauge-csv flag) and renders four panels: current nodes,
current pods, scheduling-queue length, and cluster cpu/ram utilization with
their run means.

Usage: python experiments/plot_gauges.py gauge_metrics.csv [out.png] [--stride N]
"""

from __future__ import annotations

import argparse
import csv
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def load_gauges(path: str):
    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader)
        rows = [row for row in reader if row]
    data = np.asarray(rows, dtype=np.float64)
    return header, data


def expected_utilization(t, pods, segments, anchor: float = 0.0):
    """Piecewise-constant pod-group load curve -> expected per-pod
    utilization min(1, total_load / pod_count), cyclic and anchored at the
    group's creation time (the model of core/resource_usage.py PodGroup;
    reference: src/core/resource_usage/pod_group.rs:16-101). This is the
    overlay the reference's alibaba_demo.ipynb cell 5 draws over the gauge
    utilization series."""
    durations = np.asarray([float(s["duration"]) for s in segments])
    loads = np.asarray([float(s["total_load"]) for s in segments])
    cycle = durations.sum()
    edges = np.cumsum(durations)
    phase = np.mod(np.asarray(t, np.float64) - anchor, cycle)
    idx = np.searchsorted(edges, phase, side="right")
    idx = np.minimum(idx, len(loads) - 1)
    total_load = loads[idx]
    pods_safe = np.maximum(np.asarray(pods, np.float64), 1.0)
    out = np.minimum(1.0, total_load / pods_safe)
    return np.where(np.asarray(t, np.float64) >= anchor, out, 0.0)


def plot(path: str, out: str, stride: int = 1, load_curve: str | None = None,
         curve_anchor: float = 0.0) -> None:
    header, data = load_gauges(path)
    col = {name: i for i, name in enumerate(header)}
    data = data[::stride]
    t = data[:, col["timestamp"]]

    fig, axes = plt.subplots(2, 2, figsize=(12, 8), sharex=True)
    axes[0, 0].plot(t, data[:, col["current_nodes"]])
    axes[0, 0].set_title("Nodes")
    axes[0, 1].plot(t, data[:, col["current_pods"]])
    axes[0, 1].set_title("Pods")
    axes[1, 0].plot(t, data[:, col["pods_in_scheduling_queues"]])
    axes[1, 0].set_title("Pods in scheduling queues")

    cpu = data[:, col["cluster_total_cpu_utilization"]]
    ram = data[:, col["cluster_total_ram_utilization"]]
    ax = axes[1, 1]
    ax.plot(t, cpu, label="CPU utilization")
    ax.plot(t, ram, label="RAM utilization")
    ax.axhline(float(cpu.mean()), linestyle="--", alpha=0.6,
               label=f"CPU mean {cpu.mean():.3f}")
    ax.axhline(float(ram.mean()), linestyle=":", alpha=0.6,
               label=f"RAM mean {ram.mean():.3f}")
    if load_curve:
        import yaml

        segments = yaml.safe_load(load_curve)
        expected = expected_utilization(
            t, data[:, col["current_pods"]], segments, curve_anchor
        )
        ax.plot(t, expected, linestyle="--", alpha=0.8,
                label="expected (load curve / pods)")
    ax.set_title("Cluster utilization")
    ax.legend(fontsize=8)
    for row in axes:
        for a in row:
            a.set_xlabel("simulation time (s)")
            a.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("gauge_csv")
    parser.add_argument("out", nargs="?", default="gauge_metrics.png")
    parser.add_argument("--stride", type=int, default=1)
    parser.add_argument(
        "--load-curve",
        default=None,
        help="YAML list of {duration, total_load} segments; overlays the "
        "pod-group model's expected utilization on the utilization panel "
        "(alibaba_demo.ipynb cell 5)",
    )
    parser.add_argument("--curve-anchor", type=float, default=0.0,
                        help="pod-group creation time the cyclic curve anchors to")
    args = parser.parse_args(argv)
    plot(args.gauge_csv, args.out, args.stride, args.load_curve, args.curve_anchor)
    return 0


if __name__ == "__main__":
    sys.exit(main())
