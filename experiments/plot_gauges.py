"""Plot a gauge-metrics CSV (script port of the reference's
experiments/alibaba_demo.ipynb cells 4-5).

Consumes the 8-column gauge schema written by either backend (scalar:
MetricsCollector's 5 s cycle; batched: BatchedSimulation.write_gauge_csv —
both via the CLI's --gauge-csv flag) and renders four panels: current nodes,
current pods, scheduling-queue length, and cluster cpu/ram utilization with
their run means.

Usage: python experiments/plot_gauges.py gauge_metrics.csv [out.png] [--stride N]
"""

from __future__ import annotations

import argparse
import csv
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def load_gauges(path: str):
    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader)
        rows = [row for row in reader if row]
    data = np.asarray(rows, dtype=np.float64)
    return header, data


def plot(path: str, out: str, stride: int = 1) -> None:
    header, data = load_gauges(path)
    col = {name: i for i, name in enumerate(header)}
    data = data[::stride]
    t = data[:, col["timestamp"]]

    fig, axes = plt.subplots(2, 2, figsize=(12, 8), sharex=True)
    axes[0, 0].plot(t, data[:, col["current_nodes"]])
    axes[0, 0].set_title("Nodes")
    axes[0, 1].plot(t, data[:, col["current_pods"]])
    axes[0, 1].set_title("Pods")
    axes[1, 0].plot(t, data[:, col["pods_in_scheduling_queues"]])
    axes[1, 0].set_title("Pods in scheduling queues")

    cpu = data[:, col["cluster_total_cpu_utilization"]]
    ram = data[:, col["cluster_total_ram_utilization"]]
    ax = axes[1, 1]
    ax.plot(t, cpu, label="CPU utilization")
    ax.plot(t, ram, label="RAM utilization")
    ax.axhline(float(cpu.mean()), linestyle="--", alpha=0.6,
               label=f"CPU mean {cpu.mean():.3f}")
    ax.axhline(float(ram.mean()), linestyle=":", alpha=0.6,
               label=f"RAM mean {ram.mean():.3f}")
    ax.set_title("Cluster utilization")
    ax.legend(fontsize=8)
    for row in axes:
        for a in row:
            a.set_xlabel("simulation time (s)")
            a.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("gauge_csv")
    parser.add_argument("out", nargs="?", default="gauge_metrics.png")
    parser.add_argument("--stride", type=int, default=1)
    args = parser.parse_args(argv)
    plot(args.gauge_csv, args.out, args.stride)
    return 0


if __name__ == "__main__":
    sys.exit(main())
