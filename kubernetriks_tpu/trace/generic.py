"""Generic YAML trace format (reference: src/trace/generic.rs).

Workload events: CreatePod / RemovePod / CreatePodGroup; cluster events:
CreateNode / RemoveNode. The YAML uses serde-style tags
(``event_type: !CreatePod {pod: ...}``) which the tagged loader flattens to
{"__tag__": "CreatePod", ...}.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubernetriks_tpu.autoscalers.interface import PodGroup
from kubernetriks_tpu.config import load_yaml_with_tags
from kubernetriks_tpu.core.events import (
    CreateNodeRequest,
    CreatePodGroupRequest,
    CreatePodRequest,
    RemoveNodeRequest,
    RemovePodRequest,
)
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.trace.interface import Trace, TraceEvents


def _tag_of(event_type: Any) -> str:
    if isinstance(event_type, str):
        return event_type
    return event_type.get("__tag__", "")


class GenericWorkloadTrace(Trace):
    def __init__(self, events: List[Dict[str, Any]]) -> None:
        self.events = events

    @staticmethod
    def from_yaml(text: str) -> "GenericWorkloadTrace":
        doc = load_yaml_with_tags(text) or {}
        return GenericWorkloadTrace(events=doc.get("events") or [])

    @staticmethod
    def from_file(path: str) -> "GenericWorkloadTrace":
        with open(path) as f:
            return GenericWorkloadTrace.from_yaml(f.read())

    def convert_to_simulator_events(self) -> TraceEvents:
        """reference: src/trace/generic.rs:57-86."""
        converted: TraceEvents = []
        events, self.events = self.events, []
        for event in events:
            ts = float(event["timestamp"])
            event_type = event["event_type"]
            tag = _tag_of(event_type)
            if tag == "CreatePod":
                converted.append(
                    (ts, CreatePodRequest(pod=Pod.from_dict(event_type["pod"])))
                )
            elif tag == "RemovePod":
                converted.append(
                    (ts, RemovePodRequest(pod_name=event_type["pod_name"]))
                )
            elif tag == "CreatePodGroup":
                converted.append(
                    (
                        ts,
                        CreatePodGroupRequest(
                            pod_group=PodGroup.from_dict(event_type["pod_group"])
                        ),
                    )
                )
            else:
                raise ValueError(f"unknown workload event type {tag!r}")
        converted.sort(key=lambda pair: pair[0])
        return converted

    def event_count(self) -> int:
        return len(self.events)


class GenericClusterTrace(Trace):
    def __init__(self, events: List[Dict[str, Any]]) -> None:
        self.events = events

    @staticmethod
    def from_yaml(text: str) -> "GenericClusterTrace":
        doc = load_yaml_with_tags(text) or {}
        return GenericClusterTrace(events=doc.get("events") or [])

    @staticmethod
    def from_file(path: str) -> "GenericClusterTrace":
        with open(path) as f:
            return GenericClusterTrace.from_yaml(f.read())

    def convert_to_simulator_events(self) -> TraceEvents:
        """Sets allocatable = capacity on node creation
        (reference: src/trace/generic.rs:88-112)."""
        converted: TraceEvents = []
        events, self.events = self.events, []
        for event in events:
            ts = float(event["timestamp"])
            event_type = event["event_type"]
            tag = _tag_of(event_type)
            if tag == "CreateNode":
                node = Node.from_dict(event_type["node"])
                node.status.allocatable = node.status.capacity.copy()
                converted.append((ts, CreateNodeRequest(node=node)))
            elif tag == "RemoveNode":
                converted.append(
                    (ts, RemoveNodeRequest(node_name=event_type["node_name"]))
                )
            else:
                raise ValueError(f"unknown cluster event type {tag!r}")
        converted.sort(key=lambda pair: pair[0])
        return converted

    def event_count(self) -> int:
        return len(self.events)
