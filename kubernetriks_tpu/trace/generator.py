"""Synthetic workload/cluster generators.

Extends the reference's WIP generator (reference: src/trace/generator.rs:8-43 —
pods with cpu/ram sampled from 11 power-of-2 bins, duration U[1,10000]) into a
usable, seedable pair of generators for benchmarks and load tests. Also
provides a Poisson-arrival workload for the 100-node benchmark config
(BASELINE.md configs[1]).
"""

from __future__ import annotations

import math
import random
from typing import List, Optional, Tuple

from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.trace.interface import Trace, TraceEvents

# 11 power-of-2 resource bins, 1..1024 (reference: src/trace/generator.rs:14-16).
RESOURCE_BINS = [2**i for i in range(11)]


class SyntheticWorkloadTrace(Trace):
    """Pods with bin-sampled cpu (millicores = bin x 100) and ram (bytes =
    bin GiB / 16), uniform durations, uniform arrivals."""

    def __init__(
        self,
        pod_count: int,
        seed: int = 42,
        arrival_horizon: float = 10000.0,
        duration_range: Tuple[float, float] = (1.0, 10000.0),
    ) -> None:
        self.pod_count = pod_count
        self.seed = seed
        self.arrival_horizon = arrival_horizon
        self.duration_range = duration_range
        self._converted = False

    def convert_to_simulator_events(self) -> TraceEvents:
        rng = random.Random(self.seed)
        events: TraceEvents = []
        for i in range(self.pod_count):
            cpu = rng.choice(RESOURCE_BINS) * 100
            ram = rng.choice(RESOURCE_BINS) * (1024**3 // 16)
            duration = rng.uniform(*self.duration_range)
            ts = rng.uniform(0.0, self.arrival_horizon)
            events.append(
                (ts, CreatePodRequest(pod=Pod.new(f"synthetic_pod_{i}", cpu, ram, duration)))
            )
        self._converted = True
        events.sort(key=lambda pair: pair[0])
        return events

    def event_count(self) -> int:
        return 0 if self._converted else self.pod_count


class PoissonWorkloadTrace(Trace):
    """Poisson pod arrivals at a given rate — the BASELINE benchmark shape
    (100-node cluster, synthetic Poisson arrivals)."""

    def __init__(
        self,
        rate_per_second: float,
        horizon: float,
        seed: int = 42,
        cpu: int = 1000,
        ram: int = 1024**3,
        duration_range: Tuple[float, float] = (10.0, 300.0),
        max_pods: Optional[int] = None,
        name_prefix: str = "poisson_pod",
    ) -> None:
        self.rate = rate_per_second
        self.horizon = horizon
        self.seed = seed
        self.cpu = cpu
        self.ram = ram
        self.duration_range = duration_range
        self.max_pods = max_pods
        self.name_prefix = name_prefix
        self._count: Optional[int] = None

    def convert_to_simulator_events(self) -> TraceEvents:
        rng = random.Random(self.seed)
        events: TraceEvents = []
        t = 0.0
        i = 0
        while True:
            t += rng.expovariate(self.rate)
            if t > self.horizon or (self.max_pods is not None and i >= self.max_pods):
                break
            duration = rng.uniform(*self.duration_range)
            events.append(
                (
                    t,
                    CreatePodRequest(
                        pod=Pod.new(f"{self.name_prefix}_{i}", self.cpu, self.ram, duration)
                    ),
                )
            )
            i += 1
        self._count = i
        return events

    def event_count(self) -> int:
        return self._count if self._count is not None else int(self.rate * self.horizon)


class MergedWorkloadTrace(Trace):
    """Time-merge of several workload traces into one event stream — e.g. a
    bimodal mix of a high-rate small-pod process and a low-rate large-pod
    process, the contended shape where placement policy (packing vs
    spreading) decides whether large pods ever fit. Pass distinct
    name_prefix values to the parts so pod names stay unique."""

    def __init__(self, *parts: Trace) -> None:
        self.parts = parts

    def convert_to_simulator_events(self) -> TraceEvents:
        events: TraceEvents = []
        for part in self.parts:
            events.extend(part.convert_to_simulator_events())
        events.sort(key=lambda pair: pair[0])
        return events

    def event_count(self) -> int:
        return sum(part.event_count() for part in self.parts)


class UniformClusterTrace(Trace):
    """N identical nodes created at t=0."""

    def __init__(self, node_count: int, cpu: int = 64000, ram: int = 128 * 1024**3) -> None:
        self.node_count = node_count
        self.cpu = cpu
        self.ram = ram

    def convert_to_simulator_events(self) -> TraceEvents:
        return [
            (
                0.0,
                CreateNodeRequest(node=Node.new(f"gen_node_{i}", self.cpu, self.ram)),
            )
            for i in range(self.node_count)
        ]

    def event_count(self) -> int:
        return self.node_count
