"""Alibaba cluster trace v2017 pipeline
(reference: src/trace/alibaba_cluster_trace_v2017/{workload,cluster,common}.rs).

Workload: CSV batch_instance joined to batch_task on task_id, filtered for
validity, converted to CreatePodRequests. Cluster: CSV machine_events — `add`
creates a node, `softerror`/`harderror` removes it (with dedup of re-removals
and ghost nodes).
"""

from __future__ import annotations

import csv
import io
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetriks_tpu.core.events import CreateNodeRequest, RemoveNodeRequest, CreatePodRequest
from kubernetriks_tpu.core.types import Node, Pod
from kubernetriks_tpu.trace.interface import Trace, TraceEvents

# Normalized memory 1.0 == 128 GiB; machine cpus are cores (x1000 -> millicores)
# (reference: src/trace/alibaba_cluster_trace_v2017/common.rs:1-6).
DENORMALIZATION_BASE = 128 * 1024**3
CPU_BASE = 1000


# ASCII integer-literal syntax (optional sign, digits, single underscores
# BETWEEN digits) — the header rule's integer test. ASCII-only on purpose:
# Python's int() also accepts Unicode digits, which the native feeder's
# byte-level scan (LooksLikePythonInt) cannot see, so the shared rule pins
# the ASCII subset both sides implement identically.
_ASCII_INT_RE = re.compile(r"[+-]?[0-9](?:_?[0-9])*")


def _data_rows(text: str):
    """CSV rows of a real-format Alibaba dump, tolerant of the quirks the
    circulating files actually carry: CRLF line endings and quoted fields
    (both handled by the csv module's RFC4180 state machine) plus an
    OPTIONAL header line. Header rule, shared verbatim with the native
    feeder (native/trace_feeder.cc IsHeaderRow): the FIRST row is a header
    iff its first field (ASCII-whitespace-trimmed) is non-empty and not an
    ASCII integer literal — every data row's first column is either an
    integer timestamp or empty (batch_instance's optional start_ts), while
    header names never are. Only the first row is eligible, so a malformed
    later row still surfaces as a parse error."""
    first = True
    for row in csv.reader(io.StringIO(text)):
        if not row:
            continue
        if first:
            first = False
            head = row[0].strip(" \t\f\v")
            if head and not _ASCII_INT_RE.fullmatch(head):
                continue
        yield row


def _opt_int(value: str) -> Optional[int]:
    return int(value) if value not in ("", None) else None


def _opt_float(value: str) -> Optional[float]:
    return float(value) if value not in ("", None) else None


@dataclass
class BatchTask:
    """Row of batch_task.csv (reference: workload.rs:15-25)."""

    task_create_time: int
    task_end_time: int
    job_id: int
    task_id: int
    number_of_instances: int
    status: str
    cpus_requested_per_instance: Optional[int]  # in santicores (1 core = 100)
    normalized_memory_per_instance: Optional[float]

    @staticmethod
    def from_row(row: List[str]) -> "BatchTask":
        return BatchTask(
            task_create_time=int(row[0]),
            task_end_time=int(row[1]),
            job_id=int(row[2]),
            task_id=int(row[3]),
            number_of_instances=int(row[4]),
            status=row[5],
            cpus_requested_per_instance=_opt_int(row[6]) if len(row) > 6 else None,
            normalized_memory_per_instance=_opt_float(row[7]) if len(row) > 7 else None,
        )


@dataclass
class BatchInstance:
    """Row of batch_instance.csv (reference: workload.rs:27-41)."""

    start_timestamp: Optional[int]
    end_timestamp: Optional[int]
    job_id: Optional[int]
    task_id: Optional[int]
    machine_id: Optional[int]
    status: str
    sequence_number: int
    total_sequence_number: int

    @staticmethod
    def from_row(row: List[str]) -> "BatchInstance":
        return BatchInstance(
            start_timestamp=_opt_int(row[0]),
            end_timestamp=_opt_int(row[1]),
            job_id=_opt_int(row[2]),
            task_id=_opt_int(row[3]),
            machine_id=_opt_int(row[4]),
            status=row[5],
            sequence_number=int(row[6]),
            total_sequence_number=int(row[7]),
        )


def read_batch_tasks(text: str) -> Dict[int, BatchTask]:
    """task_id-keyed; duplicate task ids are an input error
    (reference: workload.rs:152-166)."""
    tasks: Dict[int, BatchTask] = {}
    for row in _data_rows(text):
        task = BatchTask.from_row(row)
        if task.task_id in tasks:
            raise ValueError(f"duplicated task id: {task.task_id}")
        tasks[task.task_id] = task
    return tasks


def read_batch_instances(text: str) -> List[BatchInstance]:
    return [BatchInstance.from_row(row) for row in _data_rows(text)]


class AlibabaWorkloadTraceV2017(Trace):
    def __init__(
        self, batch_instances: List[BatchInstance], batch_tasks: Dict[int, BatchTask]
    ) -> None:
        self.batch_instances_events = batch_instances
        self.batch_tasks = batch_tasks

    @staticmethod
    def from_files(
        batch_instance_trace_path: str, batch_task_trace_path: str
    ) -> "AlibabaWorkloadTraceV2017":
        with open(batch_instance_trace_path) as f:
            instances = read_batch_instances(f.read())
        with open(batch_task_trace_path) as f:
            tasks = read_batch_tasks(f.read())
        return AlibabaWorkloadTraceV2017(instances, tasks)

    def make_pods_from_instances(
        self, instances: List[BatchInstance]
    ) -> List[tuple]:
        """Filter invalid rows and join to tasks; pod = (job_task_seq name,
        santicores x10 -> millicores, normalized mem x128 GiB, duration =
        end - start) (reference: workload.rs:56-120)."""
        pods = []
        pod_no = 0
        for instance in instances:
            if (
                instance.start_timestamp is None
                or instance.end_timestamp is None
                or instance.task_id is None
            ):
                continue
            task = self.batch_tasks.get(instance.task_id)
            if task is None:
                continue
            if (
                task.cpus_requested_per_instance is None
                or task.normalized_memory_per_instance is None
            ):
                continue
            if (
                instance.start_timestamp <= 0
                or instance.end_timestamp <= 0
                or instance.start_timestamp >= instance.end_timestamp
            ):
                continue

            pod_name = f"{instance.job_id}_{instance.task_id}_{pod_no}"
            pod_no += 1
            converted_cpu = task.cpus_requested_per_instance * 10  # santicores -> millicores
            converted_ram = int(task.normalized_memory_per_instance * DENORMALIZATION_BASE)
            running_duration = float(instance.end_timestamp - instance.start_timestamp)
            pod = Pod.new(pod_name, converted_cpu, converted_ram, running_duration)
            pods.append((float(instance.start_timestamp), pod))
        return pods

    def convert_to_simulator_events(self) -> TraceEvents:
        events, self.batch_instances_events = self.batch_instances_events, []
        converted = [
            (ts, CreatePodRequest(pod=pod))
            for ts, pod in self.make_pods_from_instances(events)
        ]
        self.batch_tasks = {}
        converted.sort(key=lambda pair: pair[0])
        return converted

    def event_count(self) -> int:
        return len(self.batch_instances_events)


@dataclass
class MachineEvent:
    """Row of machine_events.csv (reference: cluster.rs:16-38)."""

    timestamp: int
    machine_id: int
    event_type: str  # "add" | "softerror" | "harderror"
    event_detail: Optional[str]
    number_of_cpus: Optional[int]  # in cores
    normalized_memory: Optional[float]

    @staticmethod
    def from_row(row: List[str]) -> "MachineEvent":
        return MachineEvent(
            timestamp=int(row[0]),
            machine_id=int(row[1]),
            event_type=row[2],
            event_detail=row[3] if len(row) > 3 and row[3] else None,
            number_of_cpus=_opt_int(row[4]) if len(row) > 4 else None,
            normalized_memory=_opt_float(row[5]) if len(row) > 5 else None,
        )


def read_machine_events(text: str) -> List[MachineEvent]:
    return [MachineEvent.from_row(row) for row in _data_rows(text)]


class AlibabaClusterTraceV2017(Trace):
    def __init__(self, machine_events: List[MachineEvent]) -> None:
        self.machine_events = machine_events

    @staticmethod
    def from_file(machine_events_trace_path: str) -> "AlibabaClusterTraceV2017":
        with open(machine_events_trace_path) as f:
            return AlibabaClusterTraceV2017(read_machine_events(f.read()))

    def convert_to_simulator_events(self) -> TraceEvents:
        """`add` -> CreateNodeRequest; `softerror`/`harderror` ->
        RemoveNodeRequest with dedup of re-removals and ghost nodes
        (reference: cluster.rs:55-105). The soft/hard distinction is collapsed:
        the simulator terminates the node either way so workload reschedules."""
        events, self.machine_events = self.machine_events, []
        converted: TraceEvents = []
        created_nodes = set()
        removed_nodes = set()
        for machine_event in events:
            node_name = f"alibaba_node_{machine_event.machine_id}"
            if machine_event.event_type == "add":
                if (
                    machine_event.number_of_cpus is None
                    or machine_event.normalized_memory is None
                ):
                    raise ValueError(
                        f"machine event 'add' for machine "
                        f"{machine_event.machine_id} at t={machine_event.timestamp} "
                        f"lacks cpu/memory values"
                    )
                created_nodes.add(node_name)
                converted_cpu = machine_event.number_of_cpus * CPU_BASE
                converted_ram = int(machine_event.normalized_memory * DENORMALIZATION_BASE)
                converted.append(
                    (
                        float(machine_event.timestamp),
                        CreateNodeRequest(
                            node=Node.new(node_name, converted_cpu, converted_ram)
                        ),
                    )
                )
            elif machine_event.event_type in ("softerror", "harderror"):
                if node_name in removed_nodes or node_name not in created_nodes:
                    continue
                removed_nodes.add(node_name)
                converted.append(
                    (
                        float(machine_event.timestamp),
                        RemoveNodeRequest(node_name=node_name),
                    )
                )
            else:
                raise ValueError(
                    f"Unsupported operation for a node in alibaba cluster "
                    f"trace: {machine_event.event_type}"
                )
        converted.sort(key=lambda pair: pair[0])
        return converted

    def event_count(self) -> int:
        return len(self.machine_events)
