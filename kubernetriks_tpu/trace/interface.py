"""Trace interface: any input format converts to timestamped simulator events
(reference: src/trace/interface.rs)."""

from __future__ import annotations

from typing import Any, List, Tuple

# (timestamp, event) pairs, sorted by timestamp ascending.
TraceEvents = List[Tuple[float, Any]]


class Trace:
    def convert_to_simulator_events(self) -> TraceEvents:
        """Move-out semantics in the reference; callable once per trace."""
        raise NotImplementedError

    def event_count(self) -> int:
        raise NotImplementedError


class EmptyTrace(Trace):
    def convert_to_simulator_events(self) -> TraceEvents:
        return []

    def event_count(self) -> int:
        return 0
