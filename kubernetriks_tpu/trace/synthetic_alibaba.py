"""Synthesize Alibaba-v2017-format CSV traces at configurable scale.

The real trace is not redistributable with this repo, so benchmarks and
integration tests synthesize statistically similar CSVs in the exact column
format the parsers consume (machine_events.csv per
reference src/trace/alibaba_cluster_trace_v2017/cluster.rs:16-38;
batch_task.csv / batch_instance.csv per workload.rs:15-41). Default shape
parameters follow the reference's "modified trace": 1,313 add-only machines
with 64 cores and normalized memory ~0.69, and a fit-filtered batch workload
of ~53k tasks (reference experiments/{modify_traces,alibaba_demo}.ipynb,
BASELINE.md).
"""

from __future__ import annotations

import os

import numpy as np

REFERENCE_MACHINES = 1313
REFERENCE_TASKS = 53472


def write_machine_events(
    path: str,
    n_machines: int = REFERENCE_MACHINES,
    cores: int = 64,
    normalized_memory: float = 0.6875,  # 88 GiB of the 128 GiB base: MiB-exact
    error_fraction: float = 0.0,
    horizon: float = 86400.0,
    seed: int = 0,
) -> int:
    """machine_events.csv: `add` rows at t=0 (the reference's modified trace
    keeps only adds); optionally a fraction of machines fail later
    (softerror -> node removal). Returns the number of rows written."""
    rng = np.random.default_rng(seed)
    rows = []
    for m in range(n_machines):
        rows.append((0, m, "add", "", cores, normalized_memory))
    n_errors = int(n_machines * error_fraction)
    for m in rng.choice(n_machines, size=n_errors, replace=False):
        ts = int(rng.uniform(0.2, 0.9) * horizon)
        kind = "softerror" if rng.random() < 0.5 else "harderror"
        rows.append((ts, int(m), kind, "", "", ""))
    rows.sort(key=lambda r: (r[0], r[1]))
    with open(path, "w") as f:
        for r in rows:
            f.write(",".join(str(x) for x in r) + "\n")
    return len(rows)


def write_batch_workload(
    task_path: str,
    instance_path: str,
    n_tasks: int = REFERENCE_TASKS,
    horizon: float = 86400.0,
    max_instances_per_task: int = 3,
    cpu_santicores_range=(50, 800),
    heavy_fraction: float = 0.02,
    max_cpu_cores: int = 64,
    duration_range=(60.0, 2400.0),
    seed: int = 1,
) -> int:
    """batch_task.csv + batch_instance.csv. Task sizing follows the real
    trace's character: mostly sub-8-core requests with a small heavy tail up
    to max_cpu_cores (the fit filter of modify_traces.ipynb cell 5 guarantees
    every task fits a 64-core machine; the reference demo's cluster runs at
    ~3-10% utilization, so defaults keep aggregate demand well under
    capacity). Returns the number of instance rows."""
    rng = np.random.default_rng(seed)
    task_rows = []
    instance_rows = []
    for t in range(n_tasks):
        job_id = 1_000_000 + t // 4
        task_id = 2_000_000 + t
        n_inst = int(rng.integers(1, max_instances_per_task + 1))
        # santicores: 1 core == 100.
        if rng.random() < heavy_fraction:
            cpus = int(rng.integers(cpu_santicores_range[1], max_cpu_cores * 100 + 1))
        else:
            cpus = int(rng.integers(cpu_santicores_range[0], cpu_santicores_range[1] + 1))
        # Normalized memory, MiB-aligned against the 128 GiB base so the
        # batched path's RAM quantization is exact.
        mem_mib = int(rng.integers(64, 4096))
        mem = mem_mib / (128 * 1024)
        create = int(rng.uniform(1.0, horizon * 0.8))
        duration = int(rng.uniform(duration_range[0], min(horizon * 0.2, duration_range[1])))
        task_rows.append(
            (create, create + duration, job_id, task_id, n_inst, "Terminated", cpus, mem)
        )
        for s in range(n_inst):
            start = create + int(rng.uniform(0.0, 60.0))
            end = start + duration
            instance_rows.append(
                (start, end, job_id, task_id, int(rng.integers(0, 1313)),
                 "Terminated", s, n_inst)
            )
    with open(task_path, "w") as f:
        for r in task_rows:
            f.write(",".join(str(x) for x in r) + "\n")
    instance_rows.sort(key=lambda r: r[0])
    with open(instance_path, "w") as f:
        for r in instance_rows:
            f.write(",".join(str(x) for x in r) + "\n")
    return len(instance_rows)


def write_synthetic_trace_dir(
    out_dir: str,
    n_machines: int = REFERENCE_MACHINES,
    n_tasks: int = REFERENCE_TASKS,
    horizon: float = 86400.0,
    error_fraction: float = 0.0,
    seed: int = 0,
):
    """Write all three CSVs into out_dir; returns their paths
    (machine_events, batch_task, batch_instance)."""
    os.makedirs(out_dir, exist_ok=True)
    machines = os.path.join(out_dir, "machine_events.csv")
    tasks = os.path.join(out_dir, "batch_task.csv")
    instances = os.path.join(out_dir, "batch_instance.csv")
    write_machine_events(
        machines, n_machines, error_fraction=error_fraction,
        horizon=horizon, seed=seed,
    )
    write_batch_workload(
        tasks, instances, n_tasks, horizon=horizon, seed=seed + 1
    )
    return machines, tasks, instances
