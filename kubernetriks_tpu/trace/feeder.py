"""ctypes binding for the native C++ trace feeder (native/trace_feeder.cc).

The feeder is the framework's native host-side data loader: it parses the
Alibaba v2017 CSVs (batch_instance joined to batch_task; machine_events),
applies the reference's validity filters (reference:
src/trace/alibaba_cluster_trace_v2017/workload.rs:56-120, cluster.rs:55-105)
and returns dense, time-sorted numpy arrays ready to be compiled into device
tensors. The pure-Python pipeline in kubernetriks_tpu.trace.alibaba has
identical semantics and serves as both fallback and oracle.

The shared library is built on demand with g++ (cached next to the source,
keyed on source mtime); if no toolchain is available the callers fall back to
the Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SOURCE = os.path.join(_REPO_ROOT, "native", "trace_feeder.cc")
_LIB = os.path.join(_REPO_ROOT, "native", "build", "libtrace_feeder.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build_library() -> Optional[str]:
    """Compile the feeder if missing or stale. Returns an error string or None."""
    try:
        os.makedirs(os.path.dirname(_LIB), exist_ok=True)
        if not os.path.exists(_SOURCE):
            return f"feeder source not found: {_SOURCE}"
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SOURCE):
            return None
    except OSError as exc:
        return f"cannot stage native build dir: {exc}"
    # Build to a per-process temp path, then rename into place: concurrent
    # builders (pytest workers, parallel CLI runs) must never dlopen a
    # half-written .so.
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        _SOURCE, "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-2000:]}"
        os.replace(tmp, _LIB)
    except (OSError, subprocess.TimeoutExpired) as exc:
        return f"g++ invocation failed: {exc}"
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build_library()
        if err is not None:
            _build_error = err
            return None
        lib = ctypes.CDLL(_LIB)
        lib.feeder_parse_workload.restype = ctypes.c_void_p
        lib.feeder_parse_workload.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.feeder_parse_machines.restype = ctypes.c_void_p
        lib.feeder_parse_machines.argtypes = [ctypes.c_char_p]
        lib.feeder_error.restype = ctypes.c_char_p
        lib.feeder_error.argtypes = [ctypes.c_void_p]
        lib.feeder_workload_count.restype = ctypes.c_int64
        lib.feeder_workload_count.argtypes = [ctypes.c_void_p]
        lib.feeder_machine_count.restype = ctypes.c_int64
        lib.feeder_machine_count.argtypes = [ctypes.c_void_p]
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.feeder_workload_fill.restype = None
        lib.feeder_workload_fill.argtypes = [
            ctypes.c_void_p, f64p, i64p, i64p, f64p, i64p, i64p, i64p,
        ]
        lib.feeder_workload_fill_range.restype = None
        lib.feeder_workload_fill_range.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            f64p, i64p, i64p, f64p, i64p, i64p, i64p,
        ]
        lib.feeder_machine_fill.restype = None
        lib.feeder_machine_fill.argtypes = [ctypes.c_void_p, f64p, i32p, i64p, i64p, i64p]
        lib.feeder_free.restype = None
        lib.feeder_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_build_error() -> Optional[str]:
    _load()
    return _build_error


@dataclass
class WorkloadArrays:
    """Dense pod-creation events, stably sorted by start timestamp."""

    start_ts: np.ndarray       # (P,) float64 seconds
    cpu_millicores: np.ndarray  # (P,) int64
    ram_bytes: np.ndarray       # (P,) int64
    duration: np.ndarray        # (P,) float64 seconds
    job_id: np.ndarray          # (P,) int64; -1 encodes a missing job id
    task_id: np.ndarray         # (P,) int64
    pod_no: np.ndarray          # (P,) int64 per-trace running pod counter

    def pod_name(self, i: int) -> str:
        # Mirrors the Python path's f"{job_id}_{task_id}_{n}" naming, where a
        # missing job id renders as the literal "None".
        jid = "None" if self.job_id[i] == -1 else str(int(self.job_id[i]))
        return f"{jid}_{int(self.task_id[i])}_{int(self.pod_no[i])}"


@dataclass
class ClusterArrays:
    """Dense node lifecycle events (kind 0 = create, 1 = remove), sorted."""

    ts: np.ndarray             # (M,) float64 seconds
    kind: np.ndarray           # (M,) int32
    cpu_millicores: np.ndarray  # (M,) int64 (creates only)
    ram_bytes: np.ndarray       # (M,) int64 (creates only)
    machine_id: np.ndarray      # (M,) int64

    def node_name(self, i: int) -> str:
        return f"alibaba_node_{int(self.machine_id[i])}"


def _take_handle(lib: ctypes.CDLL, handle: int) -> int:
    if not handle:
        raise RuntimeError("native feeder returned a null handle")
    err = lib.feeder_error(ctypes.c_void_p(handle)).decode()
    if err:
        lib.feeder_free(ctypes.c_void_p(handle))
        raise ValueError(err)
    return handle


def load_workload_arrays(
    batch_instance_path: str, batch_task_path: str
) -> WorkloadArrays:
    """Parse + join + filter the workload CSVs natively."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native feeder unavailable: {_build_error}")
    handle = _take_handle(
        lib,
        lib.feeder_parse_workload(
            batch_instance_path.encode(), batch_task_path.encode()
        ),
    )
    try:
        n = lib.feeder_workload_count(ctypes.c_void_p(handle))
        out = WorkloadArrays(
            start_ts=np.empty(n, np.float64),
            cpu_millicores=np.empty(n, np.int64),
            ram_bytes=np.empty(n, np.int64),
            duration=np.empty(n, np.float64),
            job_id=np.empty(n, np.int64),
            task_id=np.empty(n, np.int64),
            pod_no=np.empty(n, np.int64),
        )
        if n:
            lib.feeder_workload_fill(
                ctypes.c_void_p(handle),
                out.start_ts, out.cpu_millicores, out.ram_bytes,
                out.duration, out.job_id, out.task_id, out.pod_no,
            )
        return out
    finally:
        lib.feeder_free(ctypes.c_void_p(handle))


class WorkloadSegmentReader:
    """Keep-alive handle over the natively parsed workload: pulls sorted
    rows [lo, lo + n) as bounded WorkloadArrays segments instead of
    materializing every column Python-side at once — the TRACE half of
    the streaming ingestion pipeline (batched/stream.py stages payload
    segments; this is the seam that feeds them for multi-million-row
    Alibaba replays: the compact parsed representation stays native-side,
    and the Python working set is one segment).

    Usage:
        with WorkloadSegmentReader(bi_path, bt_path) as r:
            for seg in r.iter_segments(rows_per_segment=1_000_000):
                ...  # seg is a WorkloadArrays over one row range

    Segment reads are pure slices of the one stable time-sort the parse
    performed, so concatenating every segment reproduces
    load_workload_arrays exactly (pinned in tests/test_native_feeder.py).
    """

    def __init__(self, batch_instance_path: str, batch_task_path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native feeder unavailable: {_build_error}")
        self._lib = lib
        self._handle: Optional[int] = _take_handle(
            lib,
            lib.feeder_parse_workload(
                batch_instance_path.encode(), batch_task_path.encode()
            ),
        )
        self._count = int(
            lib.feeder_workload_count(ctypes.c_void_p(self._handle))
        )

    def __len__(self) -> int:
        return self._count

    def read(self, lo: int, n: int) -> WorkloadArrays:
        """Rows [lo, lo + n) of the sorted workload (clamped to the end)."""
        if self._handle is None:
            raise ValueError("WorkloadSegmentReader is closed")
        if lo < 0:
            raise ValueError(f"segment lo must be >= 0, got {lo}")
        n = max(0, min(n, self._count - lo))
        out = WorkloadArrays(
            start_ts=np.empty(n, np.float64),
            cpu_millicores=np.empty(n, np.int64),
            ram_bytes=np.empty(n, np.int64),
            duration=np.empty(n, np.float64),
            job_id=np.empty(n, np.int64),
            task_id=np.empty(n, np.int64),
            pod_no=np.empty(n, np.int64),
        )
        if n:
            self._lib.feeder_workload_fill_range(
                ctypes.c_void_p(self._handle), lo, n,
                out.start_ts, out.cpu_millicores, out.ram_bytes,
                out.duration, out.job_id, out.task_id, out.pod_no,
            )
        return out

    def iter_segments(self, rows_per_segment: int):
        """Yield (lo, WorkloadArrays) covering the whole workload in order."""
        if rows_per_segment <= 0:
            raise ValueError("rows_per_segment must be positive")
        lo = 0
        while lo < self._count:
            yield lo, self.read(lo, rows_per_segment)
            lo += rows_per_segment

    def close(self) -> None:
        if self._handle is not None:
            self._lib.feeder_free(ctypes.c_void_p(self._handle))
            self._handle = None

    def __enter__(self) -> "WorkloadSegmentReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class WorkloadArraysReader:
    """Python-oracle random-access reader over a materialized
    WorkloadArrays: the same (lo, n) -> WorkloadArrays contract as
    WorkloadSegmentReader.read, for callers (payload sources, tests)
    that need row ranges without the native toolchain. Views, no copies."""

    def __init__(self, arrays: WorkloadArrays) -> None:
        self.arrays = arrays
        self._count = len(arrays.start_ts)

    def __len__(self) -> int:
        return self._count

    def read(self, lo: int, n: int) -> WorkloadArrays:
        if lo < 0:
            raise ValueError(f"segment lo must be >= 0, got {lo}")
        hi = min(lo + max(n, 0), self._count)
        a = self.arrays
        return WorkloadArrays(
            start_ts=a.start_ts[lo:hi],
            cpu_millicores=a.cpu_millicores[lo:hi],
            ram_bytes=a.ram_bytes[lo:hi],
            duration=a.duration[lo:hi],
            job_id=a.job_id[lo:hi],
            task_id=a.task_id[lo:hi],
            pod_no=a.pod_no[lo:hi],
        )


def iter_workload_segments(
    arrays: WorkloadArrays, rows_per_segment: int
):
    """Python-oracle mirror of WorkloadSegmentReader.iter_segments over an
    already-materialized WorkloadArrays (the fallback path when no native
    toolchain exists): yields (lo, WorkloadArrays) row-range views with
    identical semantics, so callers of either source see the same segment
    stream."""
    if rows_per_segment <= 0:
        raise ValueError("rows_per_segment must be positive")
    total = len(arrays.start_ts)
    lo = 0
    while lo < total:
        hi = min(lo + rows_per_segment, total)
        yield lo, WorkloadArrays(
            start_ts=arrays.start_ts[lo:hi],
            cpu_millicores=arrays.cpu_millicores[lo:hi],
            ram_bytes=arrays.ram_bytes[lo:hi],
            duration=arrays.duration[lo:hi],
            job_id=arrays.job_id[lo:hi],
            task_id=arrays.task_id[lo:hi],
            pod_no=arrays.pod_no[lo:hi],
        )
        lo = hi


def load_cluster_arrays(machine_events_path: str) -> ClusterArrays:
    """Parse + dedup the machine-events CSV natively."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native feeder unavailable: {_build_error}")
    handle = _take_handle(
        lib, lib.feeder_parse_machines(machine_events_path.encode())
    )
    try:
        n = lib.feeder_machine_count(ctypes.c_void_p(handle))
        out = ClusterArrays(
            ts=np.empty(n, np.float64),
            kind=np.empty(n, np.int32),
            cpu_millicores=np.empty(n, np.int64),
            ram_bytes=np.empty(n, np.int64),
            machine_id=np.empty(n, np.int64),
        )
        if n:
            lib.feeder_machine_fill(
                ctypes.c_void_p(handle),
                out.ts, out.kind, out.cpu_millicores, out.ram_bytes,
                out.machine_id,
            )
        return out
    finally:
        lib.feeder_free(ctypes.c_void_p(handle))


def workload_events_from_arrays(arrays: WorkloadArrays) -> List[Tuple[float, object]]:
    """Materialize the dense arrays back into CreatePodRequest trace events
    (object form used by the scalar path and the batched trace compiler)."""
    from kubernetriks_tpu.core.events import CreatePodRequest
    from kubernetriks_tpu.core.types import Pod

    events = []
    for i in range(len(arrays.start_ts)):
        pod = Pod.new(
            arrays.pod_name(i),
            int(arrays.cpu_millicores[i]),
            int(arrays.ram_bytes[i]),
            float(arrays.duration[i]),
        )
        events.append((float(arrays.start_ts[i]), CreatePodRequest(pod=pod)))
    return events


def cluster_events_from_arrays(arrays: ClusterArrays) -> List[Tuple[float, object]]:
    from kubernetriks_tpu.core.events import CreateNodeRequest, RemoveNodeRequest
    from kubernetriks_tpu.core.types import Node

    events = []
    for i in range(len(arrays.ts)):
        name = arrays.node_name(i)
        if int(arrays.kind[i]) == 0:
            events.append(
                (
                    float(arrays.ts[i]),
                    CreateNodeRequest(
                        node=Node.new(
                            name,
                            int(arrays.cpu_millicores[i]),
                            int(arrays.ram_bytes[i]),
                        )
                    ),
                )
            )
        else:
            events.append((float(arrays.ts[i]), RemoveNodeRequest(node_name=name)))
    return events


def iter_time_slabs(
    arrays: WorkloadArrays, slab_seconds: float
) -> List[Tuple[float, float, slice]]:
    """Index the sorted workload into [t0, t0+slab) windows for streaming:
    host->device transfer happens one slab at a time so multi-million-row
    traces never need to sit in HBM whole (SURVEY §5.8 'host/device
    streaming'). Returns (slab_start, slab_end, index_slice) triples."""
    if len(arrays.start_ts) == 0:
        return []
    t0 = float(arrays.start_ts[0])
    t_end = float(arrays.start_ts[-1])
    slabs = []
    lo = 0
    slab_start = t0
    while slab_start <= t_end:
        slab_end = slab_start + slab_seconds
        hi = int(np.searchsorted(arrays.start_ts, slab_end, side="left"))
        if hi > lo:
            slabs.append((slab_start, slab_end, slice(lo, hi)))
        lo = hi
        slab_start = slab_end
    return slabs


class NativeAlibabaWorkloadTrace:
    """Trace-interface adapter over the native workload arrays: drop-in for
    AlibabaWorkloadTraceV2017 when the C++ feeder is available."""

    def __init__(self, arrays: WorkloadArrays) -> None:
        self.arrays: Optional[WorkloadArrays] = arrays

    @staticmethod
    def from_files(
        batch_instance_trace_path: str, batch_task_trace_path: str
    ) -> "NativeAlibabaWorkloadTrace":
        return NativeAlibabaWorkloadTrace(
            load_workload_arrays(batch_instance_trace_path, batch_task_trace_path)
        )

    def convert_to_simulator_events(self):
        arrays, self.arrays = self.arrays, None
        if arrays is None:
            return []
        return workload_events_from_arrays(arrays)

    def event_count(self) -> int:
        return 0 if self.arrays is None else len(self.arrays.start_ts)


class NativeAlibabaClusterTrace:
    """Trace-interface adapter over the native machine-event arrays."""

    def __init__(self, arrays: ClusterArrays) -> None:
        self.arrays: Optional[ClusterArrays] = arrays

    @staticmethod
    def from_file(machine_events_trace_path: str) -> "NativeAlibabaClusterTrace":
        return NativeAlibabaClusterTrace(load_cluster_arrays(machine_events_trace_path))

    def convert_to_simulator_events(self):
        arrays, self.arrays = self.arrays, None
        if arrays is None:
            return []
        return cluster_events_from_arrays(arrays)

    def event_count(self) -> int:
        return 0 if self.arrays is None else len(self.arrays.ts)
