"""Mosaic kernel for the cluster-autoscaler scale-down walk.

The batched scale-down (`batched/autoscale.py _ca_scale_down`, reference
semantics: src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:242-290)
walks CA candidate nodes in node-name order; each under-utilized candidate
tries to first-fit its (<= K_sd) pods onto OTHER alive nodes in name order,
committing the virtual-allocatable deductions on success so later candidates
see them. The dependence chain is real — but the XLA formulation is a
`while_loop` over S candidate slots with an inner K_sd-step scan: up to
S x K_sd sequential launches of tiny (C, N) ops, measured at ~29 ms/window
on the composed flagship shape (C=256, N=96, S=64, K_sd=8) — ~75% of the
whole composed window cost.

Here the walk runs INSIDE one kernel: clusters ride the 128-wide lane axis
(the house transposed layout of ops/scheduler_kernel.py), nodes ride the
sublane axis, and the sequential candidate/pod iterations are in-kernel
loops over VMEM-resident tiles with zero per-iteration dispatch cost. Pod
requirements per candidate are pre-gathered to (S*K_sd, C) tables by cheap
vectorized XLA gathers, so the kernel never touches the (C, P) pod axis.

Semantics are bit-identical to the XLA path: same one-hot candidate mask,
same lowest-index tie-break on equal name ranks, same commit/rollback per
candidate, same early bound at the last alive candidate. The utilization
threshold compare runs in float32 in both paths (autoscale.py casts
ca_threshold to f32 for the compare so kernel and XLA agree bit-for-bit).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64 as jax_enable_x64_ctx
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # clusters per grid program (lane tile)
_SUB = 8  # f32/i32 sublane tile
_BIG_I32 = np.iinfo(np.int32).max
_VMEM_LIMIT = 100 * 1024 * 1024

# pltpu.CompilerParams in newer JAX, TPUCompilerParams in the 0.4.x line.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def ca_down_kernel_fits(n_nodes: int, n_slots: int, k_sd: int) -> bool:
    """VMEM fits-check: 9 node tiles (7 in + 2 scratch working
    allocatables), 4 slot tiles, 3 (S*K) pod tables, meta — double-buffered
    by Mosaic, ~40% headroom against the raised scoped limit."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    sp_pad = -(-n_slots // _SUB) * _SUB
    skp = -(-(n_slots * k_sd) // _SUB) * _SUB
    resident = (9 * np_pad + 4 * sp_pad + 3 * skp + _SUB) * _LANE * 4
    return 2 * resident <= int(0.8 * _VMEM_LIMIT)


def _ca_down_kernel(
    k_sd: int,
    meta_ref,        # (8, LC) f32: row0 branch(0/1), row1 threshold
    alive_ref,       # (Np, LC) int32 0/1
    notpend_ref,     # (Np, LC) int32 0/1 (no pending removal effect)
    cap_cpu_ref,     # (Np, LC) int32
    cap_ram_ref,     # (Np, LC) int32
    vcpu_ref,        # (Np, LC) int32 storage-visible virtual allocatable
    vram_ref,        # (Np, LC) int32
    rank_ref,        # (Np, LC) int32 node-name rank (BIG on padding)
    slot_ref,        # (Sp, LC) int32 global node slot per name-ordered candidate; -1 pad
    cand_alive_ref,  # (Sp, LC) int32 0/1
    cnt_ref,         # (Sp, LC) int32 pods on candidate
    prc_ref,         # (SKp, LC) int32 pod req cpu, row s*k_sd+k
    prr_ref,         # (SKp, LC) int32 pod req ram
    pv0_ref,         # (SKp, LC) int32 0/1 pod-slot valid (k < cnt)
    removed_out,     # (Sp, LC) int32
    vcpu_s,          # (Np, LC) int32 VMEM scratch: working virtual allocatable
    vram_s,          # (Np, LC) int32 VMEM scratch
):
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    bigi = jnp.int32(_BIG_I32)
    f1 = jnp.float32(1.0)
    Ki = jnp.int32(k_sd)

    branch = meta_ref[0:1, :] != jnp.float32(0.0)  # (1, LC)
    thresh = meta_ref[1:2, :]  # (1, LC) f32

    alive = alive_ref[:] != i0  # (Np, LC)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    vcpu_s[:] = vcpu_ref[:]
    vram_s[:] = vram_ref[:]
    removed_out[:] = jnp.zeros_like(removed_out)

    # Walk bound: position after the LAST alive candidate in name order
    # across the tile's lanes (dead/pad candidates inside the bound no-op
    # through the eligibility gate — same bound as the XLA while_loop).
    iota_s = jax.lax.broadcasted_iota(jnp.int32, cand_alive_ref.shape, 0)
    s_bound = jnp.max(jnp.where(cand_alive_ref[:] != i0, iota_s + i1, i0))

    def candidate(s):
        slot = slot_ref[pl.ds(s, 1), :]  # (1, LC)
        oh = iota_n == slot  # (Np, LC); slot=-1 matches nothing
        ohi = oh.astype(jnp.int32)
        alive_here = (cand_alive_ref[pl.ds(s, 1), :] != i0) & branch
        not_pend = jnp.max(ohi * notpend_ref[:], axis=0, keepdims=True) > i0

        # Integer subtract THEN cast, exactly like the XLA path's
        # (cap - valloc).astype(f32) / max(cap, 1).astype(f32).
        cap_c = jnp.max(ohi * cap_cpu_ref[:], axis=0, keepdims=True)
        cap_r = jnp.max(ohi * cap_ram_ref[:], axis=0, keepdims=True)
        vc_at = jnp.max(
            jnp.where(oh, vcpu_s[:], -bigi), axis=0, keepdims=True
        )
        vr_at = jnp.max(
            jnp.where(oh, vram_s[:], -bigi), axis=0, keepdims=True
        )
        used_c = (cap_c - vc_at).astype(jnp.float32)
        used_r = (cap_r - vr_at).astype(jnp.float32)
        capc = jnp.maximum(cap_c, i1).astype(jnp.float32)
        capr = jnp.maximum(cap_r, i1).astype(jnp.float32)
        util = jnp.maximum(used_c / capc, used_r / capr)
        eligible = alive_here & not_pend & (util < thresh)

        cnt = cnt_ref[pl.ds(s, 1), :]  # (1, LC)
        attempt = eligible & (cnt <= Ki)  # overflow: conservatively skip

        vc = vcpu_s[:]
        vr = vram_s[:]
        ok = attempt
        for k in range(k_sd):  # static unroll; K_sd is small (default 8)
            row = pl.ds(s * Ki + jnp.int32(k), 1)
            rc = prc_ref[row, :]
            rr = prr_ref[row, :]
            pv = (pv0_ref[row, :] != i0) & attempt
            fit = alive & ~oh & (rc <= vc) & (rr <= vr)
            # First-fit in NODE-NAME order, lowest-index tie-break (exactly
            # lax.argmin over the masked rank in the XLA path).
            mrank = jnp.min(
                jnp.where(fit, rank_ref[:], bigi), axis=0, keepdims=True
            )
            any_fit = mrank < bigi
            mini = jnp.min(
                jnp.where(fit & (rank_ref[:] == mrank), iota_n, bigi),
                axis=0,
                keepdims=True,
            )
            place = pv & any_fit
            tgt = place & (iota_n == mini)
            vc = vc - jnp.where(tgt, rc, i0)
            vr = vr - jnp.where(tgt, rr, i0)
            ok = ok & (~pv | any_fit)

        # Commit on success, roll back otherwise; commits persist across
        # later candidates (reference :141-156).
        success = ok  # attempt folded in at init
        vcpu_s[:] = jnp.where(success, vc, vcpu_s[:])
        vram_s[:] = jnp.where(success, vr, vram_s[:])
        removed_out[pl.ds(s, 1), :] = success.astype(jnp.int32)

    def loop_body(s):
        candidate(s)
        return s + i1

    jax.lax.while_loop(lambda s: s < s_bound, loop_body, jnp.int32(0))


def _pad_axis(x: jnp.ndarray, axis: int, to: int, value) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("k_sd", "interpret"))
def fused_ca_scale_down(
    branch: jnp.ndarray,      # (C, 1) bool/int32
    thresh: jnp.ndarray,      # (C, 1) float32
    alive: jnp.ndarray,       # (C, N) bool/int32
    not_pending: jnp.ndarray, # (C, N) bool/int32
    cap_cpu: jnp.ndarray,     # (C, N) int32
    cap_ram: jnp.ndarray,     # (C, N) int32
    vcpu: jnp.ndarray,        # (C, N) int32 storage-visible virtual allocatable
    vram: jnp.ndarray,        # (C, N) int32
    name_rank: jnp.ndarray,   # (C, N) int32
    slot_perm: jnp.ndarray,   # (C, S) int32
    cand_alive: jnp.ndarray,  # (C, S) bool/int32
    cnt: jnp.ndarray,         # (C, S) int32
    pr_cpu: jnp.ndarray,      # (C, S*K) int32
    pr_ram: jnp.ndarray,      # (C, S*K) int32
    pv0: jnp.ndarray,         # (C, S*K) bool/int32
    k_sd: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns removed_perm (C, S) bool: candidates (in name order) whose
    pods all re-placed and that the walk removes."""
    C, N = alive.shape
    S = slot_perm.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Sp = -(-S // _SUB) * _SUB
    SKp = -(-(S * k_sd) // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.T, 0, n_sub, fill), 1, Cp, fill)

    meta = jnp.concatenate(
        [
            branch.astype(jnp.float32).T,
            jnp.broadcast_to(thresh.astype(jnp.float32).T, (1, C)),
        ],
        axis=0,
    )
    meta_p = _pad_axis(_pad_axis(meta, 0, _SUB, 0.0), 1, Cp, 0.0)
    args = (
        meta_p,
        prep(alive.astype(jnp.int32), Np, 0),
        prep(not_pending.astype(jnp.int32), Np, 0),
        prep(cap_cpu.astype(jnp.int32), Np, 0),
        prep(cap_ram.astype(jnp.int32), Np, 0),
        prep(vcpu.astype(jnp.int32), Np, 0),
        prep(vram.astype(jnp.int32), Np, 0),
        prep(name_rank.astype(jnp.int32), Np, _BIG_I32),
        prep(slot_perm.astype(jnp.int32), Sp, -1),
        prep(cand_alive.astype(jnp.int32), Sp, 0),
        prep(cnt.astype(jnp.int32), Sp, 0),
        prep(pr_cpu.astype(jnp.int32), SKp, 0),
        prep(pr_ram.astype(jnp.int32), SKp, 0),
        prep(pv0.astype(jnp.int32), SKp, 0),
    )

    meta_spec = pl.BlockSpec((_SUB, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    slot_spec = pl.BlockSpec((Sp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    sk_spec = pl.BlockSpec((SKp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    with jax_enable_x64_ctx(False):
        removed_o = pl.pallas_call(
            functools.partial(_ca_down_kernel, k_sd),
            grid=(Cp // _LANE,),
            in_specs=[meta_spec] + [node_spec] * 7 + [slot_spec] * 3 + [sk_spec] * 3,
            out_specs=slot_spec,
            out_shape=jax.ShapeDtypeStruct((Sp, Cp), jnp.int32),
            scratch_shapes=[
                pltpu.VMEM((Np, _LANE), jnp.int32),
                pltpu.VMEM((Np, _LANE), jnp.int32),
            ],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_VMEM_LIMIT
            ),
            interpret=interpret,
        )(*args)

    return removed_o[:S, :C].T != 0


def ca_up_kernel_fits(n_slots: int, n_groups: int, k_up: int) -> bool:
    """VMEM fits-check for the scale-up kernel: 4 slot tiles (planned out +
    plan_seq/alloc-cpu/alloc-ram scratch), 8 group tiles (7 in + gpl out),
    3 (K_up) candidate tables, and 3 (_SUB x _LANE) meta tiles — the meta
    input, the scal scratch, AND the starved_out output tile (added with
    the reserve-starvation counter) — double-buffered, ~40% headroom."""
    sp_pad = -(-n_slots // _SUB) * _SUB
    gp_pad = -(-n_groups // _SUB) * _SUB
    kp_pad = -(-k_up // _SUB) * _SUB
    resident = (4 * sp_pad + 8 * gp_pad + 3 * kp_pad + 3 * _SUB) * _LANE * 4
    return 2 * resident <= int(0.8 * _VMEM_LIMIT)


def _ca_up_kernel(
    meta_ref,      # (8, LC) int32: row0 ca_max_nodes
    count_ref,     # (Gp, LC) int32 live CA nodes per group
    cursor_ref,    # (Gp, LC) int32 next reserved slot offset per group
    gmax_ref,      # (Gp, LC) int32 group max count (<0 unbounded; pad 0)
    gslots_ref,    # (Gp, LC) int32 reserved slots per group (pad 0)
    tmplc_ref,     # (Gp, LC) int32 template cpu
    tmplr_ref,     # (Gp, LC) int32 template ram
    gstart_ref,    # (Gp, LC) int32 first CA slot of group
    cvalid_ref,    # (Kp, LC) int32 0/1 cache candidate valid (a prefix)
    crc_ref,       # (Kp, LC) int32 candidate req cpu
    crr_ref,       # (Kp, LC) int32 candidate req ram
    planned_out,   # (Sp, LC) int32
    gpl_out,       # (Gp, LC) int32 planned per group
    starved_out,   # (8, LC) int32 row0: reserve-starved open attempts
    seq_ref,       # (Sp, LC) int32 scratch: plan order
    pcpu_ref,      # (Sp, LC) int32 scratch: virtual allocatable cpu
    pram_ref,      # (Sp, LC) int32 scratch: virtual allocatable ram
    scal_ref,      # (8, LC) int32 scratch: row0 total, row1 counter
):
    """First-fit bin-packing scale-up over the name-ordered unscheduled
    cache (reference: kube_cluster_autoscaler.rs:190-240), one in-kernel
    loop instead of the XLA while_loop's K_up sequential (C, S) passes.
    Same decision order as the XLA body: fit into already-planned nodes in
    plan order, else open a node from the FIRST group that accepts the pod
    (min-index over the eligibility mask == lax.argmax over bool); the new
    node joins at FULL template allocatable (the triggering pod is NOT
    packed into it — reference quirk, kube_cluster_autoscaler.rs:210-218)."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    bigi = jnp.int32(_BIG_I32)

    planned_out[:] = jnp.zeros_like(planned_out)
    gpl_out[:] = jnp.zeros_like(gpl_out)
    starved_out[:] = jnp.zeros_like(starved_out)
    seq_ref[:] = jnp.zeros_like(seq_ref) + bigi
    pcpu_ref[:] = jnp.zeros_like(pcpu_ref)
    pram_ref[:] = jnp.zeros_like(pram_ref)
    scal_ref[:] = jnp.zeros_like(scal_ref)
    # total0 = live CA nodes, ALL groups (max_node_count bounds CA-owned
    # nodes only — reference quirk, kube_cluster_autoscaler.rs:62-80).
    scal_ref[0:1, :] = jnp.sum(count_ref[:], axis=0, keepdims=True)

    max_nodes = meta_ref[0:1, :]
    iota_s = jax.lax.broadcasted_iota(jnp.int32, planned_out.shape, 0)
    iota_g = jax.lax.broadcasted_iota(jnp.int32, gpl_out.shape, 0)

    # Candidates are a per-lane prefix of the name-ordered cache sort, so
    # the deepest lane's count bounds the loop (same as the XLA k_bound).
    k_bound = jnp.max(jnp.sum(cvalid_ref[:], axis=0, keepdims=True))

    def candidate(k):
        row = pl.ds(k, 1)
        valid = cvalid_ref[row, :] != i0  # (1, LC)
        rc = crc_ref[row, :]
        rr = crr_ref[row, :]

        # First-fit into already-planned nodes, in plan (seq) order.
        fit = (
            (planned_out[:] != i0)
            & (rc <= pcpu_ref[:])
            & (rr <= pram_ref[:])
        )
        minseq = jnp.min(jnp.where(fit, seq_ref[:], bigi), axis=0, keepdims=True)
        any_fit = minseq < bigi
        use = valid & any_fit
        place = fit & (seq_ref[:] == minseq) & use
        pcpu_ref[:] = pcpu_ref[:] - jnp.where(place, rc, i0)
        pram_ref[:] = pram_ref[:] - jnp.where(place, rr, i0)

        # Else open a node from the first fitting group. Padding group rows
        # have gslots == 0, so cursor + gpl < gslots excludes them.
        total = scal_ref[0:1, :]
        counter = scal_ref[1:2, :]
        can_open = valid & ~any_fit & (total < max_nodes)
        gcount = count_ref[:] + gpl_out[:]
        # Base eligibility (quota headroom + template fit); g_ok adds the
        # slot-reserve cursor bound — deriving one from the other keeps the
        # starvation counter in lockstep with the open decision (same
        # predicates as the XLA path).
        g_ok_nc = (
            ((gmax_ref[:] < i0) | (gcount < gmax_ref[:]))
            & (rc <= tmplc_ref[:])
            & (rr <= tmplr_ref[:])
        )
        g_ok = g_ok_nc & (cursor_ref[:] + gpl_out[:] < gslots_ref[:])
        first_g = jnp.min(jnp.where(g_ok, iota_g, bigi), axis=0, keepdims=True)
        open_ = can_open & (first_g < bigi)
        # Reserve starvation: a group would accept this pod (with a real
        # reserve, gslots > 0) but its never-reclaimed slot reserve is
        # consumed — the silent-divergence case
        # engine.check_autoscaler_bounds surfaces loudly.
        any_nc = (
            jnp.max(
                jnp.where(g_ok_nc & (gslots_ref[:] > i0), i1, i0),
                axis=0,
                keepdims=True,
            )
            > i0
        )
        starved = can_open & ~(first_g < bigi) & any_nc
        starved_out[0:1, :] = (
            starved_out[0:1, :] + starved.astype(jnp.int32)
        )
        g_oh = (iota_g == first_g) & open_  # (Gp, LC)
        g_ohi = g_oh.astype(jnp.int32)
        s_new = jnp.sum(
            g_ohi * (gstart_ref[:] + cursor_ref[:] + gpl_out[:]),
            axis=0,
            keepdims=True,
        )
        tc = jnp.sum(g_ohi * tmplc_ref[:], axis=0, keepdims=True)
        tr = jnp.sum(g_ohi * tmplr_ref[:], axis=0, keepdims=True)
        s_oh = (iota_s == s_new) & open_  # (Sp, LC)
        planned_out[:] = jnp.where(s_oh, i1, planned_out[:])
        seq_ref[:] = jnp.where(s_oh, counter, seq_ref[:])
        pcpu_ref[:] = jnp.where(s_oh, tc, pcpu_ref[:])
        pram_ref[:] = jnp.where(s_oh, tr, pram_ref[:])
        gpl_out[:] = gpl_out[:] + g_ohi
        opi = open_.astype(jnp.int32)
        scal_ref[0:1, :] = total + opi
        scal_ref[1:2, :] = counter + opi

    def loop_body(k):
        candidate(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("n_slots", "interpret"))
def fused_ca_scale_up(
    max_nodes: jnp.ndarray,  # (C, 1) int32 global CA node quota
    ca_count: jnp.ndarray,   # (C, Gn) int32
    ca_cursor: jnp.ndarray,  # (C, Gn) int32
    ng_max: jnp.ndarray,     # (C, Gn) int32 (<0 unbounded)
    ng_slots: jnp.ndarray,   # (C, Gn) int32
    ng_tmpl_cpu: jnp.ndarray,  # (C, Gn) int32
    ng_tmpl_ram: jnp.ndarray,  # (C, Gn) int32
    ng_start: jnp.ndarray,   # (C, Gn) int32
    cvalid: jnp.ndarray,     # (C, K) bool/int32
    creq_cpu: jnp.ndarray,   # (C, K) int32
    creq_ram: jnp.ndarray,   # (C, K) int32
    n_slots: int = 0,
    interpret: bool = False,
):
    """Returns (planned (C, S) bool, planned_per_group (C, Gn) int32,
    reserve_starved (C, 1) int32 — open attempts blocked ONLY by the
    consumed slot reserve)."""
    C, Gn = ca_count.shape
    K = cvalid.shape[1]
    S = n_slots
    Cp = -(-C // _LANE) * _LANE
    Sp = -(-S // _SUB) * _SUB
    Gp = -(-Gn // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.T, 0, n_sub, fill), 1, Cp, fill)

    meta_p = prep(max_nodes.astype(jnp.int32), _SUB, 0)
    args = (
        meta_p,
        prep(ca_count.astype(jnp.int32), Gp, 0),
        prep(ca_cursor.astype(jnp.int32), Gp, 0),
        prep(ng_max.astype(jnp.int32), Gp, 0),
        prep(ng_slots.astype(jnp.int32), Gp, 0),
        prep(ng_tmpl_cpu.astype(jnp.int32), Gp, 0),
        prep(ng_tmpl_ram.astype(jnp.int32), Gp, 0),
        prep(ng_start.astype(jnp.int32), Gp, 0),
        prep(cvalid.astype(jnp.int32), Kp, 0),
        prep(creq_cpu.astype(jnp.int32), Kp, 0),
        prep(creq_ram.astype(jnp.int32), Kp, 0),
    )

    meta_spec = pl.BlockSpec((_SUB, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    slot_spec = pl.BlockSpec((Sp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    group_spec = pl.BlockSpec((Gp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    with jax_enable_x64_ctx(False):
        planned_o, gpl_o, starved_o = pl.pallas_call(
            _ca_up_kernel,
            grid=(Cp // _LANE,),
            in_specs=[meta_spec] + [group_spec] * 7 + [k_spec] * 3,
            out_specs=[slot_spec, group_spec, meta_spec],
            out_shape=[
                jax.ShapeDtypeStruct((Sp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Gp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((_SUB, Cp), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((Sp, _LANE), jnp.int32),
                pltpu.VMEM((Sp, _LANE), jnp.int32),
                pltpu.VMEM((Sp, _LANE), jnp.int32),
                pltpu.VMEM((_SUB, _LANE), jnp.int32),
            ],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_VMEM_LIMIT
            ),
            interpret=interpret,
        )(*args)

    # starved as (C, 1) so shard_map's uniform (axis, None) out_specs apply.
    return planned_o[:S, :C].T != 0, gpl_o[:Gn, :C].T, starved_o[0:1, :C].T

