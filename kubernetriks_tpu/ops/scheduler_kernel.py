"""Pallas TPU kernel: fused kube-scheduler cycle over a cluster batch.

The batched scheduling cycle (batched/step.py _run_scheduling_cycle, scalar
equivalent reference: src/core/scheduler/scheduler.rs:246-333) is a K-step
sequential loop — pod k's Fit filter + LeastAllocatedResources score +
last-wins argmax (reference: src/core/scheduler/plugin.rs:33-63,
kube_scheduler.rs:140-150) must see the allocatable updates of pods 0..k-1.
As a lax.scan, each of the K iterations round-trips the (C, N) allocatable
arrays through HBM. This kernel runs the whole loop with the node tile pinned
in VMEM: one HBM read and one write of node state per cycle instead of K.

The kernel computes only the state-dependent core (fit/score/argmax +
allocatable updates) and returns per-candidate decisions; the cheap (C,)-
shaped timing/metric mechanics stay in step.py where they replicate the
scan path's float-op ordering bit for bit.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(np.float32(-np.inf))

# Cluster rows per grid program (f32/i32 sublane tile is 8).
_TC = 8
_LANE = 128


def default_enabled() -> bool:
    """Use the kernel when running on a real TPU backend unless overridden
    via KUBERNETRIKS_PALLAS=0/1."""
    env = os.environ.get("KUBERNETRIKS_PALLAS")
    if env is not None:
        return env not in ("0", "false", "off")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _cycle_kernel(
    n_real: int,
    k_pods: int,
    alive_ref,
    alloc_cpu_ref,
    alloc_ram_ref,
    valid_ref,
    req_cpu_ref,
    req_ram_ref,
    cpu_out,
    ram_out,
    assign_out,
    fitany_out,
    best_out,
):
    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    alive = alive_ref[:] != 0  # (TC, Np)
    iota = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 1)
    lane_ok = iota < n_real

    def body(k, _):
        cpu = cpu_out[:]
        ram = ram_out[:]
        req_cpu = req_cpu_ref[:, pl.ds(k, 1)]  # (TC, 1) int32
        req_ram = req_ram_ref[:, pl.ds(k, 1)]
        valid = valid_ref[:, pl.ds(k, 1)] != 0

        fit = alive & (req_cpu <= cpu) & (req_ram <= ram)
        cpu_f = cpu.astype(jnp.float32)
        ram_f = ram.astype(jnp.float32)
        cpu_score = jnp.where(
            cpu > 0, (cpu_f - req_cpu.astype(jnp.float32)) * 100.0 / cpu_f, _NEG_INF
        )
        ram_score = jnp.where(
            ram > 0, (ram_f - req_ram.astype(jnp.float32)) * 100.0 / ram_f, _NEG_INF
        )
        score = jnp.where(fit, (cpu_score + ram_score) * 0.5, _NEG_INF)

        # Last-max-wins argmax over the real lanes (ties resolve to the
        # highest node slot, matching the reference's `>=` sweep).
        max_score = jnp.max(score, axis=1, keepdims=True)
        best = jnp.max(
            jnp.where((score == max_score) & lane_ok, iota, -1),
            axis=1,
            keepdims=True,
        )  # (TC, 1)
        any_fit = jnp.any(fit, axis=1, keepdims=True)  # padded lanes never fit
        assign = valid & any_fit

        upd = assign & (iota == best)
        cpu_out[:] = cpu - jnp.where(upd, req_cpu, 0)
        ram_out[:] = ram - jnp.where(upd, req_ram, 0)
        assign_out[:, pl.ds(k, 1)] = assign.astype(jnp.int32)
        fitany_out[:, pl.ds(k, 1)] = any_fit.astype(jnp.int32)
        best_out[:, pl.ds(k, 1)] = best
        return 0

    jax.lax.fori_loop(0, k_pods, body, 0)


def _pad_axis(x: jnp.ndarray, axis: int, to: int, value) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_schedule_cycle(
    alive: jnp.ndarray,      # (C, N) bool
    alloc_cpu: jnp.ndarray,  # (C, N) int32
    alloc_ram: jnp.ndarray,  # (C, N) int32
    valid: jnp.ndarray,      # (C, K) bool
    req_cpu: jnp.ndarray,    # (C, K) int32
    req_ram: jnp.ndarray,    # (C, K) int32
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run the K-pod scheduling loop in VMEM.

    Returns (assign (C,K) bool, fit_any (C,K) bool, best (C,K) int32,
    new_alloc_cpu (C,N) int32, new_alloc_ram (C,N) int32), identical to the
    lax.scan formulation in batched/step.py.
    """
    C, N = alloc_cpu.shape
    K = valid.shape[1]
    Cp = -(-C // _TC) * _TC
    Np = -(-N // _LANE) * _LANE
    Kp = -(-K // _LANE) * _LANE

    alive_p = _pad_axis(_pad_axis(alive.astype(jnp.int32), 1, Np, 0), 0, Cp, 0)
    cpu_p = _pad_axis(_pad_axis(alloc_cpu, 1, Np, 0), 0, Cp, 0)
    ram_p = _pad_axis(_pad_axis(alloc_ram, 1, Np, 0), 0, Cp, 0)
    valid_p = _pad_axis(_pad_axis(valid.astype(jnp.int32), 1, Kp, 0), 0, Cp, 0)
    reqc_p = _pad_axis(_pad_axis(req_cpu, 1, Kp, 0), 0, Cp, 0)
    reqr_p = _pad_axis(_pad_axis(req_ram, 1, Kp, 0), 0, Cp, 0)

    node_spec = pl.BlockSpec((_TC, Np), lambda i: (i, 0), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((_TC, Kp), lambda i: (i, 0), memory_space=pltpu.VMEM)

    kernel = functools.partial(_cycle_kernel, N, K)
    cpu_o, ram_o, assign_o, fitany_o, best_o = pl.pallas_call(
        kernel,
        grid=(Cp // _TC,),
        in_specs=[node_spec, node_spec, node_spec, cand_spec, cand_spec, cand_spec],
        out_specs=[node_spec, node_spec, cand_spec, cand_spec, cand_spec],
        out_shape=[
            jax.ShapeDtypeStruct((Cp, Np), jnp.int32),
            jax.ShapeDtypeStruct((Cp, Np), jnp.int32),
            jax.ShapeDtypeStruct((Cp, Kp), jnp.int32),
            jax.ShapeDtypeStruct((Cp, Kp), jnp.int32),
            jax.ShapeDtypeStruct((Cp, Kp), jnp.int32),
        ],
        interpret=interpret,
    )(alive_p, cpu_p, ram_p, valid_p, reqc_p, reqr_p)

    return (
        assign_o[:C, :K] != 0,
        fitany_o[:C, :K] != 0,
        best_o[:C, :K],
        cpu_o[:C, :N],
        ram_o[:C, :N],
    )
