"""Pallas TPU kernels for the batched simulation's hot loop.

Five kernels, one layout: everything works TRANSPOSED — clusters ride the
128-wide lane dimension (one grid program per 128-cluster tile) and
node/pod/candidate slots ride sublanes, because Mosaic only allows dynamic
slicing (`pl.ds(k, 1)`) on sublane dimensions, and per-lane one-hot
compares replace data-dependent scatters (TPU scatter cost is per-index).
Every kernel carries a data-dependent early exit at the tile's actual work
count, which lax.scan formulations cannot express.

- `_cycle_kernel` (fused_schedule_cycle): the K-pod scheduling loop — pod
  k's compiled-profile filter mask + weighted score (batched/pipeline.py;
  the default profile is Fit + LeastAllocatedResources, reference:
  src/core/scheduler/plugin.rs:33-63) + last-wins argmax
  (kube_scheduler.rs:140-150) must see the allocatable updates of pods
  0..k-1; the node tile stays pinned in VMEM across the loop (one HBM
  round-trip per cycle instead of K). The profile is a kernel static —
  each profile compiles its own kernel, selected at engine build.
- `_select_cycle_kernel` (fused_select_schedule_cycle): the same loop with
  candidate EXTRACTION in-kernel via an iterated per-lane lexicographic
  argmin over the queue keys — the dense-batch default, eliminating the
  (C, P) 3-key sort.
- `_free_kernel` (fused_free_resources): freed pods' requests returned to
  their nodes via one-hot adds + the finished pods' duration-estimator fold.
- `_event_kernel` (fused_event_scatter): one chunk of due trace events
  applied to the per-slot accumulators (five XLA scatters replaced).
- `_commit_kernel` (fused_commit_scatter): the cycle's decisions scattered
  back into the (P,) pod arrays.

The decision kernels return per-candidate outputs; the cheap (C,)-shaped
timing/metric mechanics stay in step.py where they replicate the scan
path's float-op ordering bit for bit. Parity: interpret-mode unit tests +
full-sim equivalence in tests/test_pallas_kernel.py, on-hardware 3-way
check in scripts/check_tpu_parity.py.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64 as jax_enable_x64_ctx
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The compiled scheduler-profile pipeline: profiles lower into the decision
# kernels as statics. pipeline.py imports only core.scheduler (NOT
# batched/state.py), so kernel-only users still dodge the x64 config flip.
from kubernetriks_tpu.batched.pipeline import (
    DEFAULT_PROFILE,
    profile_fit_score,
)

_NEG_INF = float(np.float32(-np.inf))

_LANE = 128  # clusters per grid program (lane tile)
_SUB = 8  # f32/i32 sublane tile

# pltpu.CompilerParams in newer JAX, TPUCompilerParams in the 0.4.x line.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def default_enabled() -> bool:
    """Use the kernel when running on a real TPU backend unless overridden
    via KUBERNETRIKS_PALLAS=0/1."""
    from kubernetriks_tpu.flags import flag_tristate

    env = flag_tristate("KUBERNETRIKS_PALLAS")
    if env is not None:
        return env
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Conservative per-core VMEM budget for the kernel's resident blocks; real
# v5e VMEM is ~128 MiB but leave headroom for Mosaic's own buffers and the
# surrounding fusion.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


# Lane-major hot state (KTPU_LANE_MAJOR; state.NODE_HOT_LEAVES): every
# wrapper below historically transposed its node-shaped operands into the
# kernels' one true layout (clusters on lanes) and transposed the node
# outputs back — pallas_call pins default layouts, so XLA materializes each
# of those transposes as a copy (~1.2 ms/window of marshalling at the
# composed shape). With nodes_lane_major=True the caller already carries the
# hot node leaves as (N, C): the wrapper pads WITHOUT transposing (a no-op
# copy at tile-aligned shapes) and returns node outputs lane-major. Pod-,
# candidate- and event-shaped operands keep the row-major convention — their
# producers/consumers in step.py are row-major-shaped sorts and gathers.
def _prep_node(x, lane_major: bool, n_sub: int, n_lane: int, fill):
    x = x.astype(jnp.int32)
    if not lane_major:
        x = x.T
    return _pad_axis(_pad_axis(x, 0, n_sub, fill), 1, n_lane, fill)


def _unprep_node(x, lane_major: bool, n: int, c: int):
    out = x[:n, :c]
    return out if lane_major else out.T


def kernel_fits(n_nodes: int, k_pods: int) -> bool:
    """Whether one grid program's VMEM blocks (5 node blocks of (Np, 128) +
    6 candidate blocks of (Kp, 128), all int32) fit the budget; callers fall
    back to the lax.scan formulation when they don't."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    kp_pad = -(-k_pods // _SUB) * _SUB
    resident = (5 * np_pad + 6 * kp_pad) * _LANE * 4
    return resident <= _VMEM_BUDGET_BYTES


def _fit_score_place(profile, alive, node_ok, iota_n, cpu, ram, rc, rr, valid):
    """ONE in-kernel definition of the per-candidate decision core shared by
    _cycle_kernel, _select_cycle_kernel and _select_cycle_commit_kernel:
    the compiled profile's filter mask + weighted score
    (batched/pipeline.py — the default profile is Fit +
    LeastAllocatedResources, reference plugin.rs:33-63) + last-max-wins
    argmax (ties resolve to the highest node slot, matching the
    reference's `>=` sweep over name-sorted nodes) + the allocatable
    update for the placed node. `profile` is a kernel STATIC (a
    pipeline.CompiledProfile closed over via functools.partial); its
    expressions inline into the kernel body like the shape statics do.
    Inputs: (Np, LC) node tiles, (1, LC) candidate requests/validity.
    Returns (assign (1, LC) bool, any_fit (1, LC) bool, best (1, LC) i32,
    new_cpu (Np, LC), new_ram (Np, LC))."""
    i0 = jnp.int32(0)
    neg1 = jnp.int32(-1)

    fit, score = profile_fit_score(profile, alive, cpu, ram, rc, rr)
    max_score = jnp.max(score, axis=0, keepdims=True)
    best = jnp.max(
        jnp.where((score == max_score) & node_ok, iota_n, neg1),
        axis=0,
        keepdims=True,
    )
    # any() lowers to an i1 reduction Mosaic rejects; reduce in i32. Padded
    # slots never fit (alive is 0 there).
    any_fit = jnp.max(fit.astype(jnp.int32), axis=0, keepdims=True) > i0
    assign = valid & any_fit
    upd = assign & (iota_n == best)
    new_cpu = cpu - jnp.where(upd, rc, i0)
    new_ram = ram - jnp.where(upd, rr, i0)
    return assign, any_fit, best, new_cpu, new_ram


def _cycle_kernel(
    n_real: int,
    k_pods: int,
    profile,        # pipeline.CompiledProfile (kernel static)
    alive_ref,      # (Np, LC) int32
    alloc_cpu_ref,  # (Np, LC) int32
    alloc_ram_ref,  # (Np, LC) int32
    valid_ref,      # (Kp, LC) int32
    req_cpu_ref,    # (Kp, LC) int32
    req_ram_ref,    # (Kp, LC) int32
    cpu_out,        # (Np, LC) int32
    ram_out,        # (Np, LC) int32
    assign_out,     # (Kp, LC) int32
    fitany_out,     # (Kp, LC) int32
    best_out,       # (Kp, LC) int32
):
    # All literals are explicitly typed: with jax_enable_x64 on (the batched
    # path's time arrays are f64), bare Python scalars trace as weak i64/f64
    # constants, which Mosaic cannot lower inside the kernel.
    i0 = jnp.int32(0)

    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    alive = alive_ref[:] != i0  # (Np, LC)
    iota = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    node_ok = iota < jnp.int32(n_real)  # padded sublanes are never real nodes

    # Outputs must be fully initialized even for skipped iterations.
    assign_out[:] = jnp.zeros_like(assign_out)
    fitany_out[:] = jnp.zeros_like(fitany_out)
    best_out[:] = jnp.zeros_like(best_out)

    # The loop only needs to reach the tile's last valid candidate — a
    # data-dependent early exit the lax.scan formulation cannot express.
    # prepare_cycle sorts eligible pods first, so valid is a per-cluster
    # prefix and typical cycles have far fewer pending pods than the static
    # K budget. Skipped iterations leave assign/fitany/best zeroed, which the
    # callers never read (they gate every consumer on `valid`).
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (valid_ref.shape[0], valid_ref.shape[1]), 0)
    k_live = jnp.max(jnp.where(valid_ref[:] != i0, iota_k + jnp.int32(1), i0))
    k_bound = jnp.minimum(k_live, jnp.int32(k_pods))

    def body(k):
        req_cpu = req_cpu_ref[pl.ds(k, 1), :]  # (1, LC) int32
        req_ram = req_ram_ref[pl.ds(k, 1), :]
        valid = valid_ref[pl.ds(k, 1), :] != i0

        assign, any_fit, best, new_cpu, new_ram = _fit_score_place(
            profile, alive, node_ok, iota, cpu_out[:], ram_out[:],
            req_cpu, req_ram, valid,
        )
        cpu_out[:] = new_cpu
        ram_out[:] = new_ram
        assign_out[pl.ds(k, 1), :] = assign.astype(jnp.int32)
        fitany_out[pl.ds(k, 1), :] = any_fit.astype(jnp.int32)
        best_out[pl.ds(k, 1), :] = best

    # An explicit i32-carried while loop: with jax_enable_x64 on, fori_loop
    # canonicalizes its induction variable to i64, which Mosaic cannot return
    # from the loop-body region.
    def loop_body(k):
        body(k)
        return k + jnp.int32(1)

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


# The selection kernel asks Mosaic for a raised scoped-VMEM limit; its
# fits-check budget must stay at ~40% of that because Mosaic double-buffers
# the grid blocks.
_SELECT_VMEM_LIMIT = 100 * 1024 * 1024


def select_kernel_fits(n_nodes: int, n_pods: int, k_pods: int) -> bool:
    """Whether the selection+cycle kernel's VMEM blocks fit: 6 pod blocks of
    (Pp, 128) + 5 node blocks + 5 candidate output blocks + 1 pod scratch,
    all int32, double-buffered across grid programs by Mosaic. The pod
    blocks dominate; the budget is more generous than the candidate
    kernel's because this kernel REPLACES the (C, P) lexsort and gathers,
    so its win grows with P (v5e VMEM is ~128 MiB/core)."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    pp_pad = -(-n_pods // _SUB) * _SUB
    kp_pad = -(-k_pods // _SUB) * _SUB
    resident = (5 * np_pad + 7 * pp_pad + 5 * kp_pad) * _LANE * 4
    return 2 * resident <= int(0.8 * _SELECT_VMEM_LIMIT)


def _select_cycle_kernel(
    n_nodes: int,
    k_pods: int,
    profile,        # pipeline.CompiledProfile (kernel static)
    alive_ref,      # (Np, LC) int32
    alloc_cpu_ref,  # (Np, LC) int32
    alloc_ram_ref,  # (Np, LC) int32
    elig_ref,       # (Pp, LC) int32 0/1
    qwin_ref,       # (Pp, LC) int32 queue_ts.win
    qoff_ref,       # (Pp, LC) int32 BITCAST of queue_ts.off (non-negative
                    #  f32, so the bit pattern orders identically to the float)
    qseq_ref,       # (Pp, LC) int32
    preq_cpu_ref,   # (Pp, LC) int32
    preq_ram_ref,   # (Pp, LC) int32
    cpu_out,        # (Np, LC) int32
    ram_out,        # (Np, LC) int32
    cand_out,       # (Kp, LC) int32 selected pod slot
    valid_out,      # (Kp, LC) int32
    assign_out,     # (Kp, LC) int32
    fitany_out,     # (Kp, LC) int32
    best_out,       # (Kp, LC) int32
    rem_ref,        # (Pp, LC) int32 scratch: not-yet-selected eligible pods
):
    """Fused queue selection + scheduling cycle: candidate k is extracted
    IN-KERNEL by an iterated per-lane lexicographic argmin over
    (queue win, off, seq) — exactly the sorted order of the batched
    ActiveQueue (step.lexsort_time_i32), seq unique per cluster, so the
    extraction is deterministic — then scheduled against the VMEM-resident
    node tile like _cycle_kernel. Replaces the (C, P) 3-key sort + top-K
    compaction gathers of prepare_cycle with O(live-queue-depth) passes,
    which is where dense shapes spend their fixed per-window cost."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    neg1 = jnp.int32(-1)
    bigi = jnp.int32(np.iinfo(np.int32).max)

    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    alive = alive_ref[:] != i0
    iota_n = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    node_ok = iota_n < jnp.int32(n_nodes)

    cand_out[:] = jnp.zeros_like(cand_out)
    valid_out[:] = jnp.zeros_like(valid_out)
    assign_out[:] = jnp.zeros_like(assign_out)
    fitany_out[:] = jnp.zeros_like(fitany_out)
    best_out[:] = jnp.zeros_like(best_out)
    rem_ref[:] = elig_ref[:]

    iota_p = jax.lax.broadcasted_iota(jnp.int32, elig_ref.shape, 0)
    # Early exit: the deepest per-lane queue in this tile bounds the loop.
    depth = jnp.max(jnp.sum(elig_ref[:], axis=0, keepdims=True))
    k_bound = jnp.minimum(depth, jnp.int32(k_pods))

    def body(k):
        rem = rem_ref[:] != i0  # (Pp, LC)
        # Per-lane lexicographic argmin over (win, off-bits, seq).
        w = jnp.where(rem, qwin_ref[:], bigi)
        minw = jnp.min(w, axis=0, keepdims=True)
        m1 = rem & (qwin_ref[:] == minw)
        o = jnp.where(m1, qoff_ref[:], bigi)
        mino = jnp.min(o, axis=0, keepdims=True)
        m2 = m1 & (qoff_ref[:] == mino)
        s = jnp.where(m2, qseq_ref[:], bigi)
        mins = jnp.min(s, axis=0, keepdims=True)
        sel = m2 & (qseq_ref[:] == mins)  # exactly one row per non-empty lane

        seli = sel.astype(jnp.int32)
        slot = jnp.max(jnp.where(sel, iota_p, neg1), axis=0, keepdims=True)
        valid = slot >= i0  # (1, LC)
        rc = jnp.max(seli * preq_cpu_ref[:], axis=0, keepdims=True)
        rr = jnp.max(seli * preq_ram_ref[:], axis=0, keepdims=True)

        assign, any_fit, best, new_cpu, new_ram = _fit_score_place(
            profile, alive, node_ok, iota_n, cpu_out[:], ram_out[:],
            rc, rr, valid,
        )
        cpu_out[:] = new_cpu
        ram_out[:] = new_ram
        cand_out[pl.ds(k, 1), :] = jnp.where(valid, slot, i0)
        valid_out[pl.ds(k, 1), :] = valid.astype(jnp.int32)
        assign_out[pl.ds(k, 1), :] = assign.astype(jnp.int32)
        fitany_out[pl.ds(k, 1), :] = any_fit.astype(jnp.int32)
        best_out[pl.ds(k, 1), :] = best
        rem_ref[:] = jnp.where(sel, i0, rem_ref[:])

    def loop_body(k):
        body(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(
    jax.jit,
    static_argnames=("k_pods", "interpret", "nodes_lane_major", "profile"),
)
def fused_select_schedule_cycle(
    alive: jnp.ndarray,      # (C, N) bool — (N, C) when nodes_lane_major
    alloc_cpu: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    alloc_ram: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    eligible: jnp.ndarray,   # (C, P) bool
    qwin: jnp.ndarray,       # (C, P) int32
    qoff: jnp.ndarray,       # (C, P) float32 (non-negative)
    qseq: jnp.ndarray,       # (C, P) int32
    pod_req_cpu: jnp.ndarray,  # (C, P) int32
    pod_req_ram: jnp.ndarray,  # (C, P) int32
    k_pods: int,
    interpret: bool = False,
    nodes_lane_major: bool = False,
    profile=None,  # pipeline.CompiledProfile; None = the default profile
):
    """Fused selection + scheduling loop in VMEM.

    Returns (cand (C,K) int32 pod slots, valid (C,K) bool, assign (C,K) bool,
    fit_any (C,K) bool, best (C,K) int32, new_alloc_cpu, new_alloc_ram) —
    valid rows identical to prepare_cycle's sorted top-K compaction followed
    by the lax.scan/_cycle_kernel loop (invalid rows are zeroed; every
    consumer gates on valid). With nodes_lane_major the node operands arrive
    and the allocatables return in (N, C) lane-major layout (no transposes
    at this boundary — see _prep_node)."""
    C, P = eligible.shape
    N = alloc_cpu.shape[0] if nodes_lane_major else alloc_cpu.shape[1]
    K = k_pods
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Pp = -(-P // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.astype(jnp.int32).T, 0, n_sub, fill), 1, Cp, fill)

    alive_p = _prep_node(alive, nodes_lane_major, Np, Cp, 0)
    cpu_p = _prep_node(alloc_cpu, nodes_lane_major, Np, Cp, 0)
    ram_p = _prep_node(alloc_ram, nodes_lane_major, Np, Cp, 0)
    elig_p = prep(eligible, Pp, 0)
    qwin_p = prep(qwin, Pp, 0)
    # Non-negative f32 bit patterns sort like the floats; move them through
    # the kernel as i32 so every block shares one dtype.
    qoff_p = prep(jax.lax.bitcast_convert_type(qoff, jnp.int32), Pp, 0)
    qseq_p = prep(qseq, Pp, 0)
    reqc_p = prep(pod_req_cpu, Pp, 0)
    reqr_p = prep(pod_req_ram, Pp, 0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((Pp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(
        _select_cycle_kernel, N, K, profile or DEFAULT_PROFILE
    )
    with jax_enable_x64_ctx(False):
        cpu_o, ram_o, cand_o, valid_o, assign_o, fitany_o, best_o = pl.pallas_call(
            kernel,
            grid=(Cp // _LANE,),
            in_specs=[node_spec] * 3 + [pod_spec] * 6,
            out_specs=[node_spec] * 2 + [cand_spec] * 5,
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
            ],
            scratch_shapes=[pltpu.VMEM((Pp, _LANE), jnp.int32)],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(alive_p, cpu_p, ram_p, elig_p, qwin_p, qoff_p, qseq_p, reqc_p, reqr_p)

    return (
        cand_o[:K, :C].T,
        valid_o[:K, :C].T != 0,
        assign_o[:K, :C].T != 0,
        fitany_o[:K, :C].T != 0,
        best_o[:K, :C].T,
        _unprep_node(cpu_o, nodes_lane_major, N, C),
        _unprep_node(ram_o, nodes_lane_major, N, C),
    )


def free_kernel_fits(n_nodes: int, n_pods: int) -> bool:
    """VMEM fits-check for the freed-resource kernel: 7 pod blocks (incl.
    finish mask, estimator values and scratch) + 4 node blocks,
    double-buffered by Mosaic, plus stack temporaries for the loop body's
    (Pp, LC) masks — the kernel raises the scoped limit to
    _SELECT_VMEM_LIMIT, the check keeps ~40% headroom."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    pp_pad = -(-n_pods // _SUB) * _SUB
    resident = (7 * pp_pad + 4 * np_pad) * _LANE * 4
    return 2 * resident <= int(0.8 * _SELECT_VMEM_LIMIT)


def _free_kernel(
    freed_ref,     # (Pp, LC) int32 0/1
    node_ref,      # (Pp, LC) int32 assigned node slot
    reqc_ref,      # (Pp, LC) int32
    reqr_ref,      # (Pp, LC) int32
    finish_ref,    # (Pp, LC) int32 0/1 (finishes subset of freed)
    value_ref,     # (Pp, LC) float32 estimator sample (pod duration seconds)
    acpu_ref,      # (Np, LC) int32
    aram_ref,      # (Np, LC) int32
    acpu_out,      # (Np, LC) int32
    aram_out,      # (Np, LC) int32
    stats_out,     # (8, LC) float32: rows count/total/total_sq/min/max
    rem_ref,       # (Pp, LC) int32 scratch
):
    """Return freed pods' requests to their nodes' allocatable — the batched
    analog of the per-event resource release (reference:
    src/core/node_component.rs finish/removal handling). Replaces the XLA
    top_k-compaction loop of _apply_window_events, whose per-round
    lax.top_k lowers to a FULL (C, P) sort on TPU (~4 ms/window at dense
    shapes); here each freed pod is extracted by a per-lane first-set-bit
    pass and added via a node one-hot, with a data-dependent early exit at
    the deepest lane's freed count. Integer adds commute, so the result is
    bit-identical to the XLA loop.

    The same iteration also folds the pod-duration estimator samples of the
    FINISHED subset (stats_out rows 0..4: count/total/total_sq/min/max) —
    replacing the five (C, P) masked reductions of _est_add_reduced, whose
    unfused passes cost ~1.5 ms/window at dense shapes. The float32 sums
    accumulate in a different order than XLA's tiled reduction: within the
    documented metric-accumulator tolerance (docs/PARITY.md)."""
    i0 = jnp.int32(0)
    neg1 = jnp.int32(-1)
    bigi = jnp.int32(np.iinfo(np.int32).max)
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    finf = jnp.float32(np.inf)

    acpu_out[:] = acpu_ref[:]
    aram_out[:] = aram_ref[:]
    stats_out[:] = jnp.zeros_like(stats_out)
    stats_out[3:4, :] = stats_out[3:4, :] + finf
    stats_out[4:5, :] = stats_out[4:5, :] - finf
    rem_ref[:] = freed_ref[:]
    iota_p = jax.lax.broadcasted_iota(jnp.int32, freed_ref.shape, 0)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, acpu_ref.shape, 0)
    k_bound = jnp.max(jnp.sum(freed_ref[:], axis=0, keepdims=True))

    def body(k):
        rem = rem_ref[:] != i0
        first = jnp.min(jnp.where(rem, iota_p, bigi), axis=0, keepdims=True)
        sel = rem & (iota_p == first)
        seli = sel.astype(jnp.int32)
        node = jnp.max(jnp.where(sel, node_ref[:], neg1), axis=0, keepdims=True)
        rc = jnp.max(seli * reqc_ref[:], axis=0, keepdims=True)
        rr = jnp.max(seli * reqr_ref[:], axis=0, keepdims=True)
        oh = iota_n == node  # node == -1 (empty lane) matches nothing
        acpu_out[:] = acpu_out[:] + jnp.where(oh, rc, i0)
        aram_out[:] = aram_out[:] + jnp.where(oh, rr, i0)
        rem_ref[:] = jnp.where(sel, i0, rem_ref[:])

        fin = jnp.max(seli * finish_ref[:], axis=0, keepdims=True) > i0
        v = jnp.max(jnp.where(sel, value_ref[:], -finf), axis=0, keepdims=True)
        stats_out[0:1, :] = stats_out[0:1, :] + jnp.where(fin, f1, f0)
        stats_out[1:2, :] = stats_out[1:2, :] + jnp.where(fin, v, f0)
        stats_out[2:3, :] = stats_out[2:3, :] + jnp.where(fin, v * v, f0)
        stats_out[3:4, :] = jnp.minimum(stats_out[3:4, :], jnp.where(fin, v, finf))
        stats_out[4:5, :] = jnp.maximum(stats_out[4:5, :], jnp.where(fin, v, -finf))

    def loop_body(k):
        body(k)
        return k + jnp.int32(1)

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(
    jax.jit, static_argnames=("interpret", "nodes_lane_major")
)
def fused_free_resources(
    freed: jnp.ndarray,      # (C, P) bool
    node: jnp.ndarray,       # (C, P) int32 (>= 0 for freed pods)
    req_cpu: jnp.ndarray,    # (C, P) int32
    req_ram: jnp.ndarray,    # (C, P) int32
    finishes: jnp.ndarray,   # (C, P) bool (the estimator subset of freed)
    value: jnp.ndarray,      # (C, P) float32 estimator sample per pod
    alloc_cpu: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    alloc_ram: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    interpret: bool = False,
    nodes_lane_major: bool = False,
):
    """(new_alloc_cpu, new_alloc_ram, stats (C, 5)) — the allocatables with
    every freed pod's requests added back (bit-identical to the
    top_k-compaction loop) and the finished pods' estimator fold
    (count/total/total_sq/min/max of `value`). With nodes_lane_major the
    allocatables arrive and return (N, C) lane-major (no transposes)."""
    C, P = freed.shape
    N = alloc_cpu.shape[0] if nodes_lane_major else alloc_cpu.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Pp = -(-P // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.T, 0, n_sub, fill), 1, Cp, fill)

    freed_p = prep(freed.astype(jnp.int32), Pp, 0)
    node_p = prep(node.astype(jnp.int32), Pp, -1)
    reqc_p = prep(req_cpu.astype(jnp.int32), Pp, 0)
    reqr_p = prep(req_ram.astype(jnp.int32), Pp, 0)
    fin_p = prep(finishes.astype(jnp.int32), Pp, 0)
    val_p = prep(value.astype(jnp.float32), Pp, 0.0)
    acpu_p = _prep_node(alloc_cpu, nodes_lane_major, Np, Cp, 0)
    aram_p = _prep_node(alloc_ram, nodes_lane_major, Np, Cp, 0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((Pp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    stats_spec = pl.BlockSpec((8, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    with jax_enable_x64_ctx(False):
        acpu_o, aram_o, stats_o = pl.pallas_call(
            _free_kernel,
            grid=(Cp // _LANE,),
            in_specs=[pod_spec] * 6 + [node_spec] * 2,
            out_specs=[node_spec] * 2 + [stats_spec],
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((8, Cp), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((Pp, _LANE), jnp.int32)],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(freed_p, node_p, reqc_p, reqr_p, fin_p, val_p, acpu_p, aram_p)

    return (
        _unprep_node(acpu_o, nodes_lane_major, N, C),
        _unprep_node(aram_o, nodes_lane_major, N, C),
        stats_o[:5, :C].T,
    )


def event_kernel_fits(n_nodes: int, n_pods: int, n_events: int) -> bool:
    """VMEM fits-check for the event-scatter kernel: 3 pod in + 3 pod out,
    2 node in + 2 node out, 5 event blocks, int32/f32, double-buffered,
    plus loop-body temporaries (the kernel raises the scoped limit)."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    pp_pad = -(-n_pods // _SUB) * _SUB
    ep_pad = -(-n_events // _SUB) * _SUB
    resident = (6 * pp_pad + 4 * np_pad + 5 * ep_pad) * _LANE * 4
    return 2 * resident <= int(0.8 * _SELECT_VMEM_LIMIT)


# Event kinds, duplicated from batched/state.py (importing it here would pull
# the x64 config flip into kernel-only users).
_EV_CREATE_NODE = 1
_EV_REMOVE_NODE = 2
_EV_CREATE_POD = 3
_EV_REMOVE_POD = 4


def _event_kernel(
    kind_ref,     # (Ep, LC) int32
    slot_ref,     # (Ep, LC) int32 (device coords; out-of-range = drop)
    rel_ref,      # (Ep, LC) float32 effect time rel-seconds
    seq_ref,      # (Ep, LC) int32 queue sequence for creates
    valid_ref,    # (Ep, LC) int32 0/1 (per-lane prefix)
    created_ref,  # (Np, LC) int32
    nrm_ref,      # (Np, LC) float32 node-removal time accumulator (min)
    pcr_ref,      # (Pp, LC) float32 pod-create time accumulator (min)
    pseq_ref,     # (Pp, LC) int32 pod-create seq accumulator (max)
    prm_ref,      # (Pp, LC) float32 pod-removal time accumulator (min)
    created_out,
    nrm_out,
    pcr_out,
    pseq_out,
    prm_out,
):
    """Apply one chunk of due trace events to the per-slot accumulators —
    the Pallas replacement for the five (C, E)-indexed XLA scatters in
    _apply_window_events' chunk body (measured ~5 ms/window at dense
    shapes). Event k is applied across all cluster lanes simultaneously via
    slot one-hots; min/max combiners match the scatter semantics exactly,
    and out-of-range slots (shifted-out sliding-window pods) match no
    one-hot row, reproducing mode='drop'."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)

    created_out[:] = created_ref[:]
    nrm_out[:] = nrm_ref[:]
    pcr_out[:] = pcr_ref[:]
    pseq_out[:] = pseq_ref[:]
    prm_out[:] = prm_ref[:]

    iota_n = jax.lax.broadcasted_iota(jnp.int32, created_ref.shape, 0)
    iota_p = jax.lax.broadcasted_iota(jnp.int32, pcr_ref.shape, 0)
    k_bound = jnp.max(jnp.sum(valid_ref[:], axis=0, keepdims=True))

    def body(k):
        kind = kind_ref[pl.ds(k, 1), :]
        slot = slot_ref[pl.ds(k, 1), :]
        rel = rel_ref[pl.ds(k, 1), :]
        seq = seq_ref[pl.ds(k, 1), :]
        v = valid_ref[pl.ds(k, 1), :] != i0

        is_cn = v & (kind == jnp.int32(_EV_CREATE_NODE))
        is_rn = v & (kind == jnp.int32(_EV_REMOVE_NODE))
        is_cp = v & (kind == jnp.int32(_EV_CREATE_POD))
        is_rp = v & (kind == jnp.int32(_EV_REMOVE_POD))

        oh_n = iota_n == slot
        created_out[:] = jnp.where(oh_n & is_cn, i1, created_out[:])
        nrm_out[:] = jnp.where(
            oh_n & is_rn, jnp.minimum(nrm_out[:], rel), nrm_out[:]
        )
        oh_p = iota_p == slot
        pcr_out[:] = jnp.where(
            oh_p & is_cp, jnp.minimum(pcr_out[:], rel), pcr_out[:]
        )
        pseq_out[:] = jnp.where(
            oh_p & is_cp, jnp.maximum(pseq_out[:], seq), pseq_out[:]
        )
        prm_out[:] = jnp.where(
            oh_p & is_rp, jnp.minimum(prm_out[:], rel), prm_out[:]
        )

    def loop_body(k):
        body(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(
    jax.jit, static_argnames=("interpret", "nodes_lane_major")
)
def fused_event_scatter(
    ev_kind: jnp.ndarray,   # (C, E) int32
    ev_slot: jnp.ndarray,   # (C, E) int32 device coords
    ev_rel: jnp.ndarray,    # (C, E) float32
    ev_seq: jnp.ndarray,    # (C, E) int32
    ev_valid: jnp.ndarray,  # (C, E) bool (per-lane prefix)
    created: jnp.ndarray,       # (C, N) bool — (N, C) when nodes_lane_major
    node_removal: jnp.ndarray,  # (C, N) float32 — (N, C) when nodes_lane_major
    pod_create: jnp.ndarray,    # (C, P) float32
    pod_create_seq: jnp.ndarray,  # (C, P) int32
    pod_removal: jnp.ndarray,   # (C, P) float32
    interpret: bool = False,
    nodes_lane_major: bool = False,
):
    """Returns the five accumulators with this chunk's events applied,
    bit-identical to the XLA scatter formulation. With nodes_lane_major the
    two NODE accumulators arrive and return (N, C) lane-major — the event
    chunk loop carries them in the kernel layout across iterations, so the
    per-iteration transposes vanish (the event columns are per-chunk data
    and keep the row-major convention)."""
    C, E = ev_kind.shape
    N = created.shape[0] if nodes_lane_major else created.shape[1]
    P = pod_create.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Pp = -(-P // _SUB) * _SUB
    Ep = -(-E // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.T, 0, n_sub, fill), 1, Cp, fill)

    def prep_n(x, fill):
        x2 = x if nodes_lane_major else x.T
        return _pad_axis(_pad_axis(x2, 0, Np, fill), 1, Cp, fill)

    f32inf = jnp.float32(np.inf)
    args = (
        prep(ev_kind.astype(jnp.int32), Ep, 0),
        prep(ev_slot.astype(jnp.int32), Ep, -1),
        prep(ev_rel.astype(jnp.float32), Ep, 0.0),
        prep(ev_seq.astype(jnp.int32), Ep, 0),
        prep(ev_valid.astype(jnp.int32), Ep, 0),
        prep_n(created.astype(jnp.int32), 0),
        prep_n(node_removal.astype(jnp.float32), f32inf),
        prep(pod_create.astype(jnp.float32), Pp, f32inf),
        prep(pod_create_seq.astype(jnp.int32), Pp, 0),
        prep(pod_removal.astype(jnp.float32), Pp, f32inf),
    )

    def spec(n_sub):
        return pl.BlockSpec((n_sub, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    shapes = [
        jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
        jax.ShapeDtypeStruct((Np, Cp), jnp.float32),
        jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
        jax.ShapeDtypeStruct((Pp, Cp), jnp.int32),
        jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
    ]
    with jax_enable_x64_ctx(False):
        created_o, nrm_o, pcr_o, pseq_o, prm_o = pl.pallas_call(
            _event_kernel,
            grid=(Cp // _LANE,),
            in_specs=[spec(Ep)] * 5 + [spec(Np)] * 2 + [spec(Pp)] * 3,
            out_specs=[spec(Np)] * 2 + [spec(Pp)] * 3,
            out_shape=shapes,
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(*args)

    return (
        _unprep_node(created_o, nodes_lane_major, N, C) != 0,
        _unprep_node(nrm_o, nodes_lane_major, N, C),
        pcr_o[:P, :C].T,
        pseq_o[:P, :C].T,
        prm_o[:P, :C].T,
    )


def commit_kernel_fits(n_pods: int, k_pods: int) -> bool:
    """VMEM fits-check for the commit-scatter kernel: 2 pod in + 4 pod out +
    6 candidate blocks, double-buffered, plus loop temporaries (the kernel
    raises the scoped limit)."""
    pp_pad = -(-n_pods // _SUB) * _SUB
    kp_pad = -(-k_pods // _SUB) * _SUB
    resident = (6 * pp_pad + 6 * kp_pad) * _LANE * 4
    return 2 * resident <= int(0.8 * _SELECT_VMEM_LIMIT)


# Pod phases, duplicated from batched/state.py (see _EV_* note above).
_PHASE_UNSCHEDULABLE = 2
_PHASE_RUNNING = 3


def _commit_kernel(
    cand_ref,     # (Kp, LC) int32 pod slot
    assign_ref,   # (Kp, LC) int32 0/1
    park_ref,     # (Kp, LC) int32 0/1
    best_ref,     # (Kp, LC) int32 node slot
    start_ref,    # (Kp, LC) float32 start offset rel-seconds
    parks_ref,    # (Kp, LC) float32 park offset rel-seconds
    phase_ref,    # (Pp, LC) int32
    node_ref,     # (Pp, LC) int32
    phase_out,    # (Pp, LC) int32
    node_out,     # (Pp, LC) int32
    start_out,    # (Pp, LC) float32 (+inf = untouched)
    park_out,     # (Pp, LC) float32 (+inf = untouched)
):
    """Scatter the cycle's K per-lane decisions back into the (P,) pod
    arrays — the Pallas replacement for commit_cycle's four (C, K)-indexed
    XLA scatters. Candidate slots are unique within a cycle, so the one-hot
    writes are order-independent and bit-identical to the scatters."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    inf = jnp.float32(np.inf)

    phase_out[:] = phase_ref[:]
    node_out[:] = node_ref[:]
    start_out[:] = jnp.full_like(start_out, inf)
    park_out[:] = jnp.full_like(park_out, inf)

    iota_p = jax.lax.broadcasted_iota(jnp.int32, phase_ref.shape, 0)
    # touched == assign | park == the valid prefix (assign = valid & fit,
    # park = valid & ~fit), so its per-lane count bounds the loop.
    touched_all = (assign_ref[:] + park_ref[:]) > i0
    k_bound = jnp.max(
        jnp.sum(touched_all.astype(jnp.int32), axis=0, keepdims=True)
    )

    def body(k):
        cand = cand_ref[pl.ds(k, 1), :]
        assign = assign_ref[pl.ds(k, 1), :] != i0
        park = park_ref[pl.ds(k, 1), :] != i0
        best = best_ref[pl.ds(k, 1), :]
        start_s = start_ref[pl.ds(k, 1), :]
        park_s = parks_ref[pl.ds(k, 1), :]
        touched = assign | park

        oh = iota_p == cand
        new_phase = jnp.where(
            assign, jnp.int32(_PHASE_RUNNING), jnp.int32(_PHASE_UNSCHEDULABLE)
        )
        phase_out[:] = jnp.where(oh & touched, new_phase, phase_out[:])
        node_out[:] = jnp.where(oh & assign, best, node_out[:])
        start_out[:] = jnp.where(oh & assign, start_s, start_out[:])
        park_out[:] = jnp.where(oh & park, park_s, park_out[:])

    def loop_body(k):
        body(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_commit_scatter(
    cand: jnp.ndarray,     # (C, K) int32
    assign: jnp.ndarray,   # (C, K) bool
    park: jnp.ndarray,     # (C, K) bool
    best: jnp.ndarray,     # (C, K) int32
    start_s: jnp.ndarray,  # (C, K) float32
    park_s: jnp.ndarray,   # (C, K) float32
    phase: jnp.ndarray,    # (C, P) int32
    node: jnp.ndarray,     # (C, P) int32
    interpret: bool = False,
):
    """Returns (phase, node, start_tmp, park_tmp) with the decisions
    applied; start_tmp/park_tmp are +inf where untouched, matching the XLA
    formulation in commit_cycle."""
    C, P = phase.shape
    K = cand.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Pp = -(-P // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.T, 0, n_sub, fill), 1, Cp, fill)

    args = (
        prep(cand.astype(jnp.int32), Kp, -1),
        prep(assign.astype(jnp.int32), Kp, 0),
        prep(park.astype(jnp.int32), Kp, 0),
        prep(best.astype(jnp.int32), Kp, 0),
        prep(start_s.astype(jnp.float32), Kp, 0.0),
        prep(park_s.astype(jnp.float32), Kp, 0.0),
        prep(phase.astype(jnp.int32), Pp, 0),
        prep(node.astype(jnp.int32), Pp, 0),
    )

    def spec(n_sub):
        return pl.BlockSpec((n_sub, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    with jax_enable_x64_ctx(False):
        phase_o, node_o, start_o, park_o = pl.pallas_call(
            _commit_kernel,
            grid=(Cp // _LANE,),
            in_specs=[spec(Kp)] * 6 + [spec(Pp)] * 2,
            out_specs=[spec(Pp)] * 4,
            out_shape=[
                jax.ShapeDtypeStruct((Pp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
            ],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(*args)

    return (
        phase_o[:P, :C].T,
        node_o[:P, :C].T,
        start_o[:P, :C].T,
        park_o[:P, :C].T,
    )


def _pad_axis(x: jnp.ndarray, axis: int, to: int, value) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(
    jax.jit, static_argnames=("interpret", "nodes_lane_major", "profile")
)
def fused_schedule_cycle(
    alive: jnp.ndarray,      # (C, N) bool — (N, C) when nodes_lane_major
    alloc_cpu: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    alloc_ram: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    valid: jnp.ndarray,      # (C, K) bool
    req_cpu: jnp.ndarray,    # (C, K) int32
    req_ram: jnp.ndarray,    # (C, K) int32
    interpret: bool = False,
    nodes_lane_major: bool = False,
    profile=None,  # pipeline.CompiledProfile; None = the default profile
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run the K-pod scheduling loop in VMEM.

    Returns (assign (C,K) bool, fit_any (C,K) bool, best (C,K) int32,
    new_alloc_cpu, new_alloc_ram), identical to the lax.scan formulation in
    batched/step.py. With nodes_lane_major the node operands arrive and the
    allocatables return (N, C) lane-major (no transposes).
    """
    C, K = valid.shape
    N = alloc_cpu.shape[0] if nodes_lane_major else alloc_cpu.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        # (C, n) -> padded transposed (n_sub, Cp) with clusters on lanes.
        return _pad_axis(_pad_axis(x.astype(jnp.int32).T, 0, n_sub, fill), 1, Cp, fill)

    alive_p = _prep_node(alive, nodes_lane_major, Np, Cp, 0)
    cpu_p = _prep_node(alloc_cpu, nodes_lane_major, Np, Cp, 0)
    ram_p = _prep_node(alloc_ram, nodes_lane_major, Np, Cp, 0)
    valid_p = prep(valid, Kp, 0)
    reqc_p = prep(req_cpu, Kp, 0)
    reqr_p = prep(req_ram, Kp, 0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(_cycle_kernel, N, K, profile or DEFAULT_PROFILE)
    # Trace the kernel with x64 semantics OFF: the batched path enables
    # jax_enable_x64 for its f64 time arrays, but under x64 pallas_call's own
    # index bookkeeping traces as i64, which Mosaic fails to legalize
    # (func.return). Everything crossing this boundary is i32/bool.
    # (jax.experimental.enable_x64: the installed 0.4.x has no top-level
    # jax.enable_x64.)
    with jax_enable_x64_ctx(False):
        cpu_o, ram_o, assign_o, fitany_o, best_o = pl.pallas_call(
            kernel,
            grid=(Cp // _LANE,),
            in_specs=[node_spec, node_spec, node_spec, cand_spec, cand_spec, cand_spec],
            out_specs=[node_spec, node_spec, cand_spec, cand_spec, cand_spec],
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
            ],
            interpret=interpret,
        )(alive_p, cpu_p, ram_p, valid_p, reqc_p, reqr_p)

    return (
        assign_o[:K, :C].T != 0,
        fitany_o[:K, :C].T != 0,
        best_o[:K, :C].T,
        _unprep_node(cpu_o, nodes_lane_major, N, C),
        _unprep_node(ram_o, nodes_lane_major, N, C),
    )


# --- round-4 megakernel: selection + cycle + commit in ONE launch -----------

def select_commit_kernel_fits(n_nodes: int, n_pods: int, k_pods: int) -> bool:
    """VMEM budget for the megakernel: ~5 node-shaped + 14 pod-shaped +
    3 K-shaped blocks + the (8, LANE) stats block, double-buffered by
    Mosaic (~2x block bytes)."""
    Np = -(-n_nodes // _SUB) * _SUB
    Pp = -(-n_pods // _SUB) * _SUB
    Kp = -(-k_pods // _SUB) * _SUB
    per_lane_bytes = 2 * (5 * Np + 14 * Pp + 3 * Kp + 8) * 4 * _LANE
    return per_lane_bytes <= int(_SELECT_VMEM_LIMIT * 0.8)


def _argmin_select(rem, qwin_ref, qoff_ref, qseq_ref, iota_p):
    """ONE in-kernel definition of the per-lane lexicographic argmin over
    (queue win, off-bits, seq) — the batched ActiveQueue's sorted order —
    shared by _select_cycle_kernel and _select_cycle_commit_kernel (the
    same dedup _fit_score_place provides for the decision core).
    Returns (sel one-hot (Pp, LC), seli int, slot (1, LC), valid (1, LC))."""
    i0 = jnp.int32(0)
    neg1 = jnp.int32(-1)
    bigi = jnp.int32(np.iinfo(np.int32).max)
    w = jnp.where(rem, qwin_ref[:], bigi)
    minw = jnp.min(w, axis=0, keepdims=True)
    m1 = rem & (qwin_ref[:] == minw)
    o = jnp.where(m1, qoff_ref[:], bigi)
    mino = jnp.min(o, axis=0, keepdims=True)
    m2 = m1 & (qoff_ref[:] == mino)
    sq = jnp.where(m2, qseq_ref[:], bigi)
    mins = jnp.min(sq, axis=0, keepdims=True)
    sel = m2 & (qseq_ref[:] == mins)  # exactly one row per non-empty lane
    seli = sel.astype(jnp.int32)
    slot = jnp.max(jnp.where(sel, iota_p, neg1), axis=0, keepdims=True)
    valid = slot >= i0
    return sel, seli, slot, valid


def _select_cycle_commit_kernel(
    n_nodes: int,
    k_pods: int,
    profile,        # pipeline.CompiledProfile (kernel static)
    alive_ref,      # (Np, LC) int32
    alloc_cpu_ref,  # (Np, LC) int32
    alloc_ram_ref,  # (Np, LC) int32
    elig_ref,       # (Pp, LC) int32 0/1
    qwin_ref,       # (Pp, LC) int32
    qoff_ref,       # (Pp, LC) int32 (bitcast f32, non-negative)
    qseq_ref,       # (Pp, LC) int32
    preq_cpu_ref,   # (Pp, LC) int32
    preq_ram_ref,   # (Pp, LC) int32
    waited_ref,     # (Pp, LC) float32 queue wait at cycle start
    phase_ref,      # (Pp, LC) int32
    node_ref,       # (Pp, LC) int32
    qpre_ref,       # (Kp, LC) float32 positional cd_pre table
    start_ref,      # (Kp, LC) float32 positional start-offset table
    park_ref,       # (Kp, LC) float32 positional park-offset table
    cpu_out,        # (Np, LC) int32
    ram_out,        # (Np, LC) int32
    phase_out,      # (Pp, LC) int32
    node_out,       # (Pp, LC) int32
    start_out,      # (Pp, LC) float32 (+inf = untouched)
    park_out,       # (Pp, LC) float32 (+inf = untouched)
    stats_out,      # (8, LC) float32: count/total/total_sq/min/max of
                    #   queue-time samples over assigned decisions
    rem_ref,        # (Pp, LC) int32 scratch
):
    """The whole-window scheduling megakernel (VERDICT r3 item 2): queue
    SELECTION (iterated 3-key argmin, _select_cycle_kernel), the
    fit/score/place CYCLE, and the decision COMMIT (the per-pod phase/node/
    start/park writes of _commit_kernel — the selection one-hot IS the
    commit's scatter mask) run in one Pallas launch, plus the queue-time
    estimator fold (the free kernel's stats pattern). Replaces two kernel
    launches and the (C, K) timing/metric XLA glue between them.

    Timing bit-exactness: the positional tables qpre/start/park are
    computed OUTSIDE with the same cumsum cycle_timing uses on an all-valid
    mask; valid decisions always form a position prefix, and cumsum outputs
    depend only on their input prefix, so table values at valid positions
    are bit-identical to cycle_timing's. waited is precomputed per pod with
    candidates_from_slots' exact expression. Only the estimator SUMS
    accumulate in loop order instead of XLA's tiled reduction — the
    documented ulp-level metric tolerance (docs/PARITY.md)."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    neg1 = jnp.int32(-1)
    bigi = jnp.int32(np.iinfo(np.int32).max)
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    finf = jnp.float32(np.inf)

    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    phase_out[:] = phase_ref[:]
    node_out[:] = node_ref[:]
    start_out[:] = jnp.full_like(start_out, finf)
    park_out[:] = jnp.full_like(park_out, finf)
    stats_out[:] = jnp.zeros_like(stats_out)
    stats_out[3:4, :] = stats_out[3:4, :] + finf
    stats_out[4:5, :] = stats_out[4:5, :] - finf

    alive = alive_ref[:] != i0
    iota_n = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    node_ok = iota_n < jnp.int32(n_nodes)
    rem_ref[:] = elig_ref[:]
    iota_p = jax.lax.broadcasted_iota(jnp.int32, elig_ref.shape, 0)
    depth = jnp.max(jnp.sum(elig_ref[:], axis=0, keepdims=True))
    k_bound = jnp.minimum(depth, jnp.int32(k_pods))

    def body(k):
        rem = rem_ref[:] != i0
        sel, seli, slot, valid = _argmin_select(
            rem, qwin_ref, qoff_ref, qseq_ref, iota_p
        )
        rc = jnp.max(seli * preq_cpu_ref[:], axis=0, keepdims=True)
        rr = jnp.max(seli * preq_ram_ref[:], axis=0, keepdims=True)

        assign, any_fit, best, new_cpu, new_ram = _fit_score_place(
            profile, alive, node_ok, iota_n, cpu_out[:], ram_out[:],
            rc, rr, valid,
        )
        cpu_out[:] = new_cpu
        ram_out[:] = new_ram
        park = valid & ~any_fit

        # COMMIT: the selection one-hot is the scatter mask.
        new_phase = jnp.where(
            assign, jnp.int32(_PHASE_RUNNING), jnp.int32(_PHASE_UNSCHEDULABLE)
        )
        touched = assign | park
        phase_out[:] = jnp.where(sel & touched, new_phase, phase_out[:])
        node_out[:] = jnp.where(sel & assign, best, node_out[:])
        start_s = start_ref[pl.ds(k, 1), :]
        park_s = park_ref[pl.ds(k, 1), :]
        start_out[:] = jnp.where(sel & assign, start_s, start_out[:])
        park_out[:] = jnp.where(sel & park, park_s, park_out[:])

        # Queue-time estimator fold over assigned decisions.
        waited = jnp.max(
            jnp.where(sel, waited_ref[:], -finf), axis=0, keepdims=True
        )
        qtime = waited + qpre_ref[pl.ds(k, 1), :]
        stats_out[0:1, :] = stats_out[0:1, :] + jnp.where(assign, f1, f0)
        stats_out[1:2, :] = stats_out[1:2, :] + jnp.where(assign, qtime, f0)
        stats_out[2:3, :] = stats_out[2:3, :] + jnp.where(
            assign, qtime * qtime, f0
        )
        stats_out[3:4, :] = jnp.minimum(
            stats_out[3:4, :], jnp.where(assign, qtime, finf)
        )
        stats_out[4:5, :] = jnp.maximum(
            stats_out[4:5, :], jnp.where(assign, qtime, -finf)
        )

        rem_ref[:] = jnp.where(sel, i0, rem_ref[:])

    def loop_body(k):
        body(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(
    jax.jit,
    static_argnames=("k_pods", "interpret", "nodes_lane_major", "profile"),
)
def fused_select_cycle_commit(
    alive: jnp.ndarray,      # (C, N) bool — (N, C) when nodes_lane_major
    alloc_cpu: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    alloc_ram: jnp.ndarray,  # (C, N) int32 — (N, C) when nodes_lane_major
    eligible: jnp.ndarray,   # (C, P) bool
    qwin: jnp.ndarray,       # (C, P) int32
    qoff: jnp.ndarray,       # (C, P) float32 (non-negative)
    qseq: jnp.ndarray,       # (C, P) int32
    pod_req_cpu: jnp.ndarray,   # (C, P) int32
    pod_req_ram: jnp.ndarray,   # (C, P) int32
    waited: jnp.ndarray,     # (C, P) float32
    phase: jnp.ndarray,      # (C, P) int32
    node: jnp.ndarray,       # (C, P) int32
    qpre_t: jnp.ndarray,     # (C, K) float32 positional cd_pre
    start_t: jnp.ndarray,    # (C, K) float32 positional start offsets
    park_t: jnp.ndarray,     # (C, K) float32 positional park offsets
    k_pods: int,
    interpret: bool = False,
    nodes_lane_major: bool = False,
    profile=None,  # pipeline.CompiledProfile; None = the default profile
):
    """Megakernel wrapper. Returns (alloc_cpu, alloc_ram, phase, node,
    start_tmp (+inf untouched), park_tmp, qstats (C, 5)). With
    nodes_lane_major the node operands arrive and the allocatables return
    (N, C) lane-major (no transposes at this boundary)."""
    C, P = eligible.shape
    N = alloc_cpu.shape[0] if nodes_lane_major else alloc_cpu.shape[1]
    K = k_pods
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Pp = -(-P // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.astype(jnp.int32).T, 0, n_sub, fill), 1, Cp, fill)

    def prep_f(x, n_sub, fill):
        return _pad_axis(
            _pad_axis(x.astype(jnp.float32).T, 0, n_sub, fill), 1, Cp, fill
        )

    alive_p = _prep_node(alive, nodes_lane_major, Np, Cp, 0)
    cpu_p = _prep_node(alloc_cpu, nodes_lane_major, Np, Cp, 0)
    ram_p = _prep_node(alloc_ram, nodes_lane_major, Np, Cp, 0)
    elig_p = prep(eligible, Pp, 0)
    qwin_p = prep(qwin, Pp, 0)
    qoff_p = prep(jax.lax.bitcast_convert_type(qoff, jnp.int32), Pp, 0)
    qseq_p = prep(qseq, Pp, 0)
    reqc_p = prep(pod_req_cpu, Pp, 0)
    reqr_p = prep(pod_req_ram, Pp, 0)
    waited_p = prep_f(waited, Pp, 0.0)
    phase_p = prep(phase, Pp, 0)
    node_p = prep(node, Pp, 0)
    qpre_p = prep_f(qpre_t, Kp, 0.0)
    start_p = prep_f(start_t, Kp, 0.0)
    park_p = prep_f(park_t, Kp, 0.0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((Pp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((8, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(
        _select_cycle_commit_kernel, N, K, profile or DEFAULT_PROFILE
    )
    with jax_enable_x64_ctx(False):
        (cpu_o, ram_o, phase_o, node_o, start_o, park_o, stats_o) = pl.pallas_call(
            kernel,
            grid=(Cp // _LANE,),
            in_specs=[node_spec] * 3 + [pod_spec] * 9 + [cand_spec] * 3,
            out_specs=[node_spec] * 2 + [pod_spec] * 4 + [stat_spec],
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
                jax.ShapeDtypeStruct((Pp, Cp), jnp.float32),
                jax.ShapeDtypeStruct((8, Cp), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((Pp, _LANE), jnp.int32)],
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(
            alive_p, cpu_p, ram_p, elig_p, qwin_p, qoff_p, qseq_p,
            reqc_p, reqr_p, waited_p, phase_p, node_p,
            qpre_p, start_p, park_p,
        )

    return (
        _unprep_node(cpu_o, nodes_lane_major, N, C),
        _unprep_node(ram_o, nodes_lane_major, N, C),
        phase_o[:P, :C].T,
        node_o[:P, :C].T,
        start_o[:P, :C].T,
        park_o[:P, :C].T,
        stats_o[:5, :C].T,
    )
