"""Pallas TPU kernel: fused kube-scheduler cycle over a cluster batch.

The batched scheduling cycle (batched/step.py _run_scheduling_cycle, scalar
equivalent reference: src/core/scheduler/scheduler.rs:246-333) is a K-step
sequential loop — pod k's Fit filter + LeastAllocatedResources score +
last-wins argmax (reference: src/core/scheduler/plugin.rs:33-63,
kube_scheduler.rs:140-150) must see the allocatable updates of pods 0..k-1.
As a lax.scan, each of the K iterations round-trips the (C, N) allocatable
arrays through HBM. This kernel runs the whole loop with the node tile pinned
in VMEM: one HBM read and one write of node state per cycle instead of K.

Layout: the kernel works TRANSPOSED — clusters ride the 128-wide lane
dimension (one grid program per 128-cluster tile) and node/candidate slots
ride sublanes, because Mosaic only allows dynamic slicing (the per-iteration
candidate row `pl.ds(k, 1)`) on sublane dimensions; lane-dim indices must be
statically 128-aligned.

The kernel computes only the state-dependent core (fit/score/argmax +
allocatable updates) and returns per-candidate decisions; the cheap (C,)-
shaped timing/metric mechanics stay in step.py where they replicate the
scan path's float-op ordering bit for bit.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(np.float32(-np.inf))

_LANE = 128  # clusters per grid program (lane tile)
_SUB = 8  # f32/i32 sublane tile


def default_enabled() -> bool:
    """Use the kernel when running on a real TPU backend unless overridden
    via KUBERNETRIKS_PALLAS=0/1."""
    env = os.environ.get("KUBERNETRIKS_PALLAS")
    if env is not None:
        return env not in ("0", "false", "off")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Conservative per-core VMEM budget for the kernel's resident blocks; real
# v5e VMEM is ~128 MiB but leave headroom for Mosaic's own buffers and the
# surrounding fusion.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def kernel_fits(n_nodes: int, k_pods: int) -> bool:
    """Whether one grid program's VMEM blocks (5 node blocks of (Np, 128) +
    6 candidate blocks of (Kp, 128), all int32) fit the budget; callers fall
    back to the lax.scan formulation when they don't."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    kp_pad = -(-k_pods // _SUB) * _SUB
    resident = (5 * np_pad + 6 * kp_pad) * _LANE * 4
    return resident <= _VMEM_BUDGET_BYTES


def _fit_score_place(alive, node_ok, iota_n, cpu, ram, rc, rr, valid):
    """ONE in-kernel definition of the per-candidate decision core shared by
    _cycle_kernel and _select_cycle_kernel: Fit filter +
    LeastAllocatedResources score + last-max-wins argmax (ties resolve to
    the highest node slot, matching the reference's `>=` sweep over
    name-sorted nodes) + the allocatable update for the placed node.
    Inputs: (Np, LC) node tiles, (1, LC) candidate requests/validity.
    Returns (assign (1, LC) bool, any_fit (1, LC) bool, best (1, LC) i32,
    new_cpu (Np, LC), new_ram (Np, LC))."""
    i0 = jnp.int32(0)
    neg1 = jnp.int32(-1)
    hundred = jnp.float32(100.0)
    half = jnp.float32(0.5)
    neg_inf = jnp.float32(_NEG_INF)

    fit = alive & (rc <= cpu) & (rr <= ram)
    cpu_f = cpu.astype(jnp.float32)
    ram_f = ram.astype(jnp.float32)
    cpu_score = jnp.where(
        cpu > i0, (cpu_f - rc.astype(jnp.float32)) * hundred / cpu_f, neg_inf
    )
    ram_score = jnp.where(
        ram > i0, (ram_f - rr.astype(jnp.float32)) * hundred / ram_f, neg_inf
    )
    score = jnp.where(fit, (cpu_score + ram_score) * half, neg_inf)
    max_score = jnp.max(score, axis=0, keepdims=True)
    best = jnp.max(
        jnp.where((score == max_score) & node_ok, iota_n, neg1),
        axis=0,
        keepdims=True,
    )
    # any() lowers to an i1 reduction Mosaic rejects; reduce in i32. Padded
    # slots never fit (alive is 0 there).
    any_fit = jnp.max(fit.astype(jnp.int32), axis=0, keepdims=True) > i0
    assign = valid & any_fit
    upd = assign & (iota_n == best)
    new_cpu = cpu - jnp.where(upd, rc, i0)
    new_ram = ram - jnp.where(upd, rr, i0)
    return assign, any_fit, best, new_cpu, new_ram


def _cycle_kernel(
    n_real: int,
    k_pods: int,
    alive_ref,      # (Np, LC) int32
    alloc_cpu_ref,  # (Np, LC) int32
    alloc_ram_ref,  # (Np, LC) int32
    valid_ref,      # (Kp, LC) int32
    req_cpu_ref,    # (Kp, LC) int32
    req_ram_ref,    # (Kp, LC) int32
    cpu_out,        # (Np, LC) int32
    ram_out,        # (Np, LC) int32
    assign_out,     # (Kp, LC) int32
    fitany_out,     # (Kp, LC) int32
    best_out,       # (Kp, LC) int32
):
    # All literals are explicitly typed: with jax_enable_x64 on (the batched
    # path's time arrays are f64), bare Python scalars trace as weak i64/f64
    # constants, which Mosaic cannot lower inside the kernel.
    i0 = jnp.int32(0)

    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    alive = alive_ref[:] != i0  # (Np, LC)
    iota = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    node_ok = iota < jnp.int32(n_real)  # padded sublanes are never real nodes

    # Outputs must be fully initialized even for skipped iterations.
    assign_out[:] = jnp.zeros_like(assign_out)
    fitany_out[:] = jnp.zeros_like(fitany_out)
    best_out[:] = jnp.zeros_like(best_out)

    # The loop only needs to reach the tile's last valid candidate — a
    # data-dependent early exit the lax.scan formulation cannot express.
    # prepare_cycle sorts eligible pods first, so valid is a per-cluster
    # prefix and typical cycles have far fewer pending pods than the static
    # K budget. Skipped iterations leave assign/fitany/best zeroed, which the
    # callers never read (they gate every consumer on `valid`).
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (valid_ref.shape[0], valid_ref.shape[1]), 0)
    k_live = jnp.max(jnp.where(valid_ref[:] != i0, iota_k + jnp.int32(1), i0))
    k_bound = jnp.minimum(k_live, jnp.int32(k_pods))

    def body(k):
        req_cpu = req_cpu_ref[pl.ds(k, 1), :]  # (1, LC) int32
        req_ram = req_ram_ref[pl.ds(k, 1), :]
        valid = valid_ref[pl.ds(k, 1), :] != i0

        assign, any_fit, best, new_cpu, new_ram = _fit_score_place(
            alive, node_ok, iota, cpu_out[:], ram_out[:], req_cpu, req_ram, valid
        )
        cpu_out[:] = new_cpu
        ram_out[:] = new_ram
        assign_out[pl.ds(k, 1), :] = assign.astype(jnp.int32)
        fitany_out[pl.ds(k, 1), :] = any_fit.astype(jnp.int32)
        best_out[pl.ds(k, 1), :] = best

    # An explicit i32-carried while loop: with jax_enable_x64 on, fori_loop
    # canonicalizes its induction variable to i64, which Mosaic cannot return
    # from the loop-body region.
    def loop_body(k):
        body(k)
        return k + jnp.int32(1)

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


# The selection kernel asks Mosaic for a raised scoped-VMEM limit; its
# fits-check budget must stay at ~40% of that because Mosaic double-buffers
# the grid blocks.
_SELECT_VMEM_LIMIT = 100 * 1024 * 1024


def select_kernel_fits(n_nodes: int, n_pods: int, k_pods: int) -> bool:
    """Whether the selection+cycle kernel's VMEM blocks fit: 6 pod blocks of
    (Pp, 128) + 5 node blocks + 5 candidate output blocks + 1 pod scratch,
    all int32, double-buffered across grid programs by Mosaic. The pod
    blocks dominate; the budget is more generous than the candidate
    kernel's because this kernel REPLACES the (C, P) lexsort and gathers,
    so its win grows with P (v5e VMEM is ~128 MiB/core)."""
    np_pad = -(-n_nodes // _SUB) * _SUB
    pp_pad = -(-n_pods // _SUB) * _SUB
    kp_pad = -(-k_pods // _SUB) * _SUB
    resident = (5 * np_pad + 7 * pp_pad + 5 * kp_pad) * _LANE * 4
    return 2 * resident <= int(0.8 * _SELECT_VMEM_LIMIT)


def _select_cycle_kernel(
    n_nodes: int,
    k_pods: int,
    alive_ref,      # (Np, LC) int32
    alloc_cpu_ref,  # (Np, LC) int32
    alloc_ram_ref,  # (Np, LC) int32
    elig_ref,       # (Pp, LC) int32 0/1
    qwin_ref,       # (Pp, LC) int32 queue_ts.win
    qoff_ref,       # (Pp, LC) int32 BITCAST of queue_ts.off (non-negative
                    #  f32, so the bit pattern orders identically to the float)
    qseq_ref,       # (Pp, LC) int32
    preq_cpu_ref,   # (Pp, LC) int32
    preq_ram_ref,   # (Pp, LC) int32
    cpu_out,        # (Np, LC) int32
    ram_out,        # (Np, LC) int32
    cand_out,       # (Kp, LC) int32 selected pod slot
    valid_out,      # (Kp, LC) int32
    assign_out,     # (Kp, LC) int32
    fitany_out,     # (Kp, LC) int32
    best_out,       # (Kp, LC) int32
    rem_ref,        # (Pp, LC) int32 scratch: not-yet-selected eligible pods
):
    """Fused queue selection + scheduling cycle: candidate k is extracted
    IN-KERNEL by an iterated per-lane lexicographic argmin over
    (queue win, off, seq) — exactly the sorted order of the batched
    ActiveQueue (step.lexsort_time_i32), seq unique per cluster, so the
    extraction is deterministic — then scheduled against the VMEM-resident
    node tile like _cycle_kernel. Replaces the (C, P) 3-key sort + top-K
    compaction gathers of prepare_cycle with O(live-queue-depth) passes,
    which is where dense shapes spend their fixed per-window cost."""
    i0 = jnp.int32(0)
    i1 = jnp.int32(1)
    neg1 = jnp.int32(-1)
    bigi = jnp.int32(np.iinfo(np.int32).max)

    cpu_out[:] = alloc_cpu_ref[:]
    ram_out[:] = alloc_ram_ref[:]
    alive = alive_ref[:] != i0
    iota_n = jax.lax.broadcasted_iota(jnp.int32, alive.shape, 0)
    node_ok = iota_n < jnp.int32(n_nodes)

    cand_out[:] = jnp.zeros_like(cand_out)
    valid_out[:] = jnp.zeros_like(valid_out)
    assign_out[:] = jnp.zeros_like(assign_out)
    fitany_out[:] = jnp.zeros_like(fitany_out)
    best_out[:] = jnp.zeros_like(best_out)
    rem_ref[:] = elig_ref[:]

    iota_p = jax.lax.broadcasted_iota(jnp.int32, elig_ref.shape, 0)
    # Early exit: the deepest per-lane queue in this tile bounds the loop.
    depth = jnp.max(jnp.sum(elig_ref[:], axis=0, keepdims=True))
    k_bound = jnp.minimum(depth, jnp.int32(k_pods))

    def body(k):
        rem = rem_ref[:] != i0  # (Pp, LC)
        # Per-lane lexicographic argmin over (win, off-bits, seq).
        w = jnp.where(rem, qwin_ref[:], bigi)
        minw = jnp.min(w, axis=0, keepdims=True)
        m1 = rem & (qwin_ref[:] == minw)
        o = jnp.where(m1, qoff_ref[:], bigi)
        mino = jnp.min(o, axis=0, keepdims=True)
        m2 = m1 & (qoff_ref[:] == mino)
        s = jnp.where(m2, qseq_ref[:], bigi)
        mins = jnp.min(s, axis=0, keepdims=True)
        sel = m2 & (qseq_ref[:] == mins)  # exactly one row per non-empty lane

        seli = sel.astype(jnp.int32)
        slot = jnp.max(jnp.where(sel, iota_p, neg1), axis=0, keepdims=True)
        valid = slot >= i0  # (1, LC)
        rc = jnp.max(seli * preq_cpu_ref[:], axis=0, keepdims=True)
        rr = jnp.max(seli * preq_ram_ref[:], axis=0, keepdims=True)

        assign, any_fit, best, new_cpu, new_ram = _fit_score_place(
            alive, node_ok, iota_n, cpu_out[:], ram_out[:], rc, rr, valid
        )
        cpu_out[:] = new_cpu
        ram_out[:] = new_ram
        cand_out[pl.ds(k, 1), :] = jnp.where(valid, slot, i0)
        valid_out[pl.ds(k, 1), :] = valid.astype(jnp.int32)
        assign_out[pl.ds(k, 1), :] = assign.astype(jnp.int32)
        fitany_out[pl.ds(k, 1), :] = any_fit.astype(jnp.int32)
        best_out[pl.ds(k, 1), :] = best
        rem_ref[:] = jnp.where(sel, i0, rem_ref[:])

    def loop_body(k):
        body(k)
        return k + i1

    jax.lax.while_loop(lambda k: k < k_bound, loop_body, jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("k_pods", "interpret"))
def fused_select_schedule_cycle(
    alive: jnp.ndarray,      # (C, N) bool
    alloc_cpu: jnp.ndarray,  # (C, N) int32
    alloc_ram: jnp.ndarray,  # (C, N) int32
    eligible: jnp.ndarray,   # (C, P) bool
    qwin: jnp.ndarray,       # (C, P) int32
    qoff: jnp.ndarray,       # (C, P) float32 (non-negative)
    qseq: jnp.ndarray,       # (C, P) int32
    pod_req_cpu: jnp.ndarray,  # (C, P) int32
    pod_req_ram: jnp.ndarray,  # (C, P) int32
    k_pods: int,
    interpret: bool = False,
):
    """Fused selection + scheduling loop in VMEM.

    Returns (cand (C,K) int32 pod slots, valid (C,K) bool, assign (C,K) bool,
    fit_any (C,K) bool, best (C,K) int32, new_alloc_cpu, new_alloc_ram) —
    valid rows identical to prepare_cycle's sorted top-K compaction followed
    by the lax.scan/_cycle_kernel loop (invalid rows are zeroed; every
    consumer gates on valid)."""
    C, N = alloc_cpu.shape
    P = eligible.shape[1]
    K = k_pods
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Pp = -(-P // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        return _pad_axis(_pad_axis(x.astype(jnp.int32).T, 0, n_sub, fill), 1, Cp, fill)

    alive_p = prep(alive, Np, 0)
    cpu_p = prep(alloc_cpu, Np, 0)
    ram_p = prep(alloc_ram, Np, 0)
    elig_p = prep(eligible, Pp, 0)
    qwin_p = prep(qwin, Pp, 0)
    # Non-negative f32 bit patterns sort like the floats; move them through
    # the kernel as i32 so every block shares one dtype.
    qoff_p = prep(jax.lax.bitcast_convert_type(qoff, jnp.int32), Pp, 0)
    qseq_p = prep(qseq, Pp, 0)
    reqc_p = prep(pod_req_cpu, Pp, 0)
    reqr_p = prep(pod_req_ram, Pp, 0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((Pp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(_select_cycle_kernel, N, K)
    with jax.enable_x64(False):
        cpu_o, ram_o, cand_o, valid_o, assign_o, fitany_o, best_o = pl.pallas_call(
            kernel,
            grid=(Cp // _LANE,),
            in_specs=[node_spec] * 3 + [pod_spec] * 6,
            out_specs=[node_spec] * 2 + [cand_spec] * 5,
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
            ],
            scratch_shapes=[pltpu.VMEM((Pp, _LANE), jnp.int32)],
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=_SELECT_VMEM_LIMIT
            ),
            interpret=interpret,
        )(alive_p, cpu_p, ram_p, elig_p, qwin_p, qoff_p, qseq_p, reqc_p, reqr_p)

    return (
        cand_o[:K, :C].T,
        valid_o[:K, :C].T != 0,
        assign_o[:K, :C].T != 0,
        fitany_o[:K, :C].T != 0,
        best_o[:K, :C].T,
        cpu_o[:N, :C].T,
        ram_o[:N, :C].T,
    )


def _pad_axis(x: jnp.ndarray, axis: int, to: int, value) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_schedule_cycle(
    alive: jnp.ndarray,      # (C, N) bool
    alloc_cpu: jnp.ndarray,  # (C, N) int32
    alloc_ram: jnp.ndarray,  # (C, N) int32
    valid: jnp.ndarray,      # (C, K) bool
    req_cpu: jnp.ndarray,    # (C, K) int32
    req_ram: jnp.ndarray,    # (C, K) int32
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run the K-pod scheduling loop in VMEM.

    Returns (assign (C,K) bool, fit_any (C,K) bool, best (C,K) int32,
    new_alloc_cpu (C,N) int32, new_alloc_ram (C,N) int32), identical to the
    lax.scan formulation in batched/step.py.
    """
    C, N = alloc_cpu.shape
    K = valid.shape[1]
    Cp = -(-C // _LANE) * _LANE
    Np = -(-N // _SUB) * _SUB
    Kp = -(-K // _SUB) * _SUB

    def prep(x, n_sub, fill):
        # (C, n) -> padded transposed (n_sub, Cp) with clusters on lanes.
        return _pad_axis(_pad_axis(x.astype(jnp.int32).T, 0, n_sub, fill), 1, Cp, fill)

    alive_p = prep(alive, Np, 0)
    cpu_p = prep(alloc_cpu, Np, 0)
    ram_p = prep(alloc_ram, Np, 0)
    valid_p = prep(valid, Kp, 0)
    reqc_p = prep(req_cpu, Kp, 0)
    reqr_p = prep(req_ram, Kp, 0)

    node_spec = pl.BlockSpec((Np, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)
    cand_spec = pl.BlockSpec((Kp, _LANE), lambda i: (0, i), memory_space=pltpu.VMEM)

    kernel = functools.partial(_cycle_kernel, N, K)
    # Trace the kernel with x64 semantics OFF: the batched path enables
    # jax_enable_x64 for its f64 time arrays, but under x64 pallas_call's own
    # index bookkeeping traces as i64, which Mosaic fails to legalize
    # (func.return). Everything crossing this boundary is i32/bool.
    with jax.enable_x64(False):
        cpu_o, ram_o, assign_o, fitany_o, best_o = pl.pallas_call(
            kernel,
            grid=(Cp // _LANE,),
            in_specs=[node_spec, node_spec, node_spec, cand_spec, cand_spec, cand_spec],
            out_specs=[node_spec, node_spec, cand_spec, cand_spec, cand_spec],
            out_shape=[
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Np, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
                jax.ShapeDtypeStruct((Kp, Cp), jnp.int32),
            ],
            interpret=interpret,
        )(alive_p, cpu_p, ram_p, valid_p, reqc_p, reqr_p)

    return (
        assign_o[:K, :C].T != 0,
        fitany_o[:K, :C].T != 0,
        best_o[:K, :C].T,
        cpu_o[:N, :C].T,
        ram_o[:N, :C].T,
    )
