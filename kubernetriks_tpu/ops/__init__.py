"""TPU kernels (pallas) for the framework's hot ops."""
