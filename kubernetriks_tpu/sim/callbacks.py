"""Run-loop strategies (reference: src/simulation_callbacks.rs)."""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

from kubernetriks_tpu.metrics.printer import print_metrics

if TYPE_CHECKING:
    from kubernetriks_tpu.sim.simulator import KubernetriksSimulation

logger = logging.getLogger("kubernetriks_tpu")


class SimulationCallbacks:
    def on_simulation_start(self, sim: "KubernetriksSimulation") -> None:
        pass

    def on_step(self, sim: "KubernetriksSimulation") -> bool:
        """Runs before each step; returning False stops the run."""
        return True

    def on_simulation_finish(self, sim: "KubernetriksSimulation") -> None:
        pass


def check_all_short_pods_terminated(sim: "KubernetriksSimulation") -> bool:
    metrics = sim.metrics_collector.accumulated_metrics
    return metrics.internal.terminated_pods >= metrics.total_pods_in_trace


def assert_and_print(sim: "KubernetriksSimulation") -> None:
    """Terminal invariant: terminated = succeeded + unschedulable + failed +
    removed (reference: src/simulation_callbacks.rs:44-83)."""
    metrics = sim.metrics_collector.accumulated_metrics
    assert metrics.internal.terminated_pods == (
        metrics.pods_succeeded
        + metrics.pods_unschedulable
        + metrics.pods_failed
        + metrics.pods_removed
    ), (
        f"terminated={metrics.internal.terminated_pods} != succeeded="
        f"{metrics.pods_succeeded} + unschedulable={metrics.pods_unschedulable} "
        f"+ failed={metrics.pods_failed} + removed={metrics.pods_removed}"
    )
    if sim.config.metrics_printer is not None:
        print_metrics(sim.metrics_collector, sim.config.metrics_printer)


class RunUntilAllPodsAreFinishedCallbacks(SimulationCallbacks):
    """Check termination at sim-time multiples of 1000
    (reference: src/simulation_callbacks.rs:85-97)."""

    def on_step(self, sim: "KubernetriksSimulation") -> bool:
        if sim.sim.time() % 1000.0 == 0.0:
            return not check_all_short_pods_terminated(sim)
        return True

    def on_simulation_finish(self, sim: "KubernetriksSimulation") -> None:
        assert_and_print(sim)


class RunUntilAllPodsAreFinishedAndLongRunningPodsExceedDeadlineCallbacks(
    SimulationCallbacks
):
    """Extends the above for long-running services: after all trace pods finish,
    keep stepping until the deadline (reference: src/simulation_callbacks.rs:99-129;
    the reference notes a self-acknowledged instant-termination bug at :114 — the
    deadline branch here is ordered to avoid it)."""

    def __init__(self, deadline_time: float) -> None:
        self.deadline_time = deadline_time
        self.all_short_pods_terminated = False

    def on_step(self, sim: "KubernetriksSimulation") -> bool:
        if self.all_short_pods_terminated:
            return sim.sim.time() < self.deadline_time
        if sim.sim.time() % 1000.0 == 0.0:
            self.all_short_pods_terminated = check_all_short_pods_terminated(sim)
            if self.all_short_pods_terminated:
                return sim.sim.time() < self.deadline_time
        return True

    def on_simulation_finish(self, sim: "KubernetriksSimulation") -> None:
        assert_and_print(sim)
