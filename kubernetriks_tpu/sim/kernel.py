"""Deterministic discrete-event simulation kernel.

Stands in for the external DSLab core the reference builds on (reference:
Cargo.toml:8 `dslab-core`; usage at src/simulator.rs:74-186): a global
time-ordered event queue with FIFO tie-break at equal timestamps, a component
registry (name -> id), per-component contexts that emit timestamped events,
event cancellation, and one seeded RNG owned by the simulation.

Determinism contract (mirroring the reference's tests/test_determinism.rs):
given the same seed, config and trace,
every run pops the same events in the same order and produces bit-identical
metrics. The heap orders by (time, event_id); event ids increase monotonically
in emission order, which reproduces DSLab's stable FIFO-per-timestamp ordering.
"""

from __future__ import annotations

import heapq
import random  # ktpu: prng-ok(scalar oracle kernel: the reference simulator's own seeded RNG — reference-port semantics, isolated from the batched path)
import string
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass(order=True)
class Event:
    """A scheduled message: matches DSLab's Event shape {id, time, src, dst, data}
    (reference: tests/test_cast_box.rs:16-24)."""

    time: float
    id: int
    src: int = field(compare=False)
    dst: int = field(compare=False)
    data: Any = field(compare=False)


def _snake_case(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class EventHandler:
    """Base class for simulation components.

    Dispatches incoming events to ``on_<snake_case_payload_type>`` methods —
    the Python equivalent of the reference's `cast!`/`cast_box!` match macros
    (reference: src/core/events.rs:247-268).
    """

    def on(self, event: Event) -> None:
        method = getattr(self, "on_" + _snake_case(type(event.data).__name__), None)
        if method is None:
            raise RuntimeError(
                f"{type(self).__name__}: unhandled event {type(event.data).__name__}"
            )
        method(event.data, event.time)


class SimulationContext:
    """Per-component handle for emitting events (DSLab SimulationContext
    equivalent; usage reference: src/core/node_component.rs:137-145)."""

    def __init__(self, sim: "Simulation", name: str, comp_id: int) -> None:
        self._sim = sim
        self.name = name
        self.id = comp_id

    def time(self) -> float:
        return self._sim.time()

    def emit(self, data: Any, dst: int, delay: float = 0.0) -> int:
        return self._sim._schedule(data, self.id, dst, delay)

    def emit_now(self, data: Any, dst: int) -> int:
        return self._sim._schedule(data, self.id, dst, 0.0)

    def emit_self(self, data: Any, delay: float = 0.0) -> int:
        return self._sim._schedule(data, self.id, self.id, delay)

    def emit_self_now(self, data: Any) -> int:
        return self._sim._schedule(data, self.id, self.id, 0.0)

    def cancel_event(self, event_id: int) -> None:
        self._sim.cancel_event(event_id)

    # Seeded RNG helpers, all drawing from the single simulation-owned RNG so
    # that call order fully determines the stream (DSLab equivalent:
    # ctx.gen_range / ctx.random_string, used by tests and the trace generator).
    def rand(self) -> float:
        return self._sim.rand()

    def gen_range_float(self, low: float, high: float) -> float:
        return self._sim.rng.uniform(low, high)

    def gen_range_int(self, low: int, high: int) -> int:
        """Integer in [low, high) — matches Rust's `gen_range(low..high)`."""
        return self._sim.rng.randrange(low, high)

    def random_string(self, length: int) -> str:
        return self._sim.random_string(length)


class Simulation:
    """The global event loop (DSLab Simulation equivalent)."""

    def __init__(self, seed: int) -> None:
        self.rng = random.Random(seed)  # ktpu: prng-ok(seeded reference-port RNG; the batched path never consumes it)
        self._queue: List[Event] = []
        self._next_event_id = 0
        self._time = 0.0
        self._event_count = 0
        self._contexts: Dict[str, SimulationContext] = {}
        self._handlers: Dict[int, EventHandler] = {}
        self._names: Dict[int, str] = {}
        self._next_component_id = 0
        self._cancelled: set = set()

    # --- component registry -------------------------------------------------

    def create_context(self, name: str) -> SimulationContext:
        """Get-or-create by name (DSLab semantics): a second create_context with
        the same name returns a context with the same component id, so a
        handler registered under that name receives its self-events."""
        existing = self._contexts.get(name)
        if existing is not None:
            return existing
        comp_id = self._next_component_id
        self._next_component_id += 1
        ctx = SimulationContext(self, name, comp_id)
        self._contexts[name] = ctx
        self._names[comp_id] = name
        return ctx

    def add_handler(self, name: str, handler: EventHandler) -> int:
        ctx = self._contexts.get(name)
        if ctx is None:
            ctx = self.create_context(name)
        self._handlers[ctx.id] = handler
        return ctx.id

    def lookup_name(self, comp_id: int) -> str:
        return self._names.get(comp_id, f"<component {comp_id}>")

    # --- event queue --------------------------------------------------------

    def _schedule(self, data: Any, src: int, dst: int, delay: float) -> int:
        assert delay >= 0.0, f"negative delay {delay}"
        event_id = self._next_event_id
        self._next_event_id += 1
        heapq.heappush(self._queue, Event(self._time + delay, event_id, src, dst, data))
        return event_id

    def cancel_event(self, event_id: int) -> None:
        """Lazy cancellation: the event stays queued, the pop skips it
        (replaces DSLab cancel_event; usage reference:
        src/core/node_component.rs:102-104,281-283)."""
        self._cancelled.add(event_id)

    def step(self) -> bool:
        """Pop and dispatch the next event. Returns False when the queue is empty."""
        while self._queue:
            event = heapq.heappop(self._queue)
            if event.id in self._cancelled:
                self._cancelled.discard(event.id)
                continue
            self._time = event.time
            self._event_count += 1
            handler = self._handlers.get(event.dst)
            if handler is not None:
                handler.on(event)
            return True
        return False

    def steps(self, n: int) -> bool:
        for _ in range(n):
            if not self.step():
                return False
        return True

    def step_until_no_events(self) -> None:
        while self.step():
            pass

    def step_for_duration(self, duration: float) -> None:
        self.step_until_time(self._time + duration)

    def step_until_time(self, until: float) -> None:
        while self._queue:
            nxt = self._peek_time()
            if nxt is None or nxt > until:
                break
            self.step()
        self._time = max(self._time, until)

    def _peek_time(self) -> Optional[float]:
        while self._queue and self._queue[0].id in self._cancelled:
            cancelled = heapq.heappop(self._queue)
            self._cancelled.discard(cancelled.id)
        return self._queue[0].time if self._queue else None

    def time(self) -> float:
        return self._time

    # Simulation-level RNG helpers (DSLab exposes the same on Simulation).
    def rand(self) -> float:
        return self.rng.random()

    def random_string(self, length: int) -> str:
        alphabet = string.ascii_letters + string.digits
        return "".join(self.rng.choice(alphabet) for _ in range(length))

    def event_count(self) -> int:
        """Number of events processed so far."""
        return self._event_count

    def pending_events(self) -> int:
        return sum(1 for e in self._queue if e.id not in self._cancelled)
