"""Simulation orchestrator: wires all components and runs the event loop
(reference: src/simulator.rs).
"""

from __future__ import annotations

import logging
import time as wall_time
from typing import List, Optional, Tuple

from kubernetriks_tpu.autoscalers.cluster_autoscaler import (
    ClusterAutoscaler,
    resolve_cluster_autoscaler_impl,
)
from kubernetriks_tpu.autoscalers.horizontal_pod_autoscaler import (
    HorizontalPodAutoscaler,
    resolve_horizontal_pod_autoscaler_impl,
)
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.core.api_server import KubeApiServer
from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest, RemoveNodeRequest
from kubernetriks_tpu.core.node_component import (
    NodeComponent,
    NodeComponentPool,
    NodeRuntime,
)
from kubernetriks_tpu.core.persistent_storage import PersistentStorage
from kubernetriks_tpu.core.scheduler.interface import PodSchedulingAlgorithm
from kubernetriks_tpu.core.scheduler.kube_scheduler import (
    KubeScheduler,
    kube_scheduler_config_from_spec,
)
from kubernetriks_tpu.core.scheduler.scheduler import Scheduler
from kubernetriks_tpu.core.types import Node, NodeConditionType
from kubernetriks_tpu.metrics.collector import MetricsCollector
from kubernetriks_tpu.sim.kernel import Simulation
from kubernetriks_tpu.trace.interface import Trace, TraceEvents

logger = logging.getLogger("kubernetriks_tpu")


def max_nodes_in_trace(trace_events: TraceEvents) -> int:
    """Max simultaneously-existing node count; sizes the component pool
    (reference: src/simulator.rs:51-65)."""
    count = max_count = 0
    for _, event in trace_events:
        if isinstance(event, CreateNodeRequest):
            count += 1
        elif isinstance(event, RemoveNodeRequest):
            count -= 1
        max_count = max(count, max_count)
    return max_count


class KubernetriksSimulation:
    """reference: src/simulator.rs:35-402."""

    def __init__(
        self, config: SimulationConfig, gauge_csv_path: Optional[str] = None
    ) -> None:
        self.config = config
        self.sim = Simulation(config.seed)

        api_server_ctx = self.sim.create_context("kube_api_server")
        persistent_storage_ctx = self.sim.create_context("persistent_storage")
        scheduler_ctx = self.sim.create_context("scheduler")

        self.metrics_collector = MetricsCollector(gauge_csv_path=gauge_csv_path)
        self.sim.add_handler("metrics_collector", self.metrics_collector)

        self.cluster_autoscaler: Optional[ClusterAutoscaler] = None
        cluster_autoscaler_id = None
        if config.cluster_autoscaler.enabled:
            ca_ctx = self.sim.create_context("cluster_autoscaler")
            self.cluster_autoscaler = ClusterAutoscaler(
                api_server_ctx.id,
                resolve_cluster_autoscaler_impl(config.cluster_autoscaler),
                ca_ctx,
                config,
                self.metrics_collector,
            )
            cluster_autoscaler_id = self.sim.add_handler(
                "cluster_autoscaler", self.cluster_autoscaler
            )

        self.horizontal_pod_autoscaler: Optional[HorizontalPodAutoscaler] = None
        horizontal_pod_autoscaler_id = None
        if config.horizontal_pod_autoscaler.enabled:
            hpa_ctx = self.sim.create_context("horizontal_pod_autoscaler")
            self.horizontal_pod_autoscaler = HorizontalPodAutoscaler(
                api_server_ctx.id,
                resolve_horizontal_pod_autoscaler_impl(config.horizontal_pod_autoscaler),
                hpa_ctx,
                config,
                self.metrics_collector,
            )
            horizontal_pod_autoscaler_id = self.sim.add_handler(
                "horizontal_pod_autoscaler", self.horizontal_pod_autoscaler
            )

        self.api_server = KubeApiServer(
            persistent_storage_ctx.id,
            api_server_ctx,
            config,
            self.metrics_collector,
            cluster_autoscaler_id=cluster_autoscaler_id,
            horizontal_pod_autoscaler_id=horizontal_pod_autoscaler_id,
        )
        api_server_id = self.sim.add_handler("kube_api_server", self.api_server)

        self.metrics_collector.set_context(self.sim.create_context("metrics_collector"))
        self.metrics_collector.set_api_server_component(self.api_server)
        self.metrics_collector.start_pod_metrics_collection()
        self.metrics_collector.start_gauge_metrics_recording()

        self.scheduler = Scheduler(
            api_server_id,
            # The configured profile (config.scheduler_profile; None = the
            # reference default) — same spec the batched engine compiles
            # into its device pipeline, parsed by the one shared parser.
            KubeScheduler(
                kube_scheduler_config_from_spec(
                    getattr(config, "scheduler_profile", None)
                )
            ),
            scheduler_ctx,
            config,
            self.metrics_collector,
        )
        scheduler_id = self.sim.add_handler("scheduler", self.scheduler)

        self.persistent_storage = PersistentStorage(
            api_server_id,
            scheduler_id,
            persistent_storage_ctx,
            config,
            self.metrics_collector,
        )
        self.sim.add_handler("persistent_storage", self.persistent_storage)

    # --- initialization -----------------------------------------------------

    def initialize(self, cluster_trace: Trace, workload_trace: Trace) -> None:
        """reference: src/simulator.rs:200-275."""
        client = self.sim.create_context("client")
        assert self.sim.time() == 0.0

        cluster_trace_events = cluster_trace.convert_to_simulator_events()
        workload_trace_events = workload_trace.convert_to_simulator_events()

        fault_cfg = self.config.fault_injection
        if fault_cfg is not None and fault_cfg.enabled:
            # Chaos engine (kubernetriks_tpu/chaos.py): node crash/recovery
            # chains are sampled host-side from the counter-based PRNG and
            # injected as concrete events (the batched compiler does the
            # same with cluster index c per cluster; the scalar sim is
            # cluster 0), and the pod fault oracle is installed into the
            # control-plane components for CrashLoopBackOff draws.
            from kubernetriks_tpu import chaos

            fault_seed = (
                fault_cfg.seed if fault_cfg.seed is not None else self.config.seed
            )
            horizon = chaos.fault_horizon(
                fault_cfg, cluster_trace_events, workload_trace_events
            )
            cluster_trace_events = chaos.inject_node_faults(
                cluster_trace_events,
                fault_cfg,
                fault_seed,
                0,
                horizon,
                self.config.scheduling_cycle_interval,
            )
            oracle = chaos.PodFaultOracle(
                fault_cfg, fault_seed, 0, workload_trace_events
            )
            self.api_server.fault_oracle = oracle
            self.persistent_storage.fault_oracle = oracle
            self.scheduler.fault_oracle = oracle

        trace_max_nodes = max_nodes_in_trace(cluster_trace_events)
        autoscaler_max_nodes = (
            self.cluster_autoscaler.max_nodes() if self.cluster_autoscaler else 0
        )
        max_nodes = trace_max_nodes + autoscaler_max_nodes
        logger.info(
            "Node pool capacity=%d (%d from trace and %d from cluster autoscaler)",
            max_nodes,
            trace_max_nodes,
            autoscaler_max_nodes,
        )
        self.api_server.set_node_pool(NodeComponentPool(max_nodes, self.sim))

        self.initialize_default_cluster()

        api_server_id = self.api_server.ctx.id
        for ts, event in cluster_trace_events:
            if isinstance(event, CreateNodeRequest) and not event.recovered:
                self.metrics_collector.accumulated_metrics.total_nodes_in_trace += 1
            client.emit(event, api_server_id, ts)
        for ts, event in workload_trace_events:
            if isinstance(event, CreatePodRequest):
                self.metrics_collector.accumulated_metrics.total_pods_in_trace += 1
            client.emit(event, api_server_id, ts)

        self.scheduler.start()
        if self.cluster_autoscaler is not None:
            self.cluster_autoscaler.start()
        if self.horizontal_pod_autoscaler is not None:
            self.horizontal_pod_autoscaler.start()

    def add_node(self, node: Node) -> None:
        """Direct (event-bypassing) node install into storage + api server +
        scheduler, used for the default cluster (reference: src/simulator.rs:277-301)."""
        node_name = node.metadata.name
        node_ctx = self.sim.create_context(node_name)
        node.update_condition("True", NodeConditionType.NODE_CREATED, 0.0)
        node.status.allocatable = node.status.capacity.copy()

        self.persistent_storage.add_node(node.copy())
        component = NodeComponent(node_ctx)
        component.runtime = NodeRuntime(
            api_server=self.api_server.ctx.id, node=node.copy(), config=self.config
        )
        self.api_server.add_node_component(component)
        self.scheduler.add_node(node.copy())
        self.sim.add_handler(node_name, component)

    def initialize_default_cluster(self) -> None:
        """Node-group naming rules (reference: src/simulator.rs:303-344):
        single named template -> name verbatim; multi named -> name as prefix
        with a running index; unnamed -> default_node_<idx>."""
        if not self.config.default_cluster:
            return
        total_nodes = 0
        for node_group in self.config.default_cluster:
            node_count_in_group = node_group.node_count or 1
            template_name = node_group.node_template.metadata.name

            if node_count_in_group == 1 and template_name:
                node = node_group.node_template.copy()
                node.metadata.name = template_name
                self.add_node(node)
                # NB: matching the reference, the current_nodes gauge is NOT
                # incremented for this path (simulator.rs:314-320 `continue`s
                # before the gauge update).
                continue
            name_prefix = template_name if template_name else "default_node"

            for _ in range(node_count_in_group):
                node = node_group.node_template.copy()
                node.metadata.name = f"{name_prefix}_{total_nodes}"
                self.add_node(node)
                total_nodes += 1
            self.metrics_collector.gauge_metrics.current_nodes += node_count_in_group

    def set_scheduler_algorithm(self, algorithm: PodSchedulingAlgorithm) -> None:
        self.scheduler.set_scheduler_algorithm(algorithm)

    # --- run loops ----------------------------------------------------------

    def run_with_callbacks(self, callbacks) -> None:
        """reference: src/simulator.rs:355-372."""
        callbacks.on_simulation_start(self)
        t = wall_time.perf_counter()
        while callbacks.on_step(self):
            self.sim.step()
        duration = wall_time.perf_counter() - t
        logger.info(
            "Processed %d events in %.2fs (%.0f events/s)",
            self.sim.event_count(),
            duration,
            self.sim.event_count() / duration if duration else float("inf"),
        )
        logger.info("Finished at %s", self.sim.time())
        callbacks.on_simulation_finish(self)

    def run_until_no_events(self) -> None:
        """NB: matching the reference, this re-arms the scheduler cycles
        (simulator.rs:374-387); use run_with_callbacks after initialize()."""
        self.scheduler.start()
        t = wall_time.perf_counter()
        self.sim.step_until_no_events()
        duration = wall_time.perf_counter() - t
        logger.info(
            "Processed %d events in %.2fs (%.0f events/s)",
            self.sim.event_count(),
            duration,
            self.sim.event_count() / duration if duration else float("inf"),
        )

    def step(self) -> None:
        self.sim.step()

    def step_for_duration(self, duration: float) -> None:
        self.sim.step_for_duration(duration)

    def step_until_time(self, until_time: float) -> None:
        self.sim.step_until_time(until_time)
