"""Recompile sentinel: the runtime cross-check of the fleet's
compile-once guarantee (`KTPU_EXPLAIN_RECOMPILES`).

The static half is the scenariotrace lint pass (a scenario leaf can
never flow into program-shaping positions); the dynamic half is
jit-cache-size equality asserted by `bench.py --sweep` / `--endurance`.
Both tell you THAT something recompiled — neither names WHICH jit entry
did. This module hooks `jax_log_compiles` (every XLA compilation logs
"Finished XLA compilation of <entry> in ... sec" on the
`jax._src.dispatch` logger) and turns post-warm-up compilations into a
`RecompileError` (or warning) carrying the entry names, so a
shape-drifting call or a scenario parameter that regressed to a
jit-static is diagnosed in one line instead of a cache-count diff.

Usage (the fleet and the benches wire this up):

    sent = RecompileSentinel().install()
    ...build + warm up...
    sent.seal("warm-up done")           # compiles beyond here are events
    ...steady state...
    sent.check("query stream")          # raises/warns, naming entries
    sent.uninstall()

or windowed, immune to neighboring engines compiling in between:

    with sent.expect_none("fleet wave 3"):
        ...one wave...

`KTPU_EXPLAIN_RECOMPILES` (tristate): unset -> armed only where the code
opts in explicitly (the --sweep/--endurance in-bench asserts); 1 ->
`ScenarioFleet` arms a raising sentinel around every post-warm-up wave;
0 -> forced off everywhere, including the benches.

The log hook silences the two jax compile loggers' propagation while
installed (their WARNING-level spam would otherwise hit stderr on every
legitimate warm-up compile) and restores both the propagation and the
`jax_log_compiles` setting on uninstall. Nesting is supported; the
handler stays attached until the last sentinel uninstalls.
"""

from __future__ import annotations

import logging
import threading
import warnings
from typing import List, Optional

from kubernetriks_tpu.flags import flag_tristate

_COMPILE_LOGGER = "jax._src.dispatch"
# pxla's "Compiling <fn> with global shapes..." WARNING rides a second
# logger; silenced alongside (it duplicates the dispatch signal).
_NOISE_LOGGERS = ("jax._src.interpreters.pxla",)
_PREFIX = "Finished XLA compilation of "


class RecompileError(RuntimeError):
    """A jit entry compiled after the sentinel was sealed."""


class RecompileWarning(RuntimeWarning):
    pass


class _CompileLogHandler(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.lock2 = threading.Lock()
        self.sentinels: List["RecompileSentinel"] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
            if not msg.startswith(_PREFIX):
                return
            name = msg[len(_PREFIX) :].rsplit(" in ", 1)[0]
            with self.lock2:
                for sent in self.sentinels:
                    sent._events.append(name)
        except Exception:  # a telemetry hook must never break dispatch
            pass


_HANDLER = _CompileLogHandler()
_INSTALL_LOCK = threading.Lock()
_SAVED_STATE: dict = {}


def _attach() -> None:
    import jax

    _SAVED_STATE["log_compiles"] = bool(jax.config.jax_log_compiles)
    _SAVED_STATE["propagate"] = {
        name: logging.getLogger(name).propagate
        for name in (_COMPILE_LOGGER,) + _NOISE_LOGGERS
    }
    jax.config.update("jax_log_compiles", True)
    # The handler rides EVERY compile logger: on the dispatch logger it
    # collects events; on the noise loggers it only exists so the record
    # finds a handler — propagate=False alone would still reach
    # logging.lastResort (stderr) on handler-less loggers.
    for name in (_COMPILE_LOGGER,) + _NOISE_LOGGERS:
        logger = logging.getLogger(name)
        logger.addHandler(_HANDLER)
        logger.propagate = False


def _detach() -> None:
    import jax

    for name in (_COMPILE_LOGGER,) + _NOISE_LOGGERS:
        logging.getLogger(name).removeHandler(_HANDLER)
    for name, prop in _SAVED_STATE.get("propagate", {}).items():
        logging.getLogger(name).propagate = prop
    jax.config.update(
        "jax_log_compiles", _SAVED_STATE.get("log_compiles", False)
    )


class RecompileSentinel:
    """Collects XLA-compilation events and enforces a zero-recompile
    contract past a seal point (or inside expect_none windows)."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise", "warn"):
            raise ValueError(f"mode must be 'raise' or 'warn', got {mode!r}")
        self.mode = mode
        self._events: List[str] = []
        self._sealed_at: Optional[int] = None
        self._installed = False

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "RecompileSentinel":
        with _INSTALL_LOCK:
            if not self._installed:
                if not _HANDLER.sentinels:
                    _attach()
                with _HANDLER.lock2:
                    _HANDLER.sentinels.append(self)
                self._installed = True
        return self

    def uninstall(self) -> None:
        with _INSTALL_LOCK:
            if self._installed:
                with _HANDLER.lock2:
                    if self in _HANDLER.sentinels:
                        _HANDLER.sentinels.remove(self)
                self._installed = False
                if not _HANDLER.sentinels:
                    _detach()

    def __enter__(self) -> "RecompileSentinel":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- the contract ------------------------------------------------------

    @property
    def events(self) -> List[str]:
        """Entry names of every compilation observed since install()."""
        with _HANDLER.lock2:
            return list(self._events)

    def seal(self, context: str = "warm-up") -> None:
        """Mark the end of warm-up: compilations beyond this point are
        contract violations for check()."""
        with _HANDLER.lock2:
            self._sealed_at = len(self._events)

    def post_seal_events(self) -> List[str]:
        with _HANDLER.lock2:
            if self._sealed_at is None:
                return []
            return list(self._events[self._sealed_at :])

    def _report(self, names: List[str], context: str) -> None:
        listing = ", ".join(sorted(set(names)))
        msg = (
            f"KTPU_EXPLAIN_RECOMPILES: {len(names)} post-warm-up XLA "
            f"compilation(s) during {context or 'the sealed region'} — "
            f"jit entries: {listing}. A traced input's shape/dtype "
            "drifted or a parameter regressed to a jit-static; the "
            "compile-once contract is broken."
        )
        if self.mode == "raise":
            raise RecompileError(msg)
        warnings.warn(msg, RecompileWarning, stacklevel=3)

    def check(self, context: str = "") -> None:
        """Raise (or warn) if anything compiled since seal()."""
        names = self.post_seal_events()
        if names:
            # Re-seal so a warn-mode caller is not re-warned forever.
            self.seal()
            self._report(names, context)

    def expect_none(self, context: str):
        """Context manager: no compilation may happen inside the block
        (independent of seal(), so neighboring engines compiling between
        blocks don't contaminate the verdict)."""
        sentinel = self

        class _Window:
            def __enter__(self_w):
                with _HANDLER.lock2:
                    self_w.start = len(sentinel._events)
                return sentinel

            def __exit__(self_w, exc_type, exc, tb):
                if exc_type is not None:
                    return False
                with _HANDLER.lock2:
                    names = list(sentinel._events[self_w.start :])
                if names:
                    sentinel._report(names, context)
                return False

        return _Window()


def sentinel_mode() -> Optional[bool]:
    """The KTPU_EXPLAIN_RECOMPILES tristate: None unset (benches arm
    their own sentinels, the fleet does not), True -> armed raising,
    False -> forced off everywhere."""
    return flag_tristate("KTPU_EXPLAIN_RECOMPILES")


def maybe_sentinel() -> Optional[RecompileSentinel]:
    """An installed raising sentinel when the flag is explicitly on
    (ScenarioFleet's wiring), else None."""
    if sentinel_mode() is True:
        return RecompileSentinel("raise").install()
    return None
