"""Cluster-autoscaler proxy: periodic scan cycles driving a pluggable algorithm
(reference: src/autoscalers/cluster_autoscaler/cluster_autoscaler.rs).
"""

from __future__ import annotations

from typing import Dict, TYPE_CHECKING

from kubernetriks_tpu.autoscalers.interface import (
    AutoscaleInfo,
    CaNodeGroup,
    ClusterAutoscalerAlgorithm,
    ScaleDownNodeAction,
    ScaleUpNodeAction,
)
from kubernetriks_tpu.autoscalers.kube_cluster_autoscaler import (
    CLUSTER_AUTOSCALER_ORIGIN_LABEL,
    KubeClusterAutoscaler,
)
from kubernetriks_tpu.core.events import (
    ClusterAutoscalerRequest,
    ClusterAutoscalerResponse,
    CreateNodeRequest,
    RemoveNodeRequest,
    RunClusterAutoscalerCycle,
)
from kubernetriks_tpu.core.types import Node
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import ClusterAutoscalerConfig, SimulationConfig
    from kubernetriks_tpu.metrics.collector import MetricsCollector


class ClusterAutoscaler(EventHandler):
    """Every scan_interval: request autoscale info from storage (via api
    server), hand it to the algorithm, emit Create/RemoveNodeRequest actions.
    The next cycle fires immediately if the info round-trip exceeded the scan
    interval (reference: cluster_autoscaler.rs:235-266)."""

    def __init__(
        self,
        api_server: int,
        autoscaling_algorithm: ClusterAutoscalerAlgorithm,
        ctx: SimulationContext,
        config: "SimulationConfig",
        metrics_collector: "MetricsCollector",
    ) -> None:
        ca_config = config.cluster_autoscaler
        assert ca_config.node_groups, "node groups cannot be empty for CA"
        self.node_groups: Dict[str, CaNodeGroup] = {}
        for node_group in ca_config.node_groups:
            template_name = node_group.node_template.metadata.name
            assert template_name, "CA node templates must be named"
            assert template_name not in self.node_groups, (
                "unique node group name should be used"
            )
            node_template = node_group.node_template.copy()
            node_template.status.allocatable = node_template.status.capacity.copy()
            node_template.metadata.labels["origin"] = CLUSTER_AUTOSCALER_ORIGIN_LABEL
            node_template.metadata.labels["node_group"] = template_name
            self.node_groups[template_name] = CaNodeGroup(
                node_template=node_template,
                max_count=node_group.max_count,
                current_count=0,
                total_allocated=0,
            )

        self.api_server = api_server
        self.last_cycle_time = 0.0
        self.autoscaling_algorithm = autoscaling_algorithm
        self.ctx = ctx
        self.config = config
        self.metrics_collector = metrics_collector

    def max_nodes(self) -> int:
        return self.config.cluster_autoscaler.max_node_count

    def start(self) -> None:
        self.ctx.emit_self_now(RunClusterAutoscalerCycle())

    def run_cluster_autoscaler_cycle(self, event_time: float) -> None:
        self.last_cycle_time = event_time
        self.ctx.emit(
            ClusterAutoscalerRequest(
                request_type=self.autoscaling_algorithm.info_request_type()
            ),
            self.api_server,
            self.config.as_to_ca_network_delay,
        )

    def _scale_up_request(self, node: Node) -> None:
        self.ctx.emit(
            CreateNodeRequest(node=node),
            self.api_server,
            self.config.as_to_ca_network_delay,
        )
        self.metrics_collector.accumulated_metrics.total_scaled_up_nodes += 1

    def _scale_down_request(self, node_name: str) -> None:
        self.ctx.emit(
            RemoveNodeRequest(node_name=node_name),
            self.api_server,
            self.config.as_to_ca_network_delay,
        )
        self.metrics_collector.accumulated_metrics.total_scaled_down_nodes += 1

    def take_actions(self, actions) -> None:
        for action in actions:
            if isinstance(action, ScaleUpNodeAction):
                self._scale_up_request(action.node)
            elif isinstance(action, ScaleDownNodeAction):
                self._scale_down_request(action.node_name)

    # --- event handlers -----------------------------------------------------

    def on_run_cluster_autoscaler_cycle(
        self, data: RunClusterAutoscalerCycle, time: float
    ) -> None:
        self.run_cluster_autoscaler_cycle(time)

    def on_cluster_autoscaler_response(
        self, data: ClusterAutoscalerResponse, time: float
    ) -> None:
        actions = self.autoscaling_algorithm.autoscale(
            AutoscaleInfo(scale_up=data.scale_up, scale_down=data.scale_down),
            self.node_groups,
            self.config.cluster_autoscaler.max_node_count,
        )
        self.take_actions(actions)
        delay = self.config.cluster_autoscaler.scan_interval
        if time - self.last_cycle_time > self.config.cluster_autoscaler.scan_interval:
            delay = 0.0
        self.ctx.emit_self(RunClusterAutoscalerCycle(), delay)


def resolve_cluster_autoscaler_impl(
    autoscaler_config: "ClusterAutoscalerConfig",
) -> ClusterAutoscalerAlgorithm:
    """reference: cluster_autoscaler.rs:219-233."""
    if autoscaler_config.autoscaler_type == "kube_cluster_autoscaler":
        return KubeClusterAutoscaler(autoscaler_config.kube_cluster_autoscaler)
    raise ValueError(
        f"Unsupported cluster autoscaler implementation: "
        f"{autoscaler_config.autoscaler_type!r}"
    )
