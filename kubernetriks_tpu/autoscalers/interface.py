"""Autoscaler interfaces and info payloads.

Mirrors the reference's autoscaler interface modules (reference:
src/autoscalers/cluster_autoscaler/interface.rs,
src/autoscalers/horizontal_pod_autoscaler/interface.rs).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from kubernetriks_tpu.core.types import (
    Node,
    Pod,
    RuntimeResourcesUsageModelConfig,
)

# Label value marking nodes created by the cluster autoscaler; shared by the
# CA (labeling), storage (scale-down info filter) and scale-down matching
# (reference: src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:13).
CLUSTER_AUTOSCALER_ORIGIN_LABEL = "cluster autoscaler"


# --- cluster autoscaler -----------------------------------------------------


@dataclass
class CaNodeGroup:
    """Cluster-autoscaler node group state
    (reference: src/autoscalers/cluster_autoscaler/interface.rs:7-18)."""

    node_template: Node
    # Max simultaneous nodes for this group; None = bounded only by the global
    # max_node_count.
    max_count: Optional[int] = None
    current_count: int = 0
    # Monotonic counter for unique scaled-up node names.
    total_allocated: int = 0


@dataclass
class ScaleUpNodeAction:
    node: Node


@dataclass
class ScaleDownNodeAction:
    node_name: str


@dataclass
class ScaleUpInfo:
    """reference: src/autoscalers/cluster_autoscaler/interface.rs:26-29."""

    unscheduled_pods: List[Pod]


@dataclass
class ScaleDownInfo:
    """reference: src/autoscalers/cluster_autoscaler/interface.rs:32-41."""

    nodes: List[Node]
    pods_on_autoscaled_nodes: Dict[str, Pod]
    assignments: Dict[str, Set[str]]


@dataclass
class AutoscaleInfo:
    scale_up: Optional[ScaleUpInfo] = None
    scale_down: Optional[ScaleDownInfo] = None


class AutoscaleInfoRequestType(enum.Enum):
    """reference: src/autoscalers/cluster_autoscaler/interface.rs:48-58."""

    AUTO = "Auto"
    SCALE_UP_ONLY = "ScaleUpOnly"
    SCALE_DOWN_ONLY = "ScaleDownOnly"
    BOTH = "Both"


class ClusterAutoscalerAlgorithm:
    """reference: src/autoscalers/cluster_autoscaler/interface.rs:60-68."""

    def info_request_type(self) -> AutoscaleInfoRequestType:
        raise NotImplementedError

    def autoscale(
        self,
        info: AutoscaleInfo,
        node_groups: Dict[str, CaNodeGroup],
        max_node_count: int,
    ) -> List[Any]:
        raise NotImplementedError


# --- horizontal pod autoscaler ----------------------------------------------


@dataclass
class TargetResourcesUsage:
    """Target cpu/ram utilization ratios in [0,1], relative to requests
    (reference: src/autoscalers/horizontal_pod_autoscaler/interface.rs:10-14)."""

    cpu_utilization: Optional[float] = None
    ram_utilization: Optional[float] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "TargetResourcesUsage":
        if not d:
            return TargetResourcesUsage()
        return TargetResourcesUsage(
            cpu_utilization=d.get("cpu_utilization"),
            ram_utilization=d.get("ram_utilization"),
        )


@dataclass
class PodGroup:
    """A set of long-running service pods scaled together
    (reference: src/autoscalers/horizontal_pod_autoscaler/interface.rs:19-34)."""

    name: str
    initial_pod_count: int
    max_pod_count: int
    pod_template: Pod
    target_resources_usage: TargetResourcesUsage
    resources_usage_model_config: Optional[RuntimeResourcesUsageModelConfig]

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodGroup":
        return PodGroup(
            name=d.get("name", ""),
            initial_pod_count=int(d.get("initial_pod_count", 0)),
            max_pod_count=int(d.get("max_pod_count", 0)),
            pod_template=Pod.from_dict(d.get("pod_template") or {}),
            target_resources_usage=TargetResourcesUsage.from_dict(
                d.get("target_resources_usage")
            ),
            resources_usage_model_config=RuntimeResourcesUsageModelConfig.from_dict(
                d.get("resources_usage_model_config")
            ),
        )


@dataclass
class PodGroupInfo:
    """reference: src/autoscalers/horizontal_pod_autoscaler/interface.rs:37-46."""

    creation_time: float
    pod_group: PodGroup
    created_pods: Set[str] = field(default_factory=set)
    total_created: int = 0


@dataclass
class ScaleUpPodAction:
    pod: Pod


@dataclass
class ScaleDownPodAction:
    pod_name: str


class HorizontalPodAutoscalerAlgorithm:
    """reference: src/autoscalers/horizontal_pod_autoscaler/interface.rs:53-59."""

    def autoscale(
        self, pod_group_metrics, pod_group_info: PodGroupInfo
    ) -> List[Any]:
        raise NotImplementedError
