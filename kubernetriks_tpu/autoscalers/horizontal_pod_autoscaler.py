"""Horizontal-pod-autoscaler proxy
(reference: src/autoscalers/horizontal_pod_autoscaler/horizontal_pod_autoscaler.rs).

Every scan_interval it pulls per-pod-group mean cpu/ram utilization straight
from the MetricsCollector (a direct read, not an event — reference:
horizontal_pod_autoscaler.rs:146-150), runs the algorithm per group, and emits
CreatePodRequest / RemovePodRequest actions.
"""

from __future__ import annotations

from typing import Dict, TYPE_CHECKING

from kubernetriks_tpu.autoscalers.interface import (
    HorizontalPodAutoscalerAlgorithm,
    PodGroupInfo,
    ScaleDownPodAction,
    ScaleUpPodAction,
)
from kubernetriks_tpu.autoscalers.kube_horizontal_pod_autoscaler import (
    KubeHorizontalPodAutoscaler,
)
from kubernetriks_tpu.core.events import (
    CreatePodRequest,
    RegisterPodGroup,
    RemovePodRequest,
    RunHorizontalPodAutoscalerCycle,
)
from kubernetriks_tpu.core.types import Pod
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import HorizontalPodAutoscalerConfig, SimulationConfig
    from kubernetriks_tpu.metrics.collector import MetricsCollector


class HorizontalPodAutoscaler(EventHandler):
    def __init__(
        self,
        api_server: int,
        autoscaling_algorithm: HorizontalPodAutoscalerAlgorithm,
        ctx: SimulationContext,
        config: "SimulationConfig",
        metrics_collector: "MetricsCollector",
    ) -> None:
        self.api_server = api_server
        self.pod_groups: Dict[str, PodGroupInfo] = {}
        self.autoscaling_algorithm = autoscaling_algorithm
        self.ctx = ctx
        self.config = config
        self.metrics_collector = metrics_collector

    def start(self) -> None:
        self.ctx.emit_self_now(RunHorizontalPodAutoscalerCycle())

    def _scale_up_request(self, pod: Pod) -> None:
        # NB: the reference emits HPA scale requests with the *CA* delay
        # (horizontal_pod_autoscaler.rs:100-105 uses as_to_ca_network_delay);
        # replicated for golden-trajectory parity.
        self.ctx.emit(
            CreatePodRequest(pod=pod),
            self.api_server,
            self.config.as_to_ca_network_delay,
        )
        self.metrics_collector.accumulated_metrics.total_scaled_up_pods += 1

    def _scale_down_request(self, pod_name: str) -> None:
        self.ctx.emit(
            RemovePodRequest(pod_name=pod_name),
            self.api_server,
            self.config.as_to_ca_network_delay,
        )
        self.metrics_collector.accumulated_metrics.total_scaled_down_pods += 1

    def take_actions(self, actions) -> None:
        for action in actions:
            if isinstance(action, ScaleUpPodAction):
                self._scale_up_request(action.pod)
            elif isinstance(action, ScaleDownPodAction):
                self._scale_down_request(action.pod_name)

    def run_horizontal_pod_autoscaler_cycle(self) -> None:
        """Sorted group order replaces the reference's nondeterministic HashMap
        iteration (horizontal_pod_autoscaler.rs:152-160) — a determinism fix,
        not a semantic change."""
        metrics = self.metrics_collector.pod_metrics_mean_utilization()
        actions = []
        for group_name in sorted(metrics):
            cpu_mean, ram_mean = metrics[group_name]
            pod_group_info = self.pod_groups[group_name]
            actions.extend(
                self.autoscaling_algorithm.autoscale(
                    (cpu_mean, ram_mean), pod_group_info
                )
            )
        self.take_actions(actions)
        self.ctx.emit_self(
            RunHorizontalPodAutoscalerCycle(),
            self.config.horizontal_pod_autoscaler.scan_interval,
        )

    # --- event handlers -----------------------------------------------------

    def on_run_horizontal_pod_autoscaler_cycle(
        self, data: RunHorizontalPodAutoscalerCycle, time: float
    ) -> None:
        self.run_horizontal_pod_autoscaler_cycle()

    def on_register_pod_group(self, data: RegisterPodGroup, time: float) -> None:
        self.pod_groups[data.info.pod_group.name] = data.info


def resolve_horizontal_pod_autoscaler_impl(
    autoscaler_config: "HorizontalPodAutoscalerConfig",
) -> HorizontalPodAutoscalerAlgorithm:
    """reference: horizontal_pod_autoscaler.rs:171-185."""
    if autoscaler_config.autoscaler_type == "kube_horizontal_pod_autoscaler":
        return KubeHorizontalPodAutoscaler(
            autoscaler_config.kube_horizontal_pod_autoscaler_config
        )
    raise ValueError(
        f"Unsupported horizontal pod autoscaler implementation: "
        f"{autoscaler_config.autoscaler_type!r}"
    )
