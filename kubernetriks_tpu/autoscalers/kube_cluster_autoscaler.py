"""Default cluster-autoscaler algorithm: bin-pack scale-up, utilization-threshold
scale-down with simulated re-placement
(reference: src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetriks_tpu.autoscalers.interface import (
    CLUSTER_AUTOSCALER_ORIGIN_LABEL,
    AutoscaleInfo,
    AutoscaleInfoRequestType,
    CaNodeGroup,
    ClusterAutoscalerAlgorithm,
    ScaleDownInfo,
    ScaleDownNodeAction,
    ScaleUpInfo,
    ScaleUpNodeAction,
)
from kubernetriks_tpu.config import KubeClusterAutoscalerConfig
from kubernetriks_tpu.core.types import Node, Pod


def _node_fits_pod(pod: Pod, node: Node) -> bool:
    requests = pod.spec.resources.requests
    return (
        requests.cpu <= node.status.allocatable.cpu
        and requests.ram <= node.status.allocatable.ram
    )


class KubeClusterAutoscaler(ClusterAutoscalerAlgorithm):
    """Scale-up: first-fit each unscheduled pod into already-planned nodes, then
    a new node from the first fitting group template (respecting per-group
    max_count and the global max_node_count). Scale-down: only CA-origin nodes
    whose max(cpu,ram) utilization is under the threshold and whose pods all fit
    on other nodes (simulated re-placement)."""

    def __init__(self, config: Optional[KubeClusterAutoscalerConfig] = None) -> None:
        self.config = config or KubeClusterAutoscalerConfig()

    def info_request_type(self) -> AutoscaleInfoRequestType:
        return AutoscaleInfoRequestType.AUTO

    # --- scale up -----------------------------------------------------------

    def node_count_over_quota(
        self,
        node_groups: Dict[str, CaNodeGroup],
        current_node_count: int,
        max_node_count: int,
    ) -> bool:
        """reference: kube_cluster_autoscaler.rs:62-80."""
        if current_node_count >= max_node_count:
            return True
        for group in node_groups.values():
            if group.max_count is None or group.current_count < group.max_count:
                return False
        return True

    def try_find_fitting_template(
        self, pod: Pod, node_groups: Dict[str, CaNodeGroup]
    ) -> Optional[Node]:
        """First fitting group in sorted-name order; allocates a uniquely-named
        node from its template (reference: kube_cluster_autoscaler.rs:87-112)."""
        for group_name in sorted(node_groups):
            group = node_groups[group_name]
            if group.max_count is not None and group.current_count >= group.max_count:
                continue
            if _node_fits_pod(pod, group.node_template):
                group.current_count += 1
                group.total_allocated += 1
                node = group.node_template.copy()
                node.metadata.name = f"{node.metadata.name}_{group.total_allocated}"
                node.status.allocatable = node.status.capacity.copy()
                return node
        return None

    @staticmethod
    def _try_fit_in_allocated_nodes(allocated_nodes: List[Node], pod: Pod) -> bool:
        for node in allocated_nodes:
            if _node_fits_pod(pod, node):
                node.status.allocatable.cpu -= pod.spec.resources.requests.cpu
                node.status.allocatable.ram -= pod.spec.resources.requests.ram
                return True
        return False

    def scale_up(
        self,
        info: ScaleUpInfo,
        node_groups: Dict[str, CaNodeGroup],
        max_node_count: int,
    ) -> List[ScaleUpNodeAction]:
        """reference: kube_cluster_autoscaler.rs:190-240."""
        allocated_nodes: List[Node] = []
        current_node_count = sum(g.current_count for g in node_groups.values())
        if self.node_count_over_quota(node_groups, current_node_count, max_node_count):
            return []

        for pod in info.unscheduled_pods:
            if self._try_fit_in_allocated_nodes(allocated_nodes, pod):
                continue
            if current_node_count >= max_node_count:
                continue
            node = self.try_find_fitting_template(pod, node_groups)
            if node is not None:
                # NB: matching the reference, the triggering pod is NOT packed
                # into the fresh node — it joins at full allocatable, and later
                # pods first-fit into it (kube_cluster_autoscaler.rs:210-218).
                allocated_nodes.append(node)
                current_node_count += 1

        actions = []
        for node in allocated_nodes:
            node.status.allocatable = node.status.capacity.copy()
            actions.append(ScaleUpNodeAction(node=node))
        return actions

    # --- scale down ---------------------------------------------------------

    def is_under_threshold_utilization(self, node: Node) -> bool:
        """Utilization = max(cpu, ram) of requests/capacity
        (reference: kube_cluster_autoscaler.rs:117-131)."""
        status = node.status
        cpu_utilization = (status.capacity.cpu - status.allocatable.cpu) / status.capacity.cpu
        ram_utilization = (status.capacity.ram - status.allocatable.ram) / status.capacity.ram
        return max(cpu_utilization, ram_utilization) < (
            self.config.scale_down_utilization_threshold
        )

    @staticmethod
    def all_pods_can_be_moved_to_other_nodes(
        pods: List[Pod], nodes: List[Node], current_node_idx: int
    ) -> bool:
        """Simulated re-placement: greedily place each pod on any other node;
        commits allocatable decrements on success, rolls back on failure
        (reference: kube_cluster_autoscaler.rs:133-181)."""
        if not pods:
            return True
        original = [(n.status.allocatable.cpu, n.status.allocatable.ram) for n in nodes]
        for pod in pods:
            placed = False
            for node_idx, node in enumerate(nodes):
                if node_idx == current_node_idx:
                    continue
                if _node_fits_pod(pod, node):
                    node.status.allocatable.cpu -= pod.spec.resources.requests.cpu
                    node.status.allocatable.ram -= pod.spec.resources.requests.ram
                    placed = True
                    break
            if not placed:
                for node, (cpu, ram) in zip(nodes, original):
                    node.status.allocatable.cpu = cpu
                    node.status.allocatable.ram = ram
                return False
        return True

    def scale_down(
        self, info: ScaleDownInfo, node_groups: Dict[str, CaNodeGroup]
    ) -> List[ScaleDownNodeAction]:
        """reference: kube_cluster_autoscaler.rs:242-290."""
        node_indices_to_remove: List[int] = []
        for idx, node in enumerate(info.nodes):
            if node.metadata.labels.get("origin") != CLUSTER_AUTOSCALER_ORIGIN_LABEL:
                continue
            if not self.is_under_threshold_utilization(node):
                continue
            assigned_pods = info.assignments.get(node.metadata.name)
            if assigned_pods is not None:
                pods_on_node = [
                    info.pods_on_autoscaled_nodes[pod_name]
                    for pod_name in sorted(assigned_pods)
                ]
                if not self.all_pods_can_be_moved_to_other_nodes(
                    pods_on_node, info.nodes, idx
                ):
                    continue
            node_indices_to_remove.append(idx)

        actions = []
        for idx in node_indices_to_remove:
            node = info.nodes[idx]
            node_groups[node.metadata.labels["node_group"]].current_count -= 1
            actions.append(ScaleDownNodeAction(node_name=node.metadata.name))
        return actions

    def autoscale(
        self,
        info: AutoscaleInfo,
        node_groups: Dict[str, CaNodeGroup],
        max_node_count: int,
    ) -> List:
        if info.scale_up is not None:
            return self.scale_up(info.scale_up, node_groups, max_node_count)
        if info.scale_down is not None:
            return self.scale_down(info.scale_down, node_groups)
        return []
