"""Default HPA algorithm: k8s desired-replicas formula with tolerance band
(reference: src/autoscalers/horizontal_pod_autoscaler/kube_horizontal_pod_autoscaler.rs).
"""

from __future__ import annotations

import math
from typing import List, Optional

from kubernetriks_tpu.autoscalers.interface import (
    HorizontalPodAutoscalerAlgorithm,
    PodGroupInfo,
    ScaleDownPodAction,
    ScaleUpPodAction,
)
from kubernetriks_tpu.config import KubeHorizontalPodAutoscalerConfig


class KubeHorizontalPodAutoscaler(HorizontalPodAutoscalerAlgorithm):
    """desired = ceil(current * currentMetric / targetMetric), skipped when the
    ratio is within the tolerance band around 1.0; per-metric desired values are
    maxed and clamped to the group's max_pod_count."""

    def __init__(
        self, config: Optional[KubeHorizontalPodAutoscalerConfig] = None
    ) -> None:
        self.config = config or KubeHorizontalPodAutoscalerConfig()

    def desired_number_of_pods_by_metric(
        self, current_replicas: int, current_value: float, desired_value: float
    ) -> int:
        """reference: kube_horizontal_pod_autoscaler.rs:54-71."""
        ratio = current_value / desired_value
        if abs(ratio - 1.0) <= self.config.target_threshold_tolerance:
            return current_replicas
        return math.ceil(current_replicas * ratio)

    def desired_number_of_pods(
        self, pod_group: PodGroupInfo, current_cpu: float, current_ram: float
    ) -> int:
        """reference: kube_horizontal_pod_autoscaler.rs:76-155."""
        targets = pod_group.pod_group.target_resources_usage
        current_replicas = len(pod_group.created_pods)
        desired_by_cpu = desired_by_ram = None
        if targets.cpu_utilization is not None:
            desired_by_cpu = self.desired_number_of_pods_by_metric(
                current_replicas, current_cpu, targets.cpu_utilization
            )
        if targets.ram_utilization is not None:
            desired_by_ram = self.desired_number_of_pods_by_metric(
                current_replicas, current_ram, targets.ram_utilization
            )

        max_pods = pod_group.pod_group.max_pod_count
        if desired_by_cpu is not None and desired_by_ram is not None:
            return min(max_pods, max(desired_by_cpu, desired_by_ram))
        if desired_by_cpu is not None:
            return min(max_pods, desired_by_cpu)
        if desired_by_ram is not None:
            return min(max_pods, desired_by_ram)
        return current_replicas

    def make_actions_for_group(
        self, pod_group: PodGroupInfo, desired_number_of_pods: int
    ) -> List:
        """Scale-up clones the template with pod_group labels and a monotonic
        name counter; scale-down pops the lexicographically-first (oldest by
        naming scheme) created pods (reference:
        kube_horizontal_pod_autoscaler.rs:157-216)."""
        actions: List = []
        current_pod_count = len(pod_group.created_pods)
        if current_pod_count == desired_number_of_pods:
            return actions
        if current_pod_count < desired_number_of_pods:
            for _ in range(desired_number_of_pods - current_pod_count):
                new_pod = pod_group.pod_group.pod_template.copy()
                pod_name = f"{pod_group.pod_group.name}_{pod_group.total_created}"
                new_pod.metadata.name = pod_name
                new_pod.metadata.labels["pod_group"] = pod_group.pod_group.name
                new_pod.metadata.labels["pod_group_creation_time"] = repr(
                    pod_group.creation_time
                )
                new_pod.spec.resources.usage_model_config = (
                    pod_group.pod_group.resources_usage_model_config
                )
                actions.append(ScaleUpPodAction(pod=new_pod))
                pod_group.created_pods.add(pod_name)
                pod_group.total_created += 1
        else:
            for _ in range(current_pod_count - desired_number_of_pods):
                next_pod_name = min(pod_group.created_pods)
                pod_group.created_pods.discard(next_pod_name)
                actions.append(ScaleDownPodAction(pod_name=next_pod_name))
        return actions

    def autoscale(self, pod_group_metrics, pod_group_info: PodGroupInfo) -> List:
        desired = self.desired_number_of_pods(
            pod_group_info, pod_group_metrics[0], pod_group_metrics[1]
        )
        return self.make_actions_for_group(pod_group_info, desired)
